#!/usr/bin/env python3
"""bench_diff: compare two bench JSON documents, flag regressions.

The BENCH_r*.json trajectory is how this repo proves perf PRs — but
"compare round N against round N-1" has been an eyeball job, and
eyeballs miss 12% regressions hiding in a 40-key detail dict.  This
tool makes the comparison a machine verdict:

    python tools/bench_diff.py OLD.json NEW.json [--threshold 0.10]
                               [--json] [--all]

Inputs are the driver's round documents ``{n, cmd, rc, tail, parsed}``
(``parsed`` holds the bench line ``{metric, value, detail: {...}}``);
a bare bench line document is accepted too.  Comparison runs over the
REGISTERED key-metric list below — dotted paths into ``detail`` with
an explicit direction, because "read rps went down" and "mttr went
down" are opposite verdicts.  A metric moving against its direction by
more than ``--threshold`` (default 10%) is a REGRESSION and the exit
code is 1; improvements and small moves report informationally.

Schema discipline: bench.py stamps ``schema_version`` (and the git
revision) into every document.  Documents with different schema
versions do not compare — the tool exits 2 and says so, instead of
misreporting a shape change as a perf move.  Pre-stamp documents
(BENCH_r01..r05) read as version 1 and compare among themselves.

Exit codes: 0 clean, 1 regression(s), 2 usage / not comparable.
"""

from __future__ import annotations

import json
import sys
from typing import Optional

# the registered key-metric list: (dotted path into parsed.detail,
# direction[, min_abs]).  "up" = bigger is better (throughput), "down"
# = smaller is better (latency, recovery time).  min_abs is an
# ABSOLUTE-move floor: near-zero metrics (the *_overhead_pct family
# lives around 0.1-1.0) turn sub-noise absolute moves into huge
# relative ones — 0.2% -> 0.5% is +150% "regression" on numbers both
# comfortably inside their acceptance bar, and an old value of exactly
# 0 makes any move read as infinite.  Paths absent from either
# document are skipped — rounds run on different hardware/sections all
# the time — but a path present in OLD and missing in NEW is reported
# (a silently vanished metric is how regressions hide).
KEY_METRICS: list[tuple] = [
    ("cluster_read_rps", "up"),
    ("cluster_write_rps", "up"),
    ("cluster_tcp_read_rps", "up"),
    ("cluster_native_tcp_read_rps", "up"),
    # the dataplane refactor's acceptance keys: capacity_rps per route
    # class under the declared SLO.  Absolute floors keep tiny-host
    # noise (a 40-rps CI runner wobbling to 55) from reading as a
    # verdict either way — the 10x gate is judged on real moves.
    ("capacity.http_read.capacity_rps", "up", 50.0),
    ("capacity.native_read.capacity_rps", "up", 50.0),
    ("capacity.http_write.capacity_rps", "up", 25.0),
    # popularity-aware needle cache (volume_server/needle_cache.py):
    # the capacity probe's Zipf-shaped read mix should keep this high;
    # a silent admission/invalidation regression shows up here
    ("capacity.needle_cache_hit_ratio", "up", 0.05),
    ("capacity.reqlog_read_overhead_pct", "down", 1.0),
    ("cpu_simd_mbps", "up"),
    ("tpu_inhbm_pallas_mbps", "up"),
    ("e2e_file_encode_mbps", "up"),
    ("e2e_pipeline_disk.overlap_efficiency", "up", 0.05),
    ("e2e_pipeline_tmpfs.overlap_efficiency", "up", 0.05),
    ("e2e_pipeline_disk.e2e_link_efficiency", "up", 0.05),
    ("e2e_pipeline_tmpfs.e2e_link_efficiency", "up", 0.05),
    # mesh-sharded encode plane (ec/streaming._encode_file_mesh):
    # aggregate throughput across per-device dispatch queues at the
    # widest width, and the overlap/link verdicts that certify the
    # queues actually hid drain time behind host work
    ("multichip_encode.aggregate_mbps", "up", 5.0),
    ("multichip_encode.overlap_efficiency", "up", 0.05),
    ("multichip_encode.e2e_link_efficiency", "up", 0.05),
    ("coordinator.mttr_s", "down", 1.0),
    ("alerts.eval_read_overhead_pct", "down", 1.0),
    ("trace_sampling_read_overhead_pct", "down", 1.0),
    # heat-telemetry plane (observability/heat.py): accounting must
    # stay under 1% of read rps vs the accounting-off baseline, and
    # the space-saving sketch must keep finding the Zipf head
    ("heat.accounting_overhead_pct", "down", 1.0),
    ("heat.sketch_head_recall", "up", 0.05),
    # resource-ledger plane (observability/ledger.py): per-request
    # CPU/bytes/queue-wait accounting PLUS the always-on windowed
    # profiler must stay under 1% of read rps vs the -ledger.off
    # baseline, and the serving loop's lag p99 must stay inside the
    # interactive budget under the bench read mix
    ("resource_ledger.ledger_overhead_pct", "down", 1.0),
    ("resource_ledger.loop_lag_p99_ms", "down", 5.0),
    # master HA failover drill (scenarios/failover.py): the raft
    # journal contract is ZERO pre-kill events lost across an election
    # (any increase is a regression — the 0.5 floor only absorbs float
    # noise, not a lost event), and the election + repair re-plan
    # latencies stay inside their drill budgets
    ("master_failover.journal_loss_count", "down", 0.5),
    ("master_failover.election_time_s", "down", 1.0),
    ("master_failover.repair_replan_s", "down", 5.0),
    # heat autoscaler + cold tiering (ops/autoscaler.py): the closed
    # loop must pull the flash-crowd hot set back inside the SLO, lift
    # the post-shift serving rate over the autoscale-off baseline, and
    # cost nothing while idle; tiered reads and the 64MB recall bound
    # the cold path's read-through and un-tier latencies
    ("autoscale.recovery_to_slo_s", "down", 2.0),
    ("autoscale.hot_rps_uplift_pct", "up", 10.0),
    ("autoscale.idle_overhead_pct", "down", 1.0),
    ("autoscale.tiered_read_ms", "down", 5.0),
    ("autoscale.tier_recall_s", "down", 1.0),
]


def load_document(path: str) -> dict:
    """-> the bench-line dict {metric, value, detail} from either the
    round shape {n, cmd, rc, tail, parsed} or a bare bench line.
    Raises ValueError when the document has nothing to compare."""
    with open(path, encoding="utf-8") as f:
        doc = json.load(f)
    if not isinstance(doc, dict):
        raise ValueError(f"{path}: not a JSON object")
    if "parsed" in doc or "tail" in doc:
        parsed = doc.get("parsed")
        if not isinstance(parsed, dict):
            raise ValueError(
                f"{path}: round document carries no parsed bench line "
                f"(rc={doc.get('rc')}) — nothing to compare")
        return parsed
    if "detail" in doc:
        return doc
    raise ValueError(f"{path}: neither a round document nor a bench line")


def schema_version(parsed: dict) -> int:
    """Pre-stamp documents (rounds 1-5) are version 1."""
    try:
        return int((parsed.get("detail") or {}).get("schema_version", 1))
    except (TypeError, ValueError):
        return 1


def lookup(detail: dict, dotted: str) -> Optional[float]:
    cur: object = detail
    for part in dotted.split("."):
        if not isinstance(cur, dict) or part not in cur:
            return None
        cur = cur[part]
    if isinstance(cur, bool) or not isinstance(cur, (int, float)):
        return None
    return float(cur)


def compare(old: dict, new: dict, threshold: float = 0.10,
            metrics: Optional[list[tuple]] = None) -> dict:
    """-> {comparable, rows, regressions, improvements, missing}.
    Raises ValueError on a schema mismatch (the caller exits 2)."""
    v_old, v_new = schema_version(old), schema_version(new)
    if v_old != v_new:
        raise ValueError(
            f"schema mismatch: old is v{v_old}, new is v{v_new} — "
            f"re-run the older side on the current tree instead of "
            f"comparing across schemas")
    d_old = old.get("detail") or {}
    d_new = new.get("detail") or {}
    rows: list[dict] = []
    regressions: list[dict] = []
    improvements: list[dict] = []
    missing: list[str] = []
    for entry in (metrics or KEY_METRICS):
        path, direction = entry[0], entry[1]
        min_abs = float(entry[2]) if len(entry) > 2 else 0.0
        a, b = lookup(d_old, path), lookup(d_new, path)
        if a is None and b is None:
            continue
        if a is not None and b is None:
            missing.append(path)
            continue
        if a is None:
            rows.append({"metric": path, "old": None, "new": b,
                         "verdict": "new"})
            continue
        if a == 0:
            change = 0.0 if b == 0 else float("inf")
        else:
            change = (b - a) / abs(a)
        # a move WITH the direction is good, against it is bad
        signed = change if direction == "up" else -change
        verdict = "ok"
        if abs(b - a) < min_abs:
            # sub-floor absolute move: relative % on a near-zero
            # metric is noise, never a verdict (also tames a==0 ->
            # "infinite" change)
            pass
        elif signed <= -threshold:
            verdict = "regression"
        elif signed >= threshold:
            verdict = "improvement"
        row = {"metric": path, "direction": direction,
               "old": a, "new": b,
               "change_pct": round(change * 100.0, 2)
               if change != float("inf") else None,
               "verdict": verdict}
        rows.append(row)
        if verdict == "regression":
            regressions.append(row)
        elif verdict == "improvement":
            improvements.append(row)
    return {
        "schema_version": v_old,
        "old_revision": (d_old.get("git_revision") or ""),
        "new_revision": (d_new.get("git_revision") or ""),
        "threshold_pct": round(threshold * 100.0, 1),
        "rows": rows,
        "regressions": regressions,
        "improvements": improvements,
        "missing_in_new": missing,
    }


def render(report: dict, show_all: bool = False) -> str:
    lines = [f"bench_diff (threshold {report['threshold_pct']}%, "
             f"schema v{report['schema_version']}"
             + (f", {report['old_revision'] or '?'} -> "
                f"{report['new_revision'] or '?'}"
                if report["old_revision"] or report["new_revision"]
                else "") + ")"]
    for row in report["rows"]:
        if not show_all and row["verdict"] == "ok":
            continue
        ch = row.get("change_pct")
        lines.append(
            f"  {row['verdict'].upper():<12} {row['metric']:<44} "
            f"{row['old']} -> {row['new']}"
            + (f" ({ch:+.1f}%)" if ch is not None else ""))
    for path in report["missing_in_new"]:
        lines.append(f"  MISSING      {path:<44} present in old, "
                     f"absent in new")
    n_reg = len(report["regressions"])
    lines.append(f"verdict: {n_reg} regression(s), "
                 f"{len(report['improvements'])} improvement(s), "
                 f"{len(report['missing_in_new'])} missing")
    return "\n".join(lines)


def main(argv: Optional[list[str]] = None) -> int:
    argv = list(sys.argv[1:] if argv is None else argv)
    threshold = 0.10
    as_json = False
    show_all = False
    paths: list[str] = []
    i = 0
    while i < len(argv):
        a = argv[i]
        if a == "--threshold":
            i += 1
            if i >= len(argv):
                print("--threshold needs a value", file=sys.stderr)
                return 2
            try:
                threshold = float(argv[i])
            except ValueError:
                print(f"bad threshold {argv[i]!r}", file=sys.stderr)
                return 2
        elif a == "--json":
            as_json = True
        elif a == "--all":
            show_all = True
        elif a.startswith("-"):
            print(f"unknown flag {a}", file=sys.stderr)
            return 2
        else:
            paths.append(a)
        i += 1
    if len(paths) != 2:
        print("usage: bench_diff.py OLD.json NEW.json "
              "[--threshold 0.10] [--json] [--all]", file=sys.stderr)
        return 2
    try:
        old = load_document(paths[0])
        new = load_document(paths[1])
        report = compare(old, new, threshold=threshold)
    except (OSError, ValueError, json.JSONDecodeError) as e:
        print(f"bench_diff: {e}", file=sys.stderr)
        return 2
    if as_json:
        print(json.dumps(report, indent=2, sort_keys=True))
    else:
        print(render(report, show_all=show_all))
    return 1 if report["regressions"] else 0


if __name__ == "__main__":
    raise SystemExit(main())
