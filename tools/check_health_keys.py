#!/usr/bin/env python3
"""Tier-1 consistency lint for the degraded-signal tables.

Four tables describe "what counts as degraded" and they MUST agree:

  stats/aggregate.py   HEALTH_FAMILIES      — the /cluster/health keys
  observability/analysis.py DEGRADE_COUNTER_KEYS — the analyzer verdict
  observability/events.py   EVENT_TYPES + HEALTH_EVENT_TYPES — journal
  observability/alerts.py   default_rules()  — what actually pages

Before this lint, adding a degraded counter to one table but not the
others was silent drift: a counter could degrade /cluster/health yet
never fire an alert, or an event type could exist with no counter
backing it.  Run as a tier-1 test (tests/test_check_health_keys.py) and
standalone:

    python tools/check_health_keys.py   # exit 1 + report on drift

The check functions take the tables as ARGUMENTS so the test can feed
synthetically drifted tables and prove each rule actually catches.
"""

from __future__ import annotations

import sys

# HEALTH_FAMILIES keys that legitimately stay OUT of
# DEGRADE_COUNTER_KEYS: a degraded TCP bind means a server came up
# without its fast plane — operationally alertable, but it does not
# make a pipeline MEASUREMENT degraded (the analyzer's verdict is about
# the measured run, not the serving posture).
DEGRADE_KEY_ALLOWLIST = ("degraded_binds",)

# DEGRADE_COUNTER_KEYS entries that are per-run encode stats rather
# than cluster counter families (they ride encode() stats dicts, not
# /metrics): the health table legitimately does not carry them.
PER_RUN_ONLY_KEYS = ("retries", "fallbacks")


def check_tables(health_families: dict, degrade_keys: tuple,
                 rules: list, event_types: dict,
                 health_event_types: dict,
                 extra_health_keys: tuple = ("scrub_unrepairable",),
                 allowlist: tuple = DEGRADE_KEY_ALLOWLIST,
                 per_run_only: tuple = PER_RUN_ONLY_KEYS) -> list[str]:
    """Returns human-readable violations (empty = consistent).
    `rules` is a list of alert Rule objects (or anything with .kind and
    .params)."""
    v: list[str] = []
    health_keys = set(health_families)

    # 1. every health key maps to a journal event type, and that type
    #    is registered with a severity
    for key in sorted(health_keys):
        etype = health_event_types.get(key)
        if not etype:
            v.append(f"HEALTH_FAMILIES key {key!r} has no event type in "
                     "events.HEALTH_EVENT_TYPES — its degraded moments "
                     "would never reach the journal")
        elif etype not in event_types:
            v.append(f"HEALTH_EVENT_TYPES maps {key!r} -> {etype!r} "
                     "which is not registered in events.EVENT_TYPES")
    # ... and no mapping points at a key that left the health table
    for key in sorted(health_event_types):
        if key not in health_keys:
            v.append(f"HEALTH_EVENT_TYPES covers {key!r} which is not "
                     "a HEALTH_FAMILIES key (stale mapping)")

    # 2. every health key (minus the documented allowlist) marks
    #    analyzer runs degraded
    for key in sorted(health_keys - set(allowlist)):
        if key not in degrade_keys:
            v.append(f"HEALTH_FAMILIES key {key!r} missing from "
                     "analysis.DEGRADE_COUNTER_KEYS — a run that "
                     "tripped it would still read clean")
    # ... and every degrade key that claims to be a cluster family is
    for key in degrade_keys:
        if key in per_run_only:
            continue
        if key not in health_keys:
            v.append(f"DEGRADE_COUNTER_KEYS entry {key!r} is not a "
                     "HEALTH_FAMILIES key (and not a documented "
                     "per-run stat) — /cluster/health would never "
                     "carry it")

    # 3. every health key is watched by a default counter_increase rule
    watched = {r.params.get("key") for r in rules
               if getattr(r, "kind", "") == "counter_increase"}
    for key in sorted(health_keys):
        if key not in watched:
            v.append(f"HEALTH_FAMILIES key {key!r} has no default "
                     "counter_increase alert rule — it would degrade "
                     "/cluster/health without ever paging")

    # 4. every rule that names a health key names a REAL one
    legal = health_keys | set(extra_health_keys)
    for r in rules:
        kind = getattr(r, "kind", "")
        key = (getattr(r, "params", None) or {}).get("key")
        if kind in ("counter_increase", "threshold") and key not in legal:
            v.append(f"alert rule {getattr(r, 'name', '?')!r} watches "
                     f"unknown health key {key!r}")

    # 5. the alert lifecycle's own event types exist (the journal is
    #    where transitions are recorded; losing one loses the audit
    #    trail)
    for etype in ("alert_pending", "alert_fired", "alert_resolved"):
        if etype not in event_types:
            v.append(f"event type {etype!r} missing from EVENT_TYPES — "
                     "alert transitions would journal as unregistered "
                     "types")

    # 6. a counter rule's severity must match its event type's —
    #    EVENT_TYPES is the ONE severity table; a rule hand-overriding
    #    it would page at a different level than the journal records
    for r in rules:
        if getattr(r, "kind", "") != "counter_increase":
            continue
        key = (getattr(r, "params", None) or {}).get("key")
        etype = health_event_types.get(key or "")
        want = event_types.get(etype or "")
        got = getattr(r, "severity", None)
        if want and got != want:
            v.append(f"alert rule {getattr(r, 'name', '?')!r} severity "
                     f"{got!r} disagrees with EVENT_TYPES[{etype!r}] = "
                     f"{want!r}")
    return v


def check_repo() -> list[str]:
    """The real tables, imported live — what tier-1 runs."""
    from seaweedfs_tpu.observability.alerts import (EXTRA_HEALTH_KEYS,
                                                    default_rules)
    from seaweedfs_tpu.observability.analysis import DEGRADE_COUNTER_KEYS
    from seaweedfs_tpu.observability.events import (EVENT_TYPES,
                                                    HEALTH_EVENT_TYPES)
    from seaweedfs_tpu.stats.aggregate import HEALTH_FAMILIES

    return check_tables(HEALTH_FAMILIES, DEGRADE_COUNTER_KEYS,
                        default_rules(), EVENT_TYPES,
                        HEALTH_EVENT_TYPES,
                        extra_health_keys=EXTRA_HEALTH_KEYS)


def main() -> int:
    import os
    sys.path.insert(0, os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))
    violations = check_repo()
    for msg in violations:
        print(f"check_health_keys: {msg}")
    if violations:
        print(f"check_health_keys: {len(violations)} violation(s)")
        return 1
    print("check_health_keys: degraded-signal tables consistent")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
