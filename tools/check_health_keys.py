#!/usr/bin/env python3
"""Shim over weedlint rule W401 (tools/weedlint/rules_health_keys.py).

The degraded-signal table-consistency lint moved onto the unified
weedlint engine (PR 10); this entry point and `check_tables` /
`check_repo` survive so existing invocations and tests keep working:

    python tools/check_health_keys.py         # exit 1 + report on drift
    python -m tools.weedlint --rule W401
"""

from __future__ import annotations

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from tools.weedlint.rules_health_keys import (  # noqa: E402,F401
    DEGRADE_KEY_ALLOWLIST, PER_RUN_ONLY_KEYS, check_tables)
from tools.weedlint.rules_health_keys import \
    check_live_tables as check_repo  # noqa: E402,F401


def main() -> int:
    violations = check_repo()
    for msg in violations:
        print(f"check_health_keys: {msg}")
    if violations:
        print(f"check_health_keys: {len(violations)} violation(s)")
        return 1
    print("check_health_keys: degraded-signal tables consistent")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
