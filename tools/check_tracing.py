#!/usr/bin/env python
"""Shim over weedlint rule W201 (tools/weedlint/rules_tracing.py).

The tracing-chokepoint lint moved onto the unified weedlint engine
(PR 10); this entry point and its helper names survive so existing
invocations and tests keep working:

    python tools/check_tracing.py [repo_root]
    python -m tools.weedlint --rule W201
"""

from __future__ import annotations

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from tools.weedlint import Repo, get_rule  # noqa: E402
from tools.weedlint.rules_tracing import (check_httpd_source as _httpd,  # noqa: E402
                                          check_package_source as _pkg)


def _strs(findings) -> list[str]:
    return [f"{f.path}:{f.line}: {f.message}" for f in findings]


def check_httpd_source(src: str, path: str) -> list[str]:
    return _strs(_httpd(src, path))


def check_package_source(src: str, path: str) -> list[str]:
    return _strs(_pkg(src, path))


def check_repo(root: str) -> list[str]:
    return _strs(get_rule("W201").check(Repo(root)))


def main(argv: list[str]) -> int:
    root = argv[0] if argv else os.path.dirname(
        os.path.dirname(os.path.abspath(__file__)))
    problems = check_repo(root)
    for p in problems:
        print(p)
    print(f"check_tracing: {len(problems)} problem(s)", file=sys.stderr)
    return 1 if problems else 0


if __name__ == "__main__":
    sys.exit(main(sys.argv[1:]))
