#!/usr/bin/env python
"""Tracing-coverage lint: new code cannot silently opt out of tracing.

PR 6's distributed tracing is enforced at two chokepoints, not at every
call site: `utils/httpd.py` Router.dispatch is the ONE ingress every
HTTP handler runs under (trace-context adoption + request span), and
`utils/httpd.py`'s pooled client helpers are the ONE egress every
outbound hop rides (Traceparent injection + rpc.client span).  That
design only holds if nothing routes around the chokepoints — which is
exactly what this lint asserts:

  1. Router.dispatch still adopts/restores the trace context
     (begin_request/end_request) and opens the request span; the framed
     TCP front (_serve_conn) still mints its headerless ingress.
  2. The outbound helpers (_pooled_request, http_download) still call
     inject_trace_headers.
  3. No module inside the seaweedfs_tpu package performs raw outbound
     HTTP (urllib.request / http.client) — that would bypass header
     injection, so the hop would shatter the trace.  utils/httpd.py
     itself is the sole allowed user.
  4. No Router subclass overrides dispatch outside utils/httpd.py
     (an override could drop the request span / context restore).

  python tools/check_tracing.py [repo_root]

Exit status 0 = clean, 1 = violations (one per line on stdout).
Stdlib-only — runs as a tier-1 test (tests/test_check_tracing.py).
"""

from __future__ import annotations

import ast
import os
import sys

PACKAGE = "seaweedfs_tpu"
HTTPD_REL = os.path.join(PACKAGE, "utils", "httpd.py")
FRAMING_REL = os.path.join(PACKAGE, "utils", "framing.py")
SKIP_DIRS = {".git", "__pycache__", ".claude", ".pytest_cache",
             "node_modules", ".venv", "venv"}
# modules whose presence in package code means a hand-rolled HTTP hop
# that would skip Traceparent injection
RAW_HTTP_MODULES = {"urllib.request", "http.client"}
# the egress helpers that must inject the trace header
OUTBOUND_HELPERS = ("_pooled_request", "http_download")


def _calls_in(node: ast.AST) -> set[str]:
    """Names of everything called inside `node` (bare and attribute
    calls both reduce to their final name)."""
    names: set[str] = set()
    for sub in ast.walk(node):
        if isinstance(sub, ast.Call):
            f = sub.func
            if isinstance(f, ast.Name):
                names.add(f.id)
            elif isinstance(f, ast.Attribute):
                names.add(f.attr)
    return names


def _functions(tree: ast.AST) -> dict[str, ast.AST]:
    """Every function/method in the module, by name (methods shadow
    module-level functions of the same name only if later — good enough
    for this lint's unique names)."""
    out: dict[str, ast.AST] = {}
    for node in ast.walk(tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            out.setdefault(node.name, node)
    return out


def check_httpd_source(src: str, path: str) -> list[str]:
    """The ingress/egress chokepoint contract on utils/httpd.py."""
    try:
        tree = ast.parse(src, filename=path)
    except SyntaxError as e:
        return [f"{path}:{e.lineno or 0}: does not parse: {e.msg}"]
    problems: list[str] = []
    fns = _functions(tree)
    dispatch = fns.get("dispatch")
    if dispatch is None:
        problems.append(f"{path}:0: Router.dispatch not found")
    else:
        calls = _calls_in(dispatch)
        for required in ("begin_request", "end_request", "span"):
            if required not in calls:
                problems.append(
                    f"{path}:{dispatch.lineno}: Router.dispatch no longer "
                    f"calls {required}() — HTTP handlers would run "
                    f"without a request span / trace context")
    for helper in OUTBOUND_HELPERS:
        fn = fns.get(helper)
        if fn is None:
            problems.append(f"{path}:0: outbound helper {helper}() "
                            f"not found")
        elif "inject_trace_headers" not in _calls_in(fn):
            problems.append(
                f"{path}:{fn.lineno}: {helper}() no longer calls "
                f"inject_trace_headers() — outbound hops would drop "
                f"the Traceparent and shatter cross-server traces")
    return problems


def check_framing_source(src: str, path: str) -> list[str]:
    """The framed-TCP ingress contract on utils/framing.py."""
    try:
        tree = ast.parse(src, filename=path)
    except SyntaxError as e:
        return [f"{path}:{e.lineno or 0}: does not parse: {e.msg}"]
    fns = _functions(tree)
    serve = fns.get("_serve_conn")
    if serve is None:
        return [f"{path}:0: FramedServer._serve_conn not found"]
    calls = _calls_in(serve)
    missing = [c for c in ("begin_request", "end_request", "span")
               if c not in calls]
    if missing:
        return [f"{path}:{serve.lineno}: _serve_conn no longer calls "
                f"{'/'.join(missing)} — the native TCP ingress would "
                f"run untraced"]
    return []


def check_package_source(src: str, path: str) -> list[str]:
    """Per-module rules for every other file in the package.

    A raw-HTTP import may carry an explicit inline waiver —
    ``# tracing-exempt: <reason>`` on the import line — for hops where
    Traceparent injection is genuinely wrong (e.g. streaming uploads to
    EXTERNAL third-party services, which must not receive our internal
    trace headers).  The waiver makes the exception deliberate and
    greppable instead of silent."""
    try:
        tree = ast.parse(src, filename=path)
    except SyntaxError as e:
        return [f"{path}:{e.lineno or 0}: does not parse: {e.msg}"]
    lines = src.splitlines()

    def waived(lineno: int) -> bool:
        line = lines[lineno - 1] if 0 < lineno <= len(lines) else ""
        return "tracing-exempt" in line

    problems: list[str] = []
    for node in ast.walk(tree):
        if isinstance(node, (ast.Import, ast.ImportFrom)) \
                and waived(node.lineno):
            continue
        if isinstance(node, ast.Import):
            for alias in node.names:
                if alias.name in RAW_HTTP_MODULES:
                    problems.append(
                        f"{path}:{node.lineno}: raw `import "
                        f"{alias.name}` — outbound HTTP must go "
                        f"through utils.httpd helpers so the "
                        f"Traceparent header propagates")
        elif isinstance(node, ast.ImportFrom):
            mod = node.module or ""
            if mod in RAW_HTTP_MODULES or \
                    (mod == "urllib"
                     and any(a.name == "request" for a in node.names)) or \
                    (mod == "http"
                     and any(a.name == "client" for a in node.names)):
                problems.append(
                    f"{path}:{node.lineno}: raw HTTP client import "
                    f"(`from {mod} import ...`) — outbound HTTP must "
                    f"go through utils.httpd helpers so the "
                    f"Traceparent header propagates")
        elif isinstance(node, ast.ClassDef):
            router_base = any(
                (isinstance(b, ast.Name) and b.id == "Router")
                or (isinstance(b, ast.Attribute) and b.attr == "Router")
                for b in node.bases)
            if not router_base:
                continue
            for item in node.body:
                if isinstance(item, (ast.FunctionDef,
                                     ast.AsyncFunctionDef)) \
                        and item.name == "dispatch":
                    problems.append(
                        f"{path}:{item.lineno}: Router subclass "
                        f"overrides dispatch() — the request span and "
                        f"trace-context restore live there; override "
                        f"hooks instead")
    return problems


def _read(path: str) -> str:
    with open(path, encoding="utf-8", errors="replace") as f:
        return f.read()


def check_repo(root: str) -> list[str]:
    problems: list[str] = []
    httpd = os.path.join(root, HTTPD_REL)
    framing = os.path.join(root, FRAMING_REL)
    if os.path.exists(httpd):
        problems.extend(check_httpd_source(_read(httpd), HTTPD_REL))
    else:
        problems.append(f"{HTTPD_REL}:0: missing")
    if os.path.exists(framing):
        problems.extend(check_framing_source(_read(framing), FRAMING_REL))
    else:
        problems.append(f"{FRAMING_REL}:0: missing")
    pkg_root = os.path.join(root, PACKAGE)
    for dirpath, dirnames, filenames in os.walk(pkg_root):
        dirnames[:] = sorted(d for d in dirnames if d not in SKIP_DIRS)
        for name in sorted(filenames):
            if not name.endswith(".py"):
                continue
            path = os.path.join(dirpath, name)
            rel = os.path.relpath(path, root)
            if rel in (HTTPD_REL,):  # the sole allowed raw-HTTP user
                continue
            problems.extend(check_package_source(_read(path), rel))
    return problems


def main(argv: list[str]) -> int:
    root = argv[0] if argv else os.path.dirname(
        os.path.dirname(os.path.abspath(__file__)))
    problems = check_repo(root)
    for p in problems:
        print(p)
    print(f"check_tracing: {len(problems)} problem(s)", file=sys.stderr)
    return 1 if problems else 0


if __name__ == "__main__":
    sys.exit(main(sys.argv[1:]))
