#!/usr/bin/env python
"""AST lint: reject code the py3.10 runtime cannot import.

The deployment container runs Python 3.10 — no PEP-701 nested
same-quote f-strings, no tomllib, no datetime.UTC.  Code written
against 3.12 does not fail loudly: a single 3.12-only f-string in a
widely-imported module silently collection-errors every test that
imports it (the seed shipped exactly that in volume_server/server.py
and it killed ~300 tests until PR 1 found it by hand).  This lint makes
that class of bug a tier-1 failure instead of a silent one:

  python tools/check_py310.py [root ...]    # default: the repo root

Checks, per .py file:
  - the file parses as py3.10 syntax (ast.parse with
    feature_version=(3, 10); under a 3.10 interpreter the parse itself
    also rejects 3.12-only constructs like nested same-quote f-strings);
  - `import tomllib` / `from tomllib import ...` only inside an
    ImportError-catching try (the utils/config.py gating pattern) or a
    sys.version_info guard;
  - `from datetime import UTC` / `datetime.UTC` under the same gating
    rule (py3.11+ only).

Exit status 0 = clean, 1 = violations (one per line on stdout).
Stdlib-only, no third-party deps — safe to run anywhere, including as
a tier-1 test (tests/test_check_py310.py).
"""

from __future__ import annotations

import ast
import os
import sys

TARGET = (3, 10)
SKIP_DIRS = {".git", "__pycache__", ".claude", ".pytest_cache",
             "node_modules", ".venv", "venv"}
# modules that do not exist on the target runtime
BANNED_MODULES = {"tomllib"}
_IMPORT_ERRORS = {"ImportError", "ModuleNotFoundError", "Exception",
                  "BaseException"}


def _is_gate(node: ast.AST) -> bool:
    """A node whose body may legally contain target-incompatible
    imports: a try with an except arm catching ImportError (or wider),
    or an `if` test mentioning sys.version_info."""
    if isinstance(node, ast.Try):
        for h in node.handlers:
            if h.type is None:
                return True
            names = []
            t = h.type
            for el in (t.elts if isinstance(t, ast.Tuple) else [t]):
                if isinstance(el, ast.Name):
                    names.append(el.id)
                elif isinstance(el, ast.Attribute):
                    names.append(el.attr)
            if _IMPORT_ERRORS & set(names):
                return True
        return False
    if isinstance(node, ast.If):
        for sub in ast.walk(node.test):
            if isinstance(sub, ast.Attribute) and \
                    sub.attr == "version_info":
                return True
    return False


def check_source(src: str, path: str) -> list[str]:
    """Problems found in one file's source, as `path:line: message`."""
    try:
        tree = ast.parse(src, filename=path, feature_version=TARGET)
    except SyntaxError as e:
        return [f"{path}:{e.lineno or 0}: does not parse as "
                f"py{TARGET[0]}.{TARGET[1]} syntax: {e.msg}"]
    problems: list[str] = []

    def visit(node: ast.AST, gated: bool) -> None:
        gated = gated or _is_gate(node)
        if isinstance(node, ast.Import):
            for alias in node.names:
                root = alias.name.split(".")[0]
                if root in BANNED_MODULES and not gated:
                    problems.append(
                        f"{path}:{node.lineno}: ungated `import "
                        f"{alias.name}` ({root} does not exist on "
                        f"py{TARGET[0]}.{TARGET[1]})")
        elif isinstance(node, ast.ImportFrom):
            mod = (node.module or "").split(".")[0]
            if mod in BANNED_MODULES and not gated:
                problems.append(
                    f"{path}:{node.lineno}: ungated `from {node.module} "
                    f"import ...` ({mod} does not exist on "
                    f"py{TARGET[0]}.{TARGET[1]})")
            if mod == "datetime" and not gated and \
                    any(a.name == "UTC" for a in node.names):
                problems.append(
                    f"{path}:{node.lineno}: ungated `from datetime "
                    f"import UTC` (py3.11+ only; use timezone.utc)")
        elif isinstance(node, ast.Attribute):
            if node.attr == "UTC" and not gated and \
                    isinstance(node.value, ast.Name) and \
                    node.value.id == "datetime":
                problems.append(
                    f"{path}:{node.lineno}: ungated `datetime.UTC` "
                    f"(py3.11+ only; use datetime.timezone.utc)")
        for child in ast.iter_child_nodes(node):
            visit(child, gated)

    visit(tree, False)
    return problems


def check_file(path: str) -> list[str]:
    try:
        with open(path, encoding="utf-8", errors="replace") as f:
            return check_source(f.read(), path)
    except OSError as e:
        return [f"{path}:0: unreadable: {e}"]


def iter_py_files(root: str):
    for dirpath, dirnames, filenames in os.walk(root):
        dirnames[:] = sorted(d for d in dirnames if d not in SKIP_DIRS)
        for name in sorted(filenames):
            if name.endswith(".py"):
                yield os.path.join(dirpath, name)


def check_tree(root: str) -> list[str]:
    problems: list[str] = []
    for path in iter_py_files(root):
        problems.extend(check_file(path))
    return problems


def main(argv: list[str]) -> int:
    roots = argv or [os.path.dirname(os.path.dirname(
        os.path.abspath(__file__)))]
    problems: list[str] = []
    checked = 0
    for root in roots:
        if os.path.isfile(root):
            problems.extend(check_file(root))
            checked += 1
        else:
            for path in iter_py_files(root):
                problems.extend(check_file(path))
                checked += 1
    for p in problems:
        print(p)
    print(f"check_py310: {checked} files, {len(problems)} problem(s)",
          file=sys.stderr)
    return 1 if problems else 0


if __name__ == "__main__":
    sys.exit(main(sys.argv[1:]))
