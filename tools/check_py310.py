#!/usr/bin/env python
"""Shim over weedlint rule W101 (tools/weedlint/rules_py310.py).

The standalone py3.10-compat AST lint moved onto the unified weedlint
engine (PR 10); this entry point and its helper names survive so
existing invocations and tests keep working:

    python tools/check_py310.py [root]        # exit 1 on violations
    python -m tools.weedlint --rule W101      # the same check
"""

from __future__ import annotations

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from tools.weedlint import Repo, get_rule  # noqa: E402
from tools.weedlint.rules_py310 import check_source as _check  # noqa: E402


def check_source(src: str, path: str) -> list[str]:
    return [f"{f.path}:{f.line}: {f.message}" for f in _check(src, path)]


def check_tree(root: str) -> list[str]:
    findings = get_rule("W101").check(Repo(root))
    return [f"{f.path}:{f.line}: {f.message}" for f in findings]


def main(argv: list[str]) -> int:
    roots = argv or [os.path.dirname(
        os.path.dirname(os.path.abspath(__file__)))]
    problems: list[str] = []
    for root in roots:  # the old CLI took several roots/files: keep it
        if os.path.isfile(root):
            problems.extend(
                check_source(open(root, encoding="utf-8").read(), root))
        else:
            problems.extend(check_tree(root))
    for p in problems:
        print(p)
    print(f"check_py310: {len(problems)} problem(s)", file=sys.stderr)
    return 1 if problems else 0


if __name__ == "__main__":
    sys.exit(main(sys.argv[1:]))
