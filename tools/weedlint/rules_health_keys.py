"""W401: the four degraded-signal tables must agree.

Ported from tools/check_health_keys.py (PR 9).  Four tables describe
"what counts as degraded" and they MUST stay consistent:

  stats/aggregate.py        HEALTH_FAMILIES       /cluster/health keys
  observability/analysis.py DEGRADE_COUNTER_KEYS  analyzer verdict
  observability/events.py   EVENT_TYPES + HEALTH_EVENT_TYPES
  observability/alerts.py   default_rules()       what actually pages

check_tables() takes the tables as ARGUMENTS so tests can feed
synthetically drifted tables and prove each consistency rule catches.
The repo rule imports the live tables (the lint runs in-process, like
the tier-1 test always has).
"""

from __future__ import annotations

import os

from .engine import Finding, Repo, Rule, register

EVENTS_REL = os.path.join("seaweedfs_tpu", "observability", "events.py")

# HEALTH_FAMILIES keys that legitimately stay OUT of
# DEGRADE_COUNTER_KEYS: a degraded TCP bind means a server came up
# without its fast plane — operationally alertable, but it does not
# make a pipeline MEASUREMENT degraded.  The coordinator keys are
# cluster-topology conditions (volumes short of k+1 clean shards,
# master-side repair plans failing): alertable, never an attribute of
# one encode/read run's measurement.  The request-plane keys
# (requests_shed / deadline_exceeded / retry_budget_exhausted) are
# load conditions on the serving plane — they page through their
# counter rules and the burn-rate SLOs, but an encode run does not
# become a degraded MEASUREMENT because some other client got shed.
# reqlog_records_dropped is observability loss (the workload recording
# under-represents the stream): alertable, but it never makes the
# measured run itself degraded.  dataplane_conn_aborts is a serving-
# plane load/teardown condition (a slow client lost its connection, a
# stop aborted in-flight work) — it pages through its counter rule but
# does not make an encode/read MEASUREMENT degraded.  loop_lag is the
# same kind of serving-plane saturation condition (the reactor loop
# was blocked; requests waited) — it pages through its counter rule
# and the loop_stall journal-event relay, but an encode/read run's
# MEASUREMENT is not retroactively degraded because the serving loop
# hiccuped.  autoscale_failures is the same cluster-topology class as
# the coordinator keys: a failed replica-grow/tier leg pages through
# its counter rule, it never degrades one measured run.
DEGRADE_KEY_ALLOWLIST = ("degraded_binds", "ec_under_replicated",
                         "coordinator_repair_failures",
                         "requests_shed", "deadline_exceeded",
                         "retry_budget_exhausted",
                         "reqlog_records_dropped",
                         "dataplane_conn_aborts",
                         "loop_lag",
                         "autoscale_failures")

# DEGRADE_COUNTER_KEYS entries that are per-run encode stats rather
# than cluster counter families.
PER_RUN_ONLY_KEYS = ("retries", "fallbacks")


def check_tables(health_families: dict, degrade_keys: tuple,
                 rules: list, event_types: dict,
                 health_event_types: dict,
                 extra_health_keys: tuple = ("scrub_unrepairable",),
                 allowlist: tuple = DEGRADE_KEY_ALLOWLIST,
                 per_run_only: tuple = PER_RUN_ONLY_KEYS,
                 journal_event_types: tuple = (),
                 heat_metric_families: tuple = (),
                 registered_metrics=None) -> list[str]:
    """Human-readable violations (empty = consistent)."""
    v: list[str] = []
    health_keys = set(health_families)

    # 1. every health key maps to a registered journal event type
    for key in sorted(health_keys):
        etype = health_event_types.get(key)
        if not etype:
            v.append(f"HEALTH_FAMILIES key {key!r} has no event type in "
                     "events.HEALTH_EVENT_TYPES — its degraded moments "
                     "would never reach the journal")
        elif etype not in event_types:
            v.append(f"HEALTH_EVENT_TYPES maps {key!r} -> {etype!r} "
                     "which is not registered in events.EVENT_TYPES")
    for key in sorted(health_event_types):
        if key not in health_keys:
            v.append(f"HEALTH_EVENT_TYPES covers {key!r} which is not "
                     "a HEALTH_FAMILIES key (stale mapping)")

    # 2. every health key (minus the allowlist) degrades analyzer runs
    for key in sorted(health_keys - set(allowlist)):
        if key not in degrade_keys:
            v.append(f"HEALTH_FAMILIES key {key!r} missing from "
                     "analysis.DEGRADE_COUNTER_KEYS — a run that "
                     "tripped it would still read clean")
    for key in degrade_keys:
        if key in per_run_only:
            continue
        if key not in health_keys:
            v.append(f"DEGRADE_COUNTER_KEYS entry {key!r} is not a "
                     "HEALTH_FAMILIES key (and not a documented "
                     "per-run stat) — /cluster/health would never "
                     "carry it")

    # 3. every health key is watched by a default counter rule
    watched = {r.params.get("key") for r in rules
               if getattr(r, "kind", "") == "counter_increase"}
    for key in sorted(health_keys):
        if key not in watched:
            v.append(f"HEALTH_FAMILIES key {key!r} has no default "
                     "counter_increase alert rule — it would degrade "
                     "/cluster/health without ever paging")

    # 4. every rule that names a health key names a REAL one
    legal = health_keys | set(extra_health_keys)
    for r in rules:
        kind = getattr(r, "kind", "")
        key = (getattr(r, "params", None) or {}).get("key")
        if kind in ("counter_increase", "threshold") and key not in legal:
            v.append(f"alert rule {getattr(r, 'name', '?')!r} watches "
                     f"unknown health key {key!r}")

    # 5. the alert lifecycle's own event types exist
    for etype in ("alert_pending", "alert_fired", "alert_resolved"):
        if etype not in event_types:
            v.append(f"event type {etype!r} missing from EVENT_TYPES — "
                     "alert transitions would journal as unregistered "
                     "types")

    # 6. a counter rule's severity must match its event type's —
    #    EVENT_TYPES is the ONE severity table
    for r in rules:
        if getattr(r, "kind", "") != "counter_increase":
            continue
        key = (getattr(r, "params", None) or {}).get("key")
        etype = health_event_types.get(key or "")
        want = event_types.get(etype or "")
        got = getattr(r, "severity", None)
        if want and got != want:
            v.append(f"alert rule {getattr(r, 'name', '?')!r} severity "
                     f"{got!r} disagrees with EVENT_TYPES[{etype!r}] = "
                     f"{want!r}")

    # 7. detector-relay consistency: every declared journal-event type
    #    (heat.HEAT_EVENT_TYPES) is a registered event type AND has a
    #    default journal_event rule whose severity matches EVENT_TYPES;
    #    every journal_event rule watches a declared, registered type
    je_rules = {(getattr(r, "params", None) or {}).get("event"): r
                for r in rules
                if getattr(r, "kind", "") == "journal_event"}
    for etype in journal_event_types:
        if etype not in event_types:
            v.append(f"journal-event type {etype!r} is not registered "
                     "in events.EVENT_TYPES — its emits would journal "
                     "as an unregistered type")
        r = je_rules.get(etype)
        if r is None:
            v.append(f"journal-event type {etype!r} has no default "
                     "journal_event alert rule — the detector would "
                     "emit without ever paging")
        elif etype in event_types and \
                getattr(r, "severity", None) != event_types[etype]:
            v.append(f"alert rule {getattr(r, 'name', '?')!r} severity "
                     f"{getattr(r, 'severity', None)!r} disagrees with "
                     f"EVENT_TYPES[{etype!r}] = {event_types[etype]!r}")
    for etype, r in je_rules.items():
        if journal_event_types and etype not in journal_event_types:
            v.append(f"journal_event rule {getattr(r, 'name', '?')!r} "
                     f"watches {etype!r} which is not a declared "
                     "journal-event type (heat.HEAT_EVENT_TYPES)")

    # 8. the heat plane's declared metric families exist in the live
    #    registry — a renamed gauge must not silently detach dashboards
    if registered_metrics is not None:
        for fam in heat_metric_families:
            if fam not in registered_metrics:
                v.append(f"heat metric family {fam!r} "
                         "(heat.HEAT_METRIC_FAMILIES) is not "
                         "registered in the stats registry")
    return v


def check_live_tables() -> list[str]:
    """The real tables, imported live."""
    from seaweedfs_tpu.observability.alerts import (EXTRA_HEALTH_KEYS,
                                                    default_rules)
    from seaweedfs_tpu.observability.analysis import DEGRADE_COUNTER_KEYS
    from seaweedfs_tpu.observability.events import (EVENT_TYPES,
                                                    HEALTH_EVENT_TYPES)
    from seaweedfs_tpu.observability.heat import (HEAT_EVENT_TYPES,
                                                  HEAT_METRIC_FAMILIES)
    from seaweedfs_tpu.observability.ledger import (LEDGER_EVENT_TYPES,
                                                    LEDGER_METRIC_FAMILIES)
    from seaweedfs_tpu.stats.aggregate import HEALTH_FAMILIES
    from seaweedfs_tpu.stats.metrics import (REGISTRY, dataplane_metrics,
                                             heat_metrics, ledger_metrics)

    # force-register the lazily-created singletons whose families the
    # declared tuples promise
    heat_metrics()
    ledger_metrics()
    dataplane_metrics()
    registered = {getattr(c, "name", "") for c in REGISTRY._collectors}
    return check_tables(HEALTH_FAMILIES, DEGRADE_COUNTER_KEYS,
                        default_rules(), EVENT_TYPES,
                        HEALTH_EVENT_TYPES,
                        extra_health_keys=EXTRA_HEALTH_KEYS,
                        journal_event_types=HEAT_EVENT_TYPES
                        + LEDGER_EVENT_TYPES,
                        heat_metric_families=HEAT_METRIC_FAMILIES
                        + LEDGER_METRIC_FAMILIES,
                        registered_metrics=registered)


@register
class HealthKeysRule(Rule):
    id = "W401"
    name = "health-keys"
    summary = ("HEALTH_FAMILIES / DEGRADE_COUNTER_KEYS / EVENT_TYPES / "
               "default alert rules must stay mutually consistent")
    hint = ("add the key to every table (aggregate.py, analysis.py, "
            "events.py, alerts.default_rules) or to the documented "
            "allowlists")

    def check(self, repo: Repo) -> list[Finding]:
        if repo.get(EVENTS_REL) is None:
            # a tree without the observability stack (mini test repos,
            # partial checkouts) has no tables to cross-check — and
            # importing a foreign `seaweedfs_tpu` from such a root
            # would poison sys.modules for the whole process
            return []
        import sys
        if repo.root not in sys.path:  # the repo under lint must win
            sys.path.insert(0, repo.root)
        return [self.finding(EVENTS_REL, 0, msg)
                for msg in check_live_tables()]
