"""W901: every outbound call must carry an explicit timeout/deadline.

The deadline plane (utils/deadline.py) clamps every egress to the
caller's remaining budget — but only requests that HAVE a budget.  A
call site that leans on a helper's implicit default is a site where
nobody decided how long a hung peer may pin this thread: the default
silently changes under it, and the one call that mattered during an
incident turns out to have been willing to wait an hour.  This rule
makes the bound a visible, reviewed decision at EVERY egress site.

Checked callables (the same egress-site tables W201/W504 enforce
tracing and lock discipline against):

  http_json / http_json_retry / http_bytes / http_download /
  _pooled_request    — the pooled-HTTP chokepoint helpers
                       (utils/httpd.py);
  urlopen            — the one raw-HTTP user (W201-waived sites);
  create_connection  — raw sockets (the framed-TCP plane).

A call passes when it supplies `timeout=` (keyword) or fills the
helper's positional timeout slot.  Genuinely unbounded calls carry a
reasoned `# weedlint: disable=W901 <why>` waiver; the baseline stays
empty — new egress sites must decide their bound on day one.
"""

from __future__ import annotations

import ast

from .engine import Finding, Repo, Rule, register

PACKAGE = "seaweedfs_tpu"

# callable name -> 0-based index of its positional timeout slot
TIMEOUT_SLOTS = {
    "http_json": 3,
    "http_json_retry": 3,
    "http_bytes": 4,
    "http_download": 3,
    "_pooled_request": 4,
    "urlopen": 1,
    "create_connection": 1,
}


def _call_name(node: ast.Call) -> str:
    f = node.func
    if isinstance(f, ast.Name):
        return f.id
    if isinstance(f, ast.Attribute):
        return f.attr
    return ""


def call_has_timeout(node: ast.Call, slot: int) -> bool:
    if any(kw.arg == "timeout" for kw in node.keywords):
        return True
    if len(node.args) > slot:
        # the slot is filled positionally — unless by *args, which
        # cannot be verified statically (treated as missing so the
        # author writes timeout= explicitly or waives)
        return not any(isinstance(a, ast.Starred)
                       for a in node.args[:slot + 1])
    return False


def check_source(src: str, path: str, tree=None) -> list[Finding]:
    """Timeout-less egress calls in one module."""
    if tree is None:
        try:
            tree = ast.parse(src, filename=path)
        except SyntaxError:
            return []  # W101 reports unparseable files
    out: list[Finding] = []
    for node in ast.walk(tree):
        if not isinstance(node, ast.Call):
            continue
        name = _call_name(node)
        slot = TIMEOUT_SLOTS.get(name)
        if slot is None:
            continue
        if not call_has_timeout(node, slot):
            out.append(Finding(
                "W901", path, node.lineno,
                f"outbound call {name}() passes no explicit timeout — "
                f"nobody decided how long a hung peer may pin this "
                f"call site",
                "pass timeout=<seconds> (the deadline plane still "
                "clamps it to the caller's remaining budget), or "
                "waive with `# weedlint: disable=W901 <reason>`"))
    return out


@register
class TimeoutRequiredRule(Rule):
    id = "W901"
    name = "timeout-required"
    summary = ("every outbound call (http helpers, urlopen, raw "
               "sockets) must pass an explicit timeout or deadline")

    def check(self, repo: Repo) -> list[Finding]:
        problems: list[Finding] = []
        for ctx in repo.package_files(PACKAGE):
            problems.extend(check_source(ctx.source, ctx.rel, ctx.tree))
        return problems
