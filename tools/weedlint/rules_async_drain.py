"""W301: the streaming hot loop must never block on fetch.

Ported from tools/check_async_drain.py (PR 7).  The async multi-
buffered drain only pays off while nothing reintroduces a blocking
full-block fetch on the critical thread — a regression that stays
byte-correct and therefore invisible to every differential test:

  1. `_encode_file_staged`, `_encode_file_mmap` and `_encode_file_mesh`
     must each construct the AsyncDrainer (directly, or as per-device
     lanes through a DrainerGroup).
  2. Inside them, blocking-fetch calls (`_fetch`, `fetch`, `asarray`,
     `device_get`, `block_until_ready`) may appear ONLY within nested
     drain helpers (functions named `drain*`) — including the
     per-device `drain_fetch_dev`/`drain_write_dev` lane callbacks.
  3. Every `faultinject.hit("ec.drain")` in the package must sit
     lexically inside `with ... span("pipeline.drain", ...)` so
     delay-only slow-drain drills keep attributing to the drain stage.
"""

from __future__ import annotations

import ast
import os

from .engine import Finding, Repo, Rule, register

PACKAGE = "seaweedfs_tpu"
STREAMING_REL = os.path.join(PACKAGE, "ec", "streaming.py")
HOT_FUNCS = ("_encode_file_staged", "_encode_file_mmap",
             "_encode_file_mesh")
DRAINER_CTORS = {"AsyncDrainer", "DrainerGroup"}
BLOCKING_CALLS = {"_fetch", "fetch", "asarray", "device_get",
                  "block_until_ready"}
DRAIN_PREFIXES = ("drain", "_drain")


def _call_name(node: ast.Call) -> str:
    f = node.func
    if isinstance(f, ast.Name):
        return f.id
    if isinstance(f, ast.Attribute):
        return f.attr
    return ""


def _is_drain_helper(name: str) -> bool:
    return name.startswith(DRAIN_PREFIXES)


def _check_hot_func(fn: ast.AST, path: str) -> list[Finding]:
    problems: list[Finding] = []

    def walk(node: ast.AST, inside_drain: bool) -> None:
        for child in ast.iter_child_nodes(node):
            if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef)):
                walk(child, inside_drain or _is_drain_helper(child.name))
                continue
            if isinstance(child, ast.Call) and not inside_drain:
                name = _call_name(child)
                if name in BLOCKING_CALLS:
                    problems.append(Finding(
                        "W301", path, child.lineno,
                        f"blocking `{name}()` on the streaming hot "
                        f"loop (inside {fn.name}) — kernel output must "
                        f"come back through the async drainer (a "
                        f"drain* helper), not block the critical "
                        f"thread"))
            walk(child, inside_drain)

    walk(fn, False)
    return problems


def check_streaming_source(src: str, path: str) -> list[Finding]:
    """Rules 1+2 on ec/streaming.py."""
    try:
        tree = ast.parse(src, filename=path)
    except SyntaxError as e:
        return [Finding("W301", path, e.lineno or 0,
                        f"does not parse: {e.msg}")]
    problems: list[Finding] = []
    fns = {node.name: node for node in ast.walk(tree)
           if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef))}
    for name in HOT_FUNCS:
        fn = fns.get(name)
        if fn is None:
            problems.append(Finding(
                "W301", path, 0,
                f"{name} not found — the async-drain contract covers "
                f"it by name"))
            continue
        calls = {_call_name(c) for c in ast.walk(fn)
                 if isinstance(c, ast.Call)}
        if not (DRAINER_CTORS & calls):
            problems.append(Finding(
                "W301", path, fn.lineno,
                f"{name} no longer constructs AsyncDrainer (or a "
                f"DrainerGroup of per-device lanes) — the drain would "
                f"run inline on the critical thread and the drain-wait "
                f"stall returns"))
        problems.extend(_check_hot_func(fn, path))
    return problems


def check_drain_fault_source(src: str, path: str,
                             tree=None) -> list[Finding]:
    """Rule 3 on any package module."""
    if tree is None:
        try:
            tree = ast.parse(src, filename=path)
        except SyntaxError as e:
            return [Finding("W301", path, e.lineno or 0,
                            f"does not parse: {e.msg}")]
    problems: list[Finding] = []

    def span_names(with_node: ast.With) -> set[str]:
        names: set[str] = set()
        for item in with_node.items:
            ctx = item.context_expr
            if isinstance(ctx, ast.Call) and _call_name(ctx) == "span" \
                    and ctx.args \
                    and isinstance(ctx.args[0], ast.Constant):
                names.add(str(ctx.args[0].value))
        return names

    def walk(node: ast.AST, spans: frozenset) -> None:
        for child in ast.iter_child_nodes(node):
            child_spans = spans
            if isinstance(child, ast.With):
                child_spans = spans | span_names(child)
            if isinstance(child, ast.Call) \
                    and _call_name(child) == "hit" \
                    and child.args \
                    and isinstance(child.args[0], ast.Constant) \
                    and child.args[0].value == "ec.drain" \
                    and "pipeline.drain" not in spans:
                problems.append(Finding(
                    "W301", path, child.lineno,
                    'faultinject.hit("ec.drain") outside a `with '
                    'span("pipeline.drain")` block — delay-only '
                    'slow-drain drills would stop attributing to the '
                    'drain stage'))
            walk(child, child_spans)

    walk(tree, frozenset())
    return problems


@register
class AsyncDrainRule(Rule):
    id = "W301"
    name = "async-drain"
    summary = ("streaming encode hot loops must drain through "
               "AsyncDrainer, never block on fetch")

    def check(self, repo: Repo) -> list[Finding]:
        problems: list[Finding] = []
        streaming = repo.get(STREAMING_REL)
        if streaming is not None:
            problems.extend(
                check_streaming_source(streaming.source, STREAMING_REL))
        else:
            problems.append(Finding("W301", STREAMING_REL, 0, "missing"))
        for ctx in repo.package_files(PACKAGE):
            problems.extend(
                check_drain_fault_source(ctx.source, ctx.rel, ctx.tree))
        return problems
