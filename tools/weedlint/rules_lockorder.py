"""W503: interprocedural lock-ordering (deadlock) analysis.

A data race corrupts state; a lock-order cycle takes the whole server
down.  The per-class lockset checker cannot see this bug because it is
interprocedural by nature: thread 1 runs ``A.push`` (``with
self._lock`` then calls ``B.notify`` which takes ``B._lock``) while
thread 2 runs ``B.drain`` (``with self._lock`` then calls ``A.stats``
which takes ``A._lock``) — classic ABBA, invisible to any pass that
stops at the class boundary.

The rule builds a global LOCK-ACQUISITION graph over the shared call
graph (callgraph.py):

  - node: a lock at class granularity (``EventShipper._lock``) or
    module granularity (``mod.py:GLOBAL_LOCK``);
  - edge L1 -> L2: somewhere, L2 is (or can transitively be) acquired
    while L1 is held — from lexical ``with`` nesting, from a
    ``# holds:`` / ``*_locked`` entry contract followed by a ``with``,
    or from a call made under L1 into code whose transitive
    acquisition set contains L2.

Every cycle in that graph is a potential deadlock and is reported ONCE
(per strongly connected component) with the full acquisition path in
the finding hint — each hop names the function, file and line that
creates the edge, which is exactly the evidence needed to pick a
global order and fix it.

Self-cycles (re-acquiring a lock already held) are reported only for
non-reentrant locks and only from an explicit ``# holds:`` contract or
lexical nesting — the ``*_locked`` suffix seed is deliberately
excluded from self-cycle evidence because it over-approximates which
lock is held.

False-cycle caveat (documented blind spot): class-granular lock
identity merges all instances of a class, so two DIFFERENT instances
locking in opposite orders report as a cycle even when the runtime
objects are distinct.  That report is still actionable (instance
disambiguation is exactly what a reviewer must prove), and a reviewed
exception is waived on the acquisition line with
``# weedlint: disable=W503 <why the cycle cannot happen>``.
"""

from __future__ import annotations

from .callgraph import CallGraph, get_callgraph
from .engine import Finding, Repo, Rule, register


class _Edge:
    __slots__ = ("src", "dst", "rel", "lineno", "why")

    def __init__(self, src: str, dst: str, rel: str, lineno: int,
                 why: str):
        self.src = src
        self.dst = dst
        self.rel = rel
        self.lineno = lineno
        self.why = why


def _transitive_acquires(graph: CallGraph) -> dict[str, dict[str, tuple]]:
    """qname -> {lock id: (rel, lineno, via)} for every lock the
    function may acquire itself or through any resolvable callee.
    Fixpoint iteration (the graph has cycles: supervisors respawn
    workers that call back into the supervisor).  Spawn edges
    (Thread/Timer/submit) are excluded: a lock taken on the spawned
    thread never nests under the spawner's held locks."""
    edges = graph.sync_edges()
    acq: dict[str, dict[str, tuple]] = {}
    for q, node in graph.nodes.items():
        acq[q] = {a.lock: (node.rel, a.lineno, q)
                  for a in node.acquires}
    changed = True
    while changed:
        changed = False
        for q in graph.nodes:
            mine = acq[q]
            for callee in edges.get(q, ()):
                for lock, wit in acq.get(callee, {}).items():
                    if lock not in mine:
                        mine[lock] = wit
                        changed = True
    return acq


def build_lock_graph(graph: CallGraph) -> dict[str, dict[str, _Edge]]:
    """src lock -> {dst lock: witness edge}."""
    acq_star = _transitive_acquires(graph)
    out: dict[str, dict[str, _Edge]] = {}

    def add(src: str, dst: str, rel: str, lineno: int, why: str,
            allow_self: bool = False) -> None:
        if src == dst and not allow_self:
            return
        out.setdefault(src, {})
        if dst not in out[src]:
            out[src][dst] = _Edge(src, dst, rel, lineno, why)

    for q, node in graph.nodes.items():
        explicit_holds = "holds:" in graph.line(node.rel, node.lineno)
        # lexical + contract-entry nesting
        for a in node.acquires:
            for held in a.held:
                # self-cycle (re-acquiring a held non-reentrant lock)
                # only counts when the held set is trustworthy: lexical
                # nesting, or an explicit `# holds:` on the def line —
                # never the *_locked suffix's over-approximation
                held_is_lexical = held not in node.entry_holds
                allow_self = not a.reentrant and \
                    (held_is_lexical or explicit_holds)
                add(held, a.lock, node.rel, a.lineno,
                    f"{q} acquires {a.lock} at {node.rel}:{a.lineno} "
                    f"while holding {held}",
                    allow_self=allow_self)
        # interprocedural: a call under L1 reaches code acquiring L2
        for cs in node.calls:
            if not cs.held or cs.spawn:
                continue
            for callee in cs.callees:
                for lock, (wrel, wline, wq) in \
                        acq_star.get(callee, {}).items():
                    for held in cs.held:
                        add(held, lock, node.rel, cs.lineno,
                            f"{q} calls {cs.desc} at "
                            f"{node.rel}:{cs.lineno} holding {held}; "
                            f"{wq} acquires {lock} at {wrel}:{wline}")
    return out


def _sccs(adj: dict[str, dict[str, _Edge]]) -> list[list[str]]:
    """Tarjan, iterative.  Returns components with a cycle (size > 1,
    or a self-edge)."""
    index: dict[str, int] = {}
    low: dict[str, int] = {}
    on_stack: set[str] = set()
    stack: list[str] = []
    out: list[list[str]] = []
    counter = [0]
    nodes = sorted(set(adj) | {d for m in adj.values() for d in m})

    for root in nodes:
        if root in index:
            continue
        work = [(root, iter(sorted(adj.get(root, {}))))]
        index[root] = low[root] = counter[0]
        counter[0] += 1
        stack.append(root)
        on_stack.add(root)
        while work:
            v, it = work[-1]
            advanced = False
            for w in it:
                if w not in index:
                    index[w] = low[w] = counter[0]
                    counter[0] += 1
                    stack.append(w)
                    on_stack.add(w)
                    work.append((w, iter(sorted(adj.get(w, {})))))
                    advanced = True
                    break
                if w in on_stack:
                    low[v] = min(low[v], index[w])
            if advanced:
                continue
            work.pop()
            if work:
                pv = work[-1][0]
                low[pv] = min(low[pv], low[v])
            if low[v] == index[v]:
                comp = []
                while True:
                    w = stack.pop()
                    on_stack.discard(w)
                    comp.append(w)
                    if w == v:
                        break
                if len(comp) > 1 or v in adj.get(v, {}):
                    out.append(sorted(comp))
    return out


def _cycle_path(adj: dict[str, dict[str, _Edge]],
                comp: list[str]) -> list[_Edge]:
    """One concrete simple cycle through the SCC, as witness edges."""
    comp_set = set(comp)
    start = comp[0]
    if len(comp) == 1:
        return [adj[start][start]]
    # BFS back to start constrained to the component
    parent: dict[str, tuple[str, _Edge]] = {}
    queue = [start]
    seen = {start}
    while queue:
        v = queue.pop(0)
        for w, e in sorted(adj.get(v, {}).items()):
            if w not in comp_set:
                continue
            if w == start and v != start:
                path = [e]
                cur = v
                while cur != start:
                    p, pe = parent[cur]
                    path.append(pe)
                    cur = p
                return list(reversed(path))
            if w not in seen:
                seen.add(w)
                parent[w] = (v, e)
                queue.append(w)
    return []  # pragma: no cover - SCC guarantees a cycle exists


def check_lock_order(graph: CallGraph) -> list[Finding]:
    adj = build_lock_graph(graph)
    findings: list[Finding] = []
    for comp in _sccs(adj):
        path = _cycle_path(adj, comp)
        if not path:
            continue
        cycle = " -> ".join([e.src for e in path] + [path[0].src])
        anchor = path[0]
        hint = "; ".join(e.why for e in path)
        # the whole SCC is the deadlock-entangled lock SET (transitive
        # edges can make the shortest witness cycle skip members) —
        # name all of it, then give one concrete interleaving
        members = ", ".join(comp)
        findings.append(Finding(
            "W503", anchor.rel, anchor.lineno,
            f"lock-order cycle (potential deadlock) among "
            f"{{{members}}}; witness cycle {cycle}",
            f"acquisition path: {hint}.  Pick one global order (or "
            f"drop a lock before the cross-class call); waive on this "
            f"line only with proof the instances cannot interleave"))
    findings.sort(key=lambda f: (f.path, f.line, f.message))
    return findings


@register
class LockOrderRule(Rule):
    id = "W503"
    name = "lock-order-cycle"
    summary = ("lock-acquisition cycles across the whole-program call "
               "graph are potential deadlocks")

    def check(self, repo: Repo) -> list[Finding]:
        return check_lock_order(get_callgraph(repo))
