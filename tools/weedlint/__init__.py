"""weedlint: the repo's unified static-analysis framework.

One engine (tools/weedlint/engine.py), one rule registry, one CLI:

    python -m tools.weedlint                 # whole repo, every rule
    python -m tools.weedlint --rule W501     # one rule
    python -m tools.weedlint --json          # stable machine output
    python -m tools.weedlint --list-rules    # the rule table
    python -m tools.weedlint --update-baseline

Rules (see README "Static analysis" for the full table):

    W001  waiver hygiene (stale / reason-less waivers)    [engine]
    W101  py3.10 runtime compatibility                    [ported]
    W201  tracing chokepoint coverage                     [ported]
    W301  async-drain hot-loop discipline                 [ported]
    W401  degraded-signal table consistency               [ported]
    W501  lockset: guarded attribute outside its lock     [new]
    W502  lockset: unannotated mutation in threaded class [new]
    W503  lock-order cycles over the call graph
    W504  blocking call reachable under a held lock
    W505  blocking call reachable from an event-loop callback
    W601  route query-param parsing must 400, not 500     [new]
    W701  fault-point registry consistency + test cover   [new]
    W801  ec/ resource acquire without release-on-all-paths [new]
    W901  outbound calls must carry an explicit timeout
    W1001 bench.py sections must have SECTION_CAPS entries

Waive a finding inline with a reason:

    x = self._cursor  # weedlint: disable=W501 <why this is safe>
"""

from .engine import (Finding, Repo, Rule, RunResult,  # noqa: F401
                     all_rules, get_rule, main, run)
