"""W501/W502: lockset thread-safety checking for annotated classes.

Every lock-discipline bug this repo has shipped (`_sup_lock`
serialization in PR 7, scrubber verdict locking in PR 5, emit-time
server stamping in PR 9) was caught by manual review AFTER the fact.
This rule machine-checks the discipline, Go-race-detector style but
static and annotation-driven:

Annotations (plain comments, greppable, zero runtime cost):

  self._x = {}        # guarded-by: _lock
      declares that attribute `_x` of this class is protected by
      `self._lock`.  Put it on an assignment to the attribute
      (conventionally the __init__ site).

  def _helper(self):  # holds: _lock
      declares the method's CONTRACT is "called with self._lock held"
      (the `*_locked` name suffix declares the same thing).  Its body
      is checked as if the lock were held.

  def _on_event(...):  # thread-entry
      declares the method is invoked on other threads (hook callbacks,
      executor jobs the checker cannot see).  Methods passed to
      `threading.Thread(target=...)` / `Timer` / `.submit(...)` inside
      the class are discovered automatically.

  class Foo:  # weedlint: concurrent-class
      declares every public method may be called concurrently (server
      state reached from the threaded HTTP router).  Each public
      method becomes its own thread root.

Model: each thread entry is a ROOT; all public methods form one
synthetic "external caller" root (unless concurrent-class splits them).
The per-class call graph (self.m() calls and `self.m` references)
gives which roots reach which methods.  `__init__`/`__del__` are
exempt (happens-before construction / teardown).

W501 fires on a read or write of a guarded attribute that is not
lexically inside `with self.<lock>` (and not in a holds:-annotated
method), when the access can actually race: its method is reachable
from ≥ 2 roots, or the attribute is also touched from a different
root.  Code inside nested functions is checked WITHOUT the enclosing
`with` (a closure may run on another thread after the lock is
dropped).

W502 fires when a class that has thread entries at all performs a
NAKED mutation — no lock held lexically or by holds:/`*_locked`
contract — of an attribute that carries no `guarded-by:` annotation
(outside __init__, in a root-reachable method).  Self-synchronizing
attributes (Lock/Event/Queue/Thread/... constructions) are exempt.
The point is to force every shared mutable field to either name its
lock or carry an explicit waiver saying why it needs none.
"""

from __future__ import annotations

import ast
import re
from typing import Optional

from .engine import Finding, Repo, Rule, register

PACKAGE = "seaweedfs_tpu"

_GUARDED_RE = re.compile(r"#\s*guarded-by:\s*([A-Za-z_][A-Za-z0-9_]*)")
_HOLDS_RE = re.compile(r"#\s*holds:\s*([A-Za-z_][A-Za-z0-9_]*)")
_THREAD_ENTRY_RE = re.compile(r"#\s*thread-entry\b")
_CONCURRENT_RE = re.compile(r"#\s*weedlint:\s*concurrent-class\b")

# constructions whose instances synchronize themselves — mutating
# THROUGH them is safe, and rebinding them outside __init__ is rare
# enough to exempt.  Thread/Timer cover the conventional `self._thread
# = Thread(...)` management attribute itself.
_SYNC_PRIMITIVES = {
    "Lock", "RLock", "Event", "Condition", "Semaphore",
    "BoundedSemaphore", "Barrier", "Queue", "LifoQueue",
    "PriorityQueue", "SimpleQueue", "ThreadPoolExecutor", "local",
    "Thread", "Timer",
}

EXTERNAL_ROOT = "<external>"


def _self_attr(node: ast.AST) -> Optional[str]:
    """`self.X` -> "X", else None."""
    if isinstance(node, ast.Attribute) and \
            isinstance(node.value, ast.Name) and node.value.id == "self":
        return node.attr
    return None


class _ClassModel:
    """Everything the lockset needs about one class."""

    def __init__(self, node: ast.ClassDef, lines: list[str]):
        self.node = node
        self.lines = lines
        self.name = node.name
        self.methods: dict[str, ast.AST] = {}
        for item in node.body:
            if isinstance(item, (ast.FunctionDef, ast.AsyncFunctionDef)):
                self.methods[item.name] = item
        self.concurrent = self._line_has(_CONCURRENT_RE, node.lineno)
        self.guards = self._collect_guards()     # attr -> lock name
        self.sync_attrs = self._collect_sync_attrs()
        self.thread_entries = self._collect_thread_entries()
        self.edges = self._call_graph()
        self.roots = self._compute_roots()
        self.method_roots = self._reachability()
        self.attr_roots = self._attr_root_spans()

    # --- annotation parsing ----------------------------------------------
    def _line_has(self, rx: re.Pattern, lineno: int) -> bool:
        line = self.lines[lineno - 1] if 0 < lineno <= len(self.lines) \
            else ""
        return rx.search(line) is not None

    def _collect_guards(self) -> dict[str, str]:
        guards: dict[str, str] = {}
        for sub in ast.walk(self.node):
            if not isinstance(sub, (ast.Assign, ast.AnnAssign,
                                    ast.AugAssign)):
                continue
            line = self.lines[sub.lineno - 1] \
                if 0 < sub.lineno <= len(self.lines) else ""
            m = _GUARDED_RE.search(line)
            if m is None:
                continue
            targets = sub.targets if isinstance(sub, ast.Assign) \
                else [sub.target]
            for t in targets:
                attr = _self_attr(t)
                if attr is not None:
                    guards[attr] = m.group(1)
        return guards

    def _collect_sync_attrs(self) -> set[str]:
        out: set[str] = set()
        for sub in ast.walk(self.node):
            if not isinstance(sub, (ast.Assign, ast.AnnAssign)):
                continue
            value = sub.value
            if not isinstance(value, ast.Call):
                continue
            f = value.func
            name = f.id if isinstance(f, ast.Name) else (
                f.attr if isinstance(f, ast.Attribute) else "")
            if name not in _SYNC_PRIMITIVES:
                continue
            targets = sub.targets if isinstance(sub, ast.Assign) \
                else [sub.target]
            for t in targets:
                attr = _self_attr(t)
                if attr is not None:
                    out.add(attr)
        return out

    def _collect_thread_entries(self) -> set[str]:
        """Methods that run on other threads: Thread/Timer targets,
        executor submissions, and `# thread-entry` annotations."""
        entries: set[str] = set()
        for sub in ast.walk(self.node):
            if not isinstance(sub, ast.Call):
                continue
            f = sub.func
            fname = f.id if isinstance(f, ast.Name) else (
                f.attr if isinstance(f, ast.Attribute) else "")
            if fname in ("Thread", "Timer"):
                for kw in sub.keywords:
                    if kw.arg == "target":
                        attr = _self_attr(kw.value)
                        if attr in self.methods:
                            entries.add(attr)
                # Timer(interval, self.m)
                for a in sub.args:
                    attr = _self_attr(a)
                    if attr in self.methods:
                        entries.add(attr)
            elif fname == "submit" and sub.args:
                attr = _self_attr(sub.args[0])
                if attr in self.methods:
                    entries.add(attr)
        for name, fn in self.methods.items():
            if self._line_has(_THREAD_ENTRY_RE, fn.lineno):
                entries.add(name)
        return entries

    # --- graph ------------------------------------------------------------
    def _call_graph(self) -> dict[str, set[str]]:
        """method -> other class methods it calls or references."""
        edges: dict[str, set[str]] = {}
        for name, fn in self.methods.items():
            out: set[str] = set()
            for sub in ast.walk(fn):
                attr = _self_attr(sub)
                if attr is not None and attr in self.methods \
                        and attr != name:
                    out.add(attr)
            edges[name] = out
        return edges

    def _compute_roots(self) -> dict[str, set[str]]:
        """root label -> the methods it enters at."""
        roots: dict[str, set[str]] = {}
        for m in self.thread_entries:
            roots[f"thread:{m}"] = {m}
        # a PUBLIC thread-entry method stays externally callable too
        # (e.g. a journal emit() that is both the API and the hook), so
        # it belongs to the caller root as well as its own thread root
        public = {m for m in self.methods if not m.startswith("_")}
        if self.concurrent:
            for m in public:
                roots[f"caller:{m}"] = {m}
        elif public:
            roots[EXTERNAL_ROOT] = public
        return roots

    def _reachability(self) -> dict[str, set[str]]:
        """method -> set of root labels that can reach it."""
        reach: dict[str, set[str]] = {m: set() for m in self.methods}
        for label, starts in self.roots.items():
            seen: set[str] = set()
            stack = [s for s in starts if s in self.methods]
            while stack:
                m = stack.pop()
                if m in seen:
                    continue
                seen.add(m)
                stack.extend(self.edges.get(m, ()))
            for m in seen:
                reach[m].add(label)
        return reach

    def _attr_root_spans(self) -> dict[str, set[str]]:
        """guarded attr -> union of roots over every method touching
        it (the "can this access race with ANOTHER thread" test)."""
        spans: dict[str, set[str]] = {a: set() for a in self.guards}
        for name, fn in self.methods.items():
            if name in ("__init__", "__del__"):
                continue
            for sub in ast.walk(fn):
                attr = _self_attr(sub)
                if attr in spans:
                    spans[attr] |= self.method_roots.get(name, set())
        return spans

    # --- lock context -----------------------------------------------------
    def held_at_entry(self, fn: ast.AST) -> set[str]:
        held: set[str] = set()
        line = self.lines[fn.lineno - 1] \
            if 0 < fn.lineno <= len(self.lines) else ""
        for m in _HOLDS_RE.finditer(line):
            held.add(m.group(1))
        if fn.name.endswith("_locked"):
            # the repo's naming convention for called-with-lock-held
            # helpers: treat as holding every lock the class guards
            # with (plus a sentinel so the contract counts even before
            # any attribute is annotated)
            held.update(self.guards.values())
            held.add("<locked-suffix>")
        return held


def _with_locks(node: ast.With) -> set[str]:
    """Lock names acquired by `with self.<lock>:` items."""
    out: set[str] = set()
    for item in node.items:
        attr = _self_attr(item.context_expr)
        if attr is not None:
            out.add(attr)
    return out


class _MethodChecker:
    """Walk one method body tracking lexically-held locks."""

    def __init__(self, model: _ClassModel, mname: str, path: str):
        self.model = model
        self.mname = mname
        self.path = path
        self.reads: list[tuple[str, int, frozenset]] = []
        self.writes: list[tuple[str, int, frozenset]] = []

    def run(self) -> None:
        fn = self.model.methods[self.mname]
        held = frozenset(self.model.held_at_entry(fn))
        for stmt in getattr(fn, "body", []):
            self._walk(stmt, held, top=True)

    def _walk(self, node: ast.AST, held: frozenset, top: bool) -> None:
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.Lambda)) and not top:
            # a nested function may execute on another thread after the
            # enclosing `with` released the lock: check it lock-free
            for child in ast.iter_child_nodes(node):
                self._walk(child, frozenset(), top=False)
            return
        if isinstance(node, ast.With):
            inner = held | _with_locks(node)
            for item in node.items:
                self._walk(item.context_expr, held, top=False)
                if item.optional_vars is not None:
                    self._walk(item.optional_vars, held, top=False)
            for stmt in node.body:
                self._walk(stmt, inner, top=False)
            return
        # record attribute touches; store-vs-load from ctx
        attr = _self_attr(node)
        if attr is not None:
            if isinstance(node.ctx, (ast.Store, ast.Del)):
                self.writes.append((attr, node.lineno, held))
            else:
                # a Load that feeds a Subscript-store or mutating call
                # is still an access; reads and writes are flagged the
                # same way by W501, so Load is enough here
                self.reads.append((attr, node.lineno, held))
        for child in ast.iter_child_nodes(node):
            self._walk(child, held, top=False)

    def mutation_lines(self) -> list[tuple[str, int, frozenset]]:
        """Writes PLUS container mutations (`self.x[k] = v`,
        `self.x += 1` already lands in writes via Store ctx on the
        attribute for AugAssign? no — AugAssign target has Store ctx,
        so it is in writes; subscript stores show the attribute as a
        Load, handled here)."""
        fn = self.model.methods[self.mname]
        out = list(self.writes)
        held_map = {(a, ln): h for a, ln, h in self.reads}
        for sub in ast.walk(fn):
            target = None
            if isinstance(sub, (ast.Assign,)):
                targets = sub.targets
            elif isinstance(sub, (ast.AugAssign,)):
                targets = [sub.target]
            else:
                continue
            for t in targets:
                if isinstance(t, ast.Subscript):
                    target = _self_attr(t.value)
                    if target is not None:
                        held = held_map.get((target, t.value.lineno),
                                            frozenset())
                        out.append((target, sub.lineno, held))
        return out


def check_class_source(src: str, path: str,
                       tree: Optional[ast.AST] = None) -> list[Finding]:
    """Both lockset rules over every class in one module's source (the
    unit the synthetic-class tests drive)."""
    if tree is None:
        try:
            tree = ast.parse(src, filename=path)
        except SyntaxError:
            return []  # W101 owns parse errors
    lines = src.splitlines()
    findings: list[Finding] = []
    for node in ast.walk(tree):
        if isinstance(node, ast.ClassDef):
            findings.extend(_check_class(node, lines, path))
    return findings


def _check_class(node: ast.ClassDef, lines: list[str],
                 path: str) -> list[Finding]:
    model = _ClassModel(node, lines)
    multi_threaded = bool(model.thread_entries) or model.concurrent
    if not model.guards and not multi_threaded:
        return []
    findings: list[Finding] = []
    for mname in model.methods:
        if mname in ("__init__", "__del__"):
            continue
        roots = model.method_roots.get(mname, set())
        if not roots:
            continue  # dead/never-reached helper: nothing to race with
        checker = _MethodChecker(model, mname, path)
        checker.run()
        # --- W501: guarded attr touched without its lock ---------------
        seen_lines: set[tuple[str, int]] = set()
        for attr, lineno, held in checker.reads + checker.writes:
            lock = model.guards.get(attr)
            if lock is None or lock in held:
                continue
            # can it actually race?  method reachable from 2+ roots, or
            # the attribute is also touched from some OTHER root
            other = model.attr_roots.get(attr, set()) - roots
            if len(roots) < 2 and not other:
                continue
            if (attr, lineno) in seen_lines:
                continue
            seen_lines.add((attr, lineno))
            findings.append(Finding(
                "W501", path, lineno,
                f"{model.name}.{mname} touches self.{attr} "
                f"(guarded-by: {lock}) outside `with self.{lock}` — "
                f"reachable from {_fmt_roots(roots)}",
                f"wrap in `with self.{lock}:`, or mark the method "
                f"`# holds: {lock}` if every caller already holds it"))
        # --- W502: unannotated NAKED mutation in a threaded class ------
        # a mutation under SOME self.<lock> (lexically, or via a
        # holds:/’_locked’ contract) is at least deliberate — the rule
        # hunts naked writes to fields nobody has claimed a lock for
        if not multi_threaded:
            continue
        seen_w2: set[tuple[str, int]] = set()
        for attr, lineno, held in checker.mutation_lines():
            if attr in model.guards or attr in model.sync_attrs:
                continue
            if held:
                continue
            if (attr, lineno) in seen_w2:
                continue
            seen_w2.add((attr, lineno))
            findings.append(Finding(
                "W502", path, lineno,
                f"{model.name}.{mname} mutates self.{attr} but the "
                f"class has thread entries "
                f"({', '.join(sorted(model.thread_entries)) or 'concurrent-class'}) "
                f"and self.{attr} carries no `# guarded-by:` annotation",
                "annotate the attribute with its lock, or waive with "
                "a reason if it is genuinely single-threaded"))
    return findings


def _fmt_roots(roots: set[str]) -> str:
    return " + ".join(sorted(roots))


def _cached_findings(ctx) -> list[Finding]:
    """Both lockset rules share one pass per file (the engine's cached
    parse, one class-model build)."""
    cache = getattr(ctx, "_lockset_findings", None)
    if cache is None:
        tree = ctx.tree
        cache = [] if tree is None else \
            check_class_source(ctx.source, ctx.rel, tree=tree)
        ctx._lockset_findings = cache
    return cache


@register
class LocksetRule(Rule):
    id = "W501"
    name = "lockset-guarded"
    summary = ("`# guarded-by: <lock>` attributes must be accessed "
               "inside `with self.<lock>` in multi-thread-reachable "
               "methods")

    def check(self, repo: Repo) -> list[Finding]:
        out: list[Finding] = []
        for ctx in repo.package_files(PACKAGE):
            out.extend(f for f in _cached_findings(ctx)
                       if f.rule == "W501")
        return out


@register
class UnannotatedMutationRule(Rule):
    id = "W502"
    name = "lockset-unannotated"
    summary = ("classes with thread entries must annotate every "
               "mutated attribute with `# guarded-by:` (or waive)")

    def check(self, repo: Repo) -> list[Finding]:
        out: list[Finding] = []
        for ctx in repo.package_files(PACKAGE):
            out.extend(f for f in _cached_findings(ctx)
                       if f.rule == "W502")
        return out
