"""W101: reject code the py3.10 deployment runtime cannot import.

Ported from the standalone tools/check_py310.py (PR 4).  The deployment
container runs Python 3.10 — no PEP-701 nested same-quote f-strings, no
tomllib, no datetime.UTC.  A single 3.12-only construct in a widely-
imported module silently collection-errors every test that imports it
(the seed shipped exactly that in volume_server/server.py).

Checks, per .py file in the repo:
  - parses as py3.10 syntax (ast.parse feature_version=(3, 10));
  - `import tomllib` only inside an ImportError-catching try or a
    sys.version_info gate;
  - `from datetime import UTC` / `datetime.UTC` under the same rule.
"""

from __future__ import annotations

import ast

from .engine import Finding, Repo, Rule, register

TARGET = (3, 10)
BANNED_MODULES = {"tomllib"}
_IMPORT_ERRORS = {"ImportError", "ModuleNotFoundError", "Exception",
                  "BaseException"}


def _is_gate(node: ast.AST) -> bool:
    """A node whose body may legally contain target-incompatible
    imports: a try with an except arm catching ImportError (or wider),
    or an `if` test mentioning sys.version_info."""
    if isinstance(node, ast.Try):
        for h in node.handlers:
            if h.type is None:
                return True
            names = []
            t = h.type
            for el in (t.elts if isinstance(t, ast.Tuple) else [t]):
                if isinstance(el, ast.Name):
                    names.append(el.id)
                elif isinstance(el, ast.Attribute):
                    names.append(el.attr)
            if _IMPORT_ERRORS & set(names):
                return True
        return False
    if isinstance(node, ast.If):
        for sub in ast.walk(node.test):
            if isinstance(sub, ast.Attribute) and \
                    sub.attr == "version_info":
                return True
    return False


def check_source(src: str, path: str) -> list[Finding]:
    """Problems found in one file's source (the unit the planted-
    violation tests drive)."""
    try:
        tree = ast.parse(src, filename=path, feature_version=TARGET)
    except SyntaxError as e:
        return [Finding("W101", path, e.lineno or 0,
                        f"does not parse as py{TARGET[0]}.{TARGET[1]} "
                        f"syntax: {e.msg}")]
    problems: list[Finding] = []

    def visit(node: ast.AST, gated: bool) -> None:
        gated = gated or _is_gate(node)
        if isinstance(node, ast.Import):
            for alias in node.names:
                root = alias.name.split(".")[0]
                if root in BANNED_MODULES and not gated:
                    problems.append(Finding(
                        "W101", path, node.lineno,
                        f"ungated `import {alias.name}` ({root} does "
                        f"not exist on py{TARGET[0]}.{TARGET[1]})",
                        "wrap in try/except ImportError or a "
                        "sys.version_info gate"))
        elif isinstance(node, ast.ImportFrom):
            mod = (node.module or "").split(".")[0]
            if mod in BANNED_MODULES and not gated:
                problems.append(Finding(
                    "W101", path, node.lineno,
                    f"ungated `from {node.module} import ...` ({mod} "
                    f"does not exist on py{TARGET[0]}.{TARGET[1]})",
                    "wrap in try/except ImportError or a "
                    "sys.version_info gate"))
            if mod == "datetime" and not gated and \
                    any(a.name == "UTC" for a in node.names):
                problems.append(Finding(
                    "W101", path, node.lineno,
                    "ungated `from datetime import UTC` (py3.11+ "
                    "only)", "use timezone.utc"))
        elif isinstance(node, ast.Attribute):
            if node.attr == "UTC" and not gated and \
                    isinstance(node.value, ast.Name) and \
                    node.value.id == "datetime":
                problems.append(Finding(
                    "W101", path, node.lineno,
                    "ungated `datetime.UTC` (py3.11+ only)",
                    "use datetime.timezone.utc"))
        for child in ast.iter_child_nodes(node):
            visit(child, gated)

    visit(tree, False)
    return problems


@register
class Py310Rule(Rule):
    id = "W101"
    name = "py310-compat"
    summary = ("code must import on the py3.10 runtime (syntax, "
               "tomllib, datetime.UTC)")

    def check(self, repo: Repo) -> list[Finding]:
        out: list[Finding] = []
        for ctx in repo.files():
            out.extend(check_source(ctx.source, ctx.rel))
        return out
