"""W801: ec/ resources must be released on every path.

The EC pipelines juggle SharedMemory slabs, mmap views and shard file
handles across threads and processes.  A leaked /dev/shm slab survives
the process; a leaked mmap keeps a BufferError landmine armed; a
leaked fd on a 14-shard encode multiplies fast.  The discipline the
code review keeps re-enforcing by hand:

    every `open(...)` / `mmap.mmap(...)` / `SharedMemory(...)` in
    seaweedfs_tpu/ec/ must either
      - be the context expression of a `with` statement, or
      - be assigned to `self.<attr>` (object-lifetime managed: the
        owning class's close() is responsible), or
      - flow into a name (or a list the call's result is append()ed
        to) that is referenced inside a `finally:` or `except:` block
        of the same function — a release that runs on the failure
        path, not just the happy one.

Module-level and test code is out of scope; only ec/ is checked (the
resource-density there earns the strictness).
"""

from __future__ import annotations

import ast
import os
from typing import Optional

from .engine import Finding, Repo, Rule, register

EC_PREFIX = os.path.join("seaweedfs_tpu", "ec") + os.sep


def _acquire_kind(node: ast.Call) -> Optional[str]:
    f = node.func
    if isinstance(f, ast.Name) and f.id in ("open", "SharedMemory"):
        return f.id
    if isinstance(f, ast.Attribute) and f.attr in ("mmap", "SharedMemory"):
        # mmap_mod.mmap(...) / shared_memory.SharedMemory(...); method
        # calls like worker.open(...) are NOT builtin open and are
        # excluded by the Name check above
        return f.attr
    return None


def _cleanup_names(fn: ast.AST) -> set[str]:
    """Names referenced anywhere inside a finally: or except: block of
    this function (its release-on-failure surface)."""
    names: set[str] = set()

    def collect(stmts) -> None:
        for stmt in stmts:
            for sub in ast.walk(stmt):
                if isinstance(sub, ast.Name):
                    names.add(sub.id)
                elif isinstance(sub, ast.Attribute):
                    names.add(sub.attr)

    for node in ast.walk(fn):
        if isinstance(node, ast.Try):
            collect(node.finalbody)
            for h in node.handlers:
                collect(h.body)
    return names


def _outermost_functions(tree: ast.AST):
    """Module-level functions and class methods — NOT nested closures:
    a nested helper's acquires are judged against the whole enclosing
    function (its finally blocks release what the closures acquire,
    e.g. the mmap-encode's lazy parity mappings)."""
    stack = [tree]
    while stack:
        node = stack.pop()
        for child in ast.iter_child_nodes(node):
            if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef)):
                yield child
            elif isinstance(child, (ast.Module, ast.ClassDef)):
                stack.append(child)
            elif isinstance(child, (ast.If, ast.Try)):
                stack.append(child)  # conditionally-defined functions


def check_module_source(src: str, path: str, tree=None) -> list[Finding]:
    if tree is None:
        try:
            tree = ast.parse(src, filename=path)
        except SyntaxError:
            return []  # W101 owns parse errors
    findings: list[Finding] = []
    for fn in _outermost_functions(tree):
        findings.extend(_check_function(fn, path))
    return findings


def _check_function(fn: ast.AST, path: str) -> list[Finding]:
    cleanup = _cleanup_names(fn)
    findings: list[Finding] = []
    # contexts where an acquire call is fine without further analysis
    with_exprs: set[int] = set()
    bound_to: dict[int, Optional[str]] = {}  # id(call) -> bound name
    aliases: dict[str, set[str]] = {}  # name -> lists it is append()ed to
    for node in ast.walk(fn):
        if isinstance(node, ast.With):
            for item in node.items:
                for sub in ast.walk(item.context_expr):
                    if isinstance(sub, ast.Call):
                        with_exprs.add(id(sub))
        elif isinstance(node, ast.Assign):
            target = node.targets[0]
            calls: list[ast.Call] = []
            if isinstance(node.value, ast.Call):
                calls = [node.value]
            elif isinstance(node.value, (ast.ListComp, ast.DictComp,
                                         ast.SetComp, ast.GeneratorExp)):
                # inputs = {i: open(...) for ...}: the handles live in
                # the comp result, so the TARGET name is the handle
                calls = [sub for sub in ast.walk(node.value)
                         if isinstance(sub, ast.Call)]
            for call in calls:
                if isinstance(target, ast.Name):
                    bound_to[id(call)] = target.id
                elif isinstance(target, ast.Attribute) and \
                        isinstance(target.value, ast.Name) and \
                        target.value.id == "self":
                    bound_to[id(call)] = None  # self.X: exempt
        elif isinstance(node, ast.Call):
            # list.append(open(...)) / list.append(handle_name) — the
            # list carries the handle from then on
            f = node.func
            if isinstance(f, ast.Attribute) and f.attr == "append" \
                    and isinstance(f.value, ast.Name) and node.args:
                arg = node.args[0]
                if isinstance(arg, ast.Call):
                    bound_to[id(arg)] = f.value.id
                elif isinstance(arg, ast.Name):
                    aliases.setdefault(arg.id, set()).add(f.value.id)

    for node in ast.walk(fn):
        # nested functions are walked as part of the outer function
        # too; that is fine — their cleanup blocks were collected the
        # same way
        if not isinstance(node, ast.Call):
            continue
        kind = _acquire_kind(node)
        if kind is None:
            continue
        if id(node) in with_exprs:
            continue
        if id(node) in bound_to:
            name = bound_to[id(node)]
            if name is None:  # self.<attr>: the class owns the release
                continue
            if name in cleanup or aliases.get(name, set()) & cleanup:
                continue
        findings.append(Finding(
            "W801", path, node.lineno,
            f"`{kind}(...)` acquired without a release on all paths — "
            f"not a `with` context, not owned by self, and its handle "
            f"is never touched in a finally/except block of this "
            f"function",
            "use `with ...:`, or close/unlink the handle in a "
            "finally: block"))
    return findings


@register
class ResourceReleaseRule(Rule):
    id = "W801"
    name = "ec-resource-release"
    summary = ("SharedMemory/mmap/open in ec/ must be released on all "
               "paths (with-block, self-owned, or finally/except)")

    def check(self, repo: Repo) -> list[Finding]:
        out: list[Finding] = []
        for ctx in repo.files():
            if not ctx.rel.startswith(EC_PREFIX):
                continue
            tree = ctx.tree
            if tree is None:
                continue
            out.extend(check_module_source(ctx.source, ctx.rel, tree))
        return out
