"""W1101: new code cannot silently opt out of resource accounting.

The resource ledger (observability/ledger.py) is enforced at the same
two chokepoints tracing is (W201): utils/httpd.py Router.dispatch is
the ONE ingress every HTTP handler runs under, and
utils/framing.serve_frame is the ONE per-frame path both native-TCP
fronts (threaded accept loop and reactor dataplane) share.  Each must
stamp the request with RequestLedger.begin() on entry and settle it
(settle_http / settle_native) on the way out — otherwise a whole
ingress class runs unaccounted and `cluster.top` silently lies about
who is consuming the serving CPU.

A genuinely-unaccountable path is waived per line with
`# weedlint: disable=W1101 <reason>`; the checked-in baseline stays
EMPTY — both chokepoints are wired, so a violation here is a
regression, never legacy debt.
"""

from __future__ import annotations

import ast
import os

from .engine import Finding, Repo, Rule, register
from .rules_tracing import _calls_in, _functions

PACKAGE = "seaweedfs_tpu"
HTTPD_REL = os.path.join(PACKAGE, "utils", "httpd.py")
FRAMING_REL = os.path.join(PACKAGE, "utils", "framing.py")


def check_dispatch_source(src: str, path: str) -> list[Finding]:
    """The HTTP-ingress accounting contract on utils/httpd.py."""
    try:
        tree = ast.parse(src, filename=path)
    except SyntaxError as e:
        return [Finding("W1101", path, e.lineno or 0,
                        f"does not parse: {e.msg}")]
    fns = _functions(tree)
    dispatch = fns.get("dispatch")
    if dispatch is None:
        return [Finding("W1101", path, 0, "Router.dispatch not found")]
    problems: list[Finding] = []
    calls = _calls_in(dispatch)
    if "begin" not in calls:
        problems.append(Finding(
            "W1101", path, dispatch.lineno,
            "Router.dispatch no longer calls ledger.begin() — HTTP "
            "requests would run with no thread-CPU baseline and the "
            "resource ledger would attribute nothing"))
    if "settle_http" not in calls:
        problems.append(Finding(
            "W1101", path, dispatch.lineno,
            "Router.dispatch no longer calls ledger.settle_http() — "
            "HTTP requests would never land in the per-route/per-"
            "client ledgers and cluster.top would miss the whole "
            "HTTP ingress"))
    return problems


def check_framing_source(src: str, path: str) -> list[Finding]:
    """The framed-TCP accounting contract on utils/framing.py."""
    try:
        tree = ast.parse(src, filename=path)
    except SyntaxError as e:
        return [Finding("W1101", path, e.lineno or 0,
                        f"does not parse: {e.msg}")]
    fns = _functions(tree)
    frame_fn = fns.get("serve_frame")
    if frame_fn is None:
        return [Finding("W1101", path, 0,
                        "framing.serve_frame not found")]
    problems: list[Finding] = []
    calls = _calls_in(frame_fn)
    if "begin" not in calls:
        problems.append(Finding(
            "W1101", path, frame_fn.lineno,
            "serve_frame no longer calls ledger.begin() — native "
            "frames would run with no thread-CPU baseline"))
    if "settle_native" not in calls:
        problems.append(Finding(
            "W1101", path, frame_fn.lineno,
            "serve_frame no longer calls ledger.settle_native() — "
            "the native TCP ingress would run unaccounted and "
            "cluster.top would miss the fast plane entirely"))
    return problems


@register
class LedgerChokepointRule(Rule):
    id = "W1101"
    name = "ledger-chokepoint"
    summary = ("both ingress chokepoints must stamp and settle the "
               "per-request resource ledger (begin/settle_http in "
               "Router.dispatch, begin/settle_native in serve_frame)")
    hint = ("keep the ledger.begin()/settle_*() pair at the "
            "chokepoint, or waive a genuinely-unaccountable path with "
            "`# weedlint: disable=W1101 <reason>`")

    def check(self, repo: Repo) -> list[Finding]:
        problems: list[Finding] = []
        httpd = repo.get(HTTPD_REL)
        if httpd is not None:
            problems.extend(
                check_dispatch_source(httpd.source, HTTPD_REL))
        framing = repo.get(FRAMING_REL)
        if framing is not None:
            problems.extend(
                check_framing_source(framing.source, FRAMING_REL))
        return problems
