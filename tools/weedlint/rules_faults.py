"""W701: fault-point names live in ONE registry, and each is tested.

The fault-injection framework (utils/faultinject.py) is only as good
as its names: a `hit("ec.dran")` typo silently never fires, an armed
point nobody instruments silently never injects, and a registered
point no chaos drill exercises is recovery code that has never once
run.  This rule pins all three directions against the central
FAULT_POINTS registry:

  1. every `faultinject.hit("name")` / `corrupt_block("name", ...)`
     site in the package names a registered fault point;
  2. every registered fault point has at least one instrumented site;
  3. every registered fault point is exercised by at least one test
     (its quoted name appears in tests/ — arming via enable/scoped or
     asserting via fired()).

The registry is read from the AST (no package import needed), so the
rule also works on a checkout whose heavy deps are absent.
"""

from __future__ import annotations

import ast
import os

from .engine import Finding, Repo, Rule, register

PACKAGE = "seaweedfs_tpu"
FAULTINJECT_REL = os.path.join(PACKAGE, "utils", "faultinject.py")


def load_registry(src: str) -> tuple[dict[str, int], int]:
    """FAULT_POINTS from faultinject.py source -> ({name: lineno},
    dict lineno).  Empty when the registry is missing (finding)."""
    try:
        tree = ast.parse(src)
    except SyntaxError:
        return {}, 0
    for node in ast.walk(tree):
        targets = node.targets if isinstance(node, ast.Assign) else (
            [node.target] if isinstance(node, ast.AnnAssign) else [])
        if any(isinstance(t, ast.Name) and t.id == "FAULT_POINTS"
               for t in targets):
            if isinstance(node.value, ast.Dict):
                out = {}
                for k in node.value.keys:
                    if isinstance(k, ast.Constant) and \
                            isinstance(k.value, str):
                        out[k.value] = k.lineno
                return out, node.lineno
    return {}, 0


def hit_sites(src: str, path: str, tree=None) -> list[tuple[str, int]]:
    """(fault name, lineno) for every hit()/hit_peer()/peer_delay()/
    corrupt_block() literal — the full instrumented-site API of
    utils/faultinject.py (hit_peer and peer_delay are the peer-scoped
    net.* variants)."""
    if tree is None:
        try:
            tree = ast.parse(src, filename=path)
        except SyntaxError:
            return []
    out: list[tuple[str, int]] = []
    for node in ast.walk(tree):
        if not isinstance(node, ast.Call):
            continue
        f = node.func
        name = f.id if isinstance(f, ast.Name) else (
            f.attr if isinstance(f, ast.Attribute) else "")
        if name in ("hit", "hit_peer", "peer_delay", "corrupt_block") \
                and node.args and \
                isinstance(node.args[0], ast.Constant) and \
                isinstance(node.args[0].value, str):
            out.append((node.args[0].value, node.lineno))
    return out


def check_registry(registry: dict[str, int], registry_line: int,
                   sites: list[tuple[str, int, str]],
                   tests_text: str) -> list[Finding]:
    """The three-direction consistency check, tables-as-arguments so
    tests can feed planted drift.  `sites` is (name, lineno, path)."""
    findings: list[Finding] = []
    if not registry:
        return [Finding(
            "W701", FAULTINJECT_REL, registry_line,
            "FAULT_POINTS registry missing or empty — every fault "
            "point must be centrally registered with a description")]
    site_names = {name for name, _ln, _p in sites}
    for name, lineno, path in sites:
        if name not in registry:
            findings.append(Finding(
                "W701", path, lineno,
                f"fault point {name!r} is not in the FAULT_POINTS "
                f"registry (utils/faultinject.py) — a typo here would "
                f"silently never fire",
                "register it with a one-line description"))
    for name in sorted(registry):
        if name not in site_names:
            findings.append(Finding(
                "W701", FAULTINJECT_REL, registry[name],
                f"registered fault point {name!r} has no "
                f"hit()/corrupt_block() site in the package — it can "
                f"never inject",
                "instrument the site or delete the registry entry"))
        if f'"{name}"' not in tests_text and \
                f"'{name}'" not in tests_text:
            findings.append(Finding(
                "W701", FAULTINJECT_REL, registry[name],
                f"registered fault point {name!r} is not exercised by "
                f"any test under tests/ — its recovery path has never "
                f"run",
                "add a chaos drill arming it (faultinject.enable/"
                "scoped)"))
    return findings


@register
class FaultRegistryRule(Rule):
    id = "W701"
    name = "fault-registry"
    summary = ("faultinject.hit() names must be registered in "
               "FAULT_POINTS and each registered point test-exercised")

    def check(self, repo: Repo) -> list[Finding]:
        fi = repo.get(FAULTINJECT_REL)
        if fi is None:
            return [Finding("W701", FAULTINJECT_REL, 0, "missing")]
        registry, reg_line = load_registry(fi.source)
        sites: list[tuple[str, int, str]] = []
        for ctx in repo.package_files(PACKAGE):
            for name, lineno in hit_sites(ctx.source, ctx.rel, ctx.tree):
                sites.append((name, lineno, ctx.rel))
        tests_text = "\n".join(t.source for t in repo.test_files())
        return check_registry(registry, reg_line, sites, tests_text)
