"""W504: blocking calls reachable while a lock is held.

The stall signature our own alerting stack keeps attributing to
"drain_blocked" is almost never the drain: it is a hot lock held
across something slow — a shipper flushing a batch POST with its
buffer lock held, a scrubber reading a shard file inside ``_lock``, a
``Queue.get()`` with no timeout under a supervisor lock.  Every thread
that wants the lock then stalls behind one slow syscall, and at
production concurrency that reads as a cluster-wide latency cliff.

The rule classifies BLOCKING-CAPABLE call sites:

  - ``http-egress``: the repo's socket/HTTP chokepoints
    (``_pooled_request`` / ``http_json`` / ``http_bytes`` /
    ``http_download`` / ``http_post_file`` / ``urlopen``) — network
    round trips with multi-second timeouts;
  - ``sleep``: ``time.sleep(...)``;
  - ``queue``: ``.get()`` on a ``queue.Queue``-typed attribute
    without a timeout, and ``.put()`` likewise but only on BOUNDED
    queues — an unbounded ``Queue()`` put never blocks (``*_nowait``
    is exempt throughout);
  - ``event-wait``: ``.wait()`` with no timeout on an
    ``threading.Event``-typed attribute;
  - ``subprocess``: any ``subprocess.*`` invocation;
  - ``file-read``: an unbounded ``.read()`` on a handle opened in the
    same function (no size argument — the static stand-in for "over
    the size threshold").

and fires when such a site executes while a lock is held — lexically
inside ``with self._lock``, under a ``# holds:`` / ``*_locked`` entry
contract, or in a function REACHABLE through the call graph from a
call made with a lock held (the interprocedural case; the hint prints
the lock and the call chain).

Audited exceptions are waived AT THE BLOCKING LINE with::

    # weedlint: lock-io <why this blocking call is safe under the lock>

A ``lock-io`` waiver without a reason is itself a finding — the whole
point is a greppable audit trail of every place the repo blocks under
a lock on purpose.
"""

from __future__ import annotations

import ast
import re
from typing import Optional

from .callgraph import CallGraph, CallSite, Node, get_callgraph
from .engine import Finding, Repo, Rule, register

_LOCK_IO_RE = re.compile(r"#\s*weedlint:\s*lock-io(?:\s+(.*))?$")

EGRESS_CALLS = {"_pooled_request", "http_json", "http_bytes",
                "http_download", "http_post_file", "http_delete",
                "urlopen"}

_SUBPROCESS_FNS = {"run", "call", "check_call", "check_output",
                   "Popen", "communicate"}


def _last(desc: str) -> str:
    return desc.rsplit(".", 1)[-1]


def _queue_blocking(call: ast.Call, last: str) -> bool:
    """True when a Queue ``get``/``put`` can block forever: no
    ``timeout=``, no positional timeout, no ``block=False``.  ``get``
    signature is (block, timeout); ``put`` is (item, block, timeout)."""
    if any(kw.arg == "timeout" for kw in call.keywords):
        return False
    if any(kw.arg == "block" and isinstance(kw.value, ast.Constant)
           and kw.value.value is False for kw in call.keywords):
        return False
    first_flag = 0 if last == "get" else 1
    args = call.args
    if len(args) > first_flag + 1:
        return False   # positional timeout given
    if len(args) == first_flag + 1 and \
            isinstance(args[first_flag], ast.Constant) and \
            args[first_flag].value is False:
        return False   # block=False positionally
    return True


def _queue_event_receiver(call: ast.Call, node: Node,
                          graph: CallGraph,
                          kinds: str) -> bool:
    """Is the receiver of ``X.get()`` / ``X.put()`` / ``X.wait()`` a
    Queue/Event-typed self attribute?  For ``put``, only BOUNDED queues
    count — an unbounded ``Queue()`` put never blocks."""
    f = call.func
    if not isinstance(f, ast.Attribute):
        return False
    base = f.value
    if isinstance(base, ast.Attribute) and \
            isinstance(base.value, ast.Name) and base.value.id == "self":
        info = graph.class_of(node.cls) if node.cls else None
        if info is None:
            return False
        if kinds == "queue-get":
            attrs = info.queue_attrs
        elif kinds == "queue-put":
            attrs = info.bounded_queue_attrs
        else:
            attrs = info.event_attrs
        return base.attr in attrs
    return False


def classify_blocking(cs: CallSite, node: Node,
                      graph: CallGraph) -> Optional[str]:
    """Blocking category for one call site, or None."""
    desc = cs.desc
    last = _last(desc)
    call = cs.node
    if desc in ("time.sleep", "sleep"):
        return "sleep"
    if last in EGRESS_CALLS:
        return "http-egress"
    if desc.startswith("subprocess.") and last in _SUBPROCESS_FNS:
        return "subprocess"
    if last in ("get", "put") and _queue_blocking(call, last) \
            and _queue_event_receiver(call, node, graph,
                                      f"queue-{last}"):
        return "queue"
    if last == "wait" and not call.args and not call.keywords \
            and _queue_event_receiver(call, node, graph, "event"):
        return "event-wait"
    if last == "read" and not call.args and not call.keywords \
            and _reads_opened_handle(call, node):
        return "file-read"
    return None


def _reads_opened_handle(call: ast.Call, node: Node) -> bool:
    """``fh.read()`` where fh was bound from open(...) in this
    function (incl. ``with open(...) as fh``)."""
    f = call.func
    if not (isinstance(f, ast.Attribute) and
            isinstance(f.value, ast.Name)):
        return False
    name = f.value.id
    for sub in ast.walk(node.fn):
        if isinstance(sub, ast.Assign) and len(sub.targets) == 1 \
                and isinstance(sub.targets[0], ast.Name) \
                and sub.targets[0].id == name \
                and isinstance(sub.value, ast.Call) \
                and isinstance(sub.value.func, ast.Name) \
                and sub.value.func.id == "open":
            return True
        if isinstance(sub, ast.withitem) \
                and isinstance(sub.optional_vars, ast.Name) \
                and sub.optional_vars.id == name \
                and isinstance(sub.context_expr, ast.Call) \
                and isinstance(sub.context_expr.func, ast.Name) \
                and sub.context_expr.func.id == "open":
            return True
    return False


class _Origin:
    """One call site executed with a lock held — the root a
    reachability finding anchors to (that is where the fix or the
    waiver belongs, not the shared utility at the end of the chain)."""

    __slots__ = ("qname", "rel", "lineno", "lock")

    def __init__(self, qname: str, rel: str, lineno: int, lock: str):
        self.qname = qname
        self.rel = rel
        self.lineno = lineno
        self.lock = lock


def _lock_reachable(
        graph: CallGraph) -> dict[str, list[tuple[_Origin, list[str]]]]:
    """qname -> [(origin, shortest chain from origin)] for every
    function reachable from a call made with a lock held.  One BFS per
    origin so EVERY under-lock entry point is witnessed — fixing one
    origin must not hide the next."""
    edges = graph.sync_edges()
    reach: dict[str, list[tuple[_Origin, list[str]]]] = {}
    for q, node in graph.nodes.items():
        for cs in node.calls:
            if not cs.held or cs.spawn:
                continue
            origin = _Origin(q, node.rel, cs.lineno, sorted(cs.held)[0])
            seen: set[str] = set()
            queue: list[tuple[str, list[str]]] = [
                (callee, [callee]) for callee in sorted(cs.callees)]
            seen.update(c for c, _ in queue)
            while queue:
                cur, chain = queue.pop(0)
                reach.setdefault(cur, []).append((origin, chain))
                for callee in sorted(edges.get(cur, ())):
                    if callee not in seen:
                        seen.add(callee)
                        queue.append((callee, chain + [callee]))
    return reach


_HINT = ("move the call outside the lock (snapshot under the lock, do "
         "I/O after), or waive with `# weedlint: lock-io <reason>` if "
         "the block is audited and deliberate")


def check_blocking(graph: CallGraph) -> list[Finding]:
    reach = _lock_reachable(graph)
    findings: list[Finding] = []
    seen: set[tuple] = set()

    def report(rel: str, lineno: int, message: str, desc: str,
               hint: str) -> None:
        waiver = _lock_io_waiver(graph, rel, lineno)
        if waiver is not None:
            if waiver:
                return   # audited, reasoned: suppressed
            key = (rel, lineno, "no-reason")
            if key not in seen:
                seen.add(key)
                findings.append(Finding(
                    "W504", rel, lineno,
                    f"lock-io waiver on `{desc}` has no reason",
                    "# weedlint: lock-io <why blocking under this "
                    "lock is safe>"))
            return
        key = (rel, lineno, message)
        if key not in seen:
            seen.add(key)
            findings.append(Finding("W504", rel, lineno, message, hint))

    for q, node in graph.nodes.items():
        entry = node.entry_holds
        for cs in node.calls:
            cat = classify_blocking(cs, node, graph)
            if cat is None:
                continue
            # 1) a lock is held HERE — lexically (`with self.<lock>`),
            # or for the whole method via a `# holds:`/`*_locked`
            # entry contract; either way this line is the anchor
            if cs.held:
                lexical = cs.held - entry
                if lexical:
                    lock = sorted(lexical)[0]
                    if ".py:" in lock:   # module-level lock
                        how = f"under `with {lock.rsplit(':', 1)[-1]}`"
                    else:
                        how = ("under `with "
                               f"self.{lock.rsplit('.', 1)[-1]}`")
                else:
                    lock = sorted(cs.held)[0]
                    how = ("declared `# holds:`/`*_locked` — every "
                           "caller holds the lock")
                report(node.rel, cs.lineno,
                       f"{q} performs blocking {cat} call `{cs.desc}` "
                       f"while {lock} is held ({how})",
                       cs.desc, _HINT)
                continue
            # 2) reachable through the call graph from an under-lock
            # call — anchor at THAT call (the origin is where the fix
            # or waiver belongs, not the shared utility at the end of
            # the chain); every distinct origin is reported
            for origin, chain in reach.get(q, ()):
                report(origin.rel, origin.lineno,
                       f"{origin.qname} calls into "
                       f"{chain[0].split('::')[-1]} while holding "
                       f"{origin.lock}; {q.split('::')[-1]} performs "
                       f"blocking {cat} call `{cs.desc}` "
                       f"({node.rel}:{cs.lineno}) on that path",
                       cs.desc,
                       f"{_HINT}.  call chain: {origin.qname} -> "
                       + " -> ".join(c.split("::")[-1] for c in chain))
    findings.sort(key=lambda f: (f.path, f.line, f.message))
    return findings


def _lock_io_waiver(graph: CallGraph, rel: str,
                    lineno: int) -> Optional[str]:
    """The lock-io waiver on this line: None = no waiver, "" = waiver
    without a reason, else the reason text."""
    m = _LOCK_IO_RE.search(graph.line(rel, lineno))
    if m is None:
        return None
    return (m.group(1) or "").strip()


@register
class BlockingUnderLockRule(Rule):
    id = "W504"
    name = "blocking-under-lock"
    summary = ("blocking calls (HTTP egress, sleep, timeout-less "
               "queue/event waits, subprocess, unbounded reads) must "
               "not be reachable while a lock is held")

    def check(self, repo: Repo) -> list[Finding]:
        return check_blocking(get_callgraph(repo))
