"""W505: no blocking calls reachable from event-loop callbacks.

The serving dataplane (seaweedfs_tpu/utils/eventloop.py) multiplexes
EVERY connection of a process onto one selector loop.  One blocking
call on that loop — a disk pread, a ``time.sleep``, a timeout-less
queue wait — stalls every connection at once: the exact failure class
the thread-per-connection design never had, and the reason the loop's
code discipline must be machine-checked, not review-checked.

Loop entry points are marked with a ``# loop-callback`` comment on the
``def`` line (the ``# thread-entry`` convention's sibling).  From each
such root this rule walks the call graph (sync edges only — a
``submit``/``Thread`` spawn target runs on another thread) and fires
when any reachable call is classified blocking by the W504 tables
(HTTP egress, sleep, timeout-less queue/event waits, subprocess,
unbounded reads) or by the loop-specific disk-helper table
(``os.pread``/``os.open``/``os.fsync``/...).

Two scoping rules keep the findings honest:

  - calls lexically inside a NESTED def/lambda are skipped — the
    dataplane's dispatch closures are built on the loop but run on the
    worker pool, and the call graph attributes their bodies to the
    enclosing function;
  - a callee that is itself a ``# loop-callback`` root is not
    re-walked from an outer root — it gets its own findings, anchored
    where the fix belongs.

Findings anchor at the loop-side origin (the direct blocking call, or
the call site whose transitive callee blocks).  Audited exceptions are
waived AT THAT LINE with::

    # weedlint: loop-io <why this cannot actually block the loop>

(the eventloop's cache-probed inline dispatch is the one shipped
waiver).  A reason-less loop-io waiver is itself a finding.  The
baseline stays EMPTY.
"""

from __future__ import annotations

import ast
import re
from typing import Optional

from .callgraph import CallGraph, get_callgraph
from .engine import Finding, Repo, Rule, register
from .rules_blocking import classify_blocking

_LOOP_CB_RE = re.compile(r"#\s*loop-callback\b")
_LOOP_IO_RE = re.compile(r"#\s*weedlint:\s*loop-io(?:\s+(.*))?$")

# disk-touching helpers the W504 lock tables deliberately ignore (a
# lock held across one pread is merely slow) but the LOOP must never
# reach: one rotational-disk seek is ~10ms of every connection's time
LOOP_DISK_CALLS = {
    "os.pread", "os.pwrite", "os.read", "os.write", "os.open",
    "os.fsync", "os.fdatasync", "os.replace", "os.remove",
    "os.listdir", "os.stat", "open", "pread_padded",
}


def classify_loop_blocking(cs, node, graph: CallGraph) -> Optional[str]:
    cat = classify_blocking(cs, node, graph)
    if cat is not None:
        return cat
    if cs.desc in LOOP_DISK_CALLS:
        return "disk"
    return None


def _nested_lines(fn: ast.AST) -> list[tuple[int, int]]:
    """Line ranges of defs/lambdas nested inside fn — their bodies run
    wherever the closure is handed (the worker pool, here), not on the
    loop, so their call sites are out of scope."""
    out = []
    for sub in ast.walk(fn):
        if sub is fn:
            continue
        if isinstance(sub, (ast.FunctionDef, ast.AsyncFunctionDef,
                            ast.Lambda)):
            out.append((sub.lineno, getattr(sub, "end_lineno",
                                            sub.lineno)))
    return out


def _in_ranges(lineno: int, ranges: list[tuple[int, int]]) -> bool:
    return any(lo <= lineno <= hi for lo, hi in ranges)


_HINT = ("move the blocking work onto the dispatch worker pool "
         "(reactor.submit) and hand the loop only ready bytes, or "
         "waive with `# weedlint: loop-io <reason>` if the call "
         "provably cannot block")


def check_eventloop(graph: CallGraph) -> list[Finding]:
    findings: list[Finding] = []
    seen: set[tuple] = set()
    edges = graph.sync_edges()
    roots = {q: node for q, node in graph.nodes.items()
             if _LOOP_CB_RE.search(graph.line(node.rel, node.lineno))}

    def report(rel: str, lineno: int, message: str, desc: str) -> None:
        m = _LOOP_IO_RE.search(graph.line(rel, lineno))
        if m is not None:
            reason = (m.group(1) or "").strip()
            if reason:
                return  # audited, reasoned: suppressed
            key = (rel, lineno, "no-reason")
            if key not in seen:
                seen.add(key)
                findings.append(Finding(
                    "W505", rel, lineno,
                    f"loop-io waiver on `{desc}` has no reason",
                    "# weedlint: loop-io <why this cannot block the "
                    "loop>"))
            return
        key = (rel, lineno, message)
        if key not in seen:
            seen.add(key)
            findings.append(Finding("W505", rel, lineno, message,
                                    _HINT))

    for q, root in roots.items():
        skip = _nested_lines(root.fn)
        for cs in root.calls:
            if _in_ranges(cs.lineno, skip):
                continue  # closure body: runs off-loop
            # 1) the root itself blocks
            cat = classify_loop_blocking(cs, root, graph)
            if cat is not None and not cs.spawn:
                report(root.rel, cs.lineno,
                       f"loop callback {q} performs blocking {cat} "
                       f"call `{cs.desc}` on the event loop", cs.desc)
                continue
            # 2) something it (transitively) calls blocks — anchored
            # HERE, where the fix or the waiver belongs
            if cs.spawn or not cs.callees:
                continue
            visited: set[str] = set(cs.callees)
            queue: list[tuple[str, list[str]]] = [
                (c, [c]) for c in sorted(cs.callees)]
            while queue:
                cur, chain = queue.pop(0)
                if cur in roots and cur != q:
                    continue  # its own root: anchored there instead
                node = graph.nodes.get(cur)
                if node is None:
                    continue
                inner_skip = _nested_lines(node.fn)
                for inner in node.calls:
                    if inner.spawn or _in_ranges(inner.lineno,
                                                 inner_skip):
                        continue
                    cat = classify_loop_blocking(inner, node, graph)
                    if cat is not None:
                        report(root.rel, cs.lineno,
                               f"loop callback {q} reaches blocking "
                               f"{cat} call `{inner.desc}` "
                               f"({node.rel}:{inner.lineno}) via "
                               + " -> ".join(
                                   c.split("::")[-1] for c in chain),
                               cs.desc)
                for callee in sorted(edges.get(cur, ())):
                    if callee not in visited:
                        visited.add(callee)
                        queue.append((callee, chain + [callee]))
    findings.sort(key=lambda f: (f.path, f.line, f.message))
    return findings


@register
class EventLoopBlockingRule(Rule):
    id = "W505"
    name = "no-blocking-on-event-loop"
    summary = ("calls classified blocking (W504 tables + disk helpers) "
               "must not be reachable from `# loop-callback` reactor "
               "methods")

    def check(self, repo: Repo) -> list[Finding]:
        return check_eventloop(get_callgraph(repo))
