"""W902: replicated control-plane state mutates only on guarded paths.

The master HA plane (master/consensus.py) replicates control state —
journals, alert transitions, coordinator repair records, the EC
registry, vid allocations — through a raft log.  That contract has two
legal mutation contexts and NOTHING else:

  - the LEADER, behind an ``is_leader`` check (a follower that appends
    gets a silent ``False`` back and the data evaporates; a follower
    that mutates a replicated state machine directly diverges from the
    log and breaks the state-hash equality guarantee);
  - the raft APPLY path, where followers re-drive committed entries
    and snapshots through the same state machines.

This rule makes the contract lexical.  In ``seaweedfs_tpu/master/``
and ``seaweedfs_tpu/ops/``, every call to a replication-sensitive
mutator —

  - ``raft.append(...)`` / ``self.raft.append(...)`` (log append),
  - ``commit_state()`` (the synchronous vid_alloc append),
  - ``replicate_fn(...)`` (the coordinator's injected append),
  - ``apply_replicated`` / ``import_replicated`` / ``import_state`` /
    ``resume_replicated`` (the replicated state machines' write API)

— must sit inside a function that satisfies one of:

  - a ``# raft-apply`` marker on its def line(s): the follower apply
    loop and its helpers (idempotent by contract);
  - a lexical leader guard: any ``is_leader`` / ``is_leader_fn``
    reference, or a comparison against the literal ``"leader"`` (the
    role-change hook's shape);
  - a ``# leader-only`` marker on its def line(s): functions reachable
    only beneath the coordinator/telemetry loops, whose per-tick
    ``is_leader_fn()`` gate this rule cannot see interprocedurally.

Everything else is a finding: either the call site needs the guard, or
the function needs the marker that DOCUMENTS why it is exempt.
"""

from __future__ import annotations

import ast
from typing import Optional

from .engine import Finding, Repo, Rule, register

# attribute/name calls that write replicated state
MUTATORS = {"apply_replicated", "import_replicated", "import_state",
            "resume_replicated", "commit_state", "replicate_fn"}
# def-line markers that exempt a function (documented contracts)
MARKERS = ("# raft-apply", "# leader-only")
# directories the replicated control plane lives in
SCOPES = ("seaweedfs_tpu/master/", "seaweedfs_tpu/ops/")


def _is_raft_append(func: ast.AST) -> bool:
    """``raft.append(...)`` / ``<x>.raft.append(...)`` — the log-append
    spelling; list/deque ``.append`` receivers never match."""
    if not (isinstance(func, ast.Attribute) and func.attr == "append"):
        return False
    v = func.value
    return (isinstance(v, ast.Name) and v.id == "raft") or \
        (isinstance(v, ast.Attribute) and v.attr == "raft")


def _mutator_name(func: ast.AST) -> Optional[str]:
    if isinstance(func, ast.Attribute) and func.attr in MUTATORS:
        return func.attr
    if isinstance(func, ast.Name) and func.id in MUTATORS:
        return func.id
    if _is_raft_append(func):
        return "raft.append"
    return None


def _lexically_guarded(fn: ast.AST) -> bool:
    """Any ``is_leader``-ish reference or a ``== "leader"`` comparison
    anywhere in the function body (nested defs included — a closure
    under the guard inherits it)."""
    for n in ast.walk(fn):
        if isinstance(n, ast.Attribute) and "is_leader" in n.attr:
            return True
        if isinstance(n, ast.Name) and "is_leader" in n.id:
            return True
        if isinstance(n, ast.Compare):
            for c in [n.left, *n.comparators]:
                if isinstance(c, ast.Constant) and c.value == "leader":
                    return True
    return False


def _marked(lines: list[str], fn: ast.AST) -> bool:
    """A MARKERS comment anywhere on the (possibly multi-line) def
    signature, before the first body statement."""
    end = fn.body[0].lineno if getattr(fn, "body", None) else fn.lineno
    for ln in range(fn.lineno, end + 1):
        text = lines[ln - 1] if 0 < ln <= len(lines) else ""
        if any(m in text for m in MARKERS):
            return True
    return False


def check_source(src: str, path: str,
                 tree: Optional[ast.AST] = None) -> list[Finding]:
    """Findings for one module (planted-pair tests drive this
    directly; the Rule below feeds it every in-scope repo file)."""
    if tree is None:
        try:
            tree = ast.parse(src, filename=path)
        except SyntaxError:
            return []  # W101 reports unparseable files
    lines = src.splitlines()
    out: list[Finding] = []

    def visit(node: ast.AST, exempt: bool) -> None:
        """DFS carrying whether any enclosing def is marked/guarded."""
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            exempt = exempt or _marked(lines, node) \
                or _lexically_guarded(node)
        if isinstance(node, ast.Call) and not exempt:
            name = _mutator_name(node.func)
            if name is not None:
                out.append(Finding(
                    "W902", path, node.lineno,
                    f"replicated-state mutation {name}(...) outside an "
                    f"is_leader-guarded or raft-apply path — a "
                    f"follower reaching this diverges from the "
                    f"replicated log (or silently drops the append)",
                    "guard with is_leader, or mark the def line "
                    "# raft-apply (apply loop) / # leader-only "
                    "(reached only beneath the leader-gated loop)"))
        for child in ast.iter_child_nodes(node):
            visit(child, exempt)

    visit(tree, False)
    return out


@register
class LeaderGatedMutationRule(Rule):
    id = "W902"
    name = "leader-gated-mutation"
    summary = ("replicated control-plane state (raft log appends, "
               "journal/alert/coordinator imports) mutates only on "
               "is_leader-guarded or raft-apply paths")
    hint = ("guard with is_leader or mark the def line # raft-apply / "
            "# leader-only")

    def check(self, repo: Repo) -> list[Finding]:
        out: list[Finding] = []
        for ctx in repo.files():
            rel = ctx.rel.replace("\\", "/")
            if not rel.startswith(SCOPES) or ctx.tree is None:
                continue
            out.extend(check_source(ctx.source, ctx.rel, ctx.tree))
        return out
