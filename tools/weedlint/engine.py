"""weedlint core: one engine under every repo lint.

Before this module the repo carried four standalone AST lints
(check_py310 / check_tracing / check_async_drain / check_health_keys),
each re-implementing file discovery, AST walking, waiver comments, and
its own CLI.  weedlint hoists the shared machinery into one place:

  - Repo/FileCtx: file discovery (``.gitignore`` directory patterns +
    generated-file markers honored) with ONE cached ``ast.parse`` per
    file, shared by every rule;
  - Rule registry: each rule has a stable id (``W101`` ...), a summary
    for the rule table, and returns structured ``Finding``s
    (file:line + message + fix hint);
  - inline waivers: ``# weedlint: disable=W501 <reason>`` on the
    offending line suppresses that rule there.  A waiver must carry a
    reason, and a waiver whose line no longer triggers the named rule is
    itself a finding (stale waivers rot into false documentation);
  - a committed baseline (tools/weedlint_baseline.json) for
    grandfathered findings, so a new rule can land strict without a
    flag-day: baselined findings are reported as suppressed, NEW
    findings still fail.

CLI (python -m tools.weedlint):

    python -m tools.weedlint [root] [--rule W501[,W502]] [--json]
                             [--update-baseline] [--baseline PATH]
                             [--list-rules] [--changed-only [REF]]

``--changed-only`` (the pre-commit fast path) restricts REPORTED
findings to files changed vs the git ref (default HEAD, worktree diff
plus untracked); analysis still covers the whole repo, because the
interprocedural rules (W503/W504 over the cached call graph in
callgraph.py) need the whole program to be right.

Exit 0 = clean (after waivers + baseline), 1 = findings, 2 = usage.
The ``--json`` document is stable and documented (README "Static
analysis") so future tooling can diff findings across PRs.
"""

from __future__ import annotations

import ast
import fnmatch
import hashlib
import json
import os
import re
import sys
from typing import Callable, Iterable, Optional

SKIP_DIRS = {".git", "__pycache__", ".claude", ".pytest_cache",
             "node_modules", ".venv", "venv", ".hypothesis"}
# files carrying these markers in their first lines are machine-written:
# findings in them are noise nobody can fix by hand
GENERATED_MARKERS = ("@generated", "DO NOT EDIT")

BASELINE_REL = os.path.join("tools", "weedlint_baseline.json")

_WAIVER_RE = re.compile(
    r"#\s*weedlint:\s*disable=([A-Z0-9,\s]+?)(?:\s+(.*))?$")

# the engine's own rule id: waiver hygiene (stale / reason-less waivers)
WAIVER_RULE_ID = "W001"


class Finding:
    """One structured lint finding.  ``line`` is 1-based (0 = whole
    file); the fingerprint (rule + path + message, line-independent) is
    what the baseline keys on, so findings survive unrelated edits."""

    __slots__ = ("rule", "path", "line", "message", "hint")

    def __init__(self, rule: str, path: str, line: int, message: str,
                 hint: str = ""):
        self.rule = rule
        self.path = path
        self.line = int(line)
        self.message = message
        self.hint = hint

    @property
    def fingerprint(self) -> str:
        h = hashlib.sha1(
            f"{self.rule}|{self.path}|{self.message}".encode())
        return h.hexdigest()[:12]

    def to_dict(self) -> dict:
        d = {"rule": self.rule, "path": self.path, "line": self.line,
             "message": self.message, "fingerprint": self.fingerprint}
        if self.hint:
            d["hint"] = self.hint
        return d

    def render(self) -> str:
        s = f"{self.path}:{self.line}: {self.rule}: {self.message}"
        if self.hint:
            s += f"  [hint: {self.hint}]"
        return s


class FileCtx:
    """One repo file, parsed at most once no matter how many rules
    look at it."""

    def __init__(self, root: str, rel: str):
        self.root = root
        self.rel = rel
        self.path = os.path.join(root, rel)
        self._source: Optional[str] = None
        self._lines: Optional[list[str]] = None
        self._tree = None
        self._tree_err: Optional[SyntaxError] = None
        self._parsed = False

    @property
    def source(self) -> str:
        if self._source is None:
            with open(self.path, encoding="utf-8", errors="replace") as f:
                self._source = f.read()
        return self._source

    @property
    def lines(self) -> list[str]:
        if self._lines is None:
            self._lines = self.source.splitlines()
        return self._lines

    def line(self, lineno: int) -> str:
        return self.lines[lineno - 1] if 0 < lineno <= len(self.lines) \
            else ""

    @property
    def tree(self) -> Optional[ast.AST]:
        """Cached parse; None when the file does not parse (the W101
        rule reports the SyntaxError, everything else skips)."""
        if not self._parsed:
            self._parsed = True
            try:
                self._tree = ast.parse(self.source, filename=self.rel)
            except SyntaxError as e:
                self._tree_err = e
        return self._tree

    @property
    def parse_error(self) -> Optional[SyntaxError]:
        self.tree
        return self._tree_err


def _gitignore_dir_patterns(root: str) -> list[str]:
    """Directory patterns from .gitignore (``name/`` entries and plain
    names) — the shared-discovery exclusion the four old lints each
    approximated with a hardcoded set."""
    out: list[str] = []
    try:
        with open(os.path.join(root, ".gitignore"),
                  encoding="utf-8") as f:
            for raw in f:
                pat = raw.strip()
                if not pat or pat.startswith("#"):
                    continue
                if pat.endswith("/"):
                    out.append(pat.rstrip("/"))
                elif "." not in pat and "*" not in pat:
                    out.append(pat)
    except OSError:
        pass
    return out


class Repo:
    """File discovery + shared parse cache for one lint run."""

    def __init__(self, root: str):
        self.root = os.path.abspath(root)
        self._files: Optional[list[FileCtx]] = None
        self._ignored_dirs = _gitignore_dir_patterns(self.root)

    def _skip_dir(self, name: str) -> bool:
        if name in SKIP_DIRS:
            return True
        return any(fnmatch.fnmatch(name, pat)
                   for pat in self._ignored_dirs)

    def files(self) -> list[FileCtx]:
        """Every tracked .py file, sorted, generated files excluded."""
        if self._files is None:
            out: list[FileCtx] = []
            for dirpath, dirnames, filenames in os.walk(self.root):
                dirnames[:] = sorted(d for d in dirnames
                                     if not self._skip_dir(d))
                for name in sorted(filenames):
                    if not name.endswith(".py"):
                        continue
                    rel = os.path.relpath(os.path.join(dirpath, name),
                                          self.root)
                    ctx = FileCtx(self.root, rel)
                    try:
                        head = ctx.source[:400]
                    except OSError:
                        continue
                    if any(m in head for m in GENERATED_MARKERS):
                        continue
                    out.append(ctx)
            self._files = out
        return self._files

    def package_files(self, package: str = "seaweedfs_tpu") -> list[FileCtx]:
        prefix = package + os.sep
        return [f for f in self.files() if f.rel.startswith(prefix)]

    def test_files(self) -> list[FileCtx]:
        return [f for f in self.files()
                if f.rel.startswith("tests" + os.sep)]

    def get(self, rel: str) -> Optional[FileCtx]:
        for f in self.files():
            if f.rel == rel:
                return f
        return None


class Rule:
    """Base class: subclasses set id/name/summary and implement
    check(repo) -> list[Finding]."""

    id = "W000"
    name = "base"
    summary = ""
    hint = ""

    def check(self, repo: Repo) -> list[Finding]:  # pragma: no cover
        raise NotImplementedError

    def finding(self, path: str, line: int, message: str,
                hint: Optional[str] = None) -> Finding:
        return Finding(self.id, path, line, message,
                       self.hint if hint is None else hint)


_REGISTRY: dict[str, Rule] = {}


def register(rule_cls: type) -> type:
    """Class decorator: instantiate + index by rule id."""
    rule = rule_cls()
    if rule.id in _REGISTRY:  # pragma: no cover - programming error
        raise ValueError(f"duplicate rule id {rule.id}")
    _REGISTRY[rule.id] = rule
    return rule_cls


def all_rules() -> list[Rule]:
    _load_builtin_rules()
    return [_REGISTRY[k] for k in sorted(_REGISTRY)]


def get_rule(rule_id: str) -> Optional[Rule]:
    _load_builtin_rules()
    return _REGISTRY.get(rule_id)


_loaded = False


def _load_builtin_rules() -> None:
    """Import the rule modules exactly once (registration side effect)."""
    global _loaded
    if _loaded:
        return
    _loaded = True
    from . import (rules_async_drain, rules_bench,  # noqa: F401
                   rules_blocking, rules_eventloop, rules_faults,
                   rules_health_keys, rules_leader, rules_ledger,
                   rules_lockorder, rules_lockset, rules_py310,
                   rules_resources, rules_routes, rules_timeouts,
                   rules_tracing)


# --- waivers -----------------------------------------------------------------

class Waiver:
    __slots__ = ("path", "line", "ids", "reason", "used")

    def __init__(self, path: str, line: int, ids: set[str], reason: str):
        self.path = path
        self.line = line
        self.ids = ids
        self.reason = reason
        self.used: set[str] = set()


def _comment_lines(ctx: FileCtx) -> dict[int, str]:
    """lineno -> comment text, via tokenize so a docstring QUOTING the
    waiver syntax (this engine's own docs, the README examples) is
    never mistaken for a live waiver."""
    import io
    import tokenize

    out: dict[int, str] = {}
    try:
        for tok in tokenize.generate_tokens(
                io.StringIO(ctx.source).readline):
            if tok.type == tokenize.COMMENT:
                out[tok.start[0]] = tok.string
    except (tokenize.TokenError, SyntaxError, IndentationError):
        pass  # unparseable file: W101 reports it; no waivers here
    return out


def collect_waivers(files: Iterable[FileCtx]) -> list[Waiver]:
    out: list[Waiver] = []
    for ctx in files:
        if "weedlint:" not in ctx.source:
            continue
        for i, comment in sorted(_comment_lines(ctx).items()):
            if "weedlint:" not in comment:
                continue
            m = _WAIVER_RE.search(comment)
            if m is None:
                continue
            ids = {s.strip() for s in m.group(1).split(",") if s.strip()}
            out.append(Waiver(ctx.rel, i, ids, (m.group(2) or "").strip()))
    return out


def apply_waivers(findings: list[Finding],
                  waivers: list[Waiver]) -> tuple[list[Finding],
                                                  list[Finding],
                                                  list[Finding]]:
    """-> (kept, waived, waiver_findings).  A waiver suppresses matching
    findings on its own line; stale or reason-less waivers become W001
    findings so waivers cannot rot silently."""
    index: dict[tuple[str, int], list[Waiver]] = {}
    for w in waivers:
        index.setdefault((w.path, w.line), []).append(w)
    kept: list[Finding] = []
    waived: list[Finding] = []
    for f in findings:
        ws = index.get((f.path, f.line), [])
        hit = next((w for w in ws if f.rule in w.ids), None)
        if hit is not None:
            hit.used.add(f.rule)
            waived.append(f)
        else:
            kept.append(f)
    extra: list[Finding] = []
    for w in waivers:
        stale = sorted(w.ids - w.used)
        if stale:
            extra.append(Finding(
                WAIVER_RULE_ID, w.path, w.line,
                f"stale waiver: disable={','.join(stale)} suppresses "
                f"nothing on this line any more — delete it",
                "a waiver that outlives its finding is false "
                "documentation"))
        if w.used and not w.reason:
            extra.append(Finding(
                WAIVER_RULE_ID, w.path, w.line,
                f"waiver disable={','.join(sorted(w.used))} has no "
                f"reason — say WHY the finding is a false positive",
                "# weedlint: disable=W501 <why this is safe>"))
    return kept, waived, extra


# --- baseline ----------------------------------------------------------------

def load_baseline(path: str) -> dict[str, dict]:
    try:
        with open(path, encoding="utf-8") as f:
            doc = json.load(f)
    except (OSError, ValueError):
        return {}
    return dict(doc.get("findings") or {})


def save_baseline(path: str, findings: list[Finding]) -> dict:
    entries: dict[str, dict] = {}
    for f in findings:
        e = entries.setdefault(f.fingerprint, {
            "rule": f.rule, "path": f.path, "message": f.message,
            "count": 0})
        e["count"] += 1
    doc = {"version": 1,
           "comment": "grandfathered findings; regenerate with "
                      "python -m tools.weedlint --update-baseline. "
                      "Never baseline code added in the same PR — fix "
                      "it or waive it with a reason.",
           "findings": {k: entries[k] for k in sorted(entries)}}
    with open(path, "w", encoding="utf-8") as f:
        json.dump(doc, f, indent=2, sort_keys=True)
        f.write("\n")
    return doc


def apply_baseline(findings: list[Finding],
                   baseline: dict[str, dict]) -> tuple[list[Finding],
                                                       list[Finding]]:
    """-> (kept, suppressed).  Each baseline entry forgives up to
    `count` findings with that fingerprint — the grandfather clause,
    never a blank check."""
    budget = {k: int(v.get("count", 1)) for k, v in baseline.items()}
    kept: list[Finding] = []
    suppressed: list[Finding] = []
    for f in findings:
        if budget.get(f.fingerprint, 0) > 0:
            budget[f.fingerprint] -= 1
            suppressed.append(f)
        else:
            kept.append(f)
    return kept, suppressed


# --- run ---------------------------------------------------------------------

class RunResult:
    def __init__(self, root: str, rules: list[Rule],
                 findings: list[Finding], waived: list[Finding],
                 baselined: list[Finding], files_checked: int,
                 callgraph_stats: Optional[dict] = None):
        self.root = root
        self.rules = rules
        self.findings = findings
        self.waived = waived
        self.baselined = baselined
        self.files_checked = files_checked
        self.callgraph_stats = callgraph_stats

    def to_dict(self) -> dict:
        doc = {
            "version": 1,
            "root": self.root,
            "files_checked": self.files_checked,
            "rules": [r.id for r in self.rules],
            "findings": [f.to_dict() for f in self.findings],
            "counts": {"reported": len(self.findings),
                       "waived": len(self.waived),
                       "baselined": len(self.baselined)},
        }
        if self.callgraph_stats is not None:
            # interprocedural-rule health: a resolution regression
            # (unresolved ratio creeping up) silently blinds W503/W504,
            # so the stats ride every JSON document for test logs to
            # diff (test_weedlint pins the ratio)
            doc["callgraph_stats"] = self.callgraph_stats
        return doc


def changed_files(root: str, ref: str) -> set[str]:
    """ROOT-relative paths changed vs `ref` (worktree diff + untracked)
    — the --changed-only pre-commit fast path's file set.  `--relative`
    matters: findings carry root-relative paths, and when the lint root
    is a subdirectory of the git toplevel a plain `git diff` would emit
    toplevel-relative paths that never intersect them (every finding
    silently filtered away).  `ls-files` is cwd-relative already."""
    import subprocess

    out: set[str] = set()
    for args in (["git", "-C", root, "diff", "--relative",
                  "--name-only", ref],
                 ["git", "-C", root, "ls-files", "--others",
                  "--exclude-standard"]):
        p = subprocess.run(args, capture_output=True, text=True,
                           timeout=60)
        if p.returncode != 0:
            raise RuntimeError(
                f"git failed for --changed-only ({ref}): "
                f"{p.stderr.strip() or p.stdout.strip()}")
        out.update(line.strip() for line in p.stdout.splitlines()
                   if line.strip())
    return out


def run(root: str, rule_ids: Optional[list[str]] = None,
        baseline_path: Optional[str] = None,
        on_rule_error: Optional[Callable[[Rule, Exception], None]] = None,
        ignore_baseline: bool = False,
        paths_filter: Optional[set[str]] = None) -> RunResult:
    """One full lint pass.  `rule_ids` restricts which rules run
    (waiver hygiene always runs); a rule that crashes surfaces as a
    finding against itself instead of killing the run.
    `ignore_baseline` reports the grandfathered findings too — the
    --update-baseline path needs the FULL set, or regenerating on a
    clean repo would wipe every entry and fail the next run.
    `paths_filter` (--changed-only) restricts REPORTED findings to
    those paths; every rule still analyzes the whole repo (the call
    graph and cross-file contracts need the whole program) — only the
    reporting is scoped, so the fast path can never let a cross-file
    regression through into a later full run silently."""
    repo = Repo(root)
    rules = all_rules()
    if rule_ids:
        want = set(rule_ids)
        unknown = want - {r.id for r in rules}
        if unknown:
            raise KeyError(f"unknown rule id(s): {', '.join(sorted(unknown))}")
        rules = [r for r in rules if r.id in want]
    findings: list[Finding] = []
    for rule in rules:
        try:
            findings.extend(rule.check(repo))
        except Exception as e:  # noqa: BLE001 - one broken rule must
            if on_rule_error is not None:  # not hide the others' findings
                on_rule_error(rule, e)
            findings.append(Finding(
                rule.id, BASELINE_REL, 0,
                f"rule {rule.id} crashed: {type(e).__name__}: {e}",
                "fix the rule; a crashed rule fails the run"))
    waivers = collect_waivers(repo.files())
    findings, waived, waiver_findings = apply_waivers(findings, waivers)
    if rule_ids is None or WAIVER_RULE_ID in (rule_ids or []):
        findings.extend(waiver_findings)
    bl_path = baseline_path or os.path.join(repo.root, BASELINE_REL)
    baseline = {} if ignore_baseline else load_baseline(bl_path)
    findings, baselined = apply_baseline(findings, baseline)
    if paths_filter is not None:
        findings = [f for f in findings if f.path in paths_filter]
    findings.sort(key=lambda f: (f.path, f.line, f.rule, f.message))
    graph = getattr(repo, "_weedlint_callgraph", None)
    return RunResult(repo.root, rules, findings, waived, baselined,
                     len(repo.files()),
                     callgraph_stats=(graph.stats()
                                      if graph is not None else None))


# --- CLI ---------------------------------------------------------------------

def _default_root() -> str:
    return os.path.dirname(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))


def main(argv: Optional[list[str]] = None) -> int:
    argv = list(sys.argv[1:] if argv is None else argv)
    root = None
    rule_ids: Optional[list[str]] = None
    as_json = False
    update_baseline = False
    baseline_path = None
    changed_ref: Optional[str] = None
    i = 0
    while i < len(argv):
        a = argv[i]
        if a == "--json":
            as_json = True
        elif a == "--update-baseline":
            update_baseline = True
        elif a == "--changed-only":
            # optional ref argument (defaults to HEAD); a following
            # token that is an existing directory is the ROOT, not a ref
            changed_ref = "HEAD"
            if i + 1 < len(argv) and not argv[i + 1].startswith("-") \
                    and not os.path.isdir(argv[i + 1]):
                i += 1
                changed_ref = argv[i]
        elif a == "--list-rules":
            for r in all_rules():
                print(f"{r.id}  {r.name:<22} {r.summary}")
            return 0
        elif a == "--rule":
            i += 1
            if i >= len(argv):
                print("--rule needs an argument", file=sys.stderr)
                return 2
            # repeated --rule flags accumulate (--rule W503 --rule W504)
            rule_ids = (rule_ids or []) + [
                s.strip() for s in argv[i].split(",") if s.strip()]
        elif a == "--baseline":
            i += 1
            if i >= len(argv):
                print("--baseline needs an argument", file=sys.stderr)
                return 2
            baseline_path = argv[i]
        elif a.startswith("-"):
            print(f"unknown flag {a}", file=sys.stderr)
            return 2
        elif root is None:
            root = a
        else:
            print(f"unexpected argument {a}", file=sys.stderr)
            return 2
        i += 1
    root = root or _default_root()
    # the health-keys rule imports the live tables: the repo under lint
    # must win over any installed copy
    if root not in sys.path:
        sys.path.insert(0, root)
    paths_filter = None
    if changed_ref is not None and update_baseline:
        # a baseline regenerated from a FILTERED finding set would
        # silently delete every other grandfathered entry
        print("--update-baseline cannot be combined with "
              "--changed-only: the baseline must be regenerated from "
              "the full finding set", file=sys.stderr)
        return 2
    if changed_ref is not None:
        try:
            paths_filter = changed_files(root, changed_ref)
        except (RuntimeError, OSError) as e:
            print(str(e), file=sys.stderr)
            return 2
        if not paths_filter:
            print(f"weedlint: no files changed vs {changed_ref}",
                  file=sys.stderr)
            return 0
    try:
        result = run(root, rule_ids, baseline_path,
                     ignore_baseline=update_baseline,
                     paths_filter=paths_filter)
    except KeyError as e:
        print(str(e), file=sys.stderr)
        return 2
    if update_baseline:
        path = baseline_path or os.path.join(result.root, BASELINE_REL)
        save_baseline(path, result.findings)
        print(f"weedlint: baseline written to {path} "
              f"({len(result.findings)} finding(s))", file=sys.stderr)
        return 0
    if as_json:
        print(json.dumps(result.to_dict(), indent=2, sort_keys=True))
    else:
        for f in result.findings:
            print(f.render())
    scope = f", changed vs {changed_ref} only" if changed_ref else ""
    print(f"weedlint: {result.files_checked} files, "
          f"{len(result.rules)} rule(s), "
          f"{len(result.findings)} finding(s) "
          f"({len(result.waived)} waived, "
          f"{len(result.baselined)} baselined{scope})", file=sys.stderr)
    if result.callgraph_stats:
        s = result.callgraph_stats
        print(f"weedlint: callgraph {s['nodes']} nodes, "
              f"{s['edges']} edges, "
              f"{s['calls_unresolved']}/{s['calls_total']} calls "
              f"unresolved ({s['unresolved_ratio']:.0%})",
              file=sys.stderr)
    return 1 if result.findings else 0
