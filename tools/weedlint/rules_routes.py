"""W601: route handlers must answer 400, not 500, to malformed params.

PR 9's hardening, now machine-checked across every Router subclass: a
typo'd query parameter (`?limit=abc`) is the CLIENT's mistake.  An
`int()` / `float()` over `req.query` that lets ValueError escape turns
it into a 500 — which burns the error-ratio SLO budget the burn-rate
alerts watch, so a curious operator with a bad curl line can page the
on-call.

The rule: inside any `@<router>.route(...)`-decorated handler, a call
to `int(...)` or `float(...)` whose argument expression reads
`.query` must be protected — lexically inside a `try` whose handlers
catch ValueError/TypeError (or wider) — or replaced with the
`utils.httpd.qint` / `qfloat` helpers, which raise HttpError(400)
themselves.
"""

from __future__ import annotations

import ast

from .engine import Finding, Repo, Rule, register

PACKAGE = "seaweedfs_tpu"

_CATCHING = {"ValueError", "TypeError", "Exception", "BaseException",
             "HttpError"}


def _is_route_handler(fn: ast.AST) -> bool:
    for dec in getattr(fn, "decorator_list", []):
        call = dec if isinstance(dec, ast.Call) else None
        target = call.func if call is not None else dec
        if isinstance(target, ast.Attribute) and target.attr == "route":
            return True
    return False


def _reads_query(node: ast.Call) -> bool:
    for arg in list(node.args) + [kw.value for kw in node.keywords]:
        for sub in ast.walk(arg):
            if isinstance(sub, ast.Attribute) and sub.attr == "query":
                return True
    return False


def _try_catches_value_error(node: ast.Try) -> bool:
    for h in node.handlers:
        if h.type is None:
            return True
        t = h.type
        for el in (t.elts if isinstance(t, ast.Tuple) else [t]):
            name = el.id if isinstance(el, ast.Name) else (
                el.attr if isinstance(el, ast.Attribute) else "")
            if name in _CATCHING:
                return True
    return False


def check_module_source(src: str, path: str,
                        tree=None) -> list[Finding]:
    if tree is None:
        try:
            tree = ast.parse(src, filename=path)
        except SyntaxError:
            return []  # W101 owns parse errors
    findings: list[Finding] = []
    for node in ast.walk(tree):
        if not isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            continue
        if not _is_route_handler(node):
            continue
        findings.extend(_check_handler(node, path))
    return findings


def _check_handler(fn: ast.AST, path: str) -> list[Finding]:
    findings: list[Finding] = []

    def walk(node: ast.AST, protected: bool) -> None:
        if isinstance(node, ast.Try):
            body_protected = protected or _try_catches_value_error(node)
            for stmt in node.body:
                walk(stmt, body_protected)
            for h in node.handlers:
                for stmt in h.body:
                    walk(stmt, protected)
            for stmt in node.orelse + node.finalbody:
                walk(stmt, protected)
            return
        if isinstance(node, ast.Call) and not protected:
            f = node.func
            name = f.id if isinstance(f, ast.Name) else ""
            if name in ("int", "float") and _reads_query(node):
                findings.append(Finding(
                    "W601", path, node.lineno,
                    f"route handler {fn.name} parses a query param "
                    f"with bare {name}() — a malformed value raises "
                    f"ValueError and answers 500, burning the "
                    f"error-ratio SLO for a client typo",
                    "use utils.httpd.qint/qfloat, or wrap in "
                    "try/except ValueError -> HttpError(400)"))
        for child in ast.iter_child_nodes(node):
            walk(child, protected)

    for stmt in fn.body:
        walk(stmt, False)
    return findings


@register
class RouteParamRule(Rule):
    id = "W601"
    name = "route-param-400"
    summary = ("query-param int()/float() in route handlers must "
               "answer 400 on garbage, never escape as a 500")

    def check(self, repo: Repo) -> list[Finding]:
        out: list[Finding] = []
        for ctx in repo.package_files(PACKAGE):
            tree = ctx.tree
            if tree is None:
                continue
            out.extend(check_module_source(ctx.source, ctx.rel, tree))
        return out
