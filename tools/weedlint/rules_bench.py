"""W1001: every bench section must have an explicit SECTION_CAPS entry.

bench.py runs each section under a wall-clock cap; a ``section(name,
fn)`` whose name is missing from SECTION_CAPS silently falls to
SECTION_CAP_DEFAULT — which is how a new 8-minute section ends up
budgeted 300s and killed mid-measurement, or a cheap one squats 300s
of the shared child budget.  The cap is a reviewed decision per
section, so this rule makes omission a lint failure instead of a
runtime surprise.

Checked in ``bench.py`` at the repo root (absent in mini test repos —
the rule returns nothing there):

  - every ``section("<name>", ...)`` call's literal name;
  - every ``SECTION_CAPS.get("<name>", ...)`` literal key (the
    special-cased budget lookups, e.g. the e2e_stream per-leg gate)

must appear as a key of the module-level SECTION_CAPS dict.  Names
built at runtime (non-literal first arguments) cannot be verified
statically and are flagged too — a section whose cap nobody can read
off the table is the same review problem.
"""

from __future__ import annotations

import ast
from typing import Optional

from .engine import Finding, Repo, Rule, register

BENCH_REL = "bench.py"


def _literal_str(node: ast.AST) -> Optional[str]:
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return node.value
    return None


def section_caps_keys(tree: ast.AST) -> Optional[set]:
    """Keys of the module-level ``SECTION_CAPS = {...}`` dict; None
    when the table is missing entirely."""
    for node in ast.walk(tree):
        if not isinstance(node, ast.Assign):
            continue
        if not any(isinstance(t, ast.Name) and t.id == "SECTION_CAPS"
                   for t in node.targets):
            continue
        if isinstance(node.value, ast.Dict):
            return {k for k in (_literal_str(key)
                                for key in node.value.keys)
                    if k is not None}
    return None


def check_source(src: str, path: str = BENCH_REL,
                 tree: Optional[ast.AST] = None) -> list[Finding]:
    """Findings for one bench module's section/cap drift."""
    if tree is None:
        try:
            tree = ast.parse(src, filename=path)
        except SyntaxError:
            return []  # W101 reports unparseable files
    caps = section_caps_keys(tree)
    if caps is None:
        return [Finding(
            "W1001", path, 1,
            "no module-level SECTION_CAPS dict found — per-section "
            "budgets are undeclared",
            "declare SECTION_CAPS = {\"<section>\": seconds, ...}")]
    out: list[Finding] = []
    for node in ast.walk(tree):
        if not isinstance(node, ast.Call):
            continue
        f = node.func
        # section("<name>", fn)
        if isinstance(f, ast.Name) and f.id == "section" and node.args:
            name = _literal_str(node.args[0])
            if name is None:
                out.append(Finding(
                    "W1001", path, node.lineno,
                    "section(...) called with a non-literal name — its "
                    "cap cannot be read off SECTION_CAPS in review",
                    "pass the section name as a string literal"))
            elif name not in caps:
                out.append(Finding(
                    "W1001", path, node.lineno,
                    f"section {name!r} has no SECTION_CAPS entry — it "
                    f"silently falls to SECTION_CAP_DEFAULT",
                    f"add \"{name}\": <seconds> to SECTION_CAPS"))
        # SECTION_CAPS.get("<name>", default) budget lookups
        if isinstance(f, ast.Attribute) and f.attr == "get" \
                and isinstance(f.value, ast.Name) \
                and f.value.id == "SECTION_CAPS" and node.args:
            name = _literal_str(node.args[0])
            if name is not None and name not in caps:
                out.append(Finding(
                    "W1001", path, node.lineno,
                    f"SECTION_CAPS.get({name!r}, ...) falls through to "
                    f"the default — {name!r} is not a registered "
                    f"section",
                    f"add \"{name}\": <seconds> to SECTION_CAPS"))
    return out


@register
class BenchSectionCapsRule(Rule):
    id = "W1001"
    name = "bench-section-caps"
    summary = ("every bench.py section(name, ...) must carry an "
               "explicit SECTION_CAPS budget entry")
    hint = "add the section to bench.py SECTION_CAPS"

    def check(self, repo: Repo) -> list[Finding]:
        ctx = repo.get(BENCH_REL)
        if ctx is None or ctx.tree is None:
            # a tree without the bench harness (mini test repos,
            # partial checkouts) has no section table to check
            return []
        return check_source(ctx.source, ctx.rel, ctx.tree)
