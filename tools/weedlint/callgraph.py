"""Cached whole-program call graph for interprocedural weedlint rules.

The per-class lockset checker (rules_lockset) proved lock discipline is
machine-checkable, but its view ends at the class boundary: the two
bugs that actually take clusters down — lock-acquisition CYCLES across
classes (deadlock) and slow I/O performed while a lock is held — are
only visible to a pass that can follow a call from ``with self._lock``
in one class into a method of another.  This module builds that pass's
substrate once per lint run and caches it on the engine's ``Repo`` so
every interprocedural rule (W503 lock-order, W504 blocking-under-lock,
and whatever comes next) shares one graph.

Resolution rules (documented in README "Static analysis"):

  - ``self.method(...)`` -> the method on the same class, searching
    lexical base classes by name when the class itself lacks it;
  - ``self.attr.method(...)`` -> ``Cls.method`` for every class ``Cls``
    the attribute was ever assigned from a constructor call
    (``self.attr = Cls(...)`` anywhere in the class, conventionally
    ``__init__``) — multiple candidate classes all get edges
    (conservative over-approximation);
  - ``local = Cls(...); local.method(...)`` -> ``Cls.method`` via
    single-pass local type seeding inside one function body;
  - bare ``fn(...)`` -> the module-level function in the same module,
    else any same-named module-level function elsewhere in the package
    when the name was imported (``from x import fn``);
  - ``Cls(...)`` -> ``Cls.__init__``;
  - ``Thread(target=X)`` / ``Timer(t, X)`` / ``pool.submit(X, ...)``
    and callable arguments (``f(cb)`` / ``f(pace=self._pace)``) add an
    edge to ``X`` from the function RECEIVING the callable (when
    resolved) — the callback runs in the callee's context, which is
    what lock propagation needs — else from the caller.

Known blind spots (counted, never silently dropped): calls through
attributes never assigned a constructor (hook fields like ``on_emit``),
``super()`` dispatch, calls on function parameters, and duck-typed
dispatch generally.  ``stats()`` reports resolved / external /
unresolved call-site counts so a resolution regression is visible in
test logs (test_weedlint pins the unresolved ratio).

Lock modelling: a lock is identified at CLASS granularity
(``ClassName._lock``) or module granularity (``mod.py:GLOBAL_LOCK``) —
the standard static approximation (two instances of one class are not
distinguished).  ``with self.X`` counts as an acquisition when ``X``
is assigned a ``Lock/RLock/Condition`` in the class or its name says
lock-ish things; ``# holds: X`` on a def line and the ``*_locked``
name suffix seed the entry-held set the walker starts from.
"""

from __future__ import annotations

import ast
import builtins
import re
from typing import Iterable, Optional

PACKAGE = "seaweedfs_tpu"

_HOLDS_RE = re.compile(r"#\s*holds:\s*([A-Za-z_][A-Za-z0-9_]*)")
_GUARDED_RE = re.compile(r"#\s*guarded-by:\s*([A-Za-z_][A-Za-z0-9_]*)")

# constructions that make an attribute a LOCK for ordering purposes
_LOCK_CTORS = {"Lock", "RLock", "Condition"}
_RLOCK_CTORS = {"RLock"}
# attribute-name fallback when no constructor is visible in the class
_LOCKISH_NAME = re.compile(r"(^|_)(lock|mu|mutex|cv)$|_lock\b")

_QUEUE_CTORS = {"Queue", "LifoQueue", "PriorityQueue", "SimpleQueue"}
_EVENT_CTORS = {"Event"}

_BUILTINS = set(dir(builtins))


def _call_name(func: ast.AST) -> str:
    """Dotted text of a call target (best effort, for classification)."""
    parts: list[str] = []
    node = func
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
    elif isinstance(node, ast.Call):
        parts.append(_call_name(node.func) + "()")
    else:
        parts.append("?")
    return ".".join(reversed(parts))


def _self_attr(node: ast.AST) -> Optional[str]:
    if isinstance(node, ast.Attribute) and \
            isinstance(node.value, ast.Name) and node.value.id == "self":
        return node.attr
    return None


def _queue_is_bounded(call: ast.Call) -> bool:
    """Queue(maxsize) / Queue(maxsize=N) with anything that is not a
    literal 0 counts as bounded (a variable capacity is presumed
    bounded — that is the conservative direction for put())."""
    for a in call.args[:1]:
        if isinstance(a, ast.Constant) and a.value == 0:
            return False
        return True
    for kw in call.keywords:
        if kw.arg == "maxsize":
            if isinstance(kw.value, ast.Constant) and kw.value.value == 0:
                return False
            return True
    return False


def _ctor_class_name(value: ast.AST) -> Optional[str]:
    """``Cls(...)`` or ``mod.Cls(...)`` -> "Cls" (capitalized names
    only: lowercase calls are overwhelmingly factory functions whose
    return type this pass does not track)."""
    if not isinstance(value, ast.Call):
        return None
    f = value.func
    name = f.id if isinstance(f, ast.Name) else (
        f.attr if isinstance(f, ast.Attribute) else "")
    if name and name[0].isupper():
        return name
    return None


class ClassInfo:
    """Per-class facts the resolver and the lock walker need."""

    __slots__ = ("name", "rel", "node", "bases", "methods", "attr_types",
                 "lock_attrs", "rlock_attrs", "queue_attrs",
                 "bounded_queue_attrs", "event_attrs", "guards")

    def __init__(self, name: str, rel: str, node: ast.ClassDef,
                 lines: Optional[list[str]] = None):
        self.name = name
        self.rel = rel
        self.node = node
        self.bases: list[str] = []
        for b in node.bases:
            if isinstance(b, ast.Name):
                self.bases.append(b.id)
            elif isinstance(b, ast.Attribute):
                self.bases.append(b.attr)
        self.methods: dict[str, ast.AST] = {}
        for item in node.body:
            if isinstance(item, (ast.FunctionDef, ast.AsyncFunctionDef)):
                self.methods[item.name] = item
        # attr -> candidate class names (self.x = Cls(...) anywhere)
        self.attr_types: dict[str, set[str]] = {}
        self.lock_attrs: set[str] = set()
        self.rlock_attrs: set[str] = set()
        self.queue_attrs: set[str] = set()
        # bounded queues only: put() on an unbounded Queue() never
        # blocks, so only maxsize-constructed queues matter to W504
        self.bounded_queue_attrs: set[str] = set()
        self.event_attrs: set[str] = set()
        self.guards: dict[str, str] = {}
        self._collect_attrs()
        if lines:
            self._collect_guards(lines)

    def _collect_guards(self, lines: list[str]) -> None:
        """`# guarded-by:` annotations (the lockset rules' convention)
        feed the *_locked entry-hold seeding."""
        for sub in ast.walk(self.node):
            if not isinstance(sub, (ast.Assign, ast.AnnAssign,
                                    ast.AugAssign)):
                continue
            line = lines[sub.lineno - 1] \
                if 0 < sub.lineno <= len(lines) else ""
            m = _GUARDED_RE.search(line)
            if m is None:
                continue
            targets = sub.targets if isinstance(sub, ast.Assign) \
                else [sub.target]
            for t in targets:
                attr = _self_attr(t)
                if attr is not None:
                    self.guards[attr] = m.group(1)

    def _collect_attrs(self) -> None:
        for sub in ast.walk(self.node):
            if not isinstance(sub, (ast.Assign, ast.AnnAssign)):
                continue
            value = sub.value
            targets = sub.targets if isinstance(sub, ast.Assign) \
                else [sub.target]
            attrs = [a for a in (_self_attr(t) for t in targets) if a]
            if not attrs or not isinstance(value, ast.Call):
                continue
            f = value.func
            ctor = f.id if isinstance(f, ast.Name) else (
                f.attr if isinstance(f, ast.Attribute) else "")
            for attr in attrs:
                if ctor in _LOCK_CTORS:
                    self.lock_attrs.add(attr)
                    if ctor in _RLOCK_CTORS:
                        self.rlock_attrs.add(attr)
                elif ctor in _QUEUE_CTORS:
                    self.queue_attrs.add(attr)
                    if _queue_is_bounded(value):
                        self.bounded_queue_attrs.add(attr)
                elif ctor in _EVENT_CTORS:
                    self.event_attrs.add(attr)
                cname = _ctor_class_name(value)
                if cname:
                    self.attr_types.setdefault(attr, set()).add(cname)

    def is_lock_attr(self, attr: str) -> bool:
        return attr in self.lock_attrs or \
            _LOCKISH_NAME.search(attr) is not None

    def lock_id(self, attr: str) -> str:
        return f"{self.name}.{attr}"


class CallSite:
    """One call expression with the lock context it executes under.
    ``callees`` holds resolved node qnames (possibly several for
    ambiguous names, possibly none); ``kind`` is resolved / external /
    unresolved for the stats block."""

    __slots__ = ("callees", "lineno", "held", "desc", "kind", "node",
                 "spawn")

    def __init__(self, callees: list[str], lineno: int,
                 held: frozenset, desc: str, kind: str, node: ast.Call,
                 spawn: bool = False):
        self.callees = callees
        self.lineno = lineno
        self.held = held
        self.desc = desc
        self.kind = kind
        self.node = node
        # True for Thread/Timer/submit callback edges: the target runs
        # on ANOTHER thread, so the caller's held locks do not carry
        # and lock propagation must not follow this edge
        self.spawn = spawn


class Acquire:
    """One ``with self.X`` lock acquisition and what was held going in."""

    __slots__ = ("lock", "lineno", "held", "reentrant")

    def __init__(self, lock: str, lineno: int, held: frozenset,
                 reentrant: bool):
        self.lock = lock
        self.lineno = lineno
        self.held = held
        self.reentrant = reentrant


class Node:
    """One function or method in the graph."""

    __slots__ = ("qname", "rel", "cls", "name", "fn", "lineno",
                 "entry_holds", "acquires", "calls")

    def __init__(self, qname: str, rel: str, cls: Optional[str],
                 name: str, fn: ast.AST):
        self.qname = qname
        self.rel = rel
        self.cls = cls
        self.name = name
        self.fn = fn
        self.lineno = fn.lineno
        self.entry_holds: frozenset = frozenset()
        self.acquires: list[Acquire] = []
        self.calls: list[CallSite] = []


class CallGraph:
    def __init__(self):
        self.nodes: dict[str, Node] = {}
        self.classes: dict[str, list[ClassInfo]] = {}
        self.functions: dict[str, list[str]] = {}   # name -> qnames
        self.module_locks: dict[str, set[str]] = {}  # rel -> names
        self.lines: dict[str, list[str]] = {}
        self.calls_total = 0
        self.calls_resolved = 0
        self.calls_external = 0
        self.calls_unresolved = 0

    # --- queries ----------------------------------------------------------
    def edges(self) -> dict[str, set[str]]:
        out: dict[str, set[str]] = {q: set() for q in self.nodes}
        for node in self.nodes.values():
            for cs in node.calls:
                out[node.qname].update(cs.callees)
        return out

    def sync_edges(self) -> dict[str, set[str]]:
        """Edges excluding Thread/Timer/submit spawn callbacks — the
        graph lock propagation walks (a spawned thread does not
        inherit the spawner's held locks)."""
        out: dict[str, set[str]] = {q: set() for q in self.nodes}
        for node in self.nodes.values():
            for cs in node.calls:
                if not cs.spawn:
                    out[node.qname].update(cs.callees)
        return out

    def stats(self) -> dict:
        edge_count = sum(len(v) for v in self.edges().values())
        total = max(self.calls_total, 1)
        return {
            "nodes": len(self.nodes),
            "edges": edge_count,
            "calls_total": self.calls_total,
            "calls_resolved": self.calls_resolved,
            "calls_external": self.calls_external,
            "calls_unresolved": self.calls_unresolved,
            "unresolved_ratio": round(self.calls_unresolved / total, 4),
        }

    def line(self, rel: str, lineno: int) -> str:
        lines = self.lines.get(rel) or []
        return lines[lineno - 1] if 0 < lineno <= len(lines) else ""

    def class_of(self, cname: str) -> Optional[ClassInfo]:
        infos = self.classes.get(cname)
        return infos[0] if infos else None

    def resolve_method(self, cname: str,
                       mname: str) -> Optional[str]:
        """``Cls.m`` qname, following lexical bases by name."""
        seen: set[str] = set()
        stack = [cname]
        while stack:
            c = stack.pop()
            if c in seen:
                continue
            seen.add(c)
            for info in self.classes.get(c, []):
                if mname in info.methods:
                    return f"{info.rel}::{info.name}.{mname}"
                stack.extend(info.bases)
        return None


class _ModuleIndex:
    """First pass over one file: classes, functions, imports, locks."""

    def __init__(self, rel: str, tree: ast.AST, lines: list[str]):
        self.rel = rel
        self.tree = tree
        self.lines = lines
        self.classes: list[ClassInfo] = []
        self.functions: dict[str, ast.AST] = {}
        self.imported: set[str] = set()      # from x import NAME
        self.import_modules: set[str] = set()  # import NAME / as NAME
        self.locks: set[str] = set()         # module-level lock names
        for item in tree.body:
            if isinstance(item, ast.ClassDef):
                self.classes.append(ClassInfo(item.name, rel, item,
                                              lines=lines))
            elif isinstance(item, (ast.FunctionDef, ast.AsyncFunctionDef)):
                self.functions[item.name] = item
            elif isinstance(item, ast.Assign):
                value = item.value
                if isinstance(value, ast.Call):
                    f = value.func
                    ctor = f.id if isinstance(f, ast.Name) else (
                        f.attr if isinstance(f, ast.Attribute) else "")
                    if ctor in _LOCK_CTORS:
                        for t in item.targets:
                            if isinstance(t, ast.Name):
                                self.locks.add(t.id)
        for item in ast.walk(tree):
            if isinstance(item, ast.ImportFrom):
                for alias in item.names:
                    self.imported.add(alias.asname or alias.name)
            elif isinstance(item, ast.Import):
                for alias in item.names:
                    self.import_modules.add(
                        (alias.asname or alias.name).split(".")[0])


class _FunctionWalker:
    """Second pass: one function body -> acquisitions + call sites,
    tracking the lexically-held lock set.  Nested function bodies are
    walked with an EMPTY held set (a closure may run after the lock was
    released) but their calls still belong to this node."""

    def __init__(self, graph: CallGraph, mod: _ModuleIndex,
                 node: Node, cls: Optional[ClassInfo]):
        self.graph = graph
        self.mod = mod
        self.node = node
        self.cls = cls
        self.local_types: dict[str, set[str]] = {}

    def run(self) -> None:
        fn = self.node.fn
        self._seed_entry_holds(fn)
        # local constructor types first (single forward pass is enough
        # for the `x = Cls(...); x.m()` idiom)
        for sub in ast.walk(fn):
            if isinstance(sub, ast.Assign) and len(sub.targets) == 1 \
                    and isinstance(sub.targets[0], ast.Name):
                cname = _ctor_class_name(sub.value)
                if cname and cname in self.graph.classes:
                    self.local_types.setdefault(
                        sub.targets[0].id, set()).add(cname)
        for stmt in getattr(fn, "body", []):
            self._walk(stmt, self.node.entry_holds)

    def _seed_entry_holds(self, fn: ast.AST) -> None:
        held: set[str] = set()
        line = self.mod.lines[fn.lineno - 1] \
            if 0 < fn.lineno <= len(self.mod.lines) else ""
        for m in _HOLDS_RE.finditer(line):
            if self.cls is not None:
                held.add(self.cls.lock_id(m.group(1)))
            else:
                held.add(f"{self.mod.rel}:{m.group(1)}")
        if self.cls is not None and fn.name.endswith("_locked"):
            named = set(self.cls.guards.values())
            if len(self.cls.lock_attrs) == 1:
                named |= self.cls.lock_attrs
            held.update(self.cls.lock_id(a) for a in named)
        self.node.entry_holds = frozenset(held)

    def _lock_of(self, expr: ast.AST) -> Optional[tuple[str, bool]]:
        """(lock id, reentrant) for a with-item context expr, if it is
        a lock acquisition this pass models."""
        attr = _self_attr(expr)
        if attr is not None and self.cls is not None:
            if self.cls.is_lock_attr(attr):
                return (self.cls.lock_id(attr),
                        attr in self.cls.rlock_attrs)
            return None
        if isinstance(expr, ast.Name) and expr.id in self.mod.locks:
            return (f"{self.mod.rel}:{expr.id}", False)
        return None

    def _walk(self, node: ast.AST, held: frozenset,
              in_nested: bool = False) -> None:
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.Lambda)) and node is not self.node.fn:
            for child in ast.iter_child_nodes(node):
                self._walk(child, frozenset(), in_nested=True)
            return
        if isinstance(node, ast.With):
            inner = set(held)
            for item in node.items:
                self._walk(item.context_expr, frozenset(inner),
                           in_nested=in_nested)
                got = self._lock_of(item.context_expr)
                if got is not None:
                    lock, reentrant = got
                    self.node.acquires.append(
                        Acquire(lock, item.context_expr.lineno,
                                frozenset(inner), reentrant))
                    inner.add(lock)
            for stmt in node.body:
                self._walk(stmt, frozenset(inner), in_nested=in_nested)
            return
        if isinstance(node, ast.Call):
            self._record_call(node, held)
        for child in ast.iter_child_nodes(node):
            self._walk(child, held, in_nested=in_nested)

    # --- resolution -------------------------------------------------------
    def _record_call(self, call: ast.Call, held: frozenset) -> None:
        desc = _call_name(call.func)
        callees, kind = self._resolve(call.func)
        self.graph.calls_total += 1
        if callees:
            self.graph.calls_resolved += 1
        elif kind == "external":
            self.graph.calls_external += 1
        else:
            self.graph.calls_unresolved += 1
        cs = CallSite(callees, call.lineno, held, desc,
                      "resolved" if callees else kind, call)
        self.node.calls.append(cs)
        self._record_callback_targets(call, cs, held)

    def _resolve(self, func: ast.AST) -> tuple[list[str], str]:
        """-> (callee qnames, kind-if-empty)."""
        if isinstance(func, ast.Name):
            return self._resolve_name(func.id)
        if isinstance(func, ast.Attribute):
            base = func.value
            mname = func.attr
            # self.m()
            if isinstance(base, ast.Name) and base.id == "self" \
                    and self.cls is not None:
                q = self.graph.resolve_method(self.cls.name, mname)
                return ([q], "resolved") if q else ([], "unresolved")
            # self.attr.m()
            battr = _self_attr(base)
            if battr is not None and self.cls is not None:
                return self._resolve_typed(
                    self.cls.attr_types.get(battr, ()), mname)
            # local.m()
            if isinstance(base, ast.Name):
                if base.id in self.local_types:
                    return self._resolve_typed(
                        self.local_types[base.id], mname)
                if base.id in self.mod.import_modules:
                    return [], "external"
                if base.id in self.graph.classes:   # Cls.static_style()
                    q = self.graph.resolve_method(base.id, mname)
                    return ([q], "resolved") if q else ([], "unresolved")
                return [], "unresolved"
            # os.path.join style: imported module at the root
            root = base
            while isinstance(root, ast.Attribute):
                root = root.value
            if isinstance(root, ast.Name) and \
                    root.id in self.mod.import_modules:
                return [], "external"
            return [], "unresolved"
        return [], "unresolved"

    def _resolve_typed(self, cnames: Iterable[str],
                       mname: str) -> tuple[list[str], str]:
        out = []
        for cname in cnames:
            q = self.graph.resolve_method(cname, mname)
            if q:
                out.append(q)
        return (out, "resolved") if out else ([], "unresolved")

    def _resolve_name(self, name: str) -> tuple[list[str], str]:
        if name in self.mod.functions:
            return [f"{self.mod.rel}::{name}"], "resolved"
        if name in self.graph.classes:
            q = self.graph.resolve_method(name, "__init__")
            return ([q], "resolved") if q else ([], "external")
        if name in self.mod.imported:
            qs = self.graph.functions.get(name)
            if qs:
                return list(qs), "resolved"
            return [], "external"   # stdlib / gated import
        if name in _BUILTINS:
            return [], "external"
        return [], "unresolved"

    def _callable_target(self, expr: ast.AST) -> Optional[str]:
        """A callable ARGUMENT (`self._m`, bare function name,
        `self.attr.m`) -> node qname when resolvable."""
        attr = _self_attr(expr)
        if attr is not None and self.cls is not None:
            return self.graph.resolve_method(self.cls.name, attr)
        if isinstance(expr, ast.Name):
            if expr.id in self.mod.functions:
                return f"{self.mod.rel}::{expr.id}"
            return None
        if isinstance(expr, ast.Attribute):
            battr = _self_attr(expr.value)
            if battr is not None and self.cls is not None:
                for cname in self.cls.attr_types.get(battr, ()):
                    q = self.graph.resolve_method(cname, expr.attr)
                    if q:
                        return q
        return None

    def _record_callback_targets(self, call: ast.Call, cs: CallSite,
                                 held: frozenset) -> None:
        """Thread/Timer/submit targets and callable args become edges:
        attached to the RESOLVED callee when there is one (the callback
        runs in its context), else to this node."""
        targets: list[str] = []
        for kw in call.keywords:
            if kw.arg is None:
                continue
            t = self._callable_target(kw.value)
            if t is not None:
                targets.append(t)
        for a in call.args:
            t = self._callable_target(a)
            if t is not None:
                targets.append(t)
        if not targets:
            return
        fname = _call_name(call.func).rsplit(".", 1)[-1]
        if fname in ("Thread", "Timer", "submit"):
            # runs on another thread: caller's held locks do NOT carry
            for t in targets:
                self.node.calls.append(CallSite(
                    [t], call.lineno, frozenset(),
                    f"{cs.desc}->callback", "resolved", call,
                    spawn=True))
            return
        if cs.callees:
            # synchronous callback: charge it to the receiving callee,
            # whose lock context the propagation pass computes
            for callee in cs.callees:
                target_node = self.graph.nodes.get(callee)
                if target_node is not None:
                    for t in targets:
                        target_node.calls.append(CallSite(
                            [t], call.lineno, frozenset(),
                            f"callback-from:{self.node.qname}",
                            "resolved", call))
        else:
            for t in targets:
                self.node.calls.append(CallSite(
                    [t], call.lineno, held,
                    f"{cs.desc}->callback", "resolved", call))


def build_from_sources(sources: list[tuple[str, str]]) -> CallGraph:
    """Build a graph from (rel_path, source) pairs — the unit tests'
    entry point and the engine's (via ``get_callgraph``)."""
    graph = CallGraph()
    mods: list[_ModuleIndex] = []
    for rel, src in sources:
        try:
            tree = ast.parse(src, filename=rel)
        except SyntaxError:
            continue   # W101 owns parse errors
        lines = src.splitlines()
        graph.lines[rel] = lines
        mod = _ModuleIndex(rel, tree, lines)
        mods.append(mod)
        for info in mod.classes:
            graph.classes.setdefault(info.name, []).append(info)
        for fname in mod.functions:
            graph.functions.setdefault(fname, []).append(
                f"{rel}::{fname}")
        graph.module_locks[rel] = mod.locks
    # register nodes before the body walk so callback attachment can
    # find callee nodes across modules
    walk_plan: list[tuple[_ModuleIndex, Node, Optional[ClassInfo]]] = []
    for mod in mods:
        for fname, fn in mod.functions.items():
            node = Node(f"{mod.rel}::{fname}", mod.rel, None, fname, fn)
            graph.nodes[node.qname] = node
            walk_plan.append((mod, node, None))
        for info in mod.classes:
            for mname, fn in info.methods.items():
                q = f"{mod.rel}::{info.name}.{mname}"
                node = Node(q, mod.rel, info.name, mname, fn)
                graph.nodes[q] = node
                walk_plan.append((mod, node, info))
    for mod, node, cls in walk_plan:
        _FunctionWalker(graph, mod, node, cls).run()
    return graph


def get_callgraph(repo) -> CallGraph:
    """The per-run graph, built once and cached on the Repo ctx —
    every interprocedural rule reuses it."""
    cached = getattr(repo, "_weedlint_callgraph", None)
    if cached is not None:
        return cached
    sources = [(ctx.rel, ctx.source)
               for ctx in repo.package_files(PACKAGE)]
    graph = build_from_sources(sources)
    repo._weedlint_callgraph = graph
    return graph
