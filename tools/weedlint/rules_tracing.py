"""W201: new code cannot silently opt out of distributed tracing.

Ported from tools/check_tracing.py (PR 6).  Tracing is enforced at two
chokepoints, not at every call site: utils/httpd.py Router.dispatch is
the ONE ingress every HTTP handler runs under, and the pooled client
helpers are the ONE egress every outbound hop rides.  That design only
holds if nothing routes around the chokepoints:

  1. Router.dispatch still calls begin_request/end_request/span; the
     framed-TCP front (_serve_conn) still mints its headerless ingress.
  2. _pooled_request / http_download still call inject_trace_headers.
  3. No package module imports urllib.request / http.client directly
     (a raw outbound hop would drop the Traceparent) — utils/httpd.py
     is the sole allowed user; `# tracing-exempt: <reason>` waives a
     genuinely-external hop (kept for backward compatibility with the
     PR-6 waiver; `# weedlint: disable=W201 <reason>` works too).
  4. No Router subclass overrides dispatch outside utils/httpd.py.
"""

from __future__ import annotations

import ast
import os

from .engine import Finding, Repo, Rule, register

PACKAGE = "seaweedfs_tpu"
HTTPD_REL = os.path.join(PACKAGE, "utils", "httpd.py")
FRAMING_REL = os.path.join(PACKAGE, "utils", "framing.py")
RAW_HTTP_MODULES = {"urllib.request", "http.client"}
OUTBOUND_HELPERS = ("_pooled_request", "http_download")


def _calls_in(node: ast.AST) -> set[str]:
    names: set[str] = set()
    for sub in ast.walk(node):
        if isinstance(sub, ast.Call):
            f = sub.func
            if isinstance(f, ast.Name):
                names.add(f.id)
            elif isinstance(f, ast.Attribute):
                names.add(f.attr)
    return names


def _functions(tree: ast.AST) -> dict[str, ast.AST]:
    out: dict[str, ast.AST] = {}
    for node in ast.walk(tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            out.setdefault(node.name, node)
    return out


def check_httpd_source(src: str, path: str) -> list[Finding]:
    """The ingress/egress chokepoint contract on utils/httpd.py."""
    try:
        tree = ast.parse(src, filename=path)
    except SyntaxError as e:
        return [Finding("W201", path, e.lineno or 0,
                        f"does not parse: {e.msg}")]
    problems: list[Finding] = []
    fns = _functions(tree)
    dispatch = fns.get("dispatch")
    if dispatch is None:
        problems.append(Finding("W201", path, 0,
                                "Router.dispatch not found"))
    else:
        calls = _calls_in(dispatch)
        for required in ("begin_request", "end_request", "span"):
            if required not in calls:
                problems.append(Finding(
                    "W201", path, dispatch.lineno,
                    f"Router.dispatch no longer calls {required}() — "
                    f"HTTP handlers would run without a request span / "
                    f"trace context"))
    for helper in OUTBOUND_HELPERS:
        fn = fns.get(helper)
        if fn is None:
            problems.append(Finding(
                "W201", path, 0, f"outbound helper {helper}() not found"))
        elif "inject_trace_headers" not in _calls_in(fn):
            problems.append(Finding(
                "W201", path, fn.lineno,
                f"{helper}() no longer calls inject_trace_headers() — "
                f"outbound hops would drop the Traceparent and shatter "
                f"cross-server traces"))
    return problems


def check_framing_source(src: str, path: str) -> list[Finding]:
    """The framed-TCP ingress contract on utils/framing.py."""
    try:
        tree = ast.parse(src, filename=path)
    except SyntaxError as e:
        return [Finding("W201", path, e.lineno or 0,
                        f"does not parse: {e.msg}")]
    fns = _functions(tree)
    # the per-frame ingress contract lives in serve_frame (trace mint
    # + deadline-slot hygiene + recorder), shared by the threaded
    # accept loop AND the reactor dataplane
    frame_fn = fns.get("serve_frame")
    if frame_fn is None:
        return [Finding("W201", path, 0,
                        "framing.serve_frame not found")]
    calls = _calls_in(frame_fn)
    missing = [c for c in ("begin_request", "end_request", "span")
               if c not in calls]
    if missing:
        return [Finding(
            "W201", path, frame_fn.lineno,
            f"serve_frame no longer calls {'/'.join(missing)} — the "
            f"native TCP ingress would run untraced")]
    serve = fns.get("_serve_conn")
    if serve is None:
        return [Finding("W201", path, 0,
                        "FramedServer._serve_conn not found")]
    if "serve_frame" not in _calls_in(serve):
        return [Finding(
            "W201", path, serve.lineno,
            "_serve_conn no longer routes frames through serve_frame "
            "— the threaded native ingress would bypass the "
            "trace/deadline/recorder chokepoint")]
    return []


def check_package_source(src: str, path: str,
                         tree=None) -> list[Finding]:
    """Raw-HTTP imports + Router-dispatch overrides in one package
    module."""
    if tree is None:
        try:
            tree = ast.parse(src, filename=path)
        except SyntaxError as e:
            return [Finding("W201", path, e.lineno or 0,
                            f"does not parse: {e.msg}")]
    lines = src.splitlines()

    def waived(lineno: int) -> bool:
        line = lines[lineno - 1] if 0 < lineno <= len(lines) else ""
        return "tracing-exempt" in line

    problems: list[Finding] = []
    for node in ast.walk(tree):
        if isinstance(node, (ast.Import, ast.ImportFrom)) \
                and waived(node.lineno):
            continue
        if isinstance(node, ast.Import):
            for alias in node.names:
                if alias.name in RAW_HTTP_MODULES:
                    problems.append(Finding(
                        "W201", path, node.lineno,
                        f"raw `import {alias.name}` — outbound HTTP "
                        f"must go through utils.httpd helpers so the "
                        f"Traceparent header propagates"))
        elif isinstance(node, ast.ImportFrom):
            mod = node.module or ""
            if mod in RAW_HTTP_MODULES or \
                    (mod == "urllib"
                     and any(a.name == "request" for a in node.names)) or \
                    (mod == "http"
                     and any(a.name == "client" for a in node.names)):
                problems.append(Finding(
                    "W201", path, node.lineno,
                    f"raw HTTP client import (`from {mod} import ...`) "
                    f"— outbound HTTP must go through utils.httpd "
                    f"helpers so the Traceparent header propagates"))
        elif isinstance(node, ast.ClassDef):
            router_base = any(
                (isinstance(b, ast.Name) and b.id == "Router")
                or (isinstance(b, ast.Attribute) and b.attr == "Router")
                for b in node.bases)
            if not router_base:
                continue
            for item in node.body:
                if isinstance(item, (ast.FunctionDef,
                                     ast.AsyncFunctionDef)) \
                        and item.name == "dispatch":
                    problems.append(Finding(
                        "W201", path, item.lineno,
                        "Router subclass overrides dispatch() — the "
                        "request span and trace-context restore live "
                        "there; override hooks instead"))
    return problems


@register
class TracingRule(Rule):
    id = "W201"
    name = "tracing-chokepoints"
    summary = ("HTTP ingress/egress must ride the traced chokepoints; "
               "no raw urllib/http.client in the package")

    def check(self, repo: Repo) -> list[Finding]:
        problems: list[Finding] = []
        httpd = repo.get(HTTPD_REL)
        if httpd is not None:
            problems.extend(check_httpd_source(httpd.source, HTTPD_REL))
        else:
            problems.append(Finding("W201", HTTPD_REL, 0, "missing"))
        framing = repo.get(FRAMING_REL)
        if framing is not None:
            problems.extend(
                check_framing_source(framing.source, FRAMING_REL))
        else:
            problems.append(Finding("W201", FRAMING_REL, 0, "missing"))
        for ctx in repo.package_files(PACKAGE):
            if ctx.rel == HTTPD_REL:  # the sole allowed raw-HTTP user
                continue
            problems.extend(
                check_package_source(ctx.source, ctx.rel, ctx.tree))
        return problems
