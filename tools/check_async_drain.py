#!/usr/bin/env python
"""Shim over weedlint rule W301 (tools/weedlint/rules_async_drain.py).

The async-drain hot-loop lint moved onto the unified weedlint engine
(PR 10); this entry point and its helper names survive so existing
invocations and tests keep working:

    python tools/check_async_drain.py [repo_root]
    python -m tools.weedlint --rule W301
"""

from __future__ import annotations

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from tools.weedlint import Repo, get_rule  # noqa: E402
from tools.weedlint.rules_async_drain import (  # noqa: E402
    check_drain_fault_source as _fault, check_streaming_source as _streaming)


def _strs(findings) -> list[str]:
    return [f"{f.path}:{f.line}: {f.message}" for f in findings]


def check_streaming_source(src: str, path: str) -> list[str]:
    return _strs(_streaming(src, path))


def check_drain_fault_source(src: str, path: str) -> list[str]:
    return _strs(_fault(src, path))


def check_repo(root: str) -> list[str]:
    return _strs(get_rule("W301").check(Repo(root)))


def main(argv: list[str]) -> int:
    root = argv[0] if argv else os.path.dirname(
        os.path.dirname(os.path.abspath(__file__)))
    problems = check_repo(root)
    for p in problems:
        print(p)
    print(f"check_async_drain: {len(problems)} problem(s)", file=sys.stderr)
    return 1 if problems else 0


if __name__ == "__main__":
    sys.exit(main(sys.argv[1:]))
