#!/usr/bin/env python
"""Async-drain lint: the streaming hot loop must never block on fetch.

PR 7 rebuilt the drain side of ec/streaming.py as an asynchronous,
multi-buffered, parity-only writeback path (ec/overlap.py AsyncDrainer):
the pipeline's critical thread fills/dispatches/writes while a drainer
thread pulls parity back and a writer thread appends it FIFO.  The
whole point dies quietly if a later change reintroduces a blocking
full-block fetch (`np.asarray` / `jax.device_get` / `worker.fetch`) on
the critical thread — the encode still produces correct bytes, it just
stalls again, and nothing but a slow bench run would notice.  This lint
makes the regression loud:

  1. `_encode_file_staged` and `_encode_file_mmap` must both construct
     the AsyncDrainer (the async path exists and is wired).
  2. Inside those two functions, blocking-fetch calls (`_fetch`,
     `fetch`, `asarray`, `device_get`, `block_until_ready`) may appear
     ONLY within nested drain helpers (functions named `drain*`) — the
     hot loop (flush / the entry loop) never blocks on kernel output.
  3. Every `faultinject.hit("ec.drain")` in the package must sit
     lexically inside a `with ... span("pipeline.drain", ...)` block,
     so delay-only slow-drain drills keep attributing to the drain
     stage wherever the drain loop runs (PR-4 contract, now enforced).

  python tools/check_async_drain.py [repo_root]

Exit status 0 = clean, 1 = violations (one per line on stdout).
Stdlib-only — runs as a tier-1 test (tests/test_check_async_drain.py).
"""

from __future__ import annotations

import ast
import os
import sys

PACKAGE = "seaweedfs_tpu"
STREAMING_REL = os.path.join(PACKAGE, "ec", "streaming.py")
SKIP_DIRS = {".git", "__pycache__", ".claude", ".pytest_cache",
             "node_modules", ".venv", "venv"}
# the encode hot-loop functions the async-drain contract covers
HOT_FUNCS = ("_encode_file_staged", "_encode_file_mmap")
# calls that block the calling thread on kernel/worker output
BLOCKING_CALLS = {"_fetch", "fetch", "asarray", "device_get",
                  "block_until_ready"}
# nested helpers allowed to block: the drain side itself
DRAIN_PREFIXES = ("drain", "_drain")


def _call_name(node: ast.Call) -> str:
    f = node.func
    if isinstance(f, ast.Name):
        return f.id
    if isinstance(f, ast.Attribute):
        return f.attr
    return ""


def _is_drain_helper(name: str) -> bool:
    return name.startswith(DRAIN_PREFIXES)


def _check_hot_func(fn: ast.AST, path: str) -> list[str]:
    """Rule 2 on one encode function: blocking calls only inside
    drain* helpers."""
    problems: list[str] = []

    def walk(node: ast.AST, inside_drain: bool) -> None:
        for child in ast.iter_child_nodes(node):
            if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef)):
                walk(child, inside_drain or _is_drain_helper(child.name))
                continue
            if isinstance(child, ast.Call) and not inside_drain:
                name = _call_name(child)
                if name in BLOCKING_CALLS:
                    problems.append(
                        f"{path}:{child.lineno}: blocking `{name}()` on "
                        f"the streaming hot loop (inside {fn.name}) — "
                        f"kernel output must come back through the "
                        f"async drainer (a drain* helper), not block "
                        f"the critical thread")
            walk(child, inside_drain)

    walk(fn, False)
    return problems


def check_streaming_source(src: str, path: str) -> list[str]:
    """Rules 1+2 on ec/streaming.py."""
    try:
        tree = ast.parse(src, filename=path)
    except SyntaxError as e:
        return [f"{path}:{e.lineno or 0}: does not parse: {e.msg}"]
    problems: list[str] = []
    fns = {node.name: node for node in ast.walk(tree)
           if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef))}
    for name in HOT_FUNCS:
        fn = fns.get(name)
        if fn is None:
            problems.append(f"{path}:0: {name} not found — the async-"
                            f"drain contract covers it by name")
            continue
        calls = {_call_name(c) for c in ast.walk(fn)
                 if isinstance(c, ast.Call)}
        if "AsyncDrainer" not in calls:
            problems.append(
                f"{path}:{fn.lineno}: {name} no longer constructs "
                f"AsyncDrainer — the drain would run inline on the "
                f"critical thread and the drain-wait stall returns")
        problems.extend(_check_hot_func(fn, path))
    return problems


def check_drain_fault_source(src: str, path: str) -> list[str]:
    """Rule 3 on any package module: hit("ec.drain") must be inside a
    `with ... span("pipeline.drain", ...)` block."""
    try:
        tree = ast.parse(src, filename=path)
    except SyntaxError as e:
        return [f"{path}:{e.lineno or 0}: does not parse: {e.msg}"]
    problems: list[str] = []

    def span_names(with_node: ast.With) -> set[str]:
        names: set[str] = set()
        for item in with_node.items:
            ctx = item.context_expr
            if isinstance(ctx, ast.Call) and _call_name(ctx) == "span" \
                    and ctx.args \
                    and isinstance(ctx.args[0], ast.Constant):
                names.add(str(ctx.args[0].value))
        return names

    def walk(node: ast.AST, spans: frozenset) -> None:
        for child in ast.iter_child_nodes(node):
            child_spans = spans
            if isinstance(child, ast.With):
                child_spans = spans | span_names(child)
            if isinstance(child, ast.Call) \
                    and _call_name(child) == "hit" \
                    and child.args \
                    and isinstance(child.args[0], ast.Constant) \
                    and child.args[0].value == "ec.drain" \
                    and "pipeline.drain" not in spans:
                problems.append(
                    f"{path}:{child.lineno}: faultinject.hit(\"ec.drain\") "
                    f"outside a `with span(\"pipeline.drain\")` block — "
                    f"delay-only slow-drain drills would stop "
                    f"attributing to the drain stage")
            walk(child, child_spans)

    walk(tree, frozenset())
    return problems


def _read(path: str) -> str:
    with open(path, encoding="utf-8", errors="replace") as f:
        return f.read()


def check_repo(root: str) -> list[str]:
    problems: list[str] = []
    streaming = os.path.join(root, STREAMING_REL)
    if os.path.exists(streaming):
        problems.extend(
            check_streaming_source(_read(streaming), STREAMING_REL))
    else:
        problems.append(f"{STREAMING_REL}:0: missing")
    pkg_root = os.path.join(root, PACKAGE)
    for dirpath, dirnames, filenames in os.walk(pkg_root):
        dirnames[:] = sorted(d for d in dirnames if d not in SKIP_DIRS)
        for name in sorted(filenames):
            if not name.endswith(".py"):
                continue
            path = os.path.join(dirpath, name)
            rel = os.path.relpath(path, root)
            problems.extend(check_drain_fault_source(_read(path), rel))
    return problems


def main(argv: list[str]) -> int:
    root = argv[0] if argv else os.path.dirname(
        os.path.dirname(os.path.abspath(__file__)))
    problems = check_repo(root)
    for p in problems:
        print(p)
    print(f"check_async_drain: {len(problems)} problem(s)", file=sys.stderr)
    return 1 if problems else 0


if __name__ == "__main__":
    sys.exit(main(sys.argv[1:]))
