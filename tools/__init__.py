# tools/ is a package so `python -m tools.weedlint` works from the repo
# root (the tier-1 invocation); the check_*.py shims also run as plain
# scripts.
