"""Second debug-tool batch: change_superblock, check_disk_size,
remove_duplicate_fids, repeated_vacuum, stress_filer_upload,
stream_read_volume, see_meta, see_log_entry, compact_lsm.

References: the corresponding /root/reference/unmaintained/ tools.
"""

from __future__ import annotations

import io
import os
import time

import pytest

from seaweedfs_tpu.storage.needle import Needle
from seaweedfs_tpu.storage.volume import Volume

from .conftest import free_port


@pytest.fixture()
def trio(tmp_path):
    from seaweedfs_tpu.filer.server import FilerServer
    from seaweedfs_tpu.master.server import MasterServer
    from seaweedfs_tpu.volume_server.server import VolumeServer

    master = MasterServer(port=free_port(), pulse_seconds=0.3).start()
    (tmp_path / "v").mkdir()
    vol = VolumeServer([str(tmp_path / "v")], master.url, port=free_port(),
                       pulse_seconds=0.3).start()
    deadline = time.time() + 6
    while time.time() < deadline and not master.topo.all_nodes():
        time.sleep(0.05)
    filer = FilerServer(master.url, port=free_port()).start()
    yield master, vol, filer
    filer.stop()
    vol.stop()
    master.stop()


def test_change_superblock_roundtrip(tmp_path, capsys):
    from seaweedfs_tpu.tools.change_superblock import change_superblock

    v = Volume(str(tmp_path), "", 5)
    v.write_needle(Needle(cookie=1, id=1, data=b"payload" * 10))
    v.close()
    # print-only first
    sb = change_superblock(str(tmp_path), "", 5)
    assert str(sb.replica_placement) == "000"
    # change replication + ttl in place
    change_superblock(str(tmp_path), "", 5, replication="010", ttl="3d")
    v2 = Volume(str(tmp_path), "", 5)
    assert str(v2.super_block.replica_placement) == "010"
    assert str(v2.super_block.ttl) == "3d"
    assert v2.read_needle(1, cookie=1).data == b"payload" * 10
    v2.close()


def test_check_disk_size(tmp_path, capsys):
    from seaweedfs_tpu.tools.check_disk_size import check_dir, main

    v = Volume(str(tmp_path), "", 6)
    v.write_needle(Needle(cookie=1, id=1, data=b"x" * 4096))
    v.close()
    (tmp_path / "unrelated.txt").write_bytes(b"y" * 100)
    r = check_dir(str(tmp_path))
    assert r["volume_bytes"] > 4096
    assert r["other_bytes"] == 100
    assert r["fs_total"] > 0
    assert main([str(tmp_path)]) == 0
    assert "% of used is volume data" in capsys.readouterr().out


def test_remove_duplicate_fids(tmp_path):
    from seaweedfs_tpu.tools.remove_duplicate_fids import remove_duplicates

    v = Volume(str(tmp_path), "", 7)
    v.write_needle(Needle(cookie=1, id=1, data=b"old-version" * 8))
    v.write_needle(Needle(cookie=2, id=2, data=b"unique" * 8))
    v.write_needle(Needle(cookie=1, id=1, data=b"NEW-version" * 8))
    v.close()
    kept, dupes = remove_duplicates(str(tmp_path), "", 7)
    assert (kept, dupes) == (2, 1)
    # the cleaned volume keeps the LAST record for id 1
    os.replace(tmp_path / "7.dat_cleaned", tmp_path / "7.dat")
    os.unlink(tmp_path / "7.idx")
    from seaweedfs_tpu.tools.see_dat import walk_dat
    from seaweedfs_tpu.storage.super_block import SuperBlock

    datas = [rec.data for _, rec in walk_dat(str(tmp_path / "7.dat"))
             if not isinstance(rec, SuperBlock)]
    assert datas == [b"unique" * 8, b"NEW-version" * 8]


def test_remove_duplicate_fids_fix_reopen(tmp_path):
    """The full repair recipe the tool prints: dedup -> weed fix ->
    reopen.  Regression: fix used to write the .idx id-sorted, and the
    open-time integrity check (which trusts the LAST idx entry to name
    the .dat tail) truncated every record past the highest id."""
    import subprocess
    import sys

    from seaweedfs_tpu.tools.remove_duplicate_fids import remove_duplicates

    v = Volume(str(tmp_path), "", 7)
    for i in range(1, 21):
        v.write_needle(Needle(cookie=9, id=i, data=b"first-%d" % i))
    for i in range(5, 10):  # ids 5..9 rewritten -> dups at the tail
        v.write_needle(Needle(cookie=9, id=i, data=b"second-%d" % i))
    v.close()
    kept, dupes = remove_duplicates(str(tmp_path), "", 7)
    assert (kept, dupes) == (20, 5)
    os.replace(tmp_path / "7.dat", tmp_path / "7.dat_orig")
    os.replace(tmp_path / "7.dat_cleaned", tmp_path / "7.dat")
    os.unlink(tmp_path / "7.idx")
    weed = os.path.join(os.path.dirname(os.path.dirname(__file__)),
                        "weed.py")
    r = subprocess.run(
        [sys.executable, weed, "fix", "-dir", str(tmp_path),
         "-volumeId", "7"], capture_output=True, text=True, timeout=120,
        env={**os.environ, "PYTHONPATH": os.path.dirname(weed)})
    assert r.returncode == 0, r.stderr
    dat_size = (tmp_path / "7.dat").stat().st_size
    v2 = Volume(str(tmp_path), "", 7)
    try:
        # open must NOT truncate the (valid) cleaned volume
        assert (tmp_path / "7.dat").stat().st_size == dat_size
        assert v2.read_needle(7, cookie=9).data == b"second-7"
        assert v2.read_needle(15, cookie=9).data == b"first-15"
    finally:
        v2.close()


def test_repeated_vacuum_keeps_live_data(trio):
    from seaweedfs_tpu.tools.repeated_vacuum import repeated_vacuum

    master, _, _ = trio
    out = io.StringIO()
    compacted = repeated_vacuum(master.url, rounds=2, per_round=8,
                                size=2048, out=out)
    assert compacted >= 1  # deletes made garbage, vacuum compacted
    assert "CORRUPTION" not in out.getvalue()


def test_stress_filer_upload(trio):
    from seaweedfs_tpu.tools.stress_filer_upload import stress_filer

    _, _, filer = trio
    out = stress_filer(filer.url, seconds=1.5, concurrency=2,
                       min_size=512, max_size=4096)
    assert out["errors"] == 0
    assert out["uploads"] > 0 and out["reads"] > 0


def test_stream_read_volume(trio, capsys):
    from seaweedfs_tpu.client.operation import WeedClient
    from seaweedfs_tpu.tools.stream_read_volume import stream_read

    master, vol, _ = trio
    client = WeedClient(master.url)
    fid = client.upload(b"streamed needle " * 16, name="s.bin")
    vid = int(fid.split(",")[0])
    out = io.StringIO()
    count = stream_read(vol.url, vid, verbose=True, out=out)
    assert count == 1
    text = out.getvalue()
    assert "superblock: version=3" in text
    assert "s.bin" in text  # -v prints names


def test_see_meta_and_see_log_entry(trio, capsys):
    from seaweedfs_tpu.tools.see_log_entry import see_log
    from seaweedfs_tpu.tools.see_meta import walk
    from seaweedfs_tpu.utils.httpd import http_bytes

    _, _, filer = trio
    http_bytes("PUT", f"http://{filer.url}/docs/a.txt", b"alpha")
    http_bytes("PUT", f"http://{filer.url}/docs/deep/b.txt", b"beta")
    http_bytes("DELETE", f"http://{filer.url}/docs/a.txt")
    out = io.StringIO()
    n = walk(filer.url, "/", out=out)
    text = out.getvalue()
    assert "/docs/deep/b.txt" in text and n >= 2
    out = io.StringIO()
    events = see_log(filer.url, out=out)
    text = out.getvalue()
    assert events >= 3
    assert "CREATE /docs/a.txt" in text
    assert "DELETE /docs/a.txt" in text


def test_compact_lsm(tmp_path):
    from seaweedfs_tpu.filer.lsm_store import LsmStore
    from seaweedfs_tpu.tools.compact_lsm import compact

    d = str(tmp_path / "s.lsm")
    store = LsmStore(d, memtable_limit=4)
    for i in range(40):  # many flushes -> many sstables
        store.kv_put(f"k{i:03d}".encode(), f"v{i}".encode())
    store.kv_delete(b"k001")
    store.flush()
    del store
    before, after = compact(d)
    assert before > 1 and after == 1
    reopened = LsmStore(d)
    assert reopened.kv_get(b"k000") == b"v0"
    assert reopened.kv_get(b"k001") is None
    assert reopened.kv_get(b"k039") == b"v39"
