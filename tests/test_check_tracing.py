"""tools/check_tracing.py (now a shim over weedlint rule W201) as a
tier-1 gate.

Distributed tracing (PR 6) is enforced at two chokepoints: every HTTP
handler runs under Router.dispatch's request span + trace context, and
every outbound hop rides utils/httpd's injecting client helpers.  These
tests (a) pin the checker's detection of bypasses on planted sources,
and (b) run it over the WHOLE repo so a new endpoint or a hand-rolled
HTTP call that would shatter cross-server traces fails tier-1 loudly.
"""

from __future__ import annotations

import importlib.util
import os

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
TOOL = os.path.join(REPO, "tools", "check_tracing.py")


def _load():
    spec = importlib.util.spec_from_file_location("check_tracing", TOOL)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


CHECK = _load()


class TestPlantedViolations:
    def test_raw_urllib_request_rejected(self):
        for src in ("import urllib.request\n",
                    "from urllib import request\n",
                    "import http.client\n",
                    "from http import client\n"):
            problems = CHECK.check_package_source(src, "pkg/x.py")
            assert problems and "utils.httpd" in problems[0], src

    def test_tracing_exempt_waiver_accepted(self):
        src = ("import http.client  "
               "# tracing-exempt: external endpoint\n")
        assert CHECK.check_package_source(src, "pkg/x.py") == []

    def test_plain_urllib_parse_is_fine(self):
        assert CHECK.check_package_source(
            "import urllib.parse\nimport urllib.error\n", "x.py") == []

    def test_router_dispatch_override_rejected(self):
        src = ("class MyRouter(Router):\n"
               "    def dispatch(self, handler, method):\n"
               "        pass\n")
        problems = CHECK.check_package_source(src, "pkg/x.py")
        assert problems and "dispatch" in problems[0]

    def test_dispatch_without_context_rejected(self):
        # a gutted Router.dispatch (no begin_request/end_request/span)
        # must fail the chokepoint contract
        src = ("class Router:\n"
               "    def dispatch(self, handler, method):\n"
               "        return None\n"
               "def _pooled_request(m, u, b, h, t):\n"
               "    return inject_trace_headers(h)\n"
               "def http_download(m, u, d):\n"
               "    return inject_trace_headers({})\n")
        problems = CHECK.check_httpd_source(src, "httpd.py")
        assert any("begin_request" in p for p in problems)

    def test_outbound_helper_without_inject_rejected(self):
        src = ("class Router:\n"
               "    def dispatch(self, handler, method):\n"
               "        begin_request(h)\n"
               "        tracer.span('x')\n"
               "        end_request(p)\n"
               "def _pooled_request(m, u, b, h, t):\n"
               "    return None\n"
               "def http_download(m, u, d):\n"
               "    return inject_trace_headers({})\n")
        problems = CHECK.check_httpd_source(src, "httpd.py")
        assert any("_pooled_request" in p
                   and "inject_trace_headers" in p for p in problems)


class TestWholeRepo:
    def test_repo_is_clean(self):
        problems = CHECK.check_repo(REPO)
        assert problems == [], "\n".join(problems)
