"""Remote storage: configure/mount/cache/uncache/unmount + remote sync.

Reference behaviors: weed/remote_storage/, filer/read_remote.go,
shell/command_remote_*.go, command/filer_remote_sync.go.
"""

from __future__ import annotations

import json
import os
import time

import pytest

from seaweedfs_tpu.filer.server import FilerServer
from seaweedfs_tpu.master.server import MasterServer
from seaweedfs_tpu.remote_storage.client import (LocalRemoteStorage,
                                                 RemoteConf, RemoteLocation)
from seaweedfs_tpu.remote_storage.sync import RemoteSyncer
from seaweedfs_tpu.shell.commands import CommandEnv, run_command
from seaweedfs_tpu.utils.httpd import http_bytes, http_json
from seaweedfs_tpu.volume_server.server import VolumeServer
from tests.conftest import free_port


@pytest.fixture
def stack(tmp_path):
    master = MasterServer(port=free_port(), pulse_seconds=0.3).start()
    d = tmp_path / "vs0"
    d.mkdir()
    vol = VolumeServer([str(d)], master.url, port=free_port(),
                       pulse_seconds=0.3).start()
    deadline = time.time() + 5
    while time.time() < deadline and len(master.topo.all_nodes()) < 1:
        time.sleep(0.05)
    filer = FilerServer(master.url, port=free_port(), max_chunk_mb=1).start()
    env = CommandEnv(master.url, filer.url)
    env.lock()
    # a "cloud": local dir with one bucket and two objects
    cloud = tmp_path / "cloud"
    (cloud / "bkt/photos").mkdir(parents=True)
    (cloud / "bkt/photos/a.jpg").write_bytes(b"JPEGDATA" * 100)
    (cloud / "bkt/readme.txt").write_bytes(b"read me")
    yield master, vol, filer, env, cloud
    filer.stop()
    vol.stop()
    master.stop()


def test_local_client_traverse_and_io(tmp_path):
    conf = RemoteConf("c", type="local", root=str(tmp_path / "r"))
    c = LocalRemoteStorage(conf)
    loc = RemoteLocation("c", "b", "/")
    c.write_file(loc, "/x/y.bin", b"hello")
    objs = list(c.traverse(loc))
    assert [(o.key, o.size) for o in objs] == [("/x/y.bin", 5)]
    assert c.read_file(loc, "/x/y.bin") == b"hello"
    assert c.read_file(loc, "/x/y.bin", offset=1, size=3) == b"ell"
    assert c.list_buckets() == ["b"]
    c.delete_file(loc, "/x/y.bin")
    assert list(c.traverse(loc)) == []
    with pytest.raises(ValueError):
        c.read_file(loc, "/../../etc/passwd")


def test_remote_mount_lazy_cache_and_uncache(stack, tmp_path):
    master, vol, filer, env, cloud = stack
    base = f"http://{filer.url}"
    run_command(env, f"remote.configure -name mycloud -type local "
                     f"-root {cloud}")
    out = run_command(env, "remote.mount -dir /clouddata -remote mycloud/bkt")
    assert "2 entries" in out
    # metadata imported: size visible without content being local
    stat = http_json("GET", base + "/api/stat/clouddata/photos/a.jpg")
    assert stat["file_size"] == 800
    assert stat["chunks"] == []
    # first read faults the content in (CacheRemoteObjectToLocalCluster)
    status, body, _ = http_bytes("GET", base + "/clouddata/photos/a.jpg")
    assert (status, body) == (200, b"JPEGDATA" * 100)
    stat = http_json("GET", base + "/api/stat/clouddata/photos/a.jpg")
    assert len(stat["chunks"]) >= 1
    # uncache drops the chunks but keeps the metadata
    out = run_command(env, "remote.uncache -dir /clouddata")
    assert "uncached 1" in out
    stat = http_json("GET", base + "/api/stat/clouddata/photos/a.jpg")
    assert stat["chunks"] == [] and stat["file_size"] == 800
    # remote.cache pulls everything matching
    out = run_command(env, "remote.cache -dir /clouddata -include *.txt")
    assert "cached 1" in out
    # unmount removes mapping + metadata
    run_command(env, "remote.unmount -dir /clouddata")
    assert http_bytes("GET", base + "/clouddata/readme.txt")[0] == 404


def test_remote_mount_buckets(stack, tmp_path):
    master, vol, filer, env, cloud = stack
    (cloud / "second").mkdir()
    (cloud / "second/s.txt").write_bytes(b"s")
    run_command(env, f"remote.configure -name rc -type local -root {cloud}")
    out = run_command(env, "remote.mount.buckets -remote rc")
    assert "/buckets/bkt" in out and "/buckets/second" in out
    status, body, _ = http_bytes(
        "GET", f"http://{filer.url}/buckets/second/s.txt")
    assert (status, body) == (200, b"s")


def test_remote_sync_pushes_local_changes(stack, tmp_path):
    master, vol, filer, env, cloud = stack
    base = f"http://{filer.url}"
    run_command(env, f"remote.configure -name mc -type local -root {cloud}")
    run_command(env, "remote.mount -dir /rs -remote mc/bkt")
    syncer = RemoteSyncer(filer.url, "/rs")
    # local create propagates to the cloud
    http_bytes("PUT", base + "/rs/new.bin", b"fresh-bytes")
    n = syncer.run_until_caught_up()
    assert n == 1
    assert (cloud / "bkt/new.bin").read_bytes() == b"fresh-bytes"
    # the stamp echo does not re-upload
    assert syncer.run_until_caught_up() == 0
    # caching a remote object does not echo an upload
    http_bytes("GET", base + "/rs/readme.txt")
    assert syncer.run_until_caught_up() == 0
    # local delete propagates
    http_bytes("DELETE", base + "/rs/new.bin")
    assert syncer.run_until_caught_up() == 1
    assert not (cloud / "bkt/new.bin").exists()
    # rename moves the remote object
    http_bytes("PUT", base + "/rs/old.txt", b"mv-me")
    syncer.run_until_caught_up()
    http_json("POST", base + "/api/rename",
              {"from": "/rs/old.txt", "to": "/rs/new2.txt"})
    syncer.run_until_caught_up()
    assert not (cloud / "bkt/old.txt").exists()
    assert (cloud / "bkt/new2.txt").read_bytes() == b"mv-me"
