"""Compression + cipher on the upload path, chunk manifests, Range reads.

Reference behaviors: weed/util/compression.go (MaybeGzipData 10/9 rule,
IsCompressableFileType), weed/util/cipher.go (AES-256-GCM, nonce-prefixed),
weed/filer/filechunk_manifest.go (10k-batch recursive manifests),
weed/server/volume_server_handlers_read.go (Range / If-None-Match /
Content-Encoding), weed/operation/upload_content.go (client-side gzip).
"""

from __future__ import annotations

import time

import pytest

# environmental guard, not a code gate: the upload cipher rides AES-GCM
# from `cryptography`, which this container intentionally lacks — skip
# (reason makes the tier-1 log distinguish missing-lib from regression)
pytest.importorskip(
    "cryptography",
    reason="environmental: cryptography not installed in this container")

from seaweedfs_tpu.client.operation import WeedClient
from seaweedfs_tpu.filer.entry import FileChunk
from seaweedfs_tpu.filer.filechunk_manifest import (
    has_chunk_manifest,
    maybe_manifestize,
    resolve_chunk_manifest,
)
from seaweedfs_tpu.filer.server import FilerServer
from seaweedfs_tpu.master.server import MasterServer
from seaweedfs_tpu.utils.cipher import decrypt, encrypt, gen_cipher_key
from seaweedfs_tpu.utils.compression import (
    is_compressable_file_type,
    is_gzipped_content,
    maybe_gzip_data,
    ungzip_data,
)
from seaweedfs_tpu.utils.httpd import http_bytes
from seaweedfs_tpu.volume_server.server import VolumeServer
from tests.conftest import free_port


# --- pure helpers -----------------------------------------------------------

def test_cipher_roundtrip_and_tamper():
    key = gen_cipher_key()
    ct = encrypt(b"secret payload", key)
    assert ct != b"secret payload" and len(ct) > 14
    assert decrypt(ct, key) == b"secret payload"
    with pytest.raises(Exception):
        decrypt(ct[:-1] + bytes([ct[-1] ^ 1]), key)  # GCM auth must fail
    with pytest.raises(Exception):
        decrypt(ct, gen_cipher_key())


def test_maybe_gzip_win_rule():
    text = b"the quick brown fox " * 200
    gz = maybe_gzip_data(text)
    assert is_gzipped_content(gz) and ungzip_data(gz) == text
    # already-gzipped and incompressible data pass through untouched
    assert maybe_gzip_data(gz) is gz
    import os

    rnd = os.urandom(4096)
    assert maybe_gzip_data(rnd) is rnd


def test_compressable_file_type_table():
    assert is_compressable_file_type("", "text/plain") == (True, True)
    assert is_compressable_file_type(".txt", "") == (True, True)
    assert is_compressable_file_type(".zip", "") == (False, True)
    assert is_compressable_file_type(".jpg", "image/jpeg") == (False, True)
    assert is_compressable_file_type("", "application/xml") == (True, True)
    assert is_compressable_file_type("", "audio/wav") == (True, True)
    assert is_compressable_file_type(".bin", "") == (False, False)


# --- manifest unit logic ----------------------------------------------------

def _mk_chunks(n, size=10):
    return [FileChunk(file_id=f"1,{i:08x}", offset=i * size, size=size,
                      modified_ts_ns=i + 1) for i in range(n)]


def test_maybe_manifestize_batches_and_tail():
    stored: dict[str, bytes] = {}

    def save(blob: bytes) -> FileChunk:
        fid = f"9,{len(stored):08x}"
        stored[fid] = blob
        return FileChunk(file_id=fid, offset=0, size=len(blob),
                         modified_ts_ns=time.time_ns())

    chunks = _mk_chunks(10)
    out = maybe_manifestize(save, chunks, merge_factor=4)
    # 10 chunks -> 2 manifests of 4 + 2 inline
    manifests = [c for c in out if c.is_chunk_manifest]
    inline = [c for c in out if not c.is_chunk_manifest]
    assert len(manifests) == 2 and len(inline) == 2
    assert manifests[0].offset == 0 and manifests[0].size == 4 * 10
    # resolution restores the full flat list
    data, mchunks = resolve_chunk_manifest(
        lambda c: stored[c.file_id], out)
    assert sorted(c.offset for c in data) == [i * 10 for i in range(10)]
    assert len(mchunks) == 2
    # under the factor: untouched
    small = _mk_chunks(3)
    assert maybe_manifestize(save, small, merge_factor=4) == small


def test_manifest_recursion_two_levels():
    stored: dict[str, bytes] = {}

    def save(blob: bytes) -> FileChunk:
        fid = f"9,{len(stored):08x}"
        stored[fid] = blob
        return FileChunk(file_id=fid, offset=0, size=len(blob),
                         modified_ts_ns=time.time_ns())

    level1 = maybe_manifestize(save, _mk_chunks(16), merge_factor=4)
    level2 = maybe_manifestize(save, level1, merge_factor=4)
    # level1: 4 manifests; level2 collapses those... manifests pass through,
    # so level2 == level1 (manifest chunks are never re-batched)
    assert level2 == level1
    data, _ = resolve_chunk_manifest(lambda c: stored[c.file_id], level2)
    assert len(data) == 16


# --- cluster fixtures -------------------------------------------------------

@pytest.fixture
def cluster(tmp_path):
    master = MasterServer(port=free_port(), pulse_seconds=0.4).start()
    d = tmp_path / "v"
    d.mkdir()
    vol = VolumeServer([str(d)], master.url, port=free_port(),
                       pulse_seconds=0.4).start()
    deadline = time.time() + 5
    while time.time() < deadline and len(master.topo.all_nodes()) < 1:
        time.sleep(0.05)
    yield master, vol
    vol.stop()
    master.stop()


def _mk_filer(cluster, **kw):
    master, _ = cluster
    return FilerServer(master.url, port=free_port(), **kw).start()


# --- filer compression ------------------------------------------------------

def test_filer_compressible_upload_roundtrip_and_range(cluster):
    f = _mk_filer(cluster, max_chunk_mb=1)
    try:
        text = (b"line of text %d\n" % 7) * 20_000  # ~300KB, compressible
        http_bytes("PUT", f"http://{f.url}/logs/a.txt", text,
                   headers={"Content-Type": "text/plain"})
        entry = f.filer.find_entry("/logs/a.txt")
        assert entry.chunks and all(c.is_compressed for c in entry.chunks)
        status, body, _ = http_bytes("GET", f"http://{f.url}/logs/a.txt")
        assert status == 200 and body == text
        status, body, hdrs = http_bytes(
            "GET", f"http://{f.url}/logs/a.txt",
            headers={"Range": "bytes=100000-100099"})
        assert status == 206 and body == text[100000:100100]
        # stored blob on the volume server is actually gzipped
        blob, _ = f.client._get(entry.chunks[0].file_id, None)
        assert is_gzipped_content(blob)
        assert len(blob) < entry.chunks[0].size
    finally:
        f.stop()


def test_filer_incompressible_stays_raw(cluster):
    import os

    f = _mk_filer(cluster)
    try:
        data = os.urandom(50_000)
        http_bytes("PUT", f"http://{f.url}/b.bin", data)
        entry = f.filer.find_entry("/b.bin")
        assert all(not c.is_compressed for c in entry.chunks)
        _, body, _ = http_bytes("GET", f"http://{f.url}/b.bin")
        assert body == data
    finally:
        f.stop()


# --- filer cipher -----------------------------------------------------------

def test_filer_cipher_roundtrip_and_opaque_storage(cluster):
    f = _mk_filer(cluster, cipher=True, max_chunk_mb=1)
    try:
        secret = b"top secret bytes " * 10_000  # multi-chunk at 1MB? ~170KB
        http_bytes("PUT", f"http://{f.url}/vault/s.txt", secret,
                   headers={"Content-Type": "text/plain"})
        entry = f.filer.find_entry("/vault/s.txt")
        assert entry.chunks and all(c.cipher_key for c in entry.chunks)
        assert all(c.is_compressed for c in entry.chunks)  # gzip-then-seal
        # volume server holds ciphertext: neither plaintext nor gzip
        blob, _ = f.client._get(entry.chunks[0].file_id, None)
        assert secret[:64] not in blob
        assert not is_gzipped_content(blob)
        # full + ranged reads decrypt transparently
        _, body, _ = http_bytes("GET", f"http://{f.url}/vault/s.txt")
        assert body == secret
        status, body, _ = http_bytes(
            "GET", f"http://{f.url}/vault/s.txt",
            headers={"Range": "bytes=5000-5099"})
        assert status == 206 and body == secret[5000:5100]
    finally:
        f.stop()


# --- filer manifests end-to-end ---------------------------------------------

def test_filer_manifest_file_roundtrips(cluster):
    f = _mk_filer(cluster)
    try:
        f.max_chunk_size = 1024  # tiny chunks
        f.manifest_batch = 8
        data = bytes(i % 251 for i in range(40 * 1024))  # 40 chunks
        http_bytes("PUT", f"http://{f.url}/big.bin", data)
        entry = f.filer.find_entry("/big.bin")
        assert has_chunk_manifest(entry.chunks)
        assert len(entry.chunks) < 40  # collapsed
        assert entry.file_size == len(data)
        _, body, _ = http_bytes("GET", f"http://{f.url}/big.bin")
        assert body == data
        status, body, _ = http_bytes(
            "GET", f"http://{f.url}/big.bin",
            headers={"Range": "bytes=10000-20479"})
        assert status == 206 and body == data[10000:20480]
        # overwrite part of the file: new chunk shadows manifest content
        http_bytes("PUT", f"http://{f.url}/big.bin?op=append", b"")
    finally:
        f.stop()


def test_filer_manifest_with_cipher(cluster):
    f = _mk_filer(cluster, cipher=True)
    try:
        f.max_chunk_size = 1024
        f.manifest_batch = 4
        data = bytes((i * 7) % 256 for i in range(12 * 1024))
        http_bytes("PUT", f"http://{f.url}/mc.bin", data)
        entry = f.filer.find_entry("/mc.bin")
        assert has_chunk_manifest(entry.chunks)
        manifest = next(c for c in entry.chunks if c.is_chunk_manifest)
        assert manifest.cipher_key  # manifests are sealed too (they hold keys)
        _, body, _ = http_bytes("GET", f"http://{f.url}/mc.bin")
        assert body == data
    finally:
        f.stop()


# --- volume server Range / If-None-Match / client gzip ----------------------

@pytest.fixture
def weed(cluster):
    master, _ = cluster
    c = WeedClient(master.url)
    yield c
    c.close()


def test_volume_range_reads_exact_bytes(cluster, weed):
    data = bytes(i % 256 for i in range(100_000))
    fid = weed.upload(data)
    urls, _ = weed.master.lookup_with_auth(int(fid.split(",")[0]))
    url = urls[0]
    status, body, hdrs = http_bytes(
        "GET", f"http://{url}/{fid}",
        headers={"Range": "bytes=5000-5999"})
    assert status == 206
    assert body == data[5000:6000]
    assert hdrs.get("Content-Range") == "bytes 5000-5999/100000"
    # suffix range
    status, body, hdrs = http_bytes(
        "GET", f"http://{url}/{fid}", headers={"Range": "bytes=-100"})
    assert status == 206 and body == data[-100:]
    # unsatisfiable
    status, _, hdrs = http_bytes(
        "GET", f"http://{url}/{fid}",
        headers={"Range": "bytes=200000-200009"})
    assert status == 416 and hdrs.get("Content-Range") == "bytes */100000"
    assert weed.download_range(fid, 12345, 678) == data[12345:13023]


def test_volume_if_none_match_304(cluster, weed):
    fid = weed.upload(b"etag me")
    urls, _ = weed.master.lookup_with_auth(int(fid.split(",")[0]))
    url = urls[0]
    status, _, hdrs = http_bytes("GET", f"http://{url}/{fid}")
    etag = hdrs.get("ETag")
    assert status == 200 and etag
    status, body, _ = http_bytes("GET", f"http://{url}/{fid}",
                                 headers={"If-None-Match": etag})
    assert status == 304 and body == b""


def test_client_gzip_upload_sets_needle_flag(cluster, weed):
    text = b"compress me please " * 5000
    fid = weed.upload(text, name="doc.txt", mime="text/plain")
    # plain client gets plaintext back
    assert weed.download(fid) == text
    urls, _ = weed.master.lookup_with_auth(int(fid.split(",")[0]))
    url = urls[0]
    # gzip-accepting client gets the stored gzip + header
    status, body, hdrs = http_bytes(
        "GET", f"http://{url}/{fid}",
        headers={"Accept-Encoding": "gzip"})
    assert status == 200
    assert hdrs.get("Content-Encoding") == "gzip"
    assert is_gzipped_content(body) and ungzip_data(body) == text
    # non-gzip client gets server-side decompression
    status, body, hdrs = http_bytes("GET", f"http://{url}/{fid}")
    assert status == 200 and body == text
    assert hdrs.get("Content-Encoding") != "gzip"


def test_manifest_delete_reclaims_child_chunks(cluster):
    """Deleting a manifestized file must GC the manifest blob AND every
    child chunk it references (filer_delete_entry.go resolves manifests
    before queueing chunk deletion)."""
    f = _mk_filer(cluster)
    try:
        f.max_chunk_size = 1024
        f.manifest_batch = 4
        data = bytes(i % 256 for i in range(8 * 1024))  # 8 chunks
        http_bytes("PUT", f"http://{f.url}/doomed.bin", data)
        entry = f.filer.find_entry("/doomed.bin")
        assert has_chunk_manifest(entry.chunks)
        children, manifests = resolve_chunk_manifest(
            f.fetch_chunk, entry.chunks)
        all_fids = [c.file_id for c in children + manifests]
        assert len(children) == 8
        f.chunk_cache._mem.clear() if hasattr(f.chunk_cache, "_mem") else None
        http_bytes("DELETE", f"http://{f.url}/doomed.bin")
        deadline = time.time() + 10
        gone = set()
        while time.time() < deadline and len(gone) < len(all_fids):
            for fid in all_fids:
                if fid in gone:
                    continue
                try:
                    f.client.download(fid)
                except Exception:
                    gone.add(fid)
            time.sleep(0.2)
        assert gone == set(all_fids), \
            f"leaked chunks: {set(all_fids) - gone}"
    finally:
        f.stop()
