"""Chunk cache tiers, bounded tree, image resize gating, FTP stub.

Reference behaviors: util/chunk_cache/, util/bounded_tree/,
images/resizing.go, ftpd/ftp_server.go.
"""

from __future__ import annotations

import socket
import time

import pytest

from seaweedfs_tpu.gateway.ftp import FtpServer
from seaweedfs_tpu.images import resized, resizing_available
from seaweedfs_tpu.utils.bounded_tree import BoundedTree
from seaweedfs_tpu.utils.chunk_cache import (DiskChunkCache, MemChunkCache,
                                             TieredChunkCache)
from tests.conftest import free_port


# --- chunk cache ------------------------------------------------------------

def test_mem_cache_lru_eviction():
    c = MemChunkCache(limit_bytes=100)
    c.set("a", b"x" * 40)
    c.set("b", b"y" * 40)
    assert c.get("a") == b"x" * 40  # touch a -> b is now LRU
    c.set("c", b"z" * 40)           # evicts b
    assert c.get("b") is None
    assert c.get("a") and c.get("c")
    c.set("huge", b"q" * 200)       # over limit: not cached
    assert c.get("huge") is None
    c.delete("a")
    assert c.get("a") is None


def test_disk_cache_roundtrip_and_eviction(tmp_path):
    c = DiskChunkCache(str(tmp_path / "cache"), limit_bytes=100)
    c.set("1,abc", b"d" * 60)
    assert c.get("1,abc") == b"d" * 60
    time.sleep(0.02)
    c.set("2,def", b"e" * 60)  # over limit -> oldest evicted
    assert c.get("2,def") == b"e" * 60
    assert c.get("1,abc") is None
    # restart rebuilds size accounting from disk
    c2 = DiskChunkCache(str(tmp_path / "cache"), limit_bytes=100)
    assert c2.get("2,def") == b"e" * 60


def test_tiered_cache_promotion(tmp_path):
    c = TieredChunkCache(mem_limit=1024, disk_dir=str(tmp_path / "d"),
                         disk_limit=1 << 20, mem_chunk_max=100)
    small, big = b"s" * 50, b"B" * 500
    c.set("small", small)
    c.set("big", big)
    assert c.mem.get("small") == small
    assert c.mem.get("big") is None       # too big for mem tier
    assert c.get("big") == big            # served from disk
    # drop mem copy; get() must promote from disk back into mem
    c.mem.delete("small")
    assert c.get("small") == small
    assert c.mem.get("small") == small
    c.delete("big")
    assert c.get("big") is None


def test_filer_uses_chunk_cache(tmp_path):
    from seaweedfs_tpu.filer.server import FilerServer
    from seaweedfs_tpu.master.server import MasterServer
    from seaweedfs_tpu.utils.httpd import http_bytes
    from seaweedfs_tpu.volume_server.server import VolumeServer

    master = MasterServer(port=free_port(), pulse_seconds=0.3).start()
    d = tmp_path / "vs"
    d.mkdir()
    vol = VolumeServer([str(d)], master.url, port=free_port(),
                       pulse_seconds=0.3).start()
    while len(master.topo.all_nodes()) < 1:
        time.sleep(0.05)
    filer = FilerServer(master.url, port=free_port(), max_chunk_mb=1).start()
    try:
        base = f"http://{filer.url}"
        http_bytes("PUT", base + "/c.bin", b"cachable" * 1000)
        http_bytes("GET", base + "/c.bin")
        misses = filer.chunk_cache.mem.misses
        hits0 = filer.chunk_cache.mem.hits
        http_bytes("GET", base + "/c.bin")
        assert filer.chunk_cache.mem.hits > hits0
        assert filer.chunk_cache.mem.misses == misses
        # overwrite invalidates via chunk GC
        old_fids = [c.file_id
                    for c in filer.filer.find_entry("/c.bin").chunks]
        http_bytes("PUT", base + "/c.bin", b"new")
        filer.filer.flush_gc()
        assert all(filer.chunk_cache.get(f) is None for f in old_fids)
        _, body, _ = http_bytes("GET", base + "/c.bin")
        assert body == b"new"
    finally:
        filer.stop()
        vol.stop()
        master.stop()


# --- bounded tree -----------------------------------------------------------

def test_bounded_tree_visit_and_invalidate():
    t = BoundedTree(limit=3)
    for p in ("/a", "/a/b", "/c"):
        t.mark_visited(p)
    assert t.has_visited("/a/b")
    t.mark_visited("/d")  # evicts LRU (/a — /a/b was refreshed by has_visited)
    assert not t.has_visited("/a")
    t.ensure_invalidated("/a")
    assert not t.has_visited("/a/b")
    assert t.has_visited("/c")


# --- images -----------------------------------------------------------------

def test_resized_passthrough_without_pillow():
    # environment has no Pillow: resized() must be a safe no-op
    data = b"\xff\xd8\xff\xe0 fake jpeg"
    out, w, h = resized(data, "image/jpeg", 100, 100)
    if resizing_available():  # pragma: no cover - env-dependent
        assert isinstance(out, bytes)
    else:
        assert (out, w, h) == (data, 0, 0)
    # non-image content always passes through
    out, w, h = resized(b"text", "text/plain", 10, 10)
    assert (out, w, h) == (b"text", 0, 0)


# --- ftp gateway -------------------------------------------------------------

def test_ftp_gateway_end_to_end(tmp_path):
    """Drive the filer-backed FTP server with the STDLIB client
    (ftplib): login, mkdir, upload, listing, download, rename, size,
    delete — the real protocol over real sockets."""
    import ftplib
    import io
    import time as _time

    from seaweedfs_tpu.filer.server import FilerServer
    from seaweedfs_tpu.master.server import MasterServer
    from seaweedfs_tpu.volume_server.server import VolumeServer

    master = MasterServer(port=free_port(), pulse_seconds=0.4).start()
    (tmp_path / "v").mkdir()
    vol = VolumeServer([str(tmp_path / "v")], master.url, port=free_port(),
                       pulse_seconds=0.4).start()
    deadline = _time.time() + 5
    while _time.time() < deadline and not master.topo.all_nodes():
        _time.sleep(0.05)
    filer = FilerServer(master.url, port=free_port()).start()
    srv = FtpServer(filer, port=free_port(), password="pw").start()
    try:
        ftp = ftplib.FTP()
        ftp.connect("127.0.0.1", srv.port, timeout=10)
        # wrong password refused
        try:
            ftp.login("alice", "nope")
            assert False, "bad password accepted"
        except ftplib.error_perm:
            pass
        ftp.login("alice", "pw")
        ftp.mkd("/docs")
        ftp.cwd("/docs")
        payload = b"ftp payload " * 500
        ftp.storbinary("STOR hello.bin", io.BytesIO(payload))
        assert ftp.size("hello.bin") == len(payload)
        assert "hello.bin" in ftp.nlst()
        long_lines = []
        ftp.retrlines("LIST", long_lines.append)
        assert any("hello.bin" in ln for ln in long_lines)
        out = io.BytesIO()
        ftp.retrbinary("RETR hello.bin", out.write)
        assert out.getvalue() == payload
        ftp.rename("hello.bin", "renamed.bin")
        out2 = io.BytesIO()
        ftp.retrbinary("RETR /docs/renamed.bin", out2.write)
        assert out2.getvalue() == payload
        ftp.delete("renamed.bin")
        assert "renamed.bin" not in ftp.nlst()
        ftp.cwd("/")
        ftp.rmd("/docs")
        ftp.quit()
    finally:
        srv.stop()
        filer.stop()
        vol.stop()
        master.stop()


def test_ftp_rest_stor_resumes_upload(tmp_path):
    """REST n + STOR splices the received bytes over the existing file
    (FEAT advertises REST STREAM, so resumed uploads must not truncate
    the file to the tail)."""
    import ftplib
    import io
    import time as _time

    from seaweedfs_tpu.filer.server import FilerServer
    from seaweedfs_tpu.master.server import MasterServer
    from seaweedfs_tpu.volume_server.server import VolumeServer

    master = MasterServer(port=free_port(), pulse_seconds=0.4).start()
    (tmp_path / "v").mkdir()
    vol = VolumeServer([str(tmp_path / "v")], master.url, port=free_port(),
                       pulse_seconds=0.4).start()
    deadline = _time.time() + 5
    while _time.time() < deadline and not master.topo.all_nodes():
        _time.sleep(0.05)
    filer = FilerServer(master.url, port=free_port()).start()
    srv = FtpServer(filer, port=free_port()).start()
    try:
        ftp = ftplib.FTP()
        ftp.connect("127.0.0.1", srv.port, timeout=10)
        ftp.login("u", "p")
        full = b"0123456789" * 100
        ftp.storbinary("STOR f.bin", io.BytesIO(full))
        # resume: replace everything from byte 600 on
        ftp.storbinary("STOR f.bin", io.BytesIO(b"TAIL" * 10), rest=600)
        out = io.BytesIO()
        ftp.retrbinary("RETR f.bin", out.write)
        assert out.getvalue() == full[:600] + b"TAIL" * 10
        # REST+STOR to a file that does not exist yet: the splice path
        # zero-pads the gap instead of 550ing (find_entry raises
        # NotFoundError; the handler must flatten it, not crash on it)
        ftp.storbinary("STOR fresh.bin", io.BytesIO(b"XY"), rest=4)
        out3 = io.BytesIO()
        ftp.retrbinary("RETR fresh.bin", out3.write)
        assert out3.getvalue() == b"\x00\x00\x00\x00XY"
        # missing paths get the handler's own 550 text, not a generic
        # exception-name fallback
        import pytest as _pytest
        with _pytest.raises(ftplib.error_perm, match="550 no such directory"):
            ftp.cwd("/nope")
        with _pytest.raises(ftplib.error_perm, match="550 not a file"):
            ftp.size("missing.bin")
        with _pytest.raises(ftplib.error_perm, match="550 not found"):
            ftp.sendcmd("MDTM missing.bin")
        with _pytest.raises(ftplib.error_perm, match="550 not found"):
            ftp.rename("missing.bin", "x.bin")
        ftp.quit()
    finally:
        srv.stop()
        filer.stop()
        vol.stop()
        master.stop()
