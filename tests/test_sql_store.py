"""Abstract-SQL filer store: shared engine + dialects + postgres wire.

Covers the engine over embedded sqlite (CRUD, pagination, prefix bounds,
recursive delete, kv, bucket tables), the postgres dialect through the
REAL wire client against a mini v3-protocol server (trust / cleartext /
md5 / SCRAM-SHA-256 auth), mysql dialect SQL shapes, and a randomized
differential vs MemoryStore.  Ref: weed/filer/abstract_sql/
abstract_sql_store.go, weed/filer/postgres/postgres_store.go.
"""

from __future__ import annotations

import numpy as np
import pytest

from seaweedfs_tpu.filer.entry import Attr, Entry, FileChunk
from seaweedfs_tpu.filer.filer import Filer
from seaweedfs_tpu.filer.filer_store import MemoryStore
from seaweedfs_tpu.filer.pg_client import PgConn, PgError
from seaweedfs_tpu.filer.sql_store import (
    AbstractSqlStore,
    MysqlDialect,
    PostgresDialect,
    hash_string_to_long,
    sqlite_sql_store,
)

from .minipg import MiniPg

RNG = np.random.default_rng(0x50C7)


def _file(path: str, n: int = 1) -> Entry:
    chunks = [FileChunk(file_id=f"3,{i:02x}", offset=i * 10, size=10)
              for i in range(n)]
    return Entry(full_path=path, attr=Attr(mode=0o660), chunks=chunks)


@pytest.fixture(params=["sqlite", "postgres"])
def store(request, tmp_path):
    if request.param == "sqlite":
        s = sqlite_sql_store(str(tmp_path / "meta.db"))
        yield s
        s.close()
    else:
        server = MiniPg()
        s = AbstractSqlStore(PgConn("127.0.0.1", server.port), "postgres")
        yield s
        s.close()
        server.stop()


def test_dirhash_stable():
    assert hash_string_to_long("/a/b") == hash_string_to_long("/a/b")
    assert hash_string_to_long("/a/b") != hash_string_to_long("/a/c")


def test_crud_listing_pagination(store):
    for name in ("a.txt", "b.txt", "c.txt"):
        store.insert_entry(_file(f"/d/{name}", n=2))
    got = store.find_entry("/d/b.txt")
    assert got is not None and len(got.chunks) == 2
    assert got.full_path == "/d/b.txt"
    assert store.find_entry("/d/zz") is None

    names = [e.full_path for e in store.list_directory_entries("/d")]
    assert names == ["/d/a.txt", "/d/b.txt", "/d/c.txt"]
    assert [e.full_path for e in store.list_directory_entries(
        "/d", start_file="a.txt", limit=2)] == ["/d/b.txt", "/d/c.txt"]
    assert [e.full_path for e in store.list_directory_entries(
        "/d", start_file="b.txt", include_start=True, limit=1)] == [
        "/d/b.txt"]

    # upsert: same path replaces
    store.insert_entry(_file("/d/b.txt", n=5))
    assert len(store.find_entry("/d/b.txt").chunks) == 5

    store.delete_entry("/d/b.txt")
    assert store.find_entry("/d/b.txt") is None
    assert [e.full_path for e in store.list_directory_entries("/d")] == [
        "/d/a.txt", "/d/c.txt"]


def test_prefix_listing_and_escape(store):
    for name in ("apple", "apricot", "banana", "a_b", "axb"):
        store.insert_entry(_file(f"/fruit/{name}"))
    assert [e.name for e in store.list_directory_entries(
        "/fruit", prefix="ap")] == ["apple", "apricot"]
    # LIKE metacharacters in the prefix must be literal
    assert [e.name for e in store.list_directory_entries(
        "/fruit", prefix="a_")] == ["a_b"]
    assert [e.name for e in store.list_directory_entries(
        "/fruit", prefix="z")] == []


def test_delete_folder_children_recursive(store):
    for p in ("/top/f1", "/top/sub/f2", "/top/sub/deep/f3", "/other/f4"):
        store.insert_entry(_file(p))
    store.delete_folder_children("/top")
    assert store.find_entry("/top/f1") is None
    assert store.find_entry("/top/sub/f2") is None
    assert store.find_entry("/top/sub/deep/f3") is None
    assert store.find_entry("/other/f4") is not None


def test_kv_roundtrip_and_scan(store):
    store.kv_put(b"k1", b"\x00\xffbinary")
    store.kv_put(b"k2", b"v2")
    store.kv_put(b"other", b"v3")
    assert store.kv_get(b"k1") == b"\x00\xffbinary"
    assert store.kv_get(b"missing") is None
    assert [(k, v) for k, v in store.kv_scan(b"k")] == [
        (b"k1", b"\x00\xffbinary"), (b"k2", b"v2")]
    store.kv_delete(b"k1")
    assert store.kv_get(b"k1") is None


def test_bucket_tables(tmp_path):
    s = sqlite_sql_store(str(tmp_path / "m.db"), bucket_tables=True)
    s.insert_entry(_file("/buckets/photos/2024/img.jpg"))
    s.insert_entry(_file("/plain/file.txt"))
    got = s.find_entry("/buckets/photos/2024/img.jpg")
    assert got is not None and got.full_path == "/buckets/photos/2024/img.jpg"
    assert [e.full_path for e in s.list_directory_entries(
        "/buckets/photos/2024")] == ["/buckets/photos/2024/img.jpg"]

    # reads of a NEVER-written bucket are side-effect-free misses: no
    # table is created by probing random bucket names
    assert s.find_entry("/buckets/nonexistent/x") is None
    assert list(s.list_directory_entries("/buckets/nonexistent")) == []
    s.delete_entry("/buckets/nonexistent/x")  # no error either
    assert not any(t.startswith("bucket_nonexistent") for t in s._tables)

    # deleting the bucket root IS the table drop (CanDropWholeBucket):
    # O(1), cannot touch other data, leaves no orphan table
    s.delete_folder_children("/buckets/photos")
    assert s.find_entry("/buckets/photos/2024/img.jpg") is None
    assert s.find_entry("/plain/file.txt") is not None
    assert not any(t.startswith("bucket_photos") for t in s._tables)
    # bucket can be recreated after the drop
    s.insert_entry(_file("/buckets/photos/new.jpg"))
    assert s.find_entry("/buckets/photos/new.jpg") is not None
    s.close()


@pytest.mark.parametrize("auth", ["cleartext", "md5", "scram"])
def test_pg_auth_methods(auth):
    server = MiniPg(password="sekrit", auth=auth)
    try:
        conn = PgConn("127.0.0.1", server.port, password="sekrit")
        assert conn.execute("SELECT 1 + 1") == [("2",)]
        conn.close()
        with pytest.raises((PgError, ConnectionError)):
            PgConn("127.0.0.1", server.port, password="wrong")
    finally:
        server.stop()


def test_pg_parameters_no_escaping_needed():
    """Adversarial values ride the extended protocol untouched."""
    server = MiniPg()
    try:
        store = AbstractSqlStore(PgConn("127.0.0.1", server.port),
                                 "postgres")
        evil = "/d/it's%_\\a\"b;DROP TABLE filemeta;--"
        store.insert_entry(_file(evil))
        assert store.find_entry(evil) is not None
        assert [e.full_path for e in
                store.list_directory_entries("/d")] == [evil]
        store.close()
    finally:
        server.stop()


def test_mysql_dialect_sql_shapes():
    d = MysqlDialect()
    assert "ON DUPLICATE KEY UPDATE" in d.upsert("filemeta")
    assert d.upsert("filemeta").count("%s") == 4
    assert "LIMIT %s" in d.list("filemeta", inclusive=False)
    assert "name > %s" in d.list("filemeta", inclusive=False)
    assert "name >= %s" in d.list("filemeta", inclusive=True)
    p = PostgresDialect()
    assert "$1" in p.find("filemeta") and "$2" in p.find("filemeta")
    assert "ON CONFLICT" in p.upsert("filemeta")


def test_differential_vs_memory_store(store):
    mem = MemoryStore()
    rng = np.random.default_rng(11)
    dirs = ["/r", "/r/a", "/r/b"]
    names = [f"f{i:02d}" for i in range(20)]
    for _ in range(300):
        op = rng.integers(0, 4)
        path = f"{dirs[rng.integers(0, 3)]}/{names[rng.integers(0, 20)]}"
        if op == 0:
            e = _file(path, n=int(rng.integers(1, 4)))
            store.insert_entry(e)
            mem.insert_entry(e)
        elif op == 1:
            store.delete_entry(path)
            mem.delete_entry(path)
        elif op == 2:
            a = store.find_entry(path)
            b = mem.find_entry(path)
            assert (a is None) == (b is None)
            if a is not None:
                assert len(a.chunks) == len(b.chunks)
        else:
            d = dirs[rng.integers(0, 3)]
            got = [e.full_path for e in store.list_directory_entries(d)]
            want = [e.full_path for e in mem.list_directory_entries(d)]
            assert got == want


def test_filer_on_sql_store(tmp_path):
    f = Filer(sqlite_sql_store(str(tmp_path / "f.db")))
    f.create_entry(_file("/docs/readme.md"))
    assert f.find_entry("/docs/readme.md") is not None
    assert [e.name for e in f.list_directory("/docs")] == ["readme.md"]


def test_pg_reconnects_after_connection_drop():
    """A dropped TCP connection must not brick the shared PgConn: the
    next statement reconnects and retries (store statements are all
    idempotent)."""
    server = MiniPg()
    try:
        conn = PgConn("127.0.0.1", server.port)
        conn.executescript("CREATE TABLE t (a TEXT PRIMARY KEY)")
        conn.execute("INSERT INTO t VALUES ($1)", ("x",))
        conn._sock.close()  # simulate server restart / idle timeout
        assert conn.execute("SELECT a FROM t") == [("x",)]
        conn.close()
        # execute after close() reconnects cleanly too
        assert conn.execute("SELECT a FROM t") == [("x",)]
    finally:
        server.stop()


def test_bucket_name_mangling_is_injective(tmp_path):
    """'my-bucket', 'my.bucket' and 'my_bucket' must not share a table:
    deleting one must not touch the others (review repro)."""
    s = sqlite_sql_store(str(tmp_path / "m.db"), bucket_tables=True)
    for b in ("my-bucket", "my.bucket", "my_bucket"):
        s.insert_entry(_file(f"/buckets/{b}/obj"))
    s.delete_folder_children("/buckets/my.bucket")
    assert s.find_entry("/buckets/my.bucket/obj") is None
    assert s.find_entry("/buckets/my-bucket/obj") is not None
    assert s.find_entry("/buckets/my_bucket/obj") is not None
    s.close()


def test_kv_scan_ff_run_keys(store):
    """Keys whose suffix is a long 0xff run must appear in prefix scans
    (review repro: the old +8*0xff bound excluded them)."""
    store.kv_put(b"p" + b"\xff" * 9, b"v1")
    store.kv_put(b"p", b"v0")
    got = dict(store.kv_scan(b"p"))
    assert got == {b"p": b"v0", b"p" + b"\xff" * 9: b"v1"}


def test_mysql_dialect_valid_shapes():
    """The mysql dialect must not inherit sqlite-isms a real MySQL
    rejects: TEXT primary key in the kv table, single-backslash ESCAPE
    literal (review findings)."""
    d = MysqlDialect()
    assert "VARCHAR" in d.create_kv_table()
    assert "TEXT PRIMARY KEY" not in d.create_kv_table()
    assert "ESCAPE '\\\\'" in d.list("filemeta", False)
    assert "ESCAPE '\\\\'" in d.delete_children("filemeta")
    # sqlite/postgres keep the single-backslash form
    assert "ESCAPE '\\'" in PostgresDialect().list("filemeta", False)


def test_sqlite_conn_usable_after_close(tmp_path):
    """close() must not strand OTHER threads' cached connections: a late
    request reopens instead of failing on a closed handle."""
    import threading as _t

    s = sqlite_sql_store(str(tmp_path / "c.db"))
    s.insert_entry(_file("/d/x"))
    results = {}

    def worker(phase):
        try:
            results[phase] = s.find_entry("/d/x") is not None
        except Exception as e:  # pragma: no cover
            results[phase] = e

    t = _t.Thread(target=worker, args=("before",))
    t.start(); t.join()
    s.close()
    # same store object, fresh call after close: reopens cleanly
    assert s.find_entry("/d/x") is not None
    s.close()
