"""Hardlinks, LSM filer store, and the MetaAggregator.

Gates:
- hardlinks share one content record; chunks GC only at the last unlink
  (filerstore_hardlink.go)
- the LSM store is observably identical to MemoryStore under randomized
  ops, and survives crash (WAL replay), flush, and compaction
- a filer tails its peers' meta logs into the local subscription stream
  with signature-based echo suppression (meta_aggregator.go)
"""

from __future__ import annotations

import os
import time

import numpy as np
import pytest

from seaweedfs_tpu.filer.entry import Attr, Entry, FileChunk
from seaweedfs_tpu.filer.filer import Filer, NotFoundError
from seaweedfs_tpu.filer.filer_store import MemoryStore
from seaweedfs_tpu.filer.lsm_store import LsmStore

RNG = np.random.default_rng(0x11A)


def _file(path: str, fids: list[str]) -> Entry:
    chunks = [FileChunk(file_id=f, offset=i * 10, size=10)
              for i, f in enumerate(fids)]
    return Entry(full_path=path, attr=Attr(mode=0o660), chunks=chunks)


# --------------------------------------------------------------------------
# hardlinks
# --------------------------------------------------------------------------

def test_hardlink_shares_content_and_gc_at_last_unlink():
    deleted: list[str] = []
    f = Filer(delete_chunks_fn=deleted.extend)
    f.create_entry(_file("/a.txt", ["3,01"]))
    link = f.hardlink("/a.txt", "/b.txt")
    assert link.hard_link_counter == 2
    # both resolve to the same chunks
    assert [c.file_id for c in f.find_entry("/b.txt").chunks] == ["3,01"]
    assert [c.file_id for c in f.find_entry("/a.txt").chunks] == ["3,01"]
    # first unlink: no GC
    f.delete_entry("/a.txt")
    f.flush_gc()
    assert deleted == []
    with pytest.raises(NotFoundError):
        f.find_entry("/a.txt")
    assert f.find_entry("/b.txt").chunks[0].file_id == "3,01"
    # last unlink: chunks reclaimed
    f.delete_entry("/b.txt")
    f.flush_gc()
    assert deleted == ["3,01"]
    f.close()


def test_hardlink_three_links_and_update_propagates():
    f = Filer()
    f.create_entry(_file("/x", ["5,aa"]))
    f.hardlink("/x", "/y")
    z = f.hardlink("/x", "/d/z")
    assert z.hard_link_counter == 3
    # updating content through one path is visible through the others
    e = f.find_entry("/y")
    e.chunks = [FileChunk(file_id="5,bb", offset=0, size=4)]
    f.update_entry(e)
    assert [c.file_id for c in f.find_entry("/d/z").chunks] == ["5,bb"]
    assert [c.file_id for c in f.find_entry("/x").chunks] == ["5,bb"]
    f.close()


def test_hardlink_rename_keeps_counter():
    deleted: list[str] = []
    f = Filer(delete_chunks_fn=deleted.extend)
    f.create_entry(_file("/p", ["7,cc"]))
    f.hardlink("/p", "/q")
    f.rename("/q", "/q2")
    f.delete_entry("/p")
    f.flush_gc()
    assert deleted == []  # /q2 still holds the content
    assert [c.file_id for c in f.find_entry("/q2").chunks] == ["7,cc"]
    f.delete_entry("/q2")
    f.flush_gc()
    assert deleted == ["7,cc"]
    f.close()


def test_hardlink_rejects_directories_and_existing_targets():
    f = Filer()
    f.mkdir("/d")
    f.create_entry(_file("/f", ["1,00"]))
    with pytest.raises(Exception):
        f.hardlink("/d", "/link")
    with pytest.raises(Exception):
        f.hardlink("/f", "/d")  # target exists
    f.close()


# --------------------------------------------------------------------------
# LSM store
# --------------------------------------------------------------------------

def _random_paths(n):
    dirs = ["/", "/a", "/a/b", "/c"]
    out = []
    for i in range(int(n)):
        d = dirs[int(RNG.integers(0, len(dirs)))]
        name = f"f{int(RNG.integers(0, 40)):02d}"
        out.append((d.rstrip("/") or "") + "/" + name)
    return out


def test_lsm_matches_memory_randomized(tmp_path):
    lsm = LsmStore(str(tmp_path / "lsm"), memtable_limit=32,
                   compact_trigger=3)
    mem = MemoryStore()
    for i, p in enumerate(_random_paths(500)):
        if RNG.random() < 0.2:
            lsm.delete_entry(p)
            mem.delete_entry(p)
        else:
            e = _file(p, [f"1,{i:04x}"])
            lsm.insert_entry(e)
            mem.insert_entry(e)
    for d in ("/", "/a", "/a/b", "/c"):
        got = [e.full_path for e in lsm.list_directory_entries(d, limit=100)]
        want = [e.full_path for e in mem.list_directory_entries(d, limit=100)]
        assert got == want, d
    # point lookups agree
    for p in _random_paths(100):
        a, b = lsm.find_entry(p), mem.find_entry(p)
        assert (a is None) == (b is None)
        if a:
            assert a.to_dict() == b.to_dict()
    lsm.close()


def test_lsm_wal_crash_recovery(tmp_path):
    d = str(tmp_path / "lsm")
    lsm = LsmStore(d, memtable_limit=1000)  # nothing flushes
    lsm.insert_entry(_file("/crash/a", ["2,01"]))
    lsm.kv_put(b"k1", b"v1")
    lsm.delete_entry("/crash/a")
    lsm._wal.flush()  # simulate crash: no close(), no flush_memtable
    lsm2 = LsmStore(d)
    assert lsm2.find_entry("/crash/a") is None
    assert lsm2.kv_get(b"k1") == b"v1"
    # torn tail record is dropped, earlier records survive
    lsm2.kv_put(b"k2", b"v2")
    lsm2._wal.flush()
    with open(os.path.join(d, "wal.log"), "r+b") as f:
        f.truncate(os.path.getsize(os.path.join(d, "wal.log")) - 1)
    lsm3 = LsmStore(d)
    assert lsm3.kv_get(b"k1") == b"v1"
    assert lsm3.kv_get(b"k2") is None
    lsm3.close()


def test_lsm_flush_compact_and_reopen(tmp_path):
    d = str(tmp_path / "lsm")
    lsm = LsmStore(d, memtable_limit=8, compact_trigger=3)
    for i in range(100):
        lsm.insert_entry(_file(f"/m/f{i:03d}", [f"4,{i:02x}"]))
    for i in range(0, 100, 2):
        lsm.delete_entry(f"/m/f{i:03d}")
    lsm.close()
    assert any(f.endswith(".sst") for f in os.listdir(d))
    lsm2 = LsmStore(d)
    names = [e.name for e in lsm2.list_directory_entries("/m", limit=1000)]
    assert names == [f"f{i:03d}" for i in range(1, 100, 2)]
    # kv scan ordering across levels
    for i in (5, 1, 9):
        lsm2.kv_put(b"scan/%d" % i, b"%d" % i)
    assert [k for k, _ in lsm2.kv_scan(b"scan/")] == \
        [b"scan/1", b"scan/5", b"scan/9"]
    lsm2.close()


def test_lsm_backs_a_filer(tmp_path):
    f = Filer(store=LsmStore(str(tmp_path / "lsm")))
    f.create_entry(_file("/docs/readme", ["8,01"]))
    f.hardlink("/docs/readme", "/docs/copy")
    assert [e.name for e in f.list_directory("/docs")] == ["copy", "readme"]
    assert f.find_entry("/docs/copy").chunks[0].file_id == "8,01"
    f.close()


# --------------------------------------------------------------------------
# MetaAggregator
# --------------------------------------------------------------------------

def test_meta_aggregator_merges_peer_events(tmp_path):
    from seaweedfs_tpu.filer.server import FilerServer
    from seaweedfs_tpu.master.server import MasterServer
    from seaweedfs_tpu.volume_server.server import VolumeServer
    from tests.conftest import free_port

    master = MasterServer(port=free_port(), volume_size_limit_mb=64,
                          pulse_seconds=0.3).start()
    vdir = tmp_path / "v"
    vdir.mkdir()
    vs = VolumeServer([str(vdir)], master.url, port=free_port(),
                      pulse_seconds=0.3).start()
    deadline = time.time() + 5
    while time.time() < deadline and not master.topo.all_nodes():
        time.sleep(0.05)
    fa = FilerServer(master.url, port=free_port(),
                     peer_poll_seconds=0.2).start()
    fb = FilerServer(master.url, port=free_port(),
                     peers=[fa.url], peer_poll_seconds=0.2).start()
    try:
        seen: list[dict] = []
        fb.filer.subscribe(seen.append, since_ns=time.time_ns())
        # a mutation on filer A must reach a subscriber of filer B
        fa.put_file("/shared/hello.txt", b"hi from A")
        deadline = time.time() + 5
        while time.time() < deadline and not any(
                (e.get("new_entry") or {}).get("full_path")
                == "/shared/hello.txt" for e in seen):
            time.sleep(0.05)
        assert any((e.get("new_entry") or {}).get("full_path")
                   == "/shared/hello.txt" for e in seen)
        assert all(e.get("peer") == fa.url for e in seen
                   if (e.get("new_entry") or {}).get("full_path")
                   == "/shared/hello.txt")
        # filer B's own events do NOT bounce: A tails nobody, and B skips
        # events stamped with its own signature when tailing A
        before = fb.meta_aggregator.applied
        fb.put_file("/shared/from-b.txt", b"hi from B")
        time.sleep(1.0)
        assert fb.meta_aggregator.skipped_own == 0  # A carries no B events
        # cursor persisted: restart-style aggregator resumes, not replays
        cur = fb.filer.store.kv_get(b"meta.aggregator.peer/" +
                                    fa.url.encode())
        assert cur is not None and int(cur) > 0
    finally:
        fb.stop()
        fa.stop()
        vs.stop()
        master.stop()


def test_hardlink_preserves_extended_metadata():
    f = Filer()
    e = _file("/meta.bin", ["9,aa"])
    e.extended = {"x-amz-meta-owner": "carol", "xattr.user.tag": "blue"}
    f.create_entry(e)
    f.hardlink("/meta.bin", "/meta-link.bin")
    for path in ("/meta.bin", "/meta-link.bin"):
        got = f.find_entry(path)
        assert got.extended.get("x-amz-meta-owner") == "carol", path
    # updating extended through one name is visible through the other
    got = f.find_entry("/meta-link.bin")
    got.extended["x-amz-meta-owner"] = "dave"
    f.update_entry(got)
    assert f.find_entry("/meta.bin").extended["x-amz-meta-owner"] == "dave"
    f.close()
