"""In-process HBase RegionServer double speaking the native RPC framing.

Test double for a real HBase regionserver (the image has no HBase or
protobuf runtime): validates the ``HBas`` preamble + SIMPLE auth code,
parses the length-prefixed ConnectionHeader, then serves
call_id-matched Get/Mutate/Scan over the same field numbers
filer/hbase_store.py emits (utils/pb_lite both ends — the store's
docstring carries the double-only caveat).

Serves TWO regions: the well-known ``hbase:meta`` region (region rows
with info:regioninfo + info:server cells, so the client's region
discovery runs the real algorithm) and one user-table region.  Unknown
regions/tables answer a NotServingRegionException through
ResponseHeader.exception, wrong preambles drop the connection (what a
kerberized cluster does to a SIMPLE client), and stop() kills live
connections so reconnect drills see a dead server.
"""

from __future__ import annotations

import socket
import struct
import threading

from seaweedfs_tpu.utils import pb_lite as pb
from seaweedfs_tpu.utils.pb_lite import f_bytes, f_msg, f_string, f_varint

META_REGION = b"hbase:meta,,1"


def _cell(row: bytes, fam: bytes, qual: bytes, value: bytes) -> bytes:
    return (f_bytes(1, row) + f_bytes(2, fam) + f_bytes(3, qual) +
            f_varint(4, 1) + f_varint(5, 4) + f_bytes(6, value))


def _result(cells: list[bytes]) -> bytes:
    return b"".join(f_msg(1, c) for c in cells)


class MiniHBase:
    def __init__(self, table: str = "seaweedfs", require_auth: int = 0x50):
        self.table = table.encode()
        self.require_auth = require_auth
        self.region_gen = 1  # bump to simulate a region split/move
        # rows: {row: {family: {qualifier: value}}}, sorted on scan
        self.rows: dict[bytes, dict[bytes, dict[bytes, bytes]]] = {}
        self.lock = threading.Lock()
        self._scanners: dict[int, list[tuple[bytes, bytes]]] = {}
        self._next_scanner = 1
        self._conns: set[socket.socket] = set()
        self._srv = socket.socket()
        self._srv.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._srv.bind(("127.0.0.1", 0))
        self._srv.listen(16)
        self.port = self._srv.getsockname()[1]
        self._stop = False
        threading.Thread(target=self._accept, daemon=True).start()

    @property
    def region(self) -> bytes:
        gen = b"%031d" % self.region_gen
        return self.table + b",," + b"%d" % self.region_gen + b"." + gen + b"a."

    def split_region(self) -> None:
        """Region split/move drill: the served region gets a NEW encoded
        name; requests naming the old one answer
        NotServingRegionException and hbase:meta serves the new name —
        exactly what a client sees when a region splits mid-workload."""
        self.region_gen += 1

    def stop(self) -> None:
        self._stop = True
        for s in [self._srv] + list(self._conns):
            try:
                s.shutdown(socket.SHUT_RDWR)
            except OSError:
                pass
            try:
                s.close()
            except OSError:
                pass

    # -- server loop ---------------------------------------------------------
    def _accept(self) -> None:
        while not self._stop:
            try:
                conn, _ = self._srv.accept()
            except OSError:
                return
            threading.Thread(target=self._serve, args=(conn,),
                             daemon=True).start()

    def _serve(self, conn: socket.socket) -> None:
        self._conns.add(conn)

        def read_exact(n: int) -> bytes:
            chunks = []
            while n:
                piece = conn.recv(min(n, 1 << 16))
                if not piece:
                    raise ConnectionError
                chunks.append(piece)
                n -= len(piece)
            return b"".join(chunks)

        try:
            preamble = read_exact(6)
            if preamble[:4] != b"HBas" or preamble[5] != self.require_auth:
                return  # kerberized cluster: SIMPLE clients get dropped
            (hlen,) = struct.unpack(">I", read_exact(4))
            hdr = pb.decode(read_exact(hlen))
            if pb.first(hdr, 2, b"") != b"ClientService":
                return
            while True:
                (total,) = struct.unpack(">I", read_exact(4))
                body = read_exact(total)
                req_hdr, i = pb.read_delimited(body, 0)
                param, _ = pb.read_delimited(body, i)
                hf = pb.decode(req_hdr)
                call_id = pb.first(hf, 1, 0)
                method = pb.first(hf, 3, b"").decode()
                try:
                    resp = self._dispatch(method, pb.decode(param))
                    out = pb.delimited(f_varint(1, call_id)) + \
                        pb.delimited(resp)
                except _Exc as e:
                    exc = f_string(1, e.class_name) + f_string(2, str(e))
                    out = pb.delimited(f_varint(1, call_id) + f_msg(2, exc))
                conn.sendall(struct.pack(">I", len(out)) + out)
        except (ConnectionError, OSError, struct.error):
            pass
        finally:
            self._conns.discard(conn)
            conn.close()

    # -- dispatch ------------------------------------------------------------
    def _check_region(self, param: dict) -> bytes:
        spec = pb.first(param, 1)
        if spec is None:
            return b""
        name = pb.first(pb.decode(spec), 2, b"")
        if name not in (self.region, META_REGION):
            raise _Exc("org.apache.hadoop.hbase.NotServingRegionException",
                       name.decode(errors="replace"))
        return name

    def _dispatch(self, method: str, param: dict) -> bytes:
        if method == "Get":
            region = self._check_region(param)
            get = pb.decode(pb.first(param, 2, b""))
            row = pb.first(get, 1, b"")
            fams = [pb.first(pb.decode(c), 1, b"")
                    for c in get.get(2, [])]
            with self.lock:
                cells = []
                for fam, quals in self.rows.get(row, {}).items():
                    if fams and fam not in fams:
                        continue
                    for qual, val in quals.items():
                        cells.append(_cell(row, fam, qual, val))
            return f_msg(1, _result(cells)) if cells else b""
        if method == "Mutate":
            self._check_region(param)
            mut = pb.decode(pb.first(param, 2, b""))
            row = pb.first(mut, 1, b"")
            mtype = pb.first(mut, 2, 2)
            with self.lock:
                for cv in mut.get(3, []):
                    cvf = pb.decode(cv)
                    fam = pb.first(cvf, 1, b"")
                    for qv in cvf.get(2, []):
                        qvf = pb.decode(qv)
                        qual = pb.first(qvf, 1, b"")
                        if mtype == 3:  # DELETE
                            fammap = self.rows.get(row, {})
                            fammap.get(fam, {}).pop(qual, None)
                            if fammap.get(fam) == {}:
                                fammap.pop(fam, None)
                            if self.rows.get(row) == {}:
                                self.rows.pop(row, None)
                        else:  # PUT
                            val = pb.first(qvf, 2, b"")
                            self.rows.setdefault(row, {}).setdefault(
                                fam, {})[qual] = val
            return f_varint(2, 1)  # MutateResponse.processed
        if method == "Scan":
            return self._scan(param)
        raise _Exc("org.apache.hadoop.hbase.DoNotRetryIOException",
                   f"unknown method {method}")

    def _scan(self, param: dict) -> bytes:
        scanner_id = pb.first(param, 3)
        batch = pb.first(param, 4, 64)
        if pb.first(param, 5, 0):  # close_scanner
            if scanner_id is not None:
                self._scanners.pop(scanner_id, None)
            return b""
        if scanner_id is not None and scanner_id not in self._scanners:
            # real HBase faults a continuation for a scanner it does not
            # know (e.g. it restarted) — silently returning an empty
            # page would hide truncated scans from clients
            raise _Exc("org.apache.hadoop.hbase.UnknownScannerException",
                       str(scanner_id))
        if scanner_id is None:  # open: build the full result list
            region = self._check_region(param)
            scan = pb.decode(pb.first(param, 2, b""))
            start = pb.first(scan, 3, b"")
            fams = [pb.first(pb.decode(c), 1, b"")
                    for c in scan.get(1, [])]
            pending: list[tuple[bytes, bytes, bytes, bytes]] = []
            if region == META_REGION:
                pending = self._meta_rows(start)
            else:
                with self.lock:
                    for row in sorted(self.rows):
                        if row < start:
                            continue
                        for fam, quals in sorted(
                                self.rows[row].items()):
                            if fams and fam not in fams:
                                continue
                            for qual, val in sorted(quals.items()):
                                pending.append((row, fam, qual, val))
            scanner_id = self._next_scanner
            self._next_scanner += 1
            self._scanners[scanner_id] = pending
        pending = self._scanners.get(scanner_id, [])
        page, rest = pending[:batch], pending[batch:]
        self._scanners[scanner_id] = rest
        # real HBase groups a row's cells into ONE Result
        grouped: list[list] = []
        for c in page:
            if grouped and grouped[-1][0][0] == c[0]:
                grouped[-1].append(c)
            else:
                grouped.append([c])
        results = b"".join(
            f_msg(5, _result([_cell(*c) for c in cells]))
            for cells in grouped)
        more = 1 if rest else 0
        if not more:
            self._scanners.pop(scanner_id, None)
        return (f_varint(2, scanner_id) + f_varint(3, more) + results)

    def _meta_rows(self, start: bytes):
        """hbase:meta content: one region row for the user table, with
        info:regioninfo (RegionInfo proto) + info:server cells."""
        # RegionInfo{region_id=1, table_name{namespace=1,qualifier=2}=2}
        ri = (f_varint(1, 1) +
              f_msg(2, f_bytes(1, b"default") + f_bytes(2, self.table)))
        row = self.region
        rows = [(row, b"info", b"regioninfo", ri),
                (row, b"info", b"server",
                 f"127.0.0.1:{self.port}".encode())]
        return [c for c in rows if c[0] >= start]


class _Exc(Exception):
    def __init__(self, class_name: str, detail: str = ""):
        super().__init__(detail)
        self.class_name = class_name
