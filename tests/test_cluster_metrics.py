"""Cluster telemetry aggregation: scrape, parse, merge, staleness.

stats/aggregate.py + the master's /cluster/metrics and /cluster/health:
the master scrapes every heartbeat-registered volume server's /metrics,
merges the expositions (counters/gauges summed, histograms merged
bucket-by-bucket), and serves the rollup — with unreachable peers
marked stale (last-good values kept) rather than erroring.
"""

from __future__ import annotations

import json
import time

import pytest

from seaweedfs_tpu.master.server import MasterServer
from seaweedfs_tpu.stats import (ClusterAggregator, ec_pipeline_metrics,
                                 merge_families, parse_prometheus_text)
from seaweedfs_tpu.stats.metrics import Registry
from seaweedfs_tpu.utils.httpd import http_bytes, http_json
from seaweedfs_tpu.volume_server.server import VolumeServer

from tests.conftest import free_port


# --- parser / merge units ----------------------------------------------------

def _sample_registry() -> Registry:
    reg = Registry()
    c = reg.counter("t_requests_total", "reqs", labels=("type",))
    c.inc("GET", amount=5)
    c.inc("PUT", amount=2)
    g = reg.gauge("t_volumes", "vols", labels=("collection",))
    g.set("", 3)
    h = reg.histogram("t_latency_seconds", "lat", labels=("op",))
    for v in (0.0002, 0.002, 0.02, 0.2, 2.0, 20.0):
        h.observe("read", v)
    return reg


class TestPrometheusParsing:
    def test_round_trip_preserves_every_family(self):
        reg = _sample_registry()
        fams = parse_prometheus_text(reg.expose())
        assert fams["t_requests_total"].value("GET") == 5
        assert fams["t_requests_total"].value("PUT") == 2
        assert fams["t_volumes"].value("") == 3
        h = fams["t_latency_seconds"]
        assert h._totals[("read",)] == 6
        assert abs(h._sums[("read",)] - 22.2222) < 1e-6
        # re-exposing the parsed family reproduces the original text
        orig = "\n".join(
            line for line in reg.expose().splitlines()
            if line.startswith("t_latency_seconds"))
        back = "\n".join(
            line for line in h.expose() if not line.startswith("#"))
        assert back == orig

    def test_label_escaping_survives(self):
        reg = Registry()
        c = reg.counter("t_esc_total", "", labels=("path",))
        weird = 'a"b\\c\nd'
        c.inc(weird, amount=7)
        fams = parse_prometheus_text(reg.expose())
        assert fams["t_esc_total"].value(weird) == 7

    def test_merge_families_sums_across_peers(self):
        a = parse_prometheus_text(_sample_registry().expose())
        b = parse_prometheus_text(_sample_registry().expose())
        merged: dict = {}
        merge_families(merged, a)
        merge_families(merged, b)
        assert merged["t_requests_total"].value("GET") == 10
        assert merged["t_volumes"].value("") == 6
        h = merged["t_latency_seconds"]
        assert h._totals[("read",)] == 12
        # merging never mutated the per-peer caches
        assert a["t_requests_total"].value("GET") == 5

    def test_untyped_samples_default_to_gauge(self):
        fams = parse_prometheus_text("some_metric 4.5\n")
        assert fams["some_metric"].value() == 4.5


class TestAggregatorUnit:
    def test_stale_peer_keeps_last_values(self):
        texts = {"a:1": _sample_registry().expose(),
                 "b:2": _sample_registry().expose()}

        def fetch(url):
            if url not in texts:
                raise ConnectionError("down")
            return texts[url]

        agg = ClusterAggregator(lambda: ["a:1", "b:2"], fetch=fetch,
                                min_interval=0.0)
        agg.scrape(force=True)
        assert 't_requests_total{type="GET"} 10' in agg.expose()
        del texts["b:2"]  # peer dies
        agg.scrape(force=True)
        out = agg.expose()
        # marked stale, NOT dropped and NOT an error: counters hold
        assert 'SeaweedFS_cluster_peer_up{peer="b:2"} 0' in out
        assert 'SeaweedFS_cluster_peer_stale{peer="b:2"} 1' in out
        assert 'SeaweedFS_cluster_peer_up{peer="a:1"} 1' in out
        assert 't_requests_total{type="GET"} 10' in out
        assert agg.health()["stale_peers"] == ["b:2"]

    def test_never_scraped_dead_peer(self):
        agg = ClusterAggregator(
            lambda: ["x:1"],
            fetch=lambda u: (_ for _ in ()).throw(ConnectionError("no")),
            min_interval=0.0)
        agg.scrape(force=True)
        st = agg.peer_status()["x:1"]
        assert st["up"] is False and st["stale"] is True
        assert st["has_data"] is False
        assert agg.health()["peers"]["x:1"]["pipeline_health"] == {
            "worker_restarts": 0, "engine_fallbacks": 0,
            "degraded_binds": 0, "corrupt_shards": 0, "scrub_repairs": 0,
            "ec_under_replicated": 0, "coordinator_repair_failures": 0,
            "requests_shed": 0, "deadline_exceeded": 0,
            "retry_budget_exhausted": 0, "reqlog_records_dropped": 0,
            "dataplane_conn_aborts": 0, "loop_lag": 0,
            "autoscale_failures": 0}

    def test_unregistered_peer_drops_out(self):
        peers = ["a:1", "b:2"]
        agg = ClusterAggregator(lambda: list(peers),
                                fetch=lambda u: "m_total 1\n",
                                min_interval=0.0)
        agg.scrape(force=True)
        assert len(agg.peer_status()) == 2
        peers.remove("b:2")  # left the topology: gone, not stale
        agg.scrape(force=True)
        assert list(agg.peer_status()) == ["a:1"]

    def test_min_interval_rate_limits(self):
        calls = []
        agg = ClusterAggregator(lambda: ["a:1"],
                                fetch=lambda u: calls.append(u) or "x 1\n",
                                min_interval=60.0)
        agg.scrape()
        agg.scrape()
        agg.scrape()
        assert len(calls) == 1


# --- live master + volume servers -------------------------------------------

@pytest.fixture
def cluster():
    # long pulse so a stopped server stays REGISTERED (stale) instead of
    # being janitor-unregistered mid-test
    master = MasterServer(port=free_port(), pulse_seconds=5.0).start()
    master.aggregator.min_interval = 0.0  # every GET rescapes
    servers = []
    for i in range(2):
        servers.append(VolumeServer(
            [], master.url, port=free_port(), pulse_seconds=5.0).start())
    deadline = time.time() + 5
    while time.time() < deadline and len(master.topo.all_nodes()) < 2:
        time.sleep(0.05)
    assert len(master.topo.all_nodes()) == 2
    yield master, servers
    for vs in servers:
        vs.stop()
    master.stop()


class TestClusterEndpoints:
    def test_cluster_metrics_merges_and_marks_stale(self, cluster):
        master, servers = cluster
        m = ec_pipeline_metrics()
        m.worker_restarts.inc("staged", amount=3)
        # in-process servers share one REGISTRY, so each peer's scrape
        # reports the same process-wide total: the merged cluster value
        # must be exactly peers x local — the cross-peer SUM contract
        local = sum(m.worker_restarts.snapshot().values())
        status, body, _ = http_bytes(
            "GET", f"http://{master.url}/cluster/metrics")
        assert status == 200
        text = body.decode()
        fams = parse_prometheus_text(text)
        merged = sum(
            fams["SeaweedFS_ec_worker_restarts_total"].snapshot().values())
        assert merged == 2 * local
        for vs in servers:
            assert f'SeaweedFS_cluster_peer_up{{peer="{vs.url}"}} 1' \
                in text
        # request histograms merged bucket-by-bucket, still well-formed
        assert "SeaweedFS_volumeServer_request_seconds_bucket" in text

        # kill one peer: merged text still serves, peer marked stale
        dead = servers[1]
        dead.stop()
        status, body, _ = http_bytes(
            "GET", f"http://{master.url}/cluster/metrics")
        assert status == 200
        text = body.decode()
        assert f'SeaweedFS_cluster_peer_up{{peer="{dead.url}"}} 0' in text
        assert f'SeaweedFS_cluster_peer_stale{{peer="{dead.url}"}} 1' \
            in text
        # stale peer's last-good series still counted, not dipped
        fams = parse_prometheus_text(text)
        merged = sum(
            fams["SeaweedFS_ec_worker_restarts_total"].snapshot().values())
        assert merged >= 2 * local

    def test_cluster_health_json_and_shell(self, cluster):
        master, servers = cluster
        doc = http_json("GET", f"http://{master.url}/cluster/health")
        assert doc["peer_count"] == 2
        assert set(doc["totals"]) == {"worker_restarts",
                                      "engine_fallbacks",
                                      "degraded_binds",
                                      "corrupt_shards",
                                      "scrub_repairs",
                                      "ec_under_replicated",
                                      "coordinator_repair_failures",
                                      "requests_shed",
                                      "deadline_exceeded",
                                      "retry_budget_exhausted",
                                      "reqlog_records_dropped",
                                      "dataplane_conn_aborts",
                                      "loop_lag",
                                      "autoscale_failures",
                                      "scrub_unrepairable"}
        # the scrub verdict rollup rides the same scrape (PR 6): idle
        # scrubbers report not-running with zero verdicts
        for vs in servers:
            scrub = doc["peers"][vs.url].get("scrub")
            assert scrub is not None and scrub["running"] is False
        for vs in servers:
            peer = doc["peers"][vs.url]
            assert peer["up"] is True and peer["stale"] is False
            assert "pipeline_health" in peer
        # the shell rollup command renders the same document
        from seaweedfs_tpu.shell import CommandEnv, run_command

        env = CommandEnv(master.url)
        out = run_command(env, "cluster.health")
        assert "peers: 2" in out and "worker_restarts=" in out
        parsed = json.loads(run_command(env, "cluster.health -json"))
        assert parsed["peer_count"] == 2
