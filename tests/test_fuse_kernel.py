"""Kernel-boundary FUSE mount test (gated on /dev/fuse + libfuse2).

Drives mount/fuse_bridge.py through the REAL kernel: `weed mount` runs
as a subprocess against an in-process master/volume/filer trio, and the
test then exercises the VFS — mkdir, create, write, read, stat,
rename, listings, unlink, rmdir — via plain os calls on the
mountpoint.  Skips cleanly when /dev/fuse, fusermount, or libfuse.so.2
is absent (containers without --device /dev/fuse).

This is the test round-3's review found missing: the ctypes ABI layer
(struct layouts, callback signatures, dirent filling) only breaks at
the kernel boundary — its first run found a real bug (a cached root
entry listing itself as a nameless child, which EIO'd every subsequent
root getdents).  ref: weed/mount/weedfs.go:57.
"""

from __future__ import annotations

import ctypes.util
import os
import shutil
import subprocess
import sys
import time

import numpy as np
import pytest

from .conftest import free_port

pytestmark = pytest.mark.skipif(
    not os.path.exists("/dev/fuse")
    or shutil.which("fusermount") is None
    or ctypes.util.find_library("fuse") is None,
    reason="kernel FUSE unavailable (/dev/fuse, fusermount, libfuse2)")


@pytest.fixture()
def kernel_mount(tmp_path):
    from seaweedfs_tpu.filer.server import FilerServer
    from seaweedfs_tpu.master.server import MasterServer
    from seaweedfs_tpu.volume_server.server import VolumeServer

    master = MasterServer(port=free_port(), pulse_seconds=0.3).start()
    (tmp_path / "v").mkdir()
    vol = VolumeServer([str(tmp_path / "v")], master.url, port=free_port(),
                       pulse_seconds=0.3).start()
    deadline = time.time() + 6
    while time.time() < deadline and not master.topo.all_nodes():
        time.sleep(0.05)
    filer = FilerServer(master.url, port=free_port()).start()
    mp = tmp_path / "mp"
    mp.mkdir()
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    proc = subprocess.Popen(
        [sys.executable, os.path.join(repo, "weed.py"), "mount",
         "-filer", filer.url, "-dir", str(mp)],
        env=env, stdout=subprocess.DEVNULL, stderr=subprocess.DEVNULL)
    try:
        deadline = time.time() + 15
        mounted = False
        while time.time() < deadline:
            with open("/proc/mounts") as f:
                if str(mp) in f.read():
                    mounted = True
                    break
            if proc.poll() is not None:
                break
            time.sleep(0.2)
        if not mounted:
            pytest.skip("fuse mount did not come up "
                        f"(mount rc={proc.poll()})")
        yield str(mp)
    finally:
        subprocess.run(["fusermount", "-u", str(mp)],
                       stdout=subprocess.DEVNULL,
                       stderr=subprocess.DEVNULL)
        try:
            proc.wait(timeout=10)
        except subprocess.TimeoutExpired:
            proc.kill()
        filer.stop()
        vol.stop()
        master.stop()


def test_kernel_vfs_operations(kernel_mount):
    mp = kernel_mount
    # mkdir + create + small write/read
    os.mkdir(f"{mp}/docs")
    with open(f"{mp}/docs/a.txt", "w") as f:
        f.write("hello kernel")
    with open(f"{mp}/docs/a.txt") as f:
        assert f.read() == "hello kernel"
    # multi-chunk payload through the page_writer upload pipeline
    rng = np.random.default_rng(0xF05E)
    data = rng.integers(0, 256, 3 << 20, dtype=np.uint8).tobytes()
    with open(f"{mp}/docs/big.bin", "wb") as f:
        f.write(data)
    with open(f"{mp}/docs/big.bin", "rb") as f:
        assert f.read() == data
    assert os.stat(f"{mp}/docs/big.bin").st_size == len(data)
    # ranged read through the kernel page cache boundary
    with open(f"{mp}/docs/big.bin", "rb") as f:
        f.seek(1 << 20)
        assert f.read(4096) == data[1 << 20:(1 << 20) + 4096]
    # rename + listings (root listing REPEATEDLY: a cached-root bug made
    # every getdents after the first fail with EIO)
    os.rename(f"{mp}/docs/big.bin", f"{mp}/docs/renamed.bin")
    assert sorted(os.listdir(f"{mp}/docs")) == ["a.txt", "renamed.bin"]
    for _ in range(3):
        assert os.listdir(mp) == ["docs"]
    # unlink + rmdir
    os.unlink(f"{mp}/docs/renamed.bin")
    os.unlink(f"{mp}/docs/a.txt")
    os.rmdir(f"{mp}/docs")
    assert os.listdir(mp) == []


def test_kernel_mount_survives_stat_of_missing(kernel_mount):
    mp = kernel_mount
    with pytest.raises(FileNotFoundError):
        os.stat(f"{mp}/no-such-file")
    # and the mount still works afterwards
    os.mkdir(f"{mp}/ok")
    assert os.path.isdir(f"{mp}/ok")
    os.rmdir(f"{mp}/ok")


def test_meta_cache_root_listing_excludes_root():
    """In-process regression for the kernel-found bug: a cached root
    entry must not appear in its own listing as a nameless child."""
    from seaweedfs_tpu.filer.entry import Attr, Entry
    from seaweedfs_tpu.mount.meta_cache import MetaCache

    mc = MetaCache("unused:0")
    mc.put(Entry(full_path="/", attr=Attr(mode=0o755)))
    mc.put(Entry(full_path="/child", attr=Attr(mode=0o644)))
    names = [e.name for e in mc.list_cached("/")]
    assert names == ["child"]
    assert "" not in names
