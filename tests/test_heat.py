"""Heat-telemetry plane (observability/heat.py) — tier-1.

Gates: the EWMA decay math is exact (half-life, monotone cooling,
associative merge — the property the master-side cross-peer merge
leans on), the space-saving sketch finds the Zipf head in bounded
memory, the accumulator classifies the dataplane chokepoint feeds, the
master-side journal merges per-peer snapshots / detects head-set
shifts / rate-limits its events, the journal_event alert rules page on
those events (and only on events emitted AFTER the engine existed),
the W401 drift checks catch each new inconsistency class, and a LIVE
two-volume-server cluster attributes heat to the correct peer end to
end — /debug/heat, /cluster/heat, per-volume needle-cache counters on
/metrics and their /cluster/metrics fold, and the heat shell commands.
"""

from __future__ import annotations

import random
import time
from collections import Counter

import pytest

from seaweedfs_tpu.observability import events as _events
from seaweedfs_tpu.observability.alerts import (AlertEngine, Rule,
                                                default_rules)
from seaweedfs_tpu.observability.heat import (HEAT_EVENT_TYPES,
                                              HEAT_METRIC_FAMILIES,
                                              ClusterHeatJournal,
                                              DecayedCounter,
                                              HeatAccumulator,
                                              SpaceSavingSketch,
                                              _imbalance)
from seaweedfs_tpu.scenarios import ZipfSampler

H = 10.0  # test half-life, seconds


# --- DecayedCounter properties ----------------------------------------------

class TestDecayedCounter:
    def test_half_life_is_exact(self):
        c = DecayedCounter(H)
        c.add(100.0, 0.0)
        assert c.value(H) == pytest.approx(50.0)
        assert c.value(2 * H) == pytest.approx(25.0)

    def test_cooling_is_monotone_and_reads_do_not_mutate(self):
        c = DecayedCounter(H)
        c.add(7.0, 0.0)
        vals = [c.value(t) for t in (0.0, 1.0, 5.0, 20.0, 100.0)]
        assert vals == sorted(vals, reverse=True)
        assert c.value(50.0) == c.value(50.0)  # value() is pure
        assert c.mass == 7.0 and c.ts == 0.0

    def test_constant_rate_converges_to_rate_estimate(self):
        c = DecayedCounter(H)
        # 20 events/s for 15 half-lives: mass -> r*h/ln2, rate() -> r
        t = 0.0
        while t < 15 * H:
            c.add(1.0, t)
            t += 0.05
        assert c.rate(t) == pytest.approx(20.0, rel=0.02)

    def test_merge_is_associative_and_commutative(self):
        rng = random.Random(11)

        def mk():
            c = DecayedCounter(H)
            for _ in range(5):
                c.add(rng.uniform(0.1, 9.0), rng.uniform(0.0, 30.0))
            return c

        a, b, c = mk(), mk(), mk()
        ab_c = a.merged(b).merged(c)
        a_bc = a.merged(b.merged(c))
        ba_c = b.merged(a).merged(c)
        probe = 60.0
        assert ab_c.value(probe) == pytest.approx(a_bc.value(probe))
        assert ab_c.value(probe) == pytest.approx(ba_c.value(probe))

    def test_retune_preserves_mass_at_switch_instant(self):
        c = DecayedCounter(H)
        c.add(64.0, 0.0)
        c.retune(1.0, H)  # one old half-life elapsed: mass is 32
        assert c.value(H) == pytest.approx(32.0)
        assert c.value(H + 1.0) == pytest.approx(16.0)  # new constant


# --- SpaceSavingSketch -------------------------------------------------------

class TestSpaceSavingSketch:
    def test_memory_stays_bounded(self):
        sk = SpaceSavingSketch(capacity=64, half_life=3600.0)
        for i in range(5000):
            sk.touch(f"k{i}", now=i * 1e-4)
        assert len(sk) <= 64

    def test_zipf_head_recall_against_exact_counts(self):
        rng = random.Random(0x5EED)
        z = ZipfSampler(4000, 1.2)
        sk = SpaceSavingSketch(capacity=256, half_life=3600.0)
        exact: Counter = Counter()
        for i in range(60000):
            k = z.sample(rng)
            exact[k] += 1
            sk.touch(str(k), now=i * 1e-5)
        top = {r["key"] for r in sk.top(60000 * 1e-5, k=25)}
        head = [str(k) for k, _ in exact.most_common(25)]
        recall = sum(1 for k in head if k in top) / len(head)
        assert recall >= 0.9

    def test_error_bound_is_carried_and_mass_overestimates(self):
        sk = SpaceSavingSketch(capacity=8, half_life=3600.0)
        for i in range(8):
            sk.touch(f"old{i}", now=0.0)
        sk.touch("new", now=1.0)  # evicts a resident, inherits mass
        row = next(r for r in sk.top(1.0) if r["key"] == "new")
        assert row["err"] > 0.0
        assert row["mass"] >= 1.0  # true count floor + inherited err
        assert row["mass"] <= row["err"] + 1.0 + 1e-9

    def test_hot_keys_survive_eviction_pressure(self):
        sk = SpaceSavingSketch(capacity=16, half_life=3600.0)
        for i in range(400):
            sk.touch("hot", now=i * 0.01)
            sk.touch(f"cold{i}", now=i * 0.01)
        assert sk.top(4.0, k=1)[0]["key"] == "hot"


# --- HeatAccumulator ---------------------------------------------------------

class TestHeatAccumulator:
    def test_note_http_gates_on_object_routes(self):
        acc = HeatAccumulator(server="vs", half_life=H)
        acc.note_http("GET", "/status", 200, 10)       # control plane
        acc.note_http("GET", "/metrics", 200, 10)
        assert acc.status()["noted"] == 0
        acc.note_http("GET", "/3,01abcd", 200, 4096, trace_id="t1")
        acc.note_http("GET", "/3,01abcd?readDeleted=1", 200, 64)
        acc.note_http("POST", "/3,02ffff", 201, 128)
        acc.note_http("GET", "/7,99", 500, 0)
        snap = acc.snapshot()
        v3 = snap["volumes"]["3"]
        assert v3["read_rate"] > 0 and v3["write_rate"] > 0
        assert v3["trace"] == "t1"
        assert snap["volumes"]["7"]["error_rate"] > 0
        assert snap["volumes"]["7"]["error_share"] == 1.0
        fids = {r["fid"] for r in snap["needles"]}
        assert "3,01abcd" in fids  # query string stripped

    def test_cache_callbacks_feed_hit_mass_and_sketch(self):
        acc = HeatAccumulator(server="vs", half_life=H)
        for _ in range(4):
            acc.note_cache_hit(5, 0xBEEF, 4096)
        acc.note_cache_admit(5, 0xBEEF)
        snap = acc.snapshot()
        assert snap["volumes"]["5"]["cache_hit_rate"] > 0
        assert any(r["fid"] == "5,beef" for r in snap["needles"])

    def test_native_plane_feed(self):
        acc = HeatAccumulator(server="vs", half_life=H)
        acc.note_native("R", 2, 1024, fid="2,11")
        acc.note_native("W", 2, 512)
        acc.note_native("R", 2, 0, error=True)
        doc = acc.snapshot()["volumes"]["2"]
        assert doc["read_rate"] > 0 and doc["write_rate"] > 0
        assert doc["error_rate"] > 0

    def test_set_half_life_retunes_everything(self):
        acc = HeatAccumulator(server="vs", half_life=30.0)
        acc.note_read(1, 100, fid="1,aa")
        acc.set_half_life(2.0)
        assert acc.status()["half_life_s"] == 2.0
        assert acc.snapshot()["half_life_s"] == 2.0


# --- ClusterHeatJournal ------------------------------------------------------

def _snap(server, ts, vols, needles=()):
    """Fabricated wire snapshot: vols = {vid: read_rate}."""
    return {
        "server": server, "ts": ts, "half_life_s": 2.0, "noted": 1,
        "volumes": {str(vid): {
            "read_rate": rate, "byte_rate": rate * 4096,
            "write_rate": 0.0, "cache_hit_rate": 0.0,
            "error_rate": 0.0, "error_share": 0.0, "mass": rate,
            "trace": f"trace-{server}-{vid}"} for vid, rate in
            vols.items()},
        "needles": [{"fid": f, "mass": m, "err": 0.0}
                    for f, m in needles],
    }


class TestClusterHeatJournal:
    def test_merge_sums_rates_and_attributes_holders(self):
        j = ClusterHeatJournal()
        now = time.time()
        j.ingest("vs-a", [_snap("vs-a", now, {1: 10.0, 2: 1.0})])
        j.ingest("vs-b", [_snap("vs-b", now, {1: 30.0})])
        merged = j.merged(now)
        v1 = merged["volumes"][1]
        assert v1["read_rate"] == pytest.approx(40.0)
        assert sorted(v1["servers"]) == ["vs-a", "vs-b"]
        assert merged["volumes"][2]["servers"] == ["vs-a"]

    def test_stale_peers_are_excluded(self):
        j = ClusterHeatJournal(stale_s=5.0)
        now = time.time()
        j.ingest("vs-old", [_snap("vs-old", now - 60.0, {1: 99.0})])
        j.ingest("vs-new", [_snap("vs-new", now, {2: 5.0})])
        merged = j.merged(now)
        assert 1 not in merged["volumes"]
        doc = j.to_doc()
        assert doc["peers"]["vs-old"]["stale"] is True

    def test_to_doc_ranks_fits_zipf_and_measures_imbalance(self):
        j = ClusterHeatJournal()
        now = time.time()
        needles = [(f"1,{i:02x}", 64.0 / (i + 1)) for i in range(12)]
        j.ingest("vs-a", [_snap("vs-a", now, {1: 50.0}, needles)])
        j.ingest("vs-b", [_snap("vs-b", now, {2: 10.0, 3: 10.0})])
        doc = j.to_doc(top_needles=5)
        ranked = [v["volume"] for v in doc["volumes"]]
        assert ranked[0] == 1
        assert doc["volumes"][0]["share"] == pytest.approx(50 / 70.0,
                                                           abs=0.01)
        assert 1 in doc["head"]["volumes"]
        assert doc["zipf"]["s"] > 0.5  # 1/k masses ARE Zipf s=1
        assert len(doc["zipf"]["top"]) == 5
        assert doc["zipf"]["top"][0]["fid"] == "1,00"
        # vs-a carries 50 of 70: max/mean = 50/35
        assert doc["imbalance"]["server"] == pytest.approx(50 / 35.0,
                                                           abs=0.01)

    def test_shift_detector_fires_and_rate_limits(self):
        j = ClusterHeatJournal(trail_s=0.2, min_event_interval=0.0)
        journal = _events.get_journal()
        t_start = time.time()
        # stable head on volume 1 long enough to build a trailing
        # baseline strictly older than trail_s
        j.ingest("vs-a", [_snap("vs-a", time.time(), {1: 50.0})])
        time.sleep(0.3)
        j.ingest("vs-a", [_snap("vs-a", time.time(), {1: 50.0})])
        assert not journal.query(type_="flash_crowd",
                                 since_ts=t_start)
        # the head jumps to cold volume 9 (prev share 0 -> flash).
        # Event dicts round ts to ms: back the floor off so an event
        # landing within the same millisecond still matches.
        time.sleep(0.3)
        t_shift = time.time() - 0.01
        j.ingest("vs-a", [_snap("vs-a", time.time(),
                                {1: 0.1, 9: 80.0})])
        evs = journal.query(type_="flash_crowd", since_ts=t_shift)
        assert evs, "flash_crowd must fire when a cold volume takes " \
                    "the head"
        d = evs[-1]["details"]
        assert d["volume"] == 9 and d["share"] > 0.5
        assert evs[-1]["trace"] == "trace-vs-a-9"  # exemplar rides
        assert evs[-1] in j.to_doc()["shifts"] or j.to_doc()["shifts"]
        # rate limit: the same volume cannot re-fire inside the window
        j.min_event_interval = 60.0
        n_before = len(journal.query(type_="flash_crowd",
                                     since_ts=t_shift))
        j.ingest("vs-a", [_snap("vs-a", time.time(),
                                {1: 0.1, 9: 80.0})])
        time.sleep(0.25)
        j.ingest("vs-a", [_snap("vs-a", time.time(),
                                {1: 0.1, 9: 80.0})])
        assert len(journal.query(type_="flash_crowd",
                                 since_ts=t_shift)) == n_before

    def test_imbalance_math(self):
        assert _imbalance([]) == 0.0
        assert _imbalance([0.0, 0.0]) == 0.0
        assert _imbalance([10.0, 10.0]) == 1.0
        assert _imbalance([30.0, 10.0, 20.0]) == pytest.approx(1.5)


# --- journal_event alert rules ----------------------------------------------

class TestHeatAlertRules:
    def test_default_rules_cover_every_heat_event_type(self):
        rules = {r.name: r for r in default_rules()}
        for etype in HEAT_EVENT_TYPES:
            r = rules[etype]
            assert r.kind == "journal_event"
            assert r.params["event"] == etype
            assert r.severity == _events.EVENT_TYPES[etype]

    def test_journal_event_rule_fires_and_resolves(self):
        engine = AlertEngine(
            [Rule("heat_shift", "journal_event", severity="warning",
                  keep_firing_s=0.0,
                  params={"event": "heat_shift", "window_s": 5.0})],
            source_fn=lambda: ({}, {}), min_interval=0.0)
        now = time.time()
        doc = engine.evaluate(now=now, force=True)
        assert doc["alerts"][0]["state"] == "inactive"
        # event ts rounds to ms on the wire: clear the engine's
        # _created floor by more than the rounding granularity
        time.sleep(0.005)
        _events.emit("heat_shift", volume=4, share=0.4,
                     prev_share=0.01, servers=["vs-a"],
                     trace_id="deadbeef")
        doc = engine.evaluate(now=time.time(), force=True)
        a = doc["alerts"][0]
        assert a["state"] == "firing"
        assert "volume=4" in a["detail"]
        assert a["servers"] == ["vs-a"]
        # outside the window the alert resolves
        doc = engine.evaluate(now=time.time() + 30.0, force=True)
        assert doc["alerts"][0]["state"] == "resolved"

    def test_events_before_engine_creation_never_fire(self):
        _events.emit("flash_crowd", volume=2, share=0.9, prev_share=0.0)
        time.sleep(0.005)  # clear the ms rounding on the event ts
        engine = AlertEngine(
            [Rule("flash_crowd", "journal_event", severity="error",
                  params={"event": "flash_crowd", "window_s": 3600.0})],
            source_fn=lambda: ({}, {}), min_interval=0.0)
        doc = engine.evaluate(force=True)
        assert doc["alerts"][0]["state"] == "inactive"


# --- W401 drift checks -------------------------------------------------------

class TestW401HeatChecks:
    BASE = dict(health_families={}, degrade_keys=(), event_types={},
                health_event_types={})

    def _check(self, **kw):
        from tools.weedlint.rules_health_keys import check_tables
        base = dict(self.BASE)
        base["event_types"] = {"alert_pending": "warning",
                               "alert_fired": "warning",
                               "alert_resolved": "info"}
        base.update(kw)
        return check_tables(base.pop("health_families"),
                            base.pop("degrade_keys"),
                            base.pop("rules", []),
                            base.pop("event_types"),
                            base.pop("health_event_types"), **base)

    def _rule(self, etype, severity):
        return Rule(etype, "journal_event", severity=severity,
                    params={"event": etype})

    def test_consistent_tables_pass(self):
        v = self._check(
            event_types={"alert_pending": "w", "alert_fired": "w",
                         "alert_resolved": "i", "heat_shift": "warning"},
            rules=[self._rule("heat_shift", "warning")],
            journal_event_types=("heat_shift",),
            heat_metric_families=("SeaweedFS_volume_heat",),
            registered_metrics={"SeaweedFS_volume_heat"})
        assert v == []

    def test_unregistered_event_type_is_caught(self):
        v = self._check(rules=[self._rule("heat_shift", "warning")],
                        journal_event_types=("heat_shift",))
        assert any("not registered in events.EVENT_TYPES" in m
                   for m in v)

    def test_missing_rule_is_caught(self):
        v = self._check(
            event_types={"alert_pending": "w", "alert_fired": "w",
                         "alert_resolved": "i", "heat_shift": "warning"},
            journal_event_types=("heat_shift",))
        assert any("no default journal_event alert rule" in m for m in v)

    def test_severity_disagreement_is_caught(self):
        v = self._check(
            event_types={"alert_pending": "w", "alert_fired": "w",
                         "alert_resolved": "i", "heat_shift": "warning"},
            rules=[self._rule("heat_shift", "critical")],
            journal_event_types=("heat_shift",))
        assert any("disagrees with EVENT_TYPES" in m for m in v)

    def test_undeclared_watched_type_is_caught(self):
        v = self._check(
            event_types={"alert_pending": "w", "alert_fired": "w",
                         "alert_resolved": "i", "heat_shift": "warning",
                         "other": "warning"},
            rules=[self._rule("heat_shift", "warning"),
                   self._rule("other", "warning")],
            journal_event_types=("heat_shift",))
        assert any("not a declared journal-event type" in m for m in v)

    def test_missing_metric_family_is_caught(self):
        v = self._check(heat_metric_families=("SeaweedFS_volume_heat",),
                        registered_metrics=set())
        assert any("not registered in the stats registry" in m
                   for m in v)

    def test_live_tables_are_consistent(self):
        from tools.weedlint.rules_health_keys import check_live_tables
        assert check_live_tables() == []
        assert set(HEAT_EVENT_TYPES) <= set(_events.EVENT_TYPES)
        assert len(HEAT_METRIC_FAMILIES) == 3


# --- needle-cache per-volume counters + heat hooks ---------------------------

class TestNeedleCacheHeatHooks:
    def test_per_volume_counters_and_callbacks(self):
        from seaweedfs_tpu.stats import needle_cache_metrics
        from seaweedfs_tpu.storage.needle import Needle
        from seaweedfs_tpu.volume_server.needle_cache import NeedleCache

        cache = NeedleCache(max_bytes=1 << 20, admit_after=1)
        hits, admits = [], []
        cache.on_hit = lambda vid, key, nb: hits.append((vid, key, nb))
        cache.on_admit = lambda vid, key: admits.append((vid, key))
        m = needle_cache_metrics()
        h0 = m.volume_hits.snapshot().get(("9",), 0.0)
        mi0 = m.volume_misses.snapshot().get(("9",), 0.0)
        assert cache.get(9, 1) is None          # miss
        n = Needle(cookie=1, id=1, data=b"x" * 64)
        assert cache.offer(9, 1, n)             # admitted (after=1)
        assert admits == [(9, 1)]
        got = cache.get(9, 1)                   # hit
        assert got is n and hits == [(9, 1, 64)]
        assert m.volume_hits.snapshot().get(("9",), 0.0) == h0 + 1
        assert m.volume_misses.snapshot().get(("9",), 0.0) == mi0 + 1

    def test_callback_exceptions_never_break_reads(self):
        from seaweedfs_tpu.storage.needle import Needle
        from seaweedfs_tpu.volume_server.needle_cache import NeedleCache

        cache = NeedleCache(max_bytes=1 << 20, admit_after=1)
        cache.on_hit = lambda *a: 1 / 0
        cache.on_admit = lambda *a: 1 / 0
        n = Needle(cookie=1, id=2, data=b"y")
        assert cache.offer(3, 2, n)
        assert cache.get(3, 2) is n

# --- live cluster: end-to-end attribution ------------------------------------

@pytest.fixture
def heat_cluster(tmp_path):
    from seaweedfs_tpu.master.server import MasterServer
    from seaweedfs_tpu.volume_server.server import VolumeServer
    from tests.conftest import free_port

    master = MasterServer(port=free_port(), pulse_seconds=0.3).start()
    vols = []
    for i in range(2):
        d = tmp_path / f"vs{i}"
        d.mkdir()
        vols.append(VolumeServer([str(d)], master.url, port=free_port(),
                                 pulse_seconds=0.3,
                                 heat_halflife_s=2.0).start())
    deadline = time.time() + 5
    while time.time() < deadline and len(master.topo.all_nodes()) < 2:
        time.sleep(0.05)
    yield master, vols
    for v in vols:
        v.stop()
    master.stop()


class TestLiveHeatPlane:
    def test_cluster_heat_attributes_heat_to_the_right_peer(
            self, heat_cluster):
        from seaweedfs_tpu.client.operation import WeedClient
        from seaweedfs_tpu.utils.httpd import http_bytes, http_json

        master, vols = heat_cluster
        client = WeedClient(master.url)
        fid = client.upload(b"hot-object" * 50)
        vid = int(fid.split(",")[0])
        holder = next(vs for vs in vols if vid in vs.store.volumes)
        other = next(vs for vs in vols if vs is not holder)
        for _ in range(6):
            assert client.download(fid) == b"hot-object" * 50

        # the holder's own accumulator saw the reads...
        snap = http_json("GET", f"http://{holder.url}/debug/heat")
        assert str(vid) in snap["volumes"]
        assert snap["volumes"][str(vid)]["read_rate"] > 0
        # ...and the peer that holds nothing reports no heat for it
        snap2 = http_json("GET", f"http://{other.url}/debug/heat")
        assert str(vid) not in (snap2.get("volumes") or {})

        # the shipper (1s cadence) lands it in the master's journal,
        # attributed to the CORRECT peer url
        row = None
        deadline = time.time() + 8
        while time.time() < deadline and row is None:
            doc = http_json("GET", f"http://{master.url}/cluster/heat"
                                   "?top=8")
            row = next((v for v in doc.get("volumes") or []
                        if v["volume"] == vid), None)
            if row is None:
                time.sleep(0.2)
        assert row is not None, "volume heat never reached the master"
        assert row["servers"] == [holder.url]
        assert other.url not in row["servers"]
        assert doc["peers"][holder.url]["volumes"] >= 1

        # per-volume needle-cache counters surface on the holder's
        # /metrics (admit_after=2: read 1 misses, read 2 admits,
        # reads 3+ hit) and fold into the master's /cluster/metrics
        st, body, _ = http_bytes("GET", f"http://{holder.url}/metrics")
        text = body.decode()
        assert st == 200
        assert f'SeaweedFS_needle_cache_volume_hits_total{{volume="{vid}"}}' \
            in text
        assert f'SeaweedFS_needle_cache_volume_misses_total{{volume="{vid}"}}' \
            in text
        deadline = time.time() + 8
        agg = ""
        while time.time() < deadline and \
                "SeaweedFS_needle_cache_volume_hits_total" not in agg:
            st, body, _ = http_bytes(
                "GET", f"http://{master.url}/cluster/metrics")
            agg = body.decode()
            if "SeaweedFS_needle_cache_volume_hits_total" not in agg:
                time.sleep(0.3)
        assert "SeaweedFS_needle_cache_volume_hits_total" in agg
        assert "SeaweedFS_volume_heat" in agg or True  # master-side gauge
        # the master-side heat gauges come from its own registry
        st, body, _ = http_bytes("GET", f"http://{master.url}/metrics")
        assert "SeaweedFS_volume_heat" in body.decode()

    def test_shell_heat_commands_and_live_workload_profile(
            self, heat_cluster):
        from seaweedfs_tpu.client.operation import WeedClient
        from seaweedfs_tpu.shell.commands import CommandEnv, run_command

        master, vols = heat_cluster
        env = CommandEnv(master.url)
        env.lock()
        run_command(env, "workload.record -sample 1.0")
        client = WeedClient(master.url)
        fid = client.upload(b"shell-heat" * 20)
        vid = int(fid.split(",")[0])
        for _ in range(8):
            client.download(fid)
        # wait for a heat snapshot to land so the table is non-empty
        deadline = time.time() + 8
        out = ""
        while time.time() < deadline and f"{vid}" not in out:
            out = run_command(env, "heat.volumes -top 5")
            if str(vid) not in out:
                time.sleep(0.3)
        assert str(vid) in out and "zipf_s=" in out
        top = run_command(env, "heat.top -top 5")
        assert fid in top or "no needle heat yet" in top
        prof = run_command(env, "workload.profile")
        assert "zipf_s=" in prof and "records=" in prof


# --- mini flash-crowd drill --------------------------------------------------

class TestFlashCrowdDrill:
    def test_drill_alerts_on_the_newly_hot_volume(self, tmp_path):
        from seaweedfs_tpu.scenarios import flash_crowd, run_scenario

        res = run_scenario(flash_crowd(duration_s=10.0),
                           base_dir=str(tmp_path))
        byname = {c["check"]: c for c in res["checks"]}
        heat = res.get("heat") or {}
        assert byname["alert_fired"]["ok"], res["alerts"]
        assert byname["heat_alert_within_s"]["ok"], heat
        assert byname["heat_alert_named_volume"]["ok"], heat
        assert heat["alert_latency_s"] <= 5.0
        assert heat["named_volume"]
        # the acceptance bar: the event carries an exemplar trace id
        assert heat["exemplar_trace"]
        # the named volume is one the cluster doc ranks hot NOW
        hot = [str(v["volume"]) for v in
               (heat.get("cluster") or {}).get("volumes") or []]
        assert heat["named_volume"] in hot
