"""Group-commit write worker: batching, rollback, crash consistency.

Reference behaviors: weed/storage/volume_write.go:94-305 (syncWrite vs the
asyncRequestsChan worker, 4MB/128-request batches, truncate-on-sync-failure)
+ needle/async_request.go.
"""

from __future__ import annotations

import os
import threading
import time

import pytest

from seaweedfs_tpu.storage.needle import Needle
from seaweedfs_tpu.storage.volume import CookieMismatchError, Volume
from seaweedfs_tpu.storage.volume_write import GroupCommitWorker


@pytest.fixture
def vol(tmp_path):
    v = Volume(str(tmp_path), "", 1)
    yield v
    v.close()


def test_fsync_write_roundtrip(vol):
    _, size, unchanged = vol.write_needle2(
        Needle(cookie=0x11, id=1, data=b"alpha"), fsync=True)
    # needle size = 4B DataSize + len(data) + 1B flags (needle v2/v3)
    assert size == 4 + 5 + 1 and not unchanged
    assert vol.read_needle(1).data == b"alpha"


def test_concurrent_writers_batch_into_few_fsyncs(vol):
    """Many concurrent fsync writers must share fsync barriers: with a slow
    sync, the queue backs up while a batch commits, so the next batch picks
    up many requests (startWorker accumulation, volume_write.go:246-270)."""
    real_sync = vol._dat.sync

    def slow_sync():
        time.sleep(0.02)
        real_sync()

    vol._dat.sync = slow_sync
    n_writers = 48
    errors = []

    def write(i):
        try:
            vol.write_needle2(Needle(cookie=i, id=i + 1, data=b"d%d" % i),
                              fsync=True)
        except Exception as e:  # pragma: no cover
            errors.append(e)

    threads = [threading.Thread(target=write, args=(i,))
               for i in range(n_writers)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert not errors
    w = vol._group_commit
    assert w.request_count == n_writers
    assert w.fsync_count < n_writers, "no batching happened"
    assert w.fsync_count == w.batch_count
    for i in range(n_writers):
        assert vol.read_needle(i + 1).data == b"d%d" % i


def test_delete_through_worker(vol):
    vol.write_needle2(Needle(cookie=7, id=42, data=b"gone"), fsync=True)
    assert vol.delete_needle2(Needle(cookie=7, id=42), fsync=True) == 4 + 4 + 1
    with pytest.raises(KeyError):
        vol.read_needle(42)
    # double delete returns 0 (doDeleteRequest semantics)
    assert vol.delete_needle2(Needle(cookie=7, id=42), fsync=True) == 0


def test_logical_error_fails_only_that_request(vol):
    vol.write_needle2(Needle(cookie=1, id=5, data=b"orig"), fsync=True)
    w = vol.group_commit_worker()
    good = w.submit_write(Needle(cookie=2, id=6, data=b"ok"))
    bad = w.submit_write(Needle(cookie=999, id=5, data=b"clobber"))
    good.wait(5)
    with pytest.raises(CookieMismatchError):
        bad.wait(5)
    assert vol.read_needle(5).data == b"orig"
    assert vol.read_needle(6).data == b"ok"


def test_fsync_failure_truncates_batch_and_fails_requests(vol):
    vol.write_needle2(Needle(cookie=1, id=1, data=b"keep"), fsync=True)
    dat_before = vol.data_size
    idx_before = os.path.getsize(vol.idx_path)

    real_sync = vol._dat.sync
    fail_once = {"armed": True}

    def broken_sync():
        if fail_once["armed"]:
            fail_once["armed"] = False
            raise OSError(28, "No space left on device")
        real_sync()

    vol._dat.sync = broken_sync
    w = vol.group_commit_worker()
    reqs = [w.submit_write(Needle(cookie=i, id=100 + i, data=b"x" * 64))
            for i in range(5)]
    for r in reqs:
        with pytest.raises(OSError):
            r.wait(5)
    assert w.rollback_count == 1
    # .dat and .idx truncated back to the pre-batch state
    assert vol.data_size == dat_before
    assert os.path.getsize(vol.idx_path) == idx_before
    # the in-memory map was reloaded: no trace of the failed batch
    for i in range(5):
        with pytest.raises(KeyError):
            vol.read_needle(100 + i)
    # the volume still works after the rollback
    vol.write_needle2(Needle(cookie=9, id=200, data=b"after"), fsync=True)
    assert vol.read_needle(200).data == b"after"
    assert vol.read_needle(1).data == b"keep"


def test_torn_write_crash_recovery_after_batch(tmp_path):
    """Crash mid-batch: the .dat tail is torn but the .idx recorded the
    entries — reopening must truncate back to the last healthy needle
    (CheckAndFixVolumeDataIntegrity, volume_checking.go:17)."""
    v = Volume(str(tmp_path), "", 2)
    for i in range(4):
        v.write_needle2(Needle(cookie=i, id=i + 1, data=b"data-%d" % i),
                        fsync=True)
    nv_last = v.nm.get(4)
    # simulate the crash: kill the worker without close(), tear the last
    # record's bytes off the .dat
    v._group_commit.stop()
    v._group_commit = None
    v._dat.truncate(nv_last.offset + 10)
    v._dat.close()
    v.nm.close()

    v2 = Volume(str(tmp_path), "", 2)
    try:
        for i in range(3):
            assert v2.read_needle(i + 1).data == b"data-%d" % i
        with pytest.raises(KeyError):
            v2.read_needle(4)
    finally:
        v2.close()


def test_worker_stop_drains_queue(tmp_path):
    v = Volume(str(tmp_path), "", 3)
    w = v.group_commit_worker()
    reqs = [w.submit_write(Needle(cookie=i, id=i + 1, data=b"z%d" % i))
            for i in range(20)]
    v.close()  # stop() must drain, not drop
    for r in reqs:
        r.wait(5)
    v2 = Volume(str(tmp_path), "", 3)
    try:
        for i in range(20):
            assert v2.read_needle(i + 1).data == b"z%d" % i
    finally:
        v2.close()


def test_worker_respects_batch_limits(tmp_path):
    v = Volume(str(tmp_path), "", 4)
    try:
        w = GroupCommitWorker(v, max_batch_bytes=1024, max_batch_requests=4)
        v._group_commit = w
        # block the worker with a slow first commit so the queue fills
        real_sync = v._dat.sync
        v._dat.sync = lambda: (time.sleep(0.05), real_sync())[1]
        reqs = [w.submit_write(Needle(cookie=i, id=i + 1, data=b"y" * 100))
                for i in range(16)]
        for r in reqs:
            r.wait(5)
        assert w.batch_count >= 4  # 16 requests can't fit fewer batches
    finally:
        v.close()


def test_parked_worker_falls_back_to_direct_durable_write(tmp_path):
    """While commit_compact/tiering has the worker parked, fsync writes must
    not spin up a fresh worker (whose thread would block on the held
    write_lock and stall close()'s join) — they take the direct path."""
    v = Volume(str(tmp_path), "", 5)
    try:
        v.write_needle2(Needle(cookie=1, id=1, data=b"a"), fsync=True)
        assert v._group_commit is not None
        v._park_worker()
        assert v.group_commit_worker() is None
        off, size, _ = v.write_needle2(Needle(cookie=2, id=2, data=b"bb"),
                                       fsync=True)
        assert v._group_commit is None
        assert v.read_needle(2).data == b"bb"
        assert v.delete_needle2(Needle(cookie=2, id=2), fsync=True) == size
        v._unpark_worker()
        assert v.group_commit_worker() is not None
        assert v.read_needle(1).data == b"a"
    finally:
        v.close()


def test_commit_compact_unparks_worker(tmp_path):
    v = Volume(str(tmp_path), "", 6)
    try:
        for i in range(5):
            v.write_needle(Needle(cookie=i, id=i + 1, data=b"d%d" % i))
        v.delete_needle(Needle(cookie=0, id=1))
        v.compact()
        v.commit_compact()
        assert v._worker_parked is False
        v.write_needle2(Needle(cookie=9, id=9, data=b"post"), fsync=True)
        assert v._group_commit is not None
        assert v.read_needle(9).data == b"post"
    finally:
        v.close()


def test_rollback_preserves_needle_map_kind(tmp_path):
    """A sync-failure rollback must reload the volume's CONFIGURED map
    kind (and kill any stale .ldb snapshot), not silently switch to the
    dict map."""
    from seaweedfs_tpu.storage.needle_map_compact import CheckpointedNeedleMap
    from seaweedfs_tpu.utils import faultinject as fi

    v = Volume(str(tmp_path), "", 11, needle_map_kind="ldb")
    try:
        v.write_needle2(Needle(cookie=1, id=1, data=b"ok"), fsync=True)
        fi.enable("disk.sync", error_rate=1.0, max_hits=1)
        with pytest.raises(Exception):
            v.write_needle2(Needle(cookie=2, id=2, data=b"fails"),
                            fsync=True)
        assert isinstance(v.nm, CheckpointedNeedleMap), type(v.nm)
        assert v.read_needle(1).data == b"ok"
        with pytest.raises(KeyError):
            v.read_needle(2)
    finally:
        fi.clear()
        v.close()
