"""In-process etcd v3 JSON-gateway double for EtcdStore tests.

Implements the gateway subset the store uses — POST /v3/kv/put, /range,
/deleterange with base64 keys/values, range_end interval semantics,
KEY-ascending sort, and limit — over a sorted dict.  Semantics follow
the etcd API docs; no auth, single revision counter.
"""

from __future__ import annotations

import base64
import json
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer


class MiniEtcd:
    def __init__(self):
        self.kv: dict[bytes, bytes] = {}
        self.lock = threading.Lock()
        # (status, grpc-gateway error doc) answers popped per request —
        # leader-loss (503) and compaction (400) drills
        self.fail_next: list = []
        outer = self

        class Handler(BaseHTTPRequestHandler):
            protocol_version = "HTTP/1.1"

            def log_message(self, fmt, *args):
                pass

            def do_POST(self):
                n = int(self.headers.get("Content-Length") or 0)
                body = json.loads(self.rfile.read(n) or b"{}")
                if outer.fail_next:
                    status, err = outer.fail_next.pop(0)
                    payload = json.dumps(err).encode()
                    self.send_response(status)
                    self.send_header("Content-Type", "application/json")
                    self.send_header("Content-Length", str(len(payload)))
                    self.end_headers()
                    self.wfile.write(payload)
                    return
                if self.path == "/v3/kv/put":
                    resp = outer._put(body)
                elif self.path == "/v3/kv/range":
                    resp = outer._range(body)
                elif self.path == "/v3/kv/deleterange":
                    resp = outer._deleterange(body)
                else:
                    self.send_response(404)
                    self.send_header("Content-Length", "0")
                    self.end_headers()
                    return
                payload = json.dumps(resp).encode()
                self.send_response(200)
                self.send_header("Content-Type", "application/json")
                self.send_header("Content-Length", str(len(payload)))
                self.end_headers()
                self.wfile.write(payload)

        self._srv = ThreadingHTTPServer(("127.0.0.1", 0), Handler)
        self._srv.daemon_threads = True
        self.port = self._srv.server_address[1]
        threading.Thread(target=self._srv.serve_forever, daemon=True).start()

    def stop(self) -> None:
        self._srv.shutdown()
        self._srv.server_close()

    # -- ops ----------------------------------------------------------------
    @staticmethod
    def _interval(body):
        key = base64.b64decode(body.get("key", ""))
        end_s = body.get("range_end")
        end = base64.b64decode(end_s) if end_s else None
        return key, end

    @staticmethod
    def _in_range(k: bytes, key: bytes, end) -> bool:
        if end is None:
            return k == key
        if end == b"\x00":  # "from key to end of keyspace"
            return k >= key
        return key <= k < end

    def _put(self, body):
        with self.lock:
            self.kv[base64.b64decode(body["key"])] = \
                base64.b64decode(body.get("value", ""))
        return {"header": {}}

    def _range(self, body):
        key, end = self._interval(body)
        limit = int(body.get("limit") or 0)
        with self.lock:
            ks = sorted(k for k in self.kv
                        if self._in_range(k, key, end))
        more = False
        if limit and len(ks) > limit:
            ks, more = ks[:limit], True
        kvs = [{"key": base64.b64encode(k).decode(),
                "value": base64.b64encode(self.kv[k]).decode()}
               for k in ks]
        return {"header": {}, "kvs": kvs, "more": more,
                "count": str(len(kvs))}

    def _deleterange(self, body):
        key, end = self._interval(body)
        with self.lock:
            victims = [k for k in self.kv if self._in_range(k, key, end)]
            for k in victims:
                del self.kv[k]
        return {"header": {}, "deleted": str(len(victims))}
