"""EC shard bit-rot defense drills: `.eci` sidecars, verify-on-use
rebuild/read, and the scrubber's quarantine+repair loop.

The contract under test (ec/integrity.py + encoder/ec_volume/streaming
verify paths + volume_server/scrubber.py): a bit flip in ANY single
shard — injected on disk or through the ec.shard.corrupt fault point —
is detected, demoted to an erasure, and reconstruction output stays
byte-identical to the clean CPU-codec result; with more than
parity_shards corrupt shards the operation raises ShardCorruptError
instead of emitting silent garbage; the scrubber finds rot at rest,
quarantines `.ecNN` -> `.ecNN.bad`, and repairs via rebuild while
>= data_shards clean shards remain.  All of it observable: the
SeaweedFS_ec_corrupt_shards_total / _scrub_* counters and
pipeline.retry(reason=corrupt_shard) spans.
"""

from __future__ import annotations

import os
import time

import numpy as np
import pytest

from seaweedfs_tpu.ec import encoder as ec_encoder
from seaweedfs_tpu.ec.codec import ReedSolomon
from seaweedfs_tpu.ec.ec_volume import EcVolume
from seaweedfs_tpu.ec.integrity import (EciSidecar, ShardCorruptError,
                                        SidecarBuilder, backfill_sidecar,
                                        verify_shard_file)
from seaweedfs_tpu.ec.layout import to_ext
from seaweedfs_tpu.observability import disable_tracing, enable_tracing
from seaweedfs_tpu.stats import ec_integrity_metrics
from seaweedfs_tpu.storage import idx as idx_mod
from seaweedfs_tpu.storage.needle import Needle, get_actual_size
from seaweedfs_tpu.storage.types import Version, size_is_valid
from seaweedfs_tpu.storage.volume import Volume
from seaweedfs_tpu.utils import faultinject as fi

LARGE, SMALL, CHUNK = 10_000, 100, 50  # ec_test.go shrunk geometry
BS = 512  # sidecar crc block for tests: several blocks per shard

rng = np.random.default_rng(11)


def _write_test_volume(tmp_path, vid=1, n_needles=80):
    v = Volume(str(tmp_path), "", vid)
    for i in range(1, n_needles + 1):
        v.write_needle(Needle(cookie=i, id=i,
                              data=rng.bytes(int(rng.integers(1, 800)))))
    v.close()
    return os.path.join(str(tmp_path), str(vid))


def _encode(base, rs=None):
    rs = rs or ReedSolomon(10, 4)
    ec_encoder.write_ec_files(base, rs, LARGE, SMALL, chunk=CHUNK,
                              sidecar_block_size=BS)
    ec_encoder.write_sorted_file_from_idx(base)
    return rs


def _shards(base):
    return {i: open(base + to_ext(i), "rb").read() for i in range(14)
            if os.path.exists(base + to_ext(i))}


def _flip(path, offset, bit=0):
    with open(path, "r+b") as f:
        f.seek(offset)
        c = f.read(1)
        f.seek(offset)
        f.write(bytes([c[0] ^ (1 << bit)]))


@pytest.fixture()
def tracer():
    tr = enable_tracing()
    tr.clear()
    try:
        yield tr
    finally:
        disable_tracing()
        tr.clear()


# --- sidecar format -------------------------------------------------------

def test_encode_writes_sidecar_matching_backfill(tmp_path):
    """write_ec_files builds the `.eci` incrementally as shards stream
    out; it must equal a from-scratch backfill of the finished files."""
    base = _write_test_volume(tmp_path)
    _encode(base)
    sc = EciSidecar.load(base)
    assert sc is not None and sc.present_mask == (1 << 14) - 1
    assert sc.shard_size == os.path.getsize(base + to_ext(0))
    streamed = sc.crcs.copy()
    sc2 = backfill_sidecar(base, block_size=BS)
    assert np.array_equal(streamed, sc2.crcs)
    assert sc2.shard_size == sc.shard_size
    for i in range(14):
        assert verify_shard_file(sc2, base + to_ext(i), i) == []


def test_rotted_sidecar_reads_as_absent(tmp_path):
    """A corrupt sidecar must fail its own table crc and load as None —
    never mass-demote healthy shards."""
    base = _write_test_volume(tmp_path)
    _encode(base)
    _flip(base + ".eci", 40)
    assert EciSidecar.load(base) is None
    # rebuild still works, just unverified
    want = _shards(base)
    os.remove(base + to_ext(3))
    assert ec_encoder.rebuild_ec_files(base, ReedSolomon(10, 4)) == [3]
    assert _shards(base) == want


def test_sidecar_is_stale_needs_full_disagreement():
    """Stale = EVERY local shard disagrees AND there are >= 2 of them;
    a lone disagreeing shard (single-shard holder included) is
    truncation rot, never grounds to discredit the table."""
    from seaweedfs_tpu.ec.integrity import sidecar_is_stale

    sc = EciSidecar(512, 1000, np.zeros((14, 2), dtype=np.uint32),
                    (1 << 14) - 1)
    assert sidecar_is_stale(sc, [999, 999]) is True
    assert sidecar_is_stale(sc, [1000, 999]) is False  # one truncated
    assert sidecar_is_stale(sc, [999]) is False  # single-shard holder
    assert sidecar_is_stale(sc, []) is False
    assert sidecar_is_stale(None, [999, 999]) is False


def test_sidecar_builder_rejects_unequal_streams():
    b = SidecarBuilder(3, 256)
    b.update(0, b"x" * 100)
    b.update(1, b"y" * 99)
    with pytest.raises(ValueError, match="unequal"):
        b.finalize()


# --- verify-on-use: rebuild ----------------------------------------------

@pytest.mark.parametrize("corrupt_sid", [3, 12])  # one data, one parity
def test_rebuild_demotes_corrupt_survivor(tmp_path, tracer, corrupt_sid):
    """On-disk bit rot in a survivor (data or parity): the rebuild
    detects it, demotes the shard to an erasure, retries with an
    alternate survivor set, REGENERATES the rotted shard, and every
    output is byte-identical to the clean encode."""
    base = _write_test_volume(tmp_path)
    rs = _encode(base)
    orig = _shards(base)
    m = ec_integrity_metrics()
    c0 = m.corrupt_shards.value("rebuild")
    os.remove(base + to_ext(5))
    _flip(base + to_ext(corrupt_sid), 1000, bit=4)
    generated = ec_encoder.rebuild_ec_files(base, rs)
    assert sorted(generated) == sorted({5, corrupt_sid})
    assert _shards(base) == orig  # byte-identical, corruption healed
    assert m.corrupt_shards.value("rebuild") - c0 == 1
    retries = [s for s in tracer.snapshot() if s.name == "pipeline.retry"
               and s.attrs.get("reason") == "corrupt_shard"]
    assert retries and retries[0].attrs["shard"] == corrupt_sid


def test_rebuild_too_many_corrupt_raises(tmp_path):
    """> parity_shards corrupt survivors: clean shards < data_shards, so
    the rebuild must raise ShardCorruptError — never silent garbage."""
    base = _write_test_volume(tmp_path)
    rs = _encode(base)
    os.remove(base + to_ext(13))
    for sid in (1, 2, 3, 6):
        _flip(base + to_ext(sid), 64)
    with pytest.raises(ShardCorruptError) as ei:
        ec_encoder.rebuild_ec_files(base, rs)
    assert set(ei.value.corrupt_shards) == {1, 2, 3, 6}
    # the missing shard must NOT have been produced from poisoned math
    assert not os.path.exists(base + to_ext(13))


def test_rebuild_faultpoint_bit_flip(tmp_path):
    """The ec.shard.corrupt fault point: an in-memory deterministic flip
    on the read path is detected exactly like on-media rot."""
    base = _write_test_volume(tmp_path)
    rs = _encode(base)
    orig = _shards(base)
    os.remove(base + to_ext(0))
    fi.enable("ec.shard.corrupt", params={"shard": 4, "offset": 777,
                                          "bit": 6})
    try:
        generated = ec_encoder.rebuild_ec_files(base, rs)
        assert fi.fired("ec.shard.corrupt") >= 1  # the flip really landed
    finally:
        fi.clear()
    assert sorted(generated) == [0, 4]
    assert _shards(base) == orig


# --- verify-on-use: EcVolume reads ---------------------------------------

def _live_needles(base):
    return [(k, o, s) for k, o, s in idx_mod.iter_index_file(base + ".idx")
            if o != 0 and size_is_valid(s)]


def test_read_detects_flip_and_reconstructs(tmp_path, tracer):
    """A flipped bit in the shard serving a needle: the verified read
    demotes the shard and the needle reconstructs byte-identical from
    the other 13."""
    base = _write_test_volume(tmp_path)
    rs = _encode(base)
    with open(base + ".dat", "rb") as f:
        dat = f.read()
    live = _live_needles(base)
    ev = EcVolume(base, large_block_size=LARGE, small_block_size=SMALL)
    try:
        key, offset, size = live[5]
        _, _, ivs = ev.locate_ec_shard_needle(key)
        sid, soff = ivs[0].to_shard_id_and_offset(LARGE, SMALL, 10)
        fi.enable("ec.shard.corrupt",
                  params={"shard": sid, "offset": soff, "bit": 1})
        try:
            blob = ev.read_needle(key, rs)
        finally:
            fi.clear()
        actual = get_actual_size(size, Version.V3)
        assert blob == dat[offset:offset + actual]
        assert sid in ev.corrupt_shards  # demoted for the whole mount
        # every other needle still reads correctly around the demotion
        for k2, o2, s2 in live[:25]:
            got = ev.read_needle(k2, rs)
            assert got == dat[o2:o2 + get_actual_size(s2, Version.V3)]
        retries = [s for s in tracer.snapshot()
                   if s.name == "pipeline.retry"
                   and s.attrs.get("reason") == "corrupt_shard"]
        assert retries and retries[0].attrs["source"] == "read"
    finally:
        ev.close()


def test_read_unrecoverable_corruption_raises(tmp_path):
    """With > parity_shards shards rotted on disk, reads that need them
    must raise ShardCorruptError, not return wrong bytes."""
    base = _write_test_volume(tmp_path)
    rs = _encode(base)
    with open(base + ".dat", "rb") as f:
        dat = f.read()
    for sid in (0, 1, 2, 3, 4):
        _flip(base + to_ext(sid), 128)
    ev = EcVolume(base, large_block_size=LARGE, small_block_size=SMALL)
    try:
        raised = False
        for key, o, s in _live_needles(base):
            try:
                got = ev.read_needle(key, rs)
                # any read that DID succeed must be correct bytes
                assert got == dat[o:o + get_actual_size(s, Version.V3)]
            except ShardCorruptError:
                raised = True
                break
        assert raised
    finally:
        ev.close()


def test_reconstruct_interval_skips_oserror_shard(tmp_path, monkeypatch):
    """A survivor that errors at the IO layer (bad sector) is skipped
    and an alternate local shard takes its place — the read succeeds
    instead of failing outright."""
    base = _write_test_volume(tmp_path)
    rs = _encode(base)
    with open(base + ".dat", "rb") as f:
        dat = f.read()
    os.remove(base + to_ext(6))  # force reconstruction for shard-6 reads
    ev = EcVolume(base, large_block_size=LARGE, small_block_size=SMALL)
    try:
        # shard 0 is always among the first-choice survivors: make its
        # reads die like a failing disk
        real = ev.shards[0].read_at

        def dying(length, offset):
            raise OSError(5, "Input/output error")

        monkeypatch.setattr(ev.shards[0], "read_at", dying)
        for key, o, s in _live_needles(base)[:25]:
            got = ev.read_needle(key, rs)
            assert got == dat[o:o + get_actual_size(s, Version.V3)]
    finally:
        ev.close()


# --- streaming encode/rebuild --------------------------------------------

def test_streaming_rebuild_demotes_corrupt_survivor(tmp_path):
    """The StreamingEncoder rebuild (staged and, where the native
    toolchain exists, mmap) detects survivor rot via the sidecar and
    regenerates both the missing and the rotted shard byte-identical."""
    from seaweedfs_tpu.ec.streaming import StreamingEncoder

    base = str(tmp_path / "v")
    open(base + ".dat", "wb").write(
        rng.integers(0, 256, 1_200_000, dtype=np.uint8).tobytes())
    for zero_copy in (True, False):
        out = str(tmp_path / f"o{int(zero_copy)}")
        enc = StreamingEncoder(10, 4, engine="host", zero_copy=zero_copy,
                               overlap="none", dispatch_mb=1)
        enc.encode_file(base + ".dat", out, 1_000_000, 10_000)
        assert os.path.exists(out + ".eci")
        ref = _shards(out)
        os.remove(out + to_ext(7))
        _flip(out + to_ext(2), 55_555, bit=3)
        generated = enc.rebuild_files(out)
        assert sorted(generated) == [2, 7], (zero_copy, generated)
        assert _shards(out) == ref, zero_copy
        assert enc.stats["verify_s"] >= 0.0


def test_sidecar_survives_checkpoint_resume(tmp_path, monkeypatch):
    """PR-3 staged retries resume mid-file from a checkpoint; the
    sidecar's crc accumulators are re-seeded from the surviving prefix,
    so the final `.eci` must equal a clean run's."""
    import seaweedfs_tpu.ec.streaming as streaming_mod
    from seaweedfs_tpu.ec.streaming import StreamingEncoder

    base = str(tmp_path / "v")
    open(base + ".dat", "wb").write(
        rng.integers(0, 256, 2_000_000, dtype=np.uint8).tobytes())
    real = streaming_mod.preadv_into
    calls = {"n": 0}

    def flaky(f, views, off):
        calls["n"] += 1
        if calls["n"] == 15:
            raise OSError("injected fill IO error")
        return real(f, views, off)

    monkeypatch.setattr(streaming_mod, "preadv_into", flaky)
    enc = StreamingEncoder(10, 4, engine="host", zero_copy=False,
                           overlap="none", dispatch_mb=1, depth=1)
    enc.dispatch_b = 65536
    out = str(tmp_path / "o")
    enc.encode_file(base + ".dat", out, 1_000_000, 10_000)
    assert enc.stats["retries"] == 1  # the drill actually resumed
    resumed = EciSidecar.load(out)
    assert resumed is not None
    clean = backfill_sidecar(out)  # recompute from the finished shards
    assert np.array_equal(resumed.crcs, clean.crcs)
    assert resumed.shard_size == clean.shard_size


# --- scrubber -------------------------------------------------------------

def _store_with_ec_volume(tmp_path, vid=1):
    from seaweedfs_tpu.volume_server.store import Store

    _write_test_volume(tmp_path, vid=vid, n_needles=60)
    store = Store([str(tmp_path)])
    store.ec_generate(vid)
    store.ec_mount(vid)
    return store, os.path.join(str(tmp_path), str(vid))


def test_scrubber_quarantine_and_repair_roundtrip(tmp_path, tracer):
    """End to end: rot a parity shard at rest -> one scrub pass detects
    it, quarantines `.ecNN` -> `.ecNN.bad`, rebuilds it byte-identical,
    remounts, and reports verdict + counters."""
    from seaweedfs_tpu.volume_server.scrubber import EcScrubber

    store, base = _store_with_ec_volume(tmp_path)
    try:
        orig = _shards(base)
        scrub = EcScrubber(store, rate_mb_s=0)
        st = scrub.run_pass()
        assert st["verdicts"]["1"]["status"] == "clean"
        _flip(base + to_ext(11), 2048, bit=5)
        m = ec_integrity_metrics()
        r0 = m.repairs.value("repaired")
        st = scrub.run_pass()
        verdict = st["verdicts"]["1"]
        assert verdict["status"] == "repaired"
        assert verdict["corrupt_shards"] == [11]
        assert os.path.exists(base + to_ext(11) + ".bad")
        assert open(base + to_ext(11), "rb").read() == orig[11]
        assert 11 in store.ec_volumes[1].shards  # remounted whole
        assert m.repairs.value("repaired") - r0 == 1
        spans = {s.name for s in tracer.snapshot()}
        assert {"ec.scrub.pass", "ec.scrub.volume",
                "ec.scrub.quarantine"} <= spans
    finally:
        store.close()


def test_scrubber_unrepairable_quarantines_without_garbage(tmp_path):
    """Rot in 5 shards (> parity budget): the scrubber quarantines them
    and reports unrepairable — it must NOT fabricate shards."""
    from seaweedfs_tpu.volume_server.scrubber import EcScrubber

    store, base = _store_with_ec_volume(tmp_path)
    try:
        for sid in (0, 1, 2, 3, 4):
            _flip(base + to_ext(sid), 300)
        scrub = EcScrubber(store, rate_mb_s=0)
        st = scrub.run_pass()
        verdict = st["verdicts"]["1"]
        assert verdict["status"] == "unrepairable"
        assert verdict["corrupt_shards"] == [0, 1, 2, 3, 4]
        for sid in (0, 1, 2, 3, 4):
            assert os.path.exists(base + to_ext(sid) + ".bad")
            assert not os.path.exists(base + to_ext(sid))
    finally:
        store.close()


def test_scrubber_backfills_pre_sidecar_volume(tmp_path):
    """A shard set that predates sidecars gets adopted when backfill is
    on: the pass writes the `.eci` and subsequent passes verify."""
    from seaweedfs_tpu.volume_server.scrubber import EcScrubber

    store, base = _store_with_ec_volume(tmp_path)
    try:
        os.remove(base + ".eci")
        store.ec_mount(1)  # reload without sidecar
        assert store.ec_volumes[1].sidecar is None
        scrub = EcScrubber(store, rate_mb_s=0)
        st = scrub.run_pass()
        assert st["verdicts"]["1"]["status"] == "no_sidecar"
        scrub.backfill = True
        st = scrub.run_pass()
        assert st["verdicts"]["1"]["status"] == "clean"
        assert os.path.exists(base + ".eci")
    finally:
        store.close()


def test_scrubber_cursor_resumes_mid_volume(tmp_path):
    """A stop()-preserved cursor makes the next pass resume mid-volume
    (shards below the cursor skipped), then wrap clean to (0, 0)."""
    from seaweedfs_tpu.volume_server.scrubber import EcScrubber

    store, base = _store_with_ec_volume(tmp_path)
    try:
        scrub = EcScrubber(store, rate_mb_s=0)
        full_blocks = scrub.run_pass()["verdicts"]["1"]["blocks"]
        scrub.cursor = (1, 5)  # as a stop() mid-volume would leave it
        st = scrub.run_pass()
        resumed = st["verdicts"]["1"]
        assert resumed["status"] == "clean"
        assert resumed["blocks"] < full_blocks  # shards 0-4 skipped
        assert tuple(scrub.cursor) == (0, 0)  # clean wrap
    finally:
        store.close()


def test_read_truncated_shard_demotes_not_zeros(tmp_path):
    """A truncated shard must NOT serve its lost tail as trusted zeros:
    the size mismatch demotes it and needles reconstruct byte-identical
    from the other 13 (while a sidecar stale on EVERY shard — geometry
    change — still just disables verification at mount)."""
    base = _write_test_volume(tmp_path)
    rs = _encode(base)
    with open(base + ".dat", "rb") as f:
        dat = f.read()
    live = _live_needles(base)
    ev0 = EcVolume(base, large_block_size=LARGE, small_block_size=SMALL)
    key = live[10][0]
    _, _, ivs = ev0.locate_ec_shard_needle(key)
    sid, _ = ivs[0].to_shard_id_and_offset(LARGE, SMALL, 10)
    ev0.close()
    with open(base + to_ext(sid), "r+b") as f:
        f.truncate(os.path.getsize(base + to_ext(sid)) - 600)
    ev = EcVolume(base, large_block_size=LARGE, small_block_size=SMALL)
    try:
        assert ev.sidecar is not None  # one divergent shard != stale
        for k2, o2, s2 in live[:25]:
            got = ev.read_needle(k2, rs)
            assert got == dat[o2:o2 + get_actual_size(s2, Version.V3)]
        assert sid in ev.corrupt_shards
    finally:
        ev.close()


def test_scrub_stop_mid_volume_still_quarantines(tmp_path):
    """stop() mid-scan must not drop corruption already found in the
    scanned prefix: the rot is quarantined and repaired before the pass
    returns, even though the cursor resumes mid-volume."""
    from seaweedfs_tpu.volume_server.scrubber import EcScrubber

    store, base = _store_with_ec_volume(tmp_path)
    try:
        orig = _shards(base)
        _flip(base + to_ext(0), 512)  # rot in the FIRST scanned shard
        scrub = EcScrubber(store, rate_mb_s=0)
        calls = [0]

        def stop_soon():  # busy_fn: runs before every block read
            calls[0] += 1
            if calls[0] == 30:  # well past shard 0's blocks
                scrub._stop.set()
            return False

        scrub.busy_fn = stop_soon
        scrub.run_pass()
        assert scrub.cursor[0] == 1 and scrub.cursor[1] > 0  # mid-volume
        assert os.path.exists(base + to_ext(0) + ".bad")
        assert open(base + to_ext(0), "rb").read() == orig[0]
        assert scrub.verdicts[1]["status"] == "repaired"
    finally:
        store.close()


def test_scrubber_stale_sidecar_never_quarantines(tmp_path):
    """A sidecar whose geometry disagrees with EVERY present shard is
    STALE (crash between shard rewrite and sidecar rewrite) — the
    scrubber must report it, not mass-quarantine healthy shards on its
    say-so; with backfill on it re-adopts the volume instead."""
    from seaweedfs_tpu.volume_server.scrubber import EcScrubber

    store, base = _store_with_ec_volume(tmp_path)
    try:
        sc = EciSidecar.load(base)
        # perturb shard_size without changing the block count, so the
        # doctored sidecar still passes its own load-time checks
        wrong = sc.shard_size - 1 if sc.shard_size % sc.block_size == 0 \
            else sc.shard_size + 1
        EciSidecar(sc.block_size, wrong, sc.crcs, sc.present_mask).save(base)
        store.ec_mount(1)  # reload so the stale table is the live one
        scrub = EcScrubber(store, rate_mb_s=0)
        st = scrub.run_pass()
        assert st["verdicts"]["1"]["status"] == "stale_sidecar"
        for sid in range(14):
            assert os.path.exists(base + to_ext(sid)), sid
            assert not os.path.exists(base + to_ext(sid) + ".bad"), sid
        scrub.backfill = True
        st = scrub.run_pass()
        assert st["verdicts"]["1"]["status"] == "clean"
    finally:
        store.close()


def test_scrubber_detects_truncated_shard(tmp_path):
    """Blocks past EOF of a truncated shard must scan as corrupt, not
    vacuously clean: the scrubber quarantines and regenerates the full
    shard."""
    from seaweedfs_tpu.volume_server.scrubber import EcScrubber

    store, base = _store_with_ec_volume(tmp_path)
    try:
        orig = _shards(base)
        with open(base + to_ext(12), "r+b") as f:
            f.truncate(len(orig[12]) - 700)
        st = EcScrubber(store, rate_mb_s=0).run_pass()
        verdict = st["verdicts"]["1"]
        assert verdict["status"] == "repaired"
        assert verdict["corrupt_shards"] == [12]
        assert open(base + to_ext(12), "rb").read() == orig[12]
    finally:
        store.close()


def test_store_read_path_heals_corrupt_shard(tmp_path):
    """The PRODUCTION read path (Store.read_ec_needle) verifies local
    shard reads: a bit flip demotes the shard for the mount and every
    needle still reads back its exact clean bytes via reconstruction."""
    store, base = _store_with_ec_volume(tmp_path)
    try:
        ev = store.ec_volumes[1]
        live = _live_needles(base)
        clean = {k: store.read_ec_needle(1, k)[0] for k, _, _ in live[:20]}
        key = live[7][0]
        _, _, ivs = ev.locate_ec_shard_needle(key)
        sid, soff = ivs[0].to_shard_id_and_offset(
            ev.large_block_size, ev.small_block_size, ev.data_shards)
        fi.enable("ec.shard.corrupt",
                  params={"shard": sid, "offset": soff, "bit": 2})
        try:
            for k, want in clean.items():
                assert store.read_ec_needle(1, k)[0] == want, k
        finally:
            fi.clear()
        assert sid in ev.corrupt_shards
    finally:
        store.close()


# --- server routes + shell + cluster health -------------------------------

def test_scrub_routes_and_cluster_health(tmp_path):
    """/ec/scrub/start runs a pass that repairs planted rot; the verdict
    shows on /ec/scrub/status and /status, the counters ride /metrics,
    and the master's /cluster/health folds them into its degraded
    verdict (a repaired run can't pass as clean)."""
    from seaweedfs_tpu.master.server import MasterServer
    from seaweedfs_tpu.shell import CommandEnv, run_command
    from seaweedfs_tpu.utils.httpd import http_json
    from seaweedfs_tpu.volume_server.server import VolumeServer

    from tests.conftest import free_port

    d = tmp_path / "vs0"
    d.mkdir()
    base = _write_test_volume(d)
    master = MasterServer(port=free_port(), pulse_seconds=0.4).start()
    vs = VolumeServer([str(d)], master.url, port=free_port(),
                      pulse_seconds=0.4).start()
    try:
        vs.store.ec_generate(1)
        vs.store.ec_mount(1)
        orig11 = open(base + to_ext(11), "rb").read()
        _flip(base + to_ext(11), 4096)
        r = http_json("POST", f"http://{vs.url}/ec/scrub/start",
                      {"rate_mb_s": 0})
        assert r["started"] is True
        deadline = time.time() + 10
        verdict = {}
        while time.time() < deadline:
            st = http_json("GET", f"http://{vs.url}/ec/scrub/status")
            verdict = st["verdicts"].get("1", {})
            if not st["running"] and verdict:
                break
            time.sleep(0.05)
        assert verdict.get("status") == "repaired", verdict
        assert open(base + to_ext(11), "rb").read() == orig11
        status = http_json("GET", f"http://{vs.url}/status")
        assert status["EcScrub"]["verdicts"]["1"] == "repaired"
        assert status["EcIntegrity"]["corrupt_shards"] >= 1
        # shell surface
        env = CommandEnv(master.url)
        out = run_command(env, f"ec.scrub -server {vs.url} -action status")
        assert "repairs=1" in out or "repairs=" in out
        assert "corrupt=" in out
        # master rollup: the scrub counters mark the cluster degraded
        vs.heartbeat_now()
        health = http_json("GET", f"http://{master.url}/cluster/health")
        assert health["totals"]["corrupt_shards"] >= 1
        assert health["totals"]["scrub_repairs"] >= 1
        assert health["degraded"] is True
    finally:
        vs.stop()
        master.stop()


def test_targeted_rescrub_clears_stale_unrepairable_verdict(tmp_path):
    """The coordinator's post-repair follow-up: a volume scrubbed
    UNREPAIRABLE (> r rotted shards), then healed out of band (the
    cross-server repair restoring clean shards), re-verifies via a
    TARGETED one-pass scan — start(volume_id=vid) — and the stale
    verdict flips to clean immediately instead of waiting for the
    next full pass."""
    import shutil
    import time as _time

    from seaweedfs_tpu.volume_server.scrubber import EcScrubber

    store, base = _store_with_ec_volume(tmp_path)
    try:
        clean_copies = {sid: open(base + to_ext(sid), "rb").read()
                        for sid in range(14)}
        for sid in (0, 1, 2, 3, 4):
            _flip(base + to_ext(sid), 300)
        scrub = EcScrubber(store, rate_mb_s=0)
        st = scrub.run_pass()
        assert st["verdicts"]["1"]["status"] == "unrepairable"
        # out-of-band heal (what the coordinator's cross-server repair
        # does): clean shard files land back on disk, remount
        for sid in (0, 1, 2, 3, 4):
            bad = base + to_ext(sid) + ".bad"
            if os.path.exists(bad):
                os.remove(bad)
            with open(base + to_ext(sid), "wb") as f:
                f.write(clean_copies[sid])
        store.ec_unmount(1)
        store.ec_mount(1)
        # targeted re-scrub: one pass over JUST volume 1.  Wait on the
        # PASS COUNTER, not the running flag — the scan thread sets
        # running=True asynchronously, so polling the flag right after
        # start() can observe the pre-start False and read the stale
        # verdict before the scan ever ran.
        p0 = scrub.status()["passes"]
        assert scrub.start(volume_id=1) is True
        deadline = _time.time() + 10
        while _time.time() < deadline and \
                scrub.status()["passes"] == p0:
            _time.sleep(0.05)
        st = scrub.status()
        assert st["passes"] == p0 + 1
        assert st["verdicts"]["1"]["status"] == "clean"
        # the targeted marker cleared: the next start is a full scan
        assert scrub.only_vid is None
        shutil.rmtree(str(tmp_path), ignore_errors=True)
    finally:
        store.close()
