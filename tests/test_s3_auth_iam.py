"""S3 signature auth + IAM gateway tests."""

from __future__ import annotations

import json
import time
import urllib.parse
import xml.etree.ElementTree as ET

import pytest

from seaweedfs_tpu.filer.filer_store import MemoryStore
from seaweedfs_tpu.filer.server import FilerServer
from seaweedfs_tpu.gateway.iam import IamApiServer, policy_to_actions
from seaweedfs_tpu.gateway.s3 import S3ApiServer
from seaweedfs_tpu.gateway.s3_auth import (
    IDENTITY_PATH, AuthError, Identity, IdentityAccessManagement,
    decode_streaming_chunks, presign_v4, sign_v4)
from seaweedfs_tpu.master.server import MasterServer
from seaweedfs_tpu.utils.httpd import http_bytes
from seaweedfs_tpu.volume_server.server import VolumeServer

from tests.conftest import free_port  # noqa: E402


# --- unit: identity authorization ------------------------------------------

def test_can_do_scoping():
    ident = Identity("u", [("AK", "SK")],
                     ["Read:photos", "Write:photos/staging", "List"])
    assert ident.can_do("Read", "photos")
    assert ident.can_do("Read", "photos", "x/y.jpg")
    assert not ident.can_do("Read", "other")
    assert not ident.can_do("Write", "photos", "final/a")
    assert ident.can_do("Write", "photos", "staging/a")
    assert ident.can_do("List", "anything")
    admin = Identity("root", [], ["Admin"])
    assert admin.can_do("Write", "any", "thing")
    scoped_admin = Identity("ops", [], ["Admin:infra"])
    assert scoped_admin.can_do("Write", "infra", "x")
    assert not scoped_admin.can_do("Read", "photos")


def test_can_do_no_prefix_bypass():
    """A grant on bucket "photos" must not leak into "photos-backup",
    nor "photos/staging" into "photos/staging2"; only trailing-* grants
    opt into raw prefix matching."""
    ident = Identity("u", [], ["Read:photos", "Write:photos/staging"])
    assert not ident.can_do("Read", "photos-backup")
    assert not ident.can_do("Read", "photos-backup", "secret.txt")
    assert not ident.can_do("Write", "photos", "staging2/x")
    star = Identity("s", [], ["Read:photos*"])
    assert star.can_do("Read", "photos-backup")


def test_policy_to_actions():
    doc = {"Statement": [
        {"Effect": "Allow", "Action": ["s3:GetObject", "s3:ListBucket"],
         "Resource": "arn:aws:s3:::photos/*"},
        {"Effect": "Allow", "Action": "s3:*", "Resource": "*"},
        {"Effect": "Deny", "Action": "s3:PutObject", "Resource": "*"},
    ]}
    acts = policy_to_actions(doc)
    assert "Read:photos" in acts and "List:photos" in acts
    assert "Admin" in acts
    assert not any(a.startswith("Write") for a in acts)  # Deny not mapped


# --- unit: sigv4 round-trip -------------------------------------------------

def test_sigv4_sign_and_verify():
    iam = IdentityAccessManagement()
    iam.load_config({"identities": [
        {"name": "u", "credentials": [
            {"accessKey": "AK123", "secretKey": "SECRET"}],
         "actions": ["Admin"]}]})
    url = "http://localhost:8333/bucket/key.txt?partNumber=1&uploadId=x"
    body = b"hello world"
    headers = sign_v4("PUT", url, "AK123", "SECRET", body)
    parsed = urllib.parse.urlparse(url)
    query = {k: v[0] for k, v in urllib.parse.parse_qs(
        parsed.query, keep_blank_values=True).items()}
    ident = iam.authenticate("PUT", parsed.path, query, headers, body)
    assert ident.name == "u"

    # tampered body fails the content-sha check
    from seaweedfs_tpu.gateway.s3_auth import AuthError
    with pytest.raises(AuthError):
        iam.authenticate("PUT", parsed.path, query, headers, b"evil")

    # wrong secret fails signature
    bad = sign_v4("PUT", url, "AK123", "WRONG", body)
    with pytest.raises(AuthError):
        iam.authenticate("PUT", parsed.path, query, bad, body)


def test_presigned_url_verify_and_expiry():
    iam = IdentityAccessManagement()
    iam.load_config({"identities": [
        {"name": "u", "credentials": [
            {"accessKey": "AK123", "secretKey": "SECRET"}],
         "actions": ["Admin"]}]})

    def check(url):
        parsed = urllib.parse.urlparse(url)
        query = {k: v[0] for k, v in urllib.parse.parse_qs(
            parsed.query, keep_blank_values=True).items()}
        return iam.authenticate("GET", parsed.path, query,
                                {"Host": parsed.netloc}, b"")

    fresh = presign_v4("GET", "http://host:1/b/k.txt", "AK123", "SECRET",
                       expires=300)
    assert check(fresh).name == "u"

    stale_date = time.strftime("%Y%m%dT%H%M%SZ",
                               time.gmtime(time.time() - 7200))
    stale = presign_v4("GET", "http://host:1/b/k.txt", "AK123", "SECRET",
                       expires=60, amz_date=stale_date)
    with pytest.raises(AuthError, match="expired"):
        check(stale)

    # stale header-signature is rejected too (15-minute skew window)
    old_hdrs = sign_v4("GET", "http://host:1/b/k.txt", "AK123", "SECRET",
                       amz_date=stale_date)
    with pytest.raises(AuthError) as ei:
        iam.authenticate("GET", "/b/k.txt", {}, old_hdrs, b"")
    assert ei.value.code == "RequestTimeTooSkewed"


def test_streaming_chunk_decode():
    chunk1 = b"a" * 10
    chunk2 = b"bb"
    framed = (b"a;chunk-signature=deadbeef\r\n" + chunk1 + b"\r\n"
              b"2;chunk-signature=cafe\r\n" + chunk2 + b"\r\n"
              b"0;chunk-signature=00\r\n\r\n")
    assert decode_streaming_chunks(framed) == chunk1 + chunk2


# --- integration: secured gateway + IAM api --------------------------------

@pytest.fixture
def stack(tmp_path):
    master = MasterServer(port=free_port(), pulse_seconds=0.4).start()
    d = tmp_path / "vs0"
    d.mkdir()
    vol = VolumeServer([str(d)], master.url, port=free_port(),
                       pulse_seconds=0.4).start()
    deadline = time.time() + 5
    while time.time() < deadline and not master.topo.all_nodes():
        time.sleep(0.05)
    filer = FilerServer(master.url, MemoryStore(), port=free_port(),
                        max_chunk_mb=1).start()
    s3 = S3ApiServer(filer, port=free_port()).start()
    iam = IamApiServer(filer, port=free_port()).start()
    yield filer, s3, iam
    iam.stop()
    s3.stop()
    filer.stop()
    vol.stop()
    master.stop()


def _iam_call(iam, action: str, **params) -> ET.Element:
    form = urllib.parse.urlencode({"Action": action, **params})
    status, body, _ = http_bytes(
        "POST", f"http://{iam.url}/", form.encode(),
        headers={"Content-Type": "application/x-www-form-urlencoded"})
    assert status == 200, body
    return ET.fromstring(body)


def test_iam_lifecycle_and_s3_enforcement(stack):
    filer, s3, iam = stack
    ns = "{https://iam.amazonaws.com/doc/2010-05-08/}"

    # open gateway before any identity exists
    status, _, _ = http_bytes("PUT", f"http://{s3.url}/openbucket")
    assert status == 200

    _iam_call(iam, "CreateUser", UserName="alice")
    resp = _iam_call(iam, "CreateAccessKey", UserName="alice")
    ak = resp.find(f".//{ns}AccessKeyId").text
    sk = resp.find(f".//{ns}SecretAccessKey").text
    policy = json.dumps({"Statement": [
        {"Effect": "Allow",
         "Action": ["s3:GetObject", "s3:ListBucket", "s3:PutObject"],
         "Resource": "arn:aws:s3:::openbucket/*"}]})
    _iam_call(iam, "PutUserPolicy", UserName="alice", PolicyDocument=policy)

    # wait for the gateway to hot-reload the identity file
    deadline = time.time() + 5
    while time.time() < deadline and not s3.iam.enabled():
        time.sleep(0.05)
    assert s3.iam.enabled()

    # unsigned requests are now rejected
    status, body, _ = http_bytes("PUT", f"http://{s3.url}/openbucket/f.txt",
                                 b"data")
    assert status == 403

    # signed with alice's key: object PUT/GET succeeds in her bucket
    url = f"http://{s3.url}/openbucket/f.txt"
    headers = sign_v4("PUT", url, ak, sk, b"data")
    status, _, _ = http_bytes("PUT", url, b"data", headers=headers)
    assert status == 200
    headers = sign_v4("GET", url, ak, sk)
    status, body, _ = http_bytes("GET", url, headers=headers)
    assert status == 200 and body == b"data"

    # but she may not write another bucket
    url2 = f"http://{s3.url}/otherbucket"
    headers = sign_v4("PUT", url2, ak, sk)
    status, _, _ = http_bytes("PUT", url2, headers=headers)
    assert status == 403

    # wrong secret is rejected
    headers = sign_v4("GET", url, ak, "bogus")
    status, _, _ = http_bytes("GET", url, headers=headers)
    assert status == 403

    # ListAccessKeys shows the key; DeleteAccessKey revokes access
    resp = _iam_call(iam, "ListAccessKeys", UserName="alice")
    assert resp.find(f".//{ns}AccessKeyId").text == ak
    _iam_call(iam, "DeleteAccessKey", UserName="alice", AccessKeyId=ak)
    deadline = time.time() + 5
    while time.time() < deadline:
        headers = sign_v4("GET", url, ak, sk)
        if http_bytes("GET", url, headers=headers)[0] == 403:
            break
        time.sleep(0.05)
    headers = sign_v4("GET", url, ak, sk)
    assert http_bytes("GET", url, headers=headers)[0] == 403


def test_streaming_upload_decoded_on_open_gateway(stack):
    """aws-chunked framing must be stripped even with auth disabled."""
    filer, s3, iam = stack
    payload = b"plain object bytes"
    framed = (b"12;chunk-signature=00\r\n" + payload + b"\r\n"
              b"0;chunk-signature=00\r\n\r\n")
    url = f"http://{s3.url}/openb"
    assert http_bytes("PUT", url)[0] == 200
    status, _, _ = http_bytes(
        "PUT", f"{url}/s.bin", framed,
        headers={"X-Amz-Content-Sha256":
                 "STREAMING-AWS4-HMAC-SHA256-PAYLOAD"})
    assert status == 200
    status, body, _ = http_bytes("GET", f"{url}/s.bin")
    assert status == 200 and body == payload


def test_streaming_unsigned_trailer_upload_decoded(stack):
    """STREAMING-UNSIGNED-PAYLOAD-TRAILER (modern SDK default): unsigned
    chunks + trailer headers after the 0-chunk must also be unframed."""
    filer, s3, iam = stack
    payload = b"trailer-framed bytes"
    framed = (b"14\r\n" + payload + b"\r\n"
              b"0\r\n"
              b"x-amz-checksum-crc32c:AAAAAA==\r\n\r\n")
    url = f"http://{s3.url}/trailerb"
    assert http_bytes("PUT", url)[0] == 200
    status, _, _ = http_bytes(
        "PUT", f"{url}/t.bin", framed,
        headers={"X-Amz-Content-Sha256":
                 "STREAMING-UNSIGNED-PAYLOAD-TRAILER"})
    assert status == 200
    status, body, _ = http_bytes("GET", f"{url}/t.bin")
    assert status == 200 and body == payload


def test_iam_requires_admin_signature_once_admin_exists(stack):
    filer, s3, iam = stack
    ns = "{https://iam.amazonaws.com/doc/2010-05-08/}"
    # bootstrap an administrator (open while no admin exists)
    _iam_call(iam, "CreateUser", UserName="root")
    resp = _iam_call(iam, "CreateAccessKey", UserName="root")
    ak = resp.find(f".//{ns}AccessKeyId").text
    sk = resp.find(f".//{ns}SecretAccessKey").text
    _iam_call(iam, "PutUserPolicy", UserName="root", PolicyDocument=json.dumps(
        {"Statement": [{"Effect": "Allow", "Action": "s3:*",
                        "Resource": "*"}]}))
    # unsigned mutation now rejected
    form = urllib.parse.urlencode(
        {"Action": "CreateUser", "UserName": "mallory"}).encode()
    status, body, _ = http_bytes(
        "POST", f"http://{iam.url}/", form,
        headers={"Content-Type": "application/x-www-form-urlencoded"})
    assert status == 403, body
    # signed by root: accepted
    headers = sign_v4("POST", f"http://{iam.url}/", ak, sk, form)
    headers["Content-Type"] = "application/x-www-form-urlencoded"
    status, body, _ = http_bytes("POST", f"http://{iam.url}/", form,
                                 headers=headers)
    assert status == 200, body
    assert b"mallory" not in body or b"CreateUserResponse" in body


def test_shell_s3_commands(stack):
    filer, s3, iam = stack
    from seaweedfs_tpu.shell import CommandEnv, run_command

    env = CommandEnv("127.0.0.1:1", filer.url)  # master not needed here
    env.admin_token = 1  # pretend-locked for mutating cmds

    assert "created bucket b1" in run_command(env, "s3.bucket.create -name b1")
    listing = run_command(env, "s3.bucket.list")
    assert "b1" in listing
    out = run_command(
        env, "s3.configure -user bob -access_key BK -secret_key BS "
             "-actions Read:b1,Write:b1 -apply")
    assert "1 identities" in out
    cfg = json.loads(run_command(env, "s3.configure"))
    assert cfg["identities"][0]["name"] == "bob"
    assert "deleted bucket b1" in run_command(env, "s3.bucket.delete -name b1")
    out = run_command(env, "s3.clean.uploads -timeAgo 0s")
    assert "stale" in out
