"""Alerting engine + event journal + flight recorder (the ACTIVE third
of the observability stack).

Units drive synthetic metric sequences through the alert state machine
(pending -> firing -> resolved, hold-downs, burn-rate fast/slow window
matrix, counter-reset tolerance) and the event journal / flight
recorder in isolation; the live drill runs a real master + volume
server, injects `ec.shard.corrupt`, and asserts the whole chain fires
WITHOUT manual polling: scrub detects -> counters rise -> rule fires ->
events journaled with the scrub's trace id -> flight-recorder bundles
captured and fetchable.
"""

from __future__ import annotations

import json
import logging
import os
import time

import numpy as np
import pytest

from seaweedfs_tpu.observability import context as trace_context
from seaweedfs_tpu.observability.alerts import (AlertEngine, Rule,
                                                default_rules)
from seaweedfs_tpu.observability.events import (ClusterEventJournal,
                                                EVENT_TYPES,
                                                EventJournal,
                                                EventShipper)
from seaweedfs_tpu.observability.flightrecorder import FlightRecorder
from seaweedfs_tpu.stats.metrics import Counter, Histogram

rng = np.random.default_rng(23)


# --- event journal ---------------------------------------------------------

class TestEventJournal:
    def test_emit_defaults_and_filters(self):
        j = EventJournal(capacity=16)
        j.emit("worker_restart", kind="staged")
        j.emit("shard_corrupt", shard=3)
        j.emit("alert_fired", severity="critical", alert="x")
        assert [e["type"] for e in j.query()] == [
            "worker_restart", "shard_corrupt", "alert_fired"]
        # severity defaults ride the registry
        assert j.query(type_="worker_restart")[0]["severity"] == \
            EVENT_TYPES["worker_restart"]
        assert [e["type"] for e in j.query(min_severity="error")] == \
            ["shard_corrupt", "alert_fired"]
        assert j.query(severity="critical")[0]["details"]["alert"] == "x"
        seq = j.query(type_="worker_restart")[0]["seq"]
        assert all(e["seq"] > seq for e in j.query(since_seq=seq))

    def test_bounded_ring_counts_drops(self):
        j = EventJournal(capacity=4)
        for i in range(10):
            j.emit("worker_restart", i=i)
        assert len(j.query(limit=0)) == 4
        assert j.dropped == 6
        # the tail keeps the most RECENT events
        assert j.query(limit=2)[-1]["details"]["i"] == 9

    def test_trace_and_server_ride_thread_locals(self):
        j = EventJournal()
        ctx = trace_context.TraceContext("ab" * 16)
        prev = trace_context.activate(ctx)
        prev_srv = trace_context.swap_server("vs:8080")
        try:
            e = j.emit("shard_corrupt", shard=1).to_dict()
        finally:
            trace_context.swap_server(prev_srv)
            trace_context.activate(prev)
        assert e["trace"] == "ab" * 16
        assert e["server"] == "vs:8080"
        # outside any decision: no trace, no server
        e2 = j.emit("shard_corrupt", shard=2).to_dict()
        assert "trace" not in e2 and "server" not in e2

    def test_cluster_journal_dedups_and_bounds(self):
        src = EventJournal(namespace="n1")
        docs = [src.emit("worker_restart", server="vs:1",
                         i=i).to_dict() for i in range(3)]
        cj = ClusterEventJournal(capacity=4)
        assert cj.ingest("vs:1", docs) == 3
        # re-ship (chained shippers / retries) is a no-op
        assert cj.ingest("vs:1", docs) == 0
        assert len(cj) == 3
        assert all(e["server"] == "vs:1" for e in cj.query())
        other = EventJournal(namespace="n2")
        more = [other.emit("shard_corrupt", server="vs:2",
                           i=i).to_dict() for i in range(3)]
        cj.ingest("vs:2", more)
        assert len(cj) == 4 and cj.dropped == 2  # oldest evicted
        assert cj.query(type_="shard_corrupt", server="vs:2")

    def test_transport_labels_but_never_claims_attribution(self):
        """The shipping hop records itself as `via`; an event that
        arrives unattributed STAYS unattributed — the transport must
        not claim emission (co-located shippers would otherwise race
        their conflicting stamps through the dedup)."""
        src = EventJournal(namespace="nx")
        doc = src.emit("worker_restart", kind="staged").to_dict()
        cj = ClusterEventJournal()
        cj.ingest("m:1", [doc])
        (e,) = cj.query()
        assert "server" not in e and e["via"] == "m:1"

    def test_sole_shipper_default_stamps_background_emits(self):
        """With exactly ONE shipper attached (the production
        one-server-per-process shape), events emitted on background
        threads with no request thread-local still attribute to that
        server; a second co-located shipper makes the default
        AMBIGUOUS and emits go unattributed instead of guessing."""
        j = EventJournal()
        cj = ClusterEventJournal()
        s1 = EventShipper(j, server="vs:1", local_journal=cj,
                          flush_interval=0.05).attach()
        try:
            assert j.emit("worker_restart").to_dict()["server"] == "vs:1"
            s2 = EventShipper(j, server="m:2", local_journal=cj,
                              flush_interval=0.05).attach()
            try:
                assert "server" not in j.emit("worker_restart").to_dict()
                # explicit identity always wins over the default
                assert j.emit("worker_restart", server="vs:1") \
                    .to_dict()["server"] == "vs:1"
            finally:
                s2.detach()
            # back to one shipper: the default is unambiguous again
            assert j.emit("worker_restart").to_dict()["server"] == "vs:1"
        finally:
            s1.detach()

    def test_emit_before_attach_never_ships(self):
        """attach() has no backfill — which is why the servers hook
        their shipper BEFORE any bind attempt can emit degraded_bind."""
        j = EventJournal()
        cj = ClusterEventJournal()
        j.emit("degraded_bind", role="early")
        sh = EventShipper(j, server="m:1", local_journal=cj,
                          flush_interval=0.05).attach()
        try:
            j.emit("degraded_bind", role="late")
            deadline = time.time() + 3
            while time.time() < deadline and not len(cj):
                time.sleep(0.02)
            roles = {e["details"]["role"] for e in cj.query()}
            assert roles == {"late"}
        finally:
            sh.detach()

    def test_shipper_local_short_circuit(self):
        j = EventJournal()
        cj = ClusterEventJournal()
        sh = EventShipper(j, server="m:1", local_journal=cj,
                          flush_interval=0.05).attach()
        try:
            j.emit("degraded_bind", role="tcp")
            deadline = time.time() + 3
            while time.time() < deadline and not len(cj):
                time.sleep(0.02)
            assert cj.query(type_="degraded_bind")
        finally:
            sh.detach()


# --- alert state machine ---------------------------------------------------

def _health(peers: dict, totals: dict, stale=()):
    return {"peers": {u: {"pipeline_health": ph} for u, ph in
                      peers.items()},
            "totals": totals, "stale_peers": list(stale),
            "degraded": any(totals.values()), "peer_count": len(peers)}


class TestStateMachine:
    def _engine(self, rules, source, **kw):
        return AlertEngine(rules, source_fn=source, min_interval=0.0,
                           journal=EventJournal(), **kw)

    def test_counter_increase_full_lifecycle(self):
        state = {"v": 0}
        rule = Rule("r", "counter_increase", "error", for_s=0.0,
                    keep_firing_s=10.0, params={"key": "corrupt_shards"})
        eng = self._engine([rule], lambda: (_health(
            {"vs:1": {"corrupt_shards": state["v"]}},
            {"corrupt_shards": state["v"]}), {}))
        # first sight = baseline, never a fire
        assert eng.evaluate(now=1.0, force=True)["alerts"][0]["state"] \
            == "inactive"
        state["v"] = 2
        d = eng.evaluate(now=2.0, force=True)["alerts"][0]
        assert d["state"] == "firing" and d["value"] == 2
        assert d["servers"] == ["vs:1"]
        # still firing while quiet < keep_firing_s
        assert eng.evaluate(now=5.0, force=True)["alerts"][0]["state"] \
            == "firing"
        # resolves after sustained quiet
        d = eng.evaluate(now=13.0, force=True)["alerts"][0]
        assert d["state"] == "resolved"
        # journal recorded the transitions
        types = [e["type"] for e in eng.journal.query()]
        assert types == ["alert_pending", "alert_fired",
                         "alert_resolved"]
        # reactivation starts a fresh cycle
        state["v"] = 3
        assert eng.evaluate(now=14.0, force=True)["alerts"][0]["state"] \
            == "firing"

    def test_hold_down_respected(self):
        """A condition shorter than for_s never fires."""
        state = {"v": 0}
        rule = Rule("r", "counter_increase", for_s=5.0,
                    params={"key": "worker_restarts"})
        eng = self._engine([rule], lambda: (_health(
            {"vs:1": {"worker_restarts": state["v"]}},
            {"worker_restarts": state["v"]}), {}))
        eng.evaluate(now=1.0, force=True)
        state["v"] = 1
        assert eng.evaluate(now=2.0, force=True)["alerts"][0]["state"] \
            == "pending"
        # condition clears before the hold-down elapses: back to
        # inactive, alert_fired never journaled
        assert eng.evaluate(now=3.0, force=True)["alerts"][0]["state"] \
            == "inactive"
        assert not eng.journal.query(type_="alert_fired")
        # sustained condition crosses the hold-down and fires
        state["v"] = 2
        eng.evaluate(now=4.0, force=True)
        state["v"] = 3
        eng.evaluate(now=6.0, force=True)
        state["v"] = 4
        d = eng.evaluate(now=9.5, force=True)["alerts"][0]
        assert d["state"] == "firing"

    def test_counter_reset_tolerated(self):
        """A peer restart drops its counter to 0: re-baseline, never
        fire, and the next REAL increase still fires."""
        state = {"v": 7}
        rule = Rule("r", "counter_increase",
                    params={"key": "engine_fallbacks"})
        eng = self._engine([rule], lambda: (_health(
            {"vs:1": {"engine_fallbacks": state["v"]}},
            {"engine_fallbacks": state["v"]}), {}))
        eng.evaluate(now=1.0, force=True)
        state["v"] = 0  # restart
        assert eng.evaluate(now=2.0, force=True)["alerts"][0]["state"] \
            == "inactive"
        state["v"] = 1
        assert eng.evaluate(now=3.0, force=True)["alerts"][0]["state"] \
            == "firing"

    def test_threshold_and_peer_down(self):
        totals = {"scrub_unrepairable": 0}
        stale: list = []
        rules = [Rule("unrep", "threshold", "critical",
                      params={"key": "scrub_unrepairable", "min": 1}),
                 Rule("peer", "peer_down", keep_firing_s=0.0)]
        eng = self._engine(rules, lambda: (_health({}, totals, stale),
                                           {}))
        d = {a["name"]: a for a in
             eng.evaluate(now=1.0, force=True)["alerts"]}
        assert d["unrep"]["state"] == "inactive"
        assert d["peer"]["state"] == "inactive"
        totals["scrub_unrepairable"] = 2
        stale.append("vs:9")
        d = {a["name"]: a for a in
             eng.evaluate(now=2.0, force=True)["alerts"]}
        assert d["unrep"]["state"] == "firing"
        assert d["peer"]["state"] == "firing"
        assert "vs:9" in d["peer"]["detail"]
        totals["scrub_unrepairable"] = 0
        stale.clear()
        d = {a["name"]: a for a in
             eng.evaluate(now=3.0, force=True)["alerts"]}
        # keep_firing_s=0 resolves on the first clean evaluation
        assert d["peer"]["state"] == "resolved"

    def test_on_fire_called_once_with_servers(self):
        fired = []
        state = {"v": 0}
        rule = Rule("r", "counter_increase",
                    params={"key": "corrupt_shards"})
        eng = self._engine(
            [rule], lambda: (_health(
                {"vs:1": {"corrupt_shards": state["v"]}},
                {"corrupt_shards": state["v"]}), {}),
            on_fire=lambda r, st, servers: fired.append(
                (r.name, servers)))
        eng.evaluate(now=1.0, force=True)
        state["v"] = 1
        eng.evaluate(now=2.0, force=True)
        state["v"] = 2
        eng.evaluate(now=3.0, force=True)  # still firing: no re-fire
        assert fired == [("r", ["vs:1"])]

    def test_ttl_early_return_serves_last_state(self):
        """An unforced evaluate inside min_interval returns the last
        round's state WITHOUT re-evaluating (and without deadlocking —
        the early return re-takes the engine lock for the snapshot)."""
        calls = []
        rule = Rule("r", "counter_increase",
                    params={"key": "corrupt_shards"})
        eng = AlertEngine(
            [rule], lambda: (calls.append(1) or _health(
                {"vs:1": {"corrupt_shards": 0}},
                {"corrupt_shards": 0}), {}),
            min_interval=60.0, journal=EventJournal())
        eng.evaluate(now=100.0, force=True)
        d = eng.evaluate(now=101.0)  # inside the TTL, not forced
        assert d["evaluations"] == 1 and len(calls) == 1
        d = eng.evaluate(now=200.0)  # TTL elapsed
        assert d["evaluations"] == 2 and len(calls) == 2

    def test_broken_rule_isolated(self):
        """One rule raising must not stop the others evaluating."""
        state = {"v": 0}
        rules = [Rule("bad", "counter_increase", params={}),  # no key
                 Rule("good", "counter_increase",
                      params={"key": "corrupt_shards"})]
        eng = self._engine(rules, lambda: (_health(
            {"vs:1": {"corrupt_shards": state["v"]}},
            {"corrupt_shards": state["v"]}), {}))
        eng.evaluate(now=1.0, force=True)
        state["v"] = 1
        d = {a["name"]: a for a in
             eng.evaluate(now=2.0, force=True)["alerts"]}
        assert d["good"]["state"] == "firing"
        assert "rule error" in d["bad"]["detail"]


# --- burn-rate windows -----------------------------------------------------

def _error_rule(**over):
    params = {"mode": "error_ratio", "errors": "E", "requests": "R",
              "max_ratio": 0.01, "fast_s": 10.0, "slow_s": 60.0,
              "min_requests": 10}
    params.update(over)
    return Rule("burn", "burn_rate", "critical", keep_firing_s=0.0,
                params=params)


class _Red:
    """Synthetic per-route RED counters the burn rules read."""

    def __init__(self):
        self.req = Counter("R", labels=("type",))
        self.err = Counter("E", labels=("type",))
        self.hist = Histogram("H", labels=("type",),
                              buckets=(0.01, 0.1, 0.5, 1.0))

    @property
    def families(self):
        return {"R": self.req, "E": self.err, "H": self.hist}


class TestBurnRate:
    def _engine(self, rule, red):
        return AlertEngine([rule], lambda: ({"peers": {}, "totals": {},
                                             "stale_peers": []},
                                            red.families),
                           min_interval=0.0, journal=EventJournal())

    def test_fast_blip_does_not_fire_slow_burn_does(self):
        red = _Red()
        eng = self._engine(_error_rule(max_ratio=0.05), red)
        now = 1000.0
        # 60s of clean history: 600 requests, 0 errors
        for i in range(7):
            red.req.inc("read", amount=100)
            eng.evaluate(now=now + i * 10, force=True)
        # one fast window with 8% errors — but over the slow window the
        # ratio is 8/700 ~ 1.1% < 5%: fast breaches, slow doesn't
        red.req.inc("read", amount=100)
        red.err.inc("read", amount=8)
        d = eng.evaluate(now=now + 70, force=True)["alerts"][0]
        assert d["state"] == "inactive"
        # sustain the burn: every subsequent window runs at 8% errors,
        # so the slow ratio climbs past 5% too -> fires
        state = "inactive"
        for i in range(8, 15):
            red.req.inc("read", amount=100)
            red.err.inc("read", amount=8)
            state = eng.evaluate(
                now=now + i * 10, force=True)["alerts"][0]["state"]
            if state == "firing":
                break
        assert state == "firing"

    def test_windows_need_history(self):
        """No base sample older than the window yet -> never fires (a
        fresh engine must not page on startup)."""
        red = _Red()
        eng = self._engine(_error_rule(), red)
        red.req.inc("read", amount=100)
        red.err.inc("read", amount=50)
        d = eng.evaluate(now=1000.0, force=True)["alerts"][0]
        assert d["state"] == "inactive"
        red.req.inc("read", amount=100)
        red.err.inc("read", amount=50)
        # 15s later: fast window evaluable, slow (60s) still not
        d = eng.evaluate(now=1015.0, force=True)["alerts"][0]
        assert d["state"] == "inactive"

    def test_min_requests_guards_noise(self):
        red = _Red()
        eng = self._engine(_error_rule(min_requests=50), red)
        now = 1000.0
        for i in range(7):
            red.req.inc("read", amount=5)
            eng.evaluate(now=now + i * 10, force=True)
        red.req.inc("read", amount=5)
        red.err.inc("read", amount=5)  # 100% errors but 5 < 50 reqs
        d = eng.evaluate(now=now + 70, force=True)["alerts"][0]
        assert d["state"] == "inactive"

    def test_counter_reset_skips_route(self):
        red = _Red()
        eng = self._engine(_error_rule(), red)
        now = 1000.0
        for i in range(7):
            red.req.inc("read", amount=100)
            eng.evaluate(now=now + i * 10, force=True)
        # "restart": replace counters with smaller values
        red.req = Counter("R", labels=("type",))
        red.err = Counter("E", labels=("type",))
        red.req.inc("read", amount=10)
        red.err.inc("read", amount=10)
        d = eng.evaluate(now=now + 70, force=True)["alerts"][0]
        assert d["state"] == "inactive"  # negative delta: re-baseline

    def test_p99_latency_burn(self):
        rule = Rule("lat", "burn_rate", "critical", keep_firing_s=0.0,
                    params={"mode": "p99", "family": "H",
                            "max_p99_s": 0.3, "fast_s": 10.0,
                            "slow_s": 60.0, "min_requests": 10})
        red = _Red()
        eng = self._engine(rule, red)
        now = 1000.0
        for i in range(7):
            for _ in range(50):
                red.hist.observe("read", 0.005)  # all fast
            eng.evaluate(now=now + i * 10, force=True)
        d = eng.evaluate(now=now + 69, force=True)["alerts"][0]
        assert d["state"] == "inactive"
        # sustained slowness: p99 lands in the 0.5s bucket > 0.3s SLO
        state = "inactive"
        for i in range(7, 15):
            for _ in range(50):
                red.hist.observe("read", 0.4)
            state = eng.evaluate(
                now=now + i * 10, force=True)["alerts"][0]["state"]
            if state == "firing":
                break
        assert state == "firing"
        assert "p99" in eng.to_dict()["alerts"][0]["detail"]


# --- flight recorder -------------------------------------------------------

class TestFlightRecorder:
    def test_capture_list_get_roundtrip(self, tmp_path):
        from seaweedfs_tpu.stats import ec_pipeline_metrics

        ec_pipeline_metrics()  # ensure the exposition has families
        fr = FlightRecorder(spool_dir=str(tmp_path / "spool"))
        meta = fr.capture(reason="unit", alert="r1", server="vs:1",
                          profile_s=0.0)
        assert meta["id"].startswith("fr-") and meta["bytes"] > 0
        ids = [b["id"] for b in fr.list()]
        assert meta["id"] in ids
        doc = fr.get(meta["id"])
        assert doc["format"] == "seaweedfs-tpu-flightrecorder-v1"
        assert doc["meta"]["alert"] == "r1"
        assert set(doc) >= {"trace", "profile", "metrics", "events"}
        assert "SeaweedFS" in doc["metrics"]
        # the capture itself journals a flight_capture event
        from seaweedfs_tpu.observability.events import get_journal

        assert any(e["type"] == "flight_capture"
                   and e["details"]["id"] == meta["id"]
                   for e in get_journal().query(type_="flight_capture"))

    def test_bad_ids_rejected(self, tmp_path):
        fr = FlightRecorder(spool_dir=str(tmp_path / "spool"))
        assert fr.get("../../etc/passwd") is None
        assert fr.get("") is None
        assert fr.get("nope") is None

    def test_oldest_bundle_eviction(self, tmp_path):
        fr = FlightRecorder(spool_dir=str(tmp_path / "spool"),
                            max_bundles=3)
        ids = [fr.capture(reason=f"n{i}", profile_s=0.0)["id"]
               for i in range(5)]
        kept = {b["id"] for b in fr.list()}
        assert len(kept) == 3
        assert ids[-1] in kept and ids[0] not in kept
        assert fr.evicted == 2
        assert fr.get(ids[0]) is None

    def test_byte_cap_eviction(self, tmp_path):
        fr = FlightRecorder(spool_dir=str(tmp_path / "spool"),
                            max_bytes=1)  # everything over budget
        fr.capture(reason="a", profile_s=0.0)
        fr.capture(reason="b", profile_s=0.0)
        assert len(fr.list()) <= 1


# --- satellites ------------------------------------------------------------

class TestGlogSatellites:
    def test_v_warningf_errorf_exist_and_gate(self, caplog):
        from seaweedfs_tpu.utils import glog

        glog.set_verbosity(1)
        with caplog.at_level(logging.DEBUG, logger="weed"):
            glog.V(1).warningf("w %d", 1)
            glog.V(1).errorf("e %d", 2)
            glog.V(3).warningf("hidden")
            glog.V(3).errorf("hidden")
            glog.V(3).infof("hidden")
        glog.set_verbosity(0)
        msgs = [r.getMessage() for r in caplog.records]
        assert "w 1" in msgs and "e 2" in msgs
        assert "hidden" not in msgs
        levels = {r.getMessage(): r.levelno for r in caplog.records}
        assert levels["w 1"] == logging.WARNING
        assert levels["e 2"] == logging.ERROR

    def test_init_honors_level(self):
        from seaweedfs_tpu.utils import glog

        logger = logging.getLogger("weed")
        old_level, old_handlers = logger.level, list(logger.handlers)
        try:
            glog.init(level=logging.WARNING)
            assert logger.level == logging.WARNING
            glog.init(level=logging.DEBUG)
            assert logger.level == logging.DEBUG
        finally:
            logger.setLevel(old_level)
            logger.handlers[:] = old_handlers

    def test_trace_prefix_when_sampled(self):
        from seaweedfs_tpu.utils.glog import _trace_prefix_filter

        rec = logging.LogRecord("weed", logging.INFO, "f", 1, "m", (),
                                None)
        ctx = trace_context.TraceContext("cd" * 16)
        prev = trace_context.activate(ctx)
        try:
            _trace_prefix_filter(rec)
            assert rec.trace == f"[trace {'cd' * 16}] "
        finally:
            trace_context.activate(prev)
        # unsampled / no decision: empty prefix, never an error
        _trace_prefix_filter(rec)
        assert rec.trace == ""
        prev = trace_context.activate(trace_context.NOT_SAMPLED)
        try:
            _trace_prefix_filter(rec)
            assert rec.trace == ""
        finally:
            trace_context.activate(prev)


def test_default_rules_cover_health_families():
    from seaweedfs_tpu.stats.aggregate import HEALTH_FAMILIES

    watched = {r.params.get("key") for r in default_rules()
               if r.kind == "counter_increase"}
    assert watched == set(HEALTH_FAMILIES)
    kinds = {r.kind for r in default_rules()}
    assert kinds == {"counter_increase", "threshold", "peer_down",
                     "burn_rate", "journal_event"}


def test_degraded_bind_event_reaches_cluster_journal(tmp_path):
    """A degraded TCP bind happens DURING server startup — the event
    shipper must already be hooked (attach before the bind attempts,
    no backfill exists) and the event must carry the server's own
    identity even with co-located shippers."""
    import socket

    from seaweedfs_tpu.master.server import MasterServer
    from seaweedfs_tpu.utils.framing import tcp_port_for
    from seaweedfs_tpu.utils.httpd import http_json
    from seaweedfs_tpu.volume_server.server import VolumeServer

    from tests.conftest import free_port

    master = MasterServer(port=free_port(), pulse_seconds=0.4).start()
    vport = free_port()
    blocker = socket.socket()
    blocker.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
    blocker.bind(("127.0.0.1", tcp_port_for(vport)))
    blocker.listen(1)
    vs = VolumeServer([], master.url, port=vport,
                      pulse_seconds=0.4).start()
    try:
        deadline = time.time() + 10
        ev = None
        while time.time() < deadline:
            evs = http_json(
                "GET", f"http://{master.url}/cluster/events"
                       "?type=degraded_bind")
            if evs["count"]:
                ev = evs["events"][-1]
                break
            time.sleep(0.2)
        assert ev is not None, "degraded_bind never shipped"
        assert ev["details"]["role"] == "volume-tcp"
        assert ev["server"] == vs.url
    finally:
        vs.stop()
        master.stop()
        blocker.close()


# --- live drill ------------------------------------------------------------

@pytest.fixture()
def tracer():
    from seaweedfs_tpu.observability import (disable_tracing,
                                             enable_tracing)

    tr = enable_tracing()
    tr.clear()
    try:
        yield tr
    finally:
        disable_tracing()
        tr.clear()


def test_live_corrupt_shard_drill(tmp_path, tracer):
    """The acceptance drill: inject ec.shard.corrupt on a live volume
    server; WITHOUT manual polling the master's telemetry loop must
    produce a firing /cluster/alerts entry, correlated /cluster/events
    records carrying the scrub pass's trace id, and fetchable
    flight-recorder bundles containing the trace dump and metrics
    snapshot."""
    from seaweedfs_tpu.master.server import MasterServer
    from seaweedfs_tpu.shell import CommandEnv, run_command
    from seaweedfs_tpu.storage.needle import Needle
    from seaweedfs_tpu.storage.volume import Volume
    from seaweedfs_tpu.utils import faultinject as fi
    from seaweedfs_tpu.utils.httpd import http_bytes, http_json
    from seaweedfs_tpu.volume_server.server import VolumeServer

    from tests.conftest import free_port

    d = tmp_path / "vs0"
    d.mkdir()
    v = Volume(str(d), "", 1)
    for i in range(1, 60):
        v.write_needle(Needle(cookie=i, id=i, data=rng.bytes(500)))
    v.close()
    master = MasterServer(port=free_port(), pulse_seconds=0.4,
                          metrics_aggregation_seconds=0.25).start()
    master.aggregator.min_interval = 0.0
    master.alert_engine.min_interval = 0.0
    vs = VolumeServer([str(d)], master.url, port=free_port(),
                      pulse_seconds=0.4).start()
    try:
        deadline = time.time() + 5
        while time.time() < deadline and not master.topo.all_nodes():
            time.sleep(0.05)
        vs.store.ec_generate(1)
        vs.store.ec_mount(1)
        # let the loop establish counter baselines BEFORE the injection
        # (the engine never fires on first sight of a nonzero counter)
        deadline = time.time() + 5
        while time.time() < deadline and \
                not master.alert_engine.evaluations:
            time.sleep(0.05)
        # the injected bit rot: scrub's verify reads shard 11 flipped
        fi.enable("ec.shard.corrupt",
                  params={"shard": 11, "offset": 4096, "bit": 0},
                  max_hits=1)
        r = http_json("POST", f"http://{vs.url}/ec/scrub/start",
                      {"rate_mb_s": 0})
        assert r["started"] is True

        # 1. the alert fires AUTONOMOUSLY (nobody calls evaluate here)
        deadline = time.time() + 20
        firing = {}
        while time.time() < deadline:
            firing = {a["name"]: a for a in
                      master.alert_engine.to_dict()["alerts"]
                      if a["state"] == "firing"}
            if "corrupt_shards_increase" in firing:
                break
            time.sleep(0.1)
        assert "corrupt_shards_increase" in firing, firing
        assert firing["corrupt_shards_increase"]["servers"] == [vs.url]

        # 2. correlated journal entries carry the scrub's trace id —
        #    shard_corrupt and scrub_repair share ONE trace (the pass),
        #    attributed to the volume server
        deadline = time.time() + 10
        corrupt_ev = repair_ev = None
        while time.time() < deadline:
            evs = http_json(
                "GET", f"http://{master.url}/cluster/events?limit=100")
            by_type = {}
            for e in evs["events"]:
                by_type.setdefault(e["type"], e)
            corrupt_ev = by_type.get("shard_corrupt")
            repair_ev = by_type.get("scrub_repair")
            if corrupt_ev and repair_ev:
                break
            time.sleep(0.1)
        assert corrupt_ev and repair_ev, "events never reached master"
        scrub_trace = corrupt_ev.get("trace", "")
        assert len(scrub_trace) == 32
        assert repair_ev.get("trace") == scrub_trace
        assert corrupt_ev.get("server") == vs.url
        assert corrupt_ev["details"]["shard"] == 11
        # the firing alert self-heals its exemplar to that trace
        deadline = time.time() + 10
        exemplar = ""
        while time.time() < deadline:
            a = {x["name"]: x for x in
                 master.alert_engine.to_dict()["alerts"]}
            exemplar = a["corrupt_shards_increase"].get(
                "exemplar_trace", "")
            if exemplar:
                break
            time.sleep(0.1)
        assert exemplar == scrub_trace

        # 3. flight-recorder bundles captured and fetchable
        deadline = time.time() + 15
        bundles = []
        while time.time() < deadline:
            doc = http_json("GET", f"http://{master.url}/cluster/alerts"
                                   "?state=firing")
            for a in doc["alerts"]:
                if a["name"] == "corrupt_shards_increase" and \
                        a.get("bundles"):
                    bundles = a["bundles"]
            if bundles:
                break
            time.sleep(0.2)
        ok = [b for b in bundles if b.get("id")]
        assert ok, bundles
        bid, bsrv = ok[0]["id"], ok[0]["server"]
        listing = http_json("GET",
                            f"http://{bsrv}/debug/flightrecorder")
        assert any(b["id"] == bid for b in listing["bundles"])
        bdoc = http_json("GET",
                         f"http://{bsrv}/debug/flightrecorder/{bid}")
        assert bdoc["meta"]["alert"] == "corrupt_shards_increase"
        # the bundle freezes the evidence: trace dump with the scrub's
        # spans, a metrics exposition, and the event tail
        span_names = {s["name"] for s in bdoc["trace"]["spans"]}
        assert "ec.scrub.pass" in span_names
        assert "SeaweedFS_ec_corrupt_shards_total" in bdoc["metrics"]
        assert any(e["type"] == "shard_corrupt"
                   for e in bdoc["events"])

        # 4. per-server journal serves the same story locally
        local = http_json("GET", f"http://{vs.url}/debug/events"
                                 "?type=shard_corrupt")
        assert local["count"] >= 1

        # 5. shell ergonomics: stable text + json, and cluster.health
        #    carries the one-line alerts rollup
        env = CommandEnv(master.url)
        out = run_command(env, "alerts.list -firing")
        assert "corrupt_shards_increase" in out and "firing" in out
        parsed = json.loads(run_command(env, "alerts.list -json"))
        assert parsed["firing"] >= 1
        out = run_command(env, "events.tail -n 50 -type shard_corrupt")
        assert "shard_corrupt" in out and scrub_trace in out
        out = run_command(env, "cluster.health")
        assert any(line.startswith("alerts:") and "firing" in line
                   for line in out.splitlines())
        cap = run_command(env, f"alerts.capture -server {vs.url} "
                               "-reason drill")
        assert "bundle fr-" in cap

        # 6. a 5xx bumps the per-route error counter (burn-rate
        #    numerator): garbage JSON into an ingest route
        status, _, _ = http_bytes(
            "POST", f"http://{master.url}/cluster/events/ingest",
            b"not json", headers={"Content-Type": "application/json"})
        assert status == 500
        from seaweedfs_tpu.stats import master_metrics

        errs = master_metrics().request_errors.snapshot()
        assert errs.get(("cluster_events_ingest",), 0) >= 1
    finally:
        fi.clear()
        vs.stop()
        master.stop()
