"""weed fix / compact / export CLI commands (command/{fix,compact,export}.go)."""

from __future__ import annotations

import os
import subprocess
import sys
import tarfile

from seaweedfs_tpu.storage.needle import Needle
from seaweedfs_tpu.storage.volume import Volume

WEED = os.path.join(os.path.dirname(os.path.dirname(__file__)), "weed.py")


def _run(*argv):
    return subprocess.run([sys.executable, WEED, *argv],
                          capture_output=True, text=True, timeout=120,
                          env={**os.environ,
                               "PYTHONPATH": os.path.dirname(WEED)})


def _make_volume(tmp_path, vid=7):
    v = Volume(str(tmp_path), "", vid)
    for i in range(1, 6):
        n = Needle(cookie=i, id=i, data=b"data-%d" % i)
        n.name = b"file%d.txt" % i
        from seaweedfs_tpu.storage.needle import FLAG_HAS_NAME

        n.set_flag(FLAG_HAS_NAME)
        v.write_needle(n)
    v.delete_needle(Needle(cookie=2, id=2))
    v.close()
    return vid


def test_fix_rebuilds_idx(tmp_path):
    vid = _make_volume(tmp_path)
    idx = tmp_path / f"{vid}.idx"
    original = idx.read_bytes()
    idx.write_bytes(b"garbage!")  # corrupt the index
    r = _run("fix", "-dir", str(tmp_path), "-volumeId", str(vid))
    assert r.returncode == 0, r.stderr
    assert "scanned 6 records (4 live)" in r.stdout
    # the volume opens and serves from the rebuilt index
    v = Volume(str(tmp_path), "", vid)
    try:
        assert v.read_needle(1).data == b"data-1"
        assert v.read_needle(5).data == b"data-5"
        import pytest

        with pytest.raises(KeyError):
            v.read_needle(2)
    finally:
        v.close()
    # fix appends entries in .dat scan order with live-path tombstone
    # shape, so the rebuilt index is byte-identical to the original
    # live-written log
    assert idx.read_bytes() == original


def test_compact_command(tmp_path):
    vid = _make_volume(tmp_path)
    before = (tmp_path / f"{vid}.dat").stat().st_size
    r = _run("compact", "-dir", str(tmp_path), "-volumeId", str(vid))
    assert r.returncode == 0, r.stderr
    assert (tmp_path / f"{vid}.dat").stat().st_size < before
    v = Volume(str(tmp_path), "", vid)
    try:
        assert v.read_needle(3).data == b"data-3"
    finally:
        v.close()


def test_export_list_and_tar(tmp_path):
    vid = _make_volume(tmp_path)
    r = _run("export", "-dir", str(tmp_path), "-volumeId", str(vid))
    assert r.returncode == 0, r.stderr
    assert "file3.txt" in r.stdout
    assert "id 2" not in r.stdout  # deleted: hidden by default
    out = str(tmp_path / "vol.tar")
    r = _run("export", "-dir", str(tmp_path), "-volumeId", str(vid),
             "-o", out)
    assert r.returncode == 0, r.stderr
    with tarfile.open(out) as t:
        names = t.getnames()
        assert "1_file1.txt" in names and len(names) == 4
        assert t.extractfile("5_file5.txt").read() == b"data-5"


def test_filer_cat_copy_meta_tail(tmp_path):
    """filer.copy uploads a tree, filer.cat reads it back, scaffold emits
    templates (command/{filer_copy,filer_cat,scaffold}.go)."""
    import time

    from seaweedfs_tpu.filer.filer_store import MemoryStore
    from seaweedfs_tpu.filer.server import FilerServer
    from seaweedfs_tpu.master.server import MasterServer
    from seaweedfs_tpu.volume_server.server import VolumeServer
    from tests.conftest import free_port

    master = MasterServer(port=free_port(), pulse_seconds=0.3).start()
    d = tmp_path / "v"
    d.mkdir()
    vs = VolumeServer([str(d)], master.url, port=free_port(),
                      pulse_seconds=0.3).start()
    deadline = time.time() + 5
    while time.time() < deadline and not master.topo.all_nodes():
        time.sleep(0.05)
    filer = FilerServer(master.url, MemoryStore(), port=free_port()).start()
    try:
        src = tmp_path / "tree"
        (src / "sub").mkdir(parents=True)
        (src / "a.txt").write_bytes(b"alpha")
        (src / "sub" / "b.txt").write_bytes(b"beta")
        r = _run("filer.copy", "-filer", filer.url, str(src), "/imported")
        assert r.returncode == 0, r.stderr
        r = _run("filer.cat", "-filer", filer.url, "/imported/tree/a.txt")
        assert r.returncode == 0 and r.stdout == "alpha"
        r = _run("filer.cat", "-filer", filer.url,
                 "/imported/tree/sub/b.txt")
        assert r.stdout == "beta"
    finally:
        filer.stop()
        vs.stop()
        master.stop()


def test_scaffold_and_version():
    r = _run("version")
    assert r.returncode == 0 and "seaweedfs-tpu" in r.stdout
    for name in ("security", "filer", "replication", "master",
                 "notification", "shell"):
        r = _run("scaffold", "-config", name)
        assert r.returncode == 0 and r.stdout.strip(), name


def test_autocomplete_emits_bash_completion(capsys):
    import weed

    try:
        weed.main(["autocomplete"])
    except SystemExit:
        pass
    out = capsys.readouterr().out
    assert "complete -F _weed_complete" in out
    for cmd in ("master", "volume", "filer", "benchmark", "shell"):
        assert cmd in out


def test_fix_preserves_idx_on_malformed_dat(tmp_path):
    """A corrupt .dat superblock must not cost the operator the only
    surviving index: fix builds to a temp file and renames on success."""
    vid = _make_volume(tmp_path)
    idx = tmp_path / f"{vid}.idx"
    original = idx.read_bytes()
    (tmp_path / f"{vid}.dat").write_bytes(b"\xde\xad")  # malformed
    r = _run("fix", "-dir", str(tmp_path), "-volumeId", str(vid))
    assert r.returncode != 0
    assert idx.read_bytes() == original  # untouched
    assert not (tmp_path / f"{vid}.idx_fix").exists()


def test_filer_copy_include_concurrency_checksize(tmp_path):
    """filer.copy parity flags: -include glob, -c workers, -check.size
    skip-unchanged (command/filer_copy.go:54-62)."""
    import time

    from seaweedfs_tpu.filer.filer_store import MemoryStore
    from seaweedfs_tpu.filer.server import FilerServer
    from seaweedfs_tpu.master.server import MasterServer
    from seaweedfs_tpu.volume_server.server import VolumeServer
    from tests.conftest import free_port

    master = vs = filer = None
    try:
        master = MasterServer(port=free_port(), pulse_seconds=0.3).start()
        d = tmp_path / "v"
        d.mkdir()
        vs = VolumeServer([str(d)], master.url, port=free_port(),
                          pulse_seconds=0.3).start()
        deadline = time.time() + 5
        while time.time() < deadline and not master.topo.all_nodes():
            time.sleep(0.05)
        filer = FilerServer(master.url, MemoryStore(),
                            port=free_port()).start()
        src = tmp_path / "tree"
        src.mkdir()
        (src / "a.pdf").write_bytes(b"pdf-a")
        (src / "b.txt").write_bytes(b"txt-b")
        (src / "c.pdf").write_bytes(b"pdf-c")
        r = _run("filer.copy", "-filer", filer.url, "-include", "*.pdf",
                 "-c", "2", str(src), "/docs")
        assert r.returncode == 0, r.stderr
        names = [e.name for e in filer.filer.list_directory("/docs/tree")]
        assert sorted(names) == ["a.pdf", "c.pdf"]  # b.txt filtered
        # -check.size: second run skips unchanged files
        r = _run("filer.copy", "-filer", filer.url, "-include", "*.pdf",
                 "-check.size", str(src), "/docs")
        assert r.returncode == 0, r.stderr
        assert r.stdout.count("same size, skipped") == 2
    finally:
        for srv in (filer, vs, master):
            if srv is not None:
                srv.stop()


def test_upload_dir_include_ttl(tmp_path):
    """weed upload -dir -include -ttl (command/upload.go:39-45)."""
    import json as _json
    import time

    from seaweedfs_tpu.master.server import MasterServer
    from seaweedfs_tpu.volume_server.server import VolumeServer
    from tests.conftest import free_port

    master = vs = None
    try:
        master = MasterServer(port=free_port(), pulse_seconds=0.3).start()
        d = tmp_path / "v"
        d.mkdir()
        vs = VolumeServer([str(d)], master.url, port=free_port(),
                          pulse_seconds=0.3).start()
        deadline = time.time() + 5
        while time.time() < deadline and not master.topo.all_nodes():
            time.sleep(0.05)
        src = tmp_path / "up"
        src.mkdir()
        (src / "x.log").write_bytes(b"log")
        (src / "y.dat").write_bytes(b"dat")
        r = _run("upload", "-master", master.url, "-dir", str(src),
                 "-include", "*.log", "-ttl", "1h")
        assert r.returncode == 0, r.stderr
        lines = [_json.loads(line) for line in r.stdout.splitlines()]
        assert len(lines) == 1 and lines[0]["file"].endswith("x.log")
        # the fid serves the bytes back
        from seaweedfs_tpu.client.operation import WeedClient

        assert WeedClient(master.url).download(lines[0]["fid"]) == b"log"
        # no inputs at all is a clean error
        r = _run("upload", "-master", master.url)
        assert r.returncode != 0
    finally:
        for srv in (vs, master):
            if srv is not None:
                srv.stop()
