"""weed fix / compact / export CLI commands (command/{fix,compact,export}.go)."""

from __future__ import annotations

import os
import subprocess
import sys
import tarfile

from seaweedfs_tpu.storage.needle import Needle
from seaweedfs_tpu.storage.volume import Volume

WEED = os.path.join(os.path.dirname(os.path.dirname(__file__)), "weed.py")


def _run(*argv):
    return subprocess.run([sys.executable, WEED, *argv],
                          capture_output=True, text=True, timeout=120,
                          env={**os.environ,
                               "PYTHONPATH": os.path.dirname(WEED)})


def _make_volume(tmp_path, vid=7):
    v = Volume(str(tmp_path), "", vid)
    for i in range(1, 6):
        n = Needle(cookie=i, id=i, data=b"data-%d" % i)
        n.name = b"file%d.txt" % i
        from seaweedfs_tpu.storage.needle import FLAG_HAS_NAME

        n.set_flag(FLAG_HAS_NAME)
        v.write_needle(n)
    v.delete_needle(Needle(cookie=2, id=2))
    v.close()
    return vid


def test_fix_rebuilds_idx(tmp_path):
    vid = _make_volume(tmp_path)
    idx = tmp_path / f"{vid}.idx"
    original = idx.read_bytes()
    idx.write_bytes(b"garbage!")  # corrupt the index
    r = _run("fix", "-dir", str(tmp_path), "-volumeId", str(vid))
    assert r.returncode == 0, r.stderr
    assert "wrote 4 live entries" in r.stdout
    # the volume opens and serves from the rebuilt index
    v = Volume(str(tmp_path), "", vid)
    try:
        assert v.read_needle(1).data == b"data-1"
        assert v.read_needle(5).data == b"data-5"
        import pytest

        with pytest.raises(KeyError):
            v.read_needle(2)
    finally:
        v.close()
    assert len(idx.read_bytes()) % 16 == 0 and idx.read_bytes() != original


def test_compact_command(tmp_path):
    vid = _make_volume(tmp_path)
    before = (tmp_path / f"{vid}.dat").stat().st_size
    r = _run("compact", "-dir", str(tmp_path), "-volumeId", str(vid))
    assert r.returncode == 0, r.stderr
    assert (tmp_path / f"{vid}.dat").stat().st_size < before
    v = Volume(str(tmp_path), "", vid)
    try:
        assert v.read_needle(3).data == b"data-3"
    finally:
        v.close()


def test_export_list_and_tar(tmp_path):
    vid = _make_volume(tmp_path)
    r = _run("export", "-dir", str(tmp_path), "-volumeId", str(vid))
    assert r.returncode == 0, r.stderr
    assert "file3.txt" in r.stdout
    assert "id 2" not in r.stdout  # deleted: hidden by default
    out = str(tmp_path / "vol.tar")
    r = _run("export", "-dir", str(tmp_path), "-volumeId", str(vid),
             "-o", out)
    assert r.returncode == 0, r.stderr
    with tarfile.open(out) as t:
        names = t.getnames()
        assert "1_file1.txt" in names and len(names) == 4
        assert t.extractfile("5_file5.txt").read() == b"data-5"
