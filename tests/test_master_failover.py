"""Master HA acceptance: raft election-safety properties, snapshot
catch-up with state-hash equality, and the live 3-master failover drill.

Three layers, cheapest first:

  * property-style unit tests drive RaftNode.handle_vote /
    handle_append directly — term monotonicity, single-vote-per-term,
    stale-term append rejection, the log up-to-dateness election
    restriction, split-vote re-campaigning, and at-most-one-leader-
    per-term under randomized vote traffic;
  * a restarted third master whose needed entries were compacted away
    catches up via InstallSnapshot and then serves an IDENTICAL
    /cluster/events + /cluster/coordinator view (sha256 state-hash
    equality over the journal and replicated repair records);
  * the scenarios/failover.py drill kills the leader mid write-storm
    and mid EC repair and machine-checks election time, zero journal
    loss, post-failover assign latency, and re-planned repair cause
    attribution (the spec's expectations -> verdict).
"""

from __future__ import annotations

import hashlib
import json
import random
import time

from seaweedfs_tpu.master.consensus import RaftNode
from seaweedfs_tpu.master.server import MasterServer
from seaweedfs_tpu.utils.httpd import http_json
from tests.conftest import free_port
from tests.test_consensus import _wait_one_leader


# --- election-safety properties (no servers, direct RPC handlers) ---------

def _voter(me: str = "127.0.0.1:9001",
           peers: tuple = ("127.0.0.1:9002", "127.0.0.1:9003")) -> RaftNode:
    return RaftNode(me, list(peers), read_state=lambda: {})


def _entry(index: int, term: int) -> dict:
    return {"index": index, "term": term, "kind": "event", "data": {}}


class TestElectionSafety:
    def test_term_monotonic_under_random_rpcs(self):
        """A node's current term (and every response term) never
        decreases, whatever interleaving of vote/append RPCs arrives."""
        rng = random.Random(0x5AFE)
        node = _voter()
        prev = node.term
        for _ in range(300):
            term = rng.randrange(0, 40)
            if rng.random() < 0.5:
                r = node.handle_vote(
                    term, rng.choice(["127.0.0.1:9002", "127.0.0.1:9003"]),
                    None, rng.randrange(0, 4), rng.randrange(0, 4))
            else:
                r = node.handle_append(term, "127.0.0.1:9002", state=None,
                                       prev_index=0, prev_term=0,
                                       entries=[], commit=0)
            assert r["term"] >= prev
            assert node.term >= prev
            assert r["term"] == node.term
            prev = node.term

    def test_single_vote_per_term(self):
        node = _voter()
        assert node.handle_vote(4, "127.0.0.1:9002")["granted"] is True
        # same term, different candidate: denied (vote already cast)
        assert node.handle_vote(4, "127.0.0.1:9003")["granted"] is False
        # same term, same candidate (retransmitted request): re-granted
        assert node.handle_vote(4, "127.0.0.1:9002")["granted"] is True
        # stale term: denied outright, current term echoed back
        r = node.handle_vote(3, "127.0.0.1:9003")
        assert r["granted"] is False and r["term"] == 4

    def test_stale_term_append_rejected(self):
        node = _voter()
        r = node.handle_append(5, "127.0.0.1:9002", prev_index=0,
                               prev_term=0, entries=[_entry(1, 5)],
                               commit=1)
        assert r["ok"] is True
        assert node.term == 5 and node.leader == "127.0.0.1:9002"
        # a deposed leader's append from an older term must not mutate
        # the log, the commit index, or the known-leader pointer
        stale = node.handle_append(3, "127.0.0.1:9003", prev_index=1,
                                   prev_term=3, entries=[_entry(2, 3)],
                                   commit=2)
        assert stale["ok"] is False and stale["term"] == 5
        assert node.leader == "127.0.0.1:9002"
        assert node.log.last_index == 1 and node.commit_index == 1

    def test_vote_denied_to_candidate_with_stale_log(self):
        """Raft's election restriction: the winner must hold every
        committed entry, so votes compare (last_term, last_index)."""
        node = _voter()
        node.handle_append(2, "127.0.0.1:9002", prev_index=0, prev_term=0,
                           entries=[_entry(1, 2), _entry(2, 2)], commit=2)
        # older last term loses regardless of log length
        assert node.handle_vote(5, "127.0.0.1:9003",
                                None, 9, 1)["granted"] is False
        # same last term but shorter log loses
        assert node.handle_vote(6, "127.0.0.1:9003",
                                None, 1, 2)["granted"] is False
        # same last term, same length: at least as up-to-date, granted
        assert node.handle_vote(7, "127.0.0.1:9003",
                                None, 2, 2)["granted"] is True

    def test_split_vote_recampaigns_with_fresh_term(self):
        """A candidate that cannot assemble a quorum (peers down /
        votes split) stays a candidate and re-campaigns under a NEW
        term — it never declares itself leader on a partial tally."""
        node = RaftNode("127.0.0.1:9201",
                        [f"127.0.0.1:{free_port()}",
                         f"127.0.0.1:{free_port()}"],
                        read_state=lambda: {})
        t0 = node.term
        node._campaign()  # both peers unreachable: self-vote only
        assert node.role == "candidate" and node.term == t0 + 1
        node._campaign()
        assert node.role == "candidate" and node.term == t0 + 2
        assert node.voted_for == node.me

    def test_at_most_one_leader_per_term_randomized(self):
        """Randomized split-vote traffic over a 5-node electorate:
        whenever a candidate assembles a quorum of grants for a term,
        no other candidate can for the SAME term (vote stickiness +
        term monotonicity make grant quorums exclusive)."""
        rng = random.Random(0xE1EC7)
        names = [f"127.0.0.1:{9100 + i}" for i in range(5)]
        voters = {n: RaftNode(n, [p for p in names if p != n],
                              read_state=lambda: {})
                  for n in names}
        quorum = len(names) // 2 + 1
        winners: dict[int, set] = {}
        for _ in range(400):
            term = rng.randrange(1, 30)
            cand = rng.choice(names)
            granted = sum(
                1 for v in voters.values()
                if v.handle_vote(term, cand, None, 0, 0)["granted"])
            if granted >= quorum:
                winners.setdefault(term, set()).add(cand)
        assert winners, "no term ever reached quorum — test is inert"
        for term, who in winners.items():
            assert len(who) == 1, \
                f"two leaders elected in term {term}: {sorted(who)}"


# --- snapshot catch-up: the state-hash equality contract ------------------

def _view(m: MasterServer) -> dict:
    """What /cluster/events + /cluster/coordinator serve, read off the
    Python objects (the HTTP routes are leader-gated on followers)."""
    return {"events": m.event_journal.query(limit=0),
            "coordinator": m.coordinator.export_replicated()}


def _state_hash(view: dict) -> str:
    return hashlib.sha256(
        json.dumps(view, sort_keys=True).encode()).hexdigest()


def test_restarted_master_catches_up_via_snapshot(tmp_path):
    """Stop one of three masters, push the replicated journal past the
    compaction threshold so its needed entries no longer exist as log
    entries, then restart it: the leader must bring it back with an
    InstallSnapshot (snapshots_installed > 0) and its /cluster/events
    + /cluster/coordinator views must be byte-identical to the
    leader's (and to the never-restarted follower's)."""
    ports = [free_port() for _ in range(3)]
    urls = [f"127.0.0.1:{p}" for p in ports]
    masters = []
    for i, p in enumerate(ports):
        m = MasterServer(port=p,
                         peers=[u for j, u in enumerate(urls) if j != i],
                         mdir=str(tmp_path / f"m{i}"), pulse_seconds=0.3)
        m.raft.snapshot_threshold = 8  # compact early: drill scale
        masters.append(m.start())
    try:
        leader = _wait_one_leader(masters)
        followers = [m for m in masters if m is not leader]
        # detach ALL event shippers: with co-located masters every
        # master's shipper short-circuits process events into its OWN
        # journal (via=itself), so any background emission in this
        # process (including stragglers from earlier tests) lands with
        # three different `via` labels and the id-dedup'd journals can
        # never reconverge.  With no shippers, the POST ingest below is
        # the only fill path and the state hashes are deterministic.
        for m in masters:
            m._event_shipper.detach()
        victim = followers[-1]
        vi = masters.index(victim)
        victim_last = victim.raft.log.last_index
        victim.stop()
        masters.remove(victim)

        # journal traffic while the third master is down: one raft
        # entry per batch, far past the snapshot threshold
        want = {f"catchup-{i}" for i in range(40)}
        for i in range(40):
            http_json("POST",
                      f"http://{leader.url}/cluster/events/ingest",
                      {"server": "drill",
                       "events": [{"id": f"catchup-{i}",
                                   "type": "drill_marker",
                                   "severity": "info", "server": "drill",
                                   "ts": round(time.time(), 3),
                                   "details": {"i": i}}]})
        # and one replicated repair record (the coordinator leg)
        rec = {"id": "77:planned:1.000", "op": "planned", "vid": 77,
               "at": 1.0, "alert": "ec_under_replicated",
               "cause_trace": "ab" * 16, "cause_event": "catchup-0"}
        leader.coordinator.apply_replicated(rec)
        leader._replicate_coordinator_record(rec)

        deadline = time.time() + 20
        while time.time() < deadline and \
                leader.raft.log.base_index <= victim_last:
            time.sleep(0.1)
        assert leader.raft.log.base_index > victim_last, \
            f"log never compacted past the stopped master " \
            f"(base={leader.raft.log.base_index}, victim={victim_last})"

        # restart on the SAME address + mdir (a rebooted process)
        m3 = MasterServer(port=ports[vi],
                          peers=[u for u in urls if u != urls[vi]],
                          mdir=str(tmp_path / f"m{vi}"),
                          pulse_seconds=0.3)
        m3.raft.snapshot_threshold = 8
        m3.start()
        m3._event_shipper.detach()
        masters.append(m3)

        deadline = time.time() + 25
        while time.time() < deadline:
            ids = {e["id"] for e in m3.event_journal.query(limit=0)}
            if m3.raft.snapshots_installed > 0 and want <= ids:
                break
            time.sleep(0.1)
        assert m3.raft.snapshots_installed > 0, \
            f"no InstallSnapshot received; raft={m3.raft.status()}"
        ids = {e["id"] for e in m3.event_journal.query(limit=0)}
        assert want <= ids, f"missing events: {sorted(want - ids)[:5]}"

        # state-hash equality: all three masters serve the same views.
        # Background emissions (alert transitions, shipped snapshots) may
        # still be replicating when we get here, so poll both sides until
        # they converge instead of comparing a single racy instant.
        for m in masters:
            if m is leader:
                continue
            conv_deadline = time.time() + 10
            while True:
                leader_view = _view(leader)
                v = _view(m)
                if _state_hash(v) == _state_hash(leader_view):
                    break
                if time.time() >= conv_deadline:
                    mine = {e["id"]: e for e in v["events"]}
                    theirs = {e["id"]: e for e in leader_view["events"]}
                    diff = [eid for eid in theirs
                            if mine.get(eid) != theirs[eid]]
                    raise AssertionError(
                        f"state hash mismatch on {m.url}: "
                        f"missing/differing events {diff[:5]}, "
                        f"extra {sorted(set(mine) - set(theirs))[:5]}, "
                        f"first diff: mine={mine.get(diff[0]) if diff else None} "
                        f"theirs={theirs[diff[0]] if diff else None}, "
                        f"coordinator mine={v['coordinator']} "
                        f"theirs={leader_view['coordinator']}")
                time.sleep(0.2)
        assert leader.coordinator.export_replicated()["pending"] \
            .get("77", {}).get("cause_trace") == "ab" * 16

        # the operator surface over the same facts: cluster.raft walks
        # every master and cluster.health carries the quorum line
        from seaweedfs_tpu.shell import CommandEnv, run_command

        out = run_command(CommandEnv(",".join(urls)), "cluster.raft")
        assert out.splitlines()[0].startswith("masters: 3 (leader ")
        assert f"leader {leader.url}" in out
        assert out.count("term=") == 3  # one row per master
        assert "installed=1" in out  # m3's InstallSnapshot is visible
        doc = json.loads(run_command(CommandEnv(urls[0]),
                                     "cluster.raft -json"))
        assert set(doc["masters"]) == set(urls)
        health = run_command(CommandEnv(leader.url), "cluster.health")
        assert f"masters: 3 (leader {leader.url}, term " in health
    finally:
        for m in masters:
            m.stop()


# --- autoscaler HA: kill-during-replica-add --------------------------------

def test_leader_kill_during_replica_add_no_duplicate(tmp_path):
    """The heat autoscaler's grow_planned record rides the raft log
    BEFORE the copy executes, so a leader killed mid-replica-add leaves
    its plan on a quorum and the promoted leader RESUMES it — never
    duplicates it.  Both kill windows, against real volume servers:

      * vid 1: the old leader's copy already LANDED (the dst holds the
        volume) but grow_done was never recorded — the new leader must
        close the plan with ZERO further volume_copy calls;
      * vid 2: the copy never started — the new leader re-executes it
        exactly once, to the SAME raft-recorded destination.

    In both cases the original flash-crowd cause attribution (alert id
    + exemplar trace + causing event) survives the election."""
    import os as _os

    from seaweedfs_tpu.volume_server.server import VolumeServer

    ports = [free_port() for _ in range(3)]
    urls = [f"127.0.0.1:{p}" for p in ports]
    masters = []
    for i, p in enumerate(ports):
        masters.append(MasterServer(
            port=p, peers=[u for j, u in enumerate(urls) if j != i],
            mdir=str(tmp_path / f"m{i}"), pulse_seconds=0.3).start())
    servers = []
    try:
        leader = _wait_one_leader(masters)
        master_list = ",".join(urls)
        for i in range(2):
            root = str(tmp_path / f"v{i}")
            _os.makedirs(root, exist_ok=True)
            servers.append(VolumeServer(
                [root], master_list, port=free_port(), rack=f"r{i}",
                data_center="dc1", pulse_seconds=0.3,
                max_volume_count=8).start())
        src, dst = servers[0].url, servers[1].url
        for vid in (1, 2):
            http_json("POST", f"http://{src}/admin/assign_volume",
                      {"volume_id": vid})
        deadline = time.time() + 15
        while time.time() < deadline:
            with leader.topo.lock:
                nodes = {n.url: set(n.volumes)
                         for n in leader.topo.all_nodes()}
            if len(nodes) == 2 and {1, 2} <= nodes.get(src, set()):
                break
            time.sleep(0.1)
        else:
            raise AssertionError(f"topology never converged: {nodes}")

        # the flash crowd names both volumes (cause attribution source)
        trace = "cd" * 16
        leader.autoscaler.on_events([
            {"id": f"evt-fc-{vid}", "type": "flash_crowd",
             "trace": trace, "details": {"volume": vid}}
            for vid in (1, 2)])

        # the old leader plans both grows (quorum-replicated), then
        # dies mid-actuation: vid 1 AFTER its copy landed, vid 2 before
        auto = leader.autoscaler
        auto._record("grow_planned", 1, auto._cause(1), dst=dst,
                     src=src, share=0.9)
        auto.executor.admin_post(dst, "/admin/volume_copy", {
            "volume_id": 1, "collection": "",
            "source_data_node": src})
        auto.executor.refresh_heartbeats([dst])
        auto._record("grow_planned", 2, auto._cause(2), dst=dst,
                     src=src, share=0.4)
        leader.stop()
        masters.remove(leader)

        new_leader = _wait_one_leader(masters, timeout=20.0)
        # the promoted leader must SEE the landed copy before resuming
        deadline = time.time() + 15
        while time.time() < deadline:
            with new_leader.topo.lock:
                holders = {vid: [n.url for n in new_leader.topo
                                 .all_nodes() if vid in n.volumes]
                           for vid in (1, 2)}
            if sorted(holders[1]) == sorted([src, dst]) \
                    and holders[2] == [src]:
                break
            time.sleep(0.1)
        else:
            raise AssertionError(f"post-failover topology: {holders}")

        copies = []
        real_post = new_leader.autoscaler.executor._post_fn

        def counting_post(server, path, payload, timeout):
            if path == "/admin/volume_copy":
                copies.append((server, payload.get("volume_id")))
            return real_post(server, path, payload, timeout)

        new_leader.autoscaler.executor._post_fn = counting_post
        out = new_leader.autoscaler.run_cycle()
        assert out["resumed"] == 2, out

        # vid 1: closed without re-copying — zero duplicate adds
        assert [c for c in copies if c[1] == 1] == []
        # vid 2: exactly one copy, to the raft-recorded destination
        assert [c for c in copies if c[1] == 2] == [(dst, 2)]
        doc = new_leader.autoscaler.export_replicated()
        assert doc["pending"] == {}  # both plans closed
        done = {r["vid"]: r for r in doc["log"]
                if r["op"] == "grow_done"}
        for vid in (1, 2):
            assert done[vid]["resumed_from"], done[vid]
            assert done[vid]["cause_trace"] == trace
            assert done[vid]["alert"] == "flash_crowd"
            assert done[vid]["cause_event"] == f"evt-fc-{vid}"
            assert done[vid]["dst"] == dst
        # exactly two holders each — nothing grew twice anywhere
        new_leader.autoscaler.executor.refresh_heartbeats([src, dst])
        deadline = time.time() + 15
        while time.time() < deadline:
            with new_leader.topo.lock:
                holders = {vid: sorted(n.url for n in new_leader.topo
                                       .all_nodes() if vid in n.volumes)
                           for vid in (1, 2)}
            if all(holders[vid] == sorted([src, dst])
                   for vid in (1, 2)):
                break
            time.sleep(0.1)
        assert all(holders[vid] == sorted([src, dst])
                   for vid in (1, 2)), holders

        # the resumed grow_done records reached the surviving follower
        follower = next(m for m in masters if m is not new_leader)
        deadline = time.time() + 10
        while time.time() < deadline:
            fdoc = follower.autoscaler.export_replicated()
            if fdoc["pending"] == {} and \
                    set(fdoc["targets"]) == {"1", "2"}:
                break
            time.sleep(0.1)
        assert fdoc["pending"] == {}, fdoc
        assert fdoc["targets"]["1"]["added"] == [dst]
        assert fdoc["targets"]["2"]["added"] == [dst]
    finally:
        for vs in servers:
            vs.stop()
        for m in masters:
            m.stop()


# --- the live failover drill (scenarios/failover.py) ----------------------

def test_leader_failover_drill(tmp_path):
    """Kill the raft leader of a 3-master quorum mid write-storm and
    mid EC repair: a new leader takes over within the election budget,
    /dir/assign serves again inside one client deadline, every
    pre-kill journaled event survives (journal_loss_count == 0), and
    the orphaned repair is re-planned by the new leader with its
    ORIGINAL alert + trace cause attribution."""
    from seaweedfs_tpu.scenarios import master_failover, run_failover

    result = run_failover(master_failover(), base_dir=str(tmp_path))
    assert result["verdict"] == "pass", \
        json.dumps(result, indent=2, default=str)
