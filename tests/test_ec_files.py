"""File-level EC tests — the mirror of the reference's ec_test.go.

Uses the same shrunk geometry as ec_test.go:15-18 (largeBlock=10000,
smallBlock=100, io buffer 50) and, when available, the reference's own
Go-written fixture volume copied to a temp dir, so interval math and
striping are validated against real data laid out by the reference.
"""

import os
import shutil

import numpy as np
import pytest

from seaweedfs_tpu.ec import encoder as ec_encoder
from seaweedfs_tpu.ec.codec import CpuEngine, ReedSolomon
from seaweedfs_tpu.ec.ec_volume import EcVolume, rebuild_ecx_file
from seaweedfs_tpu.ec.layout import locate_data, to_ext
from seaweedfs_tpu.storage import idx as idx_mod
from seaweedfs_tpu.storage.needle import Needle, get_actual_size
from seaweedfs_tpu.storage.types import Version, size_is_valid
from seaweedfs_tpu.storage.volume import Volume

LARGE, SMALL, CHUNK = 10_000, 100, 50  # ec_test.go:15-18
REF_EC_DIR = "/root/reference/weed/storage/erasure_coding"

rng = np.random.default_rng(7)


def _write_test_volume(tmp_path, vid=1, n_needles=100):
    v = Volume(str(tmp_path), "", vid)
    for i in range(1, n_needles + 1):
        size = int(rng.integers(1, 800))
        v.write_needle(Needle(cookie=i, id=i, data=rng.bytes(size)))
    v.close()
    return os.path.join(str(tmp_path), str(vid))


def _validate_files(base, version=Version.V3, rs=None):
    """ec_test.go validateFiles: every live needle read from shards equals
    the .dat bytes."""
    dat_size = os.path.getsize(base + ".dat")
    with open(base + ".dat", "rb") as f:
        dat = f.read()
    shard_files = {}
    for i in range(14):
        if os.path.exists(base + to_ext(i)):
            with open(base + to_ext(i), "rb") as f:
                shard_files[i] = f.read()
    checked = 0
    for key, offset, size in idx_mod.iter_index_file(base + ".idx"):
        if offset == 0 or not size_is_valid(size):
            continue
        actual = get_actual_size(size, version)
        intervals = locate_data(LARGE, SMALL, dat_size, offset, actual)
        got = b""
        for iv in intervals:
            sid, soff = iv.to_shard_id_and_offset(LARGE, SMALL)
            got += shard_files[sid][soff : soff + iv.size]
        assert got == dat[offset : offset + actual], f"needle {key}"
        checked += 1
    assert checked > 0
    return checked


def _reconstruct_and_compare(base, rs):
    """ec_test.go readFromOtherEcFiles flavor: re-derive each shard from 10
    random others and byte-compare."""
    shards = []
    for i in range(rs.total_shards):
        with open(base + to_ext(i), "rb") as f:
            shards.append(np.frombuffer(f.read(), dtype=np.uint8))
    for victim in rng.choice(rs.total_shards, 4, replace=False):
        keep = [i for i in range(rs.total_shards) if i != victim]
        chosen = rng.choice(keep, rs.data_shards, replace=False)
        damaged = [shards[i].copy() if i in chosen else None
                   for i in range(rs.total_shards)]
        rs.reconstruct(damaged)
        assert np.array_equal(damaged[victim], shards[victim]), victim


def test_encode_validate_own_volume(tmp_path):
    base = _write_test_volume(tmp_path)
    rs = ReedSolomon(10, 4)
    ec_encoder.write_ec_files(base, rs, LARGE, SMALL, chunk=CHUNK)
    ec_encoder.write_sorted_file_from_idx(base)
    _validate_files(base)
    _reconstruct_and_compare(base, rs)


@pytest.mark.skipif(not os.path.exists(os.path.join(REF_EC_DIR, "1.dat")),
                    reason="reference fixture not available")
def test_encode_validate_reference_fixture(tmp_path):
    """Encode the Go-written fixture volume with the ec_test.go geometry and
    validate every needle through the striping math."""
    base = os.path.join(str(tmp_path), "1")
    shutil.copy(os.path.join(REF_EC_DIR, "1.dat"), base + ".dat")
    shutil.copy(os.path.join(REF_EC_DIR, "1.idx"), base + ".idx")
    rs = ReedSolomon(10, 4)
    ec_encoder.write_ec_files(base, rs, LARGE, SMALL, chunk=CHUNK)
    ec_encoder.write_sorted_file_from_idx(base)
    from seaweedfs_tpu.storage.super_block import SuperBlock

    with open(base + ".dat", "rb") as f:
        version = SuperBlock.from_bytes(f.read(8)).version
    checked = _validate_files(base, version=version)
    assert checked > 10
    _reconstruct_and_compare(base, rs)


def test_chunk_size_invariance(tmp_path):
    """Shard bytes must not depend on the IO chunk (TPU uses huge chunks)."""
    base = _write_test_volume(tmp_path)
    rs = ReedSolomon(10, 4)
    ec_encoder.write_ec_files(base, rs, LARGE, SMALL, chunk=CHUNK)
    want = [open(base + to_ext(i), "rb").read() for i in range(14)]
    ec_encoder.write_ec_files(base, rs, LARGE, SMALL, chunk=1 << 20)
    got = [open(base + to_ext(i), "rb").read() for i in range(14)]
    assert want == got


def test_rebuild_missing_shards(tmp_path):
    base = _write_test_volume(tmp_path)
    rs = ReedSolomon(10, 4)
    ec_encoder.write_ec_files(base, rs, LARGE, SMALL, chunk=CHUNK)
    originals = {}
    for victim in (0, 5, 11, 13):  # data + parity mix, worst-case 4 erasures
        with open(base + to_ext(victim), "rb") as f:
            originals[victim] = f.read()
        os.remove(base + to_ext(victim))
    generated = ec_encoder.rebuild_ec_files(base, rs)
    assert sorted(generated) == [0, 5, 11, 13]
    for victim, want in originals.items():
        with open(base + to_ext(victim), "rb") as f:
            assert f.read() == want, victim


def test_rebuild_unrepairable(tmp_path):
    base = _write_test_volume(tmp_path)
    rs = ReedSolomon(10, 4)
    ec_encoder.write_ec_files(base, rs, LARGE, SMALL, chunk=CHUNK)
    for victim in (0, 1, 2, 3, 4):
        os.remove(base + to_ext(victim))
    with pytest.raises(ValueError, match="unrepairable"):
        ec_encoder.rebuild_ec_files(base, rs)


def test_decode_back_to_volume(tmp_path):
    """encode -> decode (.dat reassembly + .idx from .ecx/.ecj) roundtrip."""
    base = _write_test_volume(tmp_path)
    with open(base + ".dat", "rb") as f:
        original_dat = f.read()
    rs = ReedSolomon(10, 4)
    ec_encoder.write_ec_files(base, rs, LARGE, SMALL, chunk=CHUNK)
    ec_encoder.write_sorted_file_from_idx(base)
    os.remove(base + ".dat")

    dat_size = ec_encoder.find_dat_file_size(base, base)
    assert dat_size == len(original_dat)
    ec_encoder.write_dat_file(base, dat_size, LARGE, SMALL)
    with open(base + ".dat", "rb") as f:
        assert f.read() == original_dat


def test_ec_volume_reads_and_deletes(tmp_path):
    base = _write_test_volume(tmp_path)
    with open(base + ".dat", "rb") as f:
        dat = f.read()
    rs = ReedSolomon(10, 4)
    ec_encoder.write_ec_files(base, rs, LARGE, SMALL, chunk=CHUNK)
    ec_encoder.write_sorted_file_from_idx(base)
    live = [(k, o, s) for k, o, s in idx_mod.iter_index_file(base + ".idx")
            if o != 0 and size_is_valid(s)]

    ev = EcVolume(base, large_block_size=LARGE, small_block_size=SMALL)
    key, offset, size = live[3]
    blob = ev.read_needle(key)
    actual = get_actual_size(size, Version.V3)
    assert blob == dat[offset : offset + actual]
    n = Needle.from_bytes(blob, size, Version.V3)
    assert n.id == key

    # degraded read: drop two shards and read through reconstruction
    ev.close()
    os.remove(base + to_ext(2))
    os.remove(base + to_ext(6))
    ev = EcVolume(base, large_block_size=LARGE, small_block_size=SMALL)
    for key, offset, size in live[:20]:
        blob = ev.read_needle(key, rs)
        assert blob == dat[offset : offset + get_actual_size(size, Version.V3)]

    # delete: tombstone in .ecx + journal entry, then replay
    ev.delete_needle(live[0][0])
    with pytest.raises(KeyError):
        ev.read_needle(live[0][0])
    ev.close()
    assert os.path.getsize(base + ".ecj") == 8
    rebuild_ecx_file(base)
    assert not os.path.exists(base + ".ecj")
    ev = EcVolume(base, large_block_size=LARGE, small_block_size=SMALL)
    with pytest.raises(KeyError):
        ev.read_needle(live[0][0])
    ev.close()
