"""Multi-master consensus: election, failover, state replication,
follower redirects, volume-server leader tracking.

Reference behaviors: server/raft_server.go (MaxVolumeId state machine,
-resumeState), master_grpc_server.go leader redirects.
"""

from __future__ import annotations

import time

import pytest

from seaweedfs_tpu.master.server import MasterServer
from seaweedfs_tpu.utils.httpd import http_bytes, http_json
from seaweedfs_tpu.volume_server.server import VolumeServer
from tests.conftest import free_port


def _wait_one_leader(masters, timeout=10.0):
    deadline = time.time() + timeout
    while time.time() < deadline:
        leaders = [m for m in masters if m.is_leader]
        if len(leaders) == 1:
            others = [m for m in masters if m is not leaders[0]]
            if all(o.leader_url == leaders[0].url for o in others):
                return leaders[0]
        time.sleep(0.1)
    raise AssertionError(
        f"no stable leader; roles={[m.raft.role for m in masters]}")


@pytest.fixture
def trio(tmp_path):
    ports = [free_port() for _ in range(3)]
    urls = [f"127.0.0.1:{p}" for p in ports]
    masters = []
    for i, p in enumerate(ports):
        peers = [u for j, u in enumerate(urls) if j != i]
        masters.append(MasterServer(
            port=p, peers=peers, mdir=str(tmp_path / f"m{i}"),
            pulse_seconds=0.3).start())
    yield masters
    for m in masters:
        m.stop()


def test_single_node_is_immediate_leader(tmp_path):
    m = MasterServer(port=free_port(), pulse_seconds=0.3).start()
    try:
        assert m.is_leader
        r = http_json("GET", f"http://{m.url}/cluster/status")
        assert r["IsLeader"] is True and r["Leader"] == m.url
    finally:
        m.stop()


def test_trio_elects_exactly_one_leader(trio):
    leader = _wait_one_leader(trio)
    status = http_json("GET", f"http://{leader.url}/cluster/status")
    assert status["IsLeader"] and len(status["Peers"]) == 2
    # followers report the same leader
    for m in trio:
        if m is not leader:
            s = http_json("GET", f"http://{m.url}/cluster/status")
            assert s["IsLeader"] is False
            assert s["Leader"] == leader.url


def test_follower_redirects_control_plane(trio, tmp_path):
    leader = _wait_one_leader(trio)
    follower = next(m for m in trio if m is not leader)
    # raw request without following redirects: 307 + Location
    status, _, headers = http_bytes(
        "GET", f"http://{follower.url}/vol/grow?count=1",
        follow_redirects=False)
    assert status == 307
    assert headers.get("Location") == \
        f"http://{leader.url}/vol/grow?count=1"
    # urllib follows GET 307s, so calls through a follower reach the
    # leader transparently (vacuum: harmless with zero volume servers)
    r = http_json("GET", f"http://{follower.url}/vol/vacuum")
    assert r["compacted"] == []


def test_failover_and_state_survives(trio, tmp_path):
    leader = _wait_one_leader(trio)
    # a volume server registers with the full master list
    d = tmp_path / "vs"
    d.mkdir()
    vs = VolumeServer([str(d)], ",".join(m.url for m in trio),
                      port=free_port(), pulse_seconds=0.3).start()
    try:
        deadline = time.time() + 8
        while time.time() < deadline and len(leader.topo.all_nodes()) < 1:
            time.sleep(0.1)
        assert len(leader.topo.all_nodes()) == 1
        # grow a volume on the leader; MaxVolumeId replicates to followers
        r = http_json("GET", f"http://{leader.url}/vol/grow?count=2")
        grown = r["volumeIds"]
        deadline = time.time() + 5
        followers = [m for m in trio if m is not leader]
        while time.time() < deadline and not all(
                f.topo.max_volume_id >= max(grown) for f in followers):
            time.sleep(0.1)
        assert all(f.topo.max_volume_id >= max(grown) for f in followers)
        # kill the leader -> a new one takes over
        leader.stop()
        remaining = followers
        new_leader = _wait_one_leader(remaining, timeout=15)
        assert new_leader is not leader
        # the volume server re-targets and re-registers via heartbeats
        deadline = time.time() + 10
        while time.time() < deadline and \
                len(new_leader.topo.all_nodes()) < 1:
            time.sleep(0.2)
        assert len(new_leader.topo.all_nodes()) == 1
        # new volume ids never reuse the replicated MaxVolumeId
        r2 = http_json("GET", f"http://{new_leader.url}/vol/grow?count=1")
        assert r2["volumeIds"][0] > max(grown)
    finally:
        vs.stop()
        # leader already stopped; fixture stops the rest


def test_raft_state_persists_across_restart(tmp_path):
    port = free_port()
    mdir = str(tmp_path / "m")
    m = MasterServer(port=port, mdir=mdir, pulse_seconds=0.3).start()
    http_json("GET", f"http://{m.url}/vol/grow?count=0")  # no-op touch
    with m.topo.lock:
        m.topo.max_volume_id = 41
    m.raft.persist()
    m.stop()
    time.sleep(0.3)
    m2 = MasterServer(port=free_port(), mdir=mdir, pulse_seconds=0.3)
    try:
        assert m2.topo.max_volume_id >= 41
    finally:
        m2.stop()


def test_grow_fails_closed_when_quorum_commit_fails(tmp_path):
    """The reserved max_volume_id must quorum-commit BEFORE any allocate
    RPC: if the commit cannot reach quorum, the grow fails with zero
    volumes created, so a new leader can never re-issue the same vid."""
    m = MasterServer(port=free_port(), pulse_seconds=0.3).start()
    d = tmp_path / "vs"
    d.mkdir()
    vs = VolumeServer([str(d)], m.url, port=free_port(),
                      pulse_seconds=0.3).start()
    try:
        deadline = time.time() + 8
        while time.time() < deadline and len(m.topo.all_nodes()) < 1:
            time.sleep(0.1)
        calls = []
        m._allocate_rpc = lambda *a, **k: calls.append(a)
        m.raft.commit_state = lambda: False  # quorum unreachable
        status, body, _ = http_bytes(
            "GET", f"http://{m.url}/vol/grow?count=1",
            follow_redirects=False)
        assert status == 500
        assert calls == [], "allocate RPC issued before the failed commit"
    finally:
        vs.stop()
        m.stop()


def test_vote_denied_to_stale_candidate():
    """Election restriction: a node that missed a quorum-committed
    max_volume_id must not win an election (it would re-issue the id)."""
    from seaweedfs_tpu.master.consensus import RaftNode

    state = {"max_volume_id": 5, "max_file_key": 100}
    voter = RaftNode("127.0.0.1:1", ["127.0.0.1:2"],
                     read_state=lambda: dict(state))
    # candidate behind on max_volume_id: denied
    r = voter.handle_vote(7, "127.0.0.1:2",
                          {"max_volume_id": 4, "max_file_key": 100})
    assert r["granted"] is False
    # term advanced anyway (raft semantics)
    assert voter.term == 7
    # up-to-date candidate: granted
    r = voter.handle_vote(8, "127.0.0.1:2",
                          {"max_volume_id": 5, "max_file_key": 100})
    assert r["granted"] is True
    # pre-upgrade candidate without state: liveness preserved
    voter2 = RaftNode("127.0.0.1:3", ["127.0.0.1:4"],
                      read_state=lambda: dict(state))
    assert voter2.handle_vote(3, "127.0.0.1:4")["granted"] is True
