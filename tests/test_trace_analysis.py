"""Critical-path analyzer acceptance: the drain-wait stall explains itself.

observability/analysis.py is the layer the next perf PR consumes — these
tests pin the contract end to end:

  - a synthetic CPU-only pipeline run with an injected slow drain
    (ec.drain fault delay) is attributed to the `drain` stage with >=80%
    of the wall, by name;
  - an injected worker-kill run (supervisor respawn) reports
    degraded=true; so does a forced per-dispatch CPU fallback;
  - offline analysis (Tracer.to_dict() round-trip and the Chrome
    trace JSON from --trace-out) produces the same report as the live
    ring;
  - the report is served on GET /debug/traces/analyze and through the
    `weed shell` trace.analyze command, and bench's trace smoke embeds
    the attribution block.
"""

from __future__ import annotations

import json

import numpy as np
import pytest

from seaweedfs_tpu import native
from seaweedfs_tpu.ec.streaming import StreamingEncoder
from seaweedfs_tpu.observability import (Tracer, analyze,
                                         attribution_summary,
                                         disable_tracing, enable_tracing,
                                         render_report)
from seaweedfs_tpu.utils import faultinject as fi

K, R = 10, 4
LARGE, SMALL = 100 << 20, 1 << 20


def _make_volume(tmp_path, size_mb: int) -> str:
    dat = str(tmp_path / "v.dat")
    rng = np.random.default_rng(0xA11)
    with open(dat, "wb") as f:
        f.write(rng.integers(0, 256, size_mb << 20,
                             dtype=np.uint8).tobytes())
    return dat


def _staged_encode(tmp_path, tracer, size_mb=12, **kw) -> StreamingEncoder:
    """CPU-only staged pipeline (no native/mmap path, no worker unless
    asked): deterministic on any host."""
    dat = _make_volume(tmp_path, size_mb)
    enc = StreamingEncoder(K, R, engine="host", zero_copy=False,
                           dispatch_mb=1, tracer=tracer,
                           **dict({"overlap": "none"}, **kw))
    enc.encode_file(dat, str(tmp_path / "v"),
                    large_block_size=LARGE, small_block_size=SMALL)
    return enc


class TestCriticalPath:
    def test_slow_drain_names_drain_with_80pct_attribution(self, tmp_path):
        """The acceptance drill: ec.drain armed with a pure delay makes
        every dispatch's drain slow; the analyzer must name `drain` as
        the critical-path stage and attribute >=80% of the wall to it."""
        tr = Tracer(capacity=1 << 14)
        fi.enable("ec.drain", delay=1.0)
        try:
            _staged_encode(tmp_path, tr)  # 12MB -> 2 dispatches
        finally:
            fi.clear()
        report = analyze(tr)
        assert len(report["runs"]) == 1
        run = report["runs"][0]
        assert run["critical_path_stage"] == "drain"
        assert run["attribution"]["drain"]["share"] >= 0.80
        assert run["overlap_efficiency"] <= 0.20
        # a pure delay is slow, not degraded: no retry/fallback evidence
        assert report["degraded"] is False
        # every second of the wall is attributed to a named bucket
        total = sum(v["s"] for v in run["attribution"].values())
        assert abs(total - run["wall_s"]) < 0.05 * run["wall_s"] + 0.01
        # the per-dispatch critical path agrees
        assert run["critical_path"]
        assert all(seg["stage"] == "drain" for seg in run["critical_path"])

    def test_clean_run_is_not_drain_bound(self, tmp_path):
        tr = Tracer(capacity=1 << 14)
        _staged_encode(tmp_path, tr)
        run = analyze(tr)["runs"][0]
        # synchronous host codec: drain is a no-op fetch
        assert run["critical_path_stage"] != "drain"
        assert run["overlap_efficiency"] > 0.5
        assert run["degraded"] is False

    def test_dispatch_fault_sets_degraded(self, tmp_path):
        """A forced per-dispatch CPU fallback (ec.dispatch error) leaves
        pipeline.fallback evidence: the run and report flag degraded."""
        tr = Tracer(capacity=1 << 14)
        fi.enable("ec.dispatch", error_rate=1.0, max_hits=1)
        try:
            enc = _staged_encode(tmp_path, tr)
        finally:
            fi.clear()
        assert enc.stats["fallbacks"] >= 1
        report = analyze(tr)
        assert report["degraded"] is True
        run = report["runs"][0]
        assert run["degraded"] is True
        assert run["fallbacks"] >= 1
        assert "dispatch_fault" in run["fallback_reasons"]

    def test_counters_alone_mark_degraded(self, tmp_path):
        """Ring rotation can evict retry spans; the restart/fallback
        counters still force the degraded verdict."""
        tr = Tracer(capacity=1 << 14)
        _staged_encode(tmp_path, tr)
        assert analyze(tr)["degraded"] is False
        report = analyze(tr, counters={"worker_restarts": 2,
                                       "engine_fallbacks": 0})
        assert report["degraded"] is True
        assert report["health"]["worker_restarts"] == 2


@pytest.mark.skipif(native.load() is None,
                    reason="no native engine: no overlap worker processes")
class TestWorkerKill:
    def test_worker_kill_run_reports_degraded(self, tmp_path):
        """The second acceptance drill: ec.worker.ack armed makes the
        supervisor SIGKILL + respawn the real parity worker mid-encode;
        the analyzer's report must set degraded=true (pipeline.retry
        spans + the restart counter both say so)."""
        tr = enable_tracing()
        tr.clear()
        fi.enable("ec.worker.ack", error_rate=1.0, max_hits=1)
        enc = None
        try:
            enc = _staged_encode(tmp_path, None, size_mb=24,
                                 overlap="process")
        finally:
            fi.clear()
            disable_tracing()
            if enc is not None and enc._proc_worker is not None:
                enc._proc_worker.close()
                enc._proc_worker = None
        assert enc.stats["worker_restarts"] >= 1
        report = analyze(tr,
                         counters={"worker_restarts":
                                   enc.stats["worker_restarts"]})
        tr.clear()
        assert report["degraded"] is True
        assert report["retry_spans"] >= 1

    def test_gap_analysis_classifies_worker_idle(self, tmp_path):
        """A clean process-overlap run merges worker.compute windows;
        gaps between them are classified against the host stages."""
        tr = Tracer(capacity=1 << 14)
        enc = _staged_encode(tmp_path, tr, size_mb=24, overlap="process")
        if enc._proc_worker is not None:
            enc._proc_worker.close()
            enc._proc_worker = None
        run = analyze(tr)["runs"][0]
        ga = run["gap_analysis"]
        assert ga["worker_windows"] >= 2
        assert run["worker_compute_s"] > 0
        # classified seconds never exceed the total gap
        assert sum(ga["classes"].values()) <= ga["gap_total_s"] + 1e-6


class TestOfflineRoundTrip:
    def test_to_dict_round_trip_equals_live_analysis(self, tmp_path):
        """export -> json -> from_dict -> analyze == live-ring analyze
        (the --trace-out offline contract)."""
        tr = Tracer(capacity=1 << 14)
        fi.enable("ec.drain", delay=0.2)
        try:
            _staged_encode(tmp_path, tr)
        finally:
            fi.clear()
        live = analyze(tr)
        doc = json.loads(json.dumps(tr.to_dict()))
        assert doc["format"] == "seaweedfs-tpu-trace-v1"
        offline = analyze(Tracer.from_dict(doc))
        # also straight from the document, no Tracer reconstruction
        offline2 = analyze(doc)
        for rep in (offline, offline2):
            assert rep["span_count"] == live["span_count"]
            assert len(rep["runs"]) == len(live["runs"])
            for a, b in zip(rep["runs"], live["runs"]):
                assert a["stage_s"] == b["stage_s"]
                assert a["critical_path_stage"] == b["critical_path_stage"]
                assert a["degraded"] == b["degraded"]
                assert a["dispatches"] == b["dispatches"]

    def test_chrome_doc_analysis_matches(self, tmp_path):
        """The Chrome trace-event JSON (bench --trace-out / GET
        /debug/traces) analyzes to the same verdict despite its
        microsecond quantization and relative time base."""
        tr = Tracer(capacity=1 << 14)
        fi.enable("ec.drain", delay=0.3)
        try:
            _staged_encode(tmp_path, tr)
        finally:
            fi.clear()
        live = analyze(tr)["runs"][0]
        chrome = json.loads(json.dumps(tr.to_chrome()))
        run = analyze(chrome)["runs"][0]
        assert run["critical_path_stage"] == live["critical_path_stage"]
        assert run["dispatches"] == live["dispatches"]
        assert abs(run["wall_s"] - live["wall_s"]) < 0.01

    def test_partial_trace_without_root_still_reports(self):
        tr = Tracer()
        with tr.span("pipeline.drain", dispatch=0):
            pass
        report = analyze(tr)
        assert report["runs"] and report["runs"][0].get("partial") is True

    def test_empty_trace(self):
        report = analyze(Tracer())
        assert report["runs"] == [] and report["degraded"] is False
        assert "no pipeline runs" in render_report(report)


class TestSurfaces:
    @pytest.fixture()
    def master(self):
        from seaweedfs_tpu.master.server import MasterServer
        from tests.conftest import free_port

        m = MasterServer(port=free_port()).start()
        try:
            yield m
        finally:
            m.stop()

    def test_analyze_endpoint_and_shell_command(self, master, tmp_path):
        from seaweedfs_tpu.shell import CommandEnv, run_command
        from seaweedfs_tpu.utils.httpd import http_bytes

        tr = enable_tracing()
        tr.clear()
        try:
            fi.enable("ec.drain", delay=0.2)
            try:
                _staged_encode(tmp_path, None)  # global tracer
            finally:
                fi.clear()
            status, body, _ = http_bytes(
                "GET", f"http://{master.url}/debug/traces/analyze")
            assert status == 200
            report = json.loads(body)
            assert report["runs"]
            assert report["runs"][0]["critical_path_stage"] == "drain"
            assert "health" in report  # counters ride along
            # text rendering
            status, text, _ = http_bytes(
                "GET",
                f"http://{master.url}/debug/traces/analyze?format=text")
            assert status == 200 and b"drain-bound" in text
            # shell command against the live server
            env = CommandEnv(master.url)
            out = run_command(env, f"trace.analyze -server {master.url}")
            assert "critical path" in out and "drain" in out
            # shell command against a saved trace file (offline)
            path = str(tmp_path / "trace.json")
            with open(path, "w") as f:
                json.dump(tr.to_chrome(), f)
            out = run_command(env, f"trace.analyze -file {path}")
            assert "drain-bound" in out
            out = run_command(env, f"trace.analyze -file {path} -json")
            assert json.loads(out)["runs"]
        finally:
            disable_tracing()
            tr.clear()

    def test_profile_endpoint_collapsed_format(self, master):
        from seaweedfs_tpu.utils.httpd import http_bytes

        status, body, _ = http_bytes(
            "GET", f"http://{master.url}/debug/profile?seconds=0.3&hz=200")
        assert status == 200
        for line in body.decode().splitlines():
            stack, _, count = line.rpartition(" ")
            assert stack and count.isdigit()

    def test_bench_trace_smoke_embeds_attribution(self, tmp_path):
        from bench import trace_smoke

        mbps, pipe = trace_smoke(size_mb=2, base_dir=str(tmp_path))
        assert mbps > 0
        attr = pipe["attribution"]
        assert set(attr) >= {"stage_s", "critical_path_stage",
                             "overlap_efficiency", "degraded", "wall_s"}
        assert attr["degraded"] is False
        assert attr["critical_path_stage"] in (
            "fill", "dispatch", "compute", "drain", "write", "setup",
            "close", "fallback", "unattributed")

    def test_attribution_summary_empty(self):
        assert attribution_summary({"runs": [], "degraded": True}) == \
            {"degraded": True}


class TestBenchSectionBudget:
    def test_exhausted_budget_skips_sections_but_emits_json(self, tmp_path):
        """A truncated bench run (child budget already spent) must skip
        every section with a recorded marker and still print its valid
        BENCH_CHILD_RESULT JSON — the BENCH_r05 rc=-9 failure mode,
        fixed."""
        import os
        import subprocess
        import sys

        repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
        scratch = str(tmp_path / "scratch.json")
        env = dict(os.environ, JAX_PLATFORMS="cpu",
                   BENCH_CHILD_BUDGET_S="1")
        p = subprocess.run(
            [sys.executable, os.path.join(repo, "bench.py"), "--child",
             scratch, "cpu"],
            env=env, capture_output=True, text=True, timeout=240)
        line = next(l for l in p.stdout.splitlines()
                    if l.startswith("BENCH_CHILD_RESULT "))
        detail = json.loads(line[len("BENCH_CHILD_RESULT "):])
        skipped = detail.get("sections_skipped", {})
        assert skipped.get("e2e_stream") == "section_timeout"
        assert skipped.get("cluster") == "section_timeout"
        # nothing measured, nothing crashed: no error_* keys
        assert not [k for k in detail if k.startswith("error_")]
        # the checkpoint scratch file is equally parseable (what the
        # parent salvages after a SIGKILL)
        with open(scratch) as f:
            assert json.load(f)["sections_skipped"]

    def test_join_bounded_abandons_before_budget_line(self):
        """A section that started under a healthy cap but whose shared
        child budget ran low mid-run must be abandoned ~grace seconds
        before the budget line (not slept through to the parent's
        SIGKILL), and a finished thread must report True."""
        import threading
        import time as _time

        from bench import _join_bounded

        stop = threading.Event()
        th = threading.Thread(target=stop.wait, daemon=True)
        th.start()
        try:
            # cap far away, budget nearly spent: give up immediately,
            # leaving slack to checkpoint and emit BENCH_CHILD_RESULT
            t0 = _time.perf_counter()
            assert _join_bounded(th, cap=60.0, remaining=lambda: 5.0,
                                 grace=8.0) is False
            assert _time.perf_counter() - t0 < 3.0
            # budget plentiful, tiny cap: abandoned at the cap instead
            t0 = _time.perf_counter()
            assert _join_bounded(th, cap=0.2,
                                 remaining=lambda: 1e9) is False
            assert _time.perf_counter() - t0 < 3.0
        finally:
            stop.set()
        th.join(5)
        assert _join_bounded(th, cap=1.0, remaining=lambda: 1e9) is True
