"""Workload flight recorder + trace-driven replay (ISSUE 14) — tier-1.

Gates, unit side: the ring is bounded and loss-counted, sampling is
deterministic under a fixed seed, redaction strips credential query
values at record time, the shipper counts what it could not deliver,
and the recording->spec fit (Zipf skew, size mix, op mix) lands within
tolerance on synthetic recordings.

Gates, live side (the ISSUE acceptance drill): a real master + volume
server record a mixed workload driven over BOTH planes (HTTP + framed
TCP) with a ``?jwt=`` credential in flight; the records ship to the
master's /cluster/workload journal; the exported recording carries no
secret; ``spec_from_recording`` fits it; ``run_scenario`` replays it
open-loop; and the replay's verdict AND the machine-checked fidelity
list (op mix / size mix / hot-set head) are green.
"""

from __future__ import annotations

import json
import random
import tempfile
import time

import pytest

from seaweedfs_tpu.observability.reqlog import (
    ReqlogRecorder,
    ReqlogShipper,
    WorkloadJournal,
    classify_route,
    get_recorder,
    redact_query,
    summarize_records,
)
from seaweedfs_tpu.scenarios.replay import (
    estimate_zipf_s,
    fit_size_mix,
    recording_profile,
    replay_fidelity,
    spec_from_recording,
)

from tests.conftest import free_port


@pytest.fixture(autouse=True)
def _recorder_off():
    """The process-global recorder must never leak an enabled state
    (or records) between tests — other suites drive HTTP traffic."""
    rl = get_recorder()
    yield
    rl.stop()
    rl.clear()


# --- redaction ---------------------------------------------------------------

class TestRedaction:
    def test_jwt_value_redacted_benign_params_survive(self):
        out = redact_query("/3,01ab?jwt=eyJSECRET&count=2&ttl=3m")
        assert "eyJSECRET" not in out
        assert "jwt=REDACTED" in out
        assert "count=2" in out and "ttl=3m" in out

    @pytest.mark.parametrize("param", [
        "token", "auth", "Authorization", "sig", "Signature", "secret",
        "password", "key", "X-Amz-Signature", "X-Amz-Security-Token"])
    def test_credential_params_redacted_case_insensitive(self, param):
        out = redact_query(f"/x?{param}=HUSH123")
        assert "HUSH123" not in out and "REDACTED" in out

    def test_plain_path_untouched(self):
        assert redact_query("/3,01ab") == "/3,01ab"

    def test_keys_param_is_data_not_credential(self):
        # exact-key matching: `keys` must not be mistaken for `key`
        assert redact_query("/l?keys=a%2Cb") == "/l?keys=a%2Cb"

    def test_encoded_values_round_trip_intact(self):
        # percent/plus-encoded values must survive redaction: a
        # decoded-then-bare-joined '%26' would split one parameter
        # into two and corrupt the recorded path
        import urllib.parse

        out = redact_query("/3,01ab?filename=a%26b%3Dc&n=x+y")
        pairs = dict(urllib.parse.parse_qsl(out.partition("?")[2]))
        assert pairs == {"filename": "a&b=c", "n": "x y"}


class TestClassify:
    @pytest.mark.parametrize("method,path,want", [
        ("GET", "/3,01ab", "http_read"),
        ("HEAD", "/3,01ab", "http_read"),
        ("POST", "/3,01ab", "http_write"),
        ("PUT", "/3,01ab", "http_write"),
        ("DELETE", "/3,01ab", "http_delete"),
        ("POST", "/submit", "http_write"),
        ("GET", "/dir/assign", "assign"),
        ("GET", "/dir/lookup", "lookup"),
        ("GET", "/metrics", "ops"),
        ("GET", "/cluster/workload/ingest", "ops"),
        ("GET", "/debug/reqlog", "ops"),
        ("GET", "/some/unknown", "other"),
    ])
    def test_route_classes(self, method, path, want):
        assert classify_route(method, path) == want

    def test_server_to_server_hops_classify_internal(self):
        # replication fan-out and the master's /submit upload proxy
        # are NOT client workload: recording them would double-count
        # every proxied/replicated write in the fitted replay spec
        assert classify_route("POST", "/3,01ab",
                              query={"type": "replicate"}) == "internal"
        assert classify_route("POST", "/3,01ab",
                              query={"type": "proxied"}) == "internal"
        assert classify_route("DELETE", "/3,01ab",
                              query={"type": "replicate"}) == "internal"

    def test_internal_skipped_like_ops(self):
        rl = ReqlogRecorder(capacity=8, sample=1.0)
        rl.start()
        assert rl.record("internal", "POST", "/3,01ab", 200) is None


# --- recorder ring -----------------------------------------------------------

class TestRecorder:
    def test_ring_bounds_and_eviction_counted(self):
        rl = ReqlogRecorder(capacity=16, sample=1.0)
        rl.start()
        for i in range(40):
            rl.record("http_read", "GET", f"/1,{i:02x}", 200)
        st = rl.status()
        assert st["records"] == 16
        assert st["recorded"] == 40
        assert st["dropped"] == 24
        # the ring keeps the NEWEST records
        kept = [r["path"] for r in rl.query(limit=0)]
        assert kept[-1] == "/1,27" and len(kept) == 16

    def test_sampling_deterministic_under_fixed_seed(self):
        def run(seed):
            rl = ReqlogRecorder(capacity=256, sample=0.5, seed=seed)
            rl.start()
            return [rl.record("http_read", "GET", "/1,aa", 200)
                    is not None for _ in range(200)]

        a, b = run(1234), run(1234)
        assert a == b
        assert 40 < sum(a) < 160  # it actually samples, not all/none
        assert run(99) != a  # and the seed matters

    def test_start_resets_window_and_rng(self):
        rl = ReqlogRecorder(capacity=64, sample=0.5, seed=7)
        rl.start()
        first = [rl.record("http_read", "GET", "/1,aa", 200)
                 is not None for _ in range(50)]
        rl.start()  # fresh window: same seed -> same decisions again
        again = [rl.record("http_read", "GET", "/1,aa", 200)
                 is not None for _ in range(50)]
        assert first == again

    def test_ops_routes_skipped_unless_opted_in(self):
        rl = ReqlogRecorder(capacity=64, sample=1.0)
        rl.start()
        assert rl.record("ops", "GET", "/metrics", 200) is None
        rl.configure(include_ops=True)
        assert rl.record("ops", "GET", "/metrics", 200) is not None

    def test_configure_shrink_counts_lost_records(self):
        rl = ReqlogRecorder(capacity=32, sample=1.0)
        rl.start()
        for i in range(32):
            rl.record("http_read", "GET", f"/1,{i:02x}", 200)
        rl.configure(capacity=16)
        assert rl.status()["records"] == 16
        assert rl.status()["dropped"] == 16

    def test_configure_capacity_zero_clamps_and_counts(self):
        # capacity=0 must not hit the [-0:] falsy slice (truncate to
        # the floor while counting NOTHING): it clamps to the floor
        # and every lost record is counted
        rl = ReqlogRecorder(capacity=64, sample=1.0)
        rl.start()
        for i in range(64):
            rl.record("http_read", "GET", f"/1,{i:02x}", 200)
        rl.configure(capacity=0)
        st = rl.status()
        assert st["capacity"] == 16
        assert st["records"] == 16
        assert st["dropped"] == 48

    def test_sample_rate_stamped_on_records(self):
        rl = ReqlogRecorder(capacity=64, sample=0.5, seed=3)
        rl.start()
        recs = [rl.record("http_read", "GET", "/1,aa", 200)
                for _ in range(40)]
        recs = [r for r in recs if r is not None]
        assert recs and all(r.to_dict()["sample"] == 0.5 for r in recs)
        # full-rate records omit the key (the compact default)
        rl2 = ReqlogRecorder(capacity=8, sample=1.0)
        rl2.start()
        d = rl2.record("http_read", "GET", "/1,aa", 200).to_dict()
        assert "sample" not in d

    def test_record_flags_and_fields(self):
        rl = ReqlogRecorder(capacity=8, sample=1.0)
        rl.start()
        rec = rl.record("http_read", "GET", "/1,aa", 503,
                        bytes_in=10, bytes_out=20, duration_ms=1.5,
                        deadline_s=2.0, shed=True, degraded=True,
                        peer="10.0.0.9", handler="volume_download")
        d = rec.to_dict()
        assert d["shed"] is True and d["degraded"] is True
        assert d["ddl_s"] == 2.0 and d["peer"] == "10.0.0.9"
        assert d["in"] == 10 and d["out"] == 20
        assert d["id"].startswith(rl.namespace)


# --- journal + shipper -------------------------------------------------------

class TestWorkloadJournal:
    def _rec(self, i, route="http_read"):
        return {"id": f"t.{i:x}", "seq": i, "ts": 1000.0 + i,
                "route": route, "method": "GET", "path": f"/1,{i:x}",
                "status": 200, "in": 0, "out": 4096, "ms": 1.0}

    def test_dedup_and_bounded_eviction(self):
        j = WorkloadJournal(capacity=8)
        batch = [self._rec(i) for i in range(6)]
        assert j.ingest("vs1", batch) == 6
        assert j.ingest("vs2", batch) == 0  # chained-shipper dedup
        j.ingest("vs1", [self._rec(i) for i in range(6, 16)])
        assert len(j) == 8
        assert j.dropped == 8

    def test_export_document_shape(self):
        j = WorkloadJournal()
        j.ingest("vs1", [self._rec(i) for i in range(5)]
                 + [self._rec(10, route="http_write")])
        doc = j.export()
        assert doc["format"].startswith("seaweedfs-tpu-workload")
        assert doc["summary"]["records"] == 6
        assert doc["summary"]["routes"]["http_read"]["ops"] == 5
        # time-ordered
        ts = [r["ts"] for r in doc["records"]]
        assert ts == sorted(ts)

    def test_query_filters(self):
        j = WorkloadJournal()
        j.ingest("vs1", [self._rec(1), self._rec(2, route="http_write")])
        assert [r["route"] for r in j.query(route="http_write")] == \
            ["http_write"]
        assert j.query(since_ts=1001.5)[0]["route"] == "http_write"


class TestShipper:
    def test_local_short_circuit(self):
        rl = ReqlogRecorder(capacity=64, sample=1.0)
        rl.start()
        j = WorkloadJournal()
        sh = ReqlogShipper(rl, server="m:1", local_journal=j,
                           flush_interval=0.05).attach()
        try:
            for i in range(10):
                rl.record("http_read", "GET", f"/1,{i:x}", 200)
            deadline = time.time() + 5
            while time.time() < deadline and len(j) < 10:
                time.sleep(0.05)
            assert len(j) == 10
            assert sh.shipped == 10 and sh.dropped == 0
        finally:
            sh.detach()

    def test_transport_loss_counted_never_raises(self):
        from seaweedfs_tpu.observability.reqlog import _dropped_counter

        rl = ReqlogRecorder(capacity=64, sample=1.0)
        rl.start()
        before = _dropped_counter().snapshot().get(("ship_error",), 0)
        # nothing listens on this port: every flush must fail, count,
        # and leave the recording path unharmed
        sh = ReqlogShipper(rl, server="vs:1",
                           master_url_fn=lambda: f"127.0.0.1:{free_port()}",
                           flush_interval=0.05).attach()
        try:
            for i in range(8):
                rl.record("http_read", "GET", f"/1,{i:x}", 200)
            deadline = time.time() + 8
            while time.time() < deadline and sh.dropped < 8:
                time.sleep(0.05)
            assert sh.dropped >= 8
            after = _dropped_counter().snapshot().get(("ship_error",), 0)
            assert after - before >= 8
        finally:
            sh.detach()

    def test_buffer_overflow_counted(self):
        rl = ReqlogRecorder(capacity=512, sample=1.0)
        rl.start()
        sh = ReqlogShipper(rl, server="vs:1", buffer_cap=4,
                           flush_interval=60.0,  # never flushes in test
                           master_url_fn=lambda: "")
        sh._prev_hook = rl.on_record
        rl.on_record = sh._on_record  # attach without the flush thread
        try:
            for i in range(10):
                rl.record("http_read", "GET", f"/1,{i:x}", 200)
            assert sh.dropped == 6  # cap 4, 10 offered
        finally:
            rl.on_record = sh._prev_hook


# --- fit ---------------------------------------------------------------------

class TestFit:
    def test_zipf_estimate_recovers_known_skew(self):
        from seaweedfs_tpu.scenarios import ZipfSampler

        rng = random.Random(11)
        for s in (0.8, 1.2):
            z = ZipfSampler(128, s)
            counts: dict[int, int] = {}
            for _ in range(30000):
                r = z.sample(rng)
                counts[r] = counts.get(r, 0) + 1
            est = estimate_zipf_s(list(counts.values()))
            assert abs(est - s) < 0.35, (s, est)

    def test_zipf_degenerate_inputs(self):
        assert estimate_zipf_s([]) == 0.0
        assert estimate_zipf_s([100]) == 0.0
        # uniform counts -> no skew
        assert estimate_zipf_s([50] * 20) < 0.1

    def test_size_mix_buckets_by_magnitude(self):
        sizes = [4096] * 90 + [65536] * 8 + [1 << 20] * 2
        mix = fit_size_mix(sizes)
        assert [b for b, _w in mix] == [4096, 65536, 1 << 20]
        weights = dict(mix)
        assert weights[4096] == pytest.approx(0.9, abs=0.01)

    def test_size_mix_empty_falls_back(self):
        assert fit_size_mix([]) == ((4096, 1.0),)

    def _recording(self, n_reads=120, n_writes=30, n_deletes=10,
                   zipf_s=1.2, keys=24):
        from seaweedfs_tpu.scenarios import ZipfSampler

        rng = random.Random(5)
        z = ZipfSampler(keys, zipf_s)
        records = []
        ts = 1000.0
        seq = 0
        for _ in range(n_reads):
            seq += 1
            ts += 0.01
            records.append({"id": f"s.{seq:x}", "seq": seq, "ts": ts,
                            "route": "http_read", "method": "GET",
                            "path": f"/1,{z.sample(rng):04x}",
                            "status": 200, "in": 0, "out": 4096,
                            "ms": 1.0, "ddl_s": 2.0})
        for i in range(n_writes):
            seq += 1
            ts += 0.01
            records.append({"id": f"s.{seq:x}", "seq": seq, "ts": ts,
                            "route": "http_write", "method": "POST",
                            "path": f"/2,{i:04x}", "status": 201,
                            "in": 4096 if i % 5 else 65536, "out": 30,
                            "ms": 2.0, "ddl_s": 2.0,
                            "handler": "submit" if i % 2 else "upload"})
        for i in range(n_deletes):
            seq += 1
            ts += 0.01
            records.append({"id": f"s.{seq:x}", "seq": seq, "ts": ts,
                            "route": "http_delete", "method": "DELETE",
                            "path": f"/2,{i:04x}", "status": 200,
                            "in": 0, "out": 10, "ms": 0.5})
        # ops noise that must NOT replay
        records.append({"id": "s.ops", "seq": seq + 1, "ts": ts,
                        "route": "ops", "method": "GET",
                        "path": "/metrics", "status": 200, "in": 0,
                        "out": 9000, "ms": 1.0})
        return {"format": "seaweedfs-tpu-workload-recording-v1",
                "records": records}

    def test_profile_and_spec_fit(self):
        rec = self._recording()
        prof = recording_profile(rec)
        assert prof["records"] == 160  # ops excluded
        assert prof["read_fraction"] == pytest.approx(0.75, abs=0.01)
        assert prof["churn_fraction"] == pytest.approx(0.25, abs=0.01)
        assert prof["submit_fraction"] == pytest.approx(0.5, abs=0.05)
        spec = spec_from_recording(rec, duration_s=5)
        assert spec.read_fraction == prof["read_fraction"]
        assert spec.target_rps > 0
        assert spec.hot_set == prof["distinct_keys"]
        assert 0.5 < spec.zipf_s < 2.0
        # spec round-trips through the ScenarioSpec dict shape
        from seaweedfs_tpu.scenarios import ScenarioSpec

        assert ScenarioSpec.from_dict(spec.to_dict()) == spec

    def test_fidelity_green_on_faithful_fit(self):
        rec = self._recording()
        spec = spec_from_recording(rec, duration_s=5)
        checks = replay_fidelity(rec, spec)
        assert checks and all(c["ok"] for c in checks), checks

    def test_fidelity_flags_a_wrong_fit(self):
        rec = self._recording()
        spec = spec_from_recording(rec, duration_s=5)
        # sabotage the op mix: a read-only spec replaying a mixed
        # recording must FAIL the machine check
        spec.read_fraction = 1.0
        checks = replay_fidelity(rec, spec)
        assert any(c["check"] == "fidelity_op_mix" and not c["ok"]
                   for c in checks)

    def test_empty_recording_refused(self):
        with pytest.raises(ValueError):
            spec_from_recording({"records": []})
        with pytest.raises(ValueError):
            # ops-only traffic is not a workload
            spec_from_recording({"records": [
                {"id": "x", "route": "ops", "ts": 1.0}]})

    def test_sampled_recording_corrects_arrival_rate(self):
        """A -sample 0.1 recording stands for 10x its record count:
        the fitted target_rps must reproduce PRODUCTION arrivals, not
        a tenth of them (the degraded-build-hides-behind-light-load
        failure open-loop replay exists to prevent)."""
        rec_full = self._recording()
        prof_full = recording_profile(rec_full)
        rec_sampled = json.loads(json.dumps(rec_full))
        # same stream recorded at 10%: keep every 10th record, each
        # stamped with the rate it was captured at
        kept = [dict(r, sample=0.1)
                for i, r in enumerate(rec_sampled["records"])
                if i % 10 == 0]
        rec_sampled["records"] = kept
        prof = recording_profile(rec_sampled)
        assert prof["observed_rps"] == pytest.approx(
            prof_full["observed_rps"], rel=0.25)
        spec = spec_from_recording(rec_sampled, duration_s=5)
        assert spec.target_rps == pytest.approx(
            prof_full["observed_rps"], rel=0.25)

    def test_fidelity_pacing_flags_underdelivered_replay(self):
        rec = self._recording()
        spec = spec_from_recording(rec, duration_s=5)
        assert spec.target_rps > 0
        ops_at = lambda frac: {  # noqa: E731
            "wall_s": spec.duration_s,
            "routes": {"read": {"ops": int(
                spec.target_rps * spec.duration_s * frac)}}}
        good = replay_fidelity(rec, spec, result=ops_at(1.0))
        pacing = [c for c in good if c["check"] == "fidelity_pacing"]
        assert pacing and pacing[0]["ok"]
        # a build that only managed 40% of the recorded arrivals must
        # NOT read as a faithful reproduction
        bad = replay_fidelity(rec, spec, result=ops_at(0.4))
        pacing = [c for c in bad if c["check"] == "fidelity_pacing"]
        assert pacing and not pacing[0]["ok"]

    def test_summarize_records_rollup(self):
        s = summarize_records([
            {"route": "http_read", "status": 200, "in": 0, "out": 10,
             "ts": 1.0},
            {"route": "http_read", "status": 500, "in": 0, "out": 0,
             "ts": 3.0}])
        assert s["routes"]["http_read"]["errors"] == 1
        assert s["window_s"] == 2.0


# --- the live acceptance drill ----------------------------------------------

class TestLiveDrill:
    def test_record_both_planes_export_replay(self, tmp_path):
        """The ISSUE 14 tier-1 drill: record a mixed workload over the
        HTTP AND native planes (with a credential in flight), export
        from the master, fit, replay via the scenario engine, and
        machine-check fidelity."""
        from seaweedfs_tpu.master.server import MasterServer
        from seaweedfs_tpu.scenarios import run_scenario
        from seaweedfs_tpu.shell import CommandEnv, run_command
        from seaweedfs_tpu.utils.framing import tcp_address
        from seaweedfs_tpu.utils.httpd import http_bytes, http_json
        from seaweedfs_tpu.volume_server.server import VolumeServer
        from seaweedfs_tpu.volume_server.tcp import TcpVolumeClient

        root = tempfile.mkdtemp(dir=str(tmp_path))
        m = MasterServer(port=free_port(), pulse_seconds=0.3).start()
        vs = VolumeServer([root], m.url, port=free_port(),
                          pulse_seconds=0.3).start()
        try:
            deadline = time.time() + 10
            while time.time() < deadline and not m.topo.all_nodes():
                time.sleep(0.05)

            env = CommandEnv(m.url)
            out = run_command(env, "workload.record -sample 1.0")
            assert "recording" in out

            # mixed workload: HTTP writes (one carrying a jwt), Zipf
            # reads over HTTP, native reads+writes over framed TCP,
            # a few deletes
            rng = random.Random(3)
            fids = []
            for i in range(24):
                r = http_json("GET",
                              f"http://{m.url}/dir/assign?count=1",
                              timeout=10.0)
                st, _b, _h = http_bytes(
                    "POST",
                    f"http://{r['url']}/{r['fid']}?jwt=HUSHSECRET42",
                    b"x" * (4096 if i % 6 else 65536), timeout=10.0)
                assert st in (200, 201)
                fids.append((r["fid"], r["url"]))
            for _ in range(150):
                fid, url = fids[min(int(rng.paretovariate(1.1)) - 1,
                                    len(fids) - 1)]
                st, _b, _h = http_bytes("GET", f"http://{url}/{fid}",
                                        timeout=10.0)
                assert st == 200
            tcp = TcpVolumeClient()
            if vs._tcp_server is not None and vs._tcp_server.alive:
                for _ in range(30):
                    fid, url = fids[min(int(rng.paretovariate(1.1)) - 1,
                                        len(fids) - 1)]
                    assert tcp.read(tcp_address(url), fid)
            for i in range(6):
                fid, url = fids.pop()
                http_bytes("DELETE", f"http://{url}/{fid}",
                           timeout=10.0)
            # master-proxied writes: each must record ONCE (the
            # client's /submit), never again as the proxied volume PUT
            for i in range(4):
                st, _b, _h = http_bytes(
                    "POST", f"http://{m.url}/submit",
                    b"proxy-me" * 64, timeout=10.0)
                assert st == 201

            out = run_command(env, "workload.stop")
            assert "stopped" in out

            # shipper flush: the master journal converges — including
            # the master's OWN submit records, which ride a different
            # shipper cadence than the volume server's bulk
            deadline = time.time() + 8
            while time.time() < deadline:
                doc = http_json(
                    "GET", f"http://{m.url}/cluster/workload/export",
                    timeout=10.0)
                if doc["summary"]["records"] >= 180 and len(
                        [r for r in doc["records"]
                         if r.get("handler") == "submit"]) >= 4:
                    break
                time.sleep(0.2)
            prof = recording_profile(doc)
            assert prof["records"] >= 180

            # the credential NEVER reached the recording
            blob = json.dumps(doc)
            assert "HUSHSECRET42" not in blob
            assert "REDACTED" in blob

            # both planes landed
            routes = doc["summary"]["routes"]
            assert routes["http_read"]["ops"] >= 140
            assert routes["http_write"]["ops"] >= 20
            if vs._tcp_server is not None and vs._tcp_server.alive:
                assert routes["native_read"]["ops"] >= 25
            # proxied/replicated hops never recorded as workload: the
            # 4 /submit writes appear exactly once each (the submit
            # handler), and no internal-hop marker reached the journal
            submits = [r for r in doc["records"]
                       if r.get("handler") == "submit"]
            assert len(submits) == 4
            assert "internal" not in routes
            assert not any("type=proxied" in (r.get("path") or "")
                           or "type=replicate" in (r.get("path") or "")
                           for r in doc["records"])

            # shell export writes the same document to disk
            out_path = str(tmp_path / "recording.json")
            out = run_command(env,
                              f"workload.export -out {out_path}")
            assert "records" in out
            with open(out_path, encoding="utf-8") as f:
                saved = json.load(f)
            assert "HUSHSECRET42" not in json.dumps(saved)

            # /debug/reqlog serves the local ring with filters, and a
            # typo'd param answers 400 not 500
            local = http_json(
                "GET", f"http://{vs.url}/debug/reqlog?route=http_read"
                       "&limit=5", timeout=10.0)
            assert local["count"] <= 5
            assert all(r["route"] == "http_read"
                       for r in local["records"])
            st, _b, _h = http_bytes(
                "GET", f"http://{vs.url}/debug/reqlog?limit=abc",
                timeout=10.0)
            assert st == 400
            # a negative limit must not bypass the response cap and
            # dump the whole ring ([-0:] slicing bug class)
            neg = http_json(
                "GET", f"http://{vs.url}/debug/reqlog?limit=-1",
                timeout=10.0)
            assert neg["count"] == 1
            # out-of-range knobs answer 400, never a 200 that starts
            # a recorder recording nothing
            for bad in ({"sample": 0}, {"sample": 1.5}, {"size": 0}):
                st, _b, _h = http_bytes(
                    "POST", f"http://{vs.url}/debug/reqlog/start",
                    json.dumps(bad).encode(), timeout=10.0)
                assert st == 400, bad
        finally:
            vs.stop()
            m.stop()

        # replay OUTSIDE the recording cluster (the engine spawns its
        # own): open-loop at a speed that fits a short drill
        spec = spec_from_recording(saved, name="drill_replay",
                                   duration_s=3.0, clients=4)
        assert spec.target_rps > 0  # open-loop pacing engaged
        result = run_scenario(spec, base_dir=str(tmp_path))
        assert result["verdict"] == "pass", result["checks"]
        checks = replay_fidelity(saved, spec, result=result)
        assert checks and all(c["ok"] for c in checks), checks

    def test_capacity_doc_roundtrip_and_health_hint(self, tmp_path):
        """POST /cluster/capacity parks a probe result; cluster.health
        renders the one-line hint from it."""
        from seaweedfs_tpu.master.server import MasterServer
        from seaweedfs_tpu.shell import CommandEnv, run_command
        from seaweedfs_tpu.utils.httpd import HttpError, http_json

        m = MasterServer(port=free_port(), pulse_seconds=0.3).start()
        try:
            env = CommandEnv(m.url)
            with pytest.raises(HttpError) as ei:
                http_json("GET", f"http://{m.url}/cluster/capacity",
                          timeout=10.0)
            assert ei.value.status == 404
            out = run_command(env, "cluster.health")
            assert "capacity:" not in out
            http_json("POST", f"http://{m.url}/cluster/capacity",
                      {"slo": {"max_p99_ms": 5.0,
                               "max_error_ratio": 0.001},
                       "probed_at": time.time(),
                       "routes": {"http_read": {"capacity_rps": 4200.0},
                                  "native_read":
                                      {"capacity_rps": 21000.0}}},
                      timeout=10.0)
            out = run_command(env, "cluster.health")
            assert "capacity:" in out
            assert "http_read~4200rps" in out
            assert "native_read~21000rps" in out
        finally:
            m.stop()
