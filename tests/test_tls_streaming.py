"""Cluster TLS/mTLS + streaming shard/volume copy.

Gates:
- servers wrapped by security.tls speak HTTPS; with a CA configured,
  clients WITHOUT a certificate are rejected (mutual TLS) while
  cluster peers (cert + CA) interoperate transparently through the
  http:// URLs every call site already builds (weed/security/tls.go)
- volume and EC shard copies stream through bounded chunks and a
  .part temp file — no full-file buffering, no torn destination files
"""

from __future__ import annotations

import os
import ssl
import subprocess
import threading
import time
import urllib.request

import pytest

from seaweedfs_tpu.security.tls import (
    TlsConfig,
    client_context,
    enable_cluster_tls,
    server_context,
)
from seaweedfs_tpu.utils.httpd import (
    Response,
    Router,
    http_download,
    http_json,
    serve,
    set_client_tls,
    stop_server,
)
from tests.conftest import free_port


@pytest.fixture(scope="module")
def certs(tmp_path_factory):
    """Self-signed CA + one node cert signed by it (openssl CLI)."""
    d = tmp_path_factory.mktemp("certs")

    def run(*argv):
        subprocess.run(argv, check=True, capture_output=True)

    ca_key, ca_crt = str(d / "ca.key"), str(d / "ca.crt")
    run("openssl", "req", "-x509", "-newkey", "rsa:2048", "-nodes",
        "-keyout", ca_key, "-out", ca_crt, "-days", "2",
        "-subj", "/CN=test-ca")
    node_key, node_csr, node_crt = (str(d / "node.key"), str(d / "node.csr"),
                                    str(d / "node.crt"))
    run("openssl", "req", "-newkey", "rsa:2048", "-nodes",
        "-keyout", node_key, "-out", node_csr, "-subj", "/CN=node")
    run("openssl", "x509", "-req", "-in", node_csr, "-CA", ca_crt,
        "-CAkey", ca_key, "-CAcreateserial", "-out", node_crt, "-days", "2")
    return TlsConfig(ca_file=ca_crt, cert_file=node_crt, key_file=node_key)


@pytest.fixture
def tls_off():
    yield
    set_client_tls(None)  # never leak TLS state into other tests


def _tls_router():
    r = Router("tlstest")

    @r.route("GET", "/ping")
    def ping(req):
        return Response({"pong": True})

    return r


def test_mtls_rejects_certless_clients_and_accepts_peers(certs, tls_off):
    port = free_port()
    srv = serve(_tls_router(), "127.0.0.1", port,
                tls_context=server_context(certs))
    try:
        # plain http client: TLS handshake garbage -> unreachable error
        set_client_tls(None)
        try:
            http_json("GET", f"http://127.0.0.1:{port}/ping", timeout=3.0)
            assert False, "plaintext client must not succeed"
        except Exception:
            pass
        # TLS client WITHOUT a client cert: handshake rejected (mTLS)
        naked = ssl.SSLContext(ssl.PROTOCOL_TLS_CLIENT)
        naked.load_verify_locations(certs.ca_file)
        naked.check_hostname = False
        with pytest.raises(Exception):
            urllib.request.urlopen(f"https://127.0.0.1:{port}/ping",
                                   timeout=3.0, context=naked).read()
        # cluster peer (cert + CA installed process-wide): http:// URL is
        # upgraded and verified transparently
        set_client_tls(client_context(certs))
        assert http_json("GET", f"http://127.0.0.1:{port}/ping",
                         timeout=5.0) == {"pong": True}
    finally:
        stop_server(srv)


def test_enable_cluster_tls_is_one_switch(certs, tls_off):
    ctx = enable_cluster_tls(certs)
    assert ctx is not None
    port = free_port()
    srv = serve(_tls_router(), "127.0.0.1", port, tls_context=ctx)
    try:
        assert http_json("GET", f"http://127.0.0.1:{port}/ping",
                         timeout=5.0) == {"pong": True}
    finally:
        stop_server(srv)
    assert enable_cluster_tls(TlsConfig()) is None  # off = no-op


def test_http_download_streams_and_never_tears(tmp_path):
    blob = os.urandom(3 * (1 << 20) + 12345)
    src = tmp_path / "src.bin"
    src.write_bytes(blob)
    r = Router("dl")
    seen_threads = []

    @r.route("GET", "/file")
    def file_(req):
        seen_threads.append(threading.current_thread().name)
        return Response(file_path=str(src))

    @r.route("GET", "/range")
    def range_(req):
        return Response(file_path=str(src), file_range=(100, 2048))

    @r.route("GET", "/missing")
    def missing(req):
        from seaweedfs_tpu.utils.httpd import HttpError

        raise HttpError(404, "nope")

    port = free_port()
    srv = serve(r, "127.0.0.1", port)
    try:
        dest = str(tmp_path / "dest.bin")
        st = http_download("GET", f"http://127.0.0.1:{port}/file", dest)
        assert st == 200
        assert open(dest, "rb").read() == blob
        assert not os.path.exists(dest + ".part")
        # ranged streaming
        dest2 = str(tmp_path / "dest2.bin")
        st = http_download("GET", f"http://127.0.0.1:{port}/range", dest2)
        assert st == 200
        assert open(dest2, "rb").read() == blob[100:100 + 2048]
        # a failed download leaves NO file under the final name
        dest3 = str(tmp_path / "dest3.bin")
        st = http_download("GET", f"http://127.0.0.1:{port}/missing", dest3)
        assert st == 404
        assert not os.path.exists(dest3) and not os.path.exists(dest3 + ".part")
    finally:
        stop_server(srv)


def test_volume_copy_streams_end_to_end(tmp_path):
    """volume.copy across two live volume servers rides the streaming
    path; bytes land identical."""
    from seaweedfs_tpu.client.operation import WeedClient
    from seaweedfs_tpu.master.server import MasterServer
    from seaweedfs_tpu.volume_server.server import VolumeServer

    master = MasterServer(port=free_port(), pulse_seconds=0.3).start()
    dirs = []
    servers = []
    for i in range(2):
        d = tmp_path / f"vs{i}"
        d.mkdir()
        dirs.append(d)
        servers.append(VolumeServer([str(d)], master.url, port=free_port(),
                                    pulse_seconds=0.3).start())
    try:
        deadline = time.time() + 5
        while time.time() < deadline and len(master.topo.all_nodes()) < 2:
            time.sleep(0.05)
        client = WeedClient(master.url)
        fid = client.upload(os.urandom(300_000), name="big.bin")
        vid = int(fid.split(",")[0])
        src = next(vs for vs in servers if vid in vs.store.volumes)
        dst = next(vs for vs in servers if vid not in vs.store.volumes)
        http_json("POST", f"http://{dst.url}/admin/volume_copy",
                  {"volume_id": vid, "source_data_node": src.url},
                  timeout=60)
        assert vid in dst.store.volumes
        a = src.store.volumes[vid].file_prefix + ".dat"
        b = dst.store.volumes[vid].file_prefix + ".dat"
        assert open(a, "rb").read() == open(b, "rb").read()
    finally:
        for vs in servers:
            vs.stop()
        master.stop()
