"""In-process WebHDFS namenode/datanode double for HdfsRemoteStorage.

Implements the REST subset the client uses — LISTSTATUS, OPEN (with
offset/length), the two-step 307-redirect CREATE, DELETE (recursive),
MKDIRS — over an in-memory tree, mirroring the response JSON shapes the
Hadoop docs specify.
"""

from __future__ import annotations

import json
import threading
import urllib.parse
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer


class MiniHdfs:
    def __init__(self):
        self.files: dict[str, bytes] = {}       # absolute path -> bytes
        self.dirs: set[str] = {"/"}
        self.lock = threading.Lock()
        outer = self

        class Handler(BaseHTTPRequestHandler):
            protocol_version = "HTTP/1.1"

            def log_message(self, fmt, *args):
                pass

            def _reply(self, status, body=b"", headers=None):
                self.send_response(status)
                for k, v in (headers or {}).items():
                    self.send_header(k, v)
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                if body:
                    self.wfile.write(body)

            def _parts(self):
                parsed = urllib.parse.urlparse(self.path)
                assert parsed.path.startswith("/webhdfs/v1")
                fs_path = urllib.parse.unquote(
                    parsed.path[len("/webhdfs/v1"):]) or "/"
                query = dict(urllib.parse.parse_qsl(parsed.query))
                return fs_path, query

            def do_GET(self):
                fs_path, q = self._parts()
                op = q.get("op", "").upper()
                with outer.lock:
                    if op == "LISTSTATUS":
                        if fs_path not in outer.dirs:
                            self._reply(404, json.dumps({
                                "RemoteException": {
                                    "exception": "FileNotFoundException"
                                }}).encode())
                            return
                        entries = []
                        prefix = fs_path.rstrip("/") + "/"
                        seen = set()
                        for p in sorted(outer.files):
                            if p.startswith(prefix):
                                rest = p[len(prefix):]
                                name = rest.split("/", 1)[0]
                                if "/" not in rest and name not in seen:
                                    seen.add(name)
                                    entries.append({
                                        "pathSuffix": name, "type": "FILE",
                                        "length": len(outer.files[p]),
                                        "modificationTime": 1700000000000})
                        for d in sorted(outer.dirs):
                            if d.startswith(prefix):
                                rest = d[len(prefix):]
                                if rest and "/" not in rest \
                                        and rest not in seen:
                                    seen.add(rest)
                                    entries.append({
                                        "pathSuffix": rest,
                                        "type": "DIRECTORY", "length": 0,
                                        "modificationTime": 1700000000000})
                        self._reply(200, json.dumps({"FileStatuses": {
                            "FileStatus": entries}}).encode())
                    elif op == "OPEN":
                        data = outer.files.get(fs_path)
                        if data is None:
                            self._reply(404, b'{"RemoteException":{}}')
                            return
                        off = int(q.get("offset", 0))
                        length = int(q.get("length", len(data) - off))
                        self._reply(200, data[off:off + length])
                    else:
                        self._reply(400)

            def do_PUT(self):
                fs_path, q = self._parts()
                op = q.get("op", "").upper()
                n = int(self.headers.get("Content-Length") or 0)
                body = self.rfile.read(n) if n else b""
                with outer.lock:
                    if op == "CREATE":
                        if "redirected" not in q:
                            # namenode step: redirect to "the datanode"
                            loc = (f"http://127.0.0.1:{outer.port}"
                                   f"{urllib.parse.quote('/webhdfs/v1' + fs_path)}"
                                   f"?op=CREATE&redirected=1")
                            self._reply(307, headers={"Location": loc})
                            return
                        outer.files[fs_path] = body
                        d = fs_path.rsplit("/", 1)[0] or "/"
                        while d and d not in outer.dirs:
                            outer.dirs.add(d)
                            d = d.rsplit("/", 1)[0] or "/"
                        self._reply(201)
                    elif op == "MKDIRS":
                        d = fs_path
                        while d and d not in outer.dirs:
                            outer.dirs.add(d)
                            d = d.rsplit("/", 1)[0] or "/"
                        self._reply(200, b'{"boolean": true}')
                    else:
                        self._reply(400)

            def do_DELETE(self):
                fs_path, q = self._parts()
                with outer.lock:
                    existed = outer.files.pop(fs_path, None) is not None
                    if q.get("recursive") == "true":
                        pref = fs_path.rstrip("/") + "/"
                        for p in [p for p in outer.files
                                  if p.startswith(pref)]:
                            del outer.files[p]
                            existed = True
                        for d in [d for d in outer.dirs
                                  if d.startswith(pref) or d == fs_path]:
                            outer.dirs.discard(d)
                            existed = True
                    self._reply(200, json.dumps(
                        {"boolean": existed}).encode())

        self._srv = ThreadingHTTPServer(("127.0.0.1", 0), Handler)
        self._srv.daemon_threads = True
        self.port = self._srv.server_address[1]
        threading.Thread(target=self._srv.serve_forever, daemon=True).start()

    def stop(self):
        self._srv.shutdown()
        self._srv.server_close()
