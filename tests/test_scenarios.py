"""Scenario harness (seaweedfs_tpu/scenarios) — tier-1.

Gates: the workload samplers have the distributions they claim, specs
round-trip, a live read scenario produces the full verdicted result
document with zero deadline violations, and a live failure-under-load
mini-drill degrades the partitioned fraction while the healthy
fraction keeps serving — the bench `scenarios` section's contract in
miniature.
"""

from __future__ import annotations

import random

import pytest

from seaweedfs_tpu.scenarios import (FaultSpec, ScenarioSpec, SizeSampler,
                                     ZipfSampler, default_scenarios,
                                     run_scenario)
from seaweedfs_tpu.scenarios.workload import payload_for, pick_op
from seaweedfs_tpu.utils import faultinject as fi


@pytest.fixture(autouse=True)
def _clean_faults():
    fi.clear()
    yield
    fi.clear()


class TestWorkload:
    def test_zipf_rank0_hottest_and_skew_orders(self):
        rng = random.Random(7)
        z = ZipfSampler(64, 1.2)
        counts = [0] * 64
        for _ in range(20000):
            counts[z.sample(rng)] += 1
        assert counts[0] == max(counts)
        assert counts[0] > 4 * counts[32]
        # pmf is monotone non-increasing in rank
        pmf = [z.pmf(r) for r in range(64)]
        assert all(a >= b for a, b in zip(pmf, pmf[1:]))
        assert abs(sum(pmf) - 1.0) < 1e-9

    def test_zipf_never_out_of_range(self):
        rng = random.Random(1)
        z = ZipfSampler(5, 1.0)
        assert all(0 <= z.sample(rng) < 5 for _ in range(2000))

    def test_size_sampler_respects_weights(self):
        rng = random.Random(3)
        s = SizeSampler(((4096, 0.9), (1 << 20, 0.1)))
        got = [s.sample(rng) for _ in range(5000)]
        small = sum(1 for b in got if b == 4096)
        assert 0.82 < small / len(got) < 0.97

    def test_pick_op_mix(self):
        rng = random.Random(5)
        ops = [pick_op(rng, 0.7, 0.5) for _ in range(8000)]
        reads = ops.count("read") / len(ops)
        assert 0.65 < reads < 0.75
        writes, deletes = ops.count("write"), ops.count("delete")
        assert writes and deletes

    def test_payload_distinct_and_sized(self):
        assert len(payload_for(4096, 3)) == 4096
        assert payload_for(16, 1) != payload_for(16, 2)


class TestSpec:
    def test_round_trip(self):
        spec = default_scenarios()[-1]
        doc = spec.to_dict()
        back = ScenarioSpec.from_dict(doc)
        assert back == spec

    def test_defaults_cover_the_three_canonical_shapes(self):
        names = [s.name for s in default_scenarios()]
        assert names == ["read_storm", "write_churn",
                         "failure_under_load"]
        fail = default_scenarios()[-1]
        assert fail.faults and fail.faults[0].point == "net.partition"
        assert fail.expectations["fault_rps_ratio_min"] >= 0.6


class TestLiveScenario:
    def test_read_scenario_result_document(self, tmp_path):
        spec = ScenarioSpec(name="mini_read", duration_s=2.5, clients=4,
                            hot_set=16, zipf_s=1.1, deadline_s=2.0,
                            expectations={
                                "max_error_ratio": 0.02,
                                "deadline_overrun_max_ms": 250.0})
        res = run_scenario(spec, base_dir=str(tmp_path))
        assert res["verdict"] == "pass", res["checks"]
        r = res["routes"]["read"]
        assert r["ops"] > 50 and r["error_ratio"] <= 0.02
        assert r["p99_ms"] > 0
        assert res["deadline"]["violations"] == 0
        assert res["phases"]["healthy"]["ok_rps"] > 0
        assert set(res["counters"]) == {"requests_shed",
                                        "deadline_exceeded",
                                        "retry_budget_exhausted"}
        # spec echo rides the document so bench JSON is self-describing
        assert res["spec"]["name"] == "mini_read"

    def test_failure_under_load_mini_drill(self, tmp_path):
        """3 servers, the middle third partitioned: the partitioned
        fraction fails FAST (errors, not stalls), the healthy fraction
        keeps serving, nothing outlives its deadline, and the fault
        timeline + alert record land in the document."""
        spec = ScenarioSpec(
            name="mini_fail", duration_s=7.5, clients=4,
            n_volume_servers=3, read_fraction=0.85,
            submit_fraction=0.5, hot_set=36, zipf_s=1.0,
            deadline_s=2.0, max_inflight=64,
            faults=(FaultSpec(point="net.partition", at_frac=1 / 3,
                              clear_frac=2 / 3, peer="vs0"),),
            expectations={"deadline_overrun_max_ms": 250.0})
        res = run_scenario(spec, base_dir=str(tmp_path))
        actions = [f["action"] for f in res["faults"]]
        assert actions == ["arm", "clear"]
        ph = res["phases"]
        assert set(ph) == {"healthy", "fault", "recovery"}
        # the partition hurt: mid-run errors appeared...
        assert ph["fault"]["error_ratio"] > 0.02
        # ...but the healthy fraction kept serving at real throughput
        assert ph["fault"]["ok_rps"] > 0.3 * ph["healthy"]["ok_rps"]
        # and recovered after the clear
        assert ph["recovery"]["error_ratio"] < ph["fault"]["error_ratio"]
        # fail-fast, never hang: nothing outlived deadline + 250ms
        assert res["deadline"]["violations"] == 0
        assert res["verdict"] == "pass", res["checks"]
        assert "alerts" in res and "timeline" in res["alerts"]
