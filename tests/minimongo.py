"""In-process MongoDB OP_MSG double for MongoStore tests.

Speaks the wire format the client uses — OP_MSG framing with kind-0
BSON sections — and implements find (equality + $gt/$gte/$lt/$lte on
one field, sort, limit), update with upsert, delete, and the
SCRAM-SHA-256 saslStart/saslContinue exchange when a password is
configured.  Storage is a list of dicts per (db, collection).
"""

from __future__ import annotations

import base64
import hashlib
import hmac
import os
import socket
import struct
import threading

from seaweedfs_tpu.filer import bson_lite as bson

OP_MSG = 2013


def _match(doc: dict, filt: dict) -> bool:
    for k, cond in filt.items():
        v = doc.get(k)
        if isinstance(cond, dict):
            for op, bound in cond.items():
                if op == "$gt" and not (v is not None and v > bound):
                    return False
                elif op == "$gte" and not (v is not None and v >= bound):
                    return False
                elif op == "$lt" and not (v is not None and v < bound):
                    return False
                elif op == "$lte" and not (v is not None and v <= bound):
                    return False
                elif op not in ("$gt", "$gte", "$lt", "$lte"):
                    raise ValueError(f"unsupported op {op}")
        elif v != cond:
            return False
    return True


class MiniMongo:
    def __init__(self, username: str = "", password: str = "",
                 tamper: str = ""):
        self.username, self.password = username, password
        self.tamper = tamper          # "" | "server_sig" (SCRAM drill)
        self.kill_cursors = False     # getMore -> CursorNotFound drill
        self.exhaust_once = False     # next find streams a moreToCome decoy
        self.colls: dict[tuple[str, str], list[dict]] = {}
        self.cursors: dict[int, list[dict]] = {}
        self._cursor_id = 0
        self.batch_cap = 4  # small: forces the client's getMore path
        self.lock = threading.Lock()
        self._sock = socket.socket()
        self._sock.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._sock.bind(("127.0.0.1", 0))
        self._sock.listen(8)
        self.port = self._sock.getsockname()[1]
        self._stop = False
        threading.Thread(target=self._accept, daemon=True,
                         name="minimongo").start()

    def stop(self) -> None:
        self._stop = True
        try:
            self._sock.close()
        except OSError:
            pass

    def _accept(self) -> None:
        while not self._stop:
            try:
                conn, _ = self._sock.accept()
            except OSError:
                return
            threading.Thread(target=self._serve, args=(conn,),
                             daemon=True).start()

    @staticmethod
    def _read_exact(conn, n: int) -> bytes:
        buf = b""
        while len(buf) < n:
            chunk = conn.recv(n - len(buf))
            if not chunk:
                raise ConnectionError
            buf += chunk
        return buf

    def _serve(self, conn) -> None:
        state = {"authed": not self.username, "scram": None}
        try:
            with conn:
                while True:
                    hdr = self._read_exact(conn, 16)
                    ln, req_id, _, opcode = struct.unpack("<iiii", hdr)
                    payload = self._read_exact(conn, ln - 16)
                    if opcode != OP_MSG or payload[4] != 0:
                        return
                    doc = bson.decode(payload[5:])
                    reply = self._handle(doc, state)
                    if self.exhaust_once and next(iter(doc)) == "find":
                        # nonconforming exhaust drill: stream a prelude
                        # reply with moreToCome (0x2) set, then the real
                        # one — the client never requested exhaustAllowed
                        # and must drain to the final message or desync
                        self.exhaust_once = False
                        decoy = bson.encode({"ok": 1, "cursor": {
                            "id": 0, "ns": "", "firstBatch": []}})
                        out = struct.pack("<I", 0x2) + b"\x00" + decoy
                        conn.sendall(struct.pack(
                            "<iiii", 16 + len(out), 0, req_id, OP_MSG) + out)
                    body = bson.encode(reply)
                    out = struct.pack("<I", 0) + b"\x00" + body
                    conn.sendall(struct.pack(
                        "<iiii", 16 + len(out), 0, req_id, OP_MSG) + out)
        except (ConnectionError, OSError, ValueError, struct.error):
            pass

    # --- commands ---------------------------------------------------------
    def _handle(self, doc: dict, state: dict) -> dict:
        op = next(iter(doc))
        if op == "saslStart":
            return self._sasl_start(doc, state)
        if op == "saslContinue":
            return self._sasl_continue(doc, state)
        if not state["authed"]:
            return {"ok": 0, "errmsg": "authentication required",
                    "code": 13}
        db = doc.get("$db", "test")
        if op == "find":
            key = (db, doc["find"])
            with self.lock:
                docs = [d for d in self.colls.get(key, [])
                        if _match(d, doc.get("filter", {}))]
            for field, direction in (doc.get("sort") or {}).items():
                docs.sort(key=lambda d: d.get(field),
                          reverse=direction < 0)
            limit = doc.get("limit") or len(docs)
            docs = [dict(d) for d in docs[:limit]]
            first, rest = docs[:self.batch_cap], docs[self.batch_cap:]
            cid = 0
            if rest:
                with self.lock:
                    self._cursor_id += 1
                    cid = self._cursor_id
                    self.cursors[cid] = rest
            return {"ok": 1, "cursor": {
                "id": cid, "ns": f"{db}.{doc['find']}",
                "firstBatch": first}}
        if op == "getMore":
            cid = doc["getMore"]
            if self.kill_cursors:
                # cursor-death drill (timeout/failover on a real mongod):
                # the canonical CursorNotFound error document
                self.cursors.pop(cid, None)
                return {"ok": 0, "code": 43, "codeName": "CursorNotFound",
                        "errmsg": f"cursor id {cid} not found"}
            with self.lock:
                rest = self.cursors.get(cid, [])
                batch, rest = rest[:self.batch_cap], rest[self.batch_cap:]
                if rest:
                    self.cursors[cid] = rest
                else:
                    self.cursors.pop(cid, None)
                    cid = 0
            return {"ok": 1, "cursor": {
                "id": cid, "ns": "", "nextBatch": batch}}
        if op == "update":
            key = (db, doc["update"])
            n = upserted = 0
            with self.lock:
                coll = self.colls.setdefault(key, [])
                for u in doc["updates"]:
                    hit = [d for d in coll if _match(d, u["q"])]
                    if hit:
                        hit[0].clear()
                        hit[0].update(u["u"])
                        n += 1
                    elif u.get("upsert"):
                        coll.append(dict(u["u"]))
                        upserted += 1
            return {"ok": 1, "n": n + upserted, "nModified": n}
        if op == "delete":
            key = (db, doc["delete"])
            n = 0
            with self.lock:
                coll = self.colls.setdefault(key, [])
                for dl in doc["deletes"]:
                    hits = [d for d in coll if _match(d, dl["q"])]
                    lim = dl.get("limit", 0) or len(hits)
                    for h in hits[:lim]:
                        coll.remove(h)
                        n += 1
            return {"ok": 1, "n": n}
        return {"ok": 0, "errmsg": f"no such command: {op}"}

    # --- SCRAM-SHA-256 ----------------------------------------------------
    def _sasl_start(self, doc: dict, state: dict) -> dict:
        body = bytes(doc["payload"]).decode()
        client_first_bare = body.split(",", 2)[2]
        client_nonce = dict(p.split("=", 1)
                            for p in client_first_bare.split(","))["r"]
        salt, iters = os.urandom(16), 4096
        server_nonce = client_nonce + \
            base64.b64encode(os.urandom(9)).decode()
        server_first = (f"r={server_nonce},"
                        f"s={base64.b64encode(salt).decode()},i={iters}")
        state["scram"] = (client_first_bare, server_first, salt, iters)
        return {"ok": 1, "conversationId": 1, "done": False,
                "payload": server_first.encode()}

    def _sasl_continue(self, doc: dict, state: dict) -> dict:
        if state["scram"] is None:
            return {"ok": 0, "errmsg": "no sasl in progress"}
        client_first_bare, server_first, salt, iters = state["scram"]
        final = bytes(doc["payload"]).decode()
        fparts = dict(p.split("=", 1) for p in final.split(","))
        salted = hashlib.pbkdf2_hmac("sha256", self.password.encode(),
                                     salt, iters)
        ckey = hmac.new(salted, b"Client Key", hashlib.sha256).digest()
        stored = hashlib.sha256(ckey).digest()
        without_proof = final[:final.rindex(",p=")]
        auth_msg = f"{client_first_bare},{server_first},{without_proof}"
        sig = hmac.new(stored, auth_msg.encode(), hashlib.sha256).digest()
        want = bytes(a ^ b for a, b in zip(ckey, sig))
        if base64.b64decode(fparts["p"]) != want:
            return {"ok": 0, "errmsg": "authentication failed", "code": 18}
        state["authed"] = True
        state["scram"] = None
        skey = hmac.new(salted, b"Server Key", hashlib.sha256).digest()
        v = hmac.new(skey, auth_msg.encode(), hashlib.sha256).digest()
        if self.tamper == "server_sig":
            # impersonator drill: correct flow, forged ServerSignature
            v = bytes(32)
        return {"ok": 1, "conversationId": 1, "done": True,
                "payload": b"v=" + base64.b64encode(v)}
