"""Span tracer core: threading, nesting, ring bound, exporters, merging.

Covers observability/tracer.py — the layer every perf PR reads timelines
from, so its invariants (consistent parent/child trees under concurrency,
bounded memory, strictly-increasing Chrome timestamps, collision-free
cross-process merges, ~zero disabled cost) are pinned here.
"""

from __future__ import annotations

import json
import threading
import time

from seaweedfs_tpu.observability import Tracer
from seaweedfs_tpu.observability.tracer import _NOOP


class TestTracerCore:
    def test_basic_span_nesting(self):
        tr = Tracer()
        with tr.span("outer", op="o"):
            with tr.span("inner", op="i"):
                pass
        spans = {s.name: s for s in tr.snapshot()}
        assert spans["inner"].parent_id == spans["outer"].span_id
        assert spans["outer"].parent_id is None
        assert spans["inner"].t0 >= spans["outer"].t0
        assert spans["inner"].t1 <= spans["outer"].t1
        assert spans["inner"].attrs == {"op": "i"}

    def test_concurrent_threads_consistent_tree(self):
        """≥4 threads nesting concurrently: every inner span parents to
        ITS thread's outer span, never across threads."""
        tr = Tracer(capacity=4096)
        n_threads, n_inner = 6, 25
        barrier = threading.Barrier(n_threads)

        def work(i):
            barrier.wait()
            with tr.span("outer", worker=i):
                for j in range(n_inner):
                    with tr.span("inner", worker=i, j=j):
                        pass

        threads = [threading.Thread(target=work, args=(i,))
                   for i in range(n_threads)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        spans = tr.snapshot()
        assert len(spans) == n_threads * (1 + n_inner)
        outer_by_worker = {s.attrs["worker"]: s for s in spans
                          if s.name == "outer"}
        assert len(outer_by_worker) == n_threads
        for s in spans:
            if s.name == "inner":
                want = outer_by_worker[s.attrs["worker"]]
                assert s.parent_id == want.span_id
                assert s.tid == want.tid
        # ids are unique
        ids = [s.span_id for s in spans]
        assert len(set(ids)) == len(ids)

    def test_ring_buffer_never_exceeds_bound(self):
        tr = Tracer(capacity=64)
        for i in range(1000):
            with tr.span("s", i=i):
                pass
            assert len(tr.snapshot()) <= 64
        spans = tr.snapshot()
        assert len(spans) == 64
        # oldest evicted, newest kept
        assert spans[-1].attrs["i"] == 999

    def test_exception_tags_span_and_propagates(self):
        tr = Tracer()
        try:
            with tr.span("boom"):
                raise ValueError("x")
        except ValueError:
            pass
        (sp,) = tr.snapshot()
        assert sp.attrs["error"] == "ValueError"

    def test_disabled_tracer_is_noop(self):
        tr = Tracer(enabled=False)
        assert tr.span("x") is _NOOP
        with tr.span("x", a=1):
            pass
        assert tr.snapshot() == []
        assert tr.add_span("y", 0.0, 1.0) is None

    def test_disabled_span_overhead_is_negligible(self):
        """The dormant-instrumentation budget: the acceptance bar is <2%
        overhead on an untraced encode.  A dispatch carries ~6 span
        sites and takes >=1ms of real work, so the per-span cost must
        be micro-seconds at most — asserted with a generous margin."""
        tr = Tracer(enabled=False)
        n = 20_000
        t0 = time.perf_counter()
        for i in range(n):
            with tr.span("hot", dispatch=i, bytes=4096):
                pass
        per_span = (time.perf_counter() - t0) / n
        # 50µs/span would still be far under 2% of a 20ms dispatch with
        # 6 sites; real cost is ~1µs
        assert per_span < 50e-6


class TestChromeExport:
    def test_round_trip_and_strictly_increasing_ts(self):
        tr = Tracer()

        def work(i):
            for j in range(20):
                with tr.span("op", i=i, j=j):
                    with tr.span("sub", i=i, j=j):
                        pass

        threads = [threading.Thread(target=work, args=(i,))
                   for i in range(4)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        doc = json.loads(json.dumps(tr.to_chrome()))
        events = [e for e in doc["traceEvents"] if e.get("ph") == "X"]
        assert len(events) == 4 * 20 * 2
        last: dict = {}
        for e in events:
            assert e["dur"] > 0
            key = (e["pid"], e["tid"])
            if key in last:
                assert e["ts"] > last[key], "ts not strictly increasing"
            last[key] = e["ts"]
        # metadata names every process and thread track
        meta = [e for e in doc["traceEvents"] if e.get("ph") == "M"]
        assert any(m["name"] == "process_name" for m in meta)
        assert any(m["name"] == "thread_name" for m in meta)

    def test_empty_tracer_exports_empty_doc(self):
        doc = Tracer().to_chrome()
        assert doc["traceEvents"] == []
        json.loads(json.dumps(doc))


class TestCrossProcessMerge:
    def test_worker_logs_merge_without_id_collisions(self):
        """Two 'worker' tracers whose namespaces collide (same pid in a
        fork-like world) merge into the parent with caller-supplied
        namespaces: all ids stay unique and roots reparent under the
        given span."""
        main = Tracer(namespace="main")
        w1 = Tracer(namespace="w")   # deliberately identical namespaces
        w2 = Tracer(namespace="w")
        with main.span("root") as root:
            for w in (w1, w2):
                with w.span("compute", job=1):
                    with w.span("inner"):
                        pass
        main.ingest_log(w1.export_log(), parent_id=root.span_id,
                        namespace="w1")
        main.ingest_log(w2.export_log(), parent_id=root.span_id,
                        namespace="w2")
        spans = main.snapshot()
        ids = [s.span_id for s in spans]
        assert len(set(ids)) == len(ids) == 5
        by_id = {s.span_id: s for s in spans}
        root_span = next(s for s in spans if s.name == "root")
        for s in spans:
            if s.name == "compute":
                assert s.parent_id == root_span.span_id
            if s.name == "inner":
                assert by_id[s.parent_id].name == "compute"

    def test_distinct_default_namespaces_merge_directly(self):
        a = Tracer(namespace="pa")
        b = Tracer(namespace="pb")
        with a.span("x"):
            pass
        with b.span("x"):
            pass
        a.ingest_log(b.export_log())
        ids = [s.span_id for s in a.snapshot()]
        assert len(set(ids)) == 2

    def test_add_span_external_timing(self):
        tr = Tracer()
        with tr.span("root") as root:
            sid = tr.add_span("worker.compute", 100.0, 100.5,
                              parent_id=root.span_id, tid=4242,
                              dispatch=3)
        spans = {s.name: s for s in tr.snapshot()}
        w = spans["worker.compute"]
        assert w.span_id == sid
        assert w.parent_id == spans["root"].span_id
        assert w.tid == 4242
        assert abs(w.duration - 0.5) < 1e-9
        assert w.attrs["dispatch"] == 3


class TestPrometheusBridge:
    def test_span_durations_feed_metrics_registry(self):
        from seaweedfs_tpu.stats import REGISTRY

        tr = Tracer(prometheus=True)
        with tr.span("bridge.test"):
            time.sleep(0.002)
        text = REGISTRY.expose()
        assert 'SeaweedFS_trace_span_seconds_bucket{name="bridge.test"' \
            in text
        assert 'SeaweedFS_trace_span_seconds_count{name="bridge.test"} 1' \
            in text

    def test_global_enable_disable(self):
        from seaweedfs_tpu.observability import (disable_tracing,
                                                 enable_tracing, get_tracer)

        tr = enable_tracing(capacity=128)
        try:
            assert tr is get_tracer()
            assert tr.capacity == 128
            tr.clear()
            with tr.span("global.s"):
                pass
            assert any(s.name == "global.s" for s in tr.snapshot())
        finally:
            disable_tracing()
            tr.clear()
        assert get_tracer().span("x") is _NOOP


class TestConcurrentExport:
    """Exporters racing span() writers on the bounded ring: the dump
    endpoints (/debug/traces, /metrics) run on HTTP handler threads
    while the pipeline keeps recording — no exception, monotonic
    timestamps, no torn spans."""

    def _hammer(self, tr, n_threads=4, spin=0.25):
        stop = threading.Event()
        errors: list = []

        def writer(i):
            j = 0
            try:
                while not stop.is_set():
                    with tr.span("hot", worker=i, j=j):
                        with tr.span("inner", worker=i):
                            pass
                    j += 1
            except Exception as e:  # pragma: no cover - the failure mode
                errors.append(e)

        threads = [threading.Thread(target=writer, args=(i,))
                   for i in range(n_threads)]
        for t in threads:
            t.start()
        return stop, threads, errors

    def test_chrome_export_races_writers(self):
        tr = Tracer(capacity=512)
        stop, threads, errors = self._hammer(tr)
        try:
            deadline = time.time() + 0.6
            docs = 0
            while time.time() < deadline:
                doc = tr.to_chrome(clear=(docs % 3 == 0))
                events = [e for e in doc["traceEvents"]
                          if e.get("ph") == "X"]
                last: dict = {}
                for e in events:
                    # no torn span: every field present and sane
                    assert e["dur"] > 0 and e["name"] in ("hot", "inner")
                    assert "span_id" in e["args"]
                    key = (e["pid"], e["tid"])
                    if key in last:
                        assert e["ts"] > last[key]
                    last[key] = e["ts"]
                json.dumps(doc)  # serializable mid-race
                docs += 1
        finally:
            stop.set()
            for t in threads:
                t.join()
        assert not errors
        assert docs > 0

    def test_prometheus_bridge_races_writers(self):
        from seaweedfs_tpu.stats import REGISTRY

        tr = Tracer(capacity=256, prometheus=True)
        stop, threads, errors = self._hammer(tr, n_threads=3)
        try:
            deadline = time.time() + 0.4
            while time.time() < deadline:
                text = REGISTRY.expose()
                assert "SeaweedFS_trace_span_seconds" in text
        finally:
            stop.set()
            for t in threads:
                t.join()
        assert not errors
        # bucket counts never exceed totals (no torn histogram rows)
        hist = next(c for c in REGISTRY._collectors
                    if getattr(c, "name", "") ==
                    "SeaweedFS_trace_span_seconds")
        for key, (counts, _s, total) in hist.snapshot().items():
            assert sum(counts) <= total

    def test_export_log_and_snapshot_clear_race(self):
        """poll-and-clear capture loop under writer load: every span is
        seen at most once and none is torn."""
        tr = Tracer(capacity=4096)
        stop, threads, errors = self._hammer(tr, n_threads=3)
        seen: set = set()
        try:
            deadline = time.time() + 0.4
            while time.time() < deadline:
                for e in tr.export_log():
                    assert e["t1"] >= e["t0"]
                for sp in tr.snapshot(clear=True):
                    assert sp.span_id not in seen  # at-most-once
                    seen.add(sp.span_id)
        finally:
            stop.set()
            for t in threads:
                t.join()
        assert not errors and seen


class TestToDictRoundTrip:
    def test_round_trip_preserves_spans_exactly(self):
        tr = Tracer(namespace="src", capacity=128)
        with tr.span("outer", k=1):
            with tr.span("inner"):
                pass
        tr.add_span("worker.compute", 10.0, 10.5, tid=777, dispatch=2)
        doc = json.loads(json.dumps(tr.to_dict()))
        back = Tracer.from_dict(doc)
        orig = {s.span_id: s for s in tr.snapshot()}
        got = {s.span_id: s for s in back.snapshot()}
        assert set(got) == set(orig)
        for sid, s in got.items():
            o = orig[sid]
            assert (s.name, s.parent_id, s.t0, s.t1, s.attrs, s.tid) == \
                (o.name, o.parent_id, o.t0, o.t1, o.attrs, o.tid)
        assert back.namespace == "src"
        assert back.capacity >= 3

    def test_from_dict_capacity_fits_spans(self):
        tr = Tracer(capacity=8)
        for i in range(8):
            with tr.span("s", i=i):
                pass
        doc = tr.to_dict()
        doc["capacity"] = 2  # hostile/old doc: must not drop spans
        assert len(Tracer.from_dict(doc).snapshot()) == 8


class TestSamplingProfiler:
    def test_busy_thread_shows_in_collapsed_output(self):
        from seaweedfs_tpu.observability import SamplingProfiler

        stop = threading.Event()

        def busy_loop_marker():
            while not stop.is_set():
                sum(i * i for i in range(500))

        th = threading.Thread(target=busy_loop_marker,
                              name="busy-marker")
        th.start()
        # a loaded CI box can stretch each sampling iteration past the
        # 4ms period (sys._current_frames walks every thread): widen the
        # window until enough samples landed instead of flaking
        prof = SamplingProfiler(hz=250)
        for _ in range(4):
            prof.run_for(0.4)
            if prof.samples > 10:
                break
        stop.set()
        th.join()
        assert prof.samples > 10
        col = prof.collapsed()
        assert "busy-marker" in col and "busy_loop_marker" in col
        # collapsed-stack grammar: `frames... count` per line, root-first
        for line in col.splitlines():
            stack, _, count = line.rpartition(" ")
            assert stack and count.isdigit()
        # the text report renders the same data
        rep = prof.report_text()
        assert "self time" in rep and "cumulative" in rep

    def test_bounded_unique_stacks(self):
        from seaweedfs_tpu.observability import SamplingProfiler

        prof = SamplingProfiler(hz=100, max_stacks=1)
        # synthetic samples: distinct stacks past the bound collapse
        # into the overflow bucket instead of growing memory
        prof._counts[("t", (("f.py", 1, "a"),))] = 1
        for i in range(50):
            prof._sample_once(set())
        assert len(prof._counts) <= 2  # bound + overflow bucket
        assert prof.dropped > 0
        assert "(overflow)" in prof.collapsed()

    def test_run_for_excludes_caller_thread(self):
        from seaweedfs_tpu.observability import SamplingProfiler

        prof = SamplingProfiler(hz=200)
        prof.run_for(0.2)
        me = threading.current_thread().name
        assert all(not line.startswith(me + ";") and "run_for" not in line
                   for line in prof.collapsed().splitlines())
