"""In-process Kafka broker double for the wire-protocol producer tests.

Serves Metadata v1 (reporting itself leader for `partitions` partitions)
and Produce v3, fully decoding RecordBatch v2 — header layout, castagnoli
CRC over the batch body, and zigzag-varint records — so the producer's
bytes are verified exactly as a real >= 0.11 broker would.
"""

from __future__ import annotations

import socket
import struct
import threading

from seaweedfs_tpu.replication.kafka import I16, I32, I64, U32, dec_varint
from seaweedfs_tpu.storage.crc import crc32c


class MiniKafka:
    def __init__(self, partitions: int = 2, fail_produce_times: int = 0):
        self.partitions = partitions
        self.records: dict[tuple[str, int], list[tuple[bytes, bytes]]] = {}
        self.crc_errors = 0
        self.fail_produce_times = fail_produce_times  # NOT_LEADER replies
        self.lock = threading.Lock()
        self._srv = socket.socket()
        self._srv.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._srv.bind(("127.0.0.1", 0))
        self._srv.listen(8)
        self.port = self._srv.getsockname()[1]
        self._stop = False
        threading.Thread(target=self._accept, daemon=True).start()

    def stop(self) -> None:
        self._stop = True
        try:
            self._srv.close()
        except OSError:
            pass

    def _accept(self) -> None:
        while not self._stop:
            try:
                conn, _ = self._srv.accept()
            except OSError:
                return
            threading.Thread(target=self._serve, args=(conn,),
                             daemon=True).start()

    @staticmethod
    def _recv_exact(conn, n):
        buf = bytearray()
        while len(buf) < n:
            piece = conn.recv(n - len(buf))
            if not piece:
                raise ConnectionError
            buf += piece
        return bytes(buf)

    def _serve(self, conn: socket.socket) -> None:
        try:
            while True:
                n = I32.unpack(self._recv_exact(conn, 4))[0]
                req = self._recv_exact(conn, n)
                api_key, api_version, corr = struct.unpack(">hhi", req[:8])
                i = 8
                cid_len = I16.unpack_from(req, i)[0]
                i += 2 + max(0, cid_len)
                if api_key == 3:
                    resp = self._metadata(req, i)
                elif api_key == 0:
                    resp = self._produce(req, i)
                else:
                    resp = b""
                payload = I32.pack(corr) + resp
                conn.sendall(I32.pack(len(payload)) + payload)
        except (ConnectionError, OSError):
            pass
        finally:
            conn.close()

    # -- Metadata v1 ---------------------------------------------------------
    def _metadata(self, req: bytes, i: int) -> bytes:
        n_topics = I32.unpack_from(req, i)[0]
        i += 4
        topics = []
        for _ in range(n_topics):
            tl = I16.unpack_from(req, i)[0]
            i += 2
            topics.append(req[i:i + tl].decode())
            i += tl
        out = bytearray()
        out += I32.pack(1)                      # one broker: us
        out += I32.pack(0)                      # node id
        out += I16.pack(9) + b"127.0.0.1"
        out += I32.pack(self.port)
        out += I16.pack(-1)                     # rack null
        out += I32.pack(0)                      # controller id
        out += I32.pack(len(topics))
        for t in topics:
            out += I16.pack(0)                  # error
            out += I16.pack(len(t)) + t.encode()
            out += b"\x00"                      # is_internal
            out += I32.pack(self.partitions)
            for p in range(self.partitions):
                out += I16.pack(0)              # error
                out += I32.pack(p)
                out += I32.pack(0)              # leader = us
                out += I32.pack(1) + I32.pack(0)  # replicas
                out += I32.pack(1) + I32.pack(0)  # isr
        return bytes(out)

    # -- Produce v3 ----------------------------------------------------------
    def _produce(self, req: bytes, i: int) -> bytes:
        tx_len = I16.unpack_from(req, i)[0]
        i += 2 + max(0, tx_len)
        i += 2 + 4                              # acks, timeout
        n_topics = I32.unpack_from(req, i)[0]
        i += 4
        out_topics = bytearray()
        for _ in range(n_topics):
            tl = I16.unpack_from(req, i)[0]
            i += 2
            topic = req[i:i + tl].decode()
            i += tl
            n_parts = I32.unpack_from(req, i)[0]
            i += 4
            parts_out = bytearray()
            for _ in range(n_parts):
                pid = I32.unpack_from(req, i)[0]
                i += 4
                blen = I32.unpack_from(req, i)[0]
                i += 4
                batch = req[i:i + blen]
                i += blen
                err = self._ingest(topic, pid, batch)
                parts_out += I32.pack(pid) + I16.pack(err)
                parts_out += I64.pack(0)        # base offset
                parts_out += I64.pack(-1)       # log append time
            out_topics += (I16.pack(len(topic)) + topic.encode()
                           + I32.pack(n_parts) + parts_out)
        return (I32.pack(n_topics) + bytes(out_topics)
                + I32.pack(0))                  # throttle_time_ms

    def _ingest(self, topic: str, pid: int, batch: bytes) -> int:
        with self.lock:
            if self.fail_produce_times > 0:
                self.fail_produce_times -= 1
                return 6  # NOT_LEADER_FOR_PARTITION
        # RecordBatch v2 header
        # 0:8 baseOffset | 8:12 batchLength | 12:16 leaderEpoch |
        # 16 magic | 17:21 crc | 21.. crc-covered body
        if batch[16] != 2:
            return 87  # INVALID_RECORD
        stored_crc = U32.unpack_from(batch, 17)[0]
        body = batch[21:]
        if crc32c(body) != stored_crc:
            with self.lock:
                self.crc_errors += 1
            return 87
        r = 2 + 4 + 8 + 8 + 8 + 2 + 4          # attrs..baseSequence
        count = I32.unpack_from(body, r)[0]
        j = r + 4
        got = []
        for _ in range(count):
            rec_len, j = dec_varint(body, j)
            end = j + rec_len
            j += 1                              # attributes
            _, j = dec_varint(body, j)          # timestampDelta
            _, j = dec_varint(body, j)          # offsetDelta
            klen, j = dec_varint(body, j)
            key = body[j:j + klen]
            j += klen
            vlen, j = dec_varint(body, j)
            value = body[j:j + vlen]
            j += vlen
            nh, j = dec_varint(body, j)
            assert nh == 0 and j == end
            got.append((bytes(key), bytes(value)))
        with self.lock:
            self.records.setdefault((topic, pid), []).extend(got)
        return 0
