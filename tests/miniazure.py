"""In-process Azure Blob service double for AzureRemoteStorage tests.

Implements the REST subset the client uses — container create/delete/
list, List Blobs (flat, prefix, NextMarker paging), Put/Get/Delete Blob,
Range reads — and VERIFIES the SharedKey signature of every request
against the same canonicalization the real service documents, so the
client's signing is proven self-consistent end-to-end.
"""

from __future__ import annotations

import base64
import hashlib
import hmac
import threading
import urllib.parse
from xml.sax.saxutils import escape
from email.utils import formatdate
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer


class MiniAzure:
    def __init__(self, account: str = "devacct",
                 key: bytes = b"0123456789abcdef" * 2,
                 page_size: int = 1000):
        self.account = account
        self.key = key
        self.key_b64 = base64.b64encode(key).decode()
        self.page_size = page_size
        # containers -> {blob name -> bytes}
        self.containers: dict[str, dict[str, bytes]] = {}
        self.lock = threading.Lock()
        outer = self

        class Handler(BaseHTTPRequestHandler):
            protocol_version = "HTTP/1.1"

            def log_message(self, fmt, *args):
                pass

            def _reply(self, status: int, body: bytes = b"",
                       headers: dict | None = None):
                self.send_response(status)
                for k, v in (headers or {}).items():
                    self.send_header(k, v)
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                if body:
                    self.wfile.write(body)

            def _check_sig(self, body: bytes) -> bool:
                auth = self.headers.get("Authorization", "")
                if not auth.startswith(f"SharedKey {outer.account}:"):
                    return False
                given = auth.rsplit(":", 1)[1]
                parsed = urllib.parse.urlparse(self.path)
                query = dict(urllib.parse.parse_qsl(parsed.query))
                xms = sorted(
                    (k.lower(), v) for k, v in self.headers.items()
                    if k.lower().startswith("x-ms-"))
                canon_headers = "".join(f"{k}:{v}\n" for k, v in xms)
                # canonicalized resource = "/" + account + FULL URI
                # path (account duplicated for path-style endpoints,
                # azurite's documented rule)
                res = f"/{outer.account}" + urllib.parse.unquote(parsed.path)
                for k in sorted(query):
                    res += f"\n{k.lower()}:{query[k]}"
                length = str(len(body)) if body else ""
                sts = "\n".join([
                    self.command, "", "", length, "",
                    self.headers.get("Content-Type", ""), "", "", "", "",
                    "", self.headers.get("Range", ""),
                ]) + "\n" + canon_headers + res
                want = base64.b64encode(hmac.new(
                    outer.key, sts.encode(), hashlib.sha256).digest()).decode()
                return hmac.compare_digest(given, want)

            def _route(self):
                n = int(self.headers.get("Content-Length") or 0)
                body = self.rfile.read(n) if n else b""
                if not self._check_sig(body):
                    self._reply(403, b"<Error>AuthenticationFailed</Error>")
                    return
                parsed = urllib.parse.urlparse(self.path)
                query = dict(urllib.parse.parse_qsl(parsed.query))
                path = urllib.parse.unquote(
                    parsed.path[len(f"/{outer.account}"):])
                parts = path.lstrip("/").split("/", 1)
                container = parts[0]
                blob = parts[1] if len(parts) > 1 else ""
                outer._dispatch(self, self.command, container, blob,
                                query, body)

            do_GET = do_PUT = do_DELETE = _route

        self._srv = ThreadingHTTPServer(("127.0.0.1", 0), Handler)
        self._srv.daemon_threads = True
        self.port = self._srv.server_address[1]
        threading.Thread(target=self._srv.serve_forever, daemon=True).start()

    def stop(self) -> None:
        self._srv.shutdown()
        self._srv.server_close()

    # -- dispatch -----------------------------------------------------------
    def _dispatch(self, h, method, container, blob, query, body):
        with self.lock:
            if not container and query.get("comp") == "list":
                names = "".join(
                    f"<Container><Name>{escape(c)}</Name></Container>"
                    for c in sorted(self.containers))
                h._reply(200, (f"<EnumerationResults><Containers>{names}"
                               f"</Containers></EnumerationResults>").encode())
                return
            if query.get("restype") == "container" and not blob:
                if method == "PUT":
                    if container in self.containers:
                        h._reply(409, b"<Error>ContainerAlreadyExists</Error>")
                    else:
                        self.containers[container] = {}
                        h._reply(201)
                elif method == "DELETE":
                    h._reply(202 if self.containers.pop(container, None)
                             is not None else 404)
                elif method == "GET" and query.get("comp") == "list":
                    self._list_blobs(h, container, query)
                else:
                    h._reply(400)
                return
            c = self.containers.get(container)
            if c is None:
                h._reply(404, b"<Error>ContainerNotFound</Error>")
                return
            if method == "PUT":
                c[blob] = body
                h._reply(201)
            elif method == "GET":
                if blob not in c:
                    h._reply(404, b"<Error>BlobNotFound</Error>")
                    return
                data = c[blob]
                rng = h.headers.get("Range", "")
                if rng.startswith("bytes="):
                    lo_s, _, hi_s = rng[6:].partition("-")
                    lo = int(lo_s)
                    hi = int(hi_s) if hi_s else len(data) - 1
                    part = data[lo:hi + 1]
                    h._reply(206, part, {
                        "Content-Range":
                        f"bytes {lo}-{lo + len(part) - 1}/{len(data)}"})
                else:
                    h._reply(200, data)
            elif method == "DELETE":
                h._reply(202 if c.pop(blob, None) is not None else 404)
            else:
                h._reply(400)

    def _list_blobs(self, h, container, query):
        c = self.containers.get(container)
        if c is None:
            h._reply(404, b"<Error>ContainerNotFound</Error>")
            return
        prefix = query.get("prefix", "")
        names = sorted(n for n in c if n.startswith(prefix))
        marker = query.get("marker", "")
        if marker:
            names = [n for n in names if n > marker]
        page, rest = names[:self.page_size], names[self.page_size:]
        items = "".join(
            f"<Blob><Name>{escape(n)}</Name><Properties>"
            f"<Content-Length>{len(c[n])}</Content-Length>"
            f"<Last-Modified>{formatdate(usegmt=True)}</Last-Modified>"
            f"<Etag>\"{hashlib.md5(c[n]).hexdigest()}\"</Etag>"
            f"</Properties></Blob>" for n in page)
        nxt = f"<NextMarker>{escape(page[-1])}</NextMarker>" if rest else ""
        h._reply(200, (f"<EnumerationResults><Blobs>{items}</Blobs>{nxt}"
                       f"</EnumerationResults>").encode())
