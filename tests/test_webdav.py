"""WebDAV gateway tests: RFC 4918 verbs over a live mini-cluster."""

from __future__ import annotations

import time
import xml.etree.ElementTree as ET

import pytest

from seaweedfs_tpu.filer.filer_store import MemoryStore
from seaweedfs_tpu.filer.server import FilerServer
from seaweedfs_tpu.gateway.webdav import WebDavServer
from seaweedfs_tpu.master.server import MasterServer
from seaweedfs_tpu.utils.httpd import http_bytes
from seaweedfs_tpu.volume_server.server import VolumeServer

from tests.conftest import free_port  # noqa: E402

DAV = "{DAV:}"


@pytest.fixture
def dav_stack(tmp_path):
    master = MasterServer(port=free_port(), pulse_seconds=0.4).start()
    d = tmp_path / "vs0"
    d.mkdir()
    vol = VolumeServer([str(d)], master.url, port=free_port(),
                       pulse_seconds=0.4).start()
    deadline = time.time() + 5
    while time.time() < deadline and not master.topo.all_nodes():
        time.sleep(0.05)
    filer = FilerServer(master.url, MemoryStore(), port=free_port(),
                        max_chunk_mb=1).start()
    dav = WebDavServer(filer, port=free_port()).start()
    yield dav
    dav.stop()
    filer.stop()
    vol.stop()
    master.stop()


def _url(dav, path):
    return f"http://{dav.url}{path}"


def test_options_advertises_dav(dav_stack):
    status, _, headers = http_bytes("OPTIONS", _url(dav_stack, "/"))
    assert status == 200
    assert "1, 2" in headers["DAV"]
    assert "PROPFIND" in headers["Allow"]


def test_put_get_roundtrip_and_propfind(dav_stack):
    payload = b"x" * (3 * 1024 * 1024 + 17)  # multi-chunk
    status, _, _ = http_bytes("PUT", _url(dav_stack, "/docs/a.bin"), payload)
    assert status == 409  # parent missing: RFC 4918 9.7.1
    status, _, _ = http_bytes("MKCOL", _url(dav_stack, "/docs"))
    assert status == 201
    status, _, _ = http_bytes(
        "PUT", _url(dav_stack, "/docs/a.bin"), payload,
        headers={"Content-Type": "application/x-test"})
    assert status == 201
    status, body, headers = http_bytes("GET", _url(dav_stack, "/docs/a.bin"))
    assert status == 200 and body == payload
    assert headers["Content-Type"] == "application/x-test"

    status, body, _ = http_bytes(
        "PROPFIND", _url(dav_stack, "/docs"), headers={"Depth": "1"})
    assert status == 207
    ms = ET.fromstring(body)
    hrefs = [e.text for e in ms.iter(f"{DAV}href")]
    assert "/docs/" in hrefs and "/docs/a.bin" in hrefs
    size = next(e.text for e in ms.iter(f"{DAV}getcontentlength"))
    assert int(size) == len(payload)
    # the collection itself carries <collection/> resourcetype
    assert any(rt.find(f"{DAV}collection") is not None
               for rt in ms.iter(f"{DAV}resourcetype"))


def test_propfind_depth_zero(dav_stack):
    http_bytes("MKCOL", _url(dav_stack, "/d0"))
    http_bytes("PUT", _url(dav_stack, "/d0/f.txt"), b"hi")
    status, body, _ = http_bytes(
        "PROPFIND", _url(dav_stack, "/d0"), headers={"Depth": "0"})
    ms = ET.fromstring(body)
    assert len(list(ms.iter(f"{DAV}response"))) == 1


def test_move_and_copy(dav_stack):
    http_bytes("MKCOL", _url(dav_stack, "/src"))
    http_bytes("PUT", _url(dav_stack, "/src/f.txt"), b"hello webdav")
    base = f"http://{dav_stack.url}"

    status, _, _ = http_bytes(
        "COPY", _url(dav_stack, "/src/f.txt"),
        headers={"Destination": f"{base}/src/copy.txt"})
    assert status == 201
    _, body, _ = http_bytes("GET", _url(dav_stack, "/src/copy.txt"))
    assert body == b"hello webdav"
    # source intact after COPY
    assert http_bytes("GET", _url(dav_stack, "/src/f.txt"))[0] == 200

    status, _, _ = http_bytes(
        "MOVE", _url(dav_stack, "/src/f.txt"),
        headers={"Destination": f"{base}/src/moved.txt"})
    assert status == 201
    assert http_bytes("GET", _url(dav_stack, "/src/f.txt"))[0] == 404
    assert http_bytes("GET", _url(dav_stack, "/src/moved.txt"))[1] == b"hello webdav"

    # Overwrite: F refuses to clobber
    status, _, _ = http_bytes(
        "MOVE", _url(dav_stack, "/src/moved.txt"),
        headers={"Destination": f"{base}/src/copy.txt", "Overwrite": "F"})
    assert status == 412


def test_delete_collection_recursive(dav_stack):
    http_bytes("MKCOL", _url(dav_stack, "/tree"))
    http_bytes("PUT", _url(dav_stack, "/tree/a"), b"1")
    http_bytes("PUT", _url(dav_stack, "/tree/b"), b"2")
    status, _, _ = http_bytes("DELETE", _url(dav_stack, "/tree"))
    assert status == 204
    assert http_bytes("GET", _url(dav_stack, "/tree"))[0] == 404


def test_lock_unlock_cycle(dav_stack):
    http_bytes("MKCOL", _url(dav_stack, "/lk"))
    http_bytes("PUT", _url(dav_stack, "/lk/f"), b"v1")
    status, body, headers = http_bytes("LOCK", _url(dav_stack, "/lk/f"))
    assert status == 200
    token = headers["Lock-Token"].strip("<>")
    assert token.startswith("opaquelocktoken:")

    # writes without the token are refused
    status, _, _ = http_bytes("PUT", _url(dav_stack, "/lk/f"), b"v2")
    assert status == 423
    # with the token in If, the write goes through
    status, _, _ = http_bytes("PUT", _url(dav_stack, "/lk/f"), b"v2",
                              headers={"If": f"(<{token}>)"})
    assert status == 204
    assert http_bytes("GET", _url(dav_stack, "/lk/f"))[1] == b"v2"

    status, _, _ = http_bytes("UNLOCK", _url(dav_stack, "/lk/f"),
                              headers={"Lock-Token": f"<{token}>"})
    assert status == 204
    # lock gone: plain writes work again
    status, _, _ = http_bytes("PUT", _url(dav_stack, "/lk/f"), b"v3")
    assert status == 204


def test_mkcol_conflicts(dav_stack):
    assert http_bytes("MKCOL", _url(dav_stack, "/a/b/c"))[0] == 409
    http_bytes("MKCOL", _url(dav_stack, "/a"))
    assert http_bytes("MKCOL", _url(dav_stack, "/a"))[0] == 405


def test_move_respects_destination_lock(dav_stack):
    dav = dav_stack
    http_bytes("PUT", _url(dav, "/locked.txt"), b"precious")
    st, body, hdrs = http_bytes("LOCK", _url(dav, "/locked.txt"))
    assert st == 200
    http_bytes("PUT", _url(dav, "/intruder.txt"), b"overwrite you",
               headers={"If": hdrs["Lock-Token"]})
    # wait — intruder has no lock; move onto the LOCKED destination
    st, _, _ = http_bytes(
        "MOVE", _url(dav, "/intruder.txt"),
        headers={"Destination": _url(dav, "/locked.txt")})
    assert st == 423  # destination lock gates the move
    st, body, _ = http_bytes("GET", _url(dav, "/locked.txt"))
    assert body == b"precious"


def test_delete_removes_lock(dav_stack):
    dav = dav_stack
    http_bytes("PUT", _url(dav, "/gone.txt"), b"x")
    st, _, hdrs = http_bytes("LOCK", _url(dav, "/gone.txt"))
    token = hdrs["Lock-Token"].strip("<>")
    st, _, _ = http_bytes("DELETE", _url(dav, "/gone.txt"),
                          headers={"If": f"<{token}>"})
    assert st == 204
    # recreation is NOT blocked by a stale lock entry
    st, _, _ = http_bytes("PUT", _url(dav, "/gone.txt"), b"fresh")
    assert st in (200, 201, 204)
    st, body, _ = http_bytes("GET", _url(dav, "/gone.txt"))
    assert body == b"fresh"


def test_move_overwrite_onto_directory_removes_children(dav_stack):
    dav = dav_stack
    http_bytes("MKCOL", _url(dav, "/dir"))
    http_bytes("PUT", _url(dav, "/dir/child.txt"), b"orphan?")
    http_bytes("PUT", _url(dav, "/file.txt"), b"the file")
    st, _, _ = http_bytes("MOVE", _url(dav, "/file.txt"),
                          headers={"Destination": _url(dav, "/dir"),
                                   "Overwrite": "T"})
    assert st == 204
    st, body, _ = http_bytes("GET", _url(dav, "/dir"))
    assert st == 200 and body == b"the file"
    # the directory's children are gone, not orphaned under a file path
    st, _, _ = http_bytes("GET", _url(dav, "/dir/child.txt"))
    assert st == 404


def test_move_percent_encoded_destination(dav_stack):
    """Destination headers arrive wire-encoded; the decoded name must be
    the stored one (regression: the HTTP layer now pre-decodes request
    targets, but headers still need their own decode)."""
    base = f"http://{dav_stack.url}"
    http_bytes("MKCOL", _url(dav_stack, "/mv"))
    http_bytes("PUT", _url(dav_stack, "/mv/plain.txt"), b"payload")
    status, _, _ = http_bytes(
        "MOVE", _url(dav_stack, "/mv/plain.txt"),
        headers={"Destination": f"{base}/mv/spaced%20name.txt"})
    assert status == 201
    st, body, _ = http_bytes("GET", base + "/mv/spaced%20name.txt")
    assert (st, body) == (200, b"payload")
    # PROPFIND lists the decoded name, href re-encoded
    st, body, _ = http_bytes("PROPFIND", _url(dav_stack, "/mv/"),
                             headers={"Depth": "1"})
    assert b"spaced%20name.txt" in body
