"""Maintenance-plane tests: volume copy/move/balance/fix.replication/fsck,
collection.delete, evacuate, fs.* commands."""

from __future__ import annotations

import socket
import time

import pytest

from seaweedfs_tpu.client.operation import WeedClient
from seaweedfs_tpu.filer.server import FilerServer
from seaweedfs_tpu.master.server import MasterServer
from seaweedfs_tpu.shell import CommandEnv, run_command
from seaweedfs_tpu.utils.httpd import http_bytes
from seaweedfs_tpu.volume_server.server import VolumeServer


from tests.conftest import free_port  # noqa: E402


@pytest.fixture
def cluster(tmp_path):
    master = MasterServer(port=free_port(), pulse_seconds=0.4).start()
    servers = []
    for i in range(3):
        d = tmp_path / f"vs{i}"
        d.mkdir()
        servers.append(VolumeServer([str(d)], master.url, port=free_port(),
                                    max_volume_count=20,
                                    pulse_seconds=0.4).start())
    deadline = time.time() + 5
    while time.time() < deadline and len(master.topo.all_nodes()) < 3:
        time.sleep(0.05)
    filer = FilerServer(master.url, port=free_port()).start()
    env = CommandEnv(master.url, filer.url)
    env.lock()
    yield master, servers, filer, env
    env.unlock()
    filer.stop()
    for vs in servers:
        vs.stop()
    master.stop()


def sync(servers):
    for vs in servers:
        vs.heartbeat_now()


def test_volume_copy_move_delete(cluster):
    master, servers, _, env = cluster
    client = WeedClient(master.url)
    fid = client.upload(b"movable data")
    vid = int(fid.split(",")[0])
    sync(servers)
    src = next(vs.url for vs in servers if vid in vs.store.volumes)
    dst = next(vs.url for vs in servers if vid not in vs.store.volumes)

    out = run_command(env, f"volume.copy -volumeId {vid} -source {src} -target {dst}")
    assert "copied" in out
    dst_vs = next(vs for vs in servers if vs.url == dst)
    assert vid in dst_vs.store.volumes
    # both replicas serve the object
    status, body, _ = http_bytes("GET", f"http://{dst}/{fid}")
    assert status == 200 and body == b"movable data"

    out = run_command(env, f"volume.delete -volumeId {vid} -node {dst}")
    assert "deleted" in out
    assert vid not in dst_vs.store.volumes

    out = run_command(env, f"volume.move -volumeId {vid} -source {src} -target {dst}")
    assert "moved" in out
    sync(servers)
    assert vid in dst_vs.store.volumes
    assert client.download(fid) == b"movable data"


def test_volume_fsck_detects_corruption(cluster):
    master, servers, _, env = cluster
    client = WeedClient(master.url)
    fid = client.upload(b"pristine bytes here")
    vid = int(fid.split(",")[0])
    sync(servers)
    out = run_command(env, f"volume.fsck -volumeId {vid}")
    assert "OK" in out and "crc_errors=0" in out
    # corrupt a byte on disk
    vs = next(vs for vs in servers if vid in vs.store.volumes)
    v = vs.store.volumes[vid]
    import os

    nv = next(iter(v.nm))
    with open(v.dat_path, "r+b") as f:
        f.seek(nv.offset + 20)
        b = f.read(1)
        f.seek(nv.offset + 20)
        f.write(bytes([b[0] ^ 0xFF]))
    out = run_command(env, f"volume.fsck -volumeId {vid}")
    assert "CORRUPT" in out


def test_fix_replication(cluster):
    master, servers, _, env = cluster
    client = WeedClient(master.url)
    fid = client.upload(b"needs two copies", replication="001")
    vid = int(fid.split(",")[0])
    time.sleep(0.2)
    sync(servers)
    holders = [vs for vs in servers if vid in vs.store.volumes]
    assert len(holders) == 2
    # lose one replica
    holders[1].store.delete_volume(vid)
    sync(servers)
    out = run_command(env, "volume.fix.replication")
    assert f"replicated {vid}" in out
    sync(servers)
    assert sum(1 for vs in servers if vid in vs.store.volumes) == 2


def test_collection_delete(cluster):
    master, servers, _, env = cluster
    client = WeedClient(master.url)
    fid = client.upload(b"collected", collection="scratch")
    vid = int(fid.split(",")[0])
    sync(servers)
    out = run_command(env, "collection.delete -collection scratch")
    assert str(vid) in out
    assert all(vid not in vs.store.volumes for vs in servers)


def test_evacuate(cluster):
    master, servers, _, env = cluster
    client = WeedClient(master.url)
    fids = [client.upload(bytes([i]) * 100) for i in range(5)]
    sync(servers)
    victim = next(vs for vs in servers if vs.store.volumes)
    out = run_command(env, f"volume.server.evacuate -node {victim.url}")
    assert "->" in out
    sync(servers)
    assert not victim.store.volumes
    for i, fid in enumerate(fids):
        assert client.download(fid) == bytes([i]) * 100


def test_fs_commands(cluster):
    _, _, filer, env = cluster
    http_bytes("PUT", f"http://{filer.url}/projects/a/readme.txt", b"hello fs")
    http_bytes("PUT", f"http://{filer.url}/projects/b/data.bin", b"12345")

    assert "a/" in run_command(env, "fs.ls /projects")
    assert "hello fs" == run_command(env, "fs.cat /projects/a/readme.txt")
    out = run_command(env, "fs.du /projects")
    assert "13 bytes" in out and "2 files" in out
    tree = run_command(env, "fs.tree /projects")
    assert "readme.txt" in tree and "data.bin" in tree
    run_command(env, "fs.mkdir /projects/c")
    assert "c/" in run_command(env, "fs.ls /projects")
    run_command(env, "fs.mv /projects/a -to /projects/renamed")
    assert "hello fs" == run_command(env, "fs.cat /projects/renamed/readme.txt")
    run_command(env, "fs.rm -r /projects/b")
    assert "data.bin" not in run_command(env, "fs.tree /projects")
