"""Replication plane: notification queues, sinks, replicator,
bidirectional filer.sync with loop prevention, meta backup.

Reference behaviors: weed/notification/, weed/replication/,
command/filer_sync.go, command/filer_backup.go, filer_meta_backup.go.
"""

from __future__ import annotations

import os
import time

import pytest

from seaweedfs_tpu.filer.server import FilerServer
from seaweedfs_tpu.master.server import MasterServer
from seaweedfs_tpu.replication.filer_sync import (MetaBackup, MetaTailer,
                                                  make_backup_tailer,
                                                  make_sync_tailer)
from seaweedfs_tpu.replication.notification import (FileQueue, MemoryQueue,
                                                    load_notification_queue)
from seaweedfs_tpu.replication.replicator import Replicator
from seaweedfs_tpu.replication.sink import LocalSink, S3Sink, load_sink
from seaweedfs_tpu.utils.httpd import http_bytes, http_json
from seaweedfs_tpu.volume_server.server import VolumeServer
from tests.conftest import free_port


@pytest.fixture
def cluster(tmp_path):
    """One master, one volume server, TWO filers (for sync tests)."""
    master = MasterServer(port=free_port(), pulse_seconds=0.4).start()
    d = tmp_path / "vs0"
    d.mkdir()
    vol = VolumeServer([str(d)], master.url, port=free_port(),
                       pulse_seconds=0.4).start()
    deadline = time.time() + 5
    while time.time() < deadline and len(master.topo.all_nodes()) < 1:
        time.sleep(0.05)
    queue = MemoryQueue()
    filer_a = FilerServer(master.url, port=free_port(), max_chunk_mb=1,
                          notification_queue=queue).start()
    filer_b = FilerServer(master.url, port=free_port(), max_chunk_mb=1).start()
    yield master, vol, filer_a, filer_b, queue
    filer_a.stop()
    filer_b.stop()
    vol.stop()
    master.stop()


# --- notification -----------------------------------------------------------

def test_notification_queue_receives_filer_events(cluster):
    _, _, fa, _, queue = cluster
    http_bytes("PUT", f"http://{fa.url}/q/a.txt", b"hello")
    http_bytes("DELETE", f"http://{fa.url}/q/a.txt")
    keys = [k for k, _ in queue.messages]
    assert "/q/a.txt" in keys
    ops = [e["op"] for k, e in queue.messages if k == "/q/a.txt"]
    assert "create" in ops and "delete" in ops


def test_file_queue_roundtrip(tmp_path):
    q = FileQueue(str(tmp_path / "queue.jsonl"))
    q.send_message("/a", {"op": "create", "x": 1})
    q.send_message("/b", {"op": "delete"})
    got = list(q.consume(0))
    assert [(k, e["op"]) for _, k, e in got] == \
        [("/a", "create"), ("/b", "delete")]
    # resume from offset skips consumed messages
    mid_offset = got[0][0]
    rest = list(q.consume(mid_offset))
    assert [(k) for _, k, _ in rest] == ["/b"]


def test_load_notification_queue_selection(tmp_path):
    q = load_notification_queue({"notification": {
        "file": {"enabled": True, "path": str(tmp_path / "q.jsonl")}}})
    assert isinstance(q, FileQueue)
    assert load_notification_queue({}) is None


# --- sinks + replicator -----------------------------------------------------

def test_backup_tailer_mirrors_to_local_dir(cluster, tmp_path):
    _, _, fa, _, _ = cluster
    base = f"http://{fa.url}"
    http_bytes("PUT", base + "/data/sub/one.bin", b"1" * 100)
    http_bytes("PUT", base + "/data/two.bin", b"22")
    backup_dir = tmp_path / "mirror"
    tailer = make_backup_tailer(fa.url, LocalSink(str(backup_dir)),
                                path_prefix="/data")
    tailer.run_until_caught_up()
    assert (backup_dir / "data/sub/one.bin").read_bytes() == b"1" * 100
    assert (backup_dir / "data/two.bin").read_bytes() == b"22"
    # incremental: update + delete flow through
    http_bytes("PUT", base + "/data/two.bin", b"new")
    http_bytes("DELETE", base + "/data/sub/one.bin")
    tailer.run_until_caught_up()
    assert (backup_dir / "data/two.bin").read_bytes() == b"new"
    assert not (backup_dir / "data/sub/one.bin").exists()


def test_backup_tailer_checkpoint_resume(cluster, tmp_path):
    _, _, fa, _, _ = cluster
    base = f"http://{fa.url}"
    ckpt = str(tmp_path / "bk.ckpt")
    mirror = tmp_path / "m"
    http_bytes("PUT", base + "/ck/a.txt", b"a")
    t1 = make_backup_tailer(fa.url, LocalSink(str(mirror)),
                            path_prefix="/ck", checkpoint_path=ckpt)
    t1.run_until_caught_up()
    applied_first = t1.applied
    assert applied_first >= 1
    # a new tailer with the same checkpoint must not re-apply history
    http_bytes("PUT", base + "/ck/b.txt", b"b")
    t2 = make_backup_tailer(fa.url, LocalSink(str(mirror)),
                            path_prefix="/ck", checkpoint_path=ckpt)
    t2.run_until_caught_up()
    assert t2.applied == 1  # only b.txt
    assert (mirror / "ck/b.txt").read_bytes() == b"b"


def test_local_sink_rejects_path_escape(tmp_path):
    sink = LocalSink(str(tmp_path / "root"))
    with pytest.raises(ValueError):
        sink.create_entry("/../evil.txt", {"attr": {"mode": 0}}, b"x")


def test_replicator_skips_system_paths_and_signatures(tmp_path):
    sink = LocalSink(str(tmp_path / "root"))
    repl = Replicator(sink, fetch=lambda p: b"data",
                      exclude_signatures=[42])
    ev = {"op": "create", "signatures": [7],
          "new_entry": {"full_path": "/topics/.system/log/x",
                        "attr": {"mode": 0o660}}, "old_entry": None}
    assert repl.replicate(ev) is False  # system path
    ev2 = {"op": "create", "signatures": [7, 42],
           "new_entry": {"full_path": "/ok.txt", "attr": {"mode": 0o660}},
           "old_entry": None}
    assert repl.replicate(ev2) is False  # excluded signature
    ev3 = dict(ev2, signatures=[7])
    assert repl.replicate(ev3) is True
    assert (tmp_path / "root/ok.txt").read_bytes() == b"data"


def test_load_sink_selection(tmp_path):
    sink = load_sink({"sink.local": {"enabled": True,
                                     "directory": str(tmp_path / "d")}})
    assert isinstance(sink, LocalSink)
    s3 = load_sink({"sink.s3": {"enabled": True, "endpoint": "h:1",
                                "bucket": "b"}})
    assert isinstance(s3, S3Sink)
    with pytest.raises(ValueError):
        load_sink({})


# --- filer.sync -------------------------------------------------------------

def test_filer_sync_bidirectional_no_loop(cluster, tmp_path):
    _, _, fa, fb, _ = cluster
    a, b = f"http://{fa.url}", f"http://{fb.url}"
    a2b = make_sync_tailer(fa.url, fb.url, since_ns=1)
    b2a = make_sync_tailer(fb.url, fa.url, since_ns=1)

    http_bytes("PUT", a + "/s/from_a.txt", b"A")
    http_bytes("PUT", b + "/s/from_b.txt", b"B")
    # run both directions to quiescence
    for _ in range(4):
        a2b.run_until_caught_up()
        b2a.run_until_caught_up()
    st, body, _ = http_bytes("GET", b + "/s/from_a.txt")
    assert (st, body) == (200, b"A")
    st, body, _ = http_bytes("GET", a + "/s/from_b.txt")
    assert (st, body) == (200, b"B")
    # loop prevention: a fully-caught-up pass applies zero events
    n1 = a2b.run_until_caught_up()
    n2 = b2a.run_until_caught_up()
    assert (n1, n2) == (0, 0)
    # delete propagates A -> B and does not echo back
    http_bytes("DELETE", a + "/s/from_a.txt")
    for _ in range(3):
        a2b.run_until_caught_up()
        b2a.run_until_caught_up()
    assert http_bytes("GET", b + "/s/from_a.txt")[0] == 404
    assert http_bytes("GET", a + "/s/from_b.txt")[0] == 200


def test_filer_sync_rename_propagates(cluster):
    _, _, fa, fb, _ = cluster
    a, b = f"http://{fa.url}", f"http://{fb.url}"
    a2b = make_sync_tailer(fa.url, fb.url, since_ns=1)
    http_bytes("PUT", a + "/r/old.txt", b"X")
    a2b.run_until_caught_up()
    http_json("POST", a + "/api/rename",
              {"from": "/r/old.txt", "to": "/r/new.txt"})
    a2b.run_until_caught_up()
    assert http_bytes("GET", b + "/r/old.txt")[0] == 404
    st, body, _ = http_bytes("GET", b + "/r/new.txt")
    assert (st, body) == (200, b"X")


# --- meta backup ------------------------------------------------------------

def test_meta_backup_snapshot_and_incremental(cluster, tmp_path):
    _, _, fa, _, _ = cluster
    base = f"http://{fa.url}"
    http_bytes("PUT", base + "/mb/a.txt", b"a")
    mb = MetaBackup(fa.url, str(tmp_path / "meta.json"), path_prefix="/mb")
    n = mb.full_snapshot()
    assert n == 1  # the subtree below /mb: just a.txt
    http_bytes("PUT", base + "/mb/b.txt", b"b")
    http_bytes("DELETE", base + "/mb/a.txt")
    mb.incremental()
    assert "/mb/b.txt" in mb.entries
    assert "/mb/a.txt" not in mb.entries
    # store survives reload
    mb2 = MetaBackup(fa.url, str(tmp_path / "meta.json"))
    assert "/mb/b.txt" in mb2.entries


def test_replicator_excludes_etc_credentials(tmp_path):
    """Default-scope replication must never copy /etc/* — in particular
    /etc/remote.conf (cloud access/secret keys) and /etc/remote.mount."""
    sink = LocalSink(str(tmp_path / "root"))
    repl = Replicator(sink, fetch=lambda p: b"secret")
    for p in ("/etc/remote.conf", "/etc/remote.mount",
              "/etc/seaweedfs/filer.conf", "/etc"):
        ev = {"op": "create", "signatures": [],
              "new_entry": {"full_path": p, "attr": {"mode": 0o660}},
              "old_entry": None}
        assert repl.replicate(ev) is False, p
    assert not (tmp_path / "root/etc").exists()


# --------------------------------------------------------------------------
# SQS notification queue (SigV4 query API, no SDK)
# --------------------------------------------------------------------------

class _MiniSqs:
    """SQS double: verifies the SigV4 signature server-side, records
    SendMessage bodies."""

    def __init__(self, access_key="AK", secret_key="SK",
                 region="us-east-1"):
        import threading
        from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

        self.access_key, self.secret_key, self.region = \
            access_key, secret_key, region
        self.messages = []
        outer = self

        class Handler(BaseHTTPRequestHandler):
            protocol_version = "HTTP/1.1"

            def log_message(self, fmt, *args):
                pass

            def do_POST(self):
                import hashlib
                import hmac as _hmac
                import urllib.parse

                n = int(self.headers.get("Content-Length") or 0)
                body = self.rfile.read(n)
                amz_date = self.headers.get("X-Amz-Date", "")
                date = amz_date[:8]
                # generic SigV4 verification: canonicalize exactly the
                # headers the client declared in SignedHeaders
                auth = self.headers.get("Authorization", "")
                signed = ""
                for part in auth.split(", "):
                    if part.startswith("SignedHeaders="):
                        signed = part[len("SignedHeaders="):]
                canonical_headers = "".join(
                    f"{h}:{(self.headers.get(h) or '').strip()}\n"
                    for h in signed.split(";") if h)
                creq = "\n".join([
                    "POST", self.path, "", canonical_headers, signed,
                    hashlib.sha256(body).hexdigest()])
                scope = f"{date}/{outer.region}/sqs/aws4_request"
                sts = "\n".join(["AWS4-HMAC-SHA256", amz_date, scope,
                                 hashlib.sha256(creq.encode()).hexdigest()])
                key = b"AWS4" + outer.secret_key.encode()
                for part in (date, outer.region, "sqs", "aws4_request"):
                    key = _hmac.new(key, part.encode(),
                                    hashlib.sha256).digest()
                want = _hmac.new(key, sts.encode(),
                                 hashlib.sha256).hexdigest()
                if f"Signature={want}" not in auth \
                        or f"Credential={outer.access_key}/" not in auth:
                    payload = b"<ErrorResponse>SignatureDoesNotMatch</ErrorResponse>"
                    self.send_response(403)
                else:
                    form = dict(urllib.parse.parse_qsl(body.decode()))
                    outer.messages.append(form)
                    payload = (b"<SendMessageResponse><SendMessageResult>"
                               b"<MessageId>x</MessageId>"
                               b"</SendMessageResult></SendMessageResponse>")
                    self.send_response(200)
                self.send_header("Content-Length", str(len(payload)))
                self.end_headers()
                self.wfile.write(payload)

        self._srv = ThreadingHTTPServer(("127.0.0.1", 0), Handler)
        self._srv.daemon_threads = True
        self.port = self._srv.server_address[1]
        threading.Thread(target=self._srv.serve_forever,
                         daemon=True).start()

    def stop(self):
        self._srv.shutdown()
        self._srv.server_close()


def test_sqs_queue_signed_send():
    import json as _json

    from seaweedfs_tpu.replication.notification import SqsQueue

    srv = _MiniSqs()
    try:
        q = SqsQueue(f"http://127.0.0.1:{srv.port}/123/events",
                     region=srv.region, access_key="AK", secret_key="SK")
        q.send_message("/buckets/b/k.txt", {"op": "create"})
        assert len(srv.messages) == 1
        form = srv.messages[0]
        assert form["Action"] == "SendMessage"
        payload = _json.loads(form["MessageBody"])
        assert payload["key"] == "/buckets/b/k.txt"
        assert payload["event"]["op"] == "create"
    finally:
        srv.stop()


def test_sqs_queue_bad_key_rejected():
    import pytest as _pytest

    from seaweedfs_tpu.replication.notification import SqsQueue
    from seaweedfs_tpu.utils.httpd import HttpError

    srv = _MiniSqs()
    try:
        q = SqsQueue(f"http://127.0.0.1:{srv.port}/123/events",
                     region=srv.region, access_key="AK",
                     secret_key="WRONG")
        with _pytest.raises(HttpError):
            q.send_message("/k", {"op": "create"})
        assert srv.messages == []
    finally:
        srv.stop()


def test_sqs_queue_from_config():
    from seaweedfs_tpu.replication.notification import (
        SqsQueue, load_notification_queue)

    q = load_notification_queue({"notification": {"aws_sqs": {
        "enabled": True, "queue_url": "http://sqs.local/1/q",
        "region": "eu-west-1", "aws_access_key_id": "A",
        "aws_secret_access_key": "S"}}})
    # network queues ride the async publisher so filer mutations never
    # block on broker round trips
    from seaweedfs_tpu.replication.notification import AsyncPublisher
    assert isinstance(q, AsyncPublisher)
    assert isinstance(q.inner, SqsQueue)
    assert q.inner.region == "eu-west-1" and q.inner.path == "/1/q"


# --------------------------------------------------------------------------
# azure / hdfs sinks via the remote-storage adapter
# --------------------------------------------------------------------------

def test_azure_sink_end_to_end():
    import base64

    from seaweedfs_tpu.replication.sink import RemoteStorageSink, load_sink
    from .miniazure import MiniAzure

    srv = MiniAzure()
    try:
        sink = load_sink({"sink.azure": {
            "enabled": True, "endpoint": f"127.0.0.1:{srv.port}",
            "account_name": srv.account,
            "account_key": base64.b64encode(srv.key).decode(),
            "container": "backup", "directory": "mirror"}})
        assert isinstance(sink, RemoteStorageSink)
        sink.client.create_bucket("backup")
        entry = {"attr": {"mode": 0o644}}
        sink.create_entry("/docs/a.txt", entry, b"azure mirror")
        assert srv.containers["backup"]["mirror/docs/a.txt"] == b"azure mirror"
        sink.delete_entry("/docs/a.txt", is_directory=False)
        assert "mirror/docs/a.txt" not in srv.containers["backup"]
    finally:
        srv.stop()


def test_hdfs_sink_end_to_end():
    from seaweedfs_tpu.replication.sink import RemoteStorageSink, load_sink
    from .minihdfs import MiniHdfs

    srv = MiniHdfs()
    try:
        sink = load_sink({"sink.hdfs": {
            "enabled": True, "namenode": f"127.0.0.1:{srv.port}",
            "directory": "weed-backup"}})
        assert isinstance(sink, RemoteStorageSink)
        entry = {"attr": {"mode": 0o644}}
        sink.create_entry("/logs/x.log", entry, b"hdfs mirror")
        assert srv.files["/weed-backup/logs/x.log"] == b"hdfs mirror"
        sink.delete_entry("/logs/x.log", is_directory=False)
        assert "/weed-backup/logs/x.log" not in srv.files
    finally:
        srv.stop()


# --------------------------------------------------------------------------
# google pub/sub queue (REST + RS256 service-account grant)
# --------------------------------------------------------------------------

def _has_cryptography() -> bool:
    try:
        import cryptography  # noqa: F401

        return True
    except ImportError:
        return False


# environmental guard: the pub/sub double signs its OAuth grant with an
# RSA key from `cryptography`, intentionally absent in this container —
# the reason string keeps the tier-1 log distinguishing missing-lib
# skips from real regressions
requires_cryptography = pytest.mark.skipif(
    not _has_cryptography(),
    reason="environmental: cryptography not installed in this container")


class _MiniPubSub:
    """Double acting as BOTH the OAuth token endpoint and the Pub/Sub
    publish endpoint; verifies the RS256 JWT grant with the service
    account's public key before issuing a token, and checks the bearer
    on publish."""

    def __init__(self):
        import json as _json
        import threading
        from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

        from cryptography.hazmat.primitives import hashes, serialization
        from cryptography.hazmat.primitives.asymmetric import padding, rsa

        key = rsa.generate_private_key(public_exponent=65537, key_size=2048)
        self.private_pem = key.private_bytes(
            serialization.Encoding.PEM,
            serialization.PrivateFormat.PKCS8,
            serialization.NoEncryption()).decode()
        public = key.public_key()
        self.messages = []
        self.token = "tok-123"
        outer = self

        class Handler(BaseHTTPRequestHandler):
            protocol_version = "HTTP/1.1"

            def log_message(self, fmt, *args):
                pass

            def do_POST(self):
                import base64 as _b64
                import urllib.parse

                n = int(self.headers.get("Content-Length") or 0)
                body = self.rfile.read(n)
                if self.path == "/token":
                    form = dict(urllib.parse.parse_qsl(body.decode()))
                    jwt = form.get("assertion", "")
                    signing_input, _, sig_b64 = jwt.rpartition(".")
                    sig = _b64.urlsafe_b64decode(
                        sig_b64 + "=" * (-len(sig_b64) % 4))
                    try:
                        public.verify(sig, signing_input.encode(),
                                      padding.PKCS1v15(), hashes.SHA256())
                    except Exception:
                        self._reply(401, b'{"error":"bad signature"}')
                        return
                    claims = _json.loads(_b64.urlsafe_b64decode(
                        signing_input.split(".")[1] + "=="))
                    assert claims["iss"] == "svc@proj.iam.example"
                    self._reply(200, _json.dumps({
                        "access_token": outer.token,
                        "expires_in": 3600}).encode())
                elif self.path.endswith(":publish"):
                    # emulator mode (token None): no Authorization header
                    want = (None if outer.token is None
                            else f"Bearer {outer.token}")
                    if self.headers.get("Authorization") != want:
                        self._reply(401, b'{"error":"bad auth"}')
                        return
                    doc = _json.loads(body)
                    for m in doc["messages"]:
                        outer.messages.append(
                            (_b64.standard_b64decode(m["data"]),
                             m.get("attributes", {})))
                    self._reply(200, b'{"messageIds":["1"]}')
                else:
                    self._reply(404)

            def _reply(self, status, body=b""):
                self.send_response(status)
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                if body:
                    self.wfile.write(body)

        self._srv = ThreadingHTTPServer(("127.0.0.1", 0), Handler)
        self._srv.daemon_threads = True
        self.port = self._srv.server_address[1]
        threading.Thread(target=self._srv.serve_forever,
                         daemon=True).start()

    def stop(self):
        self._srv.shutdown()
        self._srv.server_close()


@requires_cryptography
def test_google_pubsub_signed_grant_and_publish(tmp_path):
    import json as _json

    from seaweedfs_tpu.replication.google_pubsub import GooglePubSubQueue

    srv = _MiniPubSub()
    try:
        creds = tmp_path / "sa.json"
        creds.write_text(_json.dumps({
            "client_email": "svc@proj.iam.example",
            "private_key": srv.private_pem,
            "token_uri": f"http://127.0.0.1:{srv.port}/token"}))
        q = GooglePubSubQueue("proj", "events",
                              google_application_credentials=str(creds))
        # point publishes at the double (keep the OAuth path real)
        import seaweedfs_tpu.replication.google_pubsub as gp
        orig_send = q.send_message

        def send(key, event):
            # swap the production host for the double, keeping auth
            import seaweedfs_tpu.utils.httpd as hh
            real = hh.http_bytes

            def fake(method, url, body=None, headers=None, **kw):
                url = url.replace(f"https://{gp.PUBSUB_HOST}",
                                  f"http://127.0.0.1:{srv.port}")
                return real(method, url, body, headers=headers, **kw)

            gp.http_bytes, keep = fake, gp.http_bytes
            try:
                orig_send(key, event)
            finally:
                gp.http_bytes = keep

        send("/b/k.txt", {"op": "create"})
        assert len(srv.messages) == 1
        data, attrs = srv.messages[0]
        assert attrs["key"] == "/b/k.txt"
        assert _json.loads(data)["event"]["op"] == "create"
        # token is cached: a second publish does not re-grant
        tok = q._token
        send("/b/k2.txt", {"op": "delete"})
        assert q._token == tok and len(srv.messages) == 2
    finally:
        srv.stop()


@requires_cryptography
def test_google_pubsub_emulator_mode():
    import json as _json
    import time as _time

    from seaweedfs_tpu.replication.google_pubsub import GooglePubSubQueue
    from seaweedfs_tpu.replication.notification import (
        AsyncPublisher, load_notification_queue)

    srv = _MiniPubSub()
    srv.token = None  # emulator mode: requests must carry NO bearer
    try:
        q = load_notification_queue({"notification": {"google_pub_sub": {
            "enabled": True, "project_id": "proj", "topic": "t",
            "endpoint": f"127.0.0.1:{srv.port}"}}})
        assert isinstance(q, AsyncPublisher)
        assert isinstance(q.inner, GooglePubSubQueue)
        q.send_message("/e.txt", {"op": "create"})
        deadline = _time.time() + 5
        while _time.time() < deadline and not srv.messages:
            _time.sleep(0.02)
        data, attrs = srv.messages[0]
        assert attrs["key"] == "/e.txt"
        assert _json.loads(data)["event"]["op"] == "create"
        q.close()
    finally:
        srv.stop()
