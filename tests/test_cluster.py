"""Cluster integration tests: master + volume servers + shell, in-process.

The SURVEY.md §7 minimum end-to-end slice: assign -> PUT needles ->
ec.encode (engine selectable) -> lose shards -> degraded reads ->
ec.rebuild -> reads -> ec.decode -> reads.  Servers are real HTTP processes
(threads) on localhost ports; the shell drives them like an operator would.
"""

from __future__ import annotations

import socket
import time

import pytest

from seaweedfs_tpu.client.operation import WeedClient
from seaweedfs_tpu.master.server import MasterServer
from seaweedfs_tpu.shell import CommandEnv, run_command
from seaweedfs_tpu.utils.httpd import http_bytes, http_json
from seaweedfs_tpu.volume_server.server import VolumeServer


from tests.conftest import free_port  # noqa: E402


@pytest.fixture
def cluster(tmp_path):
    master = MasterServer(port=free_port(), volume_size_limit_mb=64,
                          pulse_seconds=0.4).start()
    servers = []
    for i in range(4):
        d = tmp_path / f"vs{i}"
        d.mkdir()
        vs = VolumeServer([str(d)], master.url, port=free_port(),
                          max_volume_count=10, pulse_seconds=0.4).start()
        servers.append(vs)
    # wait for first heartbeats
    deadline = time.time() + 5
    while time.time() < deadline:
        if len(master.topo.all_nodes()) == 4:
            break
        time.sleep(0.05)
    assert len(master.topo.all_nodes()) == 4
    yield master, servers
    for vs in servers:
        vs.stop()
    master.stop()


def sync_heartbeats(servers):
    for vs in servers:
        vs.heartbeat_now()


def test_assign_put_get_delete(cluster):
    master, servers = cluster
    client = WeedClient(master.url)
    fid = client.upload(b"hello cluster", name="hi.txt", mime="text/plain")
    assert client.download(fid) == b"hello cluster"
    client.delete(fid)
    with pytest.raises(Exception):
        client.download(fid)


def test_replicated_write(cluster):
    master, servers = cluster
    client = WeedClient(master.url)
    fid = client.upload(b"replicated data", replication="001")
    vid = int(fid.split(",")[0])
    time.sleep(0.1)
    holders = [vs for vs in servers if vid in vs.store.volumes]
    assert len(holders) == 2
    for vs in holders:
        status, body, _ = http_bytes("GET", f"http://{vs.url}/{fid}")
        assert status == 200 and body == b"replicated data"
    # delete propagates to both replicas
    client.delete(fid)
    for vs in holders:
        status, _, _ = http_bytes("GET", f"http://{vs.url}/{fid}")
        assert status == 404


def test_read_redirects_from_wrong_server(cluster):
    master, servers = cluster
    client = WeedClient(master.url)
    fid = client.upload(b"redirect me")
    vid = int(fid.split(",")[0])
    wrong = next(vs for vs in servers if vid not in vs.store.volumes)
    status, _, headers = http_bytes("GET", f"http://{wrong.url}/{fid}",
                                    follow_redirects=False)
    assert status == 302
    assert headers.get("Location", "").endswith(f"/{fid}")
    # and a normal client transparently follows to the right server
    status, body, _ = http_bytes("GET", f"http://{wrong.url}/{fid}")
    assert status == 200 and body == b"redirect me"


def test_vacuum_via_master(cluster):
    master, servers = cluster
    client = WeedClient(master.url)
    fids = [client.upload(bytes([i]) * 2000) for i in range(20)]
    for fid in fids[:15]:
        client.delete(fid)
    sync_heartbeats(servers)
    r = http_json("GET", f"http://{master.url}/vol/vacuum?garbageThreshold=0.3")
    assert r["compacted"]
    # survivors still readable with correct content
    for i, fid in enumerate(fids):
        if i < 15:
            continue
        assert client.download(fid) == bytes([i]) * 2000


@pytest.mark.parametrize("engine", ["cpu", "tpu"])
def test_ec_lifecycle_end_to_end(cluster, engine):
    """The north-star slice (SURVEY.md §7): encode -> degraded read ->
    rebuild -> read -> decode -> read."""
    master, servers = cluster
    client = WeedClient(master.url)

    payloads = {}
    fids = []
    for i in range(60):
        data = bytes([i % 251]) * (500 + i * 37)
        fid = client.upload(data, name=f"obj{i}.bin")
        payloads[fid] = data
        fids.append(fid)
    vid = int(fids[0].split(",")[0])
    sync_heartbeats(servers)

    env = CommandEnv(master.url)
    env.lock()
    out = run_command(env, f"ec.encode -volumeId {vid} -engine {engine}")
    assert f"ec encoded volume {vid}" in out

    # the normal volume is gone everywhere; reads go through EC
    assert all(vid not in vs.store.volumes for vs in servers)
    for fid, data in payloads.items():
        assert client.download(fid) == data, fid

    # lose one holder's shards (<= 4 of 14) -> degraded reads still work
    holders = [vs for vs in servers if vs.store.ec_volumes.get(vid)]
    victim = holders[0]
    lost = list(victim.store.ec_volumes[vid].shards)[:4]
    victim.store.ec_delete_shards(vid, lost)
    assert lost
    sync_heartbeats(servers)
    for fid in fids[:10]:
        assert client.download(fid) == payloads[fid]

    # rebuild restores the missing shards
    out = run_command(env, f"ec.rebuild -volumeId {vid} -engine {engine}")
    assert "rebuilt shards" in out
    sync_heartbeats(servers)
    shard_map = http_json(
        "GET", f"http://{master.url}/dir/lookup_ec?volumeId={vid}")["shards"]
    present = {int(s) for s, urls in shard_map.items() if urls}
    assert present == set(range(14))
    for fid in fids[:10]:
        assert client.download(fid) == payloads[fid]

    # decode back to a normal volume
    out = run_command(env, f"ec.decode -volumeId {vid}")
    assert "decoded ec volume" in out
    sync_heartbeats(servers)
    for fid, data in payloads.items():
        assert client.download(fid) == data
    env.unlock()


def test_ec_balance_dedupes_and_spreads(cluster):
    master, servers = cluster
    client = WeedClient(master.url)
    for i in range(30):
        client.upload(bytes([i]) * 1000)
    vid = 1
    sync_heartbeats(servers)
    env = CommandEnv(master.url)
    env.lock()
    run_command(env, f"ec.encode -volumeId {vid}")
    # duplicate a shard on a second server to exercise dedupe
    info = http_json("GET", f"http://{master.url}/dir/lookup_ec?volumeId={vid}")
    shard_map = {int(s): urls for s, urls in info["shards"].items()}
    sid, holders = next((s, u) for s, u in sorted(shard_map.items()) if u)
    other = next(vs.url for vs in servers if vs.url not in holders)
    http_json("POST", f"http://{other}/admin/ec/copy", {
        "volume_id": vid, "shard_ids": [sid], "source_data_node": holders[0]})
    http_json("POST", f"http://{other}/admin/ec/mount", {"volume_id": vid})
    sync_heartbeats(servers)
    info = http_json("GET", f"http://{master.url}/dir/lookup_ec?volumeId={vid}")
    assert len(info["shards"][str(sid)]) == 2
    run_command(env, "ec.balance")
    sync_heartbeats(servers)
    info = http_json("GET", f"http://{master.url}/dir/lookup_ec?volumeId={vid}")
    assert all(len(urls) == 1 for urls in info["shards"].values())
    env.unlock()


def test_shell_lock_required(cluster):
    master, _ = cluster
    env = CommandEnv(master.url)
    with pytest.raises(RuntimeError, match="lock"):
        run_command(env, "ec.encode -volumeId 1")


def test_shell_listing_commands(cluster):
    master, servers = cluster
    client = WeedClient(master.url)
    client.upload(b"x")
    sync_heartbeats(servers)
    env = CommandEnv(master.url)
    assert "volume server" in run_command(env, "cluster.ps")
    assert "DataNode" in run_command(env, "volume.list")


def test_master_submit_and_fid_redirect(cluster):
    """POST /submit (assign + upload in one call) and GET master/<fid>
    (permanent redirect to a volume server) — the README quickstart
    flows (master_server_handlers.go submit/redirect)."""
    master, servers = cluster
    boundary = "subm1234"
    body = (f"--{boundary}\r\n"
            'Content-Disposition: form-data; name="file"; '
            'filename="hello.txt"\r\n'
            "Content-Type: text/plain\r\n\r\n").encode() + b"submitted!" + \
        f"\r\n--{boundary}--\r\n".encode()
    st, resp, _ = http_bytes(
        "POST", f"http://{master.url}/submit", body,
        headers={"Content-Type":
                 f"multipart/form-data; boundary={boundary}"})
    assert st == 201
    import json as _json

    r = _json.loads(resp)
    assert r["fileName"] == "hello.txt" and r["size"] == 10
    fid = r["fid"]
    # the file is readable at fileUrl
    st, got, _ = http_bytes("GET", "http://" + r["fileUrl"])
    assert (st, got) == (200, b"submitted!")
    # master/<fid> 308-redirects to a holder
    st, _, hdrs = http_bytes("GET", f"http://{master.url}/{fid}",
                             follow_redirects=False)
    assert st == 308 and hdrs["Location"].endswith("/" + fid)
    st, got, _ = http_bytes("GET", hdrs["Location"])
    assert (st, got) == (200, b"submitted!")


def test_master_vol_status_and_col_delete(cluster):
    master, servers = cluster
    client = WeedClient(master.url)
    fid = client.upload(b"col data", collection="proj")
    sync_heartbeats(servers)
    st, body, _ = http_bytes("GET", f"http://{master.url}/vol/status")
    assert st == 200
    import json as _json

    vols = _json.loads(body)["Volumes"]
    infos = [v for dc in vols.values() for rack in dc.values()
             for n in rack.values() for v in n]
    assert any(v["collection"] == "proj" for v in infos)
    # delete the collection: volumes disappear from the servers
    st, _, _ = http_bytes("POST",
                          f"http://{master.url}/col/delete?collection=proj")
    assert st == 204
    assert not any("proj" == v.collection
                   for vs in servers for v in vs.store.volumes.values())
    st, _, _ = http_bytes(
        "POST", f"http://{master.url}/col/delete?collection=nope")
    assert st == 400


def test_fid_redirect_preserves_query(cluster):
    master, _ = cluster
    client = WeedClient(master.url)
    fid = client.upload(b"q data")
    st, _, hdrs = http_bytes(
        "GET", f"http://{master.url}/{fid}?readDeleted=true&width=10",
        follow_redirects=False)
    assert st == 308
    assert "readDeleted=true" in hdrs["Location"]
    assert "width=10" in hdrs["Location"]


def test_col_delete_includes_ec_volumes(cluster):
    """An EC-encoded collection must be deletable — and deletion must
    remove the shards, not orphan them (collectionDeleteHandler)."""
    master, servers = cluster
    client = WeedClient(master.url)
    client.upload(b"ec payload " * 1000, collection="ecol")
    sync_heartbeats(servers)
    env = CommandEnv(master.url)
    env.lock()
    vid = next(vid for (c, _, _), lay in master.topo.layouts.items()
               if c == "ecol" for vid in lay.vid_to_nodes)
    run_command(env, f"ec.encode -volumeId {vid} -collection ecol")
    sync_heartbeats(servers)
    assert vid in master.topo.ec_collections
    st, _, _ = http_bytes(
        "POST", f"http://{master.url}/col/delete?collection=ecol")
    assert st == 204
    assert vid not in master.topo.ec_collections
    # shards are gone from every server's disk
    import glob as _glob
    for vs in servers:
        for loc in vs.store.locations:
            assert not _glob.glob(f"{loc.directory}/*.ec[0-9][0-9]")


def test_volume_server_image_resize(cluster):
    """?width on a volume GET serves the resized image with the mime of
    the bytes actually sent (volume_server_handlers_read.go resize
    hook via the shared resized_from_query helper)."""
    import io

    from seaweedfs_tpu.images import resizing_available
    if not resizing_available():
        pytest.skip("no pillow")
    from PIL import Image

    master, _ = cluster
    a = http_json("GET", f"http://{master.url}/dir/assign")
    buf = io.BytesIO()
    Image.new("RGB", (40, 20), (0, 99, 0)).save(buf, format="PNG")
    png = buf.getvalue()
    st, _, _ = http_bytes("POST", f"http://{a['url']}/{a['fid']}", png,
                          headers={"Content-Type": "image/png"})
    assert st == 201
    st, body, hdrs = http_bytes(
        "GET", f"http://{a['url']}/{a['fid']}?width=10")
    assert st == 200 and hdrs["Content-Type"] == "image/png"
    assert Image.open(io.BytesIO(body)).size == (10, 5)
    # a resized representation carries its own ETag (no cache-key
    # conflation with the original), and conditionals match against it
    _, _, h_orig = http_bytes("GET", f"http://{a['url']}/{a['fid']}")
    assert hdrs["ETag"] != h_orig["ETag"]
    st, _, _ = http_bytes(
        "GET", f"http://{a['url']}/{a['fid']}?width=10",
        headers={"If-None-Match": hdrs["ETag"]})
    assert st == 304
    # the ORIGINAL's etag must not 304 a resize URL
    st, _, _ = http_bytes(
        "GET", f"http://{a['url']}/{a['fid']}?width=10",
        headers={"If-None-Match": h_orig["ETag"]})
    assert st == 200
