"""Unit tests for the autonomous EC rebuild/rebalance coordinator
(ops/coordinator.py): the pure planner (views, deficits, placement
scorer, rebalance plans), the transport-injected executor (repair flow,
no-orphan cleanup, wire-verification fallback), the coordinator's
queue/pause/cause-attribution machinery against a real Topology with a
fake transport, and the sidecar-aware /admin/ec/copy receiver against
live volume servers."""

from __future__ import annotations

import os
import time

import pytest

from seaweedfs_tpu.ec.layout import TOTAL_SHARDS_COUNT, to_ext
from seaweedfs_tpu.master.topology import (EcVolumeInfo, ShardBits,
                                           Topology)
from seaweedfs_tpu.ops import coordinator as coord
from seaweedfs_tpu.ops.coordinator import (ClusterView, EcCoordinator,
                                           Move, NodeView, PlanExecutor,
                                           UnrepairableError,
                                           choose_rebuild_host,
                                           clean_deficits, clone_view,
                                           placement_rank,
                                           plan_rebalance, rack_ceiling,
                                           view_from_status,
                                           view_from_topology)


def _view(nodes, shards, collections=None):
    v = ClusterView(collections=dict(collections or {}))
    for url, rack, dc, free, ec in nodes:
        v.nodes[url] = NodeView(url=url, rack=rack, dc=dc, free=free,
                                ec_shards=ec)
    for vid, m in shards.items():
        v.shards[vid] = {sid: list(us) for sid, us in m.items()}
    return v


def _spread_view(n_nodes=4, racks=2, vid=1, missing=()):
    """A volume spread round-robin over n_nodes across `racks` racks."""
    nodes = [(f"n{i}:80", f"r{i % racks}", "dc1", 10, 0)
             for i in range(n_nodes)]
    shards = {vid: {}}
    counts = [0] * n_nodes
    for sid in range(TOTAL_SHARDS_COUNT):
        if sid in missing:
            continue
        shards[vid][sid] = [f"n{sid % n_nodes}:80"]
        counts[sid % n_nodes] += 1
    v = _view(nodes, shards)
    for i in range(n_nodes):
        v.nodes[f"n{i}:80"].ec_shards = counts[i]
    return v


class TestViewAndDeficits:
    def test_view_from_status_carries_rack_dc_and_shards(self):
        doc = {
            "DataCenters": [
                {"Id": "dc1", "Racks": [
                    {"Id": "r1", "DataNodes": [
                        {"Url": "a:1", "Free": 3, "EcShards": 2}]},
                    {"Id": "r2", "DataNodes": [
                        {"Url": "b:1", "Free": 5, "EcShards": 0}]}]}],
            "EcVolumes": {"7": {"0": ["a:1"], "1": ["a:1", "b:1"]}},
            "EcCollections": {"7": "pics"},
        }
        v = view_from_status(doc, stale=("b:1",))
        assert v.nodes["a:1"].rack_key == ("dc1", "r1")
        assert v.nodes["b:1"].alive is False
        assert v.alive_holders(7, 1) == ["a:1"]
        assert v.collections[7] == "pics"
        assert v.present_shards(7) == {0, 1}

    def test_view_from_topology_and_stale_filter(self):
        topo = Topology()
        n = topo.register_node("10.0.0.1", 80, rack="rk", dc="dc")
        bits = ShardBits()
        for sid in range(5):
            bits = bits.add(sid)
        topo.sync_node_ec_shards(n, [EcVolumeInfo(3, "c", bits)])
        v = view_from_topology(topo)
        assert v.present_shards(3) == set(range(5))
        assert v.nodes["10.0.0.1:80"].rack_key == ("dc", "rk")
        v2 = view_from_topology(topo, stale=("10.0.0.1:80",))
        assert v2.present_shards(3) == set()

    def test_clean_deficits_flags(self):
        v = _spread_view(missing=(13,))
        d = clean_deficits(v)
        assert d[1] == {"clean": 13, "deficit": 1, "critical": False,
                        "under_replicated": False}
        v = _spread_view(missing=(10, 11, 12, 13))
        d = clean_deficits(v)
        assert d[1]["under_replicated"] and not d[1]["critical"]
        v = _spread_view(missing=(8, 9, 10, 11, 12, 13))
        assert clean_deficits(v)[1]["critical"]
        # full volume carries no entry at all
        assert clean_deficits(_spread_view()) == {}


class TestPlacementScorer:
    def test_prefers_fresh_rack_then_dc_then_load(self):
        v = _view(
            [("a:1", "r1", "dc1", 9, 0),   # rack already holds 2
             ("b:1", "r2", "dc1", 9, 5),   # fresh rack, same dc, loaded
             ("c:1", "r3", "dc2", 9, 9),   # fresh rack AND fresh dc
             ("d:1", "r2", "dc1", 9, 0)],  # fresh rack, same dc, idle
            {1: {0: ["a:1"], 1: ["a:1"]}})
        rank = placement_rank(v, 1, 2)
        # c wins (no shards in its rack or dc), then d (fresh rack,
        # least loaded), then b, then a (rack concentration)
        assert rank == ["c:1", "d:1", "b:1", "a:1"]

    def test_excludes_current_holders_and_dead(self):
        v = _view([("a:1", "r1", "dc1", 9, 0), ("b:1", "r2", "dc1", 9, 0)],
                  {1: {0: ["a:1"]}})
        v.nodes["b:1"].alive = False
        assert placement_rank(v, 1, 0) == []

    def test_agrees_with_volume_growth_diversity(self):
        """The scorer's tiers ARE volume_growth.diversity_pools: with
        one shard placed, the next pick lands in the pool a replica
        placement of 100 (other-DC) / 010 (other-rack) would use."""
        v = _view(
            [("main:1", "r1", "dc1", 9, 0),
             ("samerack:1", "r1", "dc1", 9, 0),
             ("otherrack:1", "r2", "dc1", 9, 0),
             ("otherdc:1", "r9", "dc2", 9, 0)],
            {1: {0: ["main:1"]}})
        rank = placement_rank(v, 1, 1, exclude=("main:1",))
        # other-DC first (fresh rack + fresh dc), then other-rack,
        # then same-rack — the 1xx > x1x > xx1 pool order
        assert rank == ["otherdc:1", "otherrack:1", "samerack:1"]

    def test_choose_rebuild_host_most_local_shards(self):
        v = _view(
            [("a:1", "r1", "dc1", 2, 6), ("b:1", "r2", "dc1", 9, 1)],
            {1: {0: ["a:1"], 1: ["a:1"], 2: ["b:1"]}})
        assert choose_rebuild_host(v, 1) == "a:1"
        v.nodes["a:1"].alive = False
        assert choose_rebuild_host(v, 1) == "b:1"
        v.nodes["b:1"].alive = False
        assert choose_rebuild_host(v, 1) is None


class TestRebalancePlanner:
    def test_dedupe_keeps_least_loaded(self):
        v = _view([("a:1", "r1", "dc1", 9, 5), ("b:1", "r2", "dc1", 9, 1)],
                  {1: {0: ["a:1", "b:1"]}})
        plan = plan_rebalance(v)
        dd = [m for m in plan if m.kind == "dedupe"]
        assert len(dd) == 1 and dd[0].src == "a:1"

    def test_rack_violation_produces_rack_moves(self):
        # every shard in one rack of a 4-rack cluster: ceiling is 4
        nodes = [("a:1", "r1", "dc1", 20, 14)] + [
            (f"x{i}:1", f"r{i}", "dc1", 20, 0) for i in range(2, 5)]
        shards = {1: {sid: ["a:1"] for sid in range(14)}}
        v = _view(nodes, shards)
        assert rack_ceiling(v) == 4
        plan = plan_rebalance(clone_view(v))
        rack_moves = [m for m in plan if m.reason == "rack"]
        assert rack_moves and all(m.src == "a:1" for m in rack_moves)
        # replaying the plan leaves no rack above the ceiling
        per_rack = {("dc1", "r1"): 14}
        for m in rack_moves:
            per_rack[("dc1", "r1")] -= 1
            key = v.nodes[m.dst].rack_key
            per_rack[key] = per_rack.get(key, 0) + 1
        assert all(c <= 4 for c in per_rack.values())

    def test_balanced_view_plans_nothing(self):
        v = _spread_view(n_nodes=7, racks=7)
        assert plan_rebalance(clone_view(v)) == []

    def test_max_moves_bounds_plan(self):
        nodes = [("a:1", "r1", "dc1", 20, 14)] + [
            (f"x{i}:1", f"r{i}", "dc1", 20, 0) for i in range(2, 9)]
        v = _view(nodes, {1: {sid: ["a:1"] for sid in range(14)}})
        assert len(plan_rebalance(clone_view(v), max_moves=3)) == 3

    def test_skew_targets_never_coconcentrate_a_volume(self):
        # one rack (no diversity pressure), one hoarder, empty peers:
        # skew moves place at most ONE shard of the volume per target —
        # server balance never trades away per-volume spread
        nodes = [("a:1", "r1", "dc1", 30, 14)] + [
            (f"x{i}:1", "r1", "dc1", 30, 0) for i in range(2, 6)]
        v = _view(nodes, {1: {sid: ["a:1"] for sid in range(14)}})
        plan = plan_rebalance(clone_view(v))
        skew = [m for m in plan if m.reason == "skew"]
        assert skew, "hoarder produced no skew moves"
        placed: dict[str, int] = {}
        for m in skew:
            placed[m.dst] = placed.get(m.dst, 0) + 1
        assert all(c == 1 for c in placed.values())


class FakeTransport:
    """Records every executor POST; programmable per-path responses and
    failures."""

    def __init__(self):
        self.calls: list[tuple[str, str, dict]] = []
        self.fail: dict[tuple, Exception] = {}   # (server, path) -> exc
        self.rebuilt: list[int] = []

    def __call__(self, server, path, payload, timeout=600.0):
        self.calls.append((server, path, dict(payload)))
        exc = self.fail.get((server, path))
        if exc is not None:
            raise exc
        if path == "/admin/ec/rebuild":
            return {"rebuilt_shard_ids": list(self.rebuilt)}
        return {}

    def of(self, path):
        return [c for c in self.calls if c[1] == path]


class TestExecutor:
    def test_repair_copies_survivors_rebuilds_and_spreads(self):
        v = _spread_view(n_nodes=4, racks=4, missing=(13,))
        t = FakeTransport()
        host = choose_rebuild_host(v, 1)
        held = {sid for sid, us in v.shards[1].items() if host in us}
        t.rebuilt = [13]
        ex = PlanExecutor(post_fn=t)
        res = ex.execute_repair(v, 1)
        assert res["host"] == host and res["rebuilt"] == [13]
        # every survivor the host lacked was copied, then dropped again
        copies = t.of("/admin/ec/copy")
        survivor_copies = [c for c in copies
                           if c[2].get("copy_ecx_file")]
        assert {c[2]["shard_ids"][0] for c in survivor_copies} == \
            set(range(13)) - held
        deletes = t.of("/admin/ec/delete")
        assert any(set(d[2]["shard_ids"]) == set(res["copied"])
                   and d[0] == host for d in deletes)
        # the rebuilt shard was spread to the scorer's pick (or kept)
        if res["moves"]:
            sid, dst = res["moves"][0]
            assert sid == 13 and dst != host

    def test_repair_failure_cleans_copied_survivors(self):
        """No orphan shards: a rebuild that dies mid-plan deletes the
        temp survivor copies off the host before re-raising."""
        v = _spread_view(n_nodes=4, racks=4, missing=(13,))
        t = FakeTransport()
        host = choose_rebuild_host(v, 1)
        t.fail[(host, "/admin/ec/rebuild")] = OSError("host died")
        ex = PlanExecutor(post_fn=t)
        with pytest.raises(OSError):
            ex.execute_repair(v, 1)
        deletes = [d for d in t.of("/admin/ec/delete") if d[0] == host]
        assert deletes, "copied survivors were never cleaned up"
        copied = {c[2]["shard_ids"][0] for c in t.of("/admin/ec/copy")}
        assert set(deletes[-1][2]["shard_ids"]) == copied

    def test_unrepairable_below_k(self):
        v = _spread_view(missing=tuple(range(5, 14)))  # 5 clean < k
        with pytest.raises(UnrepairableError):
            PlanExecutor(post_fn=FakeTransport()).execute_repair(v, 1)

    def test_wire_rejected_survivor_is_regenerated_not_fatal(self):
        """A survivor copy the receiver rejects on sidecar verification
        is skipped and regenerated by the rebuild; the rotted source
        copy is dropped afterwards."""
        v = _spread_view(n_nodes=4, racks=4, missing=(13,))
        t = FakeTransport()
        host = choose_rebuild_host(v, 1)
        # find a shard the host lacks; its holder serves rotted bytes
        bad_sid = next(s for s, us in sorted(v.shards[1].items())
                       if host not in us)
        bad_holder = v.shards[1][bad_sid][0]

        real_call = FakeTransport.__call__

        def call(self_, server, path, payload, timeout=600.0):
            if path == "/admin/ec/copy" and \
                    payload.get("shard_ids") == [bad_sid] and \
                    payload.get("source_data_node") == bad_holder:
                self_.calls.append((server, path, dict(payload)))
                raise OSError(f"shards [{bad_sid}] of volume 1 failed "
                              ".eci sidecar verification after copy; "
                              "rejected")
            return real_call(self_, server, path, payload, timeout)

        t.rebuilt = [bad_sid, 13]
        FakeTransport.__call__ = call
        try:
            res = PlanExecutor(post_fn=t).execute_repair(v, 1)
        finally:
            FakeTransport.__call__ = real_call
        assert sorted(res["rebuilt"]) == sorted([bad_sid, 13])
        # the rotted source copy was dropped after the rebuild landed
        assert any(d[0] == bad_holder and d[2]["shard_ids"] == [bad_sid]
                   for d in t.of("/admin/ec/delete"))

    def test_move_and_dedupe_mount_discipline(self):
        v = _view([("a:1", "r1", "dc1", 9, 2), ("b:1", "r2", "dc1", 9, 0)],
                  {1: {0: ["a:1"], 1: ["a:1"]}}, {1: "c"})
        t = FakeTransport()
        ex = PlanExecutor(post_fn=t)
        ex.execute_move(v, Move(1, 0, "a:1", "b:1"))
        # copy -> mount at dst, delete at src, REMOUNT src (still holds 1)
        paths = [(s, p) for s, p, _b in t.calls]
        assert paths == [("b:1", "/admin/ec/copy"),
                         ("b:1", "/admin/ec/mount"),
                         ("a:1", "/admin/ec/delete"),
                         ("a:1", "/admin/ec/mount")]
        assert all(b.get("collection") == "c" for _s, _p, b in t.calls
                   if "collection" in b)
        t.calls.clear()
        ex.execute_move(v, Move(1, 1, "a:1", "b:1"))
        # src lost its last shard: unmount instead of remount
        assert ("a:1", "/admin/ec/unmount") in [(s, p)
                                                for s, p, _b in t.calls]


def _topo_with_volume(missing=(13,), n_nodes=4, racks=2):
    topo = Topology()
    urls = []
    for i in range(n_nodes):
        node = topo.register_node("10.0.0.%d" % (i + 1), 80,
                                  rack=f"r{i % racks}", dc="dc1")
        urls.append(node.url)
        bits = ShardBits()
        for sid in range(TOTAL_SHARDS_COUNT):
            if sid in missing or sid % n_nodes != i:
                continue
            bits = bits.add(sid)
        topo.sync_node_ec_shards(node, [EcVolumeInfo(1, "", bits)])
    return topo, urls


class TestCoordinatorLoop:
    def _coordinator(self, topo, t=None, **kw):
        kw.setdefault("interval_s", 999.0)
        return EcCoordinator(topo=topo, server="m:1",
                             post_fn=t or FakeTransport(), **kw)

    def test_cycle_queues_deficits_and_sets_gauge(self):
        topo, _ = _topo_with_volume(missing=(10, 11, 12, 13))
        t = FakeTransport()
        t.rebuilt = [10, 11, 12, 13]
        c = self._coordinator(topo, t)
        c.run_cycle()
        st = c.status()
        assert st["cycles"] == 1
        # the repair ran this same cycle (fake transport "succeeds")
        assert st["repairs"]["done"] == 1
        assert t.of("/admin/ec/rebuild")
        # gauge saw the under-replicated volume during the scan
        from seaweedfs_tpu.observability import events as _events

        evs = _events.get_journal().query(type_="ec_under_replicated",
                                          limit=5)
        assert evs and evs[-1]["details"]["vid"] == 1

    def test_on_events_records_cause_and_repair_carries_it(self):
        topo, _ = _topo_with_volume(missing=(13,))
        t = FakeTransport()
        t.rebuilt = [13]
        c = self._coordinator(topo, t)
        c.on_events([
            {"id": "e1", "type": "alert_fired",
             "details": {"alert": "scrub_unrepairable",
                         "exemplar_trace": "ab" * 16}},
            {"id": "e2", "type": "scrub_unrepairable",
             "trace": "cd" * 16, "details": {"vid": 1, "shards": [13]}},
        ])
        c.run_cycle()
        from seaweedfs_tpu.observability import events as _events

        done = _events.get_journal().query(type_="repair_done", limit=5)
        assert done, "repair_done never journaled"
        d = done[-1]["details"]
        assert d["vid"] == 1
        assert d["alert"] == "scrub_unrepairable"
        assert d["cause_trace"] == "cd" * 16
        assert d["cause_event"] == "e2"

    def test_shard_corrupt_path_parses_vid(self):
        from seaweedfs_tpu.ops.coordinator import _vid_from_event

        assert _vid_from_event({"vid": 9}) == 9
        assert _vid_from_event({"path": "/data/coll_12"}) == 12
        assert _vid_from_event({"path": "/data/7"}) == 7
        assert _vid_from_event({"path": "/data/x"}) is None
        assert _vid_from_event({}) is None

    def test_pause_and_admin_lock_block_cycles(self):
        topo, _ = _topo_with_volume(missing=(13,))
        locked = {"v": False}
        t = FakeTransport()
        t.rebuilt = [13]
        c = EcCoordinator(topo=topo, post_fn=t, interval_s=0.05,
                          admin_locked_fn=lambda: locked["v"])
        c.pause("test")
        c.start()
        try:
            time.sleep(0.3)
            assert c.status()["cycles"] == 0  # paused: nothing ran
            c.resume()
            locked["v"] = True  # admin lock also blocks
            time.sleep(0.3)
            assert c.status()["cycles"] == 0
            assert c.status()["paused"] is True
            assert c.status()["pause_reason"] == "admin_lock"
            locked["v"] = False
            deadline = time.time() + 5
            while time.time() < deadline and c.status()["cycles"] == 0:
                time.sleep(0.05)
            assert c.status()["cycles"] > 0
        finally:
            c.stop()

    def test_move_budget_token_bucket(self):
        # a wildly skewed cluster, but a budget of 2 moves
        topo = Topology()
        hoarder = topo.register_node("10.0.0.1", 80, rack="r1", dc="dc1")
        bits = ShardBits()
        for sid in range(TOTAL_SHARDS_COUNT):
            bits = bits.add(sid)
        topo.sync_node_ec_shards(hoarder, [EcVolumeInfo(1, "", bits)])
        for i in range(2, 6):
            topo.register_node("10.0.0.%d" % i, 80, rack=f"r{i}",
                               dc="dc1")
        t = FakeTransport()
        c = EcCoordinator(topo=topo, post_fn=t, interval_s=999.0,
                          move_rate=0.0, move_burst=2.0)
        c.run_cycle()
        st = c.status()
        assert st["moves"] == 2  # burst spent, rate 0: hard stop
        assert st["move_budget"]["tokens"] < 1.0
        c.run_cycle()
        assert c.status()["moves"] == 2  # still no tokens

    def test_plan_fault_is_contained(self):
        from seaweedfs_tpu.utils import faultinject as fi

        topo, _ = _topo_with_volume(missing=())
        c = EcCoordinator(topo=topo, post_fn=FakeTransport(),
                          interval_s=0.05)
        fi.enable("coord.plan", error_rate=1.0, max_hits=1)
        c.start()
        try:
            deadline = time.time() + 5
            while time.time() < deadline and not c.status()["cycles"]:
                time.sleep(0.05)
            # the injected planning fault was contained: the loop
            # survived it, surfaced it, and later cycles recovered
            assert c.status()["cycles"] > 0
            assert fi.fired("coord.plan") == 1
        finally:
            fi.clear()
            c.stop()

    def test_failed_repair_backs_off_exponentially(self):
        """A persistently failing repair must not re-copy k survivors
        every cycle: after a failure the volume is held back for
        interval * 2^attempts before the next attempt."""
        topo, _ = _topo_with_volume(missing=(13,))

        def explode(*_a):
            raise OSError("disk full on every host")

        c = EcCoordinator(topo=topo, post_fn=explode, interval_s=60.0)
        c.run_cycle()
        st = c.status()
        assert st["repairs"]["failed"] == 1
        # immediately re-running plans nothing: the entry is in backoff
        c.run_cycle()
        assert c.status()["repairs"]["failed"] == 1
        # aging the last attempt past the hold re-arms it
        with c._lock:
            c._queue[1]["last_attempt_at"] -= 60.0 * 2 + 1
        c.run_cycle()
        assert c.status()["repairs"]["failed"] == 2

    def test_health_contribution_keys(self):
        topo, _ = _topo_with_volume()
        c = self._coordinator(topo)
        contrib = c.health_contribution()
        assert set(contrib) == {"ec_under_replicated",
                                "coordinator_repair_failures"}


class TestWireVerification:
    """The sidecar-aware /admin/ec/copy receiver, live."""

    @pytest.fixture
    def two_servers(self, tmp_path):
        from seaweedfs_tpu.master.server import MasterServer
        from seaweedfs_tpu.storage.needle import Needle
        from seaweedfs_tpu.volume_server.server import VolumeServer
        from tests.conftest import free_port

        master = MasterServer(port=free_port(),
                              pulse_seconds=0.3).start()
        servers = []
        dirs = []
        for i in range(2):
            d = tmp_path / f"vs{i}"
            d.mkdir()
            dirs.append(str(d))
            servers.append(VolumeServer(
                [str(d)], master.url, port=free_port(),
                pulse_seconds=0.3).start())
        vs0, vs1 = servers
        v = vs0.store.add_volume(1)
        for i in range(1, 40):
            v.write_needle(Needle(cookie=i, id=i,
                                  data=os.urandom(400)))
        vs0.store.ec_generate(1)
        vs0.store.ec_mount(1)
        yield master, vs0, vs1, dirs
        for s in servers:
            s.stop()
        master.stop()

    def test_rotted_source_copy_rejected_with_wire_event(
            self, two_servers):
        from seaweedfs_tpu.observability import events as _events
        from seaweedfs_tpu.stats import ec_integrity_metrics
        from seaweedfs_tpu.utils.httpd import http_bytes

        master, vs0, vs1, dirs = two_servers
        # rot shard 5 ON THE SOURCE after encode (the sidecar predates
        # the flip, so the receiver's verification must catch it)
        shard5 = os.path.join(dirs[0], "1" + to_ext(5))
        with open(shard5, "r+b") as f:
            f.seek(128)
            b = f.read(1)
            f.seek(128)
            f.write(bytes([b[0] ^ 0x40]))
        before = ec_integrity_metrics().corrupt_shards.value("wire")
        import json as _json

        status, body, _ = http_bytes(
            "POST", f"http://{vs1.url}/admin/ec/copy",
            _json.dumps({"volume_id": 1, "shard_ids": [5, 6],
                         "source_data_node": vs0.url}).encode(),
            headers={"Content-Type": "application/json"})
        assert status == 502, body
        assert b"sidecar verification" in body
        # the rejected shard never landed; the clean one in the same
        # request was also rolled back with the volume's file set
        assert not os.path.exists(os.path.join(dirs[1], "1" + to_ext(5)))
        # counted under source="wire" and journaled as shard_corrupt
        assert ec_integrity_metrics().corrupt_shards.value("wire") == \
            before + 1
        evs = _events.get_journal().query(type_="shard_corrupt",
                                          limit=10)
        assert any(e["details"].get("source") == "wire"
                   and e["details"].get("shard") == 5 for e in evs)

    def test_clean_copy_still_passes(self, two_servers):
        from seaweedfs_tpu.utils.httpd import http_json

        master, vs0, vs1, dirs = two_servers
        http_json("POST", f"http://{vs1.url}/admin/ec/copy",
                  {"volume_id": 1, "shard_ids": [7],
                   "source_data_node": vs0.url})
        assert os.path.exists(os.path.join(dirs[1], "1" + to_ext(7)))
        # the sidecar rode along, so vs1 can verify-on-use locally
        assert os.path.exists(os.path.join(dirs[1], "1.eci"))


def test_shell_commands_registered():
    from seaweedfs_tpu.shell import COMMANDS

    for name in ("coordinator.status", "coordinator.pause",
                 "coordinator.resume"):
        assert name in COMMANDS


class TestPostRepairRescrub:
    def test_executor_rescrub_posts_targeted_scan_to_holders(self):
        v = _spread_view(n_nodes=4, racks=4, missing=(13,))
        t = FakeTransport()
        started = PlanExecutor(post_fn=t).rescrub(v, 1)
        posts = t.of("/ec/scrub/start")
        holders = {u for us in v.shards[1].values() for u in us}
        assert set(started) == holders
        assert {p[0] for p in posts} == holders
        for _srv, _path, payload in posts:
            assert payload["volume_id"] == 1
            # NO knob overrides: start() persists any rate/interval it
            # receives, and a 0 here would unthrottle the holder's
            # configured scrub IO cap permanently
            assert "rate_mb_s" not in payload

    def test_repair_done_carries_rescrubbed_holders(self):
        """The coordinator's post-repair re-scrub: a successful repair
        immediately targets every holder of the healed volume, so a
        stale `unrepairable` verdict clears without waiting for the
        next full pass — and the repair_done event records who was
        asked."""
        topo, _ = _topo_with_volume(missing=(13,))
        t = FakeTransport()
        t.rebuilt = [13]
        c = EcCoordinator(topo=topo, server="m:1", post_fn=t,
                          interval_s=999.0)
        c.run_cycle()
        assert c.status()["repairs"]["done"] == 1
        posts = t.of("/ec/scrub/start")
        assert posts, "no targeted re-scrub after a successful repair"
        assert all(p[2]["volume_id"] == 1 for p in posts)
        from seaweedfs_tpu.observability import events as _events

        done = _events.get_journal().query(type_="repair_done", limit=5)
        assert done and done[-1]["details"]["rescrubbed"]

    def test_rescrub_failure_never_fails_the_repair(self):
        topo, _ = _topo_with_volume(missing=(13,))
        t = FakeTransport()
        t.rebuilt = [13]

        class Flaky(FakeTransport):
            def __call__(self, server, path, payload, timeout=600.0):
                if path == "/ec/scrub/start":
                    raise OSError("scrubber busy")
                return FakeTransport.__call__(self, server, path,
                                              payload, timeout)

        f = Flaky()
        f.rebuilt = [13]
        c = EcCoordinator(topo=topo, server="m:1", post_fn=f,
                          interval_s=999.0)
        c.run_cycle()
        st = c.status()
        assert st["repairs"]["done"] == 1 and not st["repairs"]["failed"]


class TestRepairRetryBudget:
    def test_reattempts_draw_from_retry_budget(self):
        """With the per-destination budget drained, a failing repair's
        RE-attempts are denied (single attempt total until the bucket
        refills) and the denial is journaled."""
        from seaweedfs_tpu.utils import backoff as _backoff

        topo, urls = _topo_with_volume(missing=(13,))
        t = FakeTransport()
        for u in urls:
            t.fail[(u, "/admin/ec/rebuild")] = OSError("wedged")
        prev = _backoff._GLOBAL
        _backoff._GLOBAL = _backoff.RetryBudget(rate=0.0, burst=0.0)
        try:
            c = EcCoordinator(topo=topo, server="m:1", post_fn=t,
                              interval_s=0.0)
            c.run_cycle()  # first attempt: not a retry, always allowed
            assert c.status()["repairs"]["failed"] == 1
            # backoff hold is interval_s*2^attempts = 0 — only the
            # budget stands between us and a retry storm
            c.run_cycle()
            c.run_cycle()
            assert c.status()["repairs"]["failed"] == 1, \
                "drained budget did not stop repair re-attempts"
            from seaweedfs_tpu.observability import events as _events

            evs = _events.get_journal().query(
                type_="retry_budget_exhausted", limit=5)
            assert evs and evs[-1]["details"]["kind"] == "coordinator"
        finally:
            _backoff._GLOBAL = prev
