"""Wire-protocol Kafka producer against a CRC-verifying broker double.

Gates:
- the RecordBatch v2 bytes decode exactly (header, castagnoli CRC,
  zigzag-varint records) on the broker side
- key-hash partitioning is stable and spreads across partitions
- a NOT_LEADER produce error triggers a metadata refresh + retry
- the notification KafkaQueue publishes filer events end-to-end
"""

from __future__ import annotations

import json

import pytest

from seaweedfs_tpu.replication.kafka import KafkaError, KafkaProducer
from seaweedfs_tpu.replication.notification import (
    KafkaQueue,
    load_notification_queue,
)

from .minikafka import MiniKafka


@pytest.fixture()
def broker():
    b = MiniKafka(partitions=2)
    yield b
    b.stop()


def test_produce_roundtrip_with_crc(broker):
    p = KafkaProducer([f"127.0.0.1:{broker.port}"])
    for i in range(20):
        p.send("events", f"key{i}".encode(), f"value{i}".encode())
    p.close()
    assert broker.crc_errors == 0
    allrecs = [r for recs in broker.records.values() for r in recs]
    assert sorted(allrecs) == sorted(
        (f"key{i}".encode(), f"value{i}".encode()) for i in range(20))
    # key hashing used both partitions
    assert len(broker.records) == 2


def test_not_leader_retry(broker):
    broker.fail_produce_times = 1
    p = KafkaProducer([f"127.0.0.1:{broker.port}"])
    p.send("t", b"k", b"v")  # first produce gets NOT_LEADER, retried
    p.close()
    assert sum(len(r) for r in broker.records.values()) == 1


def test_produce_error_surfaces(broker):
    broker.fail_produce_times = 5  # more than the single retry
    p = KafkaProducer([f"127.0.0.1:{broker.port}"])
    with pytest.raises(KafkaError):
        p.send("t", b"k", b"v")
    p.close()


def test_notification_queue_end_to_end(broker):
    import time

    from seaweedfs_tpu.replication.notification import AsyncPublisher

    q = load_notification_queue({"notification": {"kafka": {
        "enabled": True, "hosts": [f"127.0.0.1:{broker.port}"],
        "topic": "filer-events"}}})
    assert isinstance(q, AsyncPublisher)
    assert isinstance(q.inner, KafkaQueue)
    q.send_message("/buckets/b/obj.txt", {"op": "create", "size": 42})
    deadline = time.time() + 5  # async publisher delivers in background
    recs = []
    while time.time() < deadline and not recs:
        recs = [r for (t, _), recs_ in broker.records.items()
                if t == "filer-events" for r in recs_]
        time.sleep(0.02)
    assert len(recs) == 1
    key, value = recs[0]
    assert key == b"/buckets/b/obj.txt"
    payload = json.loads(value)
    assert payload["event"]["op"] == "create"


def test_bootstrap_failure():
    p = KafkaProducer(["127.0.0.1:1"])  # nothing listens
    with pytest.raises(OSError):
        p.send("t", b"k", b"v")
