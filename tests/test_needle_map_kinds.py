"""Needle-map kinds: compact (numpy sections), ldb (checkpointed), sorted.

The gate: every kind must be observably identical to MemoryNeedleMap —
same get results, same counters, same ascending iteration — across
randomized op logs including out-of-order keys, overwrites, and deletes
(needle_map_memory.go:35-56 bookkeeping).
"""

from __future__ import annotations

import os

import numpy as np
import pytest

from seaweedfs_tpu.storage import idx as idx_mod
from seaweedfs_tpu.storage import needle_map_compact as nmc
from seaweedfs_tpu.storage.needle import Needle
from seaweedfs_tpu.storage.needle_map import MemoryNeedleMap
from seaweedfs_tpu.storage.needle_map_compact import (
    CheckpointedNeedleMap,
    CompactNeedleMap,
    SortedFileNeedleMap,
)
from seaweedfs_tpu.storage.volume import Volume

RNG = np.random.default_rng(0xC0)


def _random_ops(n=3000, keyspace=700):
    ops = []
    for _ in range(n):
        key = int(RNG.integers(1, keyspace))
        if RNG.random() < 0.25:
            ops.append(("del", key, 0, 0))
        else:
            off = int(RNG.integers(1, 1 << 20)) * 8
            size = int(RNG.integers(1, 5000))
            ops.append(("put", key, off, size))
    return ops


def _apply(m, ops):
    for op, key, off, size in ops:
        if op == "put":
            m.put(key, off, size)
        else:
            m.delete(key, off or 8)


def _counters(m):
    return (m.file_counter, m.file_byte_counter, m.deletion_counter,
            m.deletion_byte_counter, m.max_file_key)


def test_compact_matches_memory_randomized(tmp_path, monkeypatch):
    # tiny sections/flush thresholds so every structural path is exercised
    monkeypatch.setattr(nmc, "_SECTION", 64)
    monkeypatch.setattr(nmc, "_TAIL_FLUSH", 32)
    monkeypatch.setattr(nmc, "_OVERFLOW_MERGE", 50)
    ops = _random_ops()
    mem = MemoryNeedleMap(str(tmp_path / "a.idx"))
    cmp_ = CompactNeedleMap(str(tmp_path / "b.idx"))
    _apply(mem, ops)
    _apply(cmp_, ops)
    assert _counters(mem) == _counters(cmp_)
    for key in range(1, 700):
        assert mem.get(key) == cmp_.get(key), key
    assert list(mem) == list(cmp_)
    mem.close()
    cmp_.close()


def test_compact_vectorized_replay_matches_scalar(tmp_path):
    ops = _random_ops(n=2000, keyspace=300)
    path = str(tmp_path / "r.idx")
    mem = MemoryNeedleMap(path)
    _apply(mem, ops)
    mem.close()
    scalar = MemoryNeedleMap.load(path)
    vector = CompactNeedleMap.load(path)
    assert _counters(scalar) == _counters(vector)
    assert list(scalar) == list(vector)
    scalar.close()
    vector.close()


def test_checkpointed_restart_replays_only_tail(tmp_path):
    path = str(tmp_path / "v.idx")
    m = CheckpointedNeedleMap(path)
    for k in range(1, 500):
        m.put(k, k * 8, 100 + k)
    m.checkpoint()
    watermark = os.path.getsize(path)
    for k in range(500, 560):
        m.put(k, k * 8, 100 + k)
    m.delete(77, 8)
    m.close()  # close checkpoints again

    # corrupt idx BYTES BEFORE the final watermark: a snapshot load must not
    # read them (full replay would choke on the counters differing)
    m2 = CheckpointedNeedleMap.load(path)
    assert m2._loaded_from_snapshot
    full = MemoryNeedleMap.load(path)
    assert _counters(m2) == _counters(full)
    assert list(m2) == list(full)
    assert m2.get(77) is None
    m2.close()
    full.close()


def test_checkpointed_tail_after_snapshot_without_second_checkpoint(tmp_path):
    path = str(tmp_path / "t.idx")
    m = CheckpointedNeedleMap(path)
    for k in range(1, 100):
        m.put(k, k * 8, 10)
    m.checkpoint()
    # append past the snapshot, then simulate a crash (no close/checkpoint)
    for k in range(100, 130):
        m.put(k, k * 8, 20)
    m.delete(5, 8)
    m._index_file.flush()
    m._index_file.close()
    m._index_file = None

    m2 = CheckpointedNeedleMap.load(path)
    assert m2._loaded_from_snapshot
    full = MemoryNeedleMap.load(path)
    assert _counters(m2) == _counters(full)
    assert list(m2) == list(full)
    m2.close()
    full.close()


def test_checkpointed_discards_snapshot_when_idx_truncated(tmp_path):
    path = str(tmp_path / "w.idx")
    m = CheckpointedNeedleMap(path)
    for k in range(1, 50):
        m.put(k, k * 8, 10)
    m.close()
    # integrity repair truncated the idx below the snapshot watermark
    with open(path, "r+b") as f:
        f.truncate(16 * 10)
    m2 = CheckpointedNeedleMap.load(path)
    assert not m2._loaded_from_snapshot
    full = MemoryNeedleMap.load(path)
    assert _counters(m2) == _counters(full)
    assert list(m2) == list(full)
    m2.close()
    full.close()


def test_sorted_file_kind(tmp_path):
    path = str(tmp_path / "s.idx")
    mem = MemoryNeedleMap(path)
    for k in (3, 1, 9, 4, 200):
        mem.put(k, k * 8, k * 10)
    mem.delete(4, 8)
    mem.close()

    sf = SortedFileNeedleMap.load(path)
    assert os.path.exists(str(tmp_path / "s.sdx"))
    assert sf.get(9).size == 90
    assert sf.get(4) is None
    assert sf.get(77) is None
    with pytest.raises(PermissionError):
        sf.put(5, 40, 1)
    sf.delete(9, 8)
    assert sf.get(9) is None
    assert sf.deletion_byte_counter == 90
    # the in-place tombstone survives reopen
    sf.close()
    sf2 = SortedFileNeedleMap.load(path)
    assert sf2.get(9) is None and sf2.get(200).size == 2000
    sf2.close()


@pytest.mark.parametrize("kind", ["compact", "ldb", "memory"])
def test_volume_roundtrip_each_kind(tmp_path, kind):
    d = str(tmp_path / kind)
    v = Volume(d, "", 9, needle_map_kind=kind)
    try:
        for i in range(1, 30):
            v.write_needle(Needle(cookie=i, id=i, data=b"x" * i))
        v.delete_needle(Needle(cookie=7, id=7))
    finally:
        v.close()
    if kind == "ldb":
        assert os.path.exists(os.path.join(d, "9.ldb"))
    v2 = Volume(d, "", 9, needle_map_kind=kind)
    try:
        assert v2.read_needle(12).data == b"x" * 12
        with pytest.raises(KeyError):
            v2.read_needle(7)
        # compaction must invalidate the ldb snapshot and still reload fine
        v2.compact()
        v2.commit_compact()
        assert v2.read_needle(20).data == b"x" * 20
    finally:
        v2.close()


def test_zero_size_needles_are_live_in_every_runtime_kind(tmp_path):
    """A 0-byte PUT is a live needle: the dict map serves it, so the
    compact kinds must too (get AND iteration)."""
    for kind, cls in (("memory", MemoryNeedleMap),
                      ("compact", CompactNeedleMap),
                      ("ldb", CheckpointedNeedleMap)):
        m = cls(str(tmp_path / f"{kind}.idx"))
        m.put(5, 80, 0)
        nv = m.get(5)
        assert nv is not None and nv.size == 0, kind
        assert [v.key for v in m] == [5], kind
        m.delete(5, 8)
        assert m.get(5) is None, kind
        m.close()


# --- 5-byte offsets (offset_5bytes.go as a per-volume option) ---------------

def test_idx_5byte_entry_layout_and_roundtrip():
    """17-byte entries: BE low u32 at [8:12], HIGH byte at [12]
    (offset_5bytes.go OffsetToBytes), size at [13:17]."""
    from seaweedfs_tpu.storage import idx as idx_mod

    off = (0x03_12345678) * 8  # needs the 5th byte
    b = idx_mod.pack_entry(0xDEAD, off, 1234, offset_size=5)
    assert len(b) == 17 == idx_mod.entry_size(5)
    assert b[8:12] == bytes.fromhex("12345678")
    assert b[12] == 0x03
    e = idx_mod.parse_entries(b, offset_size=5)[0]
    assert (int(e["key"]), int(e["offset"]) * 8, int(e["size"])) == \
        (0xDEAD, off, 1234)
    # 4-byte packing is unchanged byte-for-byte
    assert idx_mod.pack_entry(1, 80, 2) == idx_mod.pack_entry(1, 80, 2, 4)
    assert len(idx_mod.pack_entry(1, 80, 2)) == 16


@pytest.mark.parametrize("kind", ["memory", "compact", "ldb"])
def test_needle_map_kinds_5byte_offsets_roundtrip(tmp_path, kind):
    """Every writable map kind must round-trip offsets past the 32GB
    line when the volume is in 5-byte mode."""
    from seaweedfs_tpu.storage.needle_map import MemoryNeedleMap
    from seaweedfs_tpu.storage.needle_map_compact import (
        CheckpointedNeedleMap,
        CompactNeedleMap,
    )

    cls = {"memory": MemoryNeedleMap, "compact": CompactNeedleMap,
           "ldb": CheckpointedNeedleMap}[kind]
    path = str(tmp_path / "v.idx")
    big = 40 * (1 << 30)  # 40GB: unrepresentable in u32 units
    m = cls(path, replay=False, offset_size=5) \
        if kind != "ldb" else cls(path, replay=True, offset_size=5)
    m.put(1, 8, 100)
    m.put(2, big, 2000)
    m.put(3, big + 4096, 300)
    m.delete(3, big + 8192)
    assert m.get(2).offset == big
    assert m.get(3) is None
    m.close()
    # reopen: replay the 17-byte idx
    m2 = cls.load(path, offset_size=5)
    assert m2.get(1).offset == 8
    assert m2.get(2).offset == big
    assert m2.get(2).size == 2000
    assert m2.get(3) is None
    m2.close()


def test_sorted_file_kind_5byte(tmp_path):
    from seaweedfs_tpu.storage import idx as idx_mod
    from seaweedfs_tpu.storage.needle_map_compact import SortedFileNeedleMap

    path = str(tmp_path / "v.idx")
    big = 50 * (1 << 30)
    with open(path, "wb") as f:
        f.write(idx_mod.pack_entry(5, 8, 10, 5))
        f.write(idx_mod.pack_entry(9, big, 20, 5))
    m = SortedFileNeedleMap.load(path, offset_size=5)
    assert m.get(9).offset == big
    m.delete(9, big + 64)
    assert m.get(9) is None
    m.close()


def test_volume_5byte_offsets_persisted_and_roundtrip(tmp_path):
    """A volume created with offset_5=True persists the mode in its
    superblock (reopen WITHOUT the flag keeps 5-byte mode) and
    round-trips needles; 4-byte volumes keep byte-identical formats."""
    from seaweedfs_tpu.storage.needle import Needle
    from seaweedfs_tpu.storage.volume import Volume

    v = Volume(str(tmp_path / "five"), "", 7, offset_5=True)
    assert v.offset_size == 5
    v.write_needle(Needle(cookie=1, id=1, data=b"x" * 100))
    v.write_needle(Needle(cookie=2, id=2, data=b"y" * 5000))
    v.close()
    assert os.path.getsize(str(tmp_path / "five" / "7.idx")) % 17 == 0

    v2 = Volume(str(tmp_path / "five"), "", 7)  # flag comes from disk
    assert v2.offset_size == 5
    assert v2.read_needle(1, cookie=1).data == b"x" * 100
    assert v2.read_needle(2, cookie=2).data == b"y" * 5000
    # compaction keeps the mode
    v2.delete_needle(Needle(cookie=1, id=1))
    v2.compact()
    v2.commit_compact()
    assert v2.offset_size == 5
    assert v2.read_needle(2, cookie=2).data == b"y" * 5000
    with pytest.raises(Exception):
        v2.read_needle(1, cookie=1)
    v2.close()

    # a plain volume is unchanged: 16-byte idx entries, empty extra
    v4 = Volume(str(tmp_path / "four"), "", 8)
    v4.write_needle(Needle(cookie=3, id=3, data=b"z" * 64))
    v4.close()
    assert os.path.getsize(str(tmp_path / "four" / "8.idx")) == 16
    assert v4.super_block.extra == b""


def test_ec_generate_refuses_5byte_volume(tmp_path):
    """EC (.ecx) is a 16-byte-entry surface: encoding a 5-byte-offset
    volume must fail loudly, not write a corrupt index."""
    from seaweedfs_tpu.storage.needle import Needle
    from seaweedfs_tpu.volume_server.store import Store

    store = Store([str(tmp_path)], max_volume_count=2)
    store.add_volume(1, offset_5=True)
    store.write_needle(1, Needle(cookie=1, id=1, data=b"d" * 100))
    with pytest.raises(ValueError, match="5-byte"):
        store.ec_generate(1)
    store.close()
