"""Needle-map kinds: compact (numpy sections), ldb (checkpointed), sorted.

The gate: every kind must be observably identical to MemoryNeedleMap —
same get results, same counters, same ascending iteration — across
randomized op logs including out-of-order keys, overwrites, and deletes
(needle_map_memory.go:35-56 bookkeeping).
"""

from __future__ import annotations

import os

import numpy as np
import pytest

from seaweedfs_tpu.storage import idx as idx_mod
from seaweedfs_tpu.storage import needle_map_compact as nmc
from seaweedfs_tpu.storage.needle import Needle
from seaweedfs_tpu.storage.needle_map import MemoryNeedleMap
from seaweedfs_tpu.storage.needle_map_compact import (
    CheckpointedNeedleMap,
    CompactNeedleMap,
    SortedFileNeedleMap,
)
from seaweedfs_tpu.storage.volume import Volume

RNG = np.random.default_rng(0xC0)


def _random_ops(n=3000, keyspace=700):
    ops = []
    for _ in range(n):
        key = int(RNG.integers(1, keyspace))
        if RNG.random() < 0.25:
            ops.append(("del", key, 0, 0))
        else:
            off = int(RNG.integers(1, 1 << 20)) * 8
            size = int(RNG.integers(1, 5000))
            ops.append(("put", key, off, size))
    return ops


def _apply(m, ops):
    for op, key, off, size in ops:
        if op == "put":
            m.put(key, off, size)
        else:
            m.delete(key, off or 8)


def _counters(m):
    return (m.file_counter, m.file_byte_counter, m.deletion_counter,
            m.deletion_byte_counter, m.max_file_key)


def test_compact_matches_memory_randomized(tmp_path, monkeypatch):
    # tiny sections/flush thresholds so every structural path is exercised
    monkeypatch.setattr(nmc, "_SECTION", 64)
    monkeypatch.setattr(nmc, "_TAIL_FLUSH", 32)
    monkeypatch.setattr(nmc, "_OVERFLOW_MERGE", 50)
    ops = _random_ops()
    mem = MemoryNeedleMap(str(tmp_path / "a.idx"))
    cmp_ = CompactNeedleMap(str(tmp_path / "b.idx"))
    _apply(mem, ops)
    _apply(cmp_, ops)
    assert _counters(mem) == _counters(cmp_)
    for key in range(1, 700):
        assert mem.get(key) == cmp_.get(key), key
    assert list(mem) == list(cmp_)
    mem.close()
    cmp_.close()


def test_compact_vectorized_replay_matches_scalar(tmp_path):
    ops = _random_ops(n=2000, keyspace=300)
    path = str(tmp_path / "r.idx")
    mem = MemoryNeedleMap(path)
    _apply(mem, ops)
    mem.close()
    scalar = MemoryNeedleMap.load(path)
    vector = CompactNeedleMap.load(path)
    assert _counters(scalar) == _counters(vector)
    assert list(scalar) == list(vector)
    scalar.close()
    vector.close()


def test_checkpointed_restart_replays_only_tail(tmp_path):
    path = str(tmp_path / "v.idx")
    m = CheckpointedNeedleMap(path)
    for k in range(1, 500):
        m.put(k, k * 8, 100 + k)
    m.checkpoint()
    watermark = os.path.getsize(path)
    for k in range(500, 560):
        m.put(k, k * 8, 100 + k)
    m.delete(77, 8)
    m.close()  # close checkpoints again

    # corrupt idx BYTES BEFORE the final watermark: a snapshot load must not
    # read them (full replay would choke on the counters differing)
    m2 = CheckpointedNeedleMap.load(path)
    assert m2._loaded_from_snapshot
    full = MemoryNeedleMap.load(path)
    assert _counters(m2) == _counters(full)
    assert list(m2) == list(full)
    assert m2.get(77) is None
    m2.close()
    full.close()


def test_checkpointed_tail_after_snapshot_without_second_checkpoint(tmp_path):
    path = str(tmp_path / "t.idx")
    m = CheckpointedNeedleMap(path)
    for k in range(1, 100):
        m.put(k, k * 8, 10)
    m.checkpoint()
    # append past the snapshot, then simulate a crash (no close/checkpoint)
    for k in range(100, 130):
        m.put(k, k * 8, 20)
    m.delete(5, 8)
    m._index_file.flush()
    m._index_file.close()
    m._index_file = None

    m2 = CheckpointedNeedleMap.load(path)
    assert m2._loaded_from_snapshot
    full = MemoryNeedleMap.load(path)
    assert _counters(m2) == _counters(full)
    assert list(m2) == list(full)
    m2.close()
    full.close()


def test_checkpointed_discards_snapshot_when_idx_truncated(tmp_path):
    path = str(tmp_path / "w.idx")
    m = CheckpointedNeedleMap(path)
    for k in range(1, 50):
        m.put(k, k * 8, 10)
    m.close()
    # integrity repair truncated the idx below the snapshot watermark
    with open(path, "r+b") as f:
        f.truncate(16 * 10)
    m2 = CheckpointedNeedleMap.load(path)
    assert not m2._loaded_from_snapshot
    full = MemoryNeedleMap.load(path)
    assert _counters(m2) == _counters(full)
    assert list(m2) == list(full)
    m2.close()
    full.close()


def test_sorted_file_kind(tmp_path):
    path = str(tmp_path / "s.idx")
    mem = MemoryNeedleMap(path)
    for k in (3, 1, 9, 4, 200):
        mem.put(k, k * 8, k * 10)
    mem.delete(4, 8)
    mem.close()

    sf = SortedFileNeedleMap.load(path)
    assert os.path.exists(str(tmp_path / "s.sdx"))
    assert sf.get(9).size == 90
    assert sf.get(4) is None
    assert sf.get(77) is None
    with pytest.raises(PermissionError):
        sf.put(5, 40, 1)
    sf.delete(9, 8)
    assert sf.get(9) is None
    assert sf.deletion_byte_counter == 90
    # the in-place tombstone survives reopen
    sf.close()
    sf2 = SortedFileNeedleMap.load(path)
    assert sf2.get(9) is None and sf2.get(200).size == 2000
    sf2.close()


@pytest.mark.parametrize("kind", ["compact", "ldb", "memory"])
def test_volume_roundtrip_each_kind(tmp_path, kind):
    d = str(tmp_path / kind)
    v = Volume(d, "", 9, needle_map_kind=kind)
    try:
        for i in range(1, 30):
            v.write_needle(Needle(cookie=i, id=i, data=b"x" * i))
        v.delete_needle(Needle(cookie=7, id=7))
    finally:
        v.close()
    if kind == "ldb":
        assert os.path.exists(os.path.join(d, "9.ldb"))
    v2 = Volume(d, "", 9, needle_map_kind=kind)
    try:
        assert v2.read_needle(12).data == b"x" * 12
        with pytest.raises(KeyError):
            v2.read_needle(7)
        # compaction must invalidate the ldb snapshot and still reload fine
        v2.compact()
        v2.commit_compact()
        assert v2.read_needle(20).data == b"x" * 20
    finally:
        v2.close()


def test_zero_size_needles_are_live_in_every_runtime_kind(tmp_path):
    """A 0-byte PUT is a live needle: the dict map serves it, so the
    compact kinds must too (get AND iteration)."""
    for kind, cls in (("memory", MemoryNeedleMap),
                      ("compact", CompactNeedleMap),
                      ("ldb", CheckpointedNeedleMap)):
        m = cls(str(tmp_path / f"{kind}.idx"))
        m.put(5, 80, 0)
        nv = m.get(5)
        assert nv is not None and nv.size == 0, kind
        assert [v.key for v in m] == [5], kind
        m.delete(5, 8)
        assert m.get(5) is None, kind
        m.close()
