"""Multi-chip sharding tests on the virtual 8-device CPU mesh."""

import numpy as np
import pytest

import jax

from seaweedfs_tpu.ec.codec import CpuEngine, ReedSolomon
from seaweedfs_tpu.ec.gf256 import mat_invert, parity_rows
from seaweedfs_tpu.ops.gf_matmul import expand_matrix_bitplanes
from seaweedfs_tpu.parallel.mesh import (
    make_mesh,
    shard_data,
    sharded_encode_fn,
    training_step_fn,
)

rng = np.random.default_rng(42)

pytestmark = pytest.mark.skipif(len(jax.devices()) < 8,
                                reason="needs 8 virtual devices")


def _planes(d=10, p=4):
    return expand_matrix_bitplanes(parity_rows(d, p))


@pytest.mark.parametrize("dp,sp,tp", [(8, 1, 1), (2, 2, 2), (1, 2, 4), (2, 1, 4)])
def test_sharded_encode_matches_cpu(dp, sp, tp):
    d_shards, p_shards = 10, 4
    mesh = make_mesh(dp, sp, tp)
    a = jax.numpy.asarray(_planes(d_shards, p_shards))
    s, b = 2 * dp, 128 * sp  # tiny but divisible
    data = rng.integers(0, 256, (d_shards, s, b), dtype=np.uint8)
    fn = sharded_encode_fn(mesh)
    got = np.asarray(jax.device_get(fn(a, shard_data(mesh, data))))

    cpu = ReedSolomon(d_shards, p_shards, engine=CpuEngine())
    want = cpu.encode(data.reshape(d_shards, -1)).reshape(p_shards, s, b)
    assert np.array_equal(got, want)


def test_training_step_degraded_check_zero_mismatches():
    d_shards, p_shards = 10, 4
    mesh = make_mesh(2, 2, 2)
    matrix = ReedSolomon(d_shards, p_shards).matrix
    a = jax.numpy.asarray(_planes(d_shards, p_shards))
    # decode row for data shard 0 from survivors [1..9] + parity row 10
    survivors = list(range(1, d_shards)) + [d_shards]
    sub = [[int(v) for v in matrix[i]] for i in survivors]
    decode = np.array(mat_invert(sub), dtype=np.uint8)
    decode_planes = jax.numpy.asarray(expand_matrix_bitplanes(decode[:1]))

    data = rng.integers(0, 256, (d_shards, 4, 256), dtype=np.uint8)
    step = training_step_fn(mesh)
    parity, mismatches = step(a, decode_planes, shard_data(mesh, data))
    assert int(mismatches) == 0
    cpu = ReedSolomon(d_shards, p_shards, engine=CpuEngine())
    want = cpu.encode(data.reshape(d_shards, -1)).reshape(p_shards, 4, 256)
    assert np.array_equal(np.asarray(jax.device_get(parity)), want)


def test_ring_rebuild_matches_cpu_reconstruction():
    """Ring-collective rebuild (ppermute hops, the ring-parallel pattern):
    8 survivors sharded one-per-device reconstruct 2 missing data shards
    byte-identically to the CPU decode."""
    from seaweedfs_tpu.ec.gf256 import mat_mul
    from seaweedfs_tpu.parallel.mesh import ring_rebuild_fn

    d_shards, p_shards = 8, 4
    cpu = ReedSolomon(d_shards, p_shards, engine=CpuEngine())
    b = 256
    data = rng.integers(0, 256, (d_shards, b), dtype=np.uint8)
    parity = cpu.encode(data)
    all_shards = np.concatenate([data, parity])

    missing = [0, 5]
    survivors = [i for i in range(d_shards + p_shards)
                 if i not in missing][:d_shards]
    sub = [[int(v) for v in cpu.matrix[i]] for i in survivors]
    decode = mat_invert(sub)
    rec_rows = np.array([decode[m] for m in missing], dtype=np.uint8)

    from seaweedfs_tpu.parallel.mesh import ring_plane_layout

    mesh = make_mesh(1, 1, 8)  # last axis becomes the ring
    planes = jax.numpy.asarray(ring_plane_layout(
        expand_matrix_bitplanes(rec_rows), d_shards, 8))
    fn = ring_rebuild_fn(mesh)
    got = np.asarray(jax.device_get(
        fn(planes, jax.numpy.asarray(all_shards[survivors]))))
    assert np.array_equal(got, data[missing])


def test_streaming_encoder_uses_mesh_and_matches_cpu(tmp_path):
    """StreamingEncoder(engine='device') on a multi-device backend must
    shard dispatches over the full mesh (VERDICT r2: the mesh has to be
    reachable from the product path) and stay byte-identical."""
    import os

    from seaweedfs_tpu.ec import encoder as cpu_encoder
    from seaweedfs_tpu.ec.layout import to_ext
    from seaweedfs_tpu.ec.streaming import StreamingEncoder

    rng = np.random.default_rng(3)
    raw = rng.integers(0, 256, (2 << 20) + 4567, dtype=np.uint8).tobytes()
    dat = tmp_path / "m.dat"
    dat.write_bytes(raw)
    enc = StreamingEncoder(10, 4, engine="device", dispatch_mb=1)
    assert enc._mesh is not None
    assert enc._mesh.devices.size == len(jax.devices())
    enc.encode_file(str(dat), str(tmp_path / "m"))

    (tmp_path / "c.dat").write_bytes(raw)
    cpu_encoder.write_ec_files(str(tmp_path / "c"), ReedSolomon(10, 4))
    for i in range(14):
        assert (tmp_path / f"m{to_ext(i)}").read_bytes() == \
            (tmp_path / f"c{to_ext(i)}").read_bytes(), f"shard {i}"

    # rebuild through the mesh path too
    os.remove(tmp_path / "m.ec02")
    os.remove(tmp_path / "m.ec11")
    assert sorted(enc.rebuild_files(str(tmp_path / "m"))) == [2, 11]
    for i in (2, 11):
        assert (tmp_path / f"m{to_ext(i)}").read_bytes() == \
            (tmp_path / f"c{to_ext(i)}").read_bytes(), f"rebuilt {i}"


def test_store_ec_generate_tpu_takes_mesh_path(tmp_path):
    """-ec.engine=tpu through the volume server's store must reach the
    mesh-sharded encoder on a multi-device backend."""
    from seaweedfs_tpu.storage.needle import Needle
    from seaweedfs_tpu.volume_server.store import Store

    store = Store([str(tmp_path)], max_volume_count=2, ec_engine="tpu")
    try:
        store.add_volume(1)
        for i in range(1, 20):
            store.write_needle(1, Needle(cookie=i, id=i,
                                         data=bytes([i]) * 997 * i))
        store.ec_generate(1)
        enc = store._stream_enc
        assert enc is not None and enc._mesh is not None
        base = store.get_volume(1).file_prefix
        # shards must be byte-identical to the CPU engine's
        import os

        from seaweedfs_tpu.ec import encoder as cpu_encoder
        from seaweedfs_tpu.ec.layout import to_ext

        os.link(base + ".dat", base + "_cpu.dat")
        cpu_encoder.write_ec_files(base + "_cpu", ReedSolomon(10, 4))
        for i in range(14):
            with open(base + to_ext(i), "rb") as f1, \
                    open(base + "_cpu" + to_ext(i), "rb") as f2:
                assert f1.read() == f2.read(), f"shard {i}"
    finally:
        store.close()


def test_shard_map_shim_kwarg_dispatch(monkeypatch):
    """The check_rep -> check_vma rename shipped in DIFFERENT jax
    releases than the jax.shard_map promotion — pin the shim's kwarg
    dispatch against both spellings and the no-kwarg path."""
    from seaweedfs_tpu.parallel import mesh as mesh_mod

    seen = {}

    def fake_vma(f, *, mesh, in_specs, out_specs, check_vma):
        seen["kw"] = ("check_vma", check_vma)
        return "vma"

    def fake_rep(f, *, mesh, in_specs, out_specs, check_rep):
        seen["kw"] = ("check_rep", check_rep)
        return "rep"

    def fake_bare(f, *, mesh, in_specs, out_specs):
        seen["kw"] = (None, None)
        return "bare"

    # a jax whose shard_map already knows check_vma: passed through
    monkeypatch.setattr(mesh_mod.jax, "shard_map", fake_vma,
                        raising=False)
    assert mesh_mod._shard_map(lambda x: x, mesh="m", in_specs=(),
                               out_specs=(), check_vma=False) == "vma"
    assert seen["kw"] == ("check_vma", False)

    # an older public jax.shard_map that only knows check_rep: the
    # TypeError fallback must re-dispatch with the old spelling
    monkeypatch.setattr(mesh_mod.jax, "shard_map", fake_rep,
                        raising=False)
    assert mesh_mod._shard_map(lambda x: x, mesh="m", in_specs=(),
                               out_specs=(), check_vma=False) == "rep"
    assert seen["kw"] == ("check_rep", False)

    # check_vma=None: neither kwarg reaches shard_map at all
    monkeypatch.setattr(mesh_mod.jax, "shard_map", fake_bare,
                        raising=False)
    assert mesh_mod._shard_map(lambda x: x, mesh="m", in_specs=(),
                               out_specs=()) == "bare"
    assert seen["kw"] == (None, None)


def test_parse_device_spec_vocabulary():
    from seaweedfs_tpu.parallel.mesh import parse_device_spec

    devs = jax.devices()
    assert parse_device_spec(None) == list(devs)
    assert parse_device_spec("") == list(devs)
    assert parse_device_spec("all") == list(devs)
    assert parse_device_spec("3") == list(devs[:3])     # bare int = COUNT
    assert parse_device_spec("3,") == [devs[3]]         # trailing comma = index
    assert parse_device_spec("5,2") == [devs[5], devs[2]]
    for bad in ("0", "9", "x", "1,1", "5,9", ","):
        with pytest.raises(ValueError):
            parse_device_spec(bad)


def test_mesh_engine_matmul_matches_cpu():
    """MeshEngine (shard_map over the full mesh) must be byte-identical
    to the CPU LUT codec, including the pad/unpad path for widths not
    divisible by the dp*sp grid."""
    from seaweedfs_tpu.ec.codec import MeshEngine

    cpu = ReedSolomon(10, 4, engine=CpuEngine())
    mesh_rs = ReedSolomon(10, 4, engine=MeshEngine())
    for width in (1, 7, 64, 1000, 4096):
        data = rng.integers(0, 256, (10, width), dtype=np.uint8)
        assert np.array_equal(mesh_rs.encode(data), cpu.encode(data)), width


def _write_and_compare_mesh(tmp_path, devices, raw, dispatch_mb=1):
    """Encode raw via the per-device-queue mesh engine and assert all 14
    shards AND the `.eci` sidecar match the CPU reference encoder."""
    from seaweedfs_tpu.ec import encoder as cpu_encoder
    from seaweedfs_tpu.ec.layout import to_ext
    from seaweedfs_tpu.ec.streaming import StreamingEncoder

    dat = tmp_path / "m.dat"
    dat.write_bytes(raw)
    enc = StreamingEncoder(10, 4, engine="mesh", devices=devices,
                           dispatch_mb=dispatch_mb)
    enc.encode_file(str(dat), str(tmp_path / "m"))

    (tmp_path / "c.dat").write_bytes(raw)
    cpu_encoder.write_ec_files(str(tmp_path / "c"), ReedSolomon(10, 4))
    for i in range(14):
        assert (tmp_path / f"m{to_ext(i)}").read_bytes() == \
            (tmp_path / f"c{to_ext(i)}").read_bytes(), f"shard {i}"
    assert (tmp_path / "m.eci").read_bytes() == \
        (tmp_path / "c.eci").read_bytes()
    return enc


def test_mesh_streaming_encoder_byte_identical(tmp_path):
    """engine='mesh' on the forced 8-device CPU mesh: whole dispatches
    round-robin across per-device queues, output (shards + sidecar)
    byte-identical to the CPU codec."""
    # dispatch_mb=1 is the PER-SHARD block width: each dispatch covers
    # 10MB of the file, so 42MB -> 5 dispatches over 5 distinct queues
    raw = np.random.default_rng(7).integers(
        0, 256, (42 << 20) + 4567, dtype=np.uint8).tobytes()
    enc = _write_and_compare_mesh(tmp_path, "8", raw)
    st = enc.stats
    assert st["devices"] == 8
    assert st["dispatches"] == 5
    assert st["drain_pool"] == 8          # one drain lane per device
    per_dev = st["per_device"]
    # round-robin: with >= 5 dispatches at least 5 queues saw work
    assert sum(1 for v in per_dev.values() if v["dispatches"]) >= 5
    assert sum(v["dispatches"] for v in per_dev.values()) \
        == st["dispatches"]


def test_mesh_device_index_spec_encodes(tmp_path):
    """'5,2' pins the dispatch queues to exactly those device indices."""
    raw = np.random.default_rng(8).integers(
        0, 256, (2 << 20) + 131, dtype=np.uint8).tobytes()
    enc = _write_and_compare_mesh(tmp_path, "5,2", raw)
    assert enc.stats["devices"] == 2


def test_mesh_encoder_survives_drain_and_dispatch_faults(tmp_path):
    """Worker-kill drill through the per-device queues: injected drain
    fetch errors and a dispatch fault must fall back to CPU parity for
    the affected dispatches and stay byte-identical (PR-3 self-healing
    + PR-7 drain plumbing survive the mesh plane)."""
    from seaweedfs_tpu.utils import faultinject

    # 25MB -> 3 dispatches: the dispatch fault hits the first, the two
    # drain faults hit the first two drain spans
    raw = np.random.default_rng(9).integers(
        0, 256, (25 << 20) + 977, dtype=np.uint8).tobytes()
    faultinject.clear()
    try:
        faultinject.enable("ec.drain", error_rate=1.0, max_hits=2)
        faultinject.enable("ec.dispatch", error_rate=1.0, max_hits=1)
        enc = _write_and_compare_mesh(tmp_path, "4", raw)
    finally:
        faultinject.clear()
    assert enc.stats["fallbacks"] >= 3    # 2 drain-fetch + 1 dispatch
    assert enc.stats["devices"] == 4


def test_store_mesh_bad_device_spec_fails_at_init(tmp_path):
    """A bad -ec.mesh.devices must fail at server START (Store init),
    not at first encode."""
    from seaweedfs_tpu.volume_server.store import Store

    with pytest.raises(ValueError):
        Store([str(tmp_path)], ec_engine="mesh", ec_mesh_devices="99")


def test_store_ec_generate_mesh_path(tmp_path):
    """-ec.engine=mesh through the volume server's store: ec_generate
    must take the per-device-queue streaming path and stay
    byte-identical to the CPU engine."""
    import os

    from seaweedfs_tpu.ec import encoder as cpu_encoder
    from seaweedfs_tpu.ec.layout import to_ext
    from seaweedfs_tpu.storage.needle import Needle
    from seaweedfs_tpu.volume_server.store import Store

    store = Store([str(tmp_path)], max_volume_count=2, ec_engine="mesh")
    try:
        store.add_volume(1)
        for i in range(1, 20):
            store.write_needle(1, Needle(cookie=i, id=i,
                                         data=bytes([i]) * 997 * i))
        store.ec_generate(1)
        enc = store._stream_encs.get("mesh")
        assert enc is not None and enc.engine == "mesh"
        assert enc.stats["devices"] == len(jax.devices())
        base = store.get_volume(1).file_prefix
        os.link(base + ".dat", base + "_cpu.dat")
        cpu_encoder.write_ec_files(base + "_cpu", ReedSolomon(10, 4))
        for i in range(14):
            with open(base + to_ext(i), "rb") as f1, \
                    open(base + "_cpu" + to_ext(i), "rb") as f2:
                assert f1.read() == f2.read(), f"shard {i}"
    finally:
        store.close()
