"""Multi-chip sharding tests on the virtual 8-device CPU mesh."""

import numpy as np
import pytest

import jax

from seaweedfs_tpu.ec.codec import CpuEngine, ReedSolomon
from seaweedfs_tpu.ec.gf256 import mat_invert, parity_rows
from seaweedfs_tpu.ops.gf_matmul import expand_matrix_bitplanes
from seaweedfs_tpu.parallel.mesh import (
    make_mesh,
    shard_data,
    sharded_encode_fn,
    training_step_fn,
)

rng = np.random.default_rng(42)

pytestmark = pytest.mark.skipif(len(jax.devices()) < 8,
                                reason="needs 8 virtual devices")


def _planes(d=10, p=4):
    return expand_matrix_bitplanes(parity_rows(d, p))


@pytest.mark.parametrize("dp,sp,tp", [(8, 1, 1), (2, 2, 2), (1, 2, 4), (2, 1, 4)])
def test_sharded_encode_matches_cpu(dp, sp, tp):
    d_shards, p_shards = 10, 4
    mesh = make_mesh(dp, sp, tp)
    a = jax.numpy.asarray(_planes(d_shards, p_shards))
    s, b = 2 * dp, 128 * sp  # tiny but divisible
    data = rng.integers(0, 256, (d_shards, s, b), dtype=np.uint8)
    fn = sharded_encode_fn(mesh)
    got = np.asarray(jax.device_get(fn(a, shard_data(mesh, data))))

    cpu = ReedSolomon(d_shards, p_shards, engine=CpuEngine())
    want = cpu.encode(data.reshape(d_shards, -1)).reshape(p_shards, s, b)
    assert np.array_equal(got, want)


def test_training_step_degraded_check_zero_mismatches():
    d_shards, p_shards = 10, 4
    mesh = make_mesh(2, 2, 2)
    matrix = ReedSolomon(d_shards, p_shards).matrix
    a = jax.numpy.asarray(_planes(d_shards, p_shards))
    # decode row for data shard 0 from survivors [1..9] + parity row 10
    survivors = list(range(1, d_shards)) + [d_shards]
    sub = [[int(v) for v in matrix[i]] for i in survivors]
    decode = np.array(mat_invert(sub), dtype=np.uint8)
    decode_planes = jax.numpy.asarray(expand_matrix_bitplanes(decode[:1]))

    data = rng.integers(0, 256, (d_shards, 4, 256), dtype=np.uint8)
    step = training_step_fn(mesh)
    parity, mismatches = step(a, decode_planes, shard_data(mesh, data))
    assert int(mismatches) == 0
    cpu = ReedSolomon(d_shards, p_shards, engine=CpuEngine())
    want = cpu.encode(data.reshape(d_shards, -1)).reshape(p_shards, 4, 256)
    assert np.array_equal(np.asarray(jax.device_get(parity)), want)


def test_ring_rebuild_matches_cpu_reconstruction():
    """Ring-collective rebuild (ppermute hops, the ring-parallel pattern):
    8 survivors sharded one-per-device reconstruct 2 missing data shards
    byte-identically to the CPU decode."""
    from seaweedfs_tpu.ec.gf256 import mat_mul
    from seaweedfs_tpu.parallel.mesh import ring_rebuild_fn

    d_shards, p_shards = 8, 4
    cpu = ReedSolomon(d_shards, p_shards, engine=CpuEngine())
    b = 256
    data = rng.integers(0, 256, (d_shards, b), dtype=np.uint8)
    parity = cpu.encode(data)
    all_shards = np.concatenate([data, parity])

    missing = [0, 5]
    survivors = [i for i in range(d_shards + p_shards)
                 if i not in missing][:d_shards]
    sub = [[int(v) for v in cpu.matrix[i]] for i in survivors]
    decode = mat_invert(sub)
    rec_rows = np.array([decode[m] for m in missing], dtype=np.uint8)

    from seaweedfs_tpu.parallel.mesh import ring_plane_layout

    mesh = make_mesh(1, 1, 8)  # last axis becomes the ring
    planes = jax.numpy.asarray(ring_plane_layout(
        expand_matrix_bitplanes(rec_rows), d_shards, 8))
    fn = ring_rebuild_fn(mesh)
    got = np.asarray(jax.device_get(
        fn(planes, jax.numpy.asarray(all_shards[survivors]))))
    assert np.array_equal(got, data[missing])


def test_streaming_encoder_uses_mesh_and_matches_cpu(tmp_path):
    """StreamingEncoder(engine='device') on a multi-device backend must
    shard dispatches over the full mesh (VERDICT r2: the mesh has to be
    reachable from the product path) and stay byte-identical."""
    import os

    from seaweedfs_tpu.ec import encoder as cpu_encoder
    from seaweedfs_tpu.ec.layout import to_ext
    from seaweedfs_tpu.ec.streaming import StreamingEncoder

    rng = np.random.default_rng(3)
    raw = rng.integers(0, 256, (2 << 20) + 4567, dtype=np.uint8).tobytes()
    dat = tmp_path / "m.dat"
    dat.write_bytes(raw)
    enc = StreamingEncoder(10, 4, engine="device", dispatch_mb=1)
    assert enc._mesh is not None
    assert enc._mesh.devices.size == len(jax.devices())
    enc.encode_file(str(dat), str(tmp_path / "m"))

    (tmp_path / "c.dat").write_bytes(raw)
    cpu_encoder.write_ec_files(str(tmp_path / "c"), ReedSolomon(10, 4))
    for i in range(14):
        assert (tmp_path / f"m{to_ext(i)}").read_bytes() == \
            (tmp_path / f"c{to_ext(i)}").read_bytes(), f"shard {i}"

    # rebuild through the mesh path too
    os.remove(tmp_path / "m.ec02")
    os.remove(tmp_path / "m.ec11")
    assert sorted(enc.rebuild_files(str(tmp_path / "m"))) == [2, 11]
    for i in (2, 11):
        assert (tmp_path / f"m{to_ext(i)}").read_bytes() == \
            (tmp_path / f"c{to_ext(i)}").read_bytes(), f"rebuilt {i}"


def test_store_ec_generate_tpu_takes_mesh_path(tmp_path):
    """-ec.engine=tpu through the volume server's store must reach the
    mesh-sharded encoder on a multi-device backend."""
    from seaweedfs_tpu.storage.needle import Needle
    from seaweedfs_tpu.volume_server.store import Store

    store = Store([str(tmp_path)], max_volume_count=2, ec_engine="tpu")
    try:
        store.add_volume(1)
        for i in range(1, 20):
            store.write_needle(1, Needle(cookie=i, id=i,
                                         data=bytes([i]) * 997 * i))
        store.ec_generate(1)
        enc = store._stream_enc
        assert enc is not None and enc._mesh is not None
        base = store.get_volume(1).file_prefix
        # shards must be byte-identical to the CPU engine's
        import os

        from seaweedfs_tpu.ec import encoder as cpu_encoder
        from seaweedfs_tpu.ec.layout import to_ext

        os.link(base + ".dat", base + "_cpu.dat")
        cpu_encoder.write_ec_files(base + "_cpu", ReedSolomon(10, 4))
        for i in range(14):
            with open(base + to_ext(i), "rb") as f1, \
                    open(base + "_cpu" + to_ext(i), "rb") as f2:
                assert f1.read() == f2.read(), f"shard {i}"
    finally:
        store.close()
