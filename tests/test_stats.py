"""Stats plane: Prometheus collectors + /metrics exposition on live servers.

Mirrors the collector families of weed/stats/metrics.go:23-330.
"""

import time

import pytest

from seaweedfs_tpu.stats.metrics import Counter, Gauge, Histogram, Registry


class TestCollectors:
    def test_counter_labels(self):
        c = Counter("reqs_total", "requests", labels=("type",))
        c.inc("assign")
        c.inc("assign")
        c.inc("lookup", amount=3)
        assert c.value("assign") == 2
        assert c.value("lookup") == 3
        text = "\n".join(c.expose())
        assert '# TYPE reqs_total counter' in text
        assert 'reqs_total{type="assign"} 2' in text

    def test_gauge_set_add_clear(self):
        g = Gauge("vols", "volumes", labels=("collection", "type"))
        g.set("", "volume", 5)
        g.add("", "volume", 2)
        assert g.value("", "volume") == 7
        g.clear()
        assert g.value("", "volume") == 0

    def test_histogram_buckets_cumulative(self):
        h = Histogram("lat", "latency", labels=("op",), buckets=(0.01, 0.1, 1))
        for obs in (0.005, 0.05, 0.05, 0.5, 5.0):
            h.observe("read", obs)
        text = "\n".join(h.expose())
        assert 'lat_bucket{op="read",le="0.01"} 1' in text
        assert 'lat_bucket{op="read",le="0.1"} 3' in text
        assert 'lat_bucket{op="read",le="1"} 4' in text
        assert 'lat_bucket{op="read",le="+Inf"} 5' in text
        assert 'lat_count{op="read"} 5' in text

    def test_histogram_le_inclusive(self):
        h = Histogram("x", buckets=(1.0, 2.0))
        h.observe(2.0)  # le="2" is inclusive per Prometheus semantics
        text = "\n".join(h.expose())
        assert 'x_bucket{le="2"} 1' in text
        assert 'x_bucket{le="1"} 0' in text

    def test_histogram_timer(self):
        h = Histogram("t", labels=("op",))
        with h.time("w"):
            time.sleep(0.01)
        assert h._totals[("w",)] == 1
        assert h._sums[("w",)] >= 0.01

    def test_registry_exposition(self):
        reg = Registry()
        reg.counter("a_total").inc()
        reg.gauge("b").set(4)
        text = reg.expose()
        assert "a_total 1" in text and "b 4" in text

    def test_label_value_escaping(self):
        """Prometheus text format: backslash, quote and newline in label
        values must be escaped or the whole exposition is corrupt."""
        c = Counter("esc_total", labels=("p",))
        c.inc('a"b\\c\nd')
        text = "\n".join(c.expose())
        assert 'esc_total{p="a\\"b\\\\c\\nd"} 1' in text
        assert "\nd" not in text.split("# TYPE")[1]  # no raw newline leaks
        h = Histogram("esc_h", labels=("p",), buckets=(1.0,))
        h.observe('x"y', 0.5)
        text = "\n".join(h.expose())
        assert 'esc_h_bucket{p="x\\"y",le="1"} 1' in text
        assert 'esc_h_sum{p="x\\"y"} 0.5' in text

    def test_histogram_labels_pretouch_emits_full_series(self):
        """A label set touched via labels() but never observed still
        exposes every bucket (including +Inf) plus _sum/_count at 0."""
        h = Histogram("pre", labels=("op",), buckets=(0.5, 1.0))
        h.labels("idle")
        text = "\n".join(h.expose())
        assert 'pre_bucket{op="idle",le="0.5"} 0' in text
        assert 'pre_bucket{op="idle",le="+Inf"} 0' in text
        assert 'pre_sum{op="idle"} 0' in text
        assert 'pre_count{op="idle"} 0' in text
        # the bound child observes into the same series
        h.labels("busy").observe(0.7)
        text = "\n".join(h.expose())
        assert 'pre_bucket{op="busy",le="+Inf"} 1' in text
        assert 'pre_count{op="busy"} 1' in text
        with h.labels("busy").time():
            pass
        assert h._totals[("busy",)] == 2

    def test_counter_gauge_labels_pretouch(self):
        c = Counter("pt_total", labels=("t",))
        c.labels("seen")
        assert 'pt_total{t="seen"} 0' in "\n".join(c.expose())
        c.labels("seen").inc()
        assert c.value("seen") == 1
        g = Gauge("pt_g", labels=("t",))
        g.labels("x")
        assert 'pt_g{t="x"} 0' in "\n".join(g.expose())
        g.labels("x").add(2.5)
        g.labels("x").set(7)
        assert g.value("x") == 7


class TestServerMetricsEndpoints:
    @pytest.fixture()
    def cluster(self, tmp_path):
        from seaweedfs_tpu.master.server import MasterServer
        from seaweedfs_tpu.utils.httpd import http_json
        from seaweedfs_tpu.volume_server.server import VolumeServer
        from tests.conftest import free_port

        m = MasterServer(port=free_port()).start()
        vs = VolumeServer([str(tmp_path / "v")], m.url, port=free_port()).start()
        deadline = time.time() + 5
        while time.time() < deadline:
            if http_json("GET", f"http://{m.url}/dir/status")[
                    "Topology"]["Max"] > 0:
                break
            time.sleep(0.05)
        yield m, vs
        vs.stop()
        m.stop()

    def test_metrics_exposed_and_instrumented(self, cluster):
        from seaweedfs_tpu.client.operation import WeedClient
        from seaweedfs_tpu.utils.httpd import http_bytes

        m, vs = cluster
        c = WeedClient(m.url)
        fid = c.upload(b"metric me")
        assert c.download(fid) == b"metric me"

        status, body, headers = http_bytes("GET", f"http://{m.url}/metrics")
        assert status == 200
        text = body.decode()
        assert "text/plain" in headers.get("Content-Type", "")
        assert "SeaweedFS_master_received_heartbeats" in text
        assert 'SeaweedFS_master_request_total{type="assign"}' in text
        assert "SeaweedFS_master_is_leader 1" in text

        status, body, _ = http_bytes("GET", f"http://{vs.url}/metrics")
        text = body.decode()
        assert 'SeaweedFS_volumeServer_request_total{type="write_object"}' in text
        assert 'SeaweedFS_volumeServer_request_seconds_bucket' in text
        assert 'SeaweedFS_volumeServer_volumes{collection="",type="volume"}' in text


def test_volume_stats_endpoints(tmp_path):
    """/stats/counter, /stats/memory, /stats/disk on the volume server
    (volume_server.go:105-107, common.go statsCounter/MemoryHandler,
    statsDiskHandler).  All three read only local process state, so no
    topology registration is awaited."""
    from seaweedfs_tpu.master.server import MasterServer
    from seaweedfs_tpu.utils.httpd import http_json
    from seaweedfs_tpu.volume_server.server import VolumeServer
    from tests.conftest import free_port

    master = MasterServer(port=free_port(), pulse_seconds=0.3).start()
    d = tmp_path / "v"
    d.mkdir()
    vs = VolumeServer([str(d)], master.url, port=free_port(),
                      pulse_seconds=0.3).start()
    try:
        http_json("GET", f"http://{vs.url}/status")  # bump a counter
        c = http_json("GET", f"http://{vs.url}/stats/counter")
        assert sum(c["Counters"].values()) >= 1
        m = http_json("GET", f"http://{vs.url}/stats/memory")
        assert m["Memory"]["MaxRssKb"] > 0
        ds = http_json("GET", f"http://{vs.url}/stats/disk")
        assert ds["DiskStatuses"][0]["all"] > 0
        assert ds["DiskStatuses"][0]["dir"] == str(d)
    finally:
        vs.stop()
        master.stop()


class TestHistogramMerge:
    """Histogram.merge(other) — the cluster aggregator's cross-peer
    combine.  The defining property: merging two histograms equals one
    histogram that observed the UNION of both sample streams."""

    @staticmethod
    def _observe_all(h, labels, samples):
        for s in samples:
            h.observe(*labels, s)

    def test_merge_equals_observing_union(self):
        import random

        rng = random.Random(0xBEEF)
        # spans the whole default grid including past-the-last-bucket
        pool = [rng.choice((0.00005, 0.0005, 0.002, 0.05, 0.7, 2.5,
                            9.0, 42.0)) * rng.random() for _ in range(400)]
        for split in (0, 1, 137, 399, 400):
            a = Histogram("h", labels=("op",))
            b = Histogram("h", labels=("op",))
            union = Histogram("h", labels=("op",))
            self._observe_all(a, ("x",), pool[:split])
            self._observe_all(b, ("x",), pool[split:])
            self._observe_all(union, ("x",), pool)
            a.merge(b)
            assert a._counts[("x",)] == union._counts[("x",)]
            assert abs(a._sums[("x",)] - union._sums[("x",)]) < 1e-9
            assert a._totals[("x",)] == union._totals[("x",)]
            # exposition text identical too (cumulative form); _sum may
            # differ by float summation order, checked by tolerance above
            strip = lambda lines: [l for l in lines if "_sum" not in l]
            assert strip(a.expose()) == strip(union.expose())

    def test_merge_disjoint_and_overlapping_label_sets(self):
        a = Histogram("h", labels=("op",))
        b = Histogram("h", labels=("op",))
        a.observe("read", 0.01)
        b.observe("read", 0.02)
        b.observe("write", 1.0)
        a.merge(b)
        assert a._totals[("read",)] == 2
        assert a._totals[("write",)] == 1
        assert abs(a._sums[("read",)] - 0.03) < 1e-12

    def test_merge_rejects_bucket_mismatch(self):
        a = Histogram("h", buckets=(0.1, 1.0))
        b = Histogram("h", buckets=(0.2, 1.0))
        with pytest.raises(ValueError):
            a.merge(b)

    def test_merge_empty_other_is_noop(self):
        a = Histogram("h")
        a.observe(0.5)
        before = a.expose()
        a.merge(Histogram("h"))
        assert a.expose() == before

    def test_merge_concurrent_with_observe(self):
        """merge() racing observe() on the destination: totals add up,
        no exception, no torn bucket rows."""
        import threading

        dst = Histogram("h")
        src = Histogram("h")
        for _ in range(500):
            src.observe(0.005)
        stop = threading.Event()
        observed = [0]

        def hammer():
            while not stop.is_set():
                dst.observe(0.005)
                observed[0] += 1

        th = threading.Thread(target=hammer)
        th.start()
        for _ in range(20):
            dst.merge(src)
        stop.set()
        th.join()
        assert dst._totals[()] == 20 * 500 + observed[0]
        assert sum(dst._counts[()]) == dst._totals[()]

    def test_counter_and_gauge_merge(self):
        a = Counter("c", labels=("k",))
        b = Counter("c", labels=("k",))
        a.inc("x", amount=2)
        b.inc("x", amount=3)
        b.inc("y", amount=1)
        a.merge(b)
        assert a.value("x") == 5 and a.value("y") == 1
        g1 = Gauge("g")
        g2 = Gauge("g")
        g1.set(4)
        g2.set(6)
        g1.merge(g2)
        assert g1.value() == 10
