"""Streaming (overlapped-pipeline) EC encode/rebuild vs the serial CPU path.

The gate: StreamingEncoder output must be byte-identical to
encoder.write_ec_files / rebuild_ec_files for every geometry and file
size, including the strict-`>` large/small row transition and zero-padded
tails (ec_encoder.go:172-231 semantics).
"""

from __future__ import annotations

import os
import time

import numpy as np
import pytest

from seaweedfs_tpu.ec import encoder
from seaweedfs_tpu.ec.codec import ReedSolomon
from seaweedfs_tpu.ec.layout import to_ext
from seaweedfs_tpu.ec.streaming import (StreamingEncoder, _plan_entries,
                                        default_drain_pool)

RNG = np.random.default_rng(0x5EA)


def make_enc(k, r, engine, **kw):
    """ "host" = zero-copy mmap path, "host-pipeline" = staged host
    pipeline (zero_copy off), "device" = jax path."""
    if engine == "host-pipeline":
        return StreamingEncoder(k, r, engine="host", zero_copy=False, **kw)
    return StreamingEncoder(k, r, engine=engine, **kw)


def _write_dat(tmp_path, size, name="v"):
    p = tmp_path / f"{name}.dat"
    p.write_bytes(RNG.integers(0, 256, size, dtype=np.uint8).tobytes())
    return str(tmp_path / name)


def _shards(base, total):
    return [open(base + to_ext(i), "rb").read() for i in range(total)]


def _cpu_reference(tmp_path, base, large, small):
    ref = str(tmp_path / "ref")
    os.link(base + ".dat", ref + ".dat")
    encoder.write_ec_files(ref, ReedSolomon(10, 4),
                           large_block_size=large, small_block_size=small,
                           chunk=npchunk(small))
    return ref


def npchunk(small):
    # odd chunk size to exercise output-invariance of the CPU path too
    return max(64, small // 3 * 2)


@pytest.mark.parametrize("engine", ["host", "host-pipeline", "device"])
@pytest.mark.parametrize("size,large,small", [
    (0, 10_000, 100),              # empty volume
    (999, 10_000, 100),            # sub-single-row tail
    (10 * 100, 10_000, 100),       # exactly one small row
    (123_457, 10_000, 100),        # large rows + small rows + ragged tail
    (10 * 10_000, 10_000, 100),    # exact large-row multiple -> all small rows
    (3 * 10 * 10_000 + 7, 10_000, 100),
])
def test_streaming_encode_byte_identical(tmp_path, size, large, small, engine):
    base = _write_dat(tmp_path, size)
    ref = _cpu_reference(tmp_path, base, large, small)
    enc = make_enc(10, 4, engine, dispatch_mb=1)
    enc.dispatch_b = 4096  # force multi-dispatch packing paths
    enc.encode_file(base + ".dat", base,
                    large_block_size=large, small_block_size=small)
    assert _shards(base, 14) == _shards(ref, 14)


def test_streaming_encode_default_geometry_small_dispatch(tmp_path):
    # entries larger than one dispatch: small block (1MB-scaled) > buffer
    large, small = 1 << 16, 1 << 12
    base = _write_dat(tmp_path, 3 * 10 * (1 << 16) + 54321)
    ref = _cpu_reference(tmp_path, base, large, small)
    enc = StreamingEncoder(10, 4)
    enc.dispatch_b = 1 << 10  # 1KB buffer < small block -> chunked blocks
    enc.encode_file(base + ".dat", base,
                    large_block_size=large, small_block_size=small)
    assert _shards(base, 14) == _shards(ref, 14)


@pytest.mark.parametrize("engine", ["host", "host-pipeline", "device"])
@pytest.mark.parametrize("kill", [
    [0],            # one data shard
    [11],           # one parity shard
    [0, 3, 11, 13],  # worst case: 4 erasures mixed data+parity
])
def test_streaming_rebuild_byte_identical(tmp_path, kill, engine):
    large, small = 10_000, 100
    base = _write_dat(tmp_path, 123_457)
    encoder.write_ec_files(base, ReedSolomon(10, 4),
                           large_block_size=large, small_block_size=small)
    want = _shards(base, 14)
    for i in kill:
        os.unlink(base + to_ext(i))
    enc = make_enc(10, 4, engine)
    enc.dispatch_b = 4096
    got_ids = enc.rebuild_files(base)
    assert got_ids == sorted(kill)
    assert _shards(base, 14) == want


def test_streaming_rebuild_unrepairable(tmp_path):
    base = _write_dat(tmp_path, 50_000)
    encoder.write_ec_files(base, ReedSolomon(10, 4),
                           large_block_size=10_000, small_block_size=100)
    for i in range(5):  # only 9 of 14 left
        os.unlink(base + to_ext(i))
    with pytest.raises(ValueError, match="unrepairable"):
        StreamingEncoder(10, 4).rebuild_files(base)


@pytest.mark.parametrize("engine", ["host", "host-pipeline", "device"])
def test_streaming_alt_geometries(tmp_path, engine):
    for k, r in ((6, 3), (12, 4)):
        base = _write_dat(tmp_path, 77_777, name=f"g{k}{r}{engine[0]}")
        ref = str(tmp_path / f"ref{k}{r}{engine[0]}")
        os.link(base + ".dat", ref + ".dat")
        encoder.write_ec_files(ref, ReedSolomon(k, r),
                               large_block_size=10_000,
                               small_block_size=100, chunk=512)
        enc = make_enc(k, r, engine)
        enc.dispatch_b = 2048
        enc.encode_file(base + ".dat", base,
                        large_block_size=10_000, small_block_size=100)
        assert _shards(base, k + r) == _shards(ref, k + r)


def test_process_overlap_worker_byte_identical(tmp_path):
    """overlap="process" runs the codec in a separate process over
    shared memory (ec/overlap.py) — same shards, worker reused across
    encodes, clean shutdown."""
    base = _write_dat(tmp_path, 123_457, name="ov")
    ref = _cpu_reference(tmp_path, base, 10_000, 100)
    enc = StreamingEncoder(10, 4, engine="host", overlap="process")
    enc.dispatch_b = 4096
    try:
        enc.encode_file(base + ".dat", base,
                        large_block_size=10_000, small_block_size=100)
        assert _shards(base, 14) == _shards(ref, 14)
        # worker survives a second encode (buffer pool reuse)
        enc.encode_file(base + ".dat", base,
                        large_block_size=10_000, small_block_size=100)
        assert _shards(base, 14) == _shards(ref, 14)
        assert enc._proc_worker is not None
    finally:
        if enc._proc_worker is not None:
            enc._proc_worker.close()


class _SlowHandle:
    """Fake device result over a pull-model slow link: the transfer
    cost is paid INSIDE fetch (like np.asarray on a remote array whose
    async copy never really overlaps — the measured remote-TPU
    behavior), so whoever calls fetch eats LINK_S of wire time."""

    def __init__(self, parity, seq):
        self.parity = parity
        self.seq = seq


def test_async_drain_slow_link_overlap_and_fifo(tmp_path):
    """The synthetic-slow-link acceptance drill, deterministic on any
    CPU: every fetch blocks LINK_S (injected copy latency) while the
    host floor per dispatch is HOST_S (injected via the ec.dispatch
    delay fault).  With N=3 buffers the async drain must move that wire
    time onto the drainer thread — overlap_efficiency >= 0.6 where the
    inline drain measures ~HOST/(HOST+LINK) — while fetch order stays
    FIFO and the output (shards AND the write-order-crc `.eci`
    sidecar) stays byte-identical to the CPU reference."""
    from seaweedfs_tpu.ec.integrity import sidecar_path
    from seaweedfs_tpu.utils import faultinject as fi

    HOST_S, LINK_S = 0.03, 0.03
    large, small = 100 << 20, 1 << 18
    base = _write_dat(tmp_path, 60 << 20, name="slow")
    ref = str(tmp_path / "slowref")
    os.link(base + ".dat", ref + ".dat")
    encoder.write_ec_files(ref, ReedSolomon(10, 4),
                           large_block_size=large, small_block_size=small)

    def run(async_drain):
        enc = StreamingEncoder(10, 4, engine="host", zero_copy=False,
                               overlap="none", depth=2,  # N = 3 buffers
                               async_drain=async_drain)
        enc.dispatch_b = 1 << 18
        order: list[int] = []
        real_dispatch = enc._dispatch
        seq = {"n": 0}

        def slow_dispatch(planes, buf):
            h = _SlowHandle(real_dispatch(planes, buf), seq["n"])
            seq["n"] += 1
            return h

        def slow_fetch(h):
            time.sleep(LINK_S)  # the wire, paid by the fetching thread
            order.append(h.seq)
            return h.parity

        enc._dispatch = slow_dispatch
        enc._fetch = slow_fetch
        out = str(tmp_path / ("slow_async" if async_drain else "slow_ser"))
        fi.enable("ec.dispatch", delay=HOST_S)
        try:
            enc.encode_file(base + ".dat", out,
                            large_block_size=large, small_block_size=small)
        finally:
            fi.clear()
        eff = 1.0 - enc.stats["drain_wait_s"] / enc.stats["wall_s"]
        return enc, out, order, eff

    enc, out, order, eff = run(async_drain=True)
    n = enc.stats["dispatches"]
    assert n >= 16  # enough dispatches for the pipeline to fill
    # FIFO: the drainer fetches dispatches strictly in submission order
    assert order == list(range(n))
    # the link latency hides under host work: the host was blocked for
    # at most the pipeline tail, not LINK_S per dispatch
    assert eff >= 0.6, enc.stats
    # the concurrent fetch track carries the injected latency (only the
    # RESIDUAL wait: the part that already elapsed under host work is
    # exactly the latency the async drain hid)
    assert enc.stats["drain_s"] >= LINK_S
    assert enc.stats["drain_pool"] >= 1
    # parity-only drain: exactly r/k of bytes_in crossed back
    assert enc.stats["parity_bytes_drained"] == \
        enc.stats["bytes_in"] * 4 // 10
    # byte-identical shards AND sidecar (write-order crc stream intact)
    assert _shards(out, 14) == _shards(ref, 14)
    assert open(sidecar_path(out), "rb").read() == \
        open(sidecar_path(ref), "rb").read()
    # the inline drain on the same workload eats the wire serially
    # (~HOST/(HOST+LINK) efficiency): the async drain is what hides it
    _, _, order_s, eff_serial = run(async_drain=False)
    assert order_s == list(range(n))
    assert eff_serial <= eff - 0.1


def test_default_drain_pool_bounds():
    assert default_drain_pool(1) == 1
    assert default_drain_pool(2) == 1
    assert default_drain_pool(4) == 3
    assert default_drain_pool(64) == 4


def test_async_drain_device_engine_byte_identical(tmp_path):
    """The jax device path (XLA kernel on the CPU backend) through the
    async multi-buffered drain: fetches run on the drainer pool, the
    writer appends FIFO — bytes must not care."""
    base = _write_dat(tmp_path, 123_457, name="adev")
    ref = _cpu_reference(tmp_path, base, 10_000, 100)
    enc = StreamingEncoder(10, 4, engine="device", async_drain=True)
    enc.dispatch_b = 4096
    enc.encode_file(base + ".dat", base,
                    large_block_size=10_000, small_block_size=100)
    assert enc.stats["drain_pool"] >= 1
    assert enc.stats["parity_bytes_drained"] > 0
    assert _shards(base, 14) == _shards(ref, 14)


def test_plan_entries_covers_file_exactly():
    k, large, small = 10, 1000, 100
    size = 3 * k * large + 2 * k * small + 57
    seen = 0
    rows = set()
    for n, row_start, block, off in _plan_entries(size, k, large, small, 256):
        assert n <= 256
        seen += n * k
        rows.add((row_start, block))
    # every row contributes exactly k*block bytes of (padded) stripe
    padded = sum(k * b for _, b in rows)
    assert seen == padded
    # rows tile the file: last row start + k*block >= size
    assert max(rs + k * b for rs, b in rows) >= size


def test_reencode_over_stale_shards_byte_identical(tmp_path):
    """The mmap path reuses existing shard files without truncating to
    zero (page-cache preservation); every byte must still come from the
    NEW encode — stale bytes from a previous, different, LARGER encode
    must not leak through, including in zero-padded tail regions."""
    enc = StreamingEncoder(10, 4)
    big = _write_dat(tmp_path, 3 * 1024 * 1024 + 517, name="big")
    enc.encode_file(big + ".dat", str(tmp_path / "out"), 1 << 20, 64 << 10)
    small_size = 700 * 1024 + 13  # shrinks shard files, tail-heavy
    small = _write_dat(tmp_path, small_size, name="small")
    enc.encode_file(small + ".dat", str(tmp_path / "out"), 1 << 20, 64 << 10)
    (tmp_path / "fresh.dat").write_bytes((tmp_path / "small.dat").read_bytes())
    enc.encode_file(str(tmp_path / "fresh.dat"), str(tmp_path / "fresh"),
                    1 << 20, 64 << 10)
    for i in range(14):
        reused = (tmp_path / ("out" + to_ext(i))).read_bytes()
        clean = (tmp_path / ("fresh" + to_ext(i))).read_bytes()
        assert reused == clean, f"shard {i} differs after reuse"


@pytest.mark.parametrize("make", [
    lambda: StreamingEncoder(10, 4),
    None,  # CPU path exercised via encoder.rebuild_ec_files
])
def test_failed_rebuild_leaves_no_empty_shards(tmp_path, make):
    """A rebuild aborted by a survivor size mismatch must NOT leave
    zero-length .ecNN files that mask the missing shards on retry."""
    base = _write_dat(tmp_path, 50_000, name="fr")
    encoder.write_ec_files(base, ReedSolomon(10, 4),
                           large_block_size=10_000, small_block_size=100)
    os.unlink(base + to_ext(2))
    # corrupt a survivor's size so validation fails
    with open(base + to_ext(5), "ab") as f:
        f.write(b"extra")
    with pytest.raises(ValueError, match="size mismatch"):
        if make is None:
            encoder.rebuild_ec_files(base, ReedSolomon(10, 4), chunk=512)
        else:
            make().rebuild_files(base)
    assert not os.path.exists(base + to_ext(2))  # no empty ghost shard


def test_file_parity_worker_byte_identical(tmp_path):
    """overlap="mmap-process" keeps the zero-copy mmap read path but
    computes parity in a separate process that mmaps the same file
    (ec/overlap.py FileParityWorker) — byte-identical shards, worker
    reused across two different files, tail entries still handled by
    the parent."""
    base = _write_dat(tmp_path, 123_457, name="fw")
    ref = _cpu_reference(tmp_path, base, 10_000, 100)
    enc = StreamingEncoder(10, 4, engine="host", overlap="mmap-process")
    enc.dispatch_b = 4096
    try:
        enc.encode_file(base + ".dat", base,
                        large_block_size=10_000, small_block_size=100)
        assert _shards(base, 14) == _shards(ref, 14)
        assert enc._file_worker  # actually engaged
        # a SECOND file reuses the worker (re-opened in the child)
        base2 = _write_dat(tmp_path, 3 * 10 * 10_000 + 7, name="fw2")
        ref2 = str(tmp_path / "ref2")
        os.link(base2 + ".dat", ref2 + ".dat")
        encoder.write_ec_files(ref2, ReedSolomon(10, 4),
                               large_block_size=10_000,
                               small_block_size=100, chunk=npchunk(100))
        enc.encode_file(base2 + ".dat", base2,
                        large_block_size=10_000, small_block_size=100)
        assert _shards(base2, 14) == _shards(ref2, 14)
    finally:
        if enc._file_worker:
            enc._file_worker.close()


def test_file_parity_worker_respawns_on_dispatch_change(tmp_path):
    """dispatch_b is baked into the worker's shm slot ring: changing it
    must respawn the worker, not silently truncate parity columns."""
    base = _write_dat(tmp_path, 123_457, name="fwb")
    ref = _cpu_reference(tmp_path, base, 10_000, 100)
    enc = StreamingEncoder(10, 4, engine="host", overlap="mmap-process")
    enc.dispatch_b = 2048
    try:
        enc.encode_file(base + ".dat", base,
                        large_block_size=10_000, small_block_size=100)
        assert _shards(base, 14) == _shards(ref, 14)
        first = enc._file_worker
        enc.dispatch_b = 8192  # grow: stale worker would truncate at 2048
        enc.encode_file(base + ".dat", base,
                        large_block_size=10_000, small_block_size=100)
        assert _shards(base, 14) == _shards(ref, 14)
        assert enc._file_worker is not first  # respawned
    finally:
        enc._drop_file_worker()


def test_file_parity_worker_death_falls_back_serial(tmp_path):
    """A dead worker must not hang or corrupt: the encode falls back to
    serial compute and a later encode respawns a fresh worker."""
    base = _write_dat(tmp_path, 123_457, name="fwd")
    ref = _cpu_reference(tmp_path, base, 10_000, 100)
    enc = StreamingEncoder(10, 4, engine="host", overlap="mmap-process")
    enc.dispatch_b = 4096
    try:
        enc.encode_file(base + ".dat", base,
                        large_block_size=10_000, small_block_size=100)
        assert _shards(base, 14) == _shards(ref, 14)
        # kill the worker out from under the encoder
        enc._file_worker._proc.terminate()
        enc._file_worker._proc.join(timeout=10)
        enc.encode_file(base + ".dat", base,
                        large_block_size=10_000, small_block_size=100)
        assert _shards(base, 14) == _shards(ref, 14)  # still correct
        # the corpse was dropped; the NEXT encode spawns fresh and works
        enc.encode_file(base + ".dat", base,
                        large_block_size=10_000, small_block_size=100)
        assert _shards(base, 14) == _shards(ref, 14)
    finally:
        enc._drop_file_worker()
