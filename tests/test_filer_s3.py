"""Filer + S3 gateway integration tests over a live mini-cluster."""

from __future__ import annotations

import socket
import time
import xml.etree.ElementTree as ET

import pytest

from seaweedfs_tpu.filer.entry import FileChunk
from seaweedfs_tpu.filer.filechunks import read_plan, total_size
from seaweedfs_tpu.filer.filer_store import MemoryStore, SqliteStore
from seaweedfs_tpu.filer.server import FilerServer
from seaweedfs_tpu.gateway.s3 import S3ApiServer
from seaweedfs_tpu.master.server import MasterServer
from seaweedfs_tpu.utils.httpd import http_bytes
from seaweedfs_tpu.volume_server.server import VolumeServer


from tests.conftest import free_port  # noqa: E402


@pytest.fixture
def stack(tmp_path):
    master = MasterServer(port=free_port(), pulse_seconds=0.4).start()
    vols = []
    for i in range(2):
        d = tmp_path / f"vs{i}"
        d.mkdir()
        vols.append(VolumeServer([str(d)], master.url, port=free_port(),
                                 pulse_seconds=0.4).start())
    deadline = time.time() + 5
    while time.time() < deadline and len(master.topo.all_nodes()) < 2:
        time.sleep(0.05)
    filer = FilerServer(master.url, SqliteStore(str(tmp_path / "filer.db")),
                        port=free_port(), max_chunk_mb=1).start()
    s3 = S3ApiServer(filer, port=free_port()).start()
    yield master, vols, filer, s3
    s3.stop()
    filer.stop()
    for v in vols:
        v.stop()
    master.stop()


# --- chunk math unit tests -------------------------------------------------

def test_chunk_overlap_resolution():
    chunks = [
        FileChunk("1,a", 0, 100, modified_ts_ns=1),
        FileChunk("1,b", 50, 100, modified_ts_ns=2),  # newer, shadows 50-100
        FileChunk("1,c", 20, 10, modified_ts_ns=3),  # newest, shadows 20-30
    ]
    views = read_plan(chunks, 0, total_size(chunks))
    covered = [(v.logic_offset, v.logic_offset + v.size, v.file_id) for v in views]
    assert covered == [(0, 20, "1,a"), (20, 30, "1,c"), (30, 50, "1,a"),
                       (50, 150, "1,b")]
    # offsets within chunks account for shadowed prefixes
    v_b = next(v for v in views if v.file_id == "1,b")
    assert v_b.offset_in_chunk == 0
    v_a2 = next(v for v in views if v.logic_offset == 30)
    assert v_a2.offset_in_chunk == 30


def test_chunk_partial_range():
    chunks = [FileChunk("1,a", 0, 1000, modified_ts_ns=1)]
    views = read_plan(chunks, 100, 50)
    assert len(views) == 1
    assert views[0].offset_in_chunk == 100 and views[0].size == 50


# --- filer over HTTP --------------------------------------------------------

def test_filer_put_get_multichunk(stack):
    _, _, filer, _ = stack
    payload = bytes(range(256)) * 8192  # 2MB -> 2 chunks at 1MB
    status, _, _ = http_bytes("PUT", f"http://{filer.url}/docs/big.bin", payload)
    assert status == 201
    entry = filer.filer.find_entry("/docs/big.bin")
    assert len(entry.chunks) == 2
    status, body, headers = http_bytes("GET", f"http://{filer.url}/docs/big.bin")
    assert status == 200 and body == payload

    # range read across the chunk boundary
    status, body, headers = http_bytes(
        "GET", f"http://{filer.url}/docs/big.bin",
        headers={"Range": "bytes=1048570-1048589"})
    assert status == 206
    assert body == payload[1048570:1048590]


def test_filer_listing_and_mkdir(stack):
    _, _, filer, _ = stack
    import json

    for name in ("a.txt", "b.txt", "sub/c.txt"):
        http_bytes("PUT", f"http://{filer.url}/dir1/{name}", b"x")
    status, body, _ = http_bytes("GET", f"http://{filer.url}/dir1")
    listing = json.loads(body)
    names = sorted(e["FullPath"] for e in listing["Entries"])
    assert names == ["/dir1/a.txt", "/dir1/b.txt", "/dir1/sub"]


def test_filer_rename_subtree(stack):
    _, _, filer, _ = stack
    http_bytes("PUT", f"http://{filer.url}/old/deep/file.txt", b"content")
    status, _, _ = http_bytes(
        "POST", f"http://{filer.url}/api/rename",
        b'{"from": "/old", "to": "/new"}',
        headers={"Content-Type": "application/json"})
    assert status == 200
    status, body, _ = http_bytes("GET", f"http://{filer.url}/new/deep/file.txt")
    assert status == 200 and body == b"content"
    status, _, _ = http_bytes("GET", f"http://{filer.url}/old/deep/file.txt")
    assert status == 404


def test_filer_delete_frees_chunks(stack):
    master, vols, filer, _ = stack
    payload = b"z" * 100_000
    http_bytes("PUT", f"http://{filer.url}/gc/target.bin", payload)
    entry = filer.filer.find_entry("/gc/target.bin")
    fid = entry.chunks[0].file_id
    status, body, _ = http_bytes("GET", f"http://{filer.client.master.lookup(int(fid.split(',')[0]))[0]}/{fid}")
    assert status == 200
    http_bytes("DELETE", f"http://{filer.url}/gc/target.bin")
    filer.filer.flush_gc()
    url = filer.client.master.lookup(int(fid.split(",")[0]))[0]
    status, _, _ = http_bytes("GET", f"http://{url}/{fid}")
    assert status == 404  # chunk physically gone


def test_filer_overwrite_frees_old_chunks(stack):
    _, _, filer, _ = stack
    http_bytes("PUT", f"http://{filer.url}/ow/f.bin", b"version one")
    old_fid = filer.filer.find_entry("/ow/f.bin").chunks[0].file_id
    http_bytes("PUT", f"http://{filer.url}/ow/f.bin", b"version two!")
    filer.filer.flush_gc()
    status, body, _ = http_bytes("GET", f"http://{filer.url}/ow/f.bin")
    assert body == b"version two!"
    url = filer.client.master.lookup(int(old_fid.split(",")[0]))[0]
    status, _, _ = http_bytes("GET", f"http://{url}/{old_fid}")
    assert status == 404


def test_filer_rename_into_own_subtree_rejected(stack):
    _, _, filer, _ = stack
    http_bytes("PUT", f"http://{filer.url}/tree/file.txt", b"x")
    status, body, _ = http_bytes(
        "POST", f"http://{filer.url}/api/rename",
        b'{"from": "/tree", "to": "/tree/sub"}',
        headers={"Content-Type": "application/json"})
    assert status == 500 or status == 400
    assert b"subtree" in body
    # tree untouched
    status, body, _ = http_bytes("GET", f"http://{filer.url}/tree/file.txt")
    assert status == 200 and body == b"x"


def test_filer_suffix_range_and_head(stack):
    _, _, filer, _ = stack
    payload = bytes(range(200))
    http_bytes("PUT", f"http://{filer.url}/r/f.bin", payload)
    status, body, headers = http_bytes(
        "GET", f"http://{filer.url}/r/f.bin", headers={"Range": "bytes=-10"})
    assert status == 206 and body == payload[-10:]
    assert headers["Content-Range"] == "bytes 190-199/200"
    status, body, headers = http_bytes(
        "GET", f"http://{filer.url}/r/f.bin", headers={"Range": "bytes=50-"})
    assert status == 206 and body == payload[50:]
    status, body, headers = http_bytes("HEAD", f"http://{filer.url}/r/f.bin")
    assert status == 200 and body == b""
    assert headers["Content-Length"] == "200"


def test_api_stat_missing_is_404(stack):
    _, _, filer, _ = stack
    status, _, _ = http_bytes("GET", f"http://{filer.url}/api/stat/nope")
    assert status == 404


# --- S3 gateway -------------------------------------------------------------

def _s3(stack):
    return stack[3]


def test_s3_bucket_lifecycle(stack):
    s3 = _s3(stack)
    assert http_bytes("PUT", f"http://{s3.url}/mybucket", b"")[0] == 200
    assert http_bytes("HEAD", f"http://{s3.url}/mybucket")[0] == 200
    status, body, _ = http_bytes("GET", f"http://{s3.url}/")
    assert b"<Name>mybucket</Name>" in body
    assert http_bytes("DELETE", f"http://{s3.url}/mybucket")[0] == 204
    assert http_bytes("HEAD", f"http://{s3.url}/mybucket")[0] == 404


def test_s3_object_roundtrip(stack):
    s3 = _s3(stack)
    http_bytes("PUT", f"http://{s3.url}/data", b"")
    status, _, headers = http_bytes(
        "PUT", f"http://{s3.url}/data/hello.txt", b"hello s3",
        headers={"Content-Type": "text/plain"})
    assert status == 200 and headers.get("ETag")
    status, body, headers = http_bytes("GET", f"http://{s3.url}/data/hello.txt")
    assert status == 200 and body == b"hello s3"
    assert headers["Content-Type"] == "text/plain"
    # range
    status, body, _ = http_bytes("GET", f"http://{s3.url}/data/hello.txt",
                                 headers={"Range": "bytes=6-7"})
    assert status == 206 and body == b"s3"
    assert http_bytes("DELETE", f"http://{s3.url}/data/hello.txt")[0] == 204
    status, body, _ = http_bytes("GET", f"http://{s3.url}/data/hello.txt")
    assert status == 404 and b"NoSuchKey" in body


def test_s3_list_objects_v2(stack):
    s3 = _s3(stack)
    http_bytes("PUT", f"http://{s3.url}/listing", b"")
    for key in ("a.txt", "docs/one.txt", "docs/two.txt", "img/pic.png"):
        http_bytes("PUT", f"http://{s3.url}/listing/{key}", b"content")
    status, body, _ = http_bytes(
        "GET", f"http://{s3.url}/listing?delimiter=%2F")
    root = ET.fromstring(body)
    ns = {"s3": S3NS} if (S3NS := root.tag.split("}")[0].strip("{")) else {}
    keys = [e.find("s3:Key", ns).text for e in root.findall("s3:Contents", ns)]
    prefixes = [e.find("s3:Prefix", ns).text
                for e in root.findall("s3:CommonPrefixes", ns)]
    assert keys == ["a.txt"]
    assert sorted(prefixes) == ["docs/", "img/"]
    # prefix listing
    status, body, _ = http_bytes(
        "GET", f"http://{s3.url}/listing?prefix=docs%2F")
    root = ET.fromstring(body)
    keys = [e.find("s3:Key", ns).text for e in root.findall("s3:Contents", ns)]
    assert keys == ["docs/one.txt", "docs/two.txt"]


def test_s3_list_key_order_and_pagination(stack):
    """'docs.txt' must sort before 'docs/…' keys, and pagination with a
    continuation token must not skip it."""
    s3 = _s3(stack)
    http_bytes("PUT", f"http://{s3.url}/pg", b"")
    keys = ["docs/a.txt", "docs/b.txt", "docs.txt", "apple.txt"]
    for k in keys:
        http_bytes("PUT", f"http://{s3.url}/pg/{k}", b"x")
    got, token = [], ""
    for _ in range(10):
        url = f"http://{s3.url}/pg?list-type=2&max-keys=1"
        if token:
            url += f"&continuation-token={token}"
        _, body, _ = http_bytes("GET", url)
        root = ET.fromstring(body)
        ns = {"s3": root.tag.split("}")[0].strip("{")}
        got += [e.findtext("s3:Key", namespaces=ns)
                for e in root.findall("s3:Contents", ns)]
        if root.findtext("s3:IsTruncated", namespaces=ns) != "true":
            break
        token = root.findtext("s3:NextContinuationToken", namespaces=ns)
    assert got == ["apple.txt", "docs.txt", "docs/a.txt", "docs/b.txt"]


def test_s3_head_reports_real_length(stack):
    s3 = _s3(stack)
    http_bytes("PUT", f"http://{s3.url}/hd", b"")
    http_bytes("PUT", f"http://{s3.url}/hd/obj.bin", b"q" * 4242)
    status, body, headers = http_bytes("HEAD", f"http://{s3.url}/hd/obj.bin")
    assert status == 200 and body == b""
    assert headers["Content-Length"] == "4242"


def test_s3_multipart_upload(stack):
    s3 = _s3(stack)
    http_bytes("PUT", f"http://{s3.url}/mp", b"")
    status, body, _ = http_bytes("POST", f"http://{s3.url}/mp/big.bin?uploads", b"")
    upload_id = ET.fromstring(body).findtext(
        "{http://s3.amazonaws.com/doc/2006-03-01/}UploadId")
    assert upload_id
    parts = [b"A" * 1_500_000, b"B" * 1_500_000, b"C" * 10]
    for i, part in enumerate(parts, start=1):
        status, _, _ = http_bytes(
            "PUT",
            f"http://{s3.url}/mp/big.bin?partNumber={i}&uploadId={upload_id}",
            part)
        assert status == 200
    status, body, _ = http_bytes(
        "POST", f"http://{s3.url}/mp/big.bin?uploadId={upload_id}", b"")
    assert status == 200 and b"CompleteMultipartUploadResult" in body
    status, body, _ = http_bytes("GET", f"http://{s3.url}/mp/big.bin")
    assert status == 200 and body == b"".join(parts)


def test_s3_copy_object(stack):
    s3 = _s3(stack)
    http_bytes("PUT", f"http://{s3.url}/cp", b"")
    http_bytes("PUT", f"http://{s3.url}/cp/src.txt", b"copy me")
    status, body, _ = http_bytes(
        "PUT", f"http://{s3.url}/cp/dst.txt", b"",
        headers={"X-Amz-Copy-Source": "/cp/src.txt"})
    assert status == 200 and b"CopyObjectResult" in body
    status, body, _ = http_bytes("GET", f"http://{s3.url}/cp/dst.txt")
    assert body == b"copy me"


def test_s3_bucket_not_empty(stack):
    s3 = _s3(stack)
    http_bytes("PUT", f"http://{s3.url}/full", b"")
    http_bytes("PUT", f"http://{s3.url}/full/x.txt", b"x")
    status, body, _ = http_bytes("DELETE", f"http://{s3.url}/full")
    assert status == 409 and b"BucketNotEmpty" in body


# --- store backends ---------------------------------------------------------

@pytest.mark.parametrize("store_cls", [MemoryStore, "sqlite"])
def test_store_backend_semantics(tmp_path, store_cls):
    from seaweedfs_tpu.filer.entry import Attr, Entry

    store = (SqliteStore(str(tmp_path / "s.db")) if store_cls == "sqlite"
             else store_cls())
    e = Entry("/d/x.txt", Attr(mime="text/plain"))
    store.insert_entry(e)
    store.insert_entry(Entry("/d/y.txt"))
    store.insert_entry(Entry("/d/sub"))
    assert store.find_entry("/d/x.txt").attr.mime == "text/plain"
    listed = [x.name for x in store.list_directory_entries("/d")]
    assert listed == ["sub", "x.txt", "y.txt"]
    listed = [x.name for x in store.list_directory_entries("/d", prefix="x")]
    assert listed == ["x.txt"]
    listed = [x.name for x in store.list_directory_entries("/d", start_file="sub")]
    assert listed == ["x.txt", "y.txt"]
    store.delete_entry("/d/x.txt")
    assert store.find_entry("/d/x.txt") is None
    store.kv_put(b"k", b"v")
    assert store.kv_get(b"k") == b"v"
    store.kv_delete(b"k")
    assert store.kv_get(b"k") is None
