"""Tier-1 gate (via the weedlint W401 shim): the degraded-signal
tables stay mutually consistent.

tools/check_health_keys.py lints stats/aggregate.py HEALTH_FAMILIES,
analysis.py DEGRADE_COUNTER_KEYS, the events.py type registry, and the
default alert rule set against each other — a degraded counter added to
one table but not the others was previously silent drift.  The planted
tests feed the checker synthetically drifted tables and assert each
rule actually catches.
"""

from __future__ import annotations

import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", "tools"))

from check_health_keys import check_repo, check_tables  # noqa: E402

from seaweedfs_tpu.observability.alerts import Rule  # noqa: E402


def _consistent_tables():
    """A minimal mutually-consistent table set the planted tests
    perturb one piece at a time."""
    health = {"worker_restarts": "F_restarts", "corrupt_shards": "F_rot"}
    degrade = ("worker_restarts", "corrupt_shards", "retries",
               "fallbacks")
    event_types = {"worker_restart": "warning", "shard_corrupt": "error",
                   "alert_pending": "info", "alert_fired": "error",
                   "alert_resolved": "info"}
    mapping = {"worker_restarts": "worker_restart",
               "corrupt_shards": "shard_corrupt"}
    rules = [
        Rule("worker_restarts_increase", "counter_increase",
             severity="warning", params={"key": "worker_restarts"}),
        Rule("corrupt_shards_increase", "counter_increase",
             severity="error", params={"key": "corrupt_shards"}),
    ]
    return health, degrade, rules, event_types, mapping


def _check(health, degrade, rules, event_types, mapping):
    return check_tables(health, degrade, rules, event_types, mapping,
                        allowlist=(), per_run_only=("retries",
                                                    "fallbacks"))


def test_consistent_tables_pass():
    assert _check(*_consistent_tables()) == []


def test_repo_tables_are_consistent():
    """THE tier-1 gate: the real tables, imported live."""
    assert check_repo() == []


def test_health_key_without_event_type_caught():
    health, degrade, rules, event_types, mapping = _consistent_tables()
    health["scrub_repairs"] = "F_repairs"
    degrade = degrade + ("scrub_repairs",)
    rules.append(Rule("scrub_repairs_increase", "counter_increase",
                      params={"key": "scrub_repairs"}))
    out = _check(health, degrade, rules, event_types, mapping)
    assert any("no event type" in m for m in out)


def test_mapping_to_unregistered_event_type_caught():
    health, degrade, rules, event_types, mapping = _consistent_tables()
    mapping["worker_restarts"] = "worker_reborn"  # not in EVENT_TYPES
    out = _check(health, degrade, rules, event_types, mapping)
    assert any("not registered" in m for m in out)


def test_stale_mapping_caught():
    health, degrade, rules, event_types, mapping = _consistent_tables()
    mapping["engine_fallbacks"] = "worker_restart"  # key left the table
    out = _check(health, degrade, rules, event_types, mapping)
    assert any("stale mapping" in m for m in out)


def test_health_key_missing_from_degrade_keys_caught():
    health, degrade, rules, event_types, mapping = _consistent_tables()
    degrade = ("worker_restarts", "retries", "fallbacks")  # lost rot
    out = _check(health, degrade, rules, event_types, mapping)
    assert any("DEGRADE_COUNTER_KEYS" in m and "corrupt_shards" in m
               for m in out)


def test_unknown_degrade_key_caught():
    health, degrade, rules, event_types, mapping = _consistent_tables()
    degrade = degrade + ("gamma_rays",)
    out = _check(health, degrade, rules, event_types, mapping)
    assert any("gamma_rays" in m for m in out)


def test_unwatched_health_key_caught():
    health, degrade, rules, event_types, mapping = _consistent_tables()
    rules = [r for r in rules if r.params["key"] != "corrupt_shards"]
    out = _check(health, degrade, rules, event_types, mapping)
    assert any("no default" in m and "corrupt_shards" in m for m in out)


def test_rule_watching_unknown_key_caught():
    health, degrade, rules, event_types, mapping = _consistent_tables()
    rules.append(Rule("bogus", "counter_increase",
                      params={"key": "does_not_exist"}))
    out = _check(health, degrade, rules, event_types, mapping)
    assert any("unknown health key" in m for m in out)


def test_rule_severity_disagreeing_with_event_type_caught():
    health, degrade, rules, event_types, mapping = _consistent_tables()
    rules[1] = Rule("corrupt_shards_increase", "counter_increase",
                    severity="info",  # EVENT_TYPES says error
                    params={"key": "corrupt_shards"})
    out = _check(health, degrade, rules, event_types, mapping)
    assert any("disagrees with EVENT_TYPES" in m for m in out)


def test_missing_alert_lifecycle_type_caught():
    health, degrade, rules, event_types, mapping = _consistent_tables()
    del event_types["alert_resolved"]
    out = _check(health, degrade, rules, event_types, mapping)
    assert any("alert_resolved" in m for m in out)


def test_standalone_main_runs_clean():
    import subprocess

    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    p = subprocess.run(
        [sys.executable, os.path.join(repo, "tools",
                                      "check_health_keys.py")],
        capture_output=True, text=True, timeout=120,
        env={**os.environ, "JAX_PLATFORMS": "cpu"})
    assert p.returncode == 0, p.stdout + p.stderr
    assert "consistent" in p.stdout
