"""Elasticsearch filer store against an in-process REST double.

Gates mirror the redis/etcd suites: CRUD + listing pagination/prefix +
low-start_file bound, per-top-level-index deletion, kv scans, randomized
differential vs MemoryStore, and a Filer riding on top.
Ref: weed/filer/elastic/v7/elastic_store.go.
"""

from __future__ import annotations

import numpy as np
import pytest

from seaweedfs_tpu.filer.elastic_store import ElasticStore
from seaweedfs_tpu.filer.entry import Attr, Entry, FileChunk
from seaweedfs_tpu.filer.filer import Filer
from seaweedfs_tpu.filer.filer_store import MemoryStore

from .minielastic import MiniElastic


@pytest.fixture()
def server():
    s = MiniElastic()
    yield s
    s.stop()


@pytest.fixture()
def store(server):
    return ElasticStore.from_url(f"elastic://127.0.0.1:{server.port}")


def _file(path: str, n: int = 1) -> Entry:
    chunks = [FileChunk(file_id=f"3,{i:02x}", offset=i * 10, size=10)
              for i in range(n)]
    return Entry(full_path=path, attr=Attr(mode=0o660), chunks=chunks)


def test_crud_listing_pagination(store):
    for name in ("a.txt", "b.txt", "c.txt"):
        store.insert_entry(_file(f"/d/{name}", n=2))
    got = store.find_entry("/d/b.txt")
    assert got is not None and len(got.chunks) == 2
    assert store.find_entry("/d/zz") is None
    assert [e.full_path for e in store.list_directory_entries("/d")] == [
        "/d/a.txt", "/d/b.txt", "/d/c.txt"]
    assert [e.full_path for e in store.list_directory_entries(
        "/d", start_file="a.txt", limit=2)] == ["/d/b.txt", "/d/c.txt"]
    assert [e.full_path for e in store.list_directory_entries(
        "/d", start_file="b.txt", include_start=True, limit=1)] == [
        "/d/b.txt"]
    store.delete_entry("/d/b.txt")
    assert store.find_entry("/d/b.txt") is None


def test_prefix_and_low_start_file(store):
    for name in ("aa", "ab", "ba", "bb"):
        store.insert_entry(_file(f"/p/{name}"))
    assert [e.name for e in store.list_directory_entries(
        "/p", prefix="a")] == ["aa", "ab"]
    got = [e.full_path for e in store.list_directory_entries(
        "/p", start_file="aa", prefix="b", limit=2)]
    assert got == ["/p/ba", "/p/bb"]


def test_search_after_paging(store):
    for i in range(25):
        store.insert_entry(_file(f"/pg/f{i:03d}"))
    import seaweedfs_tpu.filer.elastic_store as es_mod

    old_page, es_mod.PAGE = es_mod.PAGE, 10  # force 3 pages
    try:
        names = [e.name for e in store.list_directory_entries(
            "/pg", limit=1000)]
    finally:
        es_mod.PAGE = old_page
    assert names == [f"f{i:03d}" for i in range(25)]


def test_top_level_delete_drops_index(store):
    store.insert_entry(_file("/tree/a"))
    store.insert_entry(_file("/tree/sub/b"))
    store.insert_entry(_file("/other/c"))
    store.delete_entry("/tree")  # top-level: whole index drops
    assert store.find_entry("/tree/a") is None
    assert store.find_entry("/tree/sub/b") is None
    assert store.find_entry("/other/c") is not None


def test_delete_folder_children_recursive(store):
    for p in ("/top/f1", "/top/sub/f2", "/other/f4"):
        store.insert_entry(_file(p))
    from seaweedfs_tpu.filer.entry import DIRECTORY_MODE_BIT

    store.insert_entry(Entry(full_path="/top/sub",
                             attr=Attr(mode=DIRECTORY_MODE_BIT | 0o755)))
    store.delete_folder_children("/top")
    assert store.find_entry("/top/f1") is None
    assert store.find_entry("/top/sub/f2") is None
    assert store.find_entry("/other/f4") is not None


def test_kv_scan_pages_past_search_cap(store):
    """kv_scan uses the same search_after loop as directory listings —
    a single capped _search would silently truncate large scans."""
    import seaweedfs_tpu.filer.elastic_store as es_mod

    for i in range(25):
        store.kv_put(f"pk{i:03d}".encode(), f"v{i}".encode())
    old_page, es_mod.PAGE = es_mod.PAGE, 10  # force 3 pages
    try:
        got = list(store.kv_scan(b"pk"))
    finally:
        es_mod.PAGE = old_page
    assert got == [(f"pk{i:03d}".encode(), f"v{i}".encode())
                   for i in range(25)]


def test_kv_roundtrip_and_scan(store):
    store.kv_put(b"k1", b"\x00\xffbin")
    store.kv_put(b"k2", b"v2")
    store.kv_put(b"other", b"v3")
    assert store.kv_get(b"k1") == b"\x00\xffbin"
    assert store.kv_get(b"nope") is None
    assert [(k, v) for k, v in store.kv_scan(b"k")] == [
        (b"k1", b"\x00\xffbin"), (b"k2", b"v2")]
    store.kv_delete(b"k1")
    assert store.kv_get(b"k1") is None


def test_differential_vs_memory_store(store):
    mem = MemoryStore()
    rng = np.random.default_rng(23)
    names = [f"f{i:02d}" for i in range(15)]
    for _ in range(200):
        op = rng.integers(0, 4)
        path = f"/r/{names[rng.integers(0, 15)]}"
        if op == 0:
            e = _file(path, n=int(rng.integers(1, 4)))
            store.insert_entry(e)
            mem.insert_entry(e)
        elif op == 1:
            store.delete_entry(path)
            mem.delete_entry(path)
        elif op == 2:
            assert (store.find_entry(path) is None) == \
                (mem.find_entry(path) is None)
        else:
            got = [e.full_path for e in store.list_directory_entries("/r")]
            want = [e.full_path for e in mem.list_directory_entries("/r")]
            assert got == want


def test_filer_on_elastic(store):
    f = Filer(store)
    f.create_entry(_file("/docs/readme.md"))
    assert f.find_entry("/docs/readme.md") is not None
    assert [e.name for e in f.list_directory("/docs")] == ["readme.md"]


def test_root_listing_spans_top_level_indices(store):
    """Children of '/' live in one index per top-level name — the root
    listing must search across .seaweedfs_* (review repro: it returned
    [] while /docs existed)."""
    from seaweedfs_tpu.filer.entry import DIRECTORY_MODE_BIT

    for top in ("docs", "logs"):
        store.insert_entry(Entry(
            full_path=f"/{top}",
            attr=Attr(mode=DIRECTORY_MODE_BIT | 0o755)))
        store.insert_entry(_file(f"/{top}/f.txt"))
    store.kv_put(b"noise", b"x")  # kv index must not leak into listings
    assert [e.full_path for e in store.list_directory_entries("/")] == [
        "/docs", "/logs"]
