"""HBase filer store against the in-process RegionServer double.

Gates:
- the wire handshake (preamble + ConnectionHeader) and call_id-matched
  framing round-trip; a wrong-auth server drops the client cleanly
- region discovery runs the real meta-scan algorithm (info:regioninfo
  + info:server) and a missing table raises TableNotFound
- CRUD, prefix/resume listings, recursive delete, and the kv family
  behave observably identically to MemoryStore under randomized ops
- reconnect: a restarted regionserver (same port) is picked up by the
  transparent reconnect without surfacing an error
- a Filer runs end-to-end on the store
"""

from __future__ import annotations

import numpy as np
import pytest

from seaweedfs_tpu.filer.entry import Attr, Entry, FileChunk
from seaweedfs_tpu.filer.filer import Filer
from seaweedfs_tpu.filer.filer_store import MemoryStore
from seaweedfs_tpu.filer.hbase_store import HBaseError, HbaseStore

from .minihbase import MiniHBase

RNG = np.random.default_rng(0x4BA5E)


@pytest.fixture()
def server():
    s = MiniHBase()
    yield s
    s.stop()


@pytest.fixture()
def store(server):
    return HbaseStore(port=server.port)


def _file(path: str, n: int = 1) -> Entry:
    chunks = [FileChunk(file_id=f"3,{i:02x}", offset=i * 10, size=10)
              for i in range(n)]
    return Entry(full_path=path, attr=Attr(mode=0o660), chunks=chunks)


def test_crud_and_listing(store):
    store.insert_entry(_file("/d/a.txt"))
    store.insert_entry(_file("/d/b.txt", 3))
    store.insert_entry(_file("/d/sub/deep.txt"))
    got = store.find_entry("/d/b.txt")
    assert got is not None and len(got.chunks) == 3
    # direct children only: the sub/deep row shares the prefix but is
    # not a child (reference's DirAndName check)
    assert [e.full_path for e in store.list_directory_entries("/d")] == [
        "/d/a.txt", "/d/b.txt"]
    assert [e.full_path for e in store.list_directory_entries(
        "/d", start_file="a.txt")] == ["/d/b.txt"]
    assert [e.full_path for e in store.list_directory_entries(
        "/d", start_file="a.txt", include_start=True, limit=1)] == [
        "/d/a.txt"]
    store.delete_entry("/d/a.txt")
    assert store.find_entry("/d/a.txt") is None


def test_prefix_listing_and_scan_paging(store):
    for i in range(30):
        store.insert_entry(_file(f"/pg/f{i:03d}"))
    store.insert_entry(_file("/pg/other"))
    got = [e.full_path for e in store.list_directory_entries(
        "/pg", prefix="f")]
    assert got == [f"/pg/f{i:03d}" for i in range(30)]
    # small scanner batches force continuation Scan calls
    rows = list(store._scan(b"meta", b"/pg/", batch=7))
    assert len(rows) == 31


def test_delete_folder_children_recursive(store):
    for p in ("/top/f1", "/top/sub/f2", "/top/sub/deep/f3", "/other/f4"):
        store.insert_entry(_file(p))
    store.delete_folder_children("/top")
    assert store.find_entry("/top/f1") is None
    assert store.find_entry("/top/sub/f2") is None
    assert store.find_entry("/top/sub/deep/f3") is None
    assert store.find_entry("/other/f4") is not None


def test_kv_family(store):
    store.kv_put(b"\x01\x02", b"v1")
    store.kv_put(b"\x01\x03", b"\x00\xffbin")
    store.kv_put(b"\x99", b"other")
    assert store.kv_get(b"\x01\x02") == b"v1"
    assert store.kv_get(b"nope") is None
    assert [(k, v) for k, v in store.kv_scan(b"\x01")] == [
        (b"\x01\x02", b"v1"), (b"\x01\x03", b"\x00\xffbin")]
    store.kv_delete(b"\x01\x02")
    assert store.kv_get(b"\x01\x02") is None
    # kv and meta families are isolated: same key, different cf
    store.insert_entry(_file("/x"))
    store.kv_put(b"/x", b"kv-value")
    assert store.find_entry("/x") is not None
    assert store.kv_get(b"/x") == b"kv-value"
    store.kv_delete(b"/x")
    assert store.find_entry("/x") is not None


def test_differential_vs_memory_store(store):
    mem = MemoryStore()
    names = [f"f{i:02d}" for i in range(15)]
    for _ in range(120):
        r = RNG.integers(0, 10)
        name = names[RNG.integers(0, len(names))]
        path = f"/diff/{name}"
        if r < 5:
            e = _file(path, int(RNG.integers(1, 4)))
            store.insert_entry(e)
            mem.insert_entry(e)
        elif r < 7:
            store.delete_entry(path)
            mem.delete_entry(path)
        else:
            a, b = store.find_entry(path), mem.find_entry(path)
            assert (a is None) == (b is None)
            if a is not None:
                assert a.to_dict() == b.to_dict()
        if r == 9:
            assert [e.full_path for e in store.list_directory_entries(
                "/diff", limit=100)] == \
                [e.full_path for e in mem.list_directory_entries(
                    "/diff", limit=100)]


def test_region_discovery_and_missing_table(server):
    # discovery found the region advertised in meta
    s = HbaseStore(port=server.port)
    assert s._region == server.region
    with pytest.raises(HBaseError, match="TableNotFound"):
        HbaseStore(port=server.port, table="nope")


def test_wrong_auth_dropped():
    srv = MiniHBase(require_auth=0x51)  # not SIMPLE: kerberos-only server
    try:
        with pytest.raises((ConnectionError, OSError)):
            HbaseStore(port=srv.port)
    finally:
        srv.stop()


def test_reconnect_after_server_restart(server):
    store = HbaseStore(port=server.port)
    store.insert_entry(_file("/r/a"))
    # simulate a regionserver bounce on the SAME port with state kept
    rows = server.rows
    port = server.port
    server.stop()
    srv2 = MiniHBase()
    # rebind the old port (race-free: the old listener is fully closed)
    srv2._srv.close()
    srv2._srv = __import__("socket").socket()
    srv2._srv.setsockopt(__import__("socket").SOL_SOCKET,
                         __import__("socket").SO_REUSEADDR, 1)
    srv2._srv.bind(("127.0.0.1", port))
    srv2._srv.listen(16)
    srv2.port = port
    import threading as _t
    _t.Thread(target=srv2._accept, daemon=True).start()
    srv2.rows = rows
    try:
        assert store.find_entry("/r/a") is not None  # transparent reconnect
        store.insert_entry(_file("/r/b"))
        assert store.find_entry("/r/b") is not None
    finally:
        srv2.stop()


def test_scan_survives_scanner_loss_without_truncation(server, store):
    """A scanner that dies between pages (regionserver bounce) must be
    REOPENED after the last yielded row — not silently truncate the
    scan (the double faults unknown continuations like real HBase)."""
    for i in range(40):
        store.insert_entry(_file(f"/sv/f{i:03d}"))
    rows = []
    it = store._scan(b"meta", b"/sv/", batch=10)
    for _ in range(10):  # consume the first page
        rows.append(next(it)[0])
    server._scanners.clear()  # the server "restarted": scanners gone
    rows.extend(r for r, _ in it)  # continuation must reopen + resume
    assert rows == [f"/sv/f{i:03d}".encode() for i in range(40)]


def test_ttl_entries_carry_the_ttl_attribute(store):
    """A TTL'd entry must send the gohbase-style _ttl mutation
    attribute (ms, 8-byte BE) — ref doPut's hrpc.TTL option."""
    import struct as _struct

    sent = []
    orig = store.client.call

    def spy(method, param):
        sent.append((method, param))
        return orig(method, param)

    store.client.call = spy
    store.insert_entry(Entry(full_path="/ttl/x",
                             attr=Attr(mode=0o644, ttl_seconds=3600)))
    mutates = [p for m, p in sent if m == "Mutate"]
    assert mutates and _struct.pack(">q", 3600 * 1000) in mutates[-1]
    # and a non-TTL entry must NOT carry it
    sent.clear()
    store.insert_entry(_file("/ttl/plain"))
    mutates = [p for m, p in sent if m == "Mutate"]
    assert mutates and b"_ttl" not in mutates[-1]


def test_filer_end_to_end(store):
    f = Filer(store=store)
    f.create_entry(_file("/docs/readme.md", 2))
    assert f.find_entry("/docs/readme.md").chunks[1].offset == 10
    assert [e.name for e in f.list_directory("/docs")] == ["readme.md"]
    f.delete_entry("/docs", recursive=True)


def test_url_parsing(server):
    s = HbaseStore.from_url(f"hbase://127.0.0.1:{server.port}/seaweedfs")
    s.insert_entry(_file("/u/x"))
    assert s.find_entry("/u/x") is not None
