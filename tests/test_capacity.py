"""SLO capacity probe (scenarios/capacity.py) — tier-1.

Gates: one open-loop measurement actually delivers its scheduled rate
(and reports lag when it cannot), the ramp + binary search brackets a
synthetic service's KNOWN capacity, the knee names the bound that
broke, error-bound breaches are their own knee reason, and the
rendered view is stable.  The live cluster probe is exercised by the
bench `capacity` section; here a deterministic lock-bound fake keeps
the tier-1 clock honest.
"""

from __future__ import annotations

import threading
import time

import pytest

from seaweedfs_tpu.scenarios.capacity import (
    CapacitySLO,
    find_capacity,
    measure_rate,
    render_capacity,
)


def lock_bound_service(capacity_rps: float):
    """A service that can do exactly capacity_rps ops/s: each op holds
    one lock for 1/C seconds — beyond C the convoy grows and p99 /
    schedule lag blow up, exactly like a saturated single-threaded
    server."""
    lock = threading.Lock()
    hold = 1.0 / capacity_rps

    def op() -> bool:
        with lock:
            time.sleep(hold)
        return True

    return op


class TestMeasureRate:
    def test_open_loop_hits_target_when_service_is_fast(self):
        step = measure_rate(lambda: True, rps=400, duration_s=1.0)
        assert step["achieved_rps"] >= 0.92 * 400
        assert step["errors"] == 0
        assert step["error_ratio"] == 0.0
        assert step["ops"] == 400

    def test_saturation_shows_as_lag_not_a_slower_schedule(self):
        # a 100 rps service offered 800 rps: open-loop means the
        # schedule does NOT stretch — achieved collapses toward the
        # service rate and lag grows
        step = measure_rate(lock_bound_service(100.0), rps=800,
                            duration_s=1.0)
        assert step["achieved_rps"] < 0.5 * 800
        assert step["max_lag_ms"] > 100.0

    def test_errors_counted(self):
        calls = [0]

        def op() -> bool:
            calls[0] += 1
            return calls[0] % 2 == 0

        step = measure_rate(op, rps=200, duration_s=0.5)
        assert step["error_ratio"] == pytest.approx(0.5, abs=0.1)

    def test_exceptions_count_as_errors(self):
        def op() -> bool:
            raise OSError("wire gone")

        step = measure_rate(op, rps=100, duration_s=0.3)
        assert step["error_ratio"] == 1.0


class TestFindCapacity:
    def test_brackets_known_capacity_and_names_the_knee(self):
        C = 400.0
        res = find_capacity(lock_bound_service(C),
                            CapacitySLO(max_p99_ms=40.0),
                            start_rps=50, max_rps=4000, step_s=0.7,
                            search_steps=3)
        assert res["knee_rps"] is not None
        assert res["knee"]["reason"]
        # capacity within the honest band: above half the service
        # rate (the convoy starts biting before C) and never above it
        assert 0.3 * C <= res["capacity_rps"] <= 1.15 * C
        # the curve is on the document
        assert len(res["samples"]) >= 3
        assert res["samples"][0]["sustainable"] is True

    def test_error_bound_is_its_own_knee_reason(self):
        # the fake starts failing once the offered rate passes 400:
        # a deterministic error-bound knee with latency always fine
        calls = {"rate": 0.0}
        orig = measure_rate

        def op2() -> bool:
            return calls["rate"] <= 400

        def patched(op_fn, rps, duration_s, workers=0):
            calls["rate"] = rps
            return orig(op_fn, rps, duration_s, workers)

        import seaweedfs_tpu.scenarios.capacity as cap_mod

        cap_mod_measure = cap_mod.measure_rate
        cap_mod.measure_rate = patched
        try:
            res = cap_mod.find_capacity(
                op2, CapacitySLO(), start_rps=100, max_rps=3200,
                step_s=0.2, search_steps=2)
        finally:
            cap_mod.measure_rate = cap_mod_measure
        assert res["knee"] is not None
        assert "error_ratio" in res["knee"]["reason"]
        assert res["capacity_rps"] > 0

    def test_searches_below_a_breaching_start_rps(self):
        # a ~40rps service probed with start_rps=200 must report its
        # real capacity, not 0.0 — the parked/bench baseline would
        # otherwise anchor every future comparison to a bogus zero
        C = 40.0
        res = find_capacity(lock_bound_service(C),
                            CapacitySLO(max_p99_ms=60.0),
                            start_rps=200, max_rps=800, step_s=0.6,
                            search_steps=2)
        assert res["capacity_rps"] > 0.0
        assert 0.3 * C <= res["capacity_rps"] <= 1.2 * C
        assert res["knee"] is not None

    def test_no_knee_when_cap_never_breaks(self):
        res = find_capacity(lambda: True,
                            CapacitySLO(max_p99_ms=1000.0),
                            start_rps=100, max_rps=400, step_s=0.3)
        assert res["knee"] is None and res["knee_rps"] is None
        assert res["capacity_rps"] >= 0.9 * 400


class TestRender:
    def test_render_one_line_per_route(self):
        doc = {"slo": {"max_p99_ms": 5.0, "max_error_ratio": 0.001},
               "routes": {
                   "http_read": {"capacity_rps": 4200.0,
                                 "capacity_p99_ms": 3.1,
                                 "knee_rps": 4800.0,
                                 "knee": {"reason": "p99 7.0ms > 5ms"},
                                 "bounding": {"resource": "server",
                                              "bounding_hop":
                                                  "volume 127.0.0.1"}},
                   "native_read": {"capacity_rps": 21000.0,
                                   "capacity_p99_ms": 1.0,
                                   "knee_rps": None, "knee": None,
                                   "bounding": {"resource": "unknown"}},
                   "broken": {"error": "unknown route"}}}
        out = render_capacity(doc)
        assert "http_read" in out and "capacity=4200 rps" in out
        assert "knee@4800rps" in out and "bound=server" in out
        assert "no knee found" in out
        assert "error: unknown route" in out

    def test_slo_dataclass_dict(self):
        assert CapacitySLO().to_dict() == {"max_p99_ms": 5.0,
                                           "max_error_ratio": 0.001}


class TestShellSurface:
    def test_workload_and_capacity_commands_registered(self):
        from seaweedfs_tpu.shell import COMMANDS

        for name in ("workload.record", "workload.stop",
                     "workload.export", "workload.replay",
                     "capacity.probe"):
            assert name in COMMANDS, name

    def test_workload_record_fanout_includes_filer(self):
        # filers are absent from /dir/status topology: a fan-out built
        # from it alone would silently omit the whole filer workload
        from seaweedfs_tpu.shell import CommandEnv
        from seaweedfs_tpu.shell.workload_commands import _all_servers

        env = CommandEnv("m:1", filer_url="f:2")
        env.topology = lambda: {"DataCenters": [
            {"Racks": [{"DataNodes": [{"Url": "v:3"}]}]}]}
        assert _all_servers(env) == ["m:1", "v:3", "f:2"]

    def test_capacity_probe_requires_admin_lock(self):
        # the probe drives a live cluster to its knee and writes load
        # objects: it must refuse without the exclusive lock, before
        # touching any server
        from seaweedfs_tpu.shell import CommandEnv, run_command

        env = CommandEnv("127.0.0.1:1")  # never contacted
        with pytest.raises(RuntimeError, match="lock is needed"):
            run_command(env, "capacity.probe")
