"""Adversarial / failure-mode transcripts for the wire-protocol stores.

Every wire store gets spec-derived FAILURE drills beyond CRUD: the happy
paths are covered by each store's own suite; these pin down what the
clients do when the server misbehaves — auth-layer tampering (SCRAM
impersonation/MITM shapes), topology churn (region splits, leader loss),
resource pressure (429/Overloaded), and protocol desync (wrong stream,
unrequested exhaust streams).  Reference counterparts ride real client
libraries that handle these; a hand-rolled wire client earns trust only
by demonstrating the same behavior.

ref: weed/filer/redis_cluster/redis_cluster_store.go:1 (the family whose
MOVED/ASK drills live in test_redis_cluster.py), weed/filer/hbase/
hbase_store.go:1, weed/filer/mongodb/mongodb_store.go:1.
"""

from __future__ import annotations

import pytest

from seaweedfs_tpu.filer.entry import Attr, Entry


def _file(path: str, n: int = 1) -> Entry:
    return Entry(full_path=path, attr=Attr(crtime=n, mtime=n, mode=0o644))


# --- postgres: SCRAM adversary drills (RFC 5802 §9) -------------------------

def test_pg_scram_rejects_forged_server_signature():
    """An impersonator that doesn't know the password can run the whole
    SCRAM flow but cannot compute ServerSignature — the client MUST
    verify v= and refuse the session (server-authentication half of
    SCRAM; losing it reduces SCRAM to client-only auth)."""
    from seaweedfs_tpu.filer.pg_client import PgConn, PgError
    from tests.minipg import MiniPg

    srv = MiniPg(password="sekret", auth="scram", tamper="server_sig")
    try:
        with pytest.raises(PgError, match="server signature"):
            PgConn("127.0.0.1", srv.port, password="sekret")
    finally:
        srv.stop()


def test_pg_scram_rejects_nonce_substitution():
    """The server's nonce must EXTEND the client's (RFC 5802 §5.1 r=);
    a fresh nonce is the MITM-replay shape and must abort the exchange."""
    from seaweedfs_tpu.filer.pg_client import PgConn, PgError
    from tests.minipg import MiniPg

    srv = MiniPg(password="sekret", auth="scram", tamper="nonce")
    try:
        with pytest.raises(PgError, match="nonce"):
            PgConn("127.0.0.1", srv.port, password="sekret")
    finally:
        srv.stop()


# --- mongo: OP_MSG failure drills -------------------------------------------

def test_mongo_scram_rejects_forged_server_signature():
    from seaweedfs_tpu.filer.mongo_store import MongoError, MongoStore
    from tests.minimongo import MiniMongo

    srv = MiniMongo(username="u", password="pw", tamper="server_sig")
    try:
        with pytest.raises((MongoError, OSError),
                           match="signature|server"):
            # auth runs at connect: the forged v= must abort the session
            MongoStore.from_url(f"mongodb://u:pw@127.0.0.1:{srv.port}")
    finally:
        srv.stop()


def test_mongo_cursor_death_mid_listing_raises_not_truncates():
    """A cursor that dies between getMore pages (timeout, failover on a
    real mongod) answers CursorNotFound (code 43).  The listing must
    RAISE — returning the partial page as if complete is the
    silent-data-loss shape (a caller deleting 'everything listed' would
    miss entries)."""
    from seaweedfs_tpu.filer.mongo_store import MongoError, MongoStore
    from tests.minimongo import MiniMongo

    srv = MiniMongo()
    try:
        store = MongoStore.from_url(f"mongodb://127.0.0.1:{srv.port}")
        for i in range(10):  # > batch_cap: forces the getMore path
            store.insert_entry(_file(f"/dir/f{i:02}.txt", i + 1))
        srv.kill_cursors = True
        with pytest.raises((MongoError, OSError), match="[Cc]ursor"):
            list(store.list_directory_entries("/dir", "", True, 100))
    finally:
        srv.stop()


def test_mongo_drains_unrequested_more_to_come_stream():
    """This client never sets exhaustAllowed, but a nonconforming server
    that streams a moreToCome (0x2) prelude must not desync the pooled
    connection: the client drains to the final reply and later commands
    still work."""
    from seaweedfs_tpu.filer.mongo_store import MongoStore
    from tests.minimongo import MiniMongo

    srv = MiniMongo()
    try:
        store = MongoStore.from_url(f"mongodb://127.0.0.1:{srv.port}")
        store.insert_entry(_file("/x.txt"))
        srv.exhaust_once = True
        assert store.find_entry("/x.txt") is not None
        # the connection survived: a second command parses cleanly
        assert store.find_entry("/x.txt") is not None
        assert store.find_entry("/missing") is None
    finally:
        srv.stop()


# --- cassandra: CQL error frames + stream integrity -------------------------

def test_cassandra_overloaded_error_surfaces():
    """ERROR 0x1001 (Overloaded) mid-CRUD must raise CqlError with the
    server's message — not retry forever, not silently drop the write."""
    from seaweedfs_tpu.filer.cassandra_store import CassandraStore, CqlError
    from tests.minicassandra import MiniCassandra

    srv = MiniCassandra()
    try:
        store = CassandraStore.from_url(f"cassandra://127.0.0.1:{srv.port}")
        store.insert_entry(_file("/ok.txt"))
        srv.fail_next.append(("error", 0x1001, "pool is overloaded"))
        with pytest.raises(CqlError, match="overloaded"):
            store.insert_entry(_file("/fails.txt"))
        # transient: the connection still serves the next statement
        store.insert_entry(_file("/after.txt"))
        assert store.find_entry("/after.txt") is not None
    finally:
        srv.stop()


def test_cassandra_wrong_stream_id_detected():
    """A RESULT on the wrong stream id means crossed frames (proxy bug,
    desync): the client must refuse the payload and drop the connection
    rather than hand back someone else's rows."""
    from seaweedfs_tpu.filer.cassandra_store import CassandraStore, CqlError
    from tests.minicassandra import MiniCassandra

    srv = MiniCassandra()
    try:
        store = CassandraStore.from_url(f"cassandra://127.0.0.1:{srv.port}")
        store.insert_entry(_file("/ok.txt"))
        srv.fail_next.append(("stream", 7))
        with pytest.raises(CqlError, match="stream"):
            store.find_entry("/ok.txt")
        # the poisoned connection was dropped; a fresh one reconnects
        assert store.find_entry("/ok.txt") is not None
    finally:
        srv.stop()


# --- etcd: leader loss + compaction -----------------------------------------

def test_etcd_leader_loss_retries_once():
    """503 during a leader election is the canonical transient
    (etcdserver: no leader); one bounded retry rides it out like
    clientv3's unavailable retry policy."""
    from seaweedfs_tpu.filer.etcd_store import EtcdStore
    from tests.minietcd import MiniEtcd

    srv = MiniEtcd()
    try:
        store = EtcdStore(f"127.0.0.1:{srv.port}")
        store.insert_entry(_file("/a.txt"))
        srv.fail_next.append((503, {"error": "etcdserver: no leader",
                                    "code": 14}))
        assert store.find_entry("/a.txt") is not None  # retried through
    finally:
        srv.stop()


def test_etcd_persistent_error_raises():
    """A non-transient error (compacted revision, 400) must surface,
    and two consecutive 503s exhaust the single retry."""
    from seaweedfs_tpu.filer.etcd_store import EtcdStore
    from seaweedfs_tpu.utils.httpd import HttpError
    from tests.minietcd import MiniEtcd

    srv = MiniEtcd()
    try:
        store = EtcdStore(f"127.0.0.1:{srv.port}")
        srv.fail_next.append(
            (400, {"error": "etcdserver: mvcc: required revision has "
                            "been compacted", "code": 11}))
        with pytest.raises(HttpError, match="compacted"):
            store.find_entry("/a.txt")
        srv.fail_next.extend([(503, {"error": "no leader"})] * 2)
        with pytest.raises(HttpError):
            store.find_entry("/a.txt")
    finally:
        srv.stop()


# --- elastic: backpressure + red cluster ------------------------------------

def test_elastic_429_backpressure_retried_once():
    from seaweedfs_tpu.filer.elastic_store import ElasticStore
    from tests.minielastic import MiniElastic

    srv = MiniElastic()
    try:
        store = ElasticStore(f"http://127.0.0.1:{srv.port}")
        srv.fail_next.append(429)  # es_rejected_execution, then serves
        store.insert_entry(_file("/a.txt"))
        assert store.find_entry("/a.txt") is not None
    finally:
        srv.stop()


def test_elastic_red_cluster_search_raises_not_empty():
    """A 503 on _search must raise — answering an empty listing turns a
    flaky cluster into silent data loss (callers treat empty as
    deletable)."""
    from seaweedfs_tpu.filer.elastic_store import ElasticStore
    from tests.minielastic import MiniElastic

    srv = MiniElastic()
    try:
        store = ElasticStore(f"http://127.0.0.1:{srv.port}")
        store.insert_entry(_file("/dir/a.txt"))
        srv.fail_next.append(503)
        with pytest.raises(OSError, match="503|search"):
            list(store.list_directory_entries("/dir", "", True, 10))
        assert store.find_entry("/dir/a.txt") is not None  # recovered
        # a 5xx on a point GET must raise too, not report "absent"
        srv.fail_next.append(503)
        with pytest.raises(OSError, match="503"):
            store.find_entry("/dir/a.txt")
    finally:
        srv.stop()


# --- hbase: region split ----------------------------------------------------

def test_hbase_region_split_point_ops_relocate():
    """A region split answers NotServingRegionException for the old
    region name; the client must re-scan hbase:meta and retry with the
    new region — the standard region-cache invalidation."""
    from seaweedfs_tpu.filer.hbase_store import HbaseStore
    from tests.minihbase import MiniHBase

    srv = MiniHBase()
    try:
        store = HbaseStore(port=srv.port)
        store.insert_entry(_file("/a.txt"))
        srv.split_region()
        store.insert_entry(_file("/b.txt"))        # put relocates
        assert store.find_entry("/a.txt") is not None   # get relocates
        srv.split_region()
        store.delete_entry("/b.txt")               # delete relocates
        assert store.find_entry("/b.txt") is None
    finally:
        srv.stop()


def test_hbase_region_split_mid_scan_resumes_without_truncation():
    """The split lands BETWEEN scan pages: the continuation call names
    the dead region, and the scan must relocate + resume after the last
    yielded row — every row exactly once, no silent truncation."""
    from seaweedfs_tpu.filer.hbase_store import HbaseStore
    from tests.minihbase import MiniHBase

    srv = MiniHBase()
    try:
        store = HbaseStore(port=srv.port)
        names = [f"f{i:03}.txt" for i in range(30)]
        for i, nm in enumerate(names):
            store.insert_entry(_file(f"/dir/{nm}", i + 1))
        it = iter(store.list_directory_entries("/dir", "", True, 100))
        got = [next(it).name for _ in range(5)]
        srv.split_region()  # split mid-scan
        got += [e.name for e in it]
        assert got == names
    finally:
        srv.stop()
