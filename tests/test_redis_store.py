"""Redis-protocol filer store against an in-process RESP server.

Gates:
- RedisStore is observably identical to MemoryStore under randomized ops
  (same differential harness the LSM store passes)
- listing pagination, prefix filtering, and resume markers work over
  ZRANGEBYLEX
- kv family round-trips with byte-prefix scans via the hex index
- AUTH and redis:// URL parsing
- a Filer runs end-to-end on top of it
"""

from __future__ import annotations

import numpy as np
import pytest

from seaweedfs_tpu.filer.entry import Attr, Entry, FileChunk
from seaweedfs_tpu.filer.filer import Filer, NotFoundError
from seaweedfs_tpu.filer.filer_store import MemoryStore
from seaweedfs_tpu.filer.redis_store import RedisStore, RespError

from .miniredis import MiniRedis

RNG = np.random.default_rng(0xED15)


@pytest.fixture()
def server():
    s = MiniRedis()
    yield s
    s.stop()


@pytest.fixture(params=["plain", "lua"])
def store(server, request):
    # every behavioral gate runs against BOTH variants: the pipeline
    # store and the Lua stored-procedure store share one data model
    if request.param == "lua":
        from seaweedfs_tpu.filer.redis_lua_store import RedisLuaStore

        return RedisLuaStore(port=server.port)
    return RedisStore(port=server.port)


def _file(path: str, n: int = 1) -> Entry:
    chunks = [FileChunk(file_id=f"3,{i:02x}", offset=i * 10, size=10)
              for i in range(n)]
    return Entry(full_path=path, attr=Attr(mode=0o660), chunks=chunks)


def test_crud_and_listing(store):
    store.insert_entry(_file("/d/a.txt"))
    store.insert_entry(_file("/d/b.txt", 3))
    store.insert_entry(_file("/d/c.txt"))
    got = store.find_entry("/d/b.txt")
    assert got is not None and len(got.chunks) == 3
    assert [e.full_path for e in store.list_directory_entries("/d")] == [
        "/d/a.txt", "/d/b.txt", "/d/c.txt"]
    # resume after a.txt, exclusive
    assert [e.full_path for e in store.list_directory_entries(
        "/d", start_file="a.txt")] == ["/d/b.txt", "/d/c.txt"]
    # inclusive resume + limit
    assert [e.full_path for e in store.list_directory_entries(
        "/d", start_file="b.txt", include_start=True, limit=1)] == ["/d/b.txt"]
    store.delete_entry("/d/b.txt")
    assert store.find_entry("/d/b.txt") is None
    assert [e.full_path for e in store.list_directory_entries("/d")] == [
        "/d/a.txt", "/d/c.txt"]


def test_prefix_listing(store):
    for name in ("apple", "apricot", "banana", "cherry"):
        store.insert_entry(_file(f"/fruit/{name}"))
    assert [e.full_path for e in store.list_directory_entries(
        "/fruit", prefix="ap")] == ["/fruit/apple", "/fruit/apricot"]
    assert [e.full_path for e in store.list_directory_entries(
        "/fruit", prefix="z")] == []


def test_prefix_with_low_start_file_fills_page(store):
    """start_file below the prefix range must not under-fill the page:
    LIMIT is applied server-side, so the lower bound has to be the
    tighter of (start_file, prefix)."""
    for name in ("aa", "ab", "ba", "bb"):
        store.insert_entry(_file(f"/p/{name}"))
    got = [e.full_path for e in store.list_directory_entries(
        "/p", start_file="aa", prefix="b", limit=2)]
    assert got == ["/p/ba", "/p/bb"]
    # and a resume inside the prefix range still respects start_file
    got = [e.full_path for e in store.list_directory_entries(
        "/p", start_file="ba", prefix="b", limit=2)]
    assert got == ["/p/bb"]


def test_delete_folder_children_recursive(store):
    for p in ("/t/x", "/t/sub/y", "/t/sub/deep/z", "/other/keep"):
        store.insert_entry(_file(p))
    store.delete_folder_children("/t")
    for p in ("/t/x", "/t/sub/y", "/t/sub/deep/z"):
        assert store.find_entry(p) is None
    assert store.find_entry("/other/keep") is not None
    assert list(store.list_directory_entries("/t")) == []


def test_kv_roundtrip_and_prefix_scan(store):
    store.kv_put(b"sig/alpha", b"1")
    store.kv_put(b"sig/beta", b"2")
    store.kv_put(b"other", b"3")
    assert store.kv_get(b"sig/alpha") == b"1"
    assert store.kv_get(b"missing") is None
    got = dict(store.kv_scan(b"sig/"))
    assert got == {b"sig/alpha": b"1", b"sig/beta": b"2"}
    assert len(dict(store.kv_scan(b""))) == 3
    store.kv_delete(b"sig/alpha")
    assert store.kv_get(b"sig/alpha") is None
    assert dict(store.kv_scan(b"sig/")) == {b"sig/beta": b"2"}


def test_matches_memory_randomized(store):
    """Differential: RedisStore behaves like MemoryStore (same harness the
    LSM store passes)."""
    mem = MemoryStore()
    dirs = ["/a", "/a/b", "/c"]
    names = [f"f{i:02d}" for i in range(12)]
    for _ in range(400):
        op = RNG.integers(0, 4)
        d = dirs[RNG.integers(0, len(dirs))]
        n = names[RNG.integers(0, len(names))]
        path = f"{d}/{n}"
        if op == 0:
            e = _file(path, int(RNG.integers(1, 4)))
            mem.insert_entry(e)
            store.insert_entry(e)
        elif op == 1:
            mem.delete_entry(path)
            store.delete_entry(path)
        elif op == 2:
            a, b = mem.find_entry(path), store.find_entry(path)
            assert (a is None) == (b is None)
            if a is not None:
                assert a.to_dict() == b.to_dict()
        else:
            la = [e.full_path for e in mem.list_directory_entries(d)]
            lb = [e.full_path for e in store.list_directory_entries(d)]
            assert la == lb


def test_auth_and_url_parse():
    s = MiniRedis(password="hunter2")
    try:
        with pytest.raises(RespError):
            RedisStore(port=s.port)  # no password
        st = RedisStore.from_url(f"redis://:hunter2@127.0.0.1:{s.port}/0")
        st.insert_entry(_file("/x"))
        assert st.find_entry("/x") is not None
    finally:
        s.stop()
    conf = RedisStore.from_url
    # pure-parse checks (no connection): inspect parsed fields via a failure
    with pytest.raises(OSError):
        conf("redis://127.0.0.1:1/3")  # nothing listens on port 1


def test_filer_on_redis(server, store):
    deleted: list[str] = []
    f = Filer(store=store, delete_chunks_fn=deleted.extend)
    f.mkdir("/docs")
    f.create_entry(_file("/docs/readme.md", 2))
    assert [c.file_id for c in f.find_entry("/docs/readme.md").chunks] == [
        "3,00", "3,01"]
    # hardlink wrapper rides on top of any store, including this one
    f.hardlink("/docs/readme.md", "/docs/link.md")
    assert [c.file_id for c in f.find_entry("/docs/link.md").chunks] == [
        "3,00", "3,01"]
    f.delete_entry("/docs/readme.md")
    f.flush_gc()
    assert deleted == []  # still linked
    f.delete_entry("/docs/link.md")
    f.flush_gc()
    assert sorted(deleted) == ["3,00", "3,01"]
    with pytest.raises(NotFoundError):
        f.find_entry("/docs/readme.md")
    f.close()


def test_lua_store_scripts_registered_and_noscript_fallback(server):
    from seaweedfs_tpu.filer.redis_lua_store import RedisLuaStore

    store = RedisLuaStore(port=server.port)
    assert len(server.scripts) == 3  # preloaded via SCRIPT LOAD
    # a restarted server loses its script cache: EVALSHA answers
    # NOSCRIPT, the store falls back to EVAL which re-caches
    server.scripts.clear()
    store.insert_entry(_file("/lua/a.txt"))
    assert server.scripts  # EVAL re-registered the script
    got = store.find_entry("/lua/a.txt")
    assert got is not None and got.full_path == "/lua/a.txt"
    # the listing membership landed atomically with the entry key
    assert [e.full_path for e in
            store.list_directory_entries("/lua")] == ["/lua/a.txt"]
    store.delete_entry("/lua/a.txt")
    assert store.find_entry("/lua/a.txt") is None
    assert list(store.list_directory_entries("/lua")) == []


def test_lua_store_from_url_and_plain_interop(server):
    from seaweedfs_tpu.filer.redis_lua_store import RedisLuaStore

    lua = RedisLuaStore.from_url(f"redis-lua://127.0.0.1:{server.port}/0")
    lua.insert_entry(_file("/shared/x.bin", n=2))
    # identical key model: the plain store reads what the lua store wrote
    plain = RedisStore(port=server.port)
    assert plain.find_entry("/shared/x.bin").chunks[0].file_id == "3,00"
    plain.delete_entry("/shared/x.bin")
    assert lua.find_entry("/shared/x.bin") is None


@pytest.mark.parametrize("variant", ["plain", "lua"])
def test_super_large_directories(server, variant):
    """superLargeDirectories (universal_redis_store.go:25-27,64,117,132):
    configured dirs keep no listing zset — O(1) inserts, empty listings,
    full-path lookups still work."""
    if variant == "lua":
        from seaweedfs_tpu.filer.redis_lua_store import RedisLuaStore as S
    else:
        S = RedisStore
    store = S.from_url(
        f"redis://127.0.0.1:{server.port}/0?superLargeDirs=/huge,/logs")
    assert store.super_large_dirs == {"/huge", "/logs"}
    store.insert_entry(_file("/huge/a.bin"))
    store.insert_entry(_file("/normal/b.bin"))
    # full-path lookup works; the huge dir has NO listing
    assert store.find_entry("/huge/a.bin") is not None
    assert list(store.list_directory_entries("/huge")) == []
    assert [e.full_path for e in
            store.list_directory_entries("/normal")] == ["/normal/b.bin"]
    # no zset was ever created for the huge dir
    assert server.zsets.get(b"d:/huge") in (None, set())
    # delete: entry gone, no stray ZREM bookkeeping needed
    store.delete_entry("/huge/a.bin")
    assert store.find_entry("/huge/a.bin") is None
    # recursive delete of a super-large dir is a no-op by design
    store.insert_entry(_file("/huge/keep.bin"))
    store.delete_folder_children("/huge")
    assert store.find_entry("/huge/keep.bin") is not None


def test_url_password_with_question_mark():
    s = MiniRedis(password="pa?ss")
    try:
        st = RedisStore.from_url(f"redis://:pa?ss@127.0.0.1:{s.port}/0")
        st.insert_entry(_file("/q"))
        assert st.find_entry("/q") is not None
        # and a query AFTER credentials still parses
        st2 = RedisStore.from_url(
            f"redis://:pa?ss@127.0.0.1:{s.port}/0?superLargeDirs=/big")
        assert st2.super_large_dirs == {"/big"}
    finally:
        s.stop()
