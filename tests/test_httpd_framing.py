"""HTTP/1.1 framing edge cases on the hand-rolled FastHTTPServer.

These pin the keep-alive desync class the advisor flagged: an unread
request body left in the connection's read buffer gets parsed as the
NEXT request line (request-smuggling-shaped).  The reference serves the
same hot path from Go net/http, which frames these cases for free
(ref: weed/server/volume_server_handlers_read.go:30).
"""

import socket
import threading

from seaweedfs_tpu.utils.httpd import Response, Router, serve


def _start():
    r = Router()

    @r.route("GET", "/ping")
    def ping(req):
        return Response({"ok": True})

    @r.route("POST", "/echo")
    def echo(req):
        return Response(raw=req.body)

    srv = serve(r, "127.0.0.1", 0)
    t = threading.Thread(target=srv.serve_forever, daemon=True)
    t.start()
    return srv, srv.server_address[1]


def _recv_response(sock):
    """Read exactly one HTTP response (status+headers+Content-Length body)."""
    buf = b""
    while b"\r\n\r\n" not in buf:
        piece = sock.recv(4096)
        if not piece:
            return buf, b""
        buf += piece
    head, _, rest = buf.partition(b"\r\n\r\n")
    clen = 0
    for line in head.split(b"\r\n")[1:]:
        k, _, v = line.partition(b":")
        if k.strip().lower() == b"content-length":
            clen = int(v.strip())
    while len(rest) < clen:
        piece = sock.recv(4096)
        if not piece:
            break
        rest += piece
    return head, rest[:clen]


def test_404_with_body_does_not_desync_keepalive():
    srv, port = _start()
    try:
        with socket.create_connection(("127.0.0.1", port), timeout=5) as s:
            body = b"x" * 5000
            s.sendall(b"POST /no/such/route HTTP/1.1\r\n"
                      b"Host: h\r\nContent-Length: %d\r\n\r\n" % len(body))
            s.sendall(body)
            head, _ = _recv_response(s)
            assert b" 404 " in head.split(b"\r\n")[0]
            # the SAME connection must now serve a clean second request —
            # if the body was left unread it would be parsed as a request
            # line and this would hang or error
            s.sendall(b"GET /ping HTTP/1.1\r\nHost: h\r\n\r\n")
            head2, body2 = _recv_response(s)
            assert b" 200 " in head2.split(b"\r\n")[0]
            assert b"true" in body2
    finally:
        srv.shutdown()


def test_chunked_request_refused_and_closed():
    srv, port = _start()
    try:
        with socket.create_connection(("127.0.0.1", port), timeout=5) as s:
            s.sendall(b"POST /echo HTTP/1.1\r\nHost: h\r\n"
                      b"Transfer-Encoding: chunked\r\n\r\n"
                      b"5\r\nhello\r\n0\r\n\r\n")
            head, _ = _recv_response(s)
            assert b" 501 " in head.split(b"\r\n")[0]
            assert b"Connection: close" in head
            # server must close rather than mis-frame the chunked body
            s.settimeout(5)
            assert s.recv(1) == b""
    finally:
        srv.shutdown()


def test_oversize_request_line_gets_414():
    srv, port = _start()
    try:
        with socket.create_connection(("127.0.0.1", port), timeout=5) as s:
            s.sendall(b"GET /" + b"a" * (1 << 17) + b" HTTP/1.1\r\n")
            head, _ = _recv_response(s)
            assert b" 414 " in head.split(b"\r\n")[0]
    finally:
        srv.shutdown()


def test_matched_route_keepalive_still_works():
    srv, port = _start()
    try:
        with socket.create_connection(("127.0.0.1", port), timeout=5) as s:
            for payload in (b"one", b"two"):
                s.sendall(b"POST /echo HTTP/1.1\r\nHost: h\r\n"
                          b"Content-Length: %d\r\n\r\n" % len(payload) + payload)
                head, body = _recv_response(s)
                assert b" 200 " in head.split(b"\r\n")[0]
                assert body == payload
    finally:
        srv.shutdown()
