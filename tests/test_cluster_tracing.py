"""Cluster-wide distributed tracing, live (PR 6 tentpole).

Real servers in one process: trace contexts minted at ingress, carried
on every outbound hop, spans shipped to the master's collector, and the
stitched trace served at GET /cluster/traces/<id> with cross-server
analysis.  The contracts pinned here:

  - master -> volume and gateway -> filer -> volume fan-outs each
    produce ONE rooted tree (every span reachable from a single root
    via parent edges that crossed process/server boundaries in the
    Traceparent header);
  - a malformed Traceparent mints a fresh decision — never a 500;
  - an upstream decided-not-sampled header suppresses recording;
  - /debug/traces?trace_id= and ?root= pull one request's tree without
    the whole ring;
  - `weed shell ec.scrub -all` starts+polls scrubs on every registered
    server and rolls verdicts up (PR 5's per-server leftover);
  - drop accounting is surfaced on every analysis surface.
"""

from __future__ import annotations

import json
import time

import pytest

from seaweedfs_tpu.master.server import MasterServer
from seaweedfs_tpu.observability import context as tc
from seaweedfs_tpu.observability import disable_tracing, enable_tracing
from seaweedfs_tpu.utils.httpd import http_bytes, http_json
from seaweedfs_tpu.volume_server.server import VolumeServer

from tests.conftest import free_port

FORCE = {tc.FORCE_HEADER: "1"}


@pytest.fixture
def traced():
    """Process-global tracing ON with rate 0 — only forced or propagated
    decisions record, so concurrent background work stays off the ring.
    Always restored: other tests assume the tracer is off."""
    tracer = enable_tracing()
    tracer.clear()
    tc.set_sample_rate(0.0)
    yield tracer
    disable_tracing()
    tc.set_sample_rate(1.0)
    tracer.clear()


def _zero_degrade_counters():
    """In-process fixture servers expose the TEST PROCESS's global
    metrics registry, so degrade counters incremented by earlier suite
    tests (pipeline chaos, scrub drills) would flip every stitched
    trace's verdict to DEGRADED here.  Zero the health families the
    analyzer folds in — label keys stay registered at 0 so exposition
    shape is unchanged."""
    from seaweedfs_tpu.stats import REGISTRY, Counter
    from seaweedfs_tpu.stats.aggregate import HEALTH_FAMILIES

    families = set(HEALTH_FAMILIES.values())
    with REGISTRY._lock:
        collectors = list(REGISTRY._collectors)
    for c in collectors:
        if isinstance(c, Counter) and c.name in families:
            with c._lock:
                for key in c._values:
                    c._values[key] = 0.0


@pytest.fixture
def cluster(traced, tmp_path):
    _zero_degrade_counters()
    master = MasterServer(port=free_port(), pulse_seconds=0.4).start()
    master.aggregator.min_interval = 0.0
    servers = []
    for i in range(2):
        d = tmp_path / f"vs{i}"
        d.mkdir()
        servers.append(VolumeServer(
            [str(d)], master.url, port=free_port(),
            pulse_seconds=0.4).start())
    deadline = time.time() + 5
    while time.time() < deadline and len(master.topo.all_nodes()) < 2:
        time.sleep(0.05)
    assert len(master.topo.all_nodes()) == 2
    yield master, servers
    for vs in servers:
        vs.stop()
    master.stop()


def _fetch_trace(master, trace_id, want=None, timeout=8.0):
    """Poll the collector until the stitched trace satisfies `want`
    (shippers flush on a short interval)."""
    deadline = time.time() + timeout
    last = None
    while time.time() < deadline:
        status, body, _ = http_bytes(
            "GET", f"http://{master.url}/cluster/traces/{trace_id}")
        if status == 200:
            last = json.loads(body)
            if want is None or want(last):
                return last
        time.sleep(0.15)
    return last


def _assert_one_rooted_tree(doc):
    """Every span reaches a single root via parent edges."""
    ids = {s["id"] for s in doc["spans"]}
    roots = [s for s in doc["spans"]
             if not s.get("parent") or s["parent"] not in ids]
    assert len(roots) == 1, \
        f"expected one root, got {[(r['name'], r['id']) for r in roots]}"
    return roots[0]


class TestMasterToVolume:
    def test_vol_grow_produces_one_rooted_tree(self, cluster):
        master, servers = cluster
        status, body, hdrs = http_bytes(
            "GET", f"http://{master.url}/vol/grow?count=1", headers=FORCE)
        assert status == 200, body
        trace_id = hdrs.get("X-Trace-Id")
        assert trace_id and len(trace_id) == 32

        doc = _fetch_trace(
            master, trace_id,
            want=lambda d: any(s["name"] == "http.volume.assign_volume"
                               for s in d["spans"]))
        assert doc is not None, "trace never reached the collector"
        root = _assert_one_rooted_tree(doc)
        assert root["name"] == "http.master.vol_grow"
        names = {s["name"] for s in doc["spans"]}
        assert "http.volume.assign_volume" in names
        assert "rpc.client" in names
        # the volume span's parent is the master's rpc.client span —
        # the exact edge the Traceparent header carried across servers
        by_id = {s["id"]: s for s in doc["spans"]}
        vol = next(s for s in doc["spans"]
                   if s["name"] == "http.volume.assign_volume")
        assert by_id[vol["parent"]]["name"] == "rpc.client"

        an = doc["analysis"]
        assert an["bounding_hop"] is not None
        assert an["network_s"] >= 0.0 and an["server_s"]
        assert an["degraded"] is False
        assert an["spans_dropped"] == 0

    def test_trace_index_lists_it(self, cluster):
        master, _ = cluster
        _, _, hdrs = http_bytes(
            "GET", f"http://{master.url}/cluster/status", headers=FORCE)
        trace_id = hdrs["X-Trace-Id"]
        assert _fetch_trace(master, trace_id) is not None
        idx = http_json("GET", f"http://{master.url}/cluster/traces")
        assert any(t["trace_id"] == trace_id for t in idx["traces"])

    def test_unknown_trace_is_404(self, cluster):
        master, _ = cluster
        status, _, _ = http_bytes(
            "GET", f"http://{master.url}/cluster/traces/{'0' * 32}")
        assert status == 404


class TestHeaderEdgeCases:
    def test_malformed_traceparent_never_500s_and_mints_fresh(
            self, cluster):
        master, _ = cluster
        tc.set_sample_rate(1.0)  # fresh mints must sample
        for bad in ("garbage", "00-zz-xx-01", "01-" + "0" * 32 + "-x-01"):
            status, _, hdrs = http_bytes(
                "GET", f"http://{master.url}/cluster/status",
                headers={tc.TRACEPARENT_HEADER: bad})
            assert status == 200, bad
            minted = hdrs.get("X-Trace-Id")
            assert minted and len(minted) == 32, bad

    def test_upstream_not_sampled_suppresses(self, cluster):
        master, _ = cluster
        tc.set_sample_rate(1.0)
        status, _, hdrs = http_bytes(
            "GET", f"http://{master.url}/cluster/status",
            headers={tc.TRACEPARENT_HEADER: tc.NOT_SAMPLED_HEADER})
        assert status == 200
        assert "X-Trace-Id" not in hdrs

    def test_rate_zero_unsampled_but_served(self, cluster):
        master, _ = cluster  # fixture rate is 0.0
        status, _, hdrs = http_bytes(
            "GET", f"http://{master.url}/cluster/status")
        assert status == 200
        assert "X-Trace-Id" not in hdrs


class TestDebugTraceFilters:
    def _dump(self, url, query=""):
        status, body, _ = http_bytes(
            "GET", f"http://{url}/debug/traces{query}")
        assert status == 200
        return json.loads(body)

    def test_trace_id_filter_pulls_one_request(self, cluster):
        master, _ = cluster
        tids = []
        for _ in range(2):
            _, _, hdrs = http_bytes(
                "GET", f"http://{master.url}/cluster/status",
                headers=FORCE)
            tids.append(hdrs["X-Trace-Id"])
        doc = self._dump(master.url, f"?trace_id={tids[0]}")
        events = [e for e in doc["traceEvents"] if e.get("ph") == "X"]
        assert events, "filter returned no spans"
        assert all(e["args"].get("trace_id") == tids[0] for e in events)
        # the OTHER trace's spans are on the ring but filtered out
        full = [e for e in self._dump(master.url)["traceEvents"]
                if e.get("ph") == "X"]
        assert len(full) > len(events)
        assert "spansDropped" in doc

    def test_root_filter_pulls_one_subtree(self, cluster):
        master, _ = cluster
        _, _, hdrs = http_bytes(
            "GET", f"http://{master.url}/cluster/health", headers=FORCE)
        tid = hdrs["X-Trace-Id"]
        by_trace = self._dump(master.url, f"?trace_id={tid}")
        events = [e for e in by_trace["traceEvents"]
                  if e.get("ph") == "X"]
        root = next(e for e in events
                    if e["name"].startswith("http.master."))
        sub = self._dump(master.url, f"?root={root['args']['span_id']}")
        sub_events = [e for e in sub["traceEvents"] if e.get("ph") == "X"]
        assert sub_events
        sub_ids = {e["args"]["span_id"] for e in sub_events}
        assert root["args"]["span_id"] in sub_ids
        # subtree only: every returned span is the root or parents into
        # the returned set
        for e in sub_events:
            parent = e["args"].get("parent_id")
            assert e["args"]["span_id"] == root["args"]["span_id"] \
                or parent in sub_ids

    def test_analyze_surfaces_drop_counter(self, cluster):
        master, _ = cluster
        status, body, _ = http_bytes(
            "GET", f"http://{master.url}/debug/traces/analyze")
        assert status == 200
        assert "spans_dropped" in json.loads(body)


class TestScrubAll:
    def test_shell_scrub_all_rolls_up(self, cluster):
        master, servers = cluster
        from seaweedfs_tpu.shell import CommandEnv, run_command

        env = CommandEnv(master.url)
        out = run_command(env, "ec.scrub -all -timeout 30")
        assert out.startswith("cluster scrub:")
        for vs in servers:
            assert f"{vs.url}: done" in out
        assert "/cluster/health: degraded=" in out
        # every shell command is a force-sampled trace root
        assert len(env.last_trace_id) == 32
        # the per-peer scrub verdict rollup reached /cluster/health
        doc = http_json("GET", f"http://{master.url}/cluster/health")
        assert doc["totals"]["scrub_unrepairable"] == 0
        for vs in servers:
            assert "scrub" in doc["peers"][vs.url]


def _write_test_volume(dirpath, vid=1, n_needles=60):
    import numpy as np

    from seaweedfs_tpu.storage.needle import Needle
    from seaweedfs_tpu.storage.volume import Volume

    rng = np.random.default_rng(17)
    v = Volume(str(dirpath), "", vid)
    for i in range(1, n_needles + 1):
        v.write_needle(Needle(cookie=i, id=i,
                              data=rng.bytes(int(rng.integers(1, 700)))))
    v.close()


def _flip(path, offset, bit=0):
    with open(path, "r+b") as f:
        f.seek(offset)
        c = f.read(1)
        f.seek(offset)
        f.write(bytes([c[0] ^ (1 << bit)]))


class TestEcRebuildTrace:
    """The flagship scenario at tier-1 scale: a multi-server EC rebuild
    whose survivor copies cross servers yields ONE stitched trace whose
    analysis names the bounding hop and splits network vs server time;
    corrupting a survivor mid-rebuild flips the trace's verdict to
    DEGRADED (in-trace pipeline.retry evidence, not just counters)."""

    @pytest.fixture
    def ec_cluster(self, traced, tmp_path):
        _zero_degrade_counters()
        d0 = tmp_path / "vs0"
        d0.mkdir()
        _write_test_volume(d0)
        master = MasterServer(port=free_port(), pulse_seconds=0.4).start()
        master.aggregator.min_interval = 0.0
        d1 = tmp_path / "vs1"
        d1.mkdir()
        vs0 = VolumeServer([str(d0)], master.url, port=free_port(),
                           pulse_seconds=0.4).start()
        vs1 = VolumeServer([str(d1)], master.url, port=free_port(),
                           pulse_seconds=0.4).start()
        deadline = time.time() + 5
        while time.time() < deadline and len(master.topo.all_nodes()) < 2:
            time.sleep(0.05)
        # generate 14 shards on vs0, spread 7..13 to vs1 (a REAL
        # cross-server /admin/ec/copy), drop the volume
        vs0.store.ec_generate(1)
        http_json("POST", f"http://{vs1.url}/admin/ec/copy",
                  {"volume_id": 1, "shard_ids": list(range(7, 14)),
                   "source_data_node": vs0.url})
        http_json("POST", f"http://{vs1.url}/admin/ec/mount",
                  {"volume_id": 1})
        http_json("POST", f"http://{vs0.url}/admin/ec/delete",
                  {"volume_id": 1, "shard_ids": list(range(7, 14))})
        http_json("POST", f"http://{vs0.url}/admin/ec/mount",
                  {"volume_id": 1})
        http_json("POST", f"http://{vs0.url}/admin/delete_volume",
                  {"volume_id": 1})
        # lose shard 13 (held only by vs1) so a rebuild has real work
        http_json("POST", f"http://{vs1.url}/admin/ec/delete",
                  {"volume_id": 1, "shard_ids": [13]})
        vs0.heartbeat_now()
        vs1.heartbeat_now()
        yield master, vs0, vs1, str(d1)
        vs0.stop()
        vs1.stop()
        master.stop()

    def _rebuild_and_fetch(self, master):
        from seaweedfs_tpu.shell import CommandEnv, run_command

        env = CommandEnv(master.url)
        run_command(env, "lock")
        out = run_command(env, "ec.rebuild -volumeId 1")
        # shard 13 is always rebuilt; a demoted corrupt survivor may be
        # re-made alongside it (e.g. "rebuilt shards [8, 13]")
        assert "rebuilt shards" in out and "13]" in out, out
        trace_id = env.last_trace_id
        run_command(env, "unlock")
        doc = _fetch_trace(
            master, trace_id,
            want=lambda d: any(s["name"] == "http.volume.ec_rebuild"
                               for s in d["spans"]))
        assert doc is not None, "rebuild trace never reached collector"
        return doc

    def test_rebuild_stitches_and_names_bounding_hop(self, ec_cluster):
        master, vs0, vs1, _d1 = ec_cluster
        doc = self._rebuild_and_fetch(master)
        names = {s["name"] for s in doc["spans"]}
        # survivor copies crossed servers under ONE trace id
        assert "http.volume.ec_copy" in names
        assert "http.volume.ec_rebuild" in names
        assert "rpc.client" in names
        # the shell process records no spans (tracer ring is shared in
        # this test process, so spans DO exist here) — the contract is
        # that every server-side span parents into one tree per root
        an = doc["analysis"]
        assert an["bounding_hop"] is not None
        assert an["network_s"] >= 0.0
        assert an["server_s"], "no per-server occupancy computed"
        assert an["hops"], "no cross-server hops extracted"
        # clean run: no in-trace recovery events
        assert an["degrade_events"] == 0

    def test_corrupt_survivor_flips_verdict_degraded(self, ec_cluster):
        import os

        from seaweedfs_tpu.ec.layout import to_ext
        from seaweedfs_tpu.storage.volume import volume_file_prefix

        master, vs0, vs1, d1 = ec_cluster
        # rot a survivor data shard on vs1 before the rebuild reads it
        shard8 = volume_file_prefix(d1, "", 1) + to_ext(8)
        assert os.path.exists(shard8)
        _flip(shard8, 4096)
        doc = self._rebuild_and_fetch(master)
        an = doc["analysis"]
        # verify-on-use demoted the rotted survivor mid-rebuild and the
        # retry rode the SAME trace: the stitched verdict is DEGRADED
        assert any(s["name"] == "pipeline.retry"
                   and s["attrs"].get("reason") == "corrupt_shard"
                   for s in doc["spans"])
        assert an["degrade_events"] > 0
        assert an["degraded"] is True


class TestGatewayFilerVolume:
    @pytest.fixture
    def stack(self, cluster, tmp_path):
        from seaweedfs_tpu.filer.server import FilerServer
        from seaweedfs_tpu.gateway.s3 import S3ApiServer

        master, servers = cluster
        filer = FilerServer(master.url, port=free_port(),
                            max_chunk_mb=1).start()
        s3 = S3ApiServer(filer, port=free_port()).start()
        yield master, filer, s3
        s3.stop()
        filer.stop()

    def test_s3_write_read_one_rooted_tree(self, stack):
        master, filer, s3 = stack
        status, _, _ = http_bytes("PUT", f"http://{s3.url}/tb")
        assert status == 200
        payload = b"x" * (3 << 20)  # 3 chunks at max_chunk_mb=1
        status, _, hdrs = http_bytes(
            "PUT", f"http://{s3.url}/tb/obj", payload, headers=FORCE)
        assert status == 200
        put_tid = hdrs["X-Trace-Id"]
        doc = _fetch_trace(
            master, put_tid,
            want=lambda d: any(s["name"].startswith("http.volume.")
                               for s in d["spans"]))
        assert doc is not None
        root = _assert_one_rooted_tree(doc)
        assert root["name"].startswith("http.s3.")
        names = {s["name"] for s in doc["spans"]}
        # gateway -> (filer in-process) -> master assign -> volume write:
        # the whole fan-out rides ONE trace id
        assert any(n.startswith("http.master.") for n in names)
        assert any(n.startswith("http.volume.") for n in names)

        status, body, hdrs = http_bytes(
            "GET", f"http://{s3.url}/tb/obj", headers=FORCE)
        assert status == 200 and body == payload
        get_tid = hdrs["X-Trace-Id"]
        assert get_tid != put_tid
        doc = _fetch_trace(
            master, get_tid,
            want=lambda d: any(s["name"].startswith("http.volume.")
                               for s in d["spans"]))
        assert doc is not None
        root = _assert_one_rooted_tree(doc)
        assert root["name"].startswith("http.s3.")
        # the parallel chunk reads kept the context (filer read pool)
        assert sum(1 for s in doc["spans"]
                   if s["name"].startswith("http.volume.")) >= 3
