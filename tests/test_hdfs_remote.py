"""WebHDFS remote-storage client against an in-process namenode double.

Gates: bucket (top-level dir) lifecycle, the two-step 307-redirect
CREATE, recursive traverse, offset/length OPEN reads, recursive delete.
"""

from __future__ import annotations

import pytest

from seaweedfs_tpu.remote_storage.client import (
    RemoteConf,
    RemoteLocation,
    make_client,
)
from seaweedfs_tpu.remote_storage.hdfs import HdfsRemoteStorage
from seaweedfs_tpu.utils.httpd import HttpError

from .minihdfs import MiniHdfs


@pytest.fixture()
def server():
    s = MiniHdfs()
    yield s
    s.stop()


@pytest.fixture()
def client(server):
    c = make_client(RemoteConf(name="h", type="hdfs",
                               endpoint=f"127.0.0.1:{server.port}",
                               access_key="weeduser"))
    assert isinstance(c, HdfsRemoteStorage)
    return c


def test_bucket_and_file_lifecycle(server, client):
    client.create_bucket("warehouse")
    assert client.list_buckets() == ["warehouse"]
    loc = RemoteLocation(conf_name="h", bucket="warehouse", path="/")
    obj = client.write_file(loc, "/data/part-0000", b"hdfs bytes here")
    assert obj.size == 15
    assert client.read_file(loc, "/data/part-0000") == b"hdfs bytes here"
    assert client.read_file(loc, "/data/part-0000", offset=5, size=5) == \
        b"bytes"
    assert client.read_file(loc, "/data/part-0000", size=0) == b""
    client.delete_file(loc, "/data/part-0000")
    with pytest.raises(HttpError):
        client.read_file(loc, "/data/part-0000")
    client.delete_file(loc, "/data/part-0000")  # idempotent
    client.delete_bucket("warehouse")
    assert client.list_buckets() == []


def test_traverse_recursive(server, client):
    client.create_bucket("b")
    loc = RemoteLocation(conf_name="h", bucket="b", path="/")
    client.write_file(loc, "/x.bin", b"1")
    client.write_file(loc, "/sub/y.bin", b"22")
    client.write_file(loc, "/sub/deep/z.bin", b"333")
    got = sorted((o.key, o.size) for o in client.traverse(loc))
    assert got == [("/sub/deep/z.bin", 3), ("/sub/y.bin", 2), ("/x.bin", 1)]
    assert all(o.mtime > 0 for o in client.traverse(loc))
