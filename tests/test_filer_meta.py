"""FilerConf path rules, meta log APIs, fs.meta.* / fs.configure / fs.cd
shell commands — the metadata plane of the filer.

Reference behaviors: filer/filer_conf.go (longest-prefix rules, in-FS
config hot-reload), filer_grpc_server_sub_meta.go (SubscribeMetadata),
shell/command_fs_meta_{cat,save,load}.go, command_fs_configure.go.
"""

from __future__ import annotations

import json
import time
import urllib.parse

import pytest

from seaweedfs_tpu.filer.filer_conf import FILER_CONF_PATH, FilerConf, PathConf
from seaweedfs_tpu.filer.filer_store import SqliteStore
from seaweedfs_tpu.filer.server import FilerServer
from seaweedfs_tpu.master.server import MasterServer
from seaweedfs_tpu.shell.commands import CommandEnv, run_command
from seaweedfs_tpu.utils.httpd import http_bytes, http_json
from seaweedfs_tpu.volume_server.server import VolumeServer
from tests.conftest import free_port


@pytest.fixture
def stack(tmp_path):
    master = MasterServer(port=free_port(), pulse_seconds=0.4).start()
    d = tmp_path / "vs0"
    d.mkdir()
    vol = VolumeServer([str(d)], master.url, port=free_port(),
                       pulse_seconds=0.4).start()
    deadline = time.time() + 5
    while time.time() < deadline and len(master.topo.all_nodes()) < 1:
        time.sleep(0.05)
    filer = FilerServer(master.url, SqliteStore(str(tmp_path / "filer.db")),
                        port=free_port(), max_chunk_mb=1).start()
    yield master, vol, filer
    filer.stop()
    vol.stop()
    master.stop()


# --- FilerConf unit tests ---------------------------------------------------

def test_filer_conf_longest_prefix_merge():
    fc = FilerConf()
    fc.set_rule(PathConf(location_prefix="/buckets", collection="b",
                         replication="001"))
    fc.set_rule(PathConf(location_prefix="/buckets/hot", ttl="7d",
                         collection="hot"))
    rule = fc.match_storage_rule("/buckets/hot/x.bin")
    assert rule.collection == "hot"          # longer prefix wins
    assert rule.replication == "001"         # inherited from shorter prefix
    assert rule.ttl == "7d"
    assert fc.match_storage_rule("/other/x").collection == ""


def test_filer_conf_roundtrip():
    fc = FilerConf()
    fc.set_rule(PathConf(location_prefix="/a", read_only=True,
                         volume_growth_count=2))
    fc2 = FilerConf.from_bytes(fc.to_bytes())
    assert fc2.rules["/a"].read_only is True
    assert fc2.rules["/a"].volume_growth_count == 2
    assert FilerConf.from_bytes(b"").rules == {}


# --- live server behavior ---------------------------------------------------

def test_conf_read_only_rule_enforced_and_hot_reloaded(stack):
    _, _, filer = stack
    base = f"http://{filer.url}"
    fc = FilerConf()
    fc.set_rule(PathConf(location_prefix="/frozen", read_only=True))
    status, _, _ = http_bytes("PUT", base + FILER_CONF_PATH, fc.to_bytes())
    assert status == 201
    status, body, _ = http_bytes("PUT", base + "/frozen/x.txt", b"nope")
    assert status == 403
    status, _, _ = http_bytes("PUT", base + "/ok/x.txt", b"yes")
    assert status == 201
    # delete the rule -> writes allowed again (hot reload via meta event)
    fc2 = FilerConf()
    http_bytes("PUT", base + FILER_CONF_PATH, fc2.to_bytes())
    status, _, _ = http_bytes("PUT", base + "/frozen/x.txt", b"now ok")
    assert status == 201


def test_conf_collection_ttl_applied_to_entry(stack):
    _, _, filer = stack
    base = f"http://{filer.url}"
    fc = FilerConf()
    fc.set_rule(PathConf(location_prefix="/tagged", collection="mycoll",
                         ttl="5m"))
    http_bytes("PUT", base + FILER_CONF_PATH, fc.to_bytes())
    http_bytes("PUT", base + "/tagged/f.bin", b"data")
    stat = http_json("GET", base + "/api/stat/tagged/f.bin")
    assert stat["attr"]["collection"] == "mycoll"
    assert stat["attr"]["ttl_seconds"] == 300


def test_meta_log_tail_and_prefix_filter(stack):
    _, _, filer = stack
    base = f"http://{filer.url}"
    t0 = time.time_ns()
    http_bytes("PUT", base + "/logs/a.txt", b"a")
    http_bytes("PUT", base + "/other/b.txt", b"b")
    r = http_json("GET", base + f"/api/meta/log?since_ns={t0}")
    ops = [(e["op"], (e["new_entry"] or e["old_entry"])["full_path"])
           for e in r["events"]]
    assert ("create", "/logs/a.txt") in ops
    assert ("create", "/other/b.txt") in ops
    # prefix filter
    r2 = http_json("GET", base
                   + f"/api/meta/log?since_ns={t0}&path_prefix=/logs")
    paths = [(e["new_entry"] or e["old_entry"])["full_path"]
             for e in r2["events"]]
    assert "/logs/a.txt" in paths
    assert all(p.startswith("/logs") for p in paths)
    # cursor advances past the last event
    r3 = http_json("GET", base + f"/api/meta/log?since_ns={r['next_ns']}")
    assert r3["events"] == []


def test_meta_tree_and_raw_entry_create(stack):
    _, _, filer = stack
    base = f"http://{filer.url}"
    http_bytes("PUT", base + "/t/sub/one.txt", b"1")
    http_bytes("PUT", base + "/t/two.txt", b"22")
    tree = http_json("GET", base + "/api/meta/tree?path=/t")
    paths = {e["full_path"] for e in tree["entries"]}
    assert paths == {"/t/sub", "/t/sub/one.txt", "/t/two.txt"}
    # raw create with the same chunks = a metadata-level copy
    src = next(e for e in tree["entries"]
               if e["full_path"] == "/t/two.txt")
    clone = dict(src, full_path="/t/clone.txt")
    http_json("POST", base + "/api/entry", clone)
    status, body, _ = http_bytes("GET", base + "/t/clone.txt")
    assert (status, body) == (200, b"22")


# --- shell commands ---------------------------------------------------------

def test_shell_fs_cd_pwd_and_meta_family(stack, tmp_path):
    master, _, filer = stack
    env = CommandEnv(master.url, filer.url)
    http_bytes("PUT", f"http://{filer.url}/w/d/file.txt", b"hello")
    assert run_command(env, "fs.pwd") == "/"
    run_command(env, "fs.cd /w")
    assert run_command(env, "fs.pwd") == "/w"
    assert "file.txt" in run_command(env, "fs.ls d")
    meta = json.loads(run_command(env, "fs.meta.cat d/file.txt"))
    assert meta["full_path"] == "/w/d/file.txt"
    # save + load roundtrip into a new location
    out = tmp_path / "meta.jsonl"
    run_command(env, f"fs.meta.save -o {out} /w")
    lines = [json.loads(l) for l in out.read_text().splitlines()]
    assert {e["full_path"] for e in lines} == {"/w/d", "/w/d/file.txt"}
    # metadata-only restore: move the tree aside (chunks stay live), then
    # load the dump — the reference's fs.meta.load is metadata-only too
    run_command(env, "fs.mv /w -to /w_aside")
    msg = run_command(env, f"fs.meta.load {out}")
    assert msg == "loaded 2 entries"
    status, body, _ = http_bytes("GET", f"http://{filer.url}/w/d/file.txt")
    assert (status, body) == (200, b"hello")


def test_shell_fs_configure_apply(stack):
    master, _, filer = stack
    env = CommandEnv(master.url, filer.url)
    out = run_command(
        env, "fs.configure -locationPrefix /pix -collection pictures "
             "-volumeGrowthCount 2 -apply")
    assert "pictures" in out
    rule = filer.filer_conf().match_storage_rule("/pix/cat.jpg")
    assert rule.collection == "pictures"
    assert rule.volume_growth_count == 2
    # non-apply run just prints
    out2 = run_command(env, "fs.configure -locationPrefix /tmp2 -ttl 1d")
    assert "/tmp2" in out2
    assert filer.filer_conf().match_storage_rule("/tmp2/a").ttl == ""


def test_read_only_rule_blocks_delete_and_rename(stack):
    _, _, filer = stack
    base = f"http://{filer.url}"
    http_bytes("PUT", base + "/ro/keep.txt", b"data")
    http_bytes("PUT", base + "/ok2/a.txt", b"data")
    fc = FilerConf()
    fc.set_rule(PathConf(location_prefix="/ro", read_only=True))
    http_bytes("PUT", base + FILER_CONF_PATH, fc.to_bytes())
    status, body, _ = http_bytes(
        "POST", base + "/api/rename",
        json.dumps({"from": "/ok2/a.txt", "to": "/ro/x.txt"}).encode(),
        headers={"Content-Type": "application/json"})
    assert status == 403  # rename INTO a read-only prefix is a write
    # deletes are allowed (space reclamation, quota semantics)
    status, _, _ = http_bytes("DELETE", base + "/ro/keep.txt")
    assert status == 204
    # the conf file itself stays editable even under a blanket rule
    fc.set_rule(PathConf(location_prefix="/", read_only=True))
    status, _, _ = http_bytes("PUT", base + FILER_CONF_PATH, fc.to_bytes())
    assert status == 201
    status, _, _ = http_bytes("PUT", base + FILER_CONF_PATH,
                              FilerConf().to_bytes())
    assert status == 201


def test_meta_notify_republishes(stack):
    _, _, filer = stack
    base = f"http://{filer.url}"
    http_bytes("PUT", base + "/n/a.txt", b"a")
    t0 = time.time_ns()
    r = http_json("POST", base + "/api/meta/notify", {"path": "/n"})
    assert r["count"] == 1
    r2 = http_json("GET", base + f"/api/meta/log?since_ns={t0}")
    assert any((e["new_entry"] or {}).get("full_path") == "/n/a.txt"
               for e in r2["events"])


def test_percent_encoded_paths_roundtrip(stack):
    """%-escapes in request targets are decoded once at the HTTP layer
    (Go's r.URL.Path semantics — the reference handlers all consume the
    decoded form): '/my docs/read me.md' uploaded via its encoded URL is
    stored, listed, and served under its REAL name."""
    _, _, filer = stack
    base = f"http://{filer.url}"
    enc = base + "/my%20docs/sub%25dir/read%20me.md"
    status, _, _ = http_bytes("PUT", enc, b"spaced out")
    assert status == 201
    e = filer.filer.find_entry("/my docs/sub%dir/read me.md")
    assert e.file_size == 10
    status, body, _ = http_bytes("GET", enc)
    assert (status, body) == (200, b"spaced out")
    listing = http_json("GET", base + "/my%20docs/sub%25dir/")
    assert [x["FullPath"] for x in listing["Entries"]] == \
        ["/my docs/sub%dir/read me.md"]


def _multipart(data: bytes, filename: str, ctype: str) -> tuple[bytes, str]:
    boundary = "testboundary5309"
    body = (f"--{boundary}\r\n"
            f'Content-Disposition: form-data; name="file"; '
            f'filename="{filename}"\r\n'
            f"Content-Type: {ctype}\r\n\r\n").encode() + data + \
        f"\r\n--{boundary}--\r\n".encode()
    return body, f"multipart/form-data; boundary={boundary}"


def test_multipart_upload_unwrapped(stack):
    """curl -F style multipart/form-data bodies are unwrapped to the file
    part on both the filer and the volume server, like the reference's
    needle ParseUpload (needle_parse_upload.go:37-76)."""
    master, vol, filer = stack
    payload = b"\x00multi\xffpart payload" * 9
    body, ctype = _multipart(payload, "a.bin", "application/x-custom")
    # filer path
    status, _, _ = http_bytes(
        "POST", f"http://{filer.url}/mp/a.bin", body,
        headers={"Content-Type": ctype})
    assert status == 201
    status, got, hdrs = http_bytes("GET", f"http://{filer.url}/mp/a.bin")
    assert (status, got) == (200, payload)
    assert hdrs.get("Content-Type") == "application/x-custom"
    # direct volume path: the part filename lands in the needle name
    a = http_json("GET", f"http://{master.url}/dir/assign")
    status, _, _ = http_bytes(
        "POST", f"http://{a['url']}/{a['fid']}", body,
        headers={"Content-Type": ctype})
    assert status == 201
    status, got, hdrs = http_bytes("GET", f"http://{a['url']}/{a['fid']}")
    assert (status, got) == (200, payload)
    assert hdrs.get("Content-Type") == "application/x-custom"


def test_multipart_to_directory_and_malformed(stack):
    _, _, filer = stack
    base = f"http://{filer.url}"
    body, ctype = _multipart(b"form to dir", "from form.txt", "text/plain")
    # form upload to a directory URL: the part filename names the entry
    status, _, _ = http_bytes("POST", base + "/updir/", body,
                              headers={"Content-Type": ctype})
    assert status == 201
    status, got, _ = http_bytes("GET", base + "/updir/from%20form.txt")
    assert (status, got) == (200, b"form to dir")
    # multipart content-type without a boundary is the CLIENT's error
    status, _, _ = http_bytes(
        "POST", base + "/updir/bad.bin", b"xx",
        headers={"Content-Type": "multipart/form-data"})
    assert status == 400


def test_multipart_safety_gates(stack):
    _, _, filer = stack
    base = f"http://{filer.url}"
    # a crafted part filename cannot escape the target directory
    body, ctype = _multipart(b"contained", "../../evil.txt", "text/plain")
    status, _, _ = http_bytes("POST", base + "/jail/", body,
                              headers={"Content-Type": ctype})
    assert status == 201
    assert filer.filer.find_entry("/jail/evil.txt").file_size == 9
    import pytest as _pytest

    from seaweedfs_tpu.filer.filer import NotFoundError as FilerNotFound
    with _pytest.raises(FilerNotFound):
        filer.filer.find_entry("/evil.txt")
    # PUT bodies are raw even when multipart-typed (doPutAutoChunk):
    # a stored HTTP capture whose CONTENT is multipart survives verbatim
    status, _, _ = http_bytes("PUT", base + "/jail/capture.bin", body,
                              headers={"Content-Type": ctype})
    assert status == 201
    st, got, _ = http_bytes("GET", base + "/jail/capture.bin")
    assert (st, got) == (200, body)


def test_filer_tagging_roundtrip(stack):
    """PUT /path?tagging with Seaweed-* headers, headers echoed on GET,
    DELETE ?tagging=name / ?tagging (all) — the reference's filer-level
    tagging API (filer_server_handlers_tagging.go)."""
    _, _, filer = stack
    base = f"http://{filer.url}"
    http_bytes("PUT", base + "/tagged/doc.txt", b"body")
    st, _, _ = http_bytes(
        "PUT", base + "/tagged/doc.txt?tagging",
        headers={"Seaweed-Owner": "ops", "Seaweed-Tier": "hot",
                 "Unrelated": "ignored"})
    assert st == 202
    st, body, hdrs = http_bytes("GET", base + "/tagged/doc.txt")
    assert (st, body) == (200, b"body")
    assert hdrs.get("Seaweed-Owner") == "ops"
    assert hdrs.get("Seaweed-Tier") == "hot"
    assert "Unrelated" not in hdrs
    # delete ONE named tag
    st, _, _ = http_bytes(
        "DELETE", base + "/tagged/doc.txt?tagging=Tier")
    assert st == 202
    _, _, hdrs = http_bytes("GET", base + "/tagged/doc.txt")
    assert hdrs.get("Seaweed-Owner") == "ops"
    assert "Seaweed-Tier" not in hdrs
    # delete ALL tags
    st, _, _ = http_bytes("DELETE", base + "/tagged/doc.txt?tagging")
    assert st == 202
    _, _, hdrs = http_bytes("GET", base + "/tagged/doc.txt")
    assert "Seaweed-Owner" not in hdrs
    # tagging a missing path is a clean 404
    st, _, _ = http_bytes("PUT", base + "/missing?tagging",
                          headers={"Seaweed-X": "y"})
    assert st == 404


def test_proxy_chunk_id(stack):
    """GET /?proxyChunkId=<fid> proxies the raw chunk from its volume
    server through the filer (filer_server_handlers_proxy.go)."""
    _, _, filer = stack
    base = f"http://{filer.url}"
    http_bytes("PUT", base + "/px/blob.bin", b"chunky payload")
    e = filer.filer.find_entry("/px/blob.bin")
    fid = e.chunks[0].file_id
    st, body, _ = http_bytes("GET", base + f"/?proxyChunkId={fid}")
    assert st == 200 and body == b"chunky payload"
    st, _, _ = http_bytes("GET", base + "/?proxyChunkId=999,deadbeef00")
    assert st in (404, 500)


def test_filer_kv_api(stack):
    """/api/kv mirrors the KvGet/KvPut RPC pair: empty value deletes,
    missing keys answer found=false (filer_grpc_server_kv.go)."""
    import base64

    _, _, filer = stack
    base = f"http://{filer.url}"

    def b64(b):
        return base64.b64encode(b).decode()

    r = http_json("POST", base + "/api/kv",
                  {"key": b64(b"cluster/owner"), "value": b64(b"ops-team")})
    r = http_json("GET", base + "/api/kv?key=" + b64(b"cluster/owner"))
    assert r["found"] and base64.b64decode(r["value"]) == b"ops-team"
    # empty value = delete
    http_json("POST", base + "/api/kv", {"key": b64(b"cluster/owner")})
    r = http_json("GET", base + "/api/kv?key=" + b64(b"cluster/owner"))
    assert r["found"] is False and r["value"] == ""


def test_filer_kv_api_plus_in_key(stack):
    import base64

    _, _, filer = stack
    base = f"http://{filer.url}"
    key = b"\xfb\xef\xbe"  # b64-encodes to '++++'
    k64 = base64.b64encode(key).decode()
    assert "+" in k64
    http_json("POST", base + "/api/kv",
              {"key": k64, "value": base64.b64encode(b"v").decode()})
    r = http_json("GET", base + "/api/kv?key=" + k64)
    assert r["found"] and base64.b64decode(r["value"]) == b"v"


def test_filer_tagging_case_canonicalization(stack):
    """Lowercased headers (HTTP/2-style proxies) and mixed-case deletes
    land on one canonical Seaweed-* key."""
    _, _, filer = stack
    base = f"http://{filer.url}"
    http_bytes("PUT", base + "/tagged/c.txt", b"x")
    http_bytes("PUT", base + "/tagged/c.txt?tagging",
               headers={"seaweed-owner-id": "a"})
    http_bytes("PUT", base + "/tagged/c.txt?tagging",
               headers={"SEAWEED-OWNER-ID": "b"})
    e = filer.filer.find_entry("/tagged/c.txt")
    tags = {k: v for k, v in e.extended.items() if k.startswith("Seaweed-")}
    assert tags == {"Seaweed-Owner-Id": "b"}  # one key, last write wins
    st, _, _ = http_bytes("DELETE",
                          base + "/tagged/c.txt?tagging=owner-id")
    assert st == 202
    e = filer.filer.find_entry("/tagged/c.txt")
    assert not any(k.startswith("Seaweed-") for k in e.extended)


def test_filer_image_resize_on_get(stack):
    """?width/?height on a full filer GET serves a resized image, like
    the volume server (filer_server_handlers_read.go:186)."""
    import io

    import pytest as _pytest

    from seaweedfs_tpu.images import resizing_available
    if not resizing_available():
        _pytest.skip("no pillow")
    from PIL import Image

    _, _, filer = stack
    base = f"http://{filer.url}"
    buf = io.BytesIO()
    Image.new("RGB", (64, 32), (200, 10, 10)).save(buf, format="PNG")
    png = buf.getvalue()
    http_bytes("PUT", base + "/img/red.png", png,
               headers={"Content-Type": "image/png"})
    st, body, _ = http_bytes("GET", base + "/img/red.png?width=16")
    assert st == 200
    got = Image.open(io.BytesIO(body))
    assert got.size == (16, 8)  # aspect kept
    # no params -> original bytes
    st, body, _ = http_bytes("GET", base + "/img/red.png")
    assert body == png
    # a 206 on a resize URL is a slice of the RESIZED representation
    # (resize first, then range — a resumed download must stitch)
    st, full, hdrs = http_bytes("GET", base + "/img/red.png?width=16")
    assert hdrs.get("Content-Type") == "image/png"
    st, part, _ = http_bytes("GET", base + "/img/red.png?width=16",
                             headers={"Range": "bytes=0-3"})
    assert st == 206 and part == full[:4]
    # a resize failure serves the ORIGINAL bytes, not a 500: RGBA data
    # labeled image/jpeg cannot be saved as JPEG
    buf = io.BytesIO()
    Image.new("RGBA", (8, 8), (1, 2, 3, 4)).save(buf, format="PNG")
    rgba = buf.getvalue()
    http_bytes("PUT", base + "/img/fake.jpg", rgba,
               headers={"Content-Type": "image/jpeg"})
    st, body, _ = http_bytes("GET", base + "/img/fake.jpg?width=4")
    assert st == 200 and body == rgba
    # a resized representation must not share the original's ETag, and
    # a HEAD with resize params must describe the RESIZED entity (same
    # ETag, resized Content-Length) — HEAD and GET of one URL must agree
    _, rs_body, h_rs = http_bytes("GET", base + "/img/red.png?width=16")
    _, _, h_orig = http_bytes("GET", base + "/img/red.png")
    assert h_orig.get("ETag") != h_rs.get("ETag")
    _, _, h_rs2 = http_bytes("GET", base + "/img/red.png?width=8")
    assert h_rs.get("ETag") != h_rs2.get("ETag")
    st, _, h_head = http_bytes("HEAD", base + "/img/red.png?width=16")
    assert st == 200
    assert h_head.get("ETag") == h_rs.get("ETag")
    assert h_head.get("Content-Length") == str(len(rs_body))
    # bad resize params fall back to the original bytes and must keep
    # the original ETag (identical representation, one cache key)
    _, fb_body, h_fb = http_bytes("GET", base + "/img/red.png?width=abc")
    assert fb_body == png and h_fb.get("ETag") == h_orig.get("ETag")
