"""Minimal PostgreSQL v3 wire-protocol server for tests.

Speaks enough of the frontend/backend protocol for `filer/pg_client.py`:
startup (+SSLRequest refusal), trust / cleartext / md5 / SCRAM-SHA-256
auth, the extended query protocol (Parse/Bind/Execute/Sync) and simple
Query.  SQL executes against an in-memory sqlite database after
translating $N placeholders to ? — the postgres dialect's query shapes
(ON CONFLICT upsert, LIKE ESCAPE, LIMIT) are sqlite-compatible, so the
double exercises the real wire path with real SQL semantics.
"""

from __future__ import annotations

import base64
import hashlib
import hmac
import os
import re
import socket
import sqlite3
import struct
import threading

_PH = re.compile(r"\$(\d+)")


def _msg(tag: bytes, payload: bytes) -> bytes:
    return tag + struct.pack(">I", len(payload) + 4) + payload


def _cstr(s: str) -> bytes:
    return s.encode() + b"\x00"


def _sql_err(e: Exception) -> bytes:
    """Map sqlite errors to postgres SQLSTATEs the client keys on."""
    code = b"42P01" if "no such table" in str(e) else b"42601"
    return (b"SERROR\x00C" + code + b"\x00M" + str(e).encode() +
            b"\x00\x00")


class MiniPg:
    def __init__(self, host: str = "127.0.0.1", port: int = 0,
                 password: str = "", auth: str = "trust",
                 tamper: str = ""):
        """auth: trust | cleartext | md5 | scram.
        tamper: "" | "nonce" | "server_sig" — SCRAM adversary drills."""
        self.password = password
        self.auth = auth
        self.tamper = tamper
        self._db = sqlite3.connect(":memory:", check_same_thread=False)
        self._db_lock = threading.Lock()
        self._sock = socket.socket()
        self._sock.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._sock.bind((host, port))
        self._sock.listen(8)
        self.port = self._sock.getsockname()[1]
        self._stop = False
        threading.Thread(target=self._accept, daemon=True,
                         name="minipg").start()

    def stop(self) -> None:
        self._stop = True
        try:
            self._sock.close()
        except OSError:
            pass

    # --- plumbing ---------------------------------------------------------
    def _accept(self) -> None:
        while not self._stop:
            try:
                conn, _ = self._sock.accept()
            except OSError:
                return
            threading.Thread(target=self._serve, args=(conn,),
                             daemon=True).start()

    @staticmethod
    def _read_exact(conn, n: int) -> bytes:
        buf = b""
        while len(buf) < n:
            chunk = conn.recv(n - len(buf))
            if not chunk:
                raise ConnectionError
            buf += chunk
        return buf

    def _read_startup(self, conn) -> dict:
        while True:
            (ln,) = struct.unpack(">I", self._read_exact(conn, 4))
            body = self._read_exact(conn, ln - 4)
            (code,) = struct.unpack(">I", body[:4])
            if code == 80877103:  # SSLRequest
                conn.sendall(b"N")
                continue
            if code == 196608:
                parts = body[4:].split(b"\x00")
                kv = {}
                for i in range(0, len(parts) - 1, 2):
                    if parts[i]:
                        kv[parts[i].decode()] = parts[i + 1].decode()
                return kv
            raise ConnectionError(f"unexpected startup code {code}")

    def _read_msg(self, conn) -> tuple[bytes, bytes]:
        tag = self._read_exact(conn, 1)
        (ln,) = struct.unpack(">I", self._read_exact(conn, 4))
        return tag, self._read_exact(conn, ln - 4)

    # --- auth -------------------------------------------------------------
    def _do_auth(self, conn, user: str) -> bool:
        if self.auth == "trust":
            conn.sendall(_msg(b"R", struct.pack(">I", 0)))
            return True
        if self.auth == "cleartext":
            conn.sendall(_msg(b"R", struct.pack(">I", 3)))
            tag, payload = self._read_msg(conn)
            ok = (tag == b"p"
                  and payload.rstrip(b"\x00").decode() == self.password)
        elif self.auth == "md5":
            salt = os.urandom(4)
            conn.sendall(_msg(b"R", struct.pack(">I", 5) + salt))
            tag, payload = self._read_msg(conn)
            inner = hashlib.md5((self.password + user).encode()).hexdigest()
            want = "md5" + hashlib.md5(inner.encode() + salt).hexdigest()
            ok = tag == b"p" and payload.rstrip(b"\x00").decode() == want
        else:  # scram
            ok = self._do_scram(conn)
        if ok:
            conn.sendall(_msg(b"R", struct.pack(">I", 0)))
            return True
        conn.sendall(_msg(b"E", b"SFATAL\x00C28P01\x00"
                          b"Mpassword authentication failed\x00\x00"))
        return False

    def _do_scram(self, conn) -> bool:
        conn.sendall(_msg(b"R", struct.pack(">I", 10) +
                          _cstr("SCRAM-SHA-256") + b"\x00"))
        tag, payload = self._read_msg(conn)
        if tag != b"p":
            return False
        # SASLInitialResponse: mechanism cstr + int32 len + body
        mech_end = payload.index(b"\x00")
        body = payload[mech_end + 5:].decode()
        client_first_bare = body.split(",", 2)[2]
        client_nonce = dict(p.split("=", 1)
                            for p in client_first_bare.split(","))["r"]
        salt, iters = os.urandom(16), 4096
        if self.tamper == "nonce":
            # MITM shape: a fresh nonce NOT extending the client's —
            # an honest server must echo-and-extend (RFC 5802 §5.1)
            server_nonce = base64.b64encode(os.urandom(18)).decode()
        else:
            server_nonce = (client_nonce
                            + base64.b64encode(os.urandom(9)).decode())
        server_first = (f"r={server_nonce},"
                        f"s={base64.b64encode(salt).decode()},i={iters}")
        conn.sendall(_msg(b"R", struct.pack(">I", 11) + server_first.encode()))
        tag, payload = self._read_msg(conn)
        if tag != b"p":
            return False
        final = payload.decode()
        fparts = dict(p.split("=", 1) for p in final.split(","))
        salted = hashlib.pbkdf2_hmac("sha256", self.password.encode(),
                                     salt, iters)
        client_key = hmac.new(salted, b"Client Key", hashlib.sha256).digest()
        stored = hashlib.sha256(client_key).digest()
        without_proof = final[:final.rindex(",p=")]
        auth_msg = f"{client_first_bare},{server_first},{without_proof}"
        sig = hmac.new(stored, auth_msg.encode(), hashlib.sha256).digest()
        want = bytes(a ^ b for a, b in zip(client_key, sig))
        if base64.b64decode(fparts["p"]) != want:
            return False
        skey = hmac.new(salted, b"Server Key", hashlib.sha256).digest()
        v = hmac.new(skey, auth_msg.encode(), hashlib.sha256).digest()
        if self.tamper == "server_sig":
            # impersonator shape: correct protocol, wrong ServerSignature
            # (an attacker who doesn't know the password can't compute it)
            v = bytes(32)
        conn.sendall(_msg(b"R", struct.pack(">I", 12) +
                          b"v=" + base64.b64encode(v)))
        return True

    # --- SQL --------------------------------------------------------------
    def _run(self, sql: str, params: list) -> tuple[list[tuple], int]:
        q = _PH.sub("?", sql)
        with self._db_lock:
            cur = self._db.execute(q, params)
            rows = cur.fetchall() if cur.description else []
            self._db.commit()
            return rows, cur.rowcount

    @staticmethod
    def _send_rows(conn, rows) -> None:
        for row in rows:
            out = struct.pack(">H", len(row))
            for v in row:
                if v is None:
                    out += struct.pack(">i", -1)
                else:
                    b = str(v).encode()
                    out += struct.pack(">I", len(b)) + b
            conn.sendall(_msg(b"D", out))

    def _serve(self, conn) -> None:
        try:
            kv = self._read_startup(conn)
            if not self._do_auth(conn, kv.get("user", "")):
                conn.close()
                return
            conn.sendall(_msg(b"S", _cstr("server_version") + _cstr("14.0")))
            conn.sendall(_msg(b"Z", b"I"))
            sql, params = "", []
            while True:
                tag, payload = self._read_msg(conn)
                if tag == b"X":
                    break
                if tag == b"P":  # Parse: "" + sql + n_types
                    end = payload.index(b"\x00")
                    sql_end = payload.index(b"\x00", end + 1)
                    sql = payload[end + 1:sql_end].decode()
                    conn.sendall(_msg(b"1", b""))
                elif tag == b"B":  # Bind
                    off = payload.index(b"\x00") + 1
                    off = payload.index(b"\x00", off) + 1
                    (nfmt,) = struct.unpack(">H", payload[off:off + 2])
                    off += 2 + 2 * nfmt
                    (nparams,) = struct.unpack(">H", payload[off:off + 2])
                    off += 2
                    params = []
                    for _ in range(nparams):
                        (ln,) = struct.unpack(">i", payload[off:off + 4])
                        off += 4
                        if ln < 0:
                            params.append(None)
                        else:
                            params.append(payload[off:off + ln].decode())
                            off += ln
                    conn.sendall(_msg(b"2", b""))
                elif tag == b"E":  # Execute
                    try:
                        rows, count = self._run(sql, params)
                        self._send_rows(conn, rows)
                        conn.sendall(_msg(b"C", _cstr(f"SELECT {count}")))
                    except sqlite3.Error as e:
                        conn.sendall(_msg(b"E", _sql_err(e)))
                elif tag == b"S":  # Sync
                    conn.sendall(_msg(b"Z", b"I"))
                elif tag == b"Q":  # simple query (DDL)
                    try:
                        rows, count = self._run(
                            payload.rstrip(b"\x00").decode(), [])
                        self._send_rows(conn, rows)
                        conn.sendall(_msg(b"C", _cstr(f"OK {count}")))
                    except sqlite3.Error as e:
                        conn.sendall(_msg(b"E", _sql_err(e)))
                    conn.sendall(_msg(b"Z", b"I"))
        except (ConnectionError, OSError, struct.error, ValueError):
            pass
        finally:
            try:
                conn.close()
            except OSError:
                pass
