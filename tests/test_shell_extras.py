"""Shell completeness: volume.tier.*, volume.check.disk,
volume.configure.replication, volume.deleteEmpty, volume.server.leave,
s3.bucket.quota{,.check}.

Reference behaviors: shell/command_volume_tier_{upload,download,move}.go,
command_volume_check_disk.go, command_volume_configure_replication.go,
command_volume_delete_empty.go, command_volume_server_leave.go,
command_s3_bucket_quota{,_check}.go.
"""

from __future__ import annotations

import time

import pytest

from seaweedfs_tpu.filer.server import FilerServer
from seaweedfs_tpu.master.server import MasterServer
from seaweedfs_tpu.shell.commands import CommandEnv, run_command
from seaweedfs_tpu.storage.backend import DirBackendStorage, register_backend
from seaweedfs_tpu.utils.httpd import http_bytes, http_json
from seaweedfs_tpu.volume_server.server import VolumeServer
from tests.conftest import free_port


@pytest.fixture
def cluster(tmp_path):
    register_backend(DirBackendStorage("cloudx", str(tmp_path / "cloud")))
    master = MasterServer(port=free_port(), pulse_seconds=0.3).start()
    vols = []
    for i in range(2):
        d = tmp_path / f"vs{i}"
        d.mkdir()
        vols.append(VolumeServer([str(d)], master.url, port=free_port(),
                                 pulse_seconds=0.3).start())
    deadline = time.time() + 5
    while time.time() < deadline and len(master.topo.all_nodes()) < 2:
        time.sleep(0.05)
    env = CommandEnv(master.url)
    env.lock()
    yield master, vols, env
    for v in vols:
        v.stop()
    master.stop()


def _upload(master_url: str, data: bytes, replication: str = "") -> str:
    from seaweedfs_tpu.client.operation import WeedClient

    return WeedClient(master_url).upload(data, replication=replication)


def test_tier_upload_and_download(cluster, tmp_path):
    master, vols, env = cluster
    fid = _upload(master.url, b"tiered-bytes" * 100)
    vid = int(fid.split(",")[0])
    out = run_command(env, f"volume.tier.upload -volumeId {vid} -dest cloudx")
    assert "cloudx" in out
    # reads still work through the tiered backend
    from seaweedfs_tpu.client.operation import WeedClient

    assert WeedClient(master.url).download(fid) == b"tiered-bytes" * 100
    out = run_command(env, f"volume.tier.download -volumeId {vid}")
    assert "downloaded" in out
    assert WeedClient(master.url).download(fid) == b"tiered-bytes" * 100


def test_check_disk_reports_divergence(cluster):
    master, vols, env = cluster
    fid = _upload(master.url, b"replicated", replication="001")
    vid = int(fid.split(",")[0])
    out = run_command(env, f"volume.check.disk -volumeId {vid}")
    assert "in sync" in out
    # delete the needle on ONE replica only -> diverged
    urls = env.master.lookup(vid)
    assert len(urls) == 2
    http_bytes("DELETE", f"http://{urls[0]}/{fid}?type=replicate")
    out = run_command(env, f"volume.check.disk -volumeId {vid}")
    assert "DIVERGED" in out


def test_configure_replication_rewrites_superblock(cluster):
    master, vols, env = cluster
    fid = _upload(master.url, b"rp-data")
    vid = int(fid.split(",")[0])
    out = run_command(
        env, f"volume.configure.replication -volumeId {vid} -replication 001")
    assert "001" in out
    holder = next(vs for vs in vols if vid in vs.store.volumes)
    v = holder.store.get_volume(vid)
    assert str(v.super_block.replica_placement) == "001"


def test_delete_empty_volumes(cluster):
    master, vols, env = cluster
    run_command(env, "volume.grow -count 2")
    fid = _upload(master.url, b"keepme")
    used_vid = int(fid.split(",")[0])
    time.sleep(0.8)  # let heartbeats refresh VolumeInfos
    out = run_command(env, "volume.deleteEmpty -quietFor 0 -force")
    assert "deleted empty volumes" in out
    time.sleep(0.8)
    nodes = [n for dc in env.topology()["DataCenters"]
             for r in dc["Racks"] for n in r["DataNodes"]]
    remaining = [vid for n in nodes for vid in n["VolumeIds"]]
    assert used_vid in remaining
    assert all(vid == used_vid for vid in remaining)


def test_volume_server_leave(cluster):
    master, vols, env = cluster
    out = run_command(env, f"volume.server.leave -node {vols[1].url}")
    assert "left the cluster" in out
    deadline = time.time() + 8
    while time.time() < deadline:
        nodes = [n for dc in env.topology()["DataCenters"]
                 for r in dc["Racks"] for n in r["DataNodes"]]
        if vols[1].url not in [n["Url"] for n in nodes]:
            break
        time.sleep(0.2)
    assert vols[1].url not in [n["Url"] for n in nodes]


def test_s3_bucket_quota_and_check(cluster, tmp_path):
    master, vols, env = cluster
    filer = FilerServer(master.url, port=free_port(), max_chunk_mb=1).start()
    try:
        env.filer_url = filer.url
        base = f"http://{filer.url}"
        run_command(env, "s3.bucket.create -name qb")
        http_bytes("PUT", base + "/buckets/qb/big.bin", b"x" * (2 << 20))
        run_command(env, "s3.bucket.quota -name qb -sizeMB 1")
        out = run_command(env, "s3.bucket.quota -name qb")
        assert str(1 << 20) in out
        out = run_command(env, "s3.bucket.quota.check -apply")
        assert "OVER" in out and "read-only" in out.replace("read-only", "read-only")
        # bucket writes now rejected...
        status, _, _ = http_bytes("PUT", base + "/buckets/qb/more.bin", b"y")
        assert status == 403
        # ...but deletes still allowed (reclaim space)
        status, _, _ = http_bytes("DELETE", base + "/buckets/qb/big.bin")
        assert status == 204
        out = run_command(env, "s3.bucket.quota.check -apply")
        assert "lifted read-only" in out
        status, _, _ = http_bytes("PUT", base + "/buckets/qb/more.bin", b"y")
        assert status == 201
        # remove quota
        out = run_command(env, "s3.bucket.quota -name qb -remove")
        assert "removed" in out
    finally:
        filer.stop()
