"""Fault injection + chaos drills (the framework SURVEY §5 calls for).

Gates: armed fault points actually fire and auto-disarm; a failing local
EC shard read self-heals through reconstruction; a torn disk write rolls
back cleanly and the volume keeps serving; injected network latency is
observable; everything returns to normal after clear().
"""

from __future__ import annotations

import os

import numpy as np
import pytest

from seaweedfs_tpu.storage.needle import Needle
from seaweedfs_tpu.storage.volume import Volume
from seaweedfs_tpu.utils import faultinject as fi


@pytest.fixture(autouse=True)
def _clean_faults():
    fi.clear()
    yield
    fi.clear()


def test_fault_point_fires_and_auto_disarms(tmp_path):
    v = Volume(str(tmp_path), "", 1)
    try:
        v.write_needle(Needle(cookie=1, id=1, data=b"before"))
        fi.enable("disk.read", error_rate=1.0, max_hits=2)
        with pytest.raises(OSError):
            v.read_needle(1)
        with pytest.raises(OSError):
            v.read_needle(1)
        # max_hits exhausted: reads recover without operator action
        assert v.read_needle(1).data == b"before"
        assert fi.fired("disk.read") == 2
    finally:
        v.close()


def test_torn_write_rolls_back_and_volume_survives(tmp_path):
    v = Volume(str(tmp_path), "", 2)
    try:
        v.write_needle(Needle(cookie=1, id=1, data=b"good"))
        end_before = v.data_size
        fi.enable("disk.write", error_rate=1.0, max_hits=1)
        with pytest.raises(OSError):
            v.write_needle(Needle(cookie=2, id=2, data=b"doomed"))
        # _append_record truncated back: no torn bytes, old data intact
        assert v.data_size == end_before
        assert v.read_needle(1).data == b"good"
        v.write_needle(Needle(cookie=3, id=3, data=b"after"))
        assert v.read_needle(3).data == b"after"
    finally:
        v.close()


def test_ec_degraded_read_self_heals_on_shard_io_error(tmp_path):
    """A local shard pread failing (bad sector) must not fail the read:
    the store reconstructs the interval from the other shards."""
    from seaweedfs_tpu.volume_server.store import Store

    store = Store([str(tmp_path)], max_volume_count=4)
    v = store.add_volume(7)
    payloads = {i: os.urandom(600) for i in range(1, 9)}
    for i, data in payloads.items():
        v.write_needle(Needle(cookie=i, id=i, data=data))
    store.ec_generate(7)
    store.ec_mount(7)
    # every local shard read errors ONCE; reconstruction must kick in
    fi.enable("shard.read", error_rate=1.0, max_hits=1)
    record, _ = store.read_ec_needle(7, 3)
    assert fi.fired("shard.read") == 1
    assert payloads[3] in record  # needle record embeds the data bytes
    store.close()


def test_net_latency_injection():
    import time

    from seaweedfs_tpu.master.server import MasterServer
    from seaweedfs_tpu.utils.httpd import http_json
    from tests.conftest import free_port

    m = MasterServer(port=free_port(), pulse_seconds=0.5).start()
    try:
        http_json("GET", f"http://{m.url}/cluster/status")  # warm conn
        t0 = time.perf_counter()
        http_json("GET", f"http://{m.url}/cluster/status")
        base = time.perf_counter() - t0
        fi.enable("net.request", delay=0.08)
        t0 = time.perf_counter()
        http_json("GET", f"http://{m.url}/cluster/status")
        slow = time.perf_counter() - t0
        assert slow >= base + 0.07
        fi.clear()
        t0 = time.perf_counter()
        http_json("GET", f"http://{m.url}/cluster/status")
        assert time.perf_counter() - t0 < 0.07
    finally:
        fi.clear()
        m.stop()


def test_ec_shm_fault_fails_worker_spawn():
    """Arming `ec.shm` makes parity-worker (re)spawns fail
    deterministically — the lever the CPU-fallback chaos drills pull.
    The hit fires in _spawn BEFORE the process starts, so this drill
    needs no native toolchain: construction surfaces the injected
    fault (after cleaning up its shared memory) instead of hanging on
    a worker that never comes up."""
    from seaweedfs_tpu.ec.overlap import ProcessOverlapWorker

    matrix = np.ones((4, 8), dtype=np.uint8)
    fi.enable("ec.shm", error_rate=1.0, max_hits=1)
    with pytest.raises(OSError):
        ProcessOverlapWorker(8, 4, 1 << 12, matrix, nbufs=2)
    assert fi.fired("ec.shm") == 1


def test_coord_exec_fault_fails_plan_step():
    """Arming `coord.exec` makes coordinator plan-execution steps fail
    deterministically — the lever the mid-rebuild chaos drills pull.
    The executor surfaces the fault to its caller (a failed move here);
    recovery (re-plan, no-orphan cleanup) is the coordinator's job and
    is drilled in test_pipeline_chaos."""
    from seaweedfs_tpu.ops.coordinator import (ClusterView, Move,
                                               NodeView, PlanExecutor)

    calls = []
    view = ClusterView(
        nodes={"a:1": NodeView("a:1"), "b:1": NodeView("b:1")},
        shards={1: {0: ["a:1"]}})
    ex = PlanExecutor(post_fn=lambda *a: calls.append(a) or {})
    fi.enable("coord.exec", error_rate=1.0, max_hits=1)
    with pytest.raises(OSError):
        ex.execute_move(view, Move(1, 0, "a:1", "b:1"))
    assert fi.fired("coord.exec") == 1
    assert not calls  # the fault fired BEFORE the wire was touched
    # disarmed: the same step now goes through
    ex.execute_move(view, Move(1, 0, "a:1", "b:1"))
    assert calls


def test_coord_plan_fault_is_contained_by_the_loop():
    """Arming `coord.plan` fails a planning cycle; the coordinator loop
    must contain it (surface last_error, keep cycling) instead of
    dying — the next cycle re-plans."""
    import time as _time

    from seaweedfs_tpu.master.topology import Topology
    from seaweedfs_tpu.ops.coordinator import EcCoordinator

    c = EcCoordinator(topo=Topology(), post_fn=lambda *a: {},
                      interval_s=0.05)
    fi.enable("coord.plan", error_rate=1.0, max_hits=1)
    c.start()
    try:
        deadline = _time.time() + 5
        while _time.time() < deadline and not c.status()["cycles"]:
            _time.sleep(0.05)
        st = c.status()
        assert st["cycles"] > 0  # loop survived the injected fault
        assert fi.fired("coord.plan") == 1
    finally:
        fi.clear()
        c.stop()


# --- peer-scoped network fault points (net.delay / net.drop / ---------------
# net.partition): the scenario engine's wire


def test_net_partition_peer_scoping_unit():
    """hit_peer fires only for the armed peer; params-less arming
    covers every peer."""
    fi.enable("net.partition", error_rate=1.0,
              params={"peer": "h1:80"})
    fi.hit_peer("net.partition", "h2:80")  # other peer: no-op
    assert fi.fired("net.partition") == 0
    with pytest.raises(OSError):
        fi.hit_peer("net.partition", "h1:80")
    assert fi.fired("net.partition") == 1
    fi.enable("net.partition", error_rate=1.0)  # unscoped
    with pytest.raises(OSError):
        fi.hit_peer("net.partition", "anyone:1")


def test_net_drop_error_rate_and_max_hits():
    fi.enable("net.drop", error_rate=1.0, max_hits=1,
              params={"peer": "h1:80"})
    with pytest.raises(OSError):
        fi.hit_peer("net.drop", "h1:80")
    fi.hit_peer("net.drop", "h1:80")  # max_hits spent: passes
    assert fi.fired("net.drop") == 1


def test_net_delay_query_counts_without_sleeping():
    """peer_delay returns the armed delay (counting the hit) instead
    of sleeping, so the egress can apply it deadline-aware."""
    fi.enable("net.delay", delay=7.5, params={"peer": "h1:80"})
    assert fi.peer_delay("net.delay", "h2:80") == 0.0
    assert fi.fired("net.delay") == 0
    assert fi.peer_delay("net.delay", "h1:80") == 7.5
    assert fi.fired("net.delay") == 1
    fi.disable("net.delay")
    assert fi.peer_delay("net.delay", "h1:80") == 0.0
