"""B2 native-API remote storage against an in-process b2api/v2 double.

Gates mirror the azure-remote suite: auth (incl. refresh after token
expiry), bucket + file lifecycle, prefix traverse with nextFileName
paging, ranged reads, sha1-verified uploads, and the replication-sink
adapter on top.  Ref: weed/replication/sink/b2sink/b2_sink.go.
"""

from __future__ import annotations

import pytest

from seaweedfs_tpu.remote_storage.client import (
    RemoteConf,
    RemoteLocation,
    make_client,
)

from .minib2 import MiniB2


@pytest.fixture()
def server():
    s = MiniB2()
    yield s
    s.stop()


def _conf(server, key="sekret") -> RemoteConf:
    return RemoteConf(name="b2t", type="b2", access_key="keyid",
                      secret_key=key,
                      endpoint=f"http://127.0.0.1:{server.port}")


@pytest.fixture()
def client(server):
    return make_client(_conf(server))


def test_bucket_and_file_lifecycle(server, client):
    client.create_bucket("bkt")
    assert client.list_buckets() == ["bkt"]
    loc = RemoteLocation(conf_name="b2t", bucket="bkt")
    obj = client.write_file(loc, "/dir/a.bin", b"hello b2")
    assert obj.size == 8 and obj.key == "/dir/a.bin"
    assert client.read_file(loc, "/dir/a.bin") == b"hello b2"
    assert client.read_file(loc, "/dir/a.bin", offset=6, size=2) == b"b2"
    client.delete_file(loc, "/dir/a.bin")
    with pytest.raises(FileNotFoundError):
        client.read_file(loc, "/dir/a.bin")
    client.delete_file(loc, "/dir/a.bin")  # idempotent
    client.delete_bucket("bkt")
    assert client.list_buckets() == []


def test_traverse_prefix_and_paging(server, client):
    client.create_bucket("pkt")
    loc = RemoteLocation(conf_name="b2t", bucket="pkt", path="/logs")
    for i in range(5):
        client.write_file(loc, f"/logs/f{i}.txt", bytes([i]) * (i + 1))
    client.write_file(loc, "/other/x.txt", b"outside prefix")
    got = list(client.traverse(loc))  # double pages at 2 entries
    assert [o.key for o in got] == [f"/logs/f{i}.txt" for i in range(5)]
    assert [o.size for o in got] == [1, 2, 3, 4, 5]


def test_bad_credentials_rejected(server):
    bad = make_client(_conf(server, key="wrong"))
    with pytest.raises(PermissionError):
        bad.list_buckets()


def test_token_refresh_on_expiry(server, client):
    client.create_bucket("tok")
    loc = RemoteLocation(conf_name="b2t", bucket="tok")
    client.write_file(loc, "/a", b"1")
    server.expire_tokens()  # server-side expiry -> client must re-auth
    client.write_file(loc, "/b", b"2")
    assert sorted(o.key for o in client.traverse(loc)) == ["/a", "/b"]


def test_b2_as_replication_sink(server, client):
    from seaweedfs_tpu.replication.sink import RemoteStorageSink

    client.create_bucket("sinkb")
    sink = RemoteStorageSink(client, "sinkb")
    loc = RemoteLocation(conf_name="sink", bucket="sinkb")
    sink.create_entry("/d/file.txt", {"attr": {"mode": 0o644}},
                      b"replicated to b2")
    assert client.read_file(loc, "/d/file.txt") == b"replicated to b2"
    sink.delete_entry("/d/file.txt", is_directory=False)
    with pytest.raises(FileNotFoundError):
        client.read_file(loc, "/d/file.txt")
