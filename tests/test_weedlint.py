"""weedlint (tools/weedlint) as THE tier-1 static-analysis gate.

One engine now carries every repo lint: the four ported rules
(W101 py310 / W201 tracing / W301 async-drain / W401 health-keys), the
lockset thread-safety checker (W501/W502), the interprocedural
call-graph rules (W503 lock-order deadlock / W504 blocking-under-lock
over tools/weedlint/callgraph.py), and the route-param (W601),
fault-registry (W701) and ec-resource (W801) rules.  This suite:

  - proves EVERY rule fires on a planted violation and stays quiet on
    the matching clean source (parametrized, one case per rule);
  - unit-tests the lockset checker on synthetic classes (guarded-ok,
    unguarded-read, waived, stale-waiver, two-lock, holds-contract);
  - unit-tests the call graph (self/attr/module/import resolution,
    spawn edges, unresolved-call conservatism) and both
    interprocedural rules (ABBA + three-class-via-holds cycles,
    diamond no-cycle, every W504 blocking category, the lock-io
    waiver, two-hop reachability anchored at the under-lock call);
  - pins the engine machinery (waivers, baseline, JSON output incl.
    callgraph_stats, --changed-only scoping, CLI exit codes) and the
    repo-wide call-graph resolution ratio;
  - asserts the REPO-WIDE run is clean modulo the committed baseline —
    which the W502 burn-down emptied, and a test keeps empty.
"""

from __future__ import annotations

import json
import os
import subprocess
import sys

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if REPO not in sys.path:
    sys.path.insert(0, REPO)

from tools.weedlint import engine  # noqa: E402
from tools.weedlint import rules_py310, rules_tracing  # noqa: E402
from tools.weedlint.callgraph import build_from_sources  # noqa: E402
from tools.weedlint.rules_blocking import check_blocking  # noqa: E402
from tools.weedlint.rules_lockorder import check_lock_order  # noqa: E402
from tools.weedlint.rules_async_drain import \
    check_drain_fault_source  # noqa: E402
from tools.weedlint.rules_faults import (check_registry,  # noqa: E402
                                         hit_sites, load_registry)
from tools.weedlint.rules_lockset import check_class_source  # noqa: E402
from tools.weedlint.rules_resources import \
    check_module_source as check_resources  # noqa: E402
from tools.weedlint.rules_routes import \
    check_module_source as check_routes  # noqa: E402
from tools.weedlint.rules_bench import \
    check_source as check_bench_caps  # noqa: E402
from tools.weedlint.rules_eventloop import check_eventloop  # noqa: E402
from tools.weedlint.rules_leader import \
    check_source as check_leader_gated  # noqa: E402
from tools.weedlint.rules_timeouts import \
    check_source as check_timeouts  # noqa: E402

# --- planted sources, one clean/bad pair per single-module rule -------------

W301_CLEAN = (
    "def f():\n"
    "    with tr.span('pipeline.drain'):\n"
    "        faultinject.hit('ec.drain')\n")
W301_BAD = (
    "def f():\n"
    "    faultinject.hit('ec.drain')\n")

W501_CLEAN = (
    "import threading\n"
    "class C:\n"
    "    def __init__(self):\n"
    "        self._lock = threading.Lock()\n"
    "        self._n = 0  # guarded-by: _lock\n"
    "        self._t = threading.Thread(target=self._loop)\n"
    "    def _loop(self):\n"
    "        with self._lock:\n"
    "            self._n += 1\n"
    "    def read(self):\n"
    "        with self._lock:\n"
    "            return self._n\n")
W501_BAD = W501_CLEAN.replace(
    "    def read(self):\n"
    "        with self._lock:\n"
    "            return self._n\n",
    "    def read(self):\n"
    "        return self._n\n")

W502_CLEAN = W501_CLEAN
W502_BAD = (
    "import threading\n"
    "class C:\n"
    "    def __init__(self):\n"
    "        self.hits = 0\n"
    "        self._t = threading.Thread(target=self._loop)\n"
    "    def _loop(self):\n"
    "        self.hits += 1\n")

# W503: ABBA deadlock across two classes vs the same classes locking in
# one global order
W503_BAD = (
    "import threading\n"
    "class A:\n"
    "    def __init__(self):\n"
    "        self._lock = threading.Lock()\n"
    "        self.b = B()\n"
    "    def push(self):\n"
    "        with self._lock:\n"
    "            self.b.notify()\n"
    "    def stats(self):\n"
    "        with self._lock:\n"
    "            return 1\n"
    "class B:\n"
    "    def __init__(self):\n"
    "        self._lock = threading.Lock()\n"
    "        self.a = A()\n"
    "    def notify(self):\n"
    "        with self._lock:\n"
    "            pass\n"
    "    def drain(self):\n"
    "        with self._lock:\n"
    "            self.a.stats()\n")
W503_CLEAN = W503_BAD.replace(
    "    def drain(self):\n"
    "        with self._lock:\n"
    "            self.a.stats()\n",
    "    def drain(self):\n"
    "        with self._lock:\n"
    "            pass\n")

W504_BAD = (
    "import threading, time\n"
    "class C:\n"
    "    def __init__(self):\n"
    "        self._lock = threading.Lock()\n"
    "    def outer(self):\n"
    "        with self._lock:\n"
    "            time.sleep(5)\n")
W504_CLEAN = W504_BAD.replace(
    "        with self._lock:\n"
    "            time.sleep(5)\n",
    "        with self._lock:\n"
    "            pass\n"
    "        time.sleep(5)\n")

# W505: a `# loop-callback` reactor method reaching a blocking call vs
# the same work parked on the dispatch pool via a nested closure
W505_BAD = (
    "import time\n"
    "class R:\n"
    "    def _on_readable(self, conn):  # loop-callback\n"
    "        self._helper()\n"
    "    def _helper(self):\n"
    "        time.sleep(1)\n")
W505_CLEAN = (
    "import time\n"
    "class R:\n"
    "    def _on_readable(self, conn):  # loop-callback\n"
    "        def run():\n"
    "            self._helper()\n"
    "        self.submit(run)\n"
    "    def submit(self, fn):\n"
    "        pass\n"
    "    def _helper(self):\n"
    "        time.sleep(1)\n")

W601_CLEAN = (
    "def install(router):\n"
    "    @router.route('GET', '/x')\n"
    "    def handler(req):\n"
    "        try:\n"
    "            limit = int(req.query.get('limit') or 0)\n"
    "        except ValueError:\n"
    "            raise HttpError(400, 'bad limit')\n"
    "        return limit\n")
W601_BAD = (
    "def install(router):\n"
    "    @router.route('GET', '/x')\n"
    "    def handler(req):\n"
    "        return int(req.query.get('limit') or 0)\n")

W801_CLEAN = (
    "def f(path):\n"
    "    with open(path, 'rb') as fh:\n"
    "        return fh.read()\n")
W801_BAD = (
    "def f(path):\n"
    "    fh = open(path, 'rb')\n"
    "    return fh.read()\n")

W901_CLEAN = (
    "def f(url):\n"
    "    a = http_json('GET', url, timeout=10.0)\n"
    "    b = http_bytes('GET', url, None, None, 5.0)\n"
    "    c = urlopen(url, timeout=3.0)\n"
    "    d = socket.create_connection(('h', 1), 2.0)\n"
    "    return a, b, c, d\n")
W901_BAD = (
    "def f(url):\n"
    "    return http_json('GET', url)\n")

W902_CLEAN = (
    "class M:\n"
    "    def _apply(self, data):  # raft-apply\n"
    "        self.coordinator.apply_replicated(data)\n"
    "    def _replicate(self, doc):\n"
    "        if not self.raft.is_leader:\n"
    "            return\n"
    "        self.raft.append('alert', doc)\n"
    "    def _promote(self, role):\n"
    "        if role == 'leader':\n"
    "            self.coordinator.resume_replicated()\n"
    "    def _journal(self, rec,  # leader-only\n"
    "                 sync=False):\n"
    "        self.replicate_fn(rec)\n"
    "    def harmless(self, items):\n"
    "        items.append(1)\n")  # list .append never matches
W902_BAD = (
    "class M:\n"
    "    def handle(self, req):\n"
    "        self.raft.append('event', {'events': []})\n"
    "        self.alert_engine.import_state({})\n")

W1001_CLEAN = (
    "SECTION_CAPS = {'alpha': 60, 'beta': 120}\n"
    "def run():\n"
    "    section('alpha', lambda: None)\n"
    "    cap = SECTION_CAPS.get('beta', 300)\n")
W1001_BAD = (
    "SECTION_CAPS = {'alpha': 60}\n"
    "def run():\n"
    "    section('alpha', lambda: None)\n"
    "    section('gamma', lambda: None)\n"
    "    cap = SECTION_CAPS.get('delta', 300)\n")

CASES = [
    ("W101", "x = 1\n", "import tomllib\n",
     lambda src: rules_py310.check_source(src, "t.py")),
    ("W201", "import urllib.parse\n", "import urllib.request\n",
     lambda src: rules_tracing.check_package_source(src, "pkg/t.py")),
    ("W301", W301_CLEAN, W301_BAD,
     lambda src: check_drain_fault_source(src, "t.py")),
    ("W501", W501_CLEAN, W501_BAD,
     lambda src: check_class_source(src, "t.py")),
    ("W502", W502_CLEAN, W502_BAD,
     lambda src: check_class_source(src, "t.py")),
    ("W503", W503_CLEAN, W503_BAD,
     lambda src: check_lock_order(build_from_sources([("pkg/t.py", src)]))),
    ("W504", W504_CLEAN, W504_BAD,
     lambda src: check_blocking(build_from_sources([("pkg/t.py", src)]))),
    ("W505", W505_CLEAN, W505_BAD,
     lambda src: check_eventloop(build_from_sources([("pkg/t.py", src)]))),
    ("W601", W601_CLEAN, W601_BAD,
     lambda src: check_routes(src, "t.py")),
    ("W801", W801_CLEAN, W801_BAD,
     lambda src: check_resources(src, "t.py")),
    ("W901", W901_CLEAN, W901_BAD,
     lambda src: check_timeouts(src, "t.py")),
    ("W902", W902_CLEAN, W902_BAD,
     lambda src: check_leader_gated(src, "seaweedfs_tpu/master/t.py")),
    ("W1001", W1001_CLEAN, W1001_BAD,
     lambda src: check_bench_caps(src, "bench.py")),
]


@pytest.mark.parametrize("rule_id,clean,bad,checker", CASES,
                         ids=[c[0] for c in CASES])
def test_rule_fires_on_planted_violation_and_passes_clean(
        rule_id, clean, bad, checker):
    assert [f for f in checker(clean) if f.rule == rule_id] == [], rule_id
    hits = [f for f in checker(bad) if f.rule == rule_id]
    assert hits, f"{rule_id} did not fire on its planted violation"
    assert all(f.line > 0 for f in hits)


# --- W701: fault-registry consistency (tables as arguments) -----------------

class TestFaultRegistry:
    REG = {"a.b": 3, "c.d": 4}

    def test_consistent_tables_pass(self):
        sites = [("a.b", 10, "m.py"), ("c.d", 11, "m.py")]
        assert check_registry(self.REG, 1, sites, '"a.b" "c.d"') == []

    def test_unregistered_site_caught(self):
        sites = [("a.b", 10, "m.py"), ("c.d", 11, "m.py"),
                 ("typo.name", 12, "m.py")]
        out = check_registry(self.REG, 1, sites, '"a.b" "c.d"')
        assert any("typo.name" in f.message and f.path == "m.py"
                   for f in out)

    def test_registered_without_site_caught(self):
        out = check_registry(self.REG, 1, [("a.b", 10, "m.py")],
                             '"a.b" "c.d"')
        assert any("c.d" in f.message and "never inject" in f.message
                   for f in out)

    def test_untested_point_caught(self):
        sites = [("a.b", 10, "m.py"), ("c.d", 11, "m.py")]
        out = check_registry(self.REG, 1, sites, '"a.b" only')
        assert any("c.d" in f.message and "not exercised" in f.message
                   for f in out)

    def test_live_registry_parses_and_matches_sites(self):
        fi_path = os.path.join(REPO, "seaweedfs_tpu", "utils",
                               "faultinject.py")
        with open(fi_path, encoding="utf-8") as f:
            src = f.read()
        registry, _line = load_registry(src)
        assert "ec.drain" in registry and "ec.shard.corrupt" in registry
        # the module's own hit() implementation is not a SITE
        assert all(n for n, _ln in hit_sites(src, fi_path))


# --- lockset checker on synthetic classes -----------------------------------

class TestLockset:
    def test_two_lock_class_wrong_lock_caught(self):
        src = (
            "import threading\n"
            "class C:\n"
            "    def __init__(self):\n"
            "        self._a_lock = threading.Lock()\n"
            "        self._b_lock = threading.Lock()\n"
            "        self._a = 0  # guarded-by: _a_lock\n"
            "        self._b = 0  # guarded-by: _b_lock\n"
            "        self._t = threading.Thread(target=self._loop)\n"
            "    def _loop(self):\n"
            "        with self._a_lock:\n"
            "            self._a += 1\n"
            "            self._b += 1\n"  # wrong lock held
            "    def read(self):\n"
            "        with self._b_lock:\n"
            "            return self._b\n"
            "        with self._a_lock:\n"
            "            return self._a\n")
        out = [f for f in check_class_source(src, "t.py")
               if f.rule == "W501"]
        assert len(out) == 1 and "self._b" in out[0].message \
            and "_b_lock" in out[0].message

    def test_holds_annotation_honored(self):
        src = (
            "import threading\n"
            "class C:\n"
            "    def __init__(self):\n"
            "        self._lock = threading.Lock()\n"
            "        self._n = 0  # guarded-by: _lock\n"
            "        self._t = threading.Thread(target=self._loop)\n"
            "    def _loop(self):\n"
            "        with self._lock:\n"
            "            self._bump()\n"
            "    def _bump(self):  # holds: _lock\n"
            "        self._n += 1\n"
            "    def bump(self):\n"
            "        with self._lock:\n"
            "            self._bump()\n")
        assert check_class_source(src, "t.py") == []

    def test_locked_suffix_honored(self):
        src = (
            "import threading\n"
            "class C:\n"
            "    def __init__(self):\n"
            "        self._lock = threading.Lock()\n"
            "        self._n = 0  # guarded-by: _lock\n"
            "        self._t = threading.Thread(target=self._loop)\n"
            "    def _loop(self):\n"
            "        with self._lock:\n"
            "            self._bump_locked()\n"
            "    def _bump_locked(self):\n"
            "        self._n += 1\n"
            "    def bump(self):\n"
            "        with self._lock:\n"
            "            self._bump_locked()\n")
        assert check_class_source(src, "t.py") == []

    def test_thread_entry_annotation_creates_root(self):
        # no lexical Thread() construction: the annotation alone must
        # make the hook method a root so the naked access is caught
        src = (
            "import threading\n"
            "class C:\n"
            "    def __init__(self):\n"
            "        self._lock = threading.Lock()\n"
            "        self._n = 0  # guarded-by: _lock\n"
            "    def on_event(self, ev):  # thread-entry\n"
            "        self._n += 1\n")
        out = check_class_source(src, "t.py")
        assert any(f.rule == "W501" and "on_event" in f.message
                   for f in out)

    def test_concurrent_class_marks_public_methods_as_roots(self):
        src = (
            "import threading\n"
            "class C:  # weedlint: concurrent-class\n"
            "    def __init__(self):\n"
            "        self._lock = threading.Lock()\n"
            "        self._n = 0  # guarded-by: _lock\n"
            "    def bump(self):\n"
            "        self._n += 1\n"
            "    def read(self):\n"
            "        return self._n\n")
        out = [f for f in check_class_source(src, "t.py")
               if f.rule == "W501"]
        assert len(out) == 2  # both naked accesses race each other

    def test_closure_does_not_inherit_lock(self):
        # a nested function may run on another thread after the with
        # released the lock: the access inside it counts as unlocked
        src = (
            "import threading\n"
            "class C:\n"
            "    def __init__(self):\n"
            "        self._lock = threading.Lock()\n"
            "        self._n = 0  # guarded-by: _lock\n"
            "        self._t = threading.Thread(target=self._loop)\n"
            "    def _loop(self):\n"
            "        with self._lock:\n"
            "            self._n += 1\n"
            "    def make(self):\n"
            "        with self._lock:\n"
            "            def cb():\n"
            "                return self._n\n"
            "            return cb\n")
        out = [f for f in check_class_source(src, "t.py")
               if f.rule == "W501"]
        assert len(out) == 1 and out[0].rule == "W501"

    def test_init_is_exempt(self):
        src = (
            "import threading\n"
            "class C:\n"
            "    def __init__(self):\n"
            "        self._lock = threading.Lock()\n"
            "        self._n = 0  # guarded-by: _lock\n"
            "        self._n = 1\n"  # naked in __init__: fine
            "        self._t = threading.Thread(target=self._loop)\n"
            "    def _loop(self):\n"
            "        with self._lock:\n"
            "            self._n += 1\n")
        assert check_class_source(src, "t.py") == []


# --- callgraph: resolution rules + conservatism ------------------------------

class TestCallGraph:
    def _graph(self, src: str, extra=None):
        sources = [("pkg/t.py", src)] + list(extra or [])
        return build_from_sources(sources)

    def test_self_method_resolution(self):
        g = self._graph(
            "class A:\n"
            "    def f(self):\n"
            "        self.g()\n"
            "    def g(self):\n"
            "        pass\n")
        assert "pkg/t.py::A.g" in g.edges()["pkg/t.py::A.f"]

    def test_attr_typed_cross_class_resolution(self):
        g = self._graph(
            "class Helper:\n"
            "    def work(self):\n"
            "        pass\n"
            "class A:\n"
            "    def __init__(self):\n"
            "        self.h = Helper()\n"
            "    def f(self):\n"
            "        self.h.work()\n")
        assert "pkg/t.py::Helper.work" in g.edges()["pkg/t.py::A.f"]

    def test_module_function_resolution(self):
        g = self._graph(
            "def helper():\n"
            "    pass\n"
            "def top():\n"
            "    helper()\n")
        assert "pkg/t.py::helper" in g.edges()["pkg/t.py::top"]

    def test_cross_module_import_resolution(self):
        g = self._graph(
            "from pkg.other import helper\n"
            "def top():\n"
            "    helper()\n",
            extra=[("pkg/other.py", "def helper():\n    pass\n")])
        assert "pkg/other.py::helper" in g.edges()["pkg/t.py::top"]

    def test_constructor_resolves_to_init(self):
        g = self._graph(
            "class A:\n"
            "    def __init__(self):\n"
            "        pass\n"
            "def make():\n"
            "    return A()\n")
        assert "pkg/t.py::A.__init__" in g.edges()["pkg/t.py::make"]

    def test_unresolvable_call_is_counted_not_edged(self):
        g = self._graph(
            "class A:\n"
            "    def f(self):\n"
            "        self.on_event()\n")  # hook attr, never constructed
        assert g.edges()["pkg/t.py::A.f"] == set()
        assert g.calls_unresolved == 1

    def test_stdlib_call_counts_external(self):
        g = self._graph(
            "import os\n"
            "def f():\n"
            "    os.getpid()\n")
        assert g.calls_external == 1 and g.calls_unresolved == 0

    def test_thread_target_is_spawn_edge(self):
        g = self._graph(
            "import threading\n"
            "class A:\n"
            "    def start(self):\n"
            "        threading.Thread(target=self._run).start()\n"
            "    def _run(self):\n"
            "        pass\n")
        assert "pkg/t.py::A._run" in g.edges()["pkg/t.py::A.start"]
        # ...but spawn edges are excluded from lock propagation walks
        assert "pkg/t.py::A._run" not in g.sync_edges()["pkg/t.py::A.start"]

    def test_stats_shape(self):
        g = self._graph("def f():\n    pass\n")
        s = g.stats()
        assert set(s) >= {"nodes", "edges", "calls_total",
                          "calls_resolved", "calls_external",
                          "calls_unresolved", "unresolved_ratio"}


# --- W503: lock-order cycles --------------------------------------------------

class TestLockOrder:
    def test_three_class_cycle_through_holds_contract(self):
        # the B._lock -> C._lock edge exists ONLY because _kick's
        # `# holds:` contract says B._lock is held on entry — no
        # lexical `with` covers the call into C
        src = (
            "import threading\n"
            "class A:\n"
            "    def __init__(self):\n"
            "        self._lock = threading.Lock()\n"
            "        self.b = B()\n"
            "    def step(self):\n"
            "        with self._lock:\n"
            "            self.b.enter()\n"
            "    def back(self):\n"
            "        with self._lock:\n"
            "            return 1\n"
            "class B:\n"
            "    def __init__(self):\n"
            "        self._lock = threading.Lock()\n"
            "        self.c = C()\n"
            "    def enter(self):\n"
            "        with self._lock:\n"
            "            pass\n"
            "    def _kick(self):  # holds: _lock\n"
            "        self.c.poke()\n"
            "class C:\n"
            "    def __init__(self):\n"
            "        self._lock = threading.Lock()\n"
            "        self.a = A()\n"
            "    def poke(self):\n"
            "        with self._lock:\n"
            "            self.a.back()\n")
        out = check_lock_order(build_from_sources([("pkg/t.py", src)]))
        assert len(out) == 1
        msg = out[0].message
        for lock in ("A._lock", "B._lock", "C._lock"):
            assert lock in msg, msg
        # the hint carries the acquisition-path evidence, including the
        # hop through C that only the holds: contract makes visible
        assert "acquisition path" in out[0].hint
        assert "c.poke" in out[0].hint

    def test_diamond_without_cycle_is_clean(self):
        src = (
            "import threading\n"
            "class D:\n"
            "    def __init__(self):\n"
            "        self._lock = threading.Lock()\n"
            "    def leaf(self):\n"
            "        with self._lock:\n"
            "            pass\n"
            "class B:\n"
            "    def __init__(self):\n"
            "        self._lock = threading.Lock()\n"
            "        self.d = D()\n"
            "    def mid(self):\n"
            "        with self._lock:\n"
            "            self.d.leaf()\n"
            "class C:\n"
            "    def __init__(self):\n"
            "        self._lock = threading.Lock()\n"
            "        self.d = D()\n"
            "    def mid(self):\n"
            "        with self._lock:\n"
            "            self.d.leaf()\n"
            "class A:\n"
            "    def __init__(self):\n"
            "        self._lock = threading.Lock()\n"
            "        self.b = B()\n"
            "        self.c = C()\n"
            "    def top(self):\n"
            "        with self._lock:\n"
            "            self.b.mid()\n"
            "            self.c.mid()\n")
        assert check_lock_order(
            build_from_sources([("pkg/t.py", src)])) == []

    def test_lexical_self_nesting_of_plain_lock_caught(self):
        src = (
            "import threading\n"
            "class A:\n"
            "    def __init__(self):\n"
            "        self._lock = threading.Lock()\n"
            "    def f(self):\n"
            "        with self._lock:\n"
            "            with self._lock:\n"
            "                pass\n")
        out = check_lock_order(build_from_sources([("pkg/t.py", src)]))
        assert len(out) == 1 and "A._lock" in out[0].message

    def test_rlock_self_nesting_is_fine(self):
        src = (
            "import threading\n"
            "class A:\n"
            "    def __init__(self):\n"
            "        self._lock = threading.RLock()\n"
            "    def f(self):\n"
            "        with self._lock:\n"
            "            with self._lock:\n"
            "                pass\n")
        assert check_lock_order(
            build_from_sources([("pkg/t.py", src)])) == []


# --- W504: blocking while a lock is held --------------------------------------

def _w504(src: str):
    return check_blocking(build_from_sources([("pkg/t.py", src)]))


_CLS = ("import threading, time, queue, subprocess\n"
        "class C:\n"
        "    def __init__(self):\n"
        "        self._lock = threading.Lock()\n"
        "        self._q = queue.Queue(8)\n"
        "        self._uq = queue.Queue()\n"
        "        self._ev = threading.Event()\n")

W504_CATEGORY_CASES = [
    ("sleep", "        with self._lock:\n            time.sleep(1)\n",
     "        time.sleep(1)\n"),
    ("http-egress",
     "        with self._lock:\n            http_json('GET', u)\n",
     "        http_json('GET', u)\n"),
    ("queue-get", "        with self._lock:\n            self._q.get()\n",
     "        with self._lock:\n            self._q.get(timeout=1)\n"),
    ("queue-put",
     "        with self._lock:\n            self._q.put(1)\n",
     # unbounded queue put never blocks: clean even under the lock
     "        with self._lock:\n            self._uq.put(1)\n"),
    ("event-wait",
     "        with self._lock:\n            self._ev.wait()\n",
     "        with self._lock:\n            self._ev.wait(1.0)\n"),
    ("subprocess",
     "        with self._lock:\n            subprocess.run(['x'])\n",
     "        subprocess.run(['x'])\n"),
    ("file-read",
     "        fh = open('x')\n"
     "        with self._lock:\n            fh.read()\n",
     "        fh = open('x')\n"
     "        with self._lock:\n            fh.read(4096)\n"),
]


class TestBlockingUnderLock:
    @pytest.mark.parametrize("cat,bad,clean", W504_CATEGORY_CASES,
                             ids=[c[0] for c in W504_CATEGORY_CASES])
    def test_category_fires_and_clean_passes(self, cat, bad, clean):
        bad_src = _CLS + "    def m(self, u=None):\n" + bad
        clean_src = _CLS + "    def m(self, u=None):\n" + clean
        hits = _w504(bad_src)
        assert hits and all(f.rule == "W504" for f in hits), cat
        assert _w504(clean_src) == [], cat

    def test_two_hop_reachability_anchors_at_origin(self):
        src = (
            "import threading, time\n"
            "class C:\n"
            "    def __init__(self):\n"
            "        self._lock = threading.Lock()\n"
            "    def outer(self):\n"
            "        with self._lock:\n"
            "            self.mid()\n"
            "    def mid(self):\n"
            "        self.leaf()\n"
            "    def leaf(self):\n"
            "        time.sleep(5)\n")
        out = _w504(src)
        assert len(out) == 1
        f = out[0]
        assert f.line == 7  # the under-lock self.mid() call, not leaf
        assert "C.leaf" in f.message and "sleep" in f.message
        assert "call chain" in f.hint and "C.mid" in f.hint

    def test_holds_contract_counts_as_lock_held(self):
        src = (
            "import threading, time\n"
            "class C:\n"
            "    def __init__(self):\n"
            "        self._lock = threading.Lock()\n"
            "    def _flush(self):  # holds: _lock\n"
            "        time.sleep(1)\n")
        out = _w504(src)
        assert len(out) == 1 and "holds:" in out[0].message

    def test_lock_io_waiver_honored(self):
        src = _CLS + (
            "    def m(self):\n"
            "        with self._lock:\n"
            "            time.sleep(1)  "
            "# weedlint: lock-io audited: bounded bench-only pause\n")
        assert _w504(src) == []

    def test_lock_io_waiver_without_reason_is_flagged(self):
        src = _CLS + (
            "    def m(self):\n"
            "        with self._lock:\n"
            "            time.sleep(1)  # weedlint: lock-io\n")
        out = _w504(src)
        assert len(out) == 1 and "no reason" in out[0].message

    def test_thread_spawn_does_not_carry_lock(self):
        src = (
            "import threading, time\n"
            "class C:\n"
            "    def __init__(self):\n"
            "        self._lock = threading.Lock()\n"
            "    def start(self):\n"
            "        with self._lock:\n"
            "            threading.Thread(target=self._run).start()\n"
            "    def _run(self):\n"
            "        time.sleep(5)\n")
        assert _w504(src) == []


# --- engine: waivers, baseline, run -----------------------------------------

# --- W505: blocking reachable from event-loop callbacks ----------------------

class TestEventLoopRule:
    def _check(self, src):
        return check_eventloop(build_from_sources([("pkg/t.py", src)]))

    def test_disk_helper_category(self):
        hits = self._check(
            "import os\n"
            "class R:\n"
            "    def _flush(self, conn):  # loop-callback\n"
            "        os.pread(3, 10, 0)\n")
        assert hits and "disk" in hits[0].message

    def test_loop_io_waiver_honored_and_reasonless_flagged(self):
        waived = (
            "import time\n"
            "class R:\n"
            "    def _cb(self):  # loop-callback\n"
            "        time.sleep(1)  # weedlint: loop-io cache-probed,"
            " cannot block\n")
        assert self._check(waived) == []
        hits = self._check(waived.replace(
            " cache-probed, cannot block", ""))
        assert hits and "no reason" in hits[0].message

    def test_inner_loop_callback_not_rewalked_from_outer(self):
        # the blocking call inside _inner (its own loop-callback root)
        # anchors at _inner, not duplicated at _outer's call site
        src = (
            "import time\n"
            "class R:\n"
            "    def _outer(self):  # loop-callback\n"
            "        self._inner()\n"
            "    def _inner(self):  # loop-callback\n"
            "        time.sleep(1)\n")
        hits = self._check(src)
        assert len(hits) == 1 and "_inner" in hits[0].message

    def test_spawned_thread_target_is_off_loop(self):
        src = (
            "import time, threading\n"
            "class R:\n"
            "    def _cb(self):  # loop-callback\n"
            "        threading.Thread(target=self._work).start()\n"
            "    def _work(self):\n"
            "        time.sleep(1)\n")
        assert self._check(src) == []

    def test_shipped_eventloop_module_is_clean(self):
        res = engine.run(REPO, rule_ids=["W505"])
        assert [f for f in res.findings if f.rule == "W505"] == []
        # and the rule actually has roots to walk (the reactor methods
        # are marked) — an empty root set would make the clean run
        # vacuous
        import re as _re

        src = open(os.path.join(
            REPO, "seaweedfs_tpu", "utils", "eventloop.py"),
            encoding="utf-8").read()
        assert len(_re.findall(r"#\s*loop-callback", src)) >= 8


def _mini_repo(tmp_path, body: str) -> str:
    """A throwaway repo: one package module + empty baseline."""
    pkg = tmp_path / "seaweedfs_tpu"
    pkg.mkdir()
    (pkg / "__init__.py").write_text("")
    (pkg / "mod.py").write_text(body)
    (tmp_path / "tools").mkdir()
    (tmp_path / "tools" / "weedlint_baseline.json").write_text(
        '{"version": 1, "findings": {}}')
    return str(tmp_path)


# rules that judge a tiny synthetic tree on its own terms (no live
# package import, no this-repo-specific file contracts)
FAST_RULES = ["W101", "W501", "W502", "W601", "W801"]


class TestEngine:
    def test_waiver_suppresses_with_reason(self, tmp_path):
        body = (
            "import threading\n"
            "class C:\n"
            "    def __init__(self):\n"
            "        self.hits = 0\n"
            "        self._t = threading.Thread(target=self._loop)\n"
            "    def _loop(self):\n"
            "        self.hits += 1  "
            "# weedlint: disable=W502 single scan thread owns it\n")
        root = _mini_repo(tmp_path, body)
        res = engine.run(root, rule_ids=FAST_RULES)
        assert [f.rule for f in res.findings] == []
        assert len(res.waived) == 1

    def test_waiver_without_reason_is_flagged(self, tmp_path):
        body = (
            "import threading\n"
            "class C:\n"
            "    def __init__(self):\n"
            "        self.hits = 0\n"
            "        self._t = threading.Thread(target=self._loop)\n"
            "    def _loop(self):\n"
            "        self.hits += 1  # weedlint: disable=W502\n")
        root = _mini_repo(tmp_path, body)
        res = engine.run(root)
        assert any(f.rule == "W001" and "no reason" in f.message
                   for f in res.findings)

    def test_stale_waiver_is_flagged(self, tmp_path):
        body = "x = 1  # weedlint: disable=W801 leftover excuse\n"
        root = _mini_repo(tmp_path, body)
        res = engine.run(root)
        assert any(f.rule == "W001" and "stale waiver" in f.message
                   for f in res.findings)

    def test_docstring_quoting_waiver_syntax_is_not_a_waiver(self,
                                                            tmp_path):
        body = ('"""Docs: waive with  # weedlint: disable=W501 why"""\n'
                "x = 1\n")
        root = _mini_repo(tmp_path, body)
        res = engine.run(root)
        assert [f for f in res.findings if f.rule == "W001"] == []

    def test_baseline_grandfathers_exact_count(self, tmp_path):
        body = (
            "import threading\n"
            "class C:\n"
            "    def __init__(self):\n"
            "        self.hits = 0\n"
            "        self._t = threading.Thread(target=self._loop)\n"
            "    def _loop(self):\n"
            "        self.hits += 1\n"
            "        self.hits += 2\n")
        root = _mini_repo(tmp_path, body)
        res = engine.run(root, rule_ids=FAST_RULES)
        assert len(res.findings) == 2
        bl = str(tmp_path / "bl.json")
        engine.save_baseline(bl, res.findings)
        res2 = engine.run(root, rule_ids=FAST_RULES, baseline_path=bl)
        assert res2.findings == [] and len(res2.baselined) == 2
        # a THIRD identical violation exceeds the grandfathered count
        mod = tmp_path / "seaweedfs_tpu" / "mod.py"
        mod.write_text(mod.read_text() + "        self.hits += 3\n")
        res3 = engine.run(root, rule_ids=FAST_RULES, baseline_path=bl)
        assert len(res3.findings) == 1

    def test_unknown_rule_id_raises(self, tmp_path):
        root = _mini_repo(tmp_path, "x = 1\n")
        with pytest.raises(KeyError):
            engine.run(root, rule_ids=["W999"])

    def test_json_output_schema(self, tmp_path):
        root = _mini_repo(tmp_path, "import tomllib\n")
        p = subprocess.run(
            [sys.executable, "-m", "tools.weedlint", "--json",
             "--rule", "W101", root],
            capture_output=True, text=True, cwd=REPO, timeout=120)
        assert p.returncode == 1
        doc = json.loads(p.stdout)
        assert doc["version"] == 1
        assert doc["rules"] == ["W101"]
        assert doc["counts"]["reported"] == len(doc["findings"]) == 1
        f = doc["findings"][0]
        assert set(f) >= {"rule", "path", "line", "message",
                          "fingerprint"}
        assert f["path"].endswith("mod.py")

    def test_cli_list_rules(self):
        p = subprocess.run(
            [sys.executable, "-m", "tools.weedlint", "--list-rules"],
            capture_output=True, text=True, cwd=REPO, timeout=120)
        assert p.returncode == 0
        for rid in ("W101", "W201", "W301", "W401", "W501", "W502",
                    "W601", "W701", "W801", "W901"):
            assert rid in p.stdout

    def test_cli_unknown_rule_exits_2(self):
        p = subprocess.run(
            [sys.executable, "-m", "tools.weedlint", "--rule", "W999"],
            capture_output=True, text=True, cwd=REPO, timeout=120)
        assert p.returncode == 2

    def test_json_carries_callgraph_stats_for_interprocedural_rules(
            self, tmp_path):
        root = _mini_repo(tmp_path, "x = 1\n")
        p = subprocess.run(
            [sys.executable, "-m", "tools.weedlint", "--json",
             "--rule", "W504", root],
            capture_output=True, text=True, cwd=REPO, timeout=120)
        doc = json.loads(p.stdout)
        s = doc["callgraph_stats"]
        assert set(s) >= {"nodes", "edges", "calls_total",
                          "calls_unresolved", "unresolved_ratio"}

    def test_changed_only_scopes_reporting_to_changed_files(self,
                                                            tmp_path):
        violation = (
            "import threading\n"
            "class C{n}:\n"
            "    def __init__(self):\n"
            "        self.hits = 0\n"
            "        self._t = threading.Thread(target=self._loop)\n"
            "    def _loop(self):\n"
            "        self.hits += 1\n")
        root = _mini_repo(tmp_path, violation.format(n=1))
        g = ["git", "-C", root, "-c", "user.email=t@t", "-c",
             "user.name=t"]
        subprocess.run(g + ["init", "-q"], check=True, timeout=60)
        subprocess.run(g + ["add", "-A"], check=True, timeout=60)
        subprocess.run(g + ["commit", "-qm", "seed"], check=True,
                       timeout=60)
        # a NEW (untracked) file with the same violation
        (tmp_path / "seaweedfs_tpu" / "newmod.py").write_text(
            violation.format(n=2))
        p = subprocess.run(
            [sys.executable, "-m", "tools.weedlint", "--changed-only",
             "HEAD", "--rule", "W502", root],
            capture_output=True, text=True, cwd=REPO, timeout=120)
        assert p.returncode == 1
        assert "newmod.py" in p.stdout
        assert "/mod.py:" not in p.stdout  # committed file not reported
        assert "changed vs HEAD only" in p.stderr

    def test_changed_only_works_from_a_git_subdirectory(self, tmp_path):
        """The lint root nested below the git toplevel: git diff must
        emit ROOT-relative paths (--relative) or every finding would be
        silently filtered away and the fast path would pass real
        regressions."""
        sub = tmp_path / "sub"
        sub.mkdir()
        root = _mini_repo(sub, "x = 1\n")
        g = ["git", "-C", str(tmp_path), "-c", "user.email=t@t", "-c",
             "user.name=t"]
        subprocess.run(g + ["init", "-q"], check=True, timeout=60)
        subprocess.run(g + ["add", "-A"], check=True, timeout=60)
        subprocess.run(g + ["commit", "-qm", "seed"], check=True,
                       timeout=60)
        # a TRACKED file modified with a violation, under the subdir
        (sub / "seaweedfs_tpu" / "mod.py").write_text(
            "import threading\n"
            "class C:\n"
            "    def __init__(self):\n"
            "        self.hits = 0\n"
            "        self._t = threading.Thread(target=self._loop)\n"
            "    def _loop(self):\n"
            "        self.hits += 1\n")
        p = subprocess.run(
            [sys.executable, "-m", "tools.weedlint", "--changed-only",
             "HEAD", "--rule", "W502", root],
            capture_output=True, text=True, cwd=REPO, timeout=120)
        assert p.returncode == 1, p.stdout + p.stderr
        assert "mod.py" in p.stdout

    def test_update_baseline_rejects_changed_only(self, tmp_path):
        """A baseline regenerated from a filtered finding set would
        delete every other grandfathered entry — refuse the combo."""
        root = _mini_repo(tmp_path, "x = 1\n")
        p = subprocess.run(
            [sys.executable, "-m", "tools.weedlint",
             "--update-baseline", "--changed-only", "HEAD", root],
            capture_output=True, text=True, cwd=REPO, timeout=120)
        assert p.returncode == 2
        assert "cannot be combined" in p.stderr


# --- the repo-wide tier-1 gate ----------------------------------------------

class TestWholeRepo:
    def test_repo_is_clean_modulo_baseline(self):
        """THE gate: every rule over the whole repo, zero findings
        beyond waivers and the committed baseline."""
        res = engine.run(REPO)
        assert res.findings == [], "\n".join(
            f.render() for f in res.findings)

    def test_baseline_carries_only_grandfathered_lockset_findings(self):
        """The committed baseline is a W502 grandfather list for
        pre-weedlint modules — route-param, resource and registry
        findings were all FIXED, not baselined, and new-rule findings
        must never be added here (fix or waive instead)."""
        with open(os.path.join(REPO, "tools",
                               "weedlint_baseline.json")) as f:
            doc = json.load(f)
        kinds = {e["rule"] for e in doc["findings"].values()}
        assert kinds <= {"W502"}, kinds

    def test_callgraph_resolution_stays_healthy(self):
        """A resolution regression silently blinds W503/W504, so the
        repo-wide unresolved ratio is pinned (recorded bound: 0.50 —
        currently ~0.42; raise the bound only with an explanation of
        what got less resolvable)."""
        res = engine.run(REPO, rule_ids=["W503", "W504"])
        s = res.callgraph_stats
        assert s is not None
        assert s["nodes"] > 1000 and s["edges"] > 1500
        assert s["unresolved_ratio"] <= 0.50, s

    def test_baseline_is_empty_after_the_w502_burn_down(self):
        """PR 11 burned the 37-entry W502 grandfather list down to
        zero: every finding is now fixed or carries a reasoned waiver.
        Nothing must ever be baselined again — fix it or waive it."""
        with open(os.path.join(REPO, "tools",
                               "weedlint_baseline.json")) as f:
            doc = json.load(f)
        assert doc["findings"] == {}

    def test_shell_fault_list_prints_registry(self):
        from seaweedfs_tpu.shell.commands import COMMANDS
        from seaweedfs_tpu.utils import faultinject as fi

        out = COMMANDS["fault.list"](None, {})
        for name in fi.FAULT_POINTS:
            assert name in out
        doc = json.loads(COMMANDS["fault.list"](None, {"json": "true"}))
        assert doc == dict(fi.list_points())
