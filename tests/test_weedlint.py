"""weedlint (tools/weedlint) as THE tier-1 static-analysis gate.

One engine now carries every repo lint: the four ported rules
(W101 py310 / W201 tracing / W301 async-drain / W401 health-keys), the
lockset thread-safety checker (W501/W502), and the route-param (W601),
fault-registry (W701) and ec-resource (W801) rules.  This suite:

  - proves EVERY rule fires on a planted violation and stays quiet on
    the matching clean source (parametrized, one case per rule);
  - unit-tests the lockset checker on synthetic classes (guarded-ok,
    unguarded-read, waived, stale-waiver, two-lock, holds-contract);
  - pins the engine machinery (waivers, baseline, JSON output, CLI);
  - asserts the REPO-WIDE run is clean modulo the committed baseline —
    the regression gate that replaces four per-lint whole-repo tests.
"""

from __future__ import annotations

import json
import os
import subprocess
import sys

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if REPO not in sys.path:
    sys.path.insert(0, REPO)

from tools.weedlint import engine  # noqa: E402
from tools.weedlint import rules_py310, rules_tracing  # noqa: E402
from tools.weedlint.rules_async_drain import \
    check_drain_fault_source  # noqa: E402
from tools.weedlint.rules_faults import (check_registry,  # noqa: E402
                                         hit_sites, load_registry)
from tools.weedlint.rules_lockset import check_class_source  # noqa: E402
from tools.weedlint.rules_resources import \
    check_module_source as check_resources  # noqa: E402
from tools.weedlint.rules_routes import \
    check_module_source as check_routes  # noqa: E402

# --- planted sources, one clean/bad pair per single-module rule -------------

W301_CLEAN = (
    "def f():\n"
    "    with tr.span('pipeline.drain'):\n"
    "        faultinject.hit('ec.drain')\n")
W301_BAD = (
    "def f():\n"
    "    faultinject.hit('ec.drain')\n")

W501_CLEAN = (
    "import threading\n"
    "class C:\n"
    "    def __init__(self):\n"
    "        self._lock = threading.Lock()\n"
    "        self._n = 0  # guarded-by: _lock\n"
    "        self._t = threading.Thread(target=self._loop)\n"
    "    def _loop(self):\n"
    "        with self._lock:\n"
    "            self._n += 1\n"
    "    def read(self):\n"
    "        with self._lock:\n"
    "            return self._n\n")
W501_BAD = W501_CLEAN.replace(
    "    def read(self):\n"
    "        with self._lock:\n"
    "            return self._n\n",
    "    def read(self):\n"
    "        return self._n\n")

W502_CLEAN = W501_CLEAN
W502_BAD = (
    "import threading\n"
    "class C:\n"
    "    def __init__(self):\n"
    "        self.hits = 0\n"
    "        self._t = threading.Thread(target=self._loop)\n"
    "    def _loop(self):\n"
    "        self.hits += 1\n")

W601_CLEAN = (
    "def install(router):\n"
    "    @router.route('GET', '/x')\n"
    "    def handler(req):\n"
    "        try:\n"
    "            limit = int(req.query.get('limit') or 0)\n"
    "        except ValueError:\n"
    "            raise HttpError(400, 'bad limit')\n"
    "        return limit\n")
W601_BAD = (
    "def install(router):\n"
    "    @router.route('GET', '/x')\n"
    "    def handler(req):\n"
    "        return int(req.query.get('limit') or 0)\n")

W801_CLEAN = (
    "def f(path):\n"
    "    with open(path, 'rb') as fh:\n"
    "        return fh.read()\n")
W801_BAD = (
    "def f(path):\n"
    "    fh = open(path, 'rb')\n"
    "    return fh.read()\n")

CASES = [
    ("W101", "x = 1\n", "import tomllib\n",
     lambda src: rules_py310.check_source(src, "t.py")),
    ("W201", "import urllib.parse\n", "import urllib.request\n",
     lambda src: rules_tracing.check_package_source(src, "pkg/t.py")),
    ("W301", W301_CLEAN, W301_BAD,
     lambda src: check_drain_fault_source(src, "t.py")),
    ("W501", W501_CLEAN, W501_BAD,
     lambda src: check_class_source(src, "t.py")),
    ("W502", W502_CLEAN, W502_BAD,
     lambda src: check_class_source(src, "t.py")),
    ("W601", W601_CLEAN, W601_BAD,
     lambda src: check_routes(src, "t.py")),
    ("W801", W801_CLEAN, W801_BAD,
     lambda src: check_resources(src, "t.py")),
]


@pytest.mark.parametrize("rule_id,clean,bad,checker", CASES,
                         ids=[c[0] for c in CASES])
def test_rule_fires_on_planted_violation_and_passes_clean(
        rule_id, clean, bad, checker):
    assert [f for f in checker(clean) if f.rule == rule_id] == [], rule_id
    hits = [f for f in checker(bad) if f.rule == rule_id]
    assert hits, f"{rule_id} did not fire on its planted violation"
    assert all(f.line > 0 for f in hits)


# --- W701: fault-registry consistency (tables as arguments) -----------------

class TestFaultRegistry:
    REG = {"a.b": 3, "c.d": 4}

    def test_consistent_tables_pass(self):
        sites = [("a.b", 10, "m.py"), ("c.d", 11, "m.py")]
        assert check_registry(self.REG, 1, sites, '"a.b" "c.d"') == []

    def test_unregistered_site_caught(self):
        sites = [("a.b", 10, "m.py"), ("c.d", 11, "m.py"),
                 ("typo.name", 12, "m.py")]
        out = check_registry(self.REG, 1, sites, '"a.b" "c.d"')
        assert any("typo.name" in f.message and f.path == "m.py"
                   for f in out)

    def test_registered_without_site_caught(self):
        out = check_registry(self.REG, 1, [("a.b", 10, "m.py")],
                             '"a.b" "c.d"')
        assert any("c.d" in f.message and "never inject" in f.message
                   for f in out)

    def test_untested_point_caught(self):
        sites = [("a.b", 10, "m.py"), ("c.d", 11, "m.py")]
        out = check_registry(self.REG, 1, sites, '"a.b" only')
        assert any("c.d" in f.message and "not exercised" in f.message
                   for f in out)

    def test_live_registry_parses_and_matches_sites(self):
        fi_path = os.path.join(REPO, "seaweedfs_tpu", "utils",
                               "faultinject.py")
        with open(fi_path, encoding="utf-8") as f:
            src = f.read()
        registry, _line = load_registry(src)
        assert "ec.drain" in registry and "ec.shard.corrupt" in registry
        # the module's own hit() implementation is not a SITE
        assert all(n for n, _ln in hit_sites(src, fi_path))


# --- lockset checker on synthetic classes -----------------------------------

class TestLockset:
    def test_two_lock_class_wrong_lock_caught(self):
        src = (
            "import threading\n"
            "class C:\n"
            "    def __init__(self):\n"
            "        self._a_lock = threading.Lock()\n"
            "        self._b_lock = threading.Lock()\n"
            "        self._a = 0  # guarded-by: _a_lock\n"
            "        self._b = 0  # guarded-by: _b_lock\n"
            "        self._t = threading.Thread(target=self._loop)\n"
            "    def _loop(self):\n"
            "        with self._a_lock:\n"
            "            self._a += 1\n"
            "            self._b += 1\n"  # wrong lock held
            "    def read(self):\n"
            "        with self._b_lock:\n"
            "            return self._b\n"
            "        with self._a_lock:\n"
            "            return self._a\n")
        out = [f for f in check_class_source(src, "t.py")
               if f.rule == "W501"]
        assert len(out) == 1 and "self._b" in out[0].message \
            and "_b_lock" in out[0].message

    def test_holds_annotation_honored(self):
        src = (
            "import threading\n"
            "class C:\n"
            "    def __init__(self):\n"
            "        self._lock = threading.Lock()\n"
            "        self._n = 0  # guarded-by: _lock\n"
            "        self._t = threading.Thread(target=self._loop)\n"
            "    def _loop(self):\n"
            "        with self._lock:\n"
            "            self._bump()\n"
            "    def _bump(self):  # holds: _lock\n"
            "        self._n += 1\n"
            "    def bump(self):\n"
            "        with self._lock:\n"
            "            self._bump()\n")
        assert check_class_source(src, "t.py") == []

    def test_locked_suffix_honored(self):
        src = (
            "import threading\n"
            "class C:\n"
            "    def __init__(self):\n"
            "        self._lock = threading.Lock()\n"
            "        self._n = 0  # guarded-by: _lock\n"
            "        self._t = threading.Thread(target=self._loop)\n"
            "    def _loop(self):\n"
            "        with self._lock:\n"
            "            self._bump_locked()\n"
            "    def _bump_locked(self):\n"
            "        self._n += 1\n"
            "    def bump(self):\n"
            "        with self._lock:\n"
            "            self._bump_locked()\n")
        assert check_class_source(src, "t.py") == []

    def test_thread_entry_annotation_creates_root(self):
        # no lexical Thread() construction: the annotation alone must
        # make the hook method a root so the naked access is caught
        src = (
            "import threading\n"
            "class C:\n"
            "    def __init__(self):\n"
            "        self._lock = threading.Lock()\n"
            "        self._n = 0  # guarded-by: _lock\n"
            "    def on_event(self, ev):  # thread-entry\n"
            "        self._n += 1\n")
        out = check_class_source(src, "t.py")
        assert any(f.rule == "W501" and "on_event" in f.message
                   for f in out)

    def test_concurrent_class_marks_public_methods_as_roots(self):
        src = (
            "import threading\n"
            "class C:  # weedlint: concurrent-class\n"
            "    def __init__(self):\n"
            "        self._lock = threading.Lock()\n"
            "        self._n = 0  # guarded-by: _lock\n"
            "    def bump(self):\n"
            "        self._n += 1\n"
            "    def read(self):\n"
            "        return self._n\n")
        out = [f for f in check_class_source(src, "t.py")
               if f.rule == "W501"]
        assert len(out) == 2  # both naked accesses race each other

    def test_closure_does_not_inherit_lock(self):
        # a nested function may run on another thread after the with
        # released the lock: the access inside it counts as unlocked
        src = (
            "import threading\n"
            "class C:\n"
            "    def __init__(self):\n"
            "        self._lock = threading.Lock()\n"
            "        self._n = 0  # guarded-by: _lock\n"
            "        self._t = threading.Thread(target=self._loop)\n"
            "    def _loop(self):\n"
            "        with self._lock:\n"
            "            self._n += 1\n"
            "    def make(self):\n"
            "        with self._lock:\n"
            "            def cb():\n"
            "                return self._n\n"
            "            return cb\n")
        out = [f for f in check_class_source(src, "t.py")
               if f.rule == "W501"]
        assert len(out) == 1 and out[0].rule == "W501"

    def test_init_is_exempt(self):
        src = (
            "import threading\n"
            "class C:\n"
            "    def __init__(self):\n"
            "        self._lock = threading.Lock()\n"
            "        self._n = 0  # guarded-by: _lock\n"
            "        self._n = 1\n"  # naked in __init__: fine
            "        self._t = threading.Thread(target=self._loop)\n"
            "    def _loop(self):\n"
            "        with self._lock:\n"
            "            self._n += 1\n")
        assert check_class_source(src, "t.py") == []


# --- engine: waivers, baseline, run -----------------------------------------

def _mini_repo(tmp_path, body: str) -> str:
    """A throwaway repo: one package module + empty baseline."""
    pkg = tmp_path / "seaweedfs_tpu"
    pkg.mkdir()
    (pkg / "__init__.py").write_text("")
    (pkg / "mod.py").write_text(body)
    (tmp_path / "tools").mkdir()
    (tmp_path / "tools" / "weedlint_baseline.json").write_text(
        '{"version": 1, "findings": {}}')
    return str(tmp_path)


# rules that judge a tiny synthetic tree on its own terms (no live
# package import, no this-repo-specific file contracts)
FAST_RULES = ["W101", "W501", "W502", "W601", "W801"]


class TestEngine:
    def test_waiver_suppresses_with_reason(self, tmp_path):
        body = (
            "import threading\n"
            "class C:\n"
            "    def __init__(self):\n"
            "        self.hits = 0\n"
            "        self._t = threading.Thread(target=self._loop)\n"
            "    def _loop(self):\n"
            "        self.hits += 1  "
            "# weedlint: disable=W502 single scan thread owns it\n")
        root = _mini_repo(tmp_path, body)
        res = engine.run(root, rule_ids=FAST_RULES)
        assert [f.rule for f in res.findings] == []
        assert len(res.waived) == 1

    def test_waiver_without_reason_is_flagged(self, tmp_path):
        body = (
            "import threading\n"
            "class C:\n"
            "    def __init__(self):\n"
            "        self.hits = 0\n"
            "        self._t = threading.Thread(target=self._loop)\n"
            "    def _loop(self):\n"
            "        self.hits += 1  # weedlint: disable=W502\n")
        root = _mini_repo(tmp_path, body)
        res = engine.run(root)
        assert any(f.rule == "W001" and "no reason" in f.message
                   for f in res.findings)

    def test_stale_waiver_is_flagged(self, tmp_path):
        body = "x = 1  # weedlint: disable=W801 leftover excuse\n"
        root = _mini_repo(tmp_path, body)
        res = engine.run(root)
        assert any(f.rule == "W001" and "stale waiver" in f.message
                   for f in res.findings)

    def test_docstring_quoting_waiver_syntax_is_not_a_waiver(self,
                                                            tmp_path):
        body = ('"""Docs: waive with  # weedlint: disable=W501 why"""\n'
                "x = 1\n")
        root = _mini_repo(tmp_path, body)
        res = engine.run(root)
        assert [f for f in res.findings if f.rule == "W001"] == []

    def test_baseline_grandfathers_exact_count(self, tmp_path):
        body = (
            "import threading\n"
            "class C:\n"
            "    def __init__(self):\n"
            "        self.hits = 0\n"
            "        self._t = threading.Thread(target=self._loop)\n"
            "    def _loop(self):\n"
            "        self.hits += 1\n"
            "        self.hits += 2\n")
        root = _mini_repo(tmp_path, body)
        res = engine.run(root, rule_ids=FAST_RULES)
        assert len(res.findings) == 2
        bl = str(tmp_path / "bl.json")
        engine.save_baseline(bl, res.findings)
        res2 = engine.run(root, rule_ids=FAST_RULES, baseline_path=bl)
        assert res2.findings == [] and len(res2.baselined) == 2
        # a THIRD identical violation exceeds the grandfathered count
        mod = tmp_path / "seaweedfs_tpu" / "mod.py"
        mod.write_text(mod.read_text() + "        self.hits += 3\n")
        res3 = engine.run(root, rule_ids=FAST_RULES, baseline_path=bl)
        assert len(res3.findings) == 1

    def test_unknown_rule_id_raises(self, tmp_path):
        root = _mini_repo(tmp_path, "x = 1\n")
        with pytest.raises(KeyError):
            engine.run(root, rule_ids=["W999"])

    def test_json_output_schema(self, tmp_path):
        root = _mini_repo(tmp_path, "import tomllib\n")
        p = subprocess.run(
            [sys.executable, "-m", "tools.weedlint", "--json",
             "--rule", "W101", root],
            capture_output=True, text=True, cwd=REPO, timeout=120)
        assert p.returncode == 1
        doc = json.loads(p.stdout)
        assert doc["version"] == 1
        assert doc["rules"] == ["W101"]
        assert doc["counts"]["reported"] == len(doc["findings"]) == 1
        f = doc["findings"][0]
        assert set(f) >= {"rule", "path", "line", "message",
                          "fingerprint"}
        assert f["path"].endswith("mod.py")

    def test_cli_list_rules(self):
        p = subprocess.run(
            [sys.executable, "-m", "tools.weedlint", "--list-rules"],
            capture_output=True, text=True, cwd=REPO, timeout=120)
        assert p.returncode == 0
        for rid in ("W101", "W201", "W301", "W401", "W501", "W502",
                    "W601", "W701", "W801"):
            assert rid in p.stdout

    def test_cli_unknown_rule_exits_2(self):
        p = subprocess.run(
            [sys.executable, "-m", "tools.weedlint", "--rule", "W999"],
            capture_output=True, text=True, cwd=REPO, timeout=120)
        assert p.returncode == 2


# --- the repo-wide tier-1 gate ----------------------------------------------

class TestWholeRepo:
    def test_repo_is_clean_modulo_baseline(self):
        """THE gate: every rule over the whole repo, zero findings
        beyond waivers and the committed baseline."""
        res = engine.run(REPO)
        assert res.findings == [], "\n".join(
            f.render() for f in res.findings)

    def test_baseline_carries_only_grandfathered_lockset_findings(self):
        """The committed baseline is a W502 grandfather list for
        pre-weedlint modules — route-param, resource and registry
        findings were all FIXED, not baselined, and new-rule findings
        must never be added here (fix or waive instead)."""
        with open(os.path.join(REPO, "tools",
                               "weedlint_baseline.json")) as f:
            doc = json.load(f)
        kinds = {e["rule"] for e in doc["findings"].values()}
        assert kinds <= {"W502"}, kinds

    def test_shell_fault_list_prints_registry(self):
        from seaweedfs_tpu.shell.commands import COMMANDS
        from seaweedfs_tpu.utils import faultinject as fi

        out = COMMANDS["fault.list"](None, {})
        for name in fi.FAULT_POINTS:
            assert name in out
        doc = json.loads(COMMANDS["fault.list"](None, {"json": "true"}))
        assert doc == dict(fi.list_points())
