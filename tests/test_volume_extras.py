"""Volume server extras: tiering (.vif + backends), tail/incremental
backup, and the query engine.

Covers weed/storage/backend (tiered .dat), volume_info (.vif sidecar),
volume_backup.go (BinarySearchByAppendAtNs + IncrementalBackup), and
weed/query (Query RPC semantics).
"""

import json
import os
import time

import pytest

from seaweedfs_tpu.storage.backend import (DirBackendStorage, get_backend,
                                           register_backend)
from seaweedfs_tpu.storage.needle import Needle
from seaweedfs_tpu.storage.volume import Volume
from seaweedfs_tpu.storage.volume_backup import (apply_records,
                                                 incremental_backup,
                                                 last_appended_ns,
                                                 records_since)
from seaweedfs_tpu.storage.volume_info import maybe_load_volume_info


def fill(v: Volume, n: int, start: int = 1, prefix: bytes = b"data-"):
    for i in range(start, start + n):
        v.write_needle(Needle(cookie=0xABC0 + i, id=i,
                              data=prefix + str(i).encode()))


class TestTiering:
    def test_tier_upload_read_download(self, tmp_path):
        register_backend(DirBackendStorage("cloud1", str(tmp_path / "cloud")))
        v = Volume(str(tmp_path / "v"), "", 7)
        fill(v, 20)
        assert not v.tiered
        remote = v.tier_upload("cloud1")
        # local .dat gone, .vif present, volume reopened tiered + read-only
        assert not os.path.exists(v.dat_path)
        assert maybe_load_volume_info(v.file_prefix).remote_file.key == \
            remote["key"]
        assert v.tiered and v.read_only
        # reads go through ranged requests against the backend
        n = v.read_needle(5)
        assert n.data == b"data-5"
        # writes refuse
        with pytest.raises(PermissionError):
            v.write_needle(Needle(cookie=1, id=99, data=b"x"))
        # a fresh open (new process) also comes up tiered
        v.close()
        v2 = Volume(str(tmp_path / "v"), "", 7)
        assert v2.tiered
        assert v2.read_needle(17).data == b"data-17"
        # bring it back local
        v2.tier_download()
        assert not v2.tiered and os.path.exists(v2.dat_path)
        assert v2.read_needle(5).data == b"data-5"
        v2.write_needle(Needle(cookie=1, id=99, data=b"writable again"))
        v2.close()

    def test_double_tier_upload_rejected(self, tmp_path):
        register_backend(DirBackendStorage("cloud2", str(tmp_path / "c2")))
        v = Volume(str(tmp_path / "v"), "", 8)
        fill(v, 3)
        v.tier_upload("cloud2")
        with pytest.raises(PermissionError):
            v.tier_upload("cloud2")
        v.close()

    def test_unknown_backend(self, tmp_path):
        v = Volume(str(tmp_path / "v"), "", 9)
        fill(v, 1)
        with pytest.raises(KeyError):
            v.tier_upload("nope")
        v.close()


class TestTail:
    def test_records_since_and_binary_search(self, tmp_path):
        v = Volume(str(tmp_path / "v"), "", 3)
        fill(v, 10)
        t_mid = time.time_ns()
        time.sleep(0.002)
        fill(v, 5, start=11)
        blob, last_ts = records_since(v, t_mid)
        follower = Volume(str(tmp_path / "f"), "", 3)
        assert apply_records(follower, blob) == 5
        for i in range(11, 16):
            assert follower.read_needle(i).data == v.read_needle(i).data
        with pytest.raises(KeyError):
            follower.read_needle(1)  # older records not shipped
        assert last_ts == v.last_append_at_ns
        # nothing newer -> empty
        blob2, _ = records_since(v, last_ts)
        assert blob2 == b""
        v.close()
        follower.close()

    def test_incremental_backup_with_deletes_and_resume(self, tmp_path):
        v = Volume(str(tmp_path / "v"), "", 4)
        follower = Volume(str(tmp_path / "f"), "", 4)

        def fetch(since_ns):
            return records_since(v, since_ns)

        fill(v, 8)
        assert incremental_backup(follower, fetch) == 8
        v.delete_needle(Needle(id=3))
        fill(v, 2, start=9)
        # reopen follower (fresh process): resume point derived from idx
        follower.close()
        follower = Volume(str(tmp_path / "f"), "", 4)
        assert last_appended_ns(follower) > 0
        assert incremental_backup(follower, fetch) == 3
        with pytest.raises(KeyError):
            follower.read_needle(3)  # tombstone replayed
        assert follower.read_needle(10).data == b"data-10"
        v.close()
        follower.close()


class TestQuery:
    def test_json_select_where(self):
        from seaweedfs_tpu.query import execute_query

        data = json.dumps([
            {"name": "a", "meta": {"size": 10}},
            {"name": "b", "meta": {"size": 25}},
            {"name": "c", "meta": {"size": 31}},
        ]).encode()
        rows = execute_query(data, select=["name"],
                             filt={"field": "meta.size", "operand": ">",
                                   "value": 20})
        assert rows == [{"name": "b"}, {"name": "c"}]

    def test_jsonl_and_prefix(self):
        from seaweedfs_tpu.query import execute_query

        data = b'{"k": "apple"}\n{"k": "apricot"}\n{"k": "banana"}\n'
        rows = execute_query(data, filt={"field": "k", "operand": "prefix",
                                         "value": "ap"},
                             input_format="jsonl")
        assert [r["k"] for r in rows] == ["apple", "apricot"]

    def test_csv(self):
        from seaweedfs_tpu.query import execute_query

        data = b"name,qty\nbolt,4\nnut,9\n"
        rows = execute_query(data, select=["qty"],
                             filt={"field": "name", "operand": "=",
                                   "value": "nut"},
                             input_format="csv")
        assert rows == [{"qty": "9"}]

    def test_query_endpoint(self, tmp_path):
        import time as _t

        from seaweedfs_tpu.client.operation import WeedClient
        from seaweedfs_tpu.master.server import MasterServer
        from seaweedfs_tpu.utils.httpd import http_json
        from seaweedfs_tpu.volume_server.server import VolumeServer
        from tests.conftest import free_port

        m = MasterServer(port=free_port()).start()
        vs = VolumeServer([str(tmp_path / "v")], m.url,
                          port=free_port()).start()
        try:
            deadline = _t.time() + 5
            while _t.time() < deadline:
                if http_json("GET", f"http://{m.url}/dir/status")[
                        "Topology"]["Max"] > 0:
                    break
                _t.sleep(0.05)
            c = WeedClient(m.url)
            fid = c.upload(json.dumps(
                {"user": "zoe", "score": 41}).encode())
            r = http_json("POST", f"http://{vs.url}/query", {
                "from_file_ids": [fid],
                "selections": ["user"],
                "filter": {"field": "score", "operand": ">=", "value": 40},
            })
            assert r["rows"] == [{"user": "zoe"}]
        finally:
            vs.stop()
            m.stop()


class TestTierEndpoint:
    def test_tier_upload_download_via_http(self, tmp_path):
        import time as _t

        from seaweedfs_tpu.client.operation import WeedClient
        from seaweedfs_tpu.master.server import MasterServer
        from seaweedfs_tpu.utils.httpd import http_json
        from seaweedfs_tpu.volume_server.server import VolumeServer
        from tests.conftest import free_port

        m = MasterServer(port=free_port()).start()
        vs = VolumeServer(
            [str(tmp_path / "v")], m.url, port=free_port(),
            backends={"cloudX": {"type": "dir",
                                 "root": str(tmp_path / "remote")}}).start()
        try:
            deadline = _t.time() + 5
            while _t.time() < deadline:
                if http_json("GET", f"http://{m.url}/dir/status")[
                        "Topology"]["Max"] > 0:
                    break
                _t.sleep(0.05)
            c = WeedClient(m.url)
            fid = c.upload(b"tier me out")
            vid = int(fid.split(",")[0])
            r = http_json("POST", f"http://{vs.url}/admin/tier_upload",
                          {"volume_id": vid, "backend": "cloudX"})
            assert r["remote"]["backend_id"] == "cloudX"
            # reads still served (through the backend)
            assert c.download(fid) == b"tier me out"
            http_json("POST", f"http://{vs.url}/admin/tier_download",
                      {"volume_id": vid})
            assert c.download(fid) == b"tier me out"
        finally:
            vs.stop()
            m.stop()


def test_reopened_volume_reports_file_age_not_zero(tmp_path):
    """A freshly-loaded volume's last-modified is the .dat file's mtime
    (volume_loading.go:63), never 0 — a zero would read as "infinitely
    quiet" to ec.encode's quietFor guard and TTL expiry after every
    restart."""
    import os
    import time

    from seaweedfs_tpu.storage.needle import Needle
    from seaweedfs_tpu.storage.volume import Volume

    v = Volume(str(tmp_path), "", 31)
    v.write_needle(Needle(cookie=1, id=1, data=b"aging"))
    v.close()
    old = time.time() - 3000
    os.utime(tmp_path / "31.dat", (old, old))
    v2 = Volume(str(tmp_path), "", 31)
    try:
        assert abs(v2.last_modified_ts_seconds - old) < 5
        # and a new write advances it again
        n = Needle(cookie=1, id=2, data=b"fresh")
        n.last_modified = int(time.time())
        v2.write_needle(n)
        assert v2.last_modified_ts_seconds >= int(time.time()) - 5
    finally:
        v2.close()
