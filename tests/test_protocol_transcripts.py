"""Canonical byte-level transcripts for the wire-protocol clients.

Round-3 verdict: every wire store was validated only against doubles
written by the same author — "consistent with my own assumptions".
These tests pin the clients to bytes that did NOT originate here:

- SCRAM-SHA-256: the RFC 7677 §3 worked example, replayed verbatim
  through the pg client's extracted derivation (same function the
  socket path calls) — proof and server signature must match the RFC's
  published base64 exactly.
- BSON: the two worked examples published on bsonspec.org ("hello
  world" and the awesome/5.05/1986 array), byte-for-byte against
  bson_lite in both directions.
- MongoDB OP_MSG: the client's frame for a known command must equal a
  hand-assembled frame built ONLY from the MongoDB wire-protocol doc
  (msgHeader layout, opcode 2013, flagBits, kind-0 section).
- CQL v4: the client's STARTUP and QUERY frames must equal frames
  hand-assembled from the CQL binary protocol v4 spec (§2 frame
  header, §4.1.1 STARTUP string map, §4.1.4 QUERY body), and a
  RESULT/Rows frame assembled from §4.2.5.2 must parse to the right
  tuples.

Plus skip-if-unreachable LIVE tests: when a real postgres / mongo /
cassandra answers on the standard localhost port (or WEED_TEST_PG /
WEED_TEST_MONGO / WEED_TEST_CASSANDRA gives host:port), the store runs
a CRUD cycle against the real server.
"""

from __future__ import annotations

import base64
import os
import socket
import struct

import pytest

from seaweedfs_tpu.filer import bson_lite as bson
from seaweedfs_tpu.filer.pg_client import scram_derive

# --- SCRAM-SHA-256: RFC 7677 §3 worked example ------------------------------

RFC7677_FIRST_BARE = "n=user,r=rOprNGfwEbeRWgbNEkqO"
RFC7677_SERVER_FIRST = ("r=rOprNGfwEbeRWgbNEkqO%hvYDpWUa2RaTCAfuxFIlj)hNlF$k0,"
                        "s=W22ZaJ0SNY7soEsUEjb6gQ==,i=4096")
RFC7677_CLIENT_FINAL = ("c=biws,"
                        "r=rOprNGfwEbeRWgbNEkqO%hvYDpWUa2RaTCAfuxFIlj)hNlF$k0,"
                        "p=dHzbZapWIk4jUhN+Ute9ytag9zjfMHgsqmmiz7AndVQ=")
RFC7677_SERVER_SIG = "6rriTRBi23WpRR/wtup+mMhUZUn/dB5nLTJRsjl95G4="


def test_scram_sha256_rfc7677_vector():
    final, server_sig = scram_derive("pencil", RFC7677_FIRST_BARE,
                                     RFC7677_SERVER_FIRST)
    assert final == RFC7677_CLIENT_FINAL
    assert base64.b64encode(server_sig).decode() == RFC7677_SERVER_SIG


# --- BSON: bsonspec.org published examples ----------------------------------

BSON_HELLO = (b"\x16\x00\x00\x00\x02hello\x00\x06\x00\x00\x00world\x00\x00")
BSON_AWESOME = (b"1\x00\x00\x00\x04BSON\x00&\x00\x00\x00\x020\x00\x08\x00"
                b"\x00\x00awesome\x00\x011\x00333333\x14@\x102\x00\xc2\x07"
                b"\x00\x00\x00\x00")


def test_bson_spec_examples():
    assert bson.encode({"hello": "world"}) == BSON_HELLO
    assert bson.decode(BSON_HELLO) == {"hello": "world"}
    assert bson.encode({"BSON": ["awesome", 5.05, 1986]}) == BSON_AWESOME
    assert bson.decode(BSON_AWESOME) == {"BSON": ["awesome", 5.05, 1986]}


# --- MongoDB OP_MSG framing --------------------------------------------------

class _RecorderSock:
    """Captures sendall bytes; serves a canned receive stream."""

    def __init__(self, reply: bytes = b""):
        self.sent = b""
        self._reply = reply

    def sendall(self, data: bytes) -> None:
        self.sent += bytes(data)

    def recv(self, n: int) -> bytes:
        piece, self._reply = self._reply[:n], self._reply[n:]
        return piece

    def close(self) -> None:
        pass


def test_mongo_op_msg_frame_matches_spec():
    from seaweedfs_tpu.filer.mongo_store import MongoClient

    doc = {"ping": 1, "$db": "admin"}
    body = bson.encode(doc)
    # hand-assembled per the MongoDB wire protocol doc: msgHeader
    # {messageLength, requestID, responseTo, opCode=2013} then OP_MSG
    # {flagBits u32=0, section kind byte 0, document}
    payload = struct.pack("<I", 0) + b"\x00" + body
    expect = struct.pack("<iiii", 16 + len(payload), 1, 0, 2013) + payload

    reply_doc = bson.encode({"ok": 1})
    reply_payload = struct.pack("<I", 0) + b"\x00" + reply_doc
    reply = struct.pack("<iiii", 16 + len(reply_payload), 7, 1,
                        2013) + reply_payload

    c = MongoClient.__new__(MongoClient)
    c._req_id = 0
    c._sock = _RecorderSock(reply)
    out = c._roundtrip_locked(doc)
    assert c._sock.sent == expect
    assert out == {"ok": 1}


# --- CQL v4 framing -----------------------------------------------------------

def test_cql_startup_and_query_frames_match_spec():
    from seaweedfs_tpu.filer.cassandra_store import (
        CONSISTENCY_ONE,
        OP_QUERY,
        OP_STARTUP,
        CqlClient,
        _string_map,
    )

    c = CqlClient.__new__(CqlClient)
    c._sock = _RecorderSock()
    # STARTUP (spec §4.1.1): string map {"CQL_VERSION": "3.0.0"}
    c._send_frame(OP_STARTUP, _string_map({"CQL_VERSION": "3.0.0"}))
    startup_body = (b"\x00\x01" +                      # map size [short]
                    b"\x00\x0bCQL_VERSION" +           # [string] key
                    b"\x00\x053.0.0")                  # [string] value
    # frame header (§2): version 0x04 request, flags 0, stream i16 0,
    # opcode, length u32
    expect = struct.pack(">BBhBI", 0x04, 0, 0, OP_STARTUP,
                         len(startup_body)) + startup_body
    assert c._sock.sent == expect

    # QUERY (§4.1.4): [long string] query, [consistency], [flags]
    c._sock = _RecorderSock()
    q = b"SELECT name FROM filemeta"
    c._send_frame(OP_QUERY, struct.pack(">I", len(q)) + q +
                  struct.pack(">H", CONSISTENCY_ONE) + b"\x00")
    qbody = struct.pack(">I", len(q)) + q + b"\x00\x01" + b"\x00"
    expect = struct.pack(">BBhBI", 0x04, 0, 0, OP_QUERY,
                         len(qbody)) + qbody
    assert c._sock.sent == expect


def test_cql_result_rows_parse_from_spec_bytes():
    from seaweedfs_tpu.filer.cassandra_store import CqlClient

    # RESULT/Rows metadata (§4.2.5.2): flags=1 (global table spec),
    # 2 columns, ks/table strings, per-column name + type; then rows
    def s(x: bytes) -> bytes:  # [string]
        return struct.pack(">H", len(x)) + x

    meta = (struct.pack(">iI", 0x0001, 2) + s(b"ks") + s(b"filemeta") +
            s(b"name") + struct.pack(">H", 0x000D) +   # varchar
            s(b"meta") + struct.pack(">H", 0x0003))    # blob
    rows = struct.pack(">I", 2)
    for name, val in ((b"a.txt", b"\x01\x02"), (b"b.txt", b"\x03")):
        rows += struct.pack(">i", len(name)) + name
        rows += struct.pack(">i", len(val)) + val
    got = CqlClient._parse_rows(meta + rows)
    assert got == [(b"a.txt", b"\x01\x02"), (b"b.txt", b"\x03")]


# --- live servers (skip-if-unreachable) --------------------------------------

def _reachable(env: str, default_port: int) -> tuple[str, int] | None:
    spec = os.environ.get(env, f"127.0.0.1:{default_port}")
    host, _, port_s = spec.partition(":")
    try:
        with socket.create_connection((host, int(port_s)), timeout=0.5):
            return host, int(port_s)
    except OSError:
        return None


def _store_crud_cycle(store):
    from seaweedfs_tpu.filer.entry import Attr, Entry

    e = Entry(full_path="/live-test/x.txt", attr=Attr(mode=0o660))
    store.insert_entry(e)
    try:
        got = store.find_entry("/live-test/x.txt")
        assert got is not None and got.attr.mode == 0o660
        assert "/live-test/x.txt" in [
            x.full_path for x in store.list_directory_entries("/live-test")]
    finally:
        store.delete_entry("/live-test/x.txt")
    assert store.find_entry("/live-test/x.txt") is None


def test_live_postgres():
    addr = _reachable("WEED_TEST_PG", 5432)
    if addr is None:
        pytest.skip("no postgres at WEED_TEST_PG/localhost:5432")
    from seaweedfs_tpu.filer.pg_client import PgConn
    from seaweedfs_tpu.filer.sql_store import AbstractSqlStore

    conn = PgConn(addr[0], addr[1],
                  user=os.environ.get("WEED_TEST_PG_USER", "postgres"),
                  password=os.environ.get("WEED_TEST_PG_PASSWORD", ""),
                  database=os.environ.get("WEED_TEST_PG_DB", "postgres"))
    _store_crud_cycle(AbstractSqlStore(conn, "postgres"))


def test_live_mongo():
    addr = _reachable("WEED_TEST_MONGO", 27017)
    if addr is None:
        pytest.skip("no mongod at WEED_TEST_MONGO/localhost:27017")
    from seaweedfs_tpu.filer.mongo_store import MongoClient, MongoStore

    _store_crud_cycle(MongoStore(MongoClient(host=addr[0], port=addr[1])))


def test_live_cassandra():
    addr = _reachable("WEED_TEST_CASSANDRA", 9042)
    if addr is None:
        pytest.skip("no cassandra at WEED_TEST_CASSANDRA/localhost:9042")
    from seaweedfs_tpu.filer.cassandra_store import CassandraStore, CqlClient

    _store_crud_cycle(CassandraStore(CqlClient(host=addr[0], port=addr[1])))


def test_live_redis_lua():
    addr = _reachable("WEED_TEST_REDIS", 6379)
    if addr is None:
        pytest.skip("no redis at WEED_TEST_REDIS/localhost:6379")
    from seaweedfs_tpu.filer.redis_lua_store import RedisLuaStore

    # a REAL redis interprets the Lua bodies themselves — the one gate
    # the marker-matching double cannot provide
    _store_crud_cycle(RedisLuaStore(host=addr[0], port=addr[1]))


# --- RESP2 / protobuf wire / Kafka batch: more spec-pinned bytes ------------

def test_resp2_request_frame_matches_spec():
    """The redis protocol doc's worked example: a SET command is the
    array-of-bulk-strings frame '*3\\r\\n$3\\r\\nSET\\r\\n...' verbatim."""
    from seaweedfs_tpu.filer.redis_store import RespClient

    frame = RespClient._encode((b"SET", b"mykey", b"Hello"))
    assert frame == b"*3\r\n$3\r\nSET\r\n$5\r\nmykey\r\n$5\r\nHello\r\n"


def test_protobuf_wire_examples():
    """pb_lite against the worked examples in the protobuf encoding doc:
    field 1 varint 150 -> 08 96 01; field 2 string 'testing' ->
    12 07 74 65 73 74 69 6e 67; embedded message -> 1a 03 08 96 01."""
    from seaweedfs_tpu.utils import pb_lite as pb

    assert pb.f_varint(1, 150) == b"\x08\x96\x01"
    assert pb.f_string(2, "testing") == b"\x12\x07testing"
    assert pb.f_msg(3, pb.f_varint(1, 150)) == b"\x1a\x03\x08\x96\x01"
    # decode direction round-trips the same published bytes
    fields = pb.decode(b"\x08\x96\x01\x12\x07testing")
    assert pb.first(fields, 1) == 150
    assert pb.first(fields, 2) == b"testing"


def test_hbase_rpc_preamble_bytes():
    """RPC.proto: the connection preamble _connect sends is the 4-byte
    magic 'HBas', version 0, auth code SIMPLE = 80 — exactly six
    bytes, pinned here independently of the module's own comment."""
    from seaweedfs_tpu.filer.hbase_store import RPC_PREAMBLE

    assert RPC_PREAMBLE == b"HBas" + bytes([0, 80])


def test_kafka_varints_match_protobuf_spec():
    """Kafka records use protobuf zigzag varints; pin to the table in
    the protobuf encoding doc (0->0, -1->1, 1->2, -2->3, 300 -> ac 02)."""
    from seaweedfs_tpu.replication.kafka import (dec_varint, enc_varint,
                                                 zigzag)

    assert [zigzag(n) for n in (0, -1, 1, -2, 2)] == [0, 1, 2, 3, 4]
    assert enc_varint(150) == b"\xac\x02"  # zigzag(150)=300 -> ac 02
    buf = enc_varint(-12345)
    val, i = dec_varint(buf, 0)
    assert (val, i) == (-12345, len(buf))


def test_kafka_record_batch_matches_hand_assembled_spec_frame():
    """One-record RecordBatch v2 assembled ONLY from the Kafka
    message-format doc (KIP-98 layout: baseOffset, batchLength,
    partitionLeaderEpoch, magic=2, crc32c over attributes..records,
    big-endian ints, zigzag-varint record fields) must equal the
    client's frame byte-for-byte."""
    import struct

    from seaweedfs_tpu.replication.kafka import record_batch
    from seaweedfs_tpu.storage.crc import crc32c

    key, value, ts = b"k1", b"payload", 1700000000000

    def vint(n):  # zigzag varint per the spec
        z = (n << 1) ^ (n >> 63)
        out = bytearray()
        while True:
            b = z & 0x7F
            z >>= 7
            if z:
                out.append(b | 0x80)
            else:
                out.append(b)
                return bytes(out)

    record = (b"\x00" + vint(0) + vint(0)
              + vint(len(key)) + key + vint(len(value)) + value + vint(0))
    records = vint(len(record)) + record
    crc_span = (struct.pack(">hiqqqhii", 0, 0, ts, ts, -1, -1, -1, 1)
                + records)
    head = struct.pack(">ibI", -1, 2, crc32c(crc_span))
    expect = struct.pack(">qi", 0, len(head) + len(crc_span)) + head + crc_span
    assert record_batch([(key, value)], now_ms=ts) == expect


def test_live_etcd():
    addr = _reachable("WEED_TEST_ETCD", 2379)
    if addr is None:
        pytest.skip("no etcd at WEED_TEST_ETCD/localhost:2379")
    from seaweedfs_tpu.filer.etcd_store import EtcdStore

    _store_crud_cycle(EtcdStore.from_url(f"etcd://{addr[0]}:{addr[1]}"))


def test_live_elastic():
    addr = _reachable("WEED_TEST_ELASTIC", 9200)
    if addr is None:
        pytest.skip("no elasticsearch at WEED_TEST_ELASTIC/localhost:9200")
    from seaweedfs_tpu.filer.elastic_store import ElasticStore

    _store_crud_cycle(
        ElasticStore.from_url(f"elastic://{addr[0]}:{addr[1]}"))


def test_live_hbase():
    addr = _reachable("WEED_TEST_HBASE", 16020)
    if addr is None:
        pytest.skip("no hbase regionserver at WEED_TEST_HBASE/localhost:16020")
    from seaweedfs_tpu.filer.hbase_store import HbaseStore

    _store_crud_cycle(
        HbaseStore.from_url(f"hbase://{addr[0]}:{addr[1]}/seaweedfs"))
