"""Debug-tool long tail: fix_dat, volume_tailer, load_test,
diff_volume_servers, and the `weed fuse` fstab entry point.

References: unmaintained/fix_dat/fix_dat.go,
unmaintained/volume_tailer/volume_tailer.go,
unmaintained/load_test/load_test.go,
unmaintained/diff_volume_servers/diff_volume_servers.go,
weed/command/fuse.go.
"""

from __future__ import annotations

import io
import os
import threading
import time

import numpy as np
import pytest

from seaweedfs_tpu.storage.needle import Needle
from seaweedfs_tpu.storage.volume import Volume

from .conftest import free_port

RNG = np.random.default_rng(0x700)


@pytest.fixture()
def cluster(tmp_path):
    from seaweedfs_tpu.master.server import MasterServer
    from seaweedfs_tpu.volume_server.server import VolumeServer

    master = MasterServer(port=free_port(), pulse_seconds=0.3).start()
    (tmp_path / "v").mkdir()
    vol = VolumeServer([str(tmp_path / "v")], master.url, port=free_port(),
                       pulse_seconds=0.3).start()
    deadline = time.time() + 6
    while time.time() < deadline and not master.topo.all_nodes():
        time.sleep(0.05)
    yield master, vol
    vol.stop()
    master.stop()


# --- fix_dat -----------------------------------------------------------------

def test_fix_dat_rebuilds_live_needles(tmp_path):
    from seaweedfs_tpu.tools.fix_dat import fix_dat

    v = Volume(str(tmp_path), "", 3)
    v.write_needle(Needle(cookie=1, id=1, data=b"keep-one" * 16))
    v.write_needle(Needle(cookie=2, id=2, data=b"doomed" * 16))
    v.write_needle(Needle(cookie=3, id=3, data=b"keep-two" * 40))
    v.delete_needle(Needle(cookie=2, id=2))
    v.close()
    copied, written = fix_dat(str(tmp_path), "", 3)
    assert copied == 2  # the tombstoned needle is dropped
    fixed = tmp_path / "3.dat_fixed"
    assert fixed.exists() and written == fixed.stat().st_size
    # the rebuilt dat + weed fix's idx reconstruction round-trips
    os.replace(fixed, tmp_path / "3.dat")
    os.unlink(tmp_path / "3.idx")
    import subprocess
    import sys

    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    rc = subprocess.run(
        [sys.executable, os.path.join(repo, "weed.py"), "fix",
         "-dir", str(tmp_path), "-volumeId", "3"], env=env).returncode
    assert rc == 0
    v2 = Volume(str(tmp_path), "", 3)
    assert v2.read_needle(1, cookie=1).data == b"keep-one" * 16
    assert v2.read_needle(3, cookie=3).data == b"keep-two" * 40
    with pytest.raises(Exception):
        v2.read_needle(2, cookie=2)
    v2.close()


# --- volume_tailer -----------------------------------------------------------

def test_volume_tailer_follows_appends(cluster):
    from seaweedfs_tpu.client.operation import WeedClient
    from seaweedfs_tpu.tools.volume_tailer import tail_volume

    master, vol = cluster
    client = WeedClient(master.url)
    fid = client.upload(b"first payload", name="a.txt")
    vid = int(fid.split(",")[0])
    out = io.StringIO()
    done = threading.Event()

    def run():
        tail_volume(master.url, vid, since_ns=0, timeout_s=2.5,
                    show_text=True, poll_s=0.2, out=out)
        done.set()

    t = threading.Thread(target=run, daemon=True)
    t.start()
    time.sleep(0.5)
    client.upload(b"second textual payload", name="b.txt")
    assert done.wait(timeout=15)
    text = out.getvalue()
    assert "PUT id=" in text
    assert "second textual payload" in text  # -showTextFile content
    assert text.count("PUT") >= 2


# --- load_test ---------------------------------------------------------------

def test_load_test_mixed_traffic(cluster):
    from seaweedfs_tpu.tools.load_test import run_load

    master, _ = cluster
    out = run_load(master.url, seconds=2.0, concurrency=2, size=512,
                   read_ratio=0.5)
    assert out["errors"] == 0
    assert out["writes"] > 0 and out["reads"] > 0
    assert out["write_rps"] > 0


# --- diff_volume_servers -----------------------------------------------------

def test_diff_volume_servers_reports_divergence(tmp_path):
    from seaweedfs_tpu.master.server import MasterServer
    from seaweedfs_tpu.volume_server.server import VolumeServer
    from seaweedfs_tpu.tools.diff_volume_servers import diff_servers
    from seaweedfs_tpu.utils.httpd import http_json

    master = MasterServer(port=free_port(), pulse_seconds=0.3,
                          default_replication="001").start()
    (tmp_path / "a").mkdir()
    (tmp_path / "b").mkdir()
    va = VolumeServer([str(tmp_path / "a")], master.url, port=free_port(),
                      pulse_seconds=0.3).start()
    vb = VolumeServer([str(tmp_path / "b")], master.url, port=free_port(),
                      pulse_seconds=0.3).start()
    try:
        deadline = time.time() + 6
        while time.time() < deadline and len(master.topo.all_nodes()) < 2:
            time.sleep(0.05)
        from seaweedfs_tpu.client.operation import WeedClient

        client = WeedClient(master.url)
        fid = client.upload(b"replicated-needle", name="r.bin",
                            replication="001")
        vid = int(fid.split(",")[0])
        # in sync: no differences
        out = io.StringIO()
        assert diff_servers([va.url, vb.url], vid, out=out) == 0
        # diverge one replica behind the master's back
        v = va.store.get_volume(vid)
        v.write_needle(Needle(cookie=9, id=999, data=b"only-on-a"))
        out = io.StringIO()
        assert diff_servers([va.url, vb.url], vid, out=out) == 1
        assert "only on" in out.getvalue()
        assert "999" in out.getvalue()
    finally:
        va.stop()
        vb.stop()
        master.stop()


# --- weed fuse fstab entry ---------------------------------------------------

def test_weed_fuse_option_translation(monkeypatch):
    import weed as weed_cli  # repo root on sys.path via conftest

    captured = {}

    def fake_mount(args):
        captured.update(vars(type(args)) if not isinstance(args, dict)
                        else args)
        captured["filer"] = args.filer
        captured["dir"] = args.dir
        captured["filerPath"] = args.filerPath
        captured["collection"] = args.collection
        captured["chunkSizeLimitMB"] = args.chunkSizeLimitMB
        captured["allowOthers"] = args.allowOthers

    monkeypatch.setattr(weed_cli, "cmd_mount", fake_mount)

    class A:
        mountpoint = "/mnt/weed"
        o = ("filer=10.0.0.5:8888,filer.path=/data,collection=pics,"
             "chunkSizeLimitMB=16,allow_other,rw,noatime,nonempty")

    weed_cli.cmd_fuse(A())
    assert captured["filer"] == "10.0.0.5:8888"
    assert captured["dir"] == "/mnt/weed"
    assert captured["filerPath"] == "/data"
    assert captured["collection"] == "pics"
    assert captured["chunkSizeLimitMB"] == 16
    assert captured["allowOthers"] is True
