"""etcd-protocol filer store against an in-process v3 JSON gateway double.

Gates mirror the redis-store suite: CRUD + listing pagination/prefix,
recursive folder delete via DeleteRange intervals, kv prefix scans,
randomized differential vs MemoryStore, and a Filer riding on top.
"""

from __future__ import annotations

import numpy as np
import pytest

from seaweedfs_tpu.filer.entry import Attr, Entry, FileChunk
from seaweedfs_tpu.filer.etcd_store import EtcdStore, _prefix_end
from seaweedfs_tpu.filer.filer import Filer, NotFoundError
from seaweedfs_tpu.filer.filer_store import MemoryStore

from .minietcd import MiniEtcd

RNG = np.random.default_rng(0xE7CD)


@pytest.fixture()
def server():
    s = MiniEtcd()
    yield s
    s.stop()


@pytest.fixture()
def store(server):
    return EtcdStore.from_url(f"etcd://127.0.0.1:{server.port}")


def _file(path: str, n: int = 1) -> Entry:
    chunks = [FileChunk(file_id=f"3,{i:02x}", offset=i * 10, size=10)
              for i in range(n)]
    return Entry(full_path=path, attr=Attr(mode=0o660), chunks=chunks)


def test_prefix_end_math():
    assert _prefix_end(b"abc") == b"abd"
    assert _prefix_end(b"a\xff") == b"b"
    assert _prefix_end(b"\xff\xff") == b"\x00"  # to end of keyspace


def test_crud_listing_pagination(store):
    for name in ("a.txt", "b.txt", "c.txt"):
        store.insert_entry(_file(f"/d/{name}", 2))
    assert len(store.find_entry("/d/b.txt").chunks) == 2
    assert [e.full_path for e in store.list_directory_entries("/d")] == [
        "/d/a.txt", "/d/b.txt", "/d/c.txt"]
    # exclusive resume must still fill the page (the +1 overfetch)
    assert [e.full_path for e in store.list_directory_entries(
        "/d", start_file="a.txt", limit=2)] == ["/d/b.txt", "/d/c.txt"]
    assert [e.full_path for e in store.list_directory_entries(
        "/d", start_file="a.txt", include_start=True, limit=2)] == [
        "/d/a.txt", "/d/b.txt"]
    store.delete_entry("/d/b.txt")
    assert store.find_entry("/d/b.txt") is None


def test_prefix_listing(store):
    for name in ("apple", "apricot", "banana"):
        store.insert_entry(_file(f"/f/{name}"))
    assert [e.full_path for e in store.list_directory_entries(
        "/f", prefix="ap")] == ["/f/apple", "/f/apricot"]
    assert list(store.list_directory_entries("/f", prefix="z")) == []


def test_delete_folder_children_recursive(store):
    for p in ("/t/x", "/t/sub/y", "/t/sub/deep/z", "/other/keep",
              "/tx/decoy"):
        store.insert_entry(_file(p))
    store.delete_folder_children("/t")
    for p in ("/t/x", "/t/sub/y", "/t/sub/deep/z"):
        assert store.find_entry(p) is None
    assert store.find_entry("/other/keep") is not None
    assert store.find_entry("/tx/decoy") is not None  # sibling untouched


def test_kv_and_prefix_scan(store):
    store.kv_put(b"sig/a", b"1")
    store.kv_put(b"sig/b", b"2")
    store.kv_put(b"other", b"3")
    assert store.kv_get(b"sig/a") == b"1"
    assert store.kv_get(b"nope") is None
    assert dict(store.kv_scan(b"sig/")) == {b"sig/a": b"1", b"sig/b": b"2"}
    store.kv_delete(b"sig/a")
    assert dict(store.kv_scan(b"sig/")) == {b"sig/b": b"2"}


def test_matches_memory_randomized(store):
    mem = MemoryStore()
    dirs = ["/a", "/a/b", "/c"]
    names = [f"f{i:02d}" for i in range(10)]
    for _ in range(300):
        op = RNG.integers(0, 4)
        d = dirs[RNG.integers(0, len(dirs))]
        n = names[RNG.integers(0, len(names))]
        path = f"{d}/{n}"
        if op == 0:
            e = _file(path, int(RNG.integers(1, 4)))
            mem.insert_entry(e)
            store.insert_entry(e)
        elif op == 1:
            mem.delete_entry(path)
            store.delete_entry(path)
        elif op == 2:
            a, b = mem.find_entry(path), store.find_entry(path)
            assert (a is None) == (b is None)
            if a is not None:
                assert a.to_dict() == b.to_dict()
        else:
            assert [e.full_path for e in mem.list_directory_entries(d)] == \
                [e.full_path for e in store.list_directory_entries(d)]


def test_filer_on_etcd(store):
    deleted: list[str] = []
    f = Filer(store=store, delete_chunks_fn=deleted.extend)
    f.mkdir("/docs")
    f.create_entry(_file("/docs/readme.md", 2))
    assert [c.file_id for c in f.find_entry("/docs/readme.md").chunks] == [
        "3,00", "3,01"]
    f.delete_entry("/docs/readme.md")
    f.flush_gc()
    assert sorted(deleted) == ["3,00", "3,01"]
    with pytest.raises(NotFoundError):
        f.find_entry("/docs/readme.md")
    f.close()


def test_prefix_with_low_start_file_fills_page(store):
    """start_file below the prefix range must not return an empty page:
    the range lower bound is the tighter of (start_file, prefix), like
    RedisStore (the first non-matching name would otherwise `break`
    before any match was reached)."""
    for name in ("aa", "ab", "ba", "bb"):
        store.insert_entry(_file(f"/p/{name}"))
    got = [e.full_path for e in store.list_directory_entries(
        "/p", start_file="aa", prefix="b", limit=2)]
    assert got == ["/p/ba", "/p/bb"]
    # a resume inside the prefix range still respects start_file
    got = [e.full_path for e in store.list_directory_entries(
        "/p", start_file="ba", prefix="b", limit=2)]
    assert got == ["/p/bb"]
