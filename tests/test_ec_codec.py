"""GF(2^8) field, RS codec, and engine differential tests.

The differential tests are the core gate from SURVEY.md §4: CPU (numpy LUT)
vs TPU (XLA bit-plane) vs TPU (Pallas kernel, interpreter on CPU) must be
byte-identical for every geometry.
"""

import itertools

import numpy as np
import pytest

from seaweedfs_tpu.ec.codec import CpuEngine, ReedSolomon
from seaweedfs_tpu.ec.gf256 import (
    EXP_TABLE,
    LOG_TABLE,
    MUL_TABLE,
    build_cauchy_matrix,
    build_encoding_matrix,
    constant_bit_matrix,
    gf_inv,
    gf_mul,
    mat_invert,
    mat_mul,
)

rng = np.random.default_rng(0xEC)


# --- field ---------------------------------------------------------------

def test_field_properties():
    # generator cycle covers all 255 nonzero elements
    assert len(set(EXP_TABLE[:255].tolist())) == 255
    # known powers of 2 under poly 0x11D
    assert EXP_TABLE[0] == 1 and EXP_TABLE[1] == 2 and EXP_TABLE[8] == 29
    # multiplicative inverse
    for a in range(1, 256):
        assert gf_mul(a, gf_inv(a)) == 1
    # distributivity spot check
    for _ in range(200):
        a, b, c = rng.integers(0, 256, 3)
        assert gf_mul(int(a), int(b) ^ int(c)) == gf_mul(int(a), int(b)) ^ gf_mul(int(a), int(c))


def test_mul_table_consistency():
    for _ in range(500):
        a, b = rng.integers(0, 256, 2)
        assert MUL_TABLE[a, b] == gf_mul(int(a), int(b))
    assert np.array_equal(MUL_TABLE, MUL_TABLE.T)


def test_matrix_inversion():
    m = [[1, 2, 3], [4, 69, 6], [7, 8, 90]]
    inv = mat_invert(m)
    assert mat_mul(m, inv) == [[1, 0, 0], [0, 1, 0], [0, 0, 1]]


def test_constant_bit_matrix_is_multiplication():
    for c in (0, 1, 2, 29, 142, 255):
        m = constant_bit_matrix(c)
        for x in (0, 1, 7, 128, 201, 255):
            xbits = np.array([(x >> j) & 1 for j in range(8)], dtype=np.uint8)
            ybits = (m @ xbits) % 2
            y = int(sum(int(b) << i for i, b in enumerate(ybits)))
            assert y == gf_mul(c, x), (c, x)


@pytest.mark.parametrize("d,p", [(10, 4), (6, 3), (12, 4), (3, 2)])
def test_encoding_matrix_systematic(d, p):
    for build in (build_encoding_matrix, build_cauchy_matrix):
        m = build(d, d + p)
        assert m.shape == (d + p, d)
        assert np.array_equal(m[:d], np.eye(d, dtype=np.uint8))
        # every square submatrix of total rows must be invertible (MDS-ish
        # sanity: any d surviving shards can decode)
        for rows in itertools.islice(itertools.combinations(range(d + p), d), 30):
            mat_invert([[int(v) for v in m[r]] for r in rows])  # must not raise


# --- codec ---------------------------------------------------------------

@pytest.mark.parametrize("d,p", [(10, 4), (6, 3), (12, 4)])
def test_encode_verify_reconstruct(d, p):
    rs = ReedSolomon(d, p)
    data = rng.integers(0, 256, (d, 1000), dtype=np.uint8)
    parity = rs.encode(data)
    shards = [data[i] for i in range(d)] + [parity[i] for i in range(p)]
    assert rs.verify(shards)

    # every erasure pattern up to p losses reconstructs byte-identically
    for n_lost in range(1, p + 1):
        for lost in itertools.islice(itertools.combinations(range(d + p), n_lost), 40):
            damaged = [None if i in lost else shards[i].copy() for i in range(d + p)]
            rs.reconstruct(damaged)
            for i in range(d + p):
                assert np.array_equal(damaged[i], shards[i]), (lost, i)


def test_reconstruct_data_only():
    rs = ReedSolomon(4, 2)
    data = rng.integers(0, 256, (4, 64), dtype=np.uint8)
    parity = rs.encode(data)
    shards = [data[i] for i in range(4)] + [parity[i] for i in range(2)]
    damaged = [None, shards[1], shards[2], shards[3], None, shards[5]]
    rs.reconstruct_data(damaged)
    assert np.array_equal(damaged[0], shards[0])
    assert damaged[4] is None  # parity left missing


def test_too_few_shards():
    rs = ReedSolomon(4, 2)
    with pytest.raises(ValueError):
        rs.reconstruct([None, None, None] + [np.zeros(8, np.uint8)] * 3)


# --- engine differential (the core gate) ---------------------------------

def _engines():
    from seaweedfs_tpu.ops.gf_matmul import TpuEngine

    engines = [TpuEngine(mode="xla"), TpuEngine(mode="pallas")]
    try:
        from seaweedfs_tpu.ec.codec import NativeEngine

        engines.append(NativeEngine())
    except Exception:
        pass  # no C++ toolchain in this environment
    return engines


def test_native_engine_available():
    """The C++ SIMD engine must build wherever a toolchain exists — it is
    the default CPU path and the bench baseline."""
    import shutil

    if shutil.which("make") is None or shutil.which("g++") is None:
        pytest.skip("no C++ toolchain")
    from seaweedfs_tpu.ec.codec import NativeEngine, best_cpu_engine

    assert isinstance(best_cpu_engine(), NativeEngine)


@pytest.mark.parametrize("d,p", [(10, 4), (6, 3), (12, 4)])
def test_cpu_tpu_byte_identical_encode(d, p):
    cpu = ReedSolomon(d, p, engine=CpuEngine())
    for b in (1, 50, 1000, 4096, 5000):
        data = rng.integers(0, 256, (d, b), dtype=np.uint8)
        want = cpu.encode(data)
        for eng in _engines():
            got = ReedSolomon(d, p, engine=eng).encode(data)
            assert np.array_equal(want, got), (eng.name, b)


def test_cpu_tpu_byte_identical_reconstruct():
    data = rng.integers(0, 256, (10, 2048), dtype=np.uint8)
    cpu = ReedSolomon(10, 4, engine=CpuEngine())
    parity = cpu.encode(data)
    shards = [data[i] for i in range(10)] + [parity[i] for i in range(4)]
    for eng in _engines():
        rs = ReedSolomon(10, 4, engine=eng)
        damaged = [None if i in (0, 3, 11, 13) else shards[i].copy() for i in range(14)]
        rs.reconstruct(damaged)
        for i in range(14):
            assert np.array_equal(damaged[i], shards[i]), (eng.name, i)


def test_native_matmul_rows_matches_stacked():
    """The row-pointer kernel (no survivor stack copy) must produce the
    same bytes as the contiguous matmul for every erasure pattern —
    reconstruct() picks it automatically when the engine has it."""
    from seaweedfs_tpu.ec.codec import CpuEngine, best_cpu_engine

    eng = best_cpu_engine()
    if not hasattr(eng, "matmul_rows"):
        pytest.skip("native engine unavailable")
    rng = np.random.default_rng(7)
    m = rng.integers(1, 256, (4, 10), dtype=np.uint8)
    rows = [rng.integers(0, 256, 8191, dtype=np.uint8) for _ in range(10)]
    got = eng.matmul_rows(m, rows)
    want = eng.matmul(m, np.stack(rows))
    assert np.array_equal(got, want)
    # and the pure-python reference agrees
    ref = CpuEngine().matmul(m, np.stack(rows))
    assert np.array_equal(got, ref)


def test_matmul_rows_rejects_uneven_survivors():
    from seaweedfs_tpu.ec.codec import best_cpu_engine

    eng = best_cpu_engine()
    if not hasattr(eng, "matmul_rows"):
        pytest.skip("native engine unavailable")
    m = np.ones((2, 3), dtype=np.uint8)
    rows = [np.zeros(64, np.uint8), np.zeros(32, np.uint8),
            np.zeros(64, np.uint8)]
    with pytest.raises(ValueError):
        eng.matmul_rows(m, rows)
