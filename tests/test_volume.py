"""Volume engine tests: write/read/delete/reload/compact/integrity."""

import os

import pytest

from seaweedfs_tpu.storage.needle import Needle
from seaweedfs_tpu.storage.needle_map import MemDb, MemoryNeedleMap
from seaweedfs_tpu.storage.volume import (
    CookieMismatchError,
    DeletedError,
    NotFoundError,
    Volume,
)


def make_volume(tmp_path, vid=1, collection=""):
    return Volume(str(tmp_path), collection, vid)


def test_write_read_roundtrip(tmp_path):
    v = make_volume(tmp_path)
    n = Needle(cookie=0x11, id=1, data=b"alpha")
    offset, size, unchanged = v.write_needle(n)
    assert not unchanged
    assert offset == 8  # directly after superblock
    got = v.read_needle(1, cookie=0x11)
    assert got.data == b"alpha"
    v.close()


def test_write_is_8_byte_aligned(tmp_path):
    v = make_volume(tmp_path)
    offsets = []
    for i in range(1, 20):
        n = Needle(cookie=i, id=i, data=b"x" * i)
        offset, _, _ = v.write_needle(n)
        offsets.append(offset)
    assert all(o % 8 == 0 for o in offsets)
    v.close()


def test_unchanged_write_dedupe(tmp_path):
    v = make_volume(tmp_path)
    v.write_needle(Needle(cookie=5, id=9, data=b"same"))
    size_before = v.data_size
    _, _, unchanged = v.write_needle(Needle(cookie=5, id=9, data=b"same"))
    assert unchanged
    assert v.data_size == size_before
    v.close()


def test_overwrite_cookie_check(tmp_path):
    v = make_volume(tmp_path)
    v.write_needle(Needle(cookie=5, id=9, data=b"one"))
    with pytest.raises(CookieMismatchError):
        v.write_needle(Needle(cookie=6, id=9, data=b"two"))
    v.close()


def test_delete_and_tombstone(tmp_path):
    v = make_volume(tmp_path)
    v.write_needle(Needle(cookie=1, id=7, data=b"doomed"))
    deleted_size = v.delete_needle(Needle(cookie=1, id=7))
    assert deleted_size > 0
    with pytest.raises((DeletedError, NotFoundError)):
        v.read_needle(7)
    v.close()


def test_reload_from_disk(tmp_path):
    v = make_volume(tmp_path)
    for i in range(1, 10):
        v.write_needle(Needle(cookie=i, id=i, data=bytes([i]) * 100))
    v.delete_needle(Needle(cookie=3, id=3))
    v.close()

    v2 = make_volume(tmp_path)
    assert v2.read_needle(5).data == b"\x05" * 100
    with pytest.raises((DeletedError, NotFoundError)):
        v2.read_needle(3)
    assert v2.nm.file_counter == 9
    assert v2.nm.deletion_counter == 1
    v2.close()


def test_compact_reclaims_space(tmp_path):
    v = make_volume(tmp_path)
    for i in range(1, 11):
        v.write_needle(Needle(cookie=i, id=i, data=bytes([i]) * 1000))
    for i in range(1, 6):
        v.delete_needle(Needle(cookie=i, id=i))
    size_before = v.data_size
    assert v.garbage_ratio() > 0
    v.compact()
    v.commit_compact()
    assert v.data_size < size_before
    assert v.super_block.compaction_revision == 1
    for i in range(6, 11):
        assert v.read_needle(i).data == bytes([i]) * 1000
    for i in range(1, 6):
        with pytest.raises((DeletedError, NotFoundError)):
            v.read_needle(i)
    v.close()


def test_write_during_compaction_survives_commit(tmp_path):
    """makeupDiff (volume_vacuum.go:181): a write (and a delete) landing
    between compact() and commit_compact() must survive the swap."""
    v = make_volume(tmp_path)
    for i in range(1, 6):
        v.write_needle(Needle(cookie=i, id=i, data=bytes([i]) * 500))
    v.delete_needle(Needle(cookie=1, id=1))
    v.compact()
    # in-between mutations
    v.write_needle(Needle(cookie=99, id=99, data=b"landed mid-vacuum"))
    v.delete_needle(Needle(cookie=2, id=2))
    v.commit_compact()
    assert v.read_needle(99).data == b"landed mid-vacuum"
    with pytest.raises((DeletedError, NotFoundError)):
        v.read_needle(2)
    for i in (3, 4, 5):
        assert v.read_needle(i).data == bytes([i]) * 500
    v.close()


def test_torn_write_truncation(tmp_path):
    v = make_volume(tmp_path)
    v.write_needle(Needle(cookie=1, id=1, data=b"full record"))
    good_size = v.data_size
    v.close()
    # simulate a torn write: garbage appended past the last indexed needle
    with open(os.path.join(str(tmp_path), "1.dat"), "ab") as f:
        f.write(b"\xde\xad\xbe\xef")
    v2 = make_volume(tmp_path)
    assert v2.data_size == good_size
    assert v2.read_needle(1).data == b"full record"
    v2.close()


def test_torn_dat_tail_with_persisted_idx(tmp_path):
    """Crash where the .idx append survived but the .dat pages didn't:
    reopen must drop the orphaned index entry and keep the volume healthy
    (volume_checking.go:17-45 semantics)."""
    v = make_volume(tmp_path)
    v.write_needle(Needle(cookie=1, id=1, data=b"survivor"))
    survivor_end = v.data_size
    v.write_needle(Needle(cookie=2, id=2, data=b"lost in the crash"))
    v.close()
    # lose the second needle's dat bytes but keep its idx entry
    with open(os.path.join(str(tmp_path), "1.dat"), "r+b") as f:
        f.truncate(survivor_end + 10)  # partial record
    v2 = make_volume(tmp_path)
    assert v2.read_needle(1).data == b"survivor"
    with pytest.raises((NotFoundError, DeletedError)):
        v2.read_needle(2)
    assert v2.data_size == survivor_end
    # and the volume accepts new writes cleanly after healing
    v2.write_needle(Needle(cookie=3, id=3, data=b"after recovery"))
    assert v2.read_needle(3).data == b"after recovery"
    v2.close()


def test_scan_visits_all_records(tmp_path):
    v = make_volume(tmp_path)
    for i in range(1, 6):
        v.write_needle(Needle(cookie=i, id=i, data=bytes([i]) * 10))
    seen = []
    v.scan(lambda n, off: seen.append((n.id, off)))
    assert [s[0] for s in seen] == [1, 2, 3, 4, 5]
    v.close()


def test_needle_map_replay_counters(tmp_path):
    idx_path = str(tmp_path / "x.idx")
    nm = MemoryNeedleMap(idx_path)
    nm.put(1, 8, 100)
    nm.put(2, 120, 200)
    nm.put(1, 328, 150)  # overwrite
    nm.delete(2, 536)
    nm.close()

    nm2 = MemoryNeedleMap.load(idx_path)
    assert nm2.get(1).size == 150
    assert nm2.get(2) is None
    assert nm2.file_counter == 3
    assert nm2.deletion_counter == 2
    assert nm2.max_file_key == 2
    nm2.close()


def test_memdb_sorted_file(tmp_path):
    db = MemDb()
    for key in (5, 1, 9, 3):
        db.set(key, key * 8, 10)
    out = str(tmp_path / "sorted.ecx")
    db.write_sorted_file(out)
    from seaweedfs_tpu.storage.idx import iter_index_file

    keys = [k for k, _, _ in iter_index_file(out)]
    assert keys == [1, 3, 5, 9]


# --------------------------------------------------------------------------
# mmap-backed .dat (backend/memory_map variant)
# --------------------------------------------------------------------------

def test_mmap_volume_roundtrip_and_reopen(tmp_path):
    """An mmap-backed volume must behave byte-identically to the pread
    one: write/read/delete, then reopen through BOTH file backends."""
    v = Volume(str(tmp_path), "", 7, use_mmap=True)
    for i in range(1, 40):
        v.write_needle(Needle(cookie=i, id=i, data=bytes([i]) * (i * 3)))
    assert v.read_needle(5, cookie=5).data == bytes([5]) * 15
    v.delete_needle(Needle(cookie=6, id=6))
    v.close()
    # on-disk bytes always equal the logical content: writes are pwrite,
    # only reads ride the mapping, so external readers (EC encode, tier
    # upload, volume copy) see exactly what DiskFile would produce
    import os as _os
    dat = _os.path.getsize(str(tmp_path / "7.dat"))
    assert dat % 8 == 0 and dat % (1 << 20) != 0

    # reopen with mmap
    v2 = Volume(str(tmp_path), "", 7, use_mmap=True)
    assert v2.read_needle(17, cookie=17).data == bytes([17]) * 51
    with pytest.raises((DeletedError, NotFoundError)):
        v2.read_needle(6, cookie=6)
    v2.close()

    # reopen with plain pread: same bytes, same answers
    v3 = Volume(str(tmp_path), "", 7)
    assert v3.read_needle(17, cookie=17).data == bytes([17]) * 51
    v3.close()


def test_mmap_volume_compacts(tmp_path):
    v = Volume(str(tmp_path), "", 8, use_mmap=True)
    for i in range(1, 30):
        v.write_needle(Needle(cookie=i, id=i, data=b"z" * 100))
    for i in range(1, 20):
        v.delete_needle(Needle(cookie=i, id=i))
    before = v.data_size
    v.compact()
    v.commit_compact()
    assert v.data_size < before
    assert v.read_needle(25, cookie=25).data == b"z" * 100
    v.close()
